"""Micro-benchmark CLI for the scan/aggregate hot paths.

``python -m ydb_tpu.obs.kernelbench`` measures, in-process:

  * group-by — a synthetic multi-aggregate GROUP BY program compiled
    twice (fused single-contraction vs per-aggregate reductions,
    kernels.FUSED_FORCE) and cross-checked against the CPU oracle;
  * staging — payload stream -> rechunk -> TableBlock.from_numpy ->
    device block throughput (the low-copy block pipeline);
  * pruning (``--pruning``) — zone-map scan pruning on a selective
    non-PK filter over a time-correlated table: chunks skipped/s and
    the stats-on vs stats-off (YDB_TPU_STATS=0 analog) speedup, with
    results asserted bit-identical between the two sides;
  * profile overhead (``--profile-overhead``) — warm TPC-H Q1 through
    ``ColumnShard.scan`` with query profiling active (a traced root
    span, the session's default-on state) vs inactive (the
    ``YDB_TPU_PROFILE=0`` path): profiling must be within noise of off,
    or it cannot stay default-on;
  * fusion (``--fusion``) — warm TPC-H Q3 (joins + grouped top-k)
    executed as ONE whole-plan fused dispatch (ssa.plan_fuse) vs the
    per-node fragment walk, bit-identity asserted, with per-query
    dispatch counts;
  * streaming (``--streaming``) — morsel-driven scan pipeline
    (engine.stream_sched) vs the serialized path over a COLD
    DirBlobStore scan: rows/s both sides, the measured
    ``movement|compute`` overlap coefficient of one pipelined run, and
    results asserted bit-identical between the two sides;
  * shuffle (``--shuffle``) — all_to_all repartition on a virtual
    8-device mesh with stats-sized send buckets (count-min heavy-hitter
    bound, parallel.shuffle.size_buckets) vs always-sufficient
    full-capacity buckets: rows/s, analytic bytes exchanged, the
    >=4x capacity reduction on uniform keys, and a 100%-skew
    overflow -> grow -> lossless re-exchange round, row multisets
    asserted equal throughout.

Flags: ``--rows`` ``--groups`` ``--aggs`` ``--iters`` ``--block-rows``
``--pruning`` ``--streaming`` ``--profile-overhead``
``--admission-overhead`` (multi-tenant front door absent vs installed
through the full session path) ``--memsan-overhead`` (memory
sanitizer disarmed vs armed warm Q1, zero unbudgeted allocations
asserted on the armed side) ``--fusion``
``--shuffle``
``--shuffle-rows`` ``--sf`` (scale
factor for the overhead/fusion benches) ``--json`` (report on stdout) and
``--smoke`` (tiny sizes, correctness-only; wired into tier-1 as a
non-slow test). Run under JAX_PLATFORMS=cpu for a stable reference; on
accelerators it measures whatever backend jax selects.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _build_case(rows: int, groups: int, aggs: int, seed: int = 7):
    """Synthetic grouped-aggregation case: one bounded int key (dense
    tier when `groups` is small), `aggs` decimal SUM columns plus AVG /
    COUNT / MIN / MAX riders, ~6% NULLs."""
    from ydb_tpu import dtypes
    from ydb_tpu.ssa import (
        Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op, Program,
    )
    from ydb_tpu.ssa.program import lit

    rng = np.random.default_rng(seed)
    cols = {"k": rng.integers(0, groups, rows).astype(np.int64)}
    valid = {"k": np.ones(rows, dtype=bool)}
    fields = [("k", dtypes.INT64)]
    specs = [AggSpec(Agg.COUNT_ALL, None, "n")]
    for i in range(aggs):
        name = f"v{i}"
        cols[name] = rng.integers(0, 10 ** 6, rows).astype(np.int64)
        valid[name] = rng.random(rows) > 0.06
        fields.append((name, dtypes.decimal(2)))
        specs.append(AggSpec(Agg.SUM, name, f"sum_{name}"))
    specs.append(AggSpec(Agg.AVG, "v0", "avg_v0"))
    specs.append(AggSpec(Agg.COUNT, "v0", "cnt_v0"))
    specs.append(AggSpec(Agg.MIN, "v0", "min_v0"))
    specs.append(AggSpec(Agg.MAX, "v0", "max_v0"))
    prog = Program((
        FilterStep(Call(Op.GE, Col("v0"), lit(0))),
        GroupByStep(("k",), tuple(specs)),
    ))
    schema = dtypes.schema(*fields)
    return prog, schema, cols, valid


def bench_group_by(rows: int, groups: int, aggs: int, iters: int,
                   check: bool = True) -> dict:
    import jax

    from ydb_tpu.blocks.block import TableBlock, device_aux
    from ydb_tpu.engine.oracle import OracleTable, run_oracle
    from ydb_tpu.ssa import kernels
    from ydb_tpu.ssa.compiler import compile_program

    prog, schema, cols, valid = _build_case(rows, groups, aggs)
    blk = jax.device_put(TableBlock.from_numpy(cols, schema, valid))
    out: dict = {"rows": rows, "groups": groups, "aggs": aggs}
    results = {}
    for label, force in (("fused", True), ("peragg", False)):
        kernels.FUSED_FORCE = force
        try:
            cp = compile_program(prog, schema,
                                 key_spaces={"k": groups})
            run = jax.jit(cp.run)
            aux = device_aux(cp.aux)
            res = jax.block_until_ready(run(blk, aux))
            results[label] = res
            best = float("inf")
            for _ in range(iters):
                t0 = time.perf_counter()
                jax.block_until_ready(run(blk, aux))
                best = min(best, time.perf_counter() - t0)
            out[f"{label}_rows_per_sec"] = round(rows / best)
        finally:
            kernels.FUSED_FORCE = None
    if "fused_rows_per_sec" in out and "peragg_rows_per_sec" in out:
        out["fused_speedup"] = round(
            out["fused_rows_per_sec"] / out["peragg_rows_per_sec"], 2)
    if check:
        oracle = run_oracle(
            prog, OracleTable(
                {n: (cols[n], valid[n]) for n in cols}, schema))
        for label, res in results.items():
            got = OracleTable.from_block(res)
            o_order = np.argsort(oracle.column("k"))
            g_order = np.argsort(np.asarray(got.column("k")))
            for name in got.cols:
                g = np.asarray(got.column(name), dtype=np.float64)
                o = np.asarray(oracle.column(name), dtype=np.float64)
                np.testing.assert_allclose(
                    g[g_order], o[o_order], rtol=1e-9,
                    err_msg=f"{label} vs oracle on {name}")
        out["oracle_check"] = "ok"
    return out


def bench_staging(rows: int, block_rows: int, iters: int) -> dict:
    """Block staging throughput: payloads -> rechunk -> from_numpy ->
    device blocks (the low-copy pipeline, prefetch on)."""
    import jax

    from ydb_tpu import dtypes
    from ydb_tpu.engine.reader import stream_blocks

    schema = dtypes.schema(("a", dtypes.INT64), ("b", dtypes.DOUBLE))
    rng = np.random.default_rng(3)
    chunk = 1 << 16
    payloads = []
    for off in range(0, rows, chunk):
        n = min(chunk, rows - off)
        payloads.append((
            {"a": rng.integers(0, 10 ** 9, n).astype(np.int64),
             "b": rng.random(n)},
            {"a": np.ones(n, dtype=bool), "b": np.ones(n, dtype=bool)},
        ))
    best = float("inf")
    n_blocks = 0
    for _ in range(max(1, iters)):
        t0 = time.perf_counter()
        blocks = list(stream_blocks(iter(payloads), ("a", "b"), schema,
                                    min(block_rows, rows)))
        jax.block_until_ready([b.columns["a"].data for b in blocks])
        best = min(best, time.perf_counter() - t0)
        n_blocks = len(blocks)
    return {"rows": rows, "block_rows": block_rows, "blocks": n_blocks,
            "staging_rows_per_sec": round(rows / best)}


def build_pruning_shard(rows: int, chunk_rows: int, commits: int = 4):
    """A ColumnShard holding a time-correlated events table: ``ts``
    increases with insertion order (the log/telemetry shape zone maps
    thrive on) while NOT being the PK, ``user`` is low-cardinality and
    ``val`` a decimal payload; ~3% NULL vals."""
    from ydb_tpu import dtypes
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig

    schema = dtypes.schema(
        ("event_id", dtypes.INT64, False),
        ("ts", dtypes.INT64, False),
        ("user", dtypes.INT32, False),
        ("val", dtypes.decimal(2)),
    )
    shard = ColumnShard(
        "prune", schema, MemBlobStore(), pk_column="event_id",
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           portion_chunk_rows=chunk_rows))
    rng = np.random.default_rng(11)
    per = rows // commits
    for c in range(commits):
        n = per if c < commits - 1 else rows - per * (commits - 1)
        base = c * per
        cols = {
            "event_id": (base + np.arange(n)).astype(np.int64),
            "ts": (base + np.arange(n)).astype(np.int64),
            "user": rng.integers(0, 64, n).astype(np.int32),
            "val": rng.integers(0, 10 ** 6, n).astype(np.int64),
        }
        validity = {"val": rng.random(n) > 0.03}
        shard.commit([shard.write(cols, validity)])
    return shard, rows


def bench_pruning(rows: int, chunk_rows: int, iters: int,
                  selectivity: float = 0.05, shard=None) -> dict:
    """Selective non-PK filter A/B: stats-on (zone pruning) vs
    stats-off, bit-identical results required. ``shard`` reuses an
    already-built events shard (bench.py's NDV pass shares one)."""
    from ydb_tpu import stats as stats_mod
    from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, \
        GroupByStep, Op, Program
    from ydb_tpu.ssa.program import lit

    if shard is None:
        shard, n = build_pruning_shard(rows, chunk_rows)
    else:
        shard, n = shard
    lo = int(n * 0.5)
    hi = int(n * (0.5 + selectivity))
    prog = Program((
        FilterStep(Call(Op.AND,
                        Call(Op.GE, Col("ts"), lit(lo)),
                        Call(Op.LT, Col("ts"), lit(hi)))),
        GroupByStep(("user",), (
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "val", "s"),
        )),
    ))
    out: dict = {"rows": n, "chunk_rows": chunk_rows,
                 "selectivity": selectivity}
    results = {}
    for label, force in (("stats", True), ("nostats", False)):
        stats_mod.STATS_FORCE = force
        try:
            best = float("inf")
            res = None
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                res = shard.scan(prog)
                best = min(best, time.perf_counter() - t0)
            results[label] = res
            p = dict(shard.last_scan_pruning)
            out[f"{label}_seconds"] = round(best, 4)
            out[f"{label}_chunks_read"] = p.get("chunks_read", 0)
            if force:
                out["chunks_skipped"] = p.get("chunks_skipped", 0)
                out["portions_skipped"] = p.get("portions_skipped", 0)
                out["chunks_skipped_per_sec"] = round(
                    p.get("chunks_skipped", 0) / max(best, 1e-9))
        finally:
            stats_mod.STATS_FORCE = None
    if out.get("nostats_seconds"):
        out["pruning_speedup"] = round(
            out["nostats_seconds"] / max(out["stats_seconds"], 1e-9), 2)
    ratio = out.get("nostats_chunks_read", 0) / max(
        out.get("stats_chunks_read", 1), 1)
    out["chunk_read_ratio"] = round(ratio, 2)
    # bit-identity between the two sides (group keys sort-aligned;
    # NULL slots compare by validity, not by their garbage payload)
    a, b = results["stats"], results["nostats"]
    oa = np.argsort(np.asarray(a.column("user")))
    ob = np.argsort(np.asarray(b.column("user")))
    for name in a.cols:
        av, aok = (np.asarray(x) for x in a.cols[name])
        bv, bok = (np.asarray(x) for x in b.cols[name])
        if not np.array_equal(aok[oa], bok[ob]) or not np.array_equal(
                np.where(aok, av, 0)[oa], np.where(bok, bv, 0)[ob]):
            raise AssertionError(f"stats on/off mismatch on {name}")
    out["identical"] = True
    return out


def bench_resident(rows: int, chunk_rows: int, iters: int,
                   shard=None) -> dict:
    """HBM-resident tier A/B (equality-asserted): the same shard
    scanned warm with the resident tier forced on (heat-promoted, then
    drained, so blocks assemble from pinned device arrays) vs forced
    off (every scan re-stages from host bytes). The gap is ROADMAP
    item 1's engine-vs-kernel distance at micro scale."""
    from ydb_tpu.engine import resident as resident_mod
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program

    if shard is None:
        shard, n = build_pruning_shard(rows, chunk_rows)
    else:
        shard, n = shard
    prog = Program((
        GroupByStep(("user",), (
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "val", "s"),
        )),
    ))
    out: dict = {"rows": n}
    results = {}
    for label, force in (("resident", True), ("staged", False)):
        resident_mod.RESIDENT_FORCE = force
        try:
            if force:
                # heat-driven promotion: two host-path scans cross the
                # threshold, drain pins every portion before timing
                for _ in range(2):
                    shard.scan(prog)
                shard.resident.drain()
            best = float("inf")
            res = None
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                res = shard.scan(prog)
                best = min(best, time.perf_counter() - t0)
            results[label] = res
            out[f"{label}_seconds"] = round(best, 5)
            out[f"{label}_rows_per_sec"] = round(n / max(best, 1e-9))
            if force:
                snap = shard.resident.snapshot()
                out["resident_portions"] = snap["portions"]
                out["resident_bytes"] = snap["bytes"]
        finally:
            resident_mod.RESIDENT_FORCE = None
    out["resident_speedup"] = round(
        out["staged_seconds"] / max(out["resident_seconds"], 1e-9), 2)
    # bit-identity between the two sides (group keys sort-aligned)
    a, b = results["resident"], results["staged"]
    oa = np.argsort(np.asarray(a.column("user")))
    ob = np.argsort(np.asarray(b.column("user")))
    for name in a.cols:
        av, aok = (np.asarray(x) for x in a.cols[name])
        bv, bok = (np.asarray(x) for x in b.cols[name])
        if not np.array_equal(aok[oa], bok[ob]) or not np.array_equal(
                np.where(aok, av, 0)[oa], np.where(bok, bv, 0)[ob]):
            raise AssertionError(
                f"resident on/off mismatch on {name}")
    out["identical"] = True
    shard.resident.clear()
    return out


def bench_streaming(rows: int, chunk_rows: int, iters: int) -> dict:
    """Morsel-pipeline A/B (equality-asserted): the same COLD
    DirBlobStore scan serialized (stream_sched.PIPELINE_FORCE=False,
    the YDB_TPU_STREAM_PIPELINE=0 path) vs morsel-pipelined, rows/s
    both sides, plus ONE profiled pipelined run whose data-movement
    timeline yields the measured ``movement|compute`` overlap
    coefficient. The blob store is on disk and the OS page cache is the
    only warmth, so both sides pay real read+decode per scan — the
    pipeline's overlap is what separates them."""
    import tempfile

    from ydb_tpu import dtypes
    from ydb_tpu.engine import stream_sched
    from ydb_tpu.engine.blobs import DirBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.obs import profile as profile_mod
    from ydb_tpu.obs import timeline
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program

    schema = dtypes.schema(
        ("event_id", dtypes.INT64, False),
        ("user", dtypes.INT32, False),
        ("val", dtypes.decimal(2)),
    )
    prog = Program((
        GroupByStep(("user",), (
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "val", "s"),
        )),
    ))
    out: dict = {"rows": rows, "chunk_rows": chunk_rows}
    rng = np.random.default_rng(23)
    with tempfile.TemporaryDirectory(prefix="ydbtpu_kb_stream_") as tmp:
        shard = ColumnShard(
            "stream", schema, DirBlobStore(tmp), pk_column="event_id",
            # several blocks per scan: compute on block k must have
            # movement for k+1.. to overlap with, or the coefficient is
            # structurally zero
            config=ShardConfig(compact_portion_threshold=10 ** 9,
                               portion_chunk_rows=chunk_rows,
                               scan_block_rows=max(1024, rows // 8)))
        commits = 6
        per = rows // commits
        for c in range(commits):
            n = per if c < commits - 1 else rows - per * (commits - 1)
            base = c * per
            cols = {
                "event_id": (base + np.arange(n)).astype(np.int64),
                "user": rng.integers(0, 64, n).astype(np.int32),
                "val": rng.integers(0, 10 ** 6, n).astype(np.int64),
            }
            validity = {"val": rng.random(n) > 0.03}
            shard.commit([shard.write(cols, validity)])
        shard.scan(prog)  # compile + page-cache warmup, both sides
        results = {}
        for label, force in (("serialized", False),
                             ("pipelined", True)):
            stream_sched.PIPELINE_FORCE = force
            try:
                best = float("inf")
                res = None
                for _ in range(max(1, iters)):
                    t0 = time.perf_counter()
                    res = shard.scan(prog)
                    best = min(best, time.perf_counter() - t0)
                results[label] = res
                out[f"{label}_seconds"] = round(best, 5)
                out[f"{label}_rows_per_sec"] = round(
                    rows / max(best, 1e-9))
            finally:
                stream_sched.PIPELINE_FORCE = None
        out["pipeline_speedup"] = round(
            out["serialized_seconds"]
            / max(out["pipelined_seconds"], 1e-9), 2)
        # overlap coefficient of ONE pipelined run, timeline forced on
        stream_sched.PIPELINE_FORCE = True
        prev = timeline.TIMELINE_FORCE
        timeline.TIMELINE_FORCE = True
        try:
            with profile_mod.profiled("kb_streaming") as ph:
                shard.scan(prog)
        finally:
            timeline.TIMELINE_FORCE = prev
            stream_sched.PIPELINE_FORCE = None
        occ = ph.profile.stage_occupancy or {}
        ov = (occ.get("overlap") or {}).get("movement|compute")
        if ov is not None:
            out["movement_compute_overlap"] = ov
        if shard.last_scan_pipeline:
            out["pipeline"] = dict(shard.last_scan_pipeline)
        # bit-identity between the two sides (group keys sort-aligned;
        # NULL slots compare by validity, not their garbage payload)
        a, b = results["serialized"], results["pipelined"]
        oa = np.argsort(np.asarray(a.column("user")))
        ob = np.argsort(np.asarray(b.column("user")))
        for name in a.cols:
            av, aok = (np.asarray(x) for x in a.cols[name])
            bv, bok = (np.asarray(x) for x in b.cols[name])
            if not np.array_equal(aok[oa], bok[ob]) \
                    or not np.array_equal(np.where(aok, av, 0)[oa],
                                          np.where(bok, bv, 0)[ob]):
                raise AssertionError(
                    f"streaming on/off mismatch on {name}")
        out["identical"] = True
    return out


def bench_profile_overhead(sf: float, iters: int, block_rows: int,
                           assert_within: float | None = None) -> dict:
    """Warm TPC-H Q1 with query profiling ON (traced root span — the
    session's default-on state: spans, stage timers, probe attrs,
    profile assembly) vs OFF (no active trace, the YDB_TPU_PROFILE=0
    path), plus a third side with the data-movement timeline ring
    enabled (YDB_TPU_TIMELINE=1 state). ``assert_within`` fails the
    bench when the ON side exceeds OFF by more than that fraction (the
    default-on budget); it also asserts the timeline's contract: ZERO
    ring events on the disabled path, and the enabled ring within 3%
    of the profiled run."""
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.obs import profile as profile_mod
    from ydb_tpu.obs import timeline
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    li = data.tables["lineitem"]
    n = len(li["l_orderkey"])
    shard = ColumnShard(
        "profov", tpch.LINEITEM_SCHEMA, MemBlobStore(),
        dicts=data.dicts,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=block_rows,
                           portion_chunk_rows=1 << 16))
    shard.commit([shard.write(dict(li))])
    prog = tpch.q1_program()

    def run_off():
        return shard.scan(prog)

    def run_on():
        with profile_mod.profiled("q1") as h:
            shard.scan(prog)
        return h

    def run_tl():
        # clear between rounds: profile assembly computes occupancy by
        # scanning the ring, so letting events accumulate across bench
        # rounds would charge round k with O(k) scan cost and skew the
        # A/B (a real query's working set is one ring pass of ~70
        # events, which is what this measures)
        timeline.RING.clear()
        timeline.TIMELINE_FORCE = True
        try:
            return run_on()
        finally:
            timeline.TIMELINE_FORCE = False

    prev_force = timeline.TIMELINE_FORCE
    timeline.TIMELINE_FORCE = False  # pin the A/B regardless of env
    try:
        run_off()  # warm: compile + scan-cache fill, shared by all
        run_on()
        run_tl()
        # disabled-path contract: a profiled query with the timeline
        # OFF must record nothing (the gate is the whole cost)
        rec0 = timeline.RING.recorded
        run_on()
        disabled_events = timeline.RING.recorded - rec0
        best = {"off": float("inf"), "on": float("inf"),
                "tl": float("inf")}
        # interleave the sides so host drift hits all equally
        for _ in range(max(1, iters)):
            for label, fn in (("off", run_off), ("on", run_on),
                              ("tl", run_tl)):
                t0 = time.perf_counter()
                fn()
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
        # the ring's own cost is ~0.2% — far below run-to-run jitter
        # on a min-of-iters, so the on/tl pair gets extra head-to-head
        # rounds for a stable floor before the 3% verdict
        for _ in range(max(0, 8 - max(1, iters))):
            for label, fn in (("on", run_on), ("tl", run_tl)):
                t0 = time.perf_counter()
                fn()
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
    finally:
        timeline.TIMELINE_FORCE = prev_force
    out = {
        "rows": n, "sf": sf,
        "profile_off_seconds": round(best["off"], 6),
        "profile_on_seconds": round(best["on"], 6),
        "profile_off_rows_per_sec": round(n / best["off"]),
        "profile_on_rows_per_sec": round(n / best["on"]),
        "overhead_pct": round(100 * (best["on"] / best["off"] - 1), 2),
        "timeline_on_seconds": round(best["tl"], 6),
        "timeline_overhead_pct": round(
            100 * (best["tl"] / best["on"] - 1), 2),
        "timeline_disabled_events": disabled_events,
    }
    if assert_within is not None:
        # only claim a budget verdict when one was actually checked
        if best["on"] > best["off"] * (1 + assert_within):
            raise AssertionError(
                f"profiling overhead {out['overhead_pct']}% exceeds "
                f"the {assert_within * 100:g}% budget")
        out["within_budget"] = True
        if disabled_events:
            raise AssertionError(
                f"timeline ring recorded {disabled_events} events "
                f"while disabled (gate leak)")
        # 2ms absolute slack: at micro scale the 3% band is inside
        # timer jitter; at real scale the relative bound dominates.
        # The hard <3% acceptance bound is the DISABLED path, held by
        # the on/off budget above plus the zero-event gate check; this
        # enabled-ring bound is a regression tripwire. Like every
        # other bench here it widens to the caller's smoke fraction
        # (the 3% floor still binds any tighter caller).
        tl_frac = max(0.03, assert_within)
        if best["tl"] > best["on"] * (1 + tl_frac) + 2e-3:
            raise AssertionError(
                f"timeline ring overhead "
                f"{out['timeline_overhead_pct']}% exceeds the "
                f"{tl_frac * 100:g}% budget")
        out["timeline_within_budget"] = True
    return out


def bench_chaos_overhead(sf: float, iters: int, block_rows: int,
                         assert_within: float | None = None) -> dict:
    """Warm TPC-H Q1 with the chaos subsystem fully DISARMED (the
    production state: every injection site is one module-global bool
    check) vs ARMED with p=0.0 on the hot sites (the dormant-scenario
    state: per-site lookup + seeded roll, nothing ever fires). The
    disabled path is the acceptance bound — chaos must be free when
    off; ``assert_within`` fails the bench when the armed side exceeds
    disarmed by more than that fraction."""
    from ydb_tpu import chaos
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    li = data.tables["lineitem"]
    n = len(li["l_orderkey"])
    shard = ColumnShard(
        "chaosov", tpch.LINEITEM_SCHEMA, MemBlobStore(),
        dicts=data.dicts,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=block_rows,
                           portion_chunk_rows=1 << 16))
    shard.commit([shard.write(dict(li))])
    prog = tpch.q1_program()

    # p=0.0 on every site the Q1 scan crosses: the armed side pays the
    # full lookup+roll machinery without a single fault firing (a
    # fired fault would change WHAT runs, not how fast the gate is)
    dormant = chaos.Scenario(seed=7, sites={
        "blob.get": {"kind": "io_error", "p": 0.0},
        "blob.get_range": {"kind": "io_error", "p": 0.0},
        "conveyor.task": {"kind": "delay", "p": 0.0},
    })

    def run_off():
        return shard.scan(prog)

    def run_armed():
        chaos.install(dormant)
        try:
            return shard.scan(prog)
        finally:
            chaos.clear()

    prev_force = chaos.CHAOS_FORCE
    try:
        chaos.CHAOS_FORCE = None
        chaos.clear()  # disarm + zero counters from any earlier run
        run_off()  # warm: compile + scan-cache fill, shared by both
        if chaos.counters_snapshot().get("sites"):
            raise AssertionError(
                "chaos sites counted hits on the disarmed path")
        chaos.CHAOS_FORCE = True  # open the gate for install()
        run_armed()
        best = {"off": float("inf"), "armed": float("inf")}
        # interleave the sides so host drift hits both equally
        for _ in range(max(1, iters)):
            for label, fn in (("off", run_off), ("armed", run_armed)):
                t0 = time.perf_counter()
                fn()
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
    finally:
        chaos.clear()
        chaos.CHAOS_FORCE = prev_force
    out = {
        "rows": n, "sf": sf,
        "chaos_off_seconds": round(best["off"], 6),
        "chaos_armed_seconds": round(best["armed"], 6),
        "chaos_off_rows_per_sec": round(n / best["off"]),
        "chaos_armed_rows_per_sec": round(n / best["armed"]),
        "overhead_pct": round(
            100 * (best["armed"] / best["off"] - 1), 2),
    }
    if assert_within is not None:
        if best["armed"] > best["off"] * (1 + assert_within):
            raise AssertionError(
                f"chaos armed overhead {out['overhead_pct']}% exceeds "
                f"the {assert_within * 100:g}% budget")
        out["within_budget"] = True
    return out


def bench_leaksan_overhead(sf: float, iters: int, block_rows: int,
                           assert_within: float | None = None) -> dict:
    """Warm TPC-H Q1 with the leak sanitizer DISABLED (the production
    state: every ``track()`` site is one module-global bool check
    returning None, every ``close()`` a None test) vs FORCED ON (every
    acquisition allocates a stack-bearing handle). Two invariants
    besides the timing: the disabled side must track ZERO handles, and
    the armed side must drain back to zero once the scan's conveyor
    work completes — a leak here is a bug in the resource layers, not a
    bench artifact. ``assert_within`` fails the bench when the armed
    side exceeds disabled by more than that fraction."""
    from ydb_tpu.analysis import leaksan
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.runtime.conveyor import shared_conveyor
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    li = data.tables["lineitem"]
    n = len(li["l_orderkey"])
    shard = ColumnShard(
        "leakov", tpch.LINEITEM_SCHEMA, MemBlobStore(),
        dicts=data.dicts,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=block_rows,
                           portion_chunk_rows=1 << 16))
    shard.commit([shard.write(dict(li))])
    prog = tpch.q1_program()

    def run_off():
        leaksan.set_force(False)
        return shard.scan(prog)

    def run_armed():
        leaksan.set_force(True)
        try:
            return shard.scan(prog)
        finally:
            leaksan.set_force(False)

    prev_force = leaksan.LEAKSAN_FORCE
    try:
        leaksan.reset()
        run_off()  # warm: compile + scan-cache fill, shared by both
        if leaksan.counts():
            raise AssertionError(
                "leaksan tracked handles on the disabled path: "
                f"{leaksan.counts()}")
        run_armed()  # warm the armed side (handle-alloc code paths)
        shared_conveyor().wait_idle(timeout=30.0)
        if leaksan.counts():
            raise AssertionError(
                f"armed warm Q1 leaked handles: {leaksan.counts()}")
        best = {"off": float("inf"), "armed": float("inf")}
        # interleave the sides so host drift hits both equally
        for _ in range(max(1, iters)):
            for label, fn in (("off", run_off), ("armed", run_armed)):
                t0 = time.perf_counter()
                fn()
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
    finally:
        leaksan.set_force(prev_force)
        leaksan.reset()
    out = {
        "rows": n, "sf": sf,
        "leaksan_off_seconds": round(best["off"], 6),
        "leaksan_armed_seconds": round(best["armed"], 6),
        "leaksan_off_rows_per_sec": round(n / best["off"]),
        "leaksan_armed_rows_per_sec": round(n / best["armed"]),
        "overhead_pct": round(
            100 * (best["armed"] / best["off"] - 1), 2),
        "drained": True,
    }
    if assert_within is not None:
        if best["armed"] > best["off"] * (1 + assert_within):
            raise AssertionError(
                f"leaksan armed overhead {out['overhead_pct']}% "
                f"exceeds the {assert_within * 100:g}% budget")
        out["within_budget"] = True
    return out


def bench_memsan_overhead(sf: float, iters: int, block_rows: int,
                          assert_within: float | None = None) -> dict:
    """Warm TPC-H Q1 with the memory sanitizer DISARMED (the
    production state: every ``armed()`` check is one module-global bool
    read, the raw allocators unpatched) vs FORCED ON (allocator
    wrappers installed, every charging seam walking ``nbytes_of`` over
    its pytree). Two invariants besides the timing: the armed warm
    statement must charge at least once (the seams are alive) and make
    ZERO unbudgeted device allocations — the runtime acceptance of
    devmem M001 on the engine tier. ``assert_within`` fails the bench
    when the armed side exceeds disarmed by more than that fraction
    (the <3% warm-Q1 tripwire)."""
    from ydb_tpu.analysis import memsan
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    li = data.tables["lineitem"]
    n = len(li["l_orderkey"])
    shard = ColumnShard(
        "memov", tpch.LINEITEM_SCHEMA, MemBlobStore(),
        dicts=data.dicts,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=block_rows,
                           portion_chunk_rows=1 << 16))
    shard.commit([shard.write(dict(li))])
    prog = tpch.q1_program()

    def run_off():
        memsan.set_force(False)
        return shard.scan(prog)

    def run_armed():
        memsan.set_force(True)
        st = memsan.begin_statement("q1")
        try:
            return shard.scan(prog)
        finally:
            memsan.end_statement(st, enforce=False)
            memsan.set_force(False)

    warm_snap = None
    try:
        memsan.reset()
        run_off()  # warm: compile + scan-cache fill, shared by both
        run_armed()  # warm the armed side (wrapper + charge paths)
        # one measured warm armed statement: the byte-ledger acceptance
        memsan.set_force(True)
        st = memsan.begin_statement("q1")
        try:
            shard.scan(prog)
        finally:
            warm_snap = memsan.end_statement(st, enforce=False)
            memsan.set_force(False)
        if warm_snap["unbudgeted"]:
            raise AssertionError(
                "armed warm Q1 made unbudgeted device allocations: "
                f"{warm_snap}")
        best = {"off": float("inf"), "armed": float("inf")}
        # interleave the sides so host drift hits both equally
        for _ in range(max(1, iters)):
            for label, fn in (("off", run_off), ("armed", run_armed)):
                t0 = time.perf_counter()
                fn()
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
    finally:
        memsan.set_force(None)
        memsan.reset()
    out = {
        "rows": n, "sf": sf,
        "memsan_off_seconds": round(best["off"], 6),
        "memsan_armed_seconds": round(best["armed"], 6),
        "memsan_off_rows_per_sec": round(n / best["off"]),
        "memsan_armed_rows_per_sec": round(n / best["armed"]),
        "warm_peak_bytes": warm_snap["peak"],
        "warm_charges": warm_snap["charges"],
        "warm_unbudgeted": 0,
        "overhead_pct": round(
            100 * (best["armed"] / best["off"] - 1), 2),
    }
    if assert_within is not None:
        if best["armed"] > best["off"] * (1 + assert_within):
            raise AssertionError(
                f"memsan armed overhead {out['overhead_pct']}% "
                f"exceeds the {assert_within * 100:g}% budget")
        out["within_budget"] = True
    return out


def bench_admission_overhead(sf: float, iters: int,
                             assert_within: float | None = None,
                             ) -> dict:
    """Warm TPC-H Q1 through the full ``Session.execute`` path with NO
    front door installed (the default state: one ``cluster.front_door
    is None`` attribute test per statement) vs the multi-tenant
    admission plane INSTALLED (``serving.install``) serving a single
    default-pool client — the uncontended fast path: tenant resolve,
    seat grant + release under the door lock, per-tenant SLO counters.
    The front door must be near-free for the single-tenant case or it
    cannot sit on every statement; ``assert_within`` fails the bench
    when the armed side exceeds the bare path by more than that
    fraction (the serving README's bar: <3% warm Q1)."""
    from ydb_tpu import serving
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch
    from ydb_tpu.workload.queries import TPCH

    data = tpch.TpchData(sf=sf, seed=5)
    n = len(data.tables["lineitem"]["l_orderkey"])
    q1 = TPCH["q1"]

    def boot(with_door):
        c = Cluster()
        if with_door:
            serving.install(c)
        s = c.session()
        schema = data.schema("lineitem")
        cols = ", ".join(f"{f.name} {type_to_str(f.type)}"
                         for f in schema.fields)
        s.execute(f"CREATE TABLE lineitem ({cols}, "
                  f"PRIMARY KEY (l_orderkey)) WITH (shards = 1)")
        src = data.tables["lineitem"]
        arrays = {}
        for f in schema.fields:
            v = src[f.name]
            if f.type.is_string:
                arrays[f.name] = [
                    bytes(x) for x in data.dicts[f.name].decode(
                        np.asarray(v, dtype=np.int32))]
            else:
                arrays[f.name] = v
        c.tables["lineitem"].insert(arrays)
        c._invalidate_plans()
        s.execute(q1)  # warm plan + compile caches
        return c, s

    sides = {"off": boot(False), "on": boot(True)}
    try:
        best = {"off": float("inf"), "on": float("inf")}
        # interleave the sides so host drift hits both equally
        for _ in range(max(1, iters)):
            for label, (_, s) in sides.items():
                t0 = time.perf_counter()
                s.execute(q1)
                best[label] = min(best[label],
                                  time.perf_counter() - t0)
        snap = sides["on"][0].front_door.snapshot()
        pool = snap.get(serving.DEFAULT_TENANT, {})
        if not pool.get("admitted"):
            raise AssertionError(
                "front door counted no admissions on the armed side — "
                "the bench did not exercise the admission plane")
    finally:
        for c, _ in sides.values():
            c.stop()
    out = {
        "rows": n, "sf": sf,
        "admission_off_seconds": round(best["off"], 6),
        "admission_on_seconds": round(best["on"], 6),
        "admission_off_rows_per_sec": round(n / best["off"]),
        "admission_on_rows_per_sec": round(n / best["on"]),
        "overhead_pct": round(
            100 * (best["on"] / best["off"] - 1), 2),
        "admitted": pool.get("admitted"),
        "shed": pool.get("shed"),
    }
    if assert_within is not None:
        if best["on"] > best["off"] * (1 + assert_within):
            raise AssertionError(
                f"front-door admission overhead {out['overhead_pct']}% "
                f"exceeds the {assert_within * 100:g}% budget")
        out["within_budget"] = True
    return out


def bench_fusion(sf: float, iters: int) -> dict:
    """Whole-plan fusion A/B: TPC-H Q3 (semi + inner join feeding a
    grouped two-phase-aggregate top-k) executed fused — one
    donated-buffer dispatch per shape class (ssa.plan_fuse) — vs the
    per-node memo walk, same Database both sides, results asserted
    bit-identical (Q3's sort is fully tie-broken, so rows compare
    positionally)."""
    import jax

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan.executor import Database, execute_plan
    from ydb_tpu.ssa import plan_fuse
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    db = Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts)
    plan = tpch.q3_plan()
    sig = plan_fuse.plan_signature(plan, db)
    if sig is None:
        raise AssertionError("q3 plan did not fuse")
    n = len(data.tables["lineitem"]["l_orderkey"])

    def run(force):
        old = plan_fuse.FUSE_FORCE
        plan_fuse.FUSE_FORCE = force
        try:
            return jax.block_until_ready(
                execute_plan(plan, db, use_dq=False))
        finally:
            plan_fuse.FUSE_FORCE = old

    out: dict = {
        "rows": n, "sf": sf,
        # the walk dispatches (at least) one compiled fragment per plan
        # node; the fused path replaces all of them with one dispatch
        "fragment_dispatches": sig.fused_stages,
        "fused_dispatches": 1,
        "fragments_elided": sig.fused_stages - 1,
    }
    results = {}
    best = {"fused": float("inf"), "walk": float("inf")}
    for label, force in (("fused", True), ("walk", False)):
        results[label] = run(force)  # warm: trace + compile caches
    # interleave the sides so host drift hits both equally
    for _ in range(max(1, iters)):
        for label, force in (("fused", True), ("walk", False)):
            t0 = time.perf_counter()
            run(force)
            best[label] = min(best[label], time.perf_counter() - t0)
    for label in ("fused", "walk"):
        out[f"{label}_seconds"] = round(best[label], 6)
        out[f"{label}_rows_per_sec"] = round(n / best[label])
    out["fused_speedup"] = round(best["walk"] / best["fused"], 2)
    a, b = results["fused"], results["walk"]
    assert a.schema.names == b.schema.names
    av, aok = a.to_numpy(), a.validity_numpy()
    bv, bok = b.to_numpy(), b.validity_numpy()
    for name in a.schema.names:
        if not np.array_equal(aok[name], bok[name]) or not np.array_equal(
                np.where(aok[name], av[name], 0),
                np.where(bok[name], bv[name], 0)):
            raise AssertionError(f"fused/walk mismatch on {name}")
    out["identical"] = True
    return out


def bench_batching(sf: float, iters: int, batch: int = 4) -> dict:
    """Micro-batched fused dispatch A/B (the kqp/batch.py serving
    tier's two device paths, measured bare):

    * serial — B back-to-back non-donating fused dispatches
      (``FusedPlan.run_shared``), one per statement, the batching-off
      baseline;
    * stacked — the SAME B statements' staged inputs stacked along a
      leading axis into ONE vmapped dispatch (``run_stacked``), each
      member sliced off the batched result (``slice_member``);
    * dedup — the identical-inputs fast path: ONE dispatch whose result
      every member shares (what the dispatcher runs when all members
      staged byte-identical blocks).

    Every stacked member and the dedup result are asserted bit-identical
    to the serial dispatch — the acceptance invariant the serving tier
    rides on."""
    import jax

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan.executor import Database, _stage_fused_site
    from ydb_tpu.ssa import plan_fuse
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    db = Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts)
    plan = tpch.q3_plan()
    sig = plan_fuse.plan_signature(plan, db)
    if sig is None:
        raise AssertionError("q3 plan did not fuse")
    fused = plan_fuse.build(sig, db)
    inputs = {s.key: _stage_fused_site(s, db, None, donate=False)[0]
              for s in sig.sites}
    n = len(data.tables["lineitem"]["l_orderkey"])

    def run_serial():
        out = None
        for _ in range(batch):
            out, totals = fused.run_shared(inputs)
            assert not fused.overflowed(totals)
        return jax.block_until_ready(out)

    def run_stack():
        out, totals = fused.run_stacked([inputs] * batch)
        assert not fused.overflowed(totals)
        return jax.block_until_ready(out)

    def run_dedup():
        out, totals = fused.run_shared(inputs)
        assert not fused.overflowed(totals)
        return jax.block_until_ready(out)

    sides = {"serial": run_serial, "stacked": run_stack,
             "dedup": run_dedup}
    results = {k: f() for k, f in sides.items()}  # warm (trace+compile)
    best = {k: float("inf") for k in sides}
    for _ in range(max(1, iters)):
        # interleaved so host drift hits every side equally
        for k, f in sides.items():
            t0 = time.perf_counter()
            f()
            best[k] = min(best[k], time.perf_counter() - t0)

    ser = results["serial"]
    sv, sok = ser.to_numpy(), ser.validity_numpy()

    def check(blk, label):
        bv, bok = blk.to_numpy(), blk.validity_numpy()
        for name in ser.schema.names:
            if not np.array_equal(sok[name], bok[name]) \
                    or not np.array_equal(
                        np.where(sok[name], sv[name], 0),
                        np.where(bok[name], bv[name], 0)):
                raise AssertionError(f"{label} mismatch on {name}")

    for i in range(batch):
        check(plan_fuse.slice_member(results["stacked"], i),
              f"stacked[{i}]")
    check(results["dedup"], "dedup")

    out = {"rows": n, "sf": sf, "batch": batch, "identical": True}
    for k in sides:
        out[f"{k}_seconds"] = round(best[k], 6)
        # every side serves all B statements: serial with B dispatches,
        # stacked/dedup with one
        out[f"{k}_seconds_per_statement"] = round(best[k] / batch, 6)
    out["stacked_speedup"] = round(best["serial"] / best["stacked"], 2)
    out["dedup_speedup"] = round(best["serial"] / best["dedup"], 2)
    return out


def bench_shuffle(rows_per_dev: int, iters: int,
                  with_skew: bool = True) -> dict:
    """Stats-sized vs full-capacity shuffle A/B on a virtual mesh.

    Uniform random keys repartitioned over the ``shard`` axis with the
    send bucket sized two ways: full local capacity (always sufficient,
    ships ndev x capacity rows) vs ``shuffle.size_buckets`` (mean load x
    safety margin + the count-min heavy-hitter bound from a real sketch
    over the keys). Row multisets asserted equal between the sides and
    key colocation checked; on a uniform distribution the stats bucket
    must be >=4x smaller. A 100%-skew case (every key identical, no
    stats) then exercises the overflow protocol: the undersized exchange
    reports its worst per-destination count, the bucket grows to that
    shape class, and the re-exchange is asserted lossless."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ydb_tpu import dtypes
    from ydb_tpu.blocks.block import TableBlock
    from ydb_tpu.parallel import shuffle
    from ydb_tpu.parallel.dist import _local, _relocal, stack_blocks
    from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
    from ydb_tpu.ssa.plan_fuse import shape_class
    from ydb_tpu.stats.sketch import CountMinSketch

    n_dev = len(jax.devices())
    if n_dev < 8:
        # bucket sizing is meaningful relative to the fan-out; under 8
        # destinations the mean-load bucket cannot hit the 4x target
        return {"skipped": f"needs >=8 devices, have {n_dev}"}
    n_dev = 8
    mesh = make_mesh(n_dev)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    bytes_per_row = sum(
        np.dtype(f.type.physical).itemsize + 1 for f in sch.fields)

    def stage(key_arrays):
        blocks = [
            TableBlock.from_numpy(
                {"k": key_arrays[d],
                 "v": np.arange(len(key_arrays[d]), dtype=np.int64)
                 + d * rows_per_dev},
                sch, capacity=rows_per_dev)
            for d in range(n_dev)
        ]
        return jax.device_put(
            stack_blocks(blocks), NamedSharding(mesh, P(SHARD_AXIS)))

    def exchange(B):
        def go(st):
            blk, worst = shuffle.repartition(
                _local(st), ["k"], n_dev, bucket_rows=B, with_counts=True)
            return _relocal(blk), worst
        return jax.jit(shard_map(
            go, mesh=mesh, in_specs=P(SHARD_AXIS),
            out_specs=(P(SHARD_AXIS), P()), check_vma=False))

    def collect(out):
        lens = np.asarray(out.length)
        ks = np.asarray(out.columns["k"].data)
        vs = np.asarray(out.columns["v"].data)
        rows, per_dev = [], []
        for d in range(n_dev):
            k, v = ks[d][: lens[d]], vs[d][: lens[d]]
            rows.extend(zip(k.tolist(), v.tolist()))
            per_dev.append(set(k.tolist()))
        return rows, per_dev

    rng = np.random.default_rng(11)
    uniform = [rng.integers(0, 1 << 30, rows_per_dev).astype(np.int64)
               for _ in range(n_dev)]
    want = sorted(
        (int(k), int(d * rows_per_dev + i))
        for d in range(n_dev) for i, k in enumerate(uniform[d]))

    sk = CountMinSketch()
    for arr in uniform:
        sk.add_many(arr)
    old = shuffle.SHUFFLE_STATS_FORCE
    shuffle.SHUFFLE_STATS_FORCE = True
    try:
        stats_B = shuffle.size_buckets(
            rows_per_dev, n_dev, heavy=sk.max_freq())
    finally:
        shuffle.SHUFFLE_STATS_FORCE = old
    full_B = rows_per_dev

    total = n_dev * rows_per_dev
    out: dict = {
        "rows": total, "devices": n_dev,
        "full_bucket_rows": full_B, "stats_bucket_rows": stats_B,
        "heavy_bound": sk.max_freq(),
        "capacity_ratio": round(full_B / stats_B, 2),
        # every device sends ndev buckets of B rows each exchange
        "full_bytes_exchanged": n_dev * n_dev * full_B * bytes_per_row,
        "stats_bytes_exchanged": n_dev * n_dev * stats_B * bytes_per_row,
    }
    assert out["capacity_ratio"] >= 4, (
        f"uniform keys sized {stats_B} vs full {full_B}: "
        f"ratio {out['capacity_ratio']} < 4")

    best = {}
    results = {}
    for label, B in (("stats", stats_B), ("full", full_B)):
        fn = exchange(B)
        st = stage(uniform)
        blk, worst = jax.block_until_ready(fn(st))
        assert int(np.asarray(worst)) <= B, (
            f"{label} bucket {B} overflowed on uniform keys")
        results[label] = blk
        best[label] = float("inf")
        for _ in range(max(1, iters)):
            st = stage(uniform)
            t0 = time.perf_counter()
            jax.block_until_ready(fn(st))
            best[label] = min(best[label], time.perf_counter() - t0)
        out[f"{label}_rows_per_sec"] = round(total / best[label])
    out["shuffle_speedup"] = round(best["full"] / best["stats"], 2)

    for label, blk in results.items():
        rows, per_dev = collect(blk)
        assert sorted(rows) == want, f"{label} exchange lost rows"
        for i in range(n_dev):
            for j in range(i + 1, n_dev):
                assert not (per_dev[i] & per_dev[j]), (
                    f"{label}: key on two shards")
    out["identical"] = True

    # 100% skew, no stats: every row routes to one destination, so the
    # mean-sized bucket must overflow, report its worst count, grow to
    # that shape class, and re-exchange losslessly
    if not with_skew:  # smoke keeps tier-1 cheap; --shuffle runs it
        return out
    skew = [np.full(rows_per_dev, 42, dtype=np.int64)
            for _ in range(n_dev)]
    shuffle.SHUFFLE_STATS_FORCE = True
    try:
        B = shuffle.size_buckets(rows_per_dev, n_dev, heavy=0)
    finally:
        shuffle.SHUFFLE_STATS_FORCE = old
    skew_out: dict = {"initial_bucket_rows": B, "grows": 0}
    while True:
        blk, worst = jax.block_until_ready(exchange(B)(stage(skew)))
        w = int(np.asarray(worst))
        if w <= B:
            break
        B = shape_class(w)
        skew_out["grows"] += 1
    skew_out["grown_bucket_rows"] = B
    assert skew_out["grows"] >= 1, "skew case never overflowed"
    rows, _ = collect(blk)
    skew_want = sorted(
        (42, int(d * rows_per_dev + i))
        for d in range(n_dev) for i in range(rows_per_dev))
    assert sorted(rows) == skew_want, "skew grow lost rows"
    skew_out["identical"] = True
    out["skew"] = skew_out
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ydb_tpu.obs.kernelbench",
        description="group-by + block staging micro-benchmarks")
    ap.add_argument("--rows", type=int, default=1 << 21)
    ap.add_argument("--groups", type=int, default=16)
    ap.add_argument("--aggs", type=int, default=4)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--block-rows", type=int, default=1 << 18)
    ap.add_argument("--pruning", action="store_true",
                    help="zone-map scan-pruning A/B micro-bench")
    ap.add_argument("--chunk-rows", type=int, default=1 << 14,
                    help="portion chunk size for --pruning")
    ap.add_argument("--streaming", action="store_true",
                    help="morsel-pipeline vs serialized cold-scan A/B")
    ap.add_argument("--resident", action="store_true",
                    help="HBM-resident vs staged warm scan A/B")
    ap.add_argument("--profile-overhead", action="store_true",
                    help="profiling on-vs-off warm Q1 A/B micro-bench")
    ap.add_argument("--chaos-overhead", action="store_true",
                    help="chaos disarmed vs armed-dormant warm Q1 A/B")
    ap.add_argument("--leaksan-overhead", action="store_true",
                    help="leak sanitizer disabled vs armed warm Q1 A/B")
    ap.add_argument("--admission-overhead", action="store_true",
                    help="front door absent vs installed warm Q1 A/B")
    ap.add_argument("--memsan-overhead", action="store_true",
                    help="memory sanitizer disarmed vs armed warm Q1"
                         " A/B")
    ap.add_argument("--fusion", action="store_true",
                    help="whole-plan fused vs per-fragment warm Q3 A/B")
    ap.add_argument("--batching", action="store_true",
                    help="stacked/dedup vs serial fused dispatch A/B")
    ap.add_argument("--batch", type=int, default=4,
                    help="members per micro-batch for --batching")
    ap.add_argument("--shuffle", action="store_true",
                    help="stats-sized vs full-capacity shuffle A/B")
    ap.add_argument("--shuffle-rows", type=int, default=1 << 15,
                    help="rows per device for --shuffle")
    ap.add_argument("--sf", type=float, default=0.05,
                    help="TPC-H scale factor for --profile-overhead"
                         " and --fusion")
    ap.add_argument("--json", action="store_true",
                    help="one JSON object on stdout")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny correctness-only run (tier-1 wiring)")
    args = ap.parse_args(argv)

    if args.smoke:
        args.rows, args.groups, args.aggs, args.iters = 5000, 7, 2, 1
        args.block_rows = 2048
        args.chunk_rows = 256
        args.sf = 0.01
        args.shuffle_rows = 8192

    import jax

    report = {
        "backend": jax.default_backend(),
        "group_by": bench_group_by(args.rows, args.groups, args.aggs,
                                   args.iters),
        "staging": bench_staging(args.rows, args.block_rows, args.iters),
    }
    if args.pruning or args.smoke:
        report["pruning"] = bench_pruning(
            args.rows, args.chunk_rows, args.iters)
    if args.resident or args.smoke:
        report["resident"] = bench_resident(
            args.rows, args.chunk_rows, args.iters)
    if args.streaming or args.smoke:
        report["streaming"] = bench_streaming(
            args.rows, args.chunk_rows, args.iters)
    if args.profile_overhead or args.smoke:
        # smoke: tiny run, lax bound (machinery + no-catastrophe
        # guard); real sizes measure the 2% default-on budget
        report["profile_overhead"] = bench_profile_overhead(
            args.sf, max(3, args.iters), args.block_rows,
            assert_within=(0.5 if args.smoke else None))
    if args.chaos_overhead or args.smoke:
        # smoke: tiny run, lax bound (machinery + no-catastrophe
        # guard); real sizes hold the 1% disabled-path budget
        report["chaos_overhead"] = bench_chaos_overhead(
            args.sf, max(3, args.iters), args.block_rows,
            assert_within=(0.5 if args.smoke else 0.01))
    if args.leaksan_overhead or args.smoke:
        # smoke: tiny run, lax bound (machinery + no-catastrophe
        # guard); real sizes hold the 1% disabled-path budget
        report["leaksan_overhead"] = bench_leaksan_overhead(
            args.sf, max(3, args.iters), args.block_rows,
            assert_within=(0.5 if args.smoke else 0.01))
    if args.admission_overhead or args.smoke:
        # smoke: tiny run, lax bound (machinery + no-catastrophe
        # guard); real sizes hold the 3% front-door budget
        report["admission_overhead"] = bench_admission_overhead(
            args.sf, max(3, args.iters),
            assert_within=(0.5 if args.smoke else 0.03))
    if args.memsan_overhead or args.smoke:
        # smoke: tiny run, lax bound (machinery + no-catastrophe
        # guard); real sizes hold the 3% warm-Q1 tripwire
        report["memsan_overhead"] = bench_memsan_overhead(
            args.sf, max(3, args.iters), args.block_rows,
            assert_within=(0.5 if args.smoke else 0.03))
    if args.fusion or args.smoke:
        report["fusion"] = bench_fusion(args.sf, max(3, args.iters))
    if args.batching or args.smoke:
        report["batching"] = bench_batching(
            args.sf, max(1, args.iters),
            batch=(3 if args.smoke else args.batch))
    if args.shuffle or args.smoke:
        report["shuffle"] = bench_shuffle(
            args.shuffle_rows, args.iters, with_skew=args.shuffle)
    if args.json:
        print(json.dumps(report))
    else:
        gb, st = report["group_by"], report["staging"]
        print(f"backend={report['backend']}")
        print(f"group-by rows={gb['rows']} groups={gb['groups']}: "
              f"fused {gb.get('fused_rows_per_sec'):,} rows/s, "
              f"per-agg {gb.get('peragg_rows_per_sec'):,} rows/s "
              f"(x{gb.get('fused_speedup')}), "
              f"oracle={gb.get('oracle_check', 'skipped')}")
        print(f"staging rows={st['rows']} blocks={st['blocks']}: "
              f"{st['staging_rows_per_sec']:,} rows/s")
        if "pruning" in report:
            pr = report["pruning"]
            print(f"pruning rows={pr['rows']}: chunks "
                  f"{pr.get('stats_chunks_read')} read vs "
                  f"{pr.get('nostats_chunks_read')} unpruned "
                  f"({pr.get('chunks_skipped_per_sec'):,} skipped/s, "
                  f"x{pr.get('pruning_speedup')} speedup, "
                  f"identical={pr.get('identical')})")
        if "resident" in report:
            rr = report["resident"]
            print(f"resident rows={rr['rows']}: "
                  f"{rr['resident_rows_per_sec']:,} rows/s vs staged "
                  f"{rr['staged_rows_per_sec']:,} rows/s "
                  f"(x{rr['resident_speedup']}, "
                  f"{rr['resident_portions']} portions / "
                  f"{rr['resident_bytes']:,} B pinned, "
                  f"identical={rr['identical']})")
        if "streaming" in report:
            sm = report["streaming"]
            pl = sm.get("pipeline") or {}
            print(f"streaming rows={sm['rows']}: pipelined "
                  f"{sm['pipelined_rows_per_sec']:,} rows/s vs "
                  f"serialized {sm['serialized_rows_per_sec']:,} "
                  f"rows/s (x{sm['pipeline_speedup']}, overlap="
                  f"{sm.get('movement_compute_overlap')}, "
                  f"{pl.get('morsels_io')} flights / "
                  f"{pl.get('stolen')} stolen, "
                  f"identical={sm['identical']})")
        if "profile_overhead" in report:
            po = report["profile_overhead"]
            print(f"profile overhead rows={po['rows']}: "
                  f"on {po['profile_on_rows_per_sec']:,} rows/s vs "
                  f"off {po['profile_off_rows_per_sec']:,} rows/s "
                  f"({po['overhead_pct']:+.2f}%); timeline ring "
                  f"{po['timeline_overhead_pct']:+.2f}% "
                  f"(disabled events="
                  f"{po['timeline_disabled_events']})")
        if "chaos_overhead" in report:
            co = report["chaos_overhead"]
            print(f"chaos overhead rows={co['rows']}: armed "
                  f"{co['chaos_armed_rows_per_sec']:,} rows/s vs off "
                  f"{co['chaos_off_rows_per_sec']:,} rows/s "
                  f"({co['overhead_pct']:+.2f}%)")
        if "leaksan_overhead" in report:
            lo = report["leaksan_overhead"]
            print(f"leaksan overhead rows={lo['rows']}: armed "
                  f"{lo['leaksan_armed_rows_per_sec']:,} rows/s vs off "
                  f"{lo['leaksan_off_rows_per_sec']:,} rows/s "
                  f"({lo['overhead_pct']:+.2f}%, "
                  f"drained={lo['drained']})")
        if "admission_overhead" in report:
            ao = report["admission_overhead"]
            print(f"admission overhead rows={ao['rows']}: door "
                  f"{ao['admission_on_rows_per_sec']:,} rows/s vs off "
                  f"{ao['admission_off_rows_per_sec']:,} rows/s "
                  f"({ao['overhead_pct']:+.2f}%, "
                  f"admitted={ao['admitted']})")
        if "memsan_overhead" in report:
            mo = report["memsan_overhead"]
            print(f"memsan overhead rows={mo['rows']}: armed "
                  f"{mo['memsan_armed_rows_per_sec']:,} rows/s vs off "
                  f"{mo['memsan_off_rows_per_sec']:,} rows/s "
                  f"({mo['overhead_pct']:+.2f}%, warm peak "
                  f"{mo['warm_peak_bytes']:,} bytes, "
                  f"unbudgeted={mo['warm_unbudgeted']})")
        if "fusion" in report:
            fu = report["fusion"]
            print(f"fusion rows={fu['rows']}: fused "
                  f"{fu['fused_rows_per_sec']:,} rows/s vs walk "
                  f"{fu['walk_rows_per_sec']:,} rows/s "
                  f"(x{fu['fused_speedup']}, "
                  f"{fu['fused_dispatches']} dispatch vs "
                  f"{fu['fragment_dispatches']} fragments, "
                  f"identical={fu['identical']})")
        if "batching" in report:
            ba = report["batching"]
            print(f"batching rows={ba['rows']} batch={ba['batch']}: "
                  f"serial {ba['serial_seconds_per_statement']}s/stmt "
                  f"vs stacked "
                  f"{ba['stacked_seconds_per_statement']}s/stmt "
                  f"(x{ba['stacked_speedup']}) vs dedup "
                  f"{ba['dedup_seconds_per_statement']}s/stmt "
                  f"(x{ba['dedup_speedup']}, "
                  f"identical={ba['identical']})")
        if "shuffle" in report:
            sh = report["shuffle"]
            if "skipped" in sh:
                print(f"shuffle: skipped ({sh['skipped']})")
            else:
                print(f"shuffle rows={sh['rows']} dev={sh['devices']}: "
                      f"stats {sh['stats_rows_per_sec']:,} rows/s vs "
                      f"full {sh['full_rows_per_sec']:,} rows/s "
                      f"(x{sh['shuffle_speedup']}, bucket "
                      f"{sh['stats_bucket_rows']} vs "
                      f"{sh['full_bucket_rows']} = "
                      f"x{sh['capacity_ratio']} capacity, "
                      f"{sh.get('skew', {}).get('grows', 'n/a')} "
                      f"skew grows, identical={sh['identical']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
