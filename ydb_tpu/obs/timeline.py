"""Data-movement timeline: a bounded event ring + Chrome-trace export.

The scan pipeline spreads one query over threads — blob IO and merging
on conveyor producers, block staging (pad + H2D) beside them, device
compute on the consumer — and the per-stage *sums* (obs.probes
StageTimer, EXPLAIN ANALYZE ``stages:``) say how much time each stage
took but not WHEN: whether decode overlapped compute or serialized
behind it is invisible. This module records begin/end intervals for
every pipeline event — span stages, conveyor task wait-vs-run, blob
reads, chunk decodes, H2D staging, device dispatches — into one
process-global bounded ring, and exports them as Chrome/Perfetto
``trace_event`` JSON (``/viewer/json/timeline?trace=1``, or
``python -m ydb_tpu.obs.timeline --out trace.json``) so "did decode
overlap compute?" becomes a picture.

The same intervals drive the numbers ROADMAP item 2 steers by:
``stage_occupancy`` computes per-stage busy fractions (union of a
stage's intervals over the query wall) and pairwise overlap
coefficients (|A∩B| / min(|A|, |B|)) — a movement-vs-compute
coefficient of 1.0 means the pipeline is perfectly overlapped.

Byte movement counters ride here too (always on — they are plain
counters, same cost class as ``chunks_read``): blob bytes read,
decoded bytes, staged/H2D bytes, resident-tier bytes served and
per-device shuffle bytes accumulate in a process-global table that
``kqp.session`` mirrors into the ``component="movement"`` counters on
the background cadence (rates fall out of the Prometheus scrape).

Gating: the ring is OFF by default (``YDB_TPU_TIMELINE=1`` enables;
``TIMELINE_FORCE`` is the in-process override, same contract as
``tracing.PROFILE_FORCE``). Disabled, every record site is one flag
check + one environment lookup — kernelbench's ``--profile-overhead``
A/B asserts the disabled path stays inside the profiling budget.
"""

from __future__ import annotations

import argparse
import collections
import contextlib
import json
import os
import threading
import time

from ydb_tpu.analysis import sanitizer

#: test/bench override: True/False forces the timeline regardless of
#: the environment (same contract as tracing.PROFILE_FORCE).
TIMELINE_FORCE: "bool | None" = None

#: stage categories whose intervals feed occupancy math; "movement"
#: (read+merge+stage+decode unions) vs "compute" is the coefficient
#: ROADMAP item 2 drives toward 1.0
STAGE_CATS = ("read", "merge", "stage", "compute")
#: extra interval categories recorded alongside the stages
AUX_CATS = ("blob.read", "decode", "span", "conveyor.wait",
            "conveyor.run", "dispatch")

#: movement stages unioned against compute for the overlap coefficient
MOVEMENT_CATS = ("read", "merge", "stage", "blob.read", "decode")


def timeline_enabled() -> bool:
    """Whether pipeline events land in the ring. Default OFF — the
    timeline is a diagnosis instrument, not an always-on tax."""
    if TIMELINE_FORCE is not None:
        return TIMELINE_FORCE
    return os.environ.get("YDB_TPU_TIMELINE", "") not in ("", "0", "off")


#: one event: a closed [start, end) interval on one thread.
Event = collections.namedtuple(
    "Event", ("name", "cat", "start", "end", "tid", "trace_id", "args"))

#: perf_counter origin for Chrome-trace microsecond timestamps — all
#: record sites share this clock (StageTimer uses it too), so exported
#: events land on one consistent axis
_EPOCH = time.perf_counter()


class TimelineRing:
    """Fixed-capacity overwrite-oldest event ring.

    Writers are conveyor workers + session threads concurrently; one
    tracked lock guards the slot array (record is two list writes, so
    the critical section stays tiny). Built at import time like the
    probe registry, so the lock is the always-on tracked variant whose
    recording self-gates per access.
    """

    def __init__(self, capacity: int | None = None, name: str = "ring"):
        if capacity is None:
            capacity = int(os.environ.get(
                "YDB_TPU_TIMELINE_EVENTS", str(1 << 16)))
        self.capacity = max(1, int(capacity))
        self._slots: list = [None] * self.capacity
        self._n = 0
        self._tnames: dict[int, str] = {}
        self._lock = sanitizer.TrackedLock(f"timeline.{name}.lock")

    def record(self, name: str, cat: str, start: float, end: float,
               trace_id: int = 0, args: dict | None = None) -> None:
        tid = threading.get_ident()
        e = Event(name, cat, start, end, tid, trace_id, args or {})
        tname = threading.current_thread().name
        with self._lock:
            self._slots[self._n % self.capacity] = e
            self._n += 1
            if self._tnames.get(tid) != tname:
                self._tnames[tid] = tname

    def events(self) -> list:
        """Retained events, oldest first."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return list(self._slots[:n])
            i = n % cap
            return self._slots[i:] + self._slots[:i]

    def thread_names(self) -> dict[int, str]:
        with self._lock:
            return dict(self._tnames)

    @property
    def recorded(self) -> int:
        """Total events ever recorded (≥ len(self))."""
        with self._lock:
            return self._n

    @property
    def dropped(self) -> int:
        """Events overwritten by the bound."""
        with self._lock:
            return max(0, self._n - self.capacity)

    def clear(self) -> None:
        with self._lock:
            self._slots = [None] * self.capacity
            self._n = 0

    def __len__(self) -> int:
        with self._lock:
            return min(self._n, self.capacity)


#: the process-global ring every instrumentation site records into
RING = TimelineRing()


def record(name: str, cat: str, start: float, end: float,
           trace_id: int = 0, **args) -> None:
    """Record one interval IF the timeline is enabled (the single
    guard every instrumentation site shares)."""
    if not timeline_enabled():
        return
    RING.record(name, cat, start, end, trace_id, args or None)


@contextlib.contextmanager
def event(name: str, cat: str, trace_id: int = 0, **args):
    """Time a block into the ring; a bare yield when disabled."""
    if not timeline_enabled():
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        RING.record(name, cat, t0, time.perf_counter(), trace_id,
                    args or None)


def current_trace_id() -> int:
    """Trace id of the thread's active span (0 outside any trace) —
    how ring events attribute to a query without plumbing arguments."""
    from ydb_tpu.obs import tracing

    sp = tracing.current_span()
    return sp.trace_id if sp is not None else 0


# ---- byte-movement counters (always on) ----

_move_lock = sanitizer.TrackedLock("timeline.movement.lock")
_movement = sanitizer.share_always({}, "timeline.movement")


def add_bytes(key: str, n: int) -> None:
    """Accumulate moved bytes under ``key`` (``blob_read_bytes``,
    ``decoded_bytes``, ``staged_bytes``, ``resident_bytes``,
    ``shuffle_bytes_dev<i>``)."""
    with _move_lock:
        _movement[key] = _movement.get(key, 0) + int(n)


def movement_snapshot() -> dict:
    """Lifetime byte totals; consumers (run_background, bench) diff
    snapshots for rates."""
    with _move_lock:
        return dict(_movement)


def reset_movement() -> None:
    with _move_lock:
        _movement.clear()


# ---- interval math ----

def merge_intervals(intervals) -> list:
    """Union of [start, end) intervals as a sorted disjoint list."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: list = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def union_seconds(intervals) -> float:
    return sum(e - s for s, e in merge_intervals(intervals))


def intersect_seconds(a, b) -> float:
    """Total overlap between two interval unions (two-pointer sweep)."""
    a, b = merge_intervals(a), merge_intervals(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def occupancy_from_events(events, wall: float | None = None) -> dict:
    """Per-stage busy fractions + pairwise overlap coefficients.

    ``busy[cat]`` is the union length of that category's intervals (a
    thread-overlapped stage does NOT double count); ``fraction`` is
    busy over the query wall; ``overlap["a|b"]`` is
    |A∩B| / min(|A|, |B|) for every present category pair, and
    ``overlap["movement|compute"]`` unions read+merge+stage+decode
    against compute — the serialized-pipeline detector (0.0 means blob
    IO/decode/staging fully stall compute; 1.0 means they hide behind
    it)."""
    by_cat: dict[str, list] = {}
    for e in events:
        by_cat.setdefault(e.cat, []).append((e.start, e.end))
    by_cat.pop("span", None)  # spans nest whole phases, not stages
    merged = {c: merge_intervals(iv) for c, iv in by_cat.items()}
    # ratios divide UNROUNDED union lengths (rounding busy first can
    # push a coefficient past 1.0 on microsecond-scale categories)
    busy = {c: sum(e - s for s, e in iv) for c, iv in merged.items()}
    if wall is None:
        spans = [p for iv in merged.values() for p in iv]
        wall = (max(e for _, e in spans) - min(s for s, _ in spans)
                if spans else 0.0)
    out: dict = {
        "wall_seconds": round(wall, 6),
        "busy": {c: round(b, 6) for c, b in busy.items()},
        "fraction": {c: round(b / wall, 4) if wall > 0 else 0.0
                     for c, b in busy.items()},
        "overlap": {},
    }
    cats = sorted(merged)
    for i, a in enumerate(cats):
        for b in cats[i + 1:]:
            lo = min(busy[a], busy[b])
            if lo <= 0:
                continue
            out["overlap"][f"{a}|{b}"] = round(min(
                1.0, intersect_seconds(merged[a], merged[b]) / lo), 4)
    move = [p for c in MOVEMENT_CATS for p in merged.get(c, ())]
    comp = merged.get("compute", [])
    lo = min(union_seconds(move), busy.get("compute", 0.0))
    if lo > 0:
        out["overlap"]["movement|compute"] = round(min(
            1.0, intersect_seconds(move, comp) / lo), 4)
    return out


def query_occupancy(trace_id: int, wall: float | None = None,
                    ring: TimelineRing | None = None) -> dict:
    """Occupancy for one query's ring events ({} when none landed)."""
    evs = [e for e in (ring or RING).events()
           if e.trace_id == trace_id]
    if not evs:
        return {}
    return occupancy_from_events(evs, wall)


# ---- Chrome trace_event export ----

def export_chrome_trace(events=None,
                        ring: TimelineRing | None = None) -> dict:
    """Chrome/Perfetto ``trace_event`` JSON (complete "X" events, µs
    since the process timeline epoch). Load via ui.perfetto.dev or
    chrome://tracing."""
    r = ring or RING
    if events is None:
        events = r.events()
    te = []
    for tid, tname in sorted(r.thread_names().items()):
        te.append({"name": "thread_name", "ph": "M", "pid": 0,
                   "tid": tid, "args": {"name": tname}})
    for e in events:
        args = dict(e.args)
        if e.trace_id:
            args["trace_id"] = e.trace_id
        te.append({
            "name": e.name, "cat": e.cat, "ph": "X",
            "ts": round((e.start - _EPOCH) * 1e6, 3),
            "dur": round((e.end - e.start) * 1e6, 3),
            "pid": 0, "tid": e.tid, "args": args,
        })
    return {"traceEvents": te, "displayTimeUnit": "ms"}


def summary(ring: TimelineRing | None = None) -> dict:
    """Ring state for the viewer's timeline tab: per-category event
    counts + busy seconds, bound accounting, movement byte totals."""
    r = ring or RING
    evs = r.events()
    by_cat: dict[str, list] = {}
    for e in evs:
        by_cat.setdefault(e.cat, []).append((e.start, e.end))
    return {
        "enabled": timeline_enabled(),
        "events": len(evs),
        "recorded": r.recorded,
        "dropped": r.dropped,
        "capacity": r.capacity,
        "categories": {
            c: {"events": len(iv),
                "busy_seconds": round(union_seconds(iv), 6)}
            for c, iv in sorted(by_cat.items())
        },
        "movement_bytes": movement_snapshot(),
    }


# ---- CLI: run a demo query with the timeline on, export the trace ----

def _demo(sf: float, iters: int) -> dict:
    """Warm TPC-H Q1 over a staged ColumnShard with the timeline forced
    on — a self-contained trace to open in Perfetto."""
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.obs import profile as profile_mod
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=5)
    li = data.tables["lineitem"]
    shard = ColumnShard(
        "timeline_demo", tpch.LINEITEM_SCHEMA, MemBlobStore(),
        dicts=data.dicts,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=1 << 16,
                           portion_chunk_rows=1 << 14))
    shard.commit([shard.write(dict(li))])
    prog = tpch.q1_program()
    shard.scan(prog)  # cold: compile outside the recorded window
    holder = None
    for _ in range(max(1, iters)):
        with profile_mod.profiled("q1") as holder:
            shard.scan(prog)
    return (holder.profile.to_dict() if holder and holder.profile
            else {})


def main(argv=None) -> int:
    global TIMELINE_FORCE
    ap = argparse.ArgumentParser(
        prog="python -m ydb_tpu.obs.timeline",
        description="export the pipeline timeline as Chrome-trace JSON"
                    " (runs a warm TPC-H Q1 demo unless --no-demo)")
    ap.add_argument("--out", default="trace.json",
                    help="output path for the trace_event JSON")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor for the demo query")
    ap.add_argument("--iters", type=int, default=2,
                    help="warm demo iterations recorded")
    ap.add_argument("--no-demo", action="store_true",
                    help="export whatever the ring already holds")
    args = ap.parse_args(argv)

    profile = {}
    if not args.no_demo:
        # single-threaded CLI entry, set before any worker spawns
        TIMELINE_FORCE = True  # ydb-lint: disable=C005
        profile = _demo(args.sf, args.iters)
    trace = export_chrome_trace()
    with open(args.out, "w") as f:
        json.dump(trace, f)
    s = summary()
    print(f"{args.out}: {len(trace['traceEvents'])} trace events "
          f"({s['dropped']} dropped by the ring bound)")
    for cat, st in s["categories"].items():
        print(f"  {cat}: {st['events']} events, "
              f"{st['busy_seconds']:.6f}s busy")
    occ = profile.get("stage_occupancy") or {}
    if occ.get("overlap"):
        print("  overlap: " + " ".join(
            f"{k}={v}" for k, v in sorted(occ["overlap"].items())))
    return 0


if __name__ == "__main__":
    import sys

    # under ``python -m`` this file executes as ``__main__`` while the
    # engine hooks import ``ydb_tpu.obs.timeline`` — two module
    # objects, two rings. Dispatch to the canonical instance so the
    # force flag and the ring the demo records into are the ones the
    # export reads.
    from ydb_tpu.obs import timeline as _canonical

    sys.exit(_canonical.main())
