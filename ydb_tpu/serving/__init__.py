"""serving/ — the multi-tenant front door.

The admission/tenancy plane between the protocol fronts (api/pgwire.py,
api/server.py) and the kqp session layer: tenant registry + weighted
workload pools (tenants.py) and the cross-client admission queue with
per-tenant shedding and deadline-ordered waits (admission.py). See
README.md in this directory for the full flow.

Usage::

    reg = serving.TenantRegistry()
    reg.register("gold", weight=3.0, max_inflight=32)
    reg.register("bronze", weight=1.0, max_inflight=8)
    serving.install(cluster, reg)       # cluster.front_door set

Fronts resolve a connection's tenant with :func:`resolve_tenant` and
decide whether a statement may run outside their connection-serial
lock with :func:`is_read_statement` — read statements from different
connections must overlap so the batch window (kqp/batch.py) sees the
full cross-client queue.
"""

from __future__ import annotations

from ydb_tpu.serving.admission import FrontDoor, Seat  # noqa: F401
from ydb_tpu.serving.tenants import (  # noqa: F401
    DEFAULT_TENANT,
    Tenant,
    TenantRegistry,
)

#: statement heads that never mutate state: safe to execute without the
#: protocol front's global write lock (so concurrent connections can
#: co-occupy the cross-query batch window)
_READ_HEADS = ("SELECT", "EXPLAIN", "SHOW", "VALUES")


def install(cluster, registry: TenantRegistry | None = None) -> FrontDoor:
    """Attach a :class:`FrontDoor` to the cluster (idempotent per
    cluster: a second install replaces the first)."""
    return FrontDoor(cluster, registry).install()


def is_read_statement(sql: str) -> bool:
    """True when the statement is read-only by its leading keyword
    (comments skipped). Fronts keep DDL/DML/transaction statements
    under their serial lock and let reads run concurrently."""
    s = sql.lstrip()
    while s.startswith("--") or s.startswith("/*"):
        if s.startswith("--"):
            nl = s.find("\n")
            if nl < 0:
                return False
            s = s[nl + 1:].lstrip()
        else:
            end = s.find("*/")
            if end < 0:
                return False
            s = s[end + 2:].lstrip()
    head = s[:10].upper()
    return any(head.startswith(k) for k in _READ_HEADS)


def resolve_tenant(cluster, tenant: str | None = None,
                   principal: str | None = None) -> str:
    """Connection hello -> pool name through the cluster's front door
    registry; plain default-pool behavior when no front door is
    installed (the hint is still recorded so sys views label rows)."""
    fd = getattr(cluster, "front_door", None)
    if fd is not None:
        return fd.registry.resolve(tenant=tenant, principal=principal)
    return tenant or DEFAULT_TENANT
