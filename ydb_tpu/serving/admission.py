"""The front door: one cross-client admission plane over the session
layer.

Every statement — arriving over pgwire (api/pgwire.py), the gRPC-style
proxy (api/server.py) or an in-process ``Session`` — passes through
``FrontDoor.admit`` before the workload-service pool and rm slots, so:

  * the PR 14 batch window sees the *full* cross-client queue: admitted
    statements from different network connections co-occupy the window
    and compatible SELECTs share one device dispatch;
  * shedding is per tenant, not global: each tenant pool has its own
    inflight cap and bounded admission queue, and the typed
    ``OverloadedError`` names the pool — one tenant's backlog queues
    (and sheds) against its own cap while other tenants admit freely;
  * queued admissions are ordered earliest-deadline-first *within* a
    tenant, and an admission whose statement deadline has already
    expired is shed instead of consuming a grant.

``install()`` additionally splits the shared execution budgets by
tenant weight: per-tenant workload-service pools (concurrency), a
``tenant:<name>`` quota row on the shared conveyor's ResourceBroker,
and a resident-store byte entitlement (reported on ``sys_tenant_pools``
and enforced at promotion time by the resident tier's global budget).

Every admission seat is a leak-sanitizer handle (``serving.seat``,
owner = the statement's active-registry token), so a statement that
returns without releasing its seat fails the per-statement
``assert_drained`` — the same bar batch seats and scan flights hold.
"""

from __future__ import annotations

import heapq
import os
import threading
import time

from ydb_tpu import chaos
from ydb_tpu.analysis import leaksan
from ydb_tpu.kqp.rm import OverloadedError, WorkloadService
from ydb_tpu.serving.tenants import DEFAULT_TENANT, TenantRegistry

#: states a queued admission moves through (guarded by FrontDoor._lock)
_WAITING, _GRANTED, _SHED = 0, 1, 2


class _Waiter:
    __slots__ = ("key", "state")

    def __init__(self, key: tuple):
        self.key = key
        self.state = _WAITING

    def __lt__(self, other: "_Waiter") -> bool:
        return self.key < other.key


class Seat:
    """One admitted statement's hold on its tenant pool (release once;
    idempotent so error paths may race the happy path)."""

    __slots__ = ("tenant", "_door", "_leak", "_released")

    def __init__(self, tenant: str, door: "FrontDoor", leak):
        self.tenant = tenant
        self._door = door
        self._leak = leak
        self._released = False

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        leaksan.close(self._leak)
        self._door._release(self.tenant)


class _TenantState:
    def __init__(self, cap: int, queue_size: int):
        self.cap = cap
        self.queue_size = queue_size
        self.inflight = 0
        self.waiting = 0
        self.heap: list[_Waiter] = []
        self.cond: threading.Condition | None = None
        self.admitted = 0
        self.queued = 0
        self.shed = 0


class FrontDoor:
    """Per-tenant admission seats + weighted budget shares (see module
    docstring). One instance per Cluster, attached as
    ``cluster.front_door`` by :meth:`install`."""

    def __init__(self, cluster, registry: TenantRegistry | None = None):
        self.cluster = cluster
        self.registry = registry or TenantRegistry()
        self._lock = threading.Lock()
        self._states: dict[str, _TenantState] = {}
        self._seq = 0
        self.shares: dict[str, dict] = {}

    # -- wiring ---------------------------------------------------------

    def install(self) -> "FrontDoor":
        """Attach to the cluster and apply weighted shares: per-tenant
        workload pools, broker quota rows, resident byte entitlements."""
        c = self.cluster
        if c.workload is None:
            c.workload = WorkloadService()
        pool_total = int(os.environ.get(
            "YDB_TPU_SERVING_POOL_SLOTS", "16"))
        pool_shares = self.registry.shares(pool_total)
        from ydb_tpu.engine import resident
        from ydb_tpu.runtime.conveyor import shared_conveyor
        conv = shared_conveyor()
        workers = int(os.environ.get("YDB_TPU_CONVEYOR_WORKERS", "4"))
        worker_shares = self.registry.shares(max(1, workers))
        resident_total = resident.default_budget()
        resident_shares = self.registry.shares(resident_total) \
            if resident_total > 0 else {}
        for t in self.registry.tenants():
            c.workload.configure(t.name,
                                 concurrent_limit=pool_shares[t.name],
                                 queue_size=t.queue_size)
            conv.broker.quotas[f"tenant:{t.name}"] = \
                worker_shares[t.name]
            self.shares[t.name] = {
                "weight": t.weight,
                "pool_limit": pool_shares[t.name],
                "conveyor_workers": worker_shares[t.name],
                "resident_bytes": resident_shares.get(t.name, 0),
            }
        c.front_door = self
        return self

    def _state(self, tenant: str) -> _TenantState:
        st = self._states.get(tenant)
        if st is None:
            t = self.registry.get(tenant)
            st = _TenantState(t.max_inflight, t.queue_size)
            st.cond = threading.Condition(self._lock)
            self._states[tenant] = st
        return st

    # -- admission ------------------------------------------------------

    def admit(self, tenant: str | None, deadline_at: float | None = None,
              timeout: float = 30.0, owner=None) -> Seat:
        """Block until the tenant pool has a free seat; raise the typed
        ``OverloadedError`` (naming the pool) when the pool's queue is
        full, the wait times out, or the statement deadline expires
        while queued."""
        name = tenant or DEFAULT_TENANT
        fault = chaos.hit("serving.admit", tenant=name)
        if fault is not None:
            fault.sleep()
            if fault.kind == "overload":
                self._count(name, "shed")
                raise OverloadedError(
                    f"tenant pool '{name}' overloaded (injected)")
        give_up = time.monotonic() + timeout
        if deadline_at is not None:
            give_up = min(give_up, deadline_at)
        with self._lock:
            st = self._state(name)
            while st.heap and st.heap[0].state != _WAITING:
                heapq.heappop(st.heap)  # lazily drop shed waiters
            if st.inflight < st.cap and not st.heap:
                st.inflight += 1
                st.admitted += 1
                return self._seat(name, owner)
            if st.waiting >= st.queue_size:
                st.shed += 1
                self._count_locked(name, "shed")
                raise OverloadedError(
                    f"tenant pool '{name}' overloaded: "
                    f"{st.inflight} inflight (cap {st.cap}), "
                    f"queue full ({st.queue_size})")
            # earliest-deadline-first within the tenant; FIFO among
            # deadline-less statements (seq breaks ties)
            self._seq += 1
            w = _Waiter((deadline_at if deadline_at is not None
                         else float("inf"), self._seq))
            heapq.heappush(st.heap, w)
            st.waiting += 1
            st.queued += 1
            self._promote(st)  # capacity may be free for the new head
            try:
                while w.state == _WAITING:
                    remaining = give_up - time.monotonic()
                    if remaining <= 0:
                        w.state = _SHED
                        break
                    st.cond.wait(remaining)
            finally:
                st.waiting -= 1
            if w.state != _GRANTED:
                st.shed += 1
                self._count_locked(name, "shed")
                raise OverloadedError(
                    f"tenant pool '{name}': admission wait "
                    f"expired after {timeout:.1f}s")
            st.admitted += 1
            return self._seat(name, owner)

    def _seat(self, name: str, owner) -> Seat:
        self._count_locked(name, "admitted")
        return Seat(name, self,
                    leaksan.track("serving.seat", name, owner=owner))

    def _release(self, tenant: str) -> None:
        with self._lock:
            st = self._states.get(tenant)
            if st is None:
                return
            st.inflight -= 1
            self._promote(st)

    def _promote(self, st: _TenantState) -> None:
        """Grant freed seats earliest-deadline-first; expired waiters
        are shed here so they never consume a grant."""
        now = time.monotonic()
        woke = False
        while st.inflight < st.cap and st.heap:
            w = heapq.heappop(st.heap)
            if w.state != _WAITING:
                continue
            if w.key[0] <= now:
                w.state = _SHED
                woke = True
                continue
            w.state = _GRANTED
            st.inflight += 1
            woke = True
        if woke:
            st.cond.notify_all()

    # -- observability --------------------------------------------------

    def _count(self, tenant: str, which: str) -> None:
        with self._lock:
            self._count_locked(tenant, which)

    def _count_locked(self, tenant: str, which: str) -> None:
        c = getattr(self.cluster, "counters", None)
        if c is not None:
            c.group(component="serving",
                    tenant=tenant).counter(which).inc()

    def snapshot(self) -> dict:
        """Per-tenant admission state for ``sys_tenant_pools`` and the
        background counter export."""
        out: dict = {}
        with self._lock:
            names = set(self._states) | set(self.shares) \
                | {t.name for t in self.registry.tenants()}
            for name in sorted(names):
                st = self._states.get(name)
                t = self.registry.get(name)
                share = self.shares.get(name, {})
                out[name] = {
                    "weight": share.get("weight", t.weight),
                    "inflight": st.inflight if st else 0,
                    "max_inflight": t.max_inflight,
                    "queued": st.waiting if st else 0,
                    "queue_size": t.queue_size,
                    "admitted": st.admitted if st else 0,
                    "shed": st.shed if st else 0,
                    "pool_limit": share.get("pool_limit", 0),
                    "conveyor_workers": share.get(
                        "conveyor_workers", 0),
                    "resident_bytes": share.get("resident_bytes", 0),
                }
        return out
