"""Tenant registry: who a connection belongs to and what it is owed.

The reference models tenancy as databases served by dedicated tablet
sets with per-database resource pools (ydb/core/kqp/workload_service);
here a :class:`Tenant` is a named weight over the shared single-node
budgets — the conveyor worker pool, the ResourceBroker quota table and
the resident-store byte budget — plus the per-tenant admission caps the
front door (admission.py) enforces.

Resolution order for an incoming connection (``resolve``):

  1. an explicit ``tenant`` startup parameter / request hint,
  2. a principal binding registered via ``bind_principal`` (auth token
     identity -> tenant),
  3. the default pool, so untagged clients are always served.

Unknown tenant names resolve to ``default`` rather than erroring: a
typo'd startup parameter must not take a client's traffic down, it
just loses its reserved share.
"""

from __future__ import annotations

import dataclasses
import threading

#: the pool untagged / unknown clients land in — always registered
DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One workload pool's identity and entitlements.

    ``weight`` is relative: a tenant's share of each divisible budget
    is ``weight / sum(weights)``. ``max_inflight`` is the hard per-
    tenant statement cap the front door sheds past (the boundary that
    replaces the global ``Cluster.max_inflight_statements`` valve);
    ``queue_size`` bounds the deadline-ordered admission queue behind
    that cap.
    """

    name: str
    weight: float = 1.0
    max_inflight: int = 16
    queue_size: int = 64


class TenantRegistry:
    """Thread-safe tenant table + principal bindings + share math."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: dict[str, Tenant] = {}
        self._principals: dict[str, str] = {}
        self.register(DEFAULT_TENANT)

    def register(self, name: str, weight: float = 1.0,
                 max_inflight: int = 16,
                 queue_size: int = 64) -> Tenant:
        t = Tenant(name, float(weight), int(max_inflight),
                   int(queue_size))
        with self._lock:
            self._tenants[name] = t
        return t

    def bind_principal(self, principal: str, tenant: str) -> None:
        """Route an authenticated identity (pgwire auth_tokens user,
        gRPC token principal) to a tenant without the client having to
        tag its connections."""
        with self._lock:
            self._principals[principal] = tenant

    def get(self, name: str) -> Tenant:
        with self._lock:
            return self._tenants.get(name) \
                or self._tenants[DEFAULT_TENANT]

    def tenants(self) -> list[Tenant]:
        with self._lock:
            return list(self._tenants.values())

    def resolve(self, tenant: str | None = None,
                principal: str | None = None) -> str:
        """Connection parameters -> pool name (see module docstring)."""
        with self._lock:
            if tenant and tenant in self._tenants:
                return tenant
            if principal is not None:
                bound = self._principals.get(principal)
                if bound and bound in self._tenants:
                    return bound
            return DEFAULT_TENANT

    def shares(self, total: float) -> dict[str, int]:
        """Split an integral budget by weight: every tenant gets at
        least 1 so a tiny weight degrades to trickle, never to zero."""
        with self._lock:
            ts = list(self._tenants.values())
        wsum = sum(t.weight for t in ts) or 1.0
        return {t.name: max(1, round(total * t.weight / wsum))
                for t in ts}
