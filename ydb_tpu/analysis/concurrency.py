"""Concurrency-discipline static analyzer: lock/guard rules C001-C008.

The runtime replaced the reference's actors with real threads — the
shared conveyor pool, interconnect sender/reader loops, DQ services, и
a dozen lock-guarded caches — and the races that creep in (PR 3's
scan-cache touch/evict) are exactly the ones an AST pass can catch
before they cost a debugging session. SURVEY §5.2 prescribes race
detection as a first-class auxiliary subsystem; this is the static
half (``analysis/sanitizer.py`` is the dynamic half).

Rules (each interprocedural where it matters — held-lock sets
propagate through private-method calls, and lock acquisition graphs
resolve attribute types across classes):

  C001 guard-inconsistency    an attribute written both under its
                              inferred guard (``with self._lock:``)
                              and outside it — the PR 3 scan-cache
                              race shape
  C002 lock-order-cycle       the cross-class lock acquisition-order
                              graph has a cycle (potential deadlock),
                              or a non-reentrant lock is re-acquired
                              on the same path
  C003 blocking-under-lock    a blocking call (untimed Condition/Event
                              wait, queue.get, Future.result, socket
                              recv/accept/sendall, time.sleep, device
                              syncs) while holding a lock
  C004 orphan-daemon-thread   daemon thread with no stop/join path
                              (class has none of stop/close/shutdown/
                              ..., or the Thread is started unbound)
  C005 unlocked-module-global module-global state written from
                              functions without a module lock held
  C006 per-call-lock          lock created inside a function and used
                              there — a fresh lock per call guards
                              nothing
  C007 notify-without-lock    Condition.notify/notify_all outside
                              ``with cond:``
  C008 late-binding-closure   a lambda capturing a loop variable handed
                              to an executor/Thread — every task sees
                              the LAST iteration's value

Suppression shares the lint machinery (``# ydb-lint: disable=C001`` on
the line or alone above it; ``skip-file``). Run:

    python -m ydb_tpu.analysis.concurrency [path ...] [--json] [--changed]

Default path: the ydb_tpu package. Exit 1 on unsuppressed findings.
``tests/test_concurrency_clean.py`` enforces a clean tree as a tier-1
test.
"""

from __future__ import annotations

import ast
import json
import sys
import threading

from ydb_tpu.analysis.lint import Finding, _dotted
from ydb_tpu.analysis.paths import collect_files, parse_cli
from ydb_tpu.analysis.suppress import file_skipped, filter_suppressed

RULES = {
    "C001": "guard-inconsistency",
    "C002": "lock-order-cycle",
    "C003": "blocking-under-lock",
    "C004": "orphan-daemon-thread",
    "C005": "unlocked-module-global",
    "C006": "per-call-lock",
    "C007": "notify-without-lock",
    "C008": "late-binding-closure",
}

#: self.attr method calls that mutate the receiver container
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "move_to_end", "sort", "reverse",
}
#: ctor name (last dotted part) -> lock kind; covers both threading
#: primitives and the sanitizer's tracked factories
_LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "make_lock": "lock", "make_rlock": "rlock",
    "make_condition": "condition",
    "TrackedLock": "lock", "TrackedRLock": "rlock",
}
#: ctor name -> non-lock attr type tag
_TYPE_CTORS = {
    "Queue": "queue", "SimpleQueue": "queue", "LifoQueue": "queue",
    "PriorityQueue": "queue", "Thread": "thread", "Timer": "thread",
    "Event": "event", "socket": "socket",
    "create_connection": "socket",
}
_INIT_NAMES = {"__init__", "__new__", "__post_init__",
               "__init_subclass__", "__set_name__"}
_STOP_NAMES = {"stop", "close", "shutdown", "join", "terminate",
               "cancel", "quit", "stop_all", "drain_and_stop",
               "__exit__", "__del__"}
_SUBMITTERS = {"submit", "submit_if_free", "apply_async", "map_async",
               "run_in_executor", "call_soon", "call_later",
               "call_soon_threadsafe", "add_done_callback", "spawn",
               "start_soon", "defer", "Thread", "Timer"}
#: receiver-insensitive blocking calls (attr name on any object)
_BLOCKING_ATTRS = {"recv", "accept", "sendall", "block_until_ready"}
_BLOCKING_DOTTED = {"time.sleep", "jax.block_until_ready",
                    "socket.create_connection"}


def _ctor_in(expr) -> "ast.Call | None":
    """The first constructor-looking Call in expr, looking through
    BoolOp/IfExp (``lock or threading.Lock()`` / ``a if c else B()``)."""
    if isinstance(expr, ast.Call):
        return expr
    if isinstance(expr, ast.BoolOp):
        for v in expr.values:
            c = _ctor_in(v)
            if c is not None:
                return c
    if isinstance(expr, ast.IfExp):
        for v in (expr.body, expr.orelse):
            c = _ctor_in(v)
            if c is not None:
                return c
    return None


def _lock_kind(call: "ast.Call | None") -> "str | None":
    if call is None:
        return None
    name = _dotted(call.func).rsplit(".", 1)[-1]
    return _LOCK_CTORS.get(name)


def _type_tag(call: "ast.Call | None") -> "str | None":
    if call is None:
        return None
    name = _dotted(call.func).rsplit(".", 1)[-1]
    if name in _TYPE_CTORS:
        return _TYPE_CTORS[name]
    if name[:1].isupper():
        return f"class:{name}"
    return None


class _Method:
    """Summary of one function/method body."""

    def __init__(self, name: str, node, klass: "str | None"):
        self.name = name
        self.node = node
        self.klass = klass
        # (attr, lexical_held frozenset, node, in_closure)
        self.writes: list = []
        # (lock_key, lexical_held, node)
        self.acquires: list = []
        # (method_name, lexical_held, node)
        self.self_calls: list = []
        # (attr, method_name, lexical_held, node)
        self.attr_calls: list = []
        # (func_name, lexical_held, node)  — module-function calls
        self.fn_calls: list = []
        # (description, lexical_held, node, exempt_key)
        self.blocking: list = []
        # (lock_key, lexical_held, node)
        self.notifies: list = []
        self.daemon_spawns: list = []
        # (global_name, lexical_held, node)
        self.global_writes: list = []
        self.entry_held: frozenset = frozenset()
        # distinct held-at-entry contexts across call paths (C001: a
        # helper called both locked and unlocked writes both ways)
        self.entry_contexts: set = {frozenset()}


class _Class:
    def __init__(self, name: str, module: str, node):
        self.name = name
        self.module = module
        self.node = node
        self.locks: dict = {}       # attr -> kind
        self.lock_alias: dict = {}  # condition attr -> wrapped lock attr
        self.attr_types: dict = {}  # attr -> type tag
        self.methods: dict = {}
        self.escaping: set = set()  # methods passed as values (targets)

    @property
    def key(self) -> str:
        return f"{self.module}.{self.name}"

    def canon(self, attr: str) -> str:
        return self.lock_alias.get(attr, attr)

    def has_stop_path(self) -> bool:
        return bool(_STOP_NAMES & set(self.methods))


class _Module:
    def __init__(self, modname: str, filename: str):
        self.name = modname
        self.filename = filename
        self.locks: dict = {}      # name -> kind
        self.mutables: set = set()  # module-level container globals
        self.classes: list = []
        self.functions: dict = {}  # top-level function summaries


def _call_has_timeout(call: ast.Call) -> bool:
    return bool(call.args) or any(
        k.arg in ("timeout", "block") for k in call.keywords)


class _BodyWalker:
    """Walk one function body tracking the lexical held-lock set."""

    def __init__(self, info: _Method, mod: _Module,
                 cls: "_Class | None", self_name: "str | None",
                 findings: list):
        self.info = info
        self.mod = mod
        self.cls = cls
        self.self_name = self_name
        self.findings = findings
        self.local_types: dict = {}  # local name -> type tag
        self.local_locks: dict = {}  # local name -> ctor node
        self.local_lock_used: set = set()
        self.returned: set = set()
        self.loop_vars: list = []

    # -- lock expression resolution --

    def lock_key(self, expr) -> "tuple | None":
        if isinstance(expr, ast.Name):
            if expr.id in self.mod.locks:
                return ("M", self.mod.name,
                        _canon_module(self.mod, expr.id))
            return None
        if isinstance(expr, ast.Attribute) and self.cls is not None:
            base = expr.value
            if isinstance(base, ast.Name) and base.id == self.self_name:
                if expr.attr in self.cls.locks:
                    return ("C", self.cls.key, self.cls.canon(expr.attr))
                return None
            # self.X.Y — lock on a typed member object
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == self.self_name):
                tag = self.cls.attr_types.get(base.attr, "")
                if tag.startswith("class:"):
                    other = _CLASSES.get(tag[6:])
                    if other is not None and expr.attr in other.locks:
                        return ("C", other.key, other.canon(expr.attr))
        return None

    # -- the walk --

    def walk_body(self, stmts, held: frozenset, closure: bool = False):
        for st in stmts:
            self.walk(st, held, closure)

    def walk(self, node, held: frozenset, closure: bool):
        meth = getattr(self, f"w_{type(node).__name__}", None)
        if meth is not None:
            meth(node, held, closure)
            return
        for child in ast.iter_child_nodes(node):
            self.walk(child, held, closure)

    def w_With(self, node, held, closure):
        inner = set(held)
        for item in node.items:
            key = self.lock_key(item.context_expr)
            if key is not None:
                self.info.acquires.append((key, frozenset(inner), node))
                inner.add(key)
            elif isinstance(item.context_expr, ast.Name) and \
                    item.context_expr.id in self.local_locks:
                self.local_lock_used.add(item.context_expr.id)
            self.walk(item.context_expr, held, closure)
        self.walk_body(node.body, frozenset(inner), closure)

    w_AsyncWith = w_With

    def w_FunctionDef(self, node, held, closure):
        # a nested def runs later, possibly on another thread: its body
        # sees NO lexically-held locks
        self.walk_body(node.body, frozenset(), True)

    w_AsyncFunctionDef = w_FunctionDef

    def w_Lambda(self, node, held, closure):
        self.walk(node.body, frozenset(), True)

    def w_For(self, node, held, closure):
        self.walk(node.iter, held, closure)
        names = [n.id for n in ast.walk(node.target)
                 if isinstance(n, ast.Name)]
        self.loop_vars.append(set(names))
        self.walk_body(node.body, held, closure)
        self.loop_vars.pop()
        self.walk_body(node.orelse, held, closure)

    w_AsyncFor = w_For

    def w_Return(self, node, held, closure):
        if isinstance(node.value, ast.Name):
            self.returned.add(node.value.id)
        if node.value is not None:
            self.walk(node.value, held, closure)

    def w_Global(self, node, held, closure):
        for name in node.names:
            self.local_types.setdefault(f"global:{name}", "global")

    def _record_write(self, attr, held, node, closure):
        self.info.writes.append((attr, held, node, closure))

    def _write_target(self, tgt, held, node, closure):
        if isinstance(tgt, ast.Tuple) or isinstance(tgt, ast.List):
            for el in tgt.elts:
                self._write_target(el, held, node, closure)
            return
        base = tgt
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == self.self_name and self.cls is not None:
            self._record_write(base.attr, held, node, closure)
        elif isinstance(base, ast.Name):
            name = base.id
            if f"global:{name}" in self.local_types or (
                    isinstance(tgt, ast.Subscript)
                    and name in self.mod.mutables):
                self.info.global_writes.append((name, held, node))

    def w_Assign(self, node, held, closure):
        ctor = _ctor_in(node.value)
        kind = _lock_kind(ctor)
        tag = _type_tag(ctor)
        for tgt in node.targets:
            self._write_target(tgt, held, node, closure)
            if isinstance(tgt, ast.Name):
                if kind is not None:
                    self.local_locks[tgt.id] = node
                if tag is not None:
                    self.local_types[tgt.id] = tag
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == self.self_name and \
                    self.cls is not None:
                if kind is not None:
                    self.cls.locks.setdefault(tgt.attr, kind)
                    if kind == "condition" and ctor.args and \
                            isinstance(ctor.args[0], ast.Attribute):
                        wrapped = ctor.args[0]
                        if isinstance(wrapped.value, ast.Name) and \
                                wrapped.value.id == self.self_name:
                            self.cls.lock_alias[tgt.attr] = wrapped.attr
                    if kind is not None and \
                            self.info.name not in _INIT_NAMES:
                        self._flag_lazy_lock(node)
                elif tag is not None:
                    self.cls.attr_types.setdefault(tgt.attr, tag)
        self.walk(node.value, held, closure)

    def w_AnnAssign(self, node, held, closure):
        if node.value is None:
            return
        fake = ast.Assign(targets=[node.target], value=node.value)
        ast.copy_location(fake, node)
        self.w_Assign(fake, held, closure)

    def w_AugAssign(self, node, held, closure):
        self._write_target(node.target, held, node, closure)
        self.walk(node.value, held, closure)

    def w_Delete(self, node, held, closure):
        for tgt in node.targets:
            self._write_target(tgt, held, node, closure)

    def _flag_lazy_lock(self, node):
        self.findings.append(Finding(
            self.mod.filename, node.lineno, node.col_offset, "C006",
            RULES["C006"],
            "lock created outside __init__: a lock minted per call (or"
            " lazily, racing its own creation) guards nothing — create"
            " it once in __init__"))

    def w_Call(self, node, held, closure):
        fn = node.func
        dotted = _dotted(fn)
        # mutator method on self.attr -> write
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            recv = fn.value
            if isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == self.self_name and \
                    self.cls is not None:
                self._record_write(recv.attr, held, node, closure)
            elif isinstance(recv, ast.Name) and \
                    recv.id in self.mod.mutables:
                self.info.global_writes.append((recv.id, held, node))
        # lock ops
        if isinstance(fn, ast.Attribute) and fn.attr in (
                "acquire", "release", "notify", "notify_all", "wait",
                "wait_for"):
            key = self.lock_key(fn.value)
            if key is not None:
                if fn.attr == "acquire":
                    self.info.acquires.append((key, held, node))
                elif fn.attr in ("notify", "notify_all"):
                    self.info.notifies.append((key, held, node))
                elif fn.attr in ("wait", "wait_for"):
                    if not _call_has_timeout(node):
                        self.info.blocking.append((
                            f"{_dotted(fn.value) or 'condition'}"
                            f".{fn.attr}() without timeout",
                            held, node, key))
            elif isinstance(fn.value, ast.Name) and \
                    fn.value.id in self.local_locks and \
                    fn.attr == "acquire":
                self.local_lock_used.add(fn.value.id)
        # blocking calls
        self._check_blocking(node, fn, dotted, held)
        # call-graph edges
        if isinstance(fn, ast.Attribute):
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id == self.self_name:
                self.info.self_calls.append((fn.attr, held, node))
            elif isinstance(recv, ast.Attribute) and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == self.self_name:
                self.info.attr_calls.append(
                    (recv.attr, fn.attr, held, node))
        elif isinstance(fn, ast.Name):
            self.info.fn_calls.append((fn.id, held, node))
        # thread lifecycle
        self._check_threads(node, fn, dotted)
        # C008
        self._check_late_binding(node, fn)
        for child in ast.iter_child_nodes(node):
            self.walk(child, held, closure)

    def _check_blocking(self, node, fn, dotted, held):
        desc = None
        if dotted in _BLOCKING_DOTTED:
            desc = f"{dotted}()"
        elif isinstance(fn, ast.Attribute):
            if fn.attr in _BLOCKING_ATTRS:
                desc = f".{fn.attr}()"
            elif fn.attr == "item" and not node.args:
                desc = ".item() (device sync)"
            elif fn.attr == "result" and not node.args and not any(
                    k.arg == "timeout" for k in node.keywords):
                desc = ".result() without timeout"
            elif fn.attr == "join" and not node.args and not any(
                    k.arg == "timeout" for k in node.keywords):
                desc = ".join() without timeout"
            elif fn.attr in ("get", "put") and \
                    not _call_has_timeout(node):
                recv_tag = None
                if isinstance(fn.value, ast.Name):
                    recv_tag = self.local_types.get(fn.value.id)
                elif isinstance(fn.value, ast.Attribute) and \
                        isinstance(fn.value.value, ast.Name) and \
                        fn.value.value.id == self.self_name and \
                        self.cls is not None:
                    recv_tag = self.cls.attr_types.get(fn.value.attr)
                if recv_tag == "queue":
                    desc = f"queue.{fn.attr}() without timeout"
            elif fn.attr == "wait" and not _call_has_timeout(node):
                recv_tag = None
                if isinstance(fn.value, ast.Attribute) and \
                        isinstance(fn.value.value, ast.Name) and \
                        fn.value.value.id == self.self_name and \
                        self.cls is not None:
                    recv_tag = self.cls.attr_types.get(fn.value.attr)
                if recv_tag == "event":
                    desc = ".wait() on an Event without timeout"
        if desc is not None:
            self.info.blocking.append((desc, held, node, None))

    def _check_threads(self, node, fn, dotted):
        name = dotted.rsplit(".", 1)[-1]
        if name in ("Thread", "Timer"):
            daemon = any(k.arg == "daemon" and
                         isinstance(k.value, ast.Constant) and
                         k.value.value is True
                         for k in node.keywords)
            if daemon:
                self.info.daemon_spawns.append(node)
        if isinstance(fn, ast.Attribute) and fn.attr == "start" and \
                isinstance(fn.value, ast.Call):
            ctor = _dotted(fn.value.func).rsplit(".", 1)[-1]
            if ctor in ("Thread", "Timer"):
                self.findings.append(Finding(
                    self.mod.filename, node.lineno, node.col_offset,
                    "C004", RULES["C004"],
                    "fire-and-forget Thread(...).start(): the thread"
                    " can never be joined or stopped — bind it and"
                    " give its owner a stop/join path"))

    def _check_late_binding(self, node, fn):
        if not self.loop_vars:
            return
        name = _dotted(fn).rsplit(".", 1)[-1]
        if name not in _SUBMITTERS:
            return
        live = set().union(*self.loop_vars)
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if not isinstance(arg, ast.Lambda):
                continue
            params = {a.arg for a in (
                arg.args.args + arg.args.kwonlyargs
                + arg.args.posonlyargs)}
            free = {n.id for n in ast.walk(arg.body)
                    if isinstance(n, ast.Name)} - params
            captured = sorted(free & live)
            if captured:
                self.findings.append(Finding(
                    self.mod.filename, arg.lineno, arg.col_offset,
                    "C008", RULES["C008"],
                    f"lambda captures loop variable(s)"
                    f" {', '.join(captured)} by reference: every"
                    " submitted task sees the LAST iteration's value —"
                    " bind eagerly (lambda x=x: ...) or pass args"))

    def finish(self):
        for name, node in self.local_locks.items():
            if name in self.local_lock_used and \
                    name not in self.returned:
                self._flag_lazy_lock(node)


def _canon_module(mod: _Module, name: str) -> str:
    return name  # module locks have no aliasing today


_CLASSES: dict = {}  # bare class name -> _Class (unique across run)
# serializes whole-analysis runs: the class registry is process-global
# so concurrent check_sources() calls (e.g. pytest workers in one
# process) must not interleave clear/registration. Reentrant because
# registration happens inside a run that already holds it.
_REG_LOCK = threading.RLock()


def _scan_module(src: str, filename: str, modname: str,
                 findings: list) -> "_Module | None":
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        findings.append(Finding(filename, e.lineno or 0, e.offset or 0,
                                "C000", "syntax-error", str(e.msg)))
        return None
    mod = _Module(modname, filename)
    # pass 1: module-level locks + mutable globals
    for st in tree.body:
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            tgts = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            value = st.value
            ctor = _ctor_in(value) if value is not None else None
            kind = _lock_kind(ctor)
            for t in tgts:
                if not isinstance(t, ast.Name):
                    continue
                if kind is not None:
                    mod.locks[t.id] = kind
                elif isinstance(value, (ast.Dict, ast.List, ast.Set)) \
                        or (ctor is not None and _dotted(
                            ctor.func).rsplit(".", 1)[-1] in (
                            "dict", "list", "set", "OrderedDict",
                            "defaultdict", "deque", "Counter")):
                    mod.mutables.add(t.id)
    # pass 2: classes + functions
    for st in tree.body:
        if isinstance(st, ast.ClassDef):
            mod.classes.append(_scan_class(st, mod, findings))
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[st.name] = _scan_function(
                st, mod, None, None, findings)
    return mod


def _is_static(node) -> bool:
    return any(isinstance(d, ast.Name) and
               d.id in ("staticmethod", "classmethod")
               for d in node.decorator_list)


def _scan_class(node: ast.ClassDef, mod: _Module,
                findings: list) -> _Class:
    cls = _Class(node.name, mod.name, node)
    method_nodes = [st for st in node.body if isinstance(
        st, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # pre-pass: __init__ first so lock attrs exist before other
    # methods' with-statements resolve them
    method_nodes.sort(key=lambda m: 0 if m.name in _INIT_NAMES else 1)
    for m in method_nodes:
        self_name = None
        if not _is_static(m) and m.args.args:
            self_name = m.args.args[0].arg
        cls.methods[m.name] = _scan_function(
            m, mod, cls, self_name, findings)
    # escaping methods: self.m referenced as a value (thread targets,
    # callbacks) — their entry held-set must stay empty
    names = set(cls.methods)
    for m in method_nodes:
        for n in ast.walk(m):
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name) and \
                    n.value.id in ("self",) and n.attr in names:
                cls.escaping.add(n.attr)
    # references as Call funcs are not escapes; subtract direct calls
    called = set()
    for mi in cls.methods.values():
        for name, _h, _n in mi.self_calls:
            called.add(name)
    cls.escaping -= called
    with _REG_LOCK:
        _CLASSES.setdefault(cls.name, cls)
    return cls


def _scan_function(node, mod: _Module, cls: "_Class | None",
                   self_name: "str | None", findings: list) -> _Method:
    info = _Method(node.name, node, cls.key if cls else None)
    walker = _BodyWalker(info, mod, cls, self_name, findings)
    walker.walk_body(node.body, frozenset())
    walker.finish()
    return info


# ---------------- global analysis passes ----------------


def _entry_fixpoint(cls: _Class) -> None:
    """Held-at-entry sets for private methods: the intersection of the
    held sets at every intra-class call site (a private helper called
    only under ``with self._lock:`` effectively runs guarded)."""
    for _ in range(8):
        changed = False
        for name, mi in cls.methods.items():
            if not name.startswith("_") or name.startswith("__") or \
                    name in cls.escaping:
                continue
            sites = []
            for caller in cls.methods.values():
                for callee, held, _node in caller.self_calls:
                    if callee == name:
                        sites.append(caller.entry_held | held)
            if not sites:
                continue
            new = frozenset.intersection(*sites)
            if new != mi.entry_held:
                mi.entry_held = new
                changed = True
        if not changed:
            break
    _context_fixpoint(cls)


def _context_fixpoint(cls: _Class) -> None:
    """Per-call-path entry contexts (C001): unlike the intersection
    above, a private helper reached both under a lock and without it
    keeps BOTH contexts, so its writes count as guarded AND unguarded.
    Call sites inside __init__ are construction-time and excluded."""
    for _ in range(8):
        changed = False
        for name, mi in cls.methods.items():
            if not name.startswith("_") or name.startswith("__") or \
                    name in cls.escaping:
                continue
            ctxs: set = set()
            called = False
            for cname, caller in cls.methods.items():
                if cname in _INIT_NAMES:
                    continue
                for callee, held, _node in caller.self_calls:
                    if callee == name:
                        called = True
                        for c in caller.entry_contexts:
                            ctxs.add(c | held)
            if called and len(ctxs) <= 16 and \
                    ctxs != mi.entry_contexts:
                mi.entry_contexts = ctxs
                changed = True
        if not changed:
            return


def _resolve_attr_call(cls: _Class, attr: str,
                       meth: str) -> "_Method | None":
    tag = cls.attr_types.get(attr, "")
    if tag.startswith("class:"):
        other = _CLASSES.get(tag[6:])
        if other is not None:
            return other.methods.get(meth)
    return None


def _acquire_fixpoint(classes: list) -> dict:
    """Transitive may-acquire set per method (for the lock-order
    graph): direct acquires plus everything resolved callees acquire."""
    acq: dict = {}
    for cls in classes:
        for mi in cls.methods.values():
            acq[id(mi)] = {key for key, _h, _n in mi.acquires}
    for _ in range(8):
        changed = False
        for cls in classes:
            for mi in cls.methods.values():
                cur = acq[id(mi)]
                for name, _h, _n in mi.self_calls:
                    callee = cls.methods.get(name)
                    if callee is not None and \
                            not acq[id(callee)] <= cur:
                        cur |= acq[id(callee)]
                        changed = True
                for attr, meth, _h, _n in mi.attr_calls:
                    callee = _resolve_attr_call(cls, attr, meth)
                    if callee is not None and \
                            not acq[id(callee)] <= cur:
                        cur |= acq[id(callee)]
                        changed = True
        if not changed:
            break
    return acq


def _lock_kind_of(key: tuple, modlocks: "dict | None" = None) -> str:
    if key[0] == "C":
        clsname = key[1].rsplit(".", 1)[-1]
        cls = _CLASSES.get(clsname)
        if cls is not None:
            return cls.locks.get(key[2], "lock")
    elif key[0] == "M" and modlocks is not None:
        return modlocks.get((key[1], key[2]), "lock")
    return "lock"


def _fmt_key(key: tuple) -> str:
    return f"{key[1].rsplit('.', 1)[-1]}.{key[2]}" if key[0] == "C" \
        else f"{key[1]}.{key[2]}"


def _check_classes(mods: list, findings: list) -> None:
    classes = [c for m in mods for c in m.classes]
    for cls in classes:
        _entry_fixpoint(cls)
    acq = _acquire_fixpoint(classes)
    modlocks = {(m.name, lname): kind
                for m in mods for lname, kind in m.locks.items()}

    # ---- C002: lock acquisition-order graph + cycles ----
    edges: dict = {}
    for cls in classes:
        for mi in cls.methods.values():
            eff = mi.entry_held
            for key, held, node in mi.acquires:
                for l1 in (eff | held):
                    if l1 != key:
                        edges.setdefault((l1, key), (cls, node))
                    elif _lock_kind_of(key, modlocks) != "rlock":
                        findings.append(Finding(
                            _mod_of(mods, cls).filename, node.lineno,
                            node.col_offset, "C002", RULES["C002"],
                            f"non-reentrant lock {_fmt_key(key)}"
                            " re-acquired while already held on this"
                            " path: instant self-deadlock (use an"
                            " RLock or split the critical section)"))
            for name, held, node in mi.self_calls:
                callee = cls.methods.get(name)
                if callee is None:
                    continue
                for l1 in (eff | held):
                    for l2 in acq[id(callee)]:
                        if l1 != l2:
                            edges.setdefault((l1, l2), (cls, node))
            for attr, meth, held, node in mi.attr_calls:
                callee = _resolve_attr_call(cls, attr, meth)
                if callee is None:
                    continue
                for l1 in (eff | held):
                    for l2 in acq[id(callee)]:
                        if l1 != l2:
                            edges.setdefault((l1, l2), (cls, node))
    _report_cycles(edges, mods, findings)

    # ---- per-class rules ----
    for cls in classes:
        mod = _mod_of(mods, cls)
        _check_c001(cls, mod, findings)
        _check_c003(cls, mod, findings)
        _check_c004(cls, mod, findings)
        _check_c007(cls, mod, findings)
    # ---- module functions: C003 + C005 + C007 ----
    for mod in mods:
        for fi in mod.functions.values():
            _check_fn_blocking(fi, mod, findings)
            _check_c005(fi, mod, findings)
        for cls in mod.classes:
            for mi in cls.methods.values():
                _check_c005(mi, mod, findings)


def _mod_of(mods: list, cls: _Class) -> _Module:
    for m in mods:
        if cls in m.classes:
            return m
    return mods[0]


def _report_cycles(edges: dict, mods: list, findings: list) -> None:
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    # iterative DFS cycle detection with path recovery
    seen: set = set()
    for start in sorted(graph):
        if start in seen:
            continue
        stack = [(start, iter(sorted(graph.get(start, ()))))]
        on_path = {start}
        path = [start]
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt in on_path:
                    cycle = path[path.index(nxt):] + [nxt]
                    cls, site = edges[(node, nxt)]
                    mod = _mod_of(mods, cls)
                    order = " -> ".join(_fmt_key(k) for k in cycle)
                    findings.append(Finding(
                        mod.filename, site.lineno, site.col_offset,
                        "C002", RULES["C002"],
                        f"lock acquisition-order cycle: {order} —"
                        " two threads taking these locks in opposite"
                        " order deadlock; impose one global order"))
                    continue
                if nxt in seen:
                    continue
                stack.append((nxt, iter(sorted(graph.get(nxt, ())))))
                on_path.add(nxt)
                path.append(nxt)
                advanced = True
                break
            if not advanced:
                seen.add(node)
                on_path.discard(node)
                path.pop()
                stack.pop()


def _check_c001(cls: _Class, mod: _Module, findings: list) -> None:
    skip_attrs = set(cls.locks) | {
        a for a, t in cls.attr_types.items()
        if t in ("thread", "event", "socket")}
    by_attr: dict = {}
    for name, mi in cls.methods.items():
        if name in _INIT_NAMES:
            continue
        for attr, held, node, closure in mi.writes:
            if attr in skip_attrs:
                continue
            ctxs = {frozenset()} if closure else mi.entry_contexts
            for ctx in ctxs:
                by_attr.setdefault(attr, []).append(
                    (held | ctx, node, name))
    for attr, writes in sorted(by_attr.items()):
        guarded = [w for w in writes if w[0]]
        unguarded = [w for w in writes if not w[0]]
        if not guarded or not unguarded:
            continue
        guard = sorted({_fmt_key(k) for eff, _n, _m in guarded
                       for k in eff})
        for _eff, node, meth in unguarded:
            findings.append(Finding(
                mod.filename, node.lineno, node.col_offset, "C001",
                RULES["C001"],
                f"self.{attr} is guarded by {'/'.join(guard)} elsewhere"
                f" but mutated without it in {cls.name}.{meth}(): a"
                " concurrent guarded writer races this write (the"
                " scan-cache touch/evict shape)"))


def _blocking_findings(mi: _Method, eff_entry: frozenset):
    for desc, held, node, exempt in mi.blocking:
        eff = held | eff_entry
        if exempt is not None:
            eff = eff - {exempt}
        if eff:
            yield desc, eff, node


def _check_c003(cls: _Class, mod: _Module, findings: list) -> None:
    for mi in cls.methods.values():
        for desc, eff, node in _blocking_findings(mi, mi.entry_held):
            _flag_blocking(mod, node, desc, eff, findings)
    # one-level propagation: calling a may-block callee while held.
    # The callee's own-condition exemption carries over — a helper
    # waiting on a condition the CALLER holds still releases it.
    for mi in cls.methods.values():
        for name, held, node in mi.self_calls:
            eff = held | mi.entry_held
            if not eff:
                continue
            callee = cls.methods.get(name)
            if callee is None:
                continue
            for desc, bheld, _bn, exempt in callee.blocking:
                if bheld:
                    continue  # flagged at its own site if locked there
                if eff - ({exempt} if exempt else set()):
                    _flag_blocking(
                        mod, node, f"{name}() -> {desc}", eff, findings)
                    break
        for attr, meth, held, node in mi.attr_calls:
            eff = held | mi.entry_held
            if not eff:
                continue
            callee = _resolve_attr_call(cls, attr, meth)
            if callee is None:
                continue
            for desc, bheld, _bn, exempt in callee.blocking:
                if bheld:
                    continue
                if eff - ({exempt} if exempt else set()):
                    _flag_blocking(
                        mod, node, f"{attr}.{meth}() -> {desc}", eff,
                        findings)
                    break


def _check_fn_blocking(fi: _Method, mod: _Module,
                       findings: list) -> None:
    for desc, eff, node in _blocking_findings(fi, frozenset()):
        _flag_blocking(mod, node, desc, eff, findings)


def _flag_blocking(mod, node, desc, eff, findings):
    locks = ", ".join(sorted(_fmt_key(k) for k in eff))
    findings.append(Finding(
        mod.filename, node.lineno, node.col_offset, "C003",
        RULES["C003"],
        f"blocking call {desc} while holding {locks}: every other"
        " thread needing the lock stalls behind this wait (and a"
        " cyclic wait deadlocks) — move the wait outside the critical"
        " section or bound it with a timeout"))


def _check_c004(cls: _Class, mod: _Module, findings: list) -> None:
    if cls.has_stop_path():
        return
    for mi in cls.methods.values():
        for node in mi.daemon_spawns:
            findings.append(Finding(
                mod.filename, node.lineno, node.col_offset, "C004",
                RULES["C004"],
                f"{cls.name} starts a daemon thread but has no"
                " stop/close/shutdown/join method: the thread runs"
                " until process exit with no orderly stop path"))


def _check_c005(fi: _Method, mod: _Module, findings: list) -> None:
    for name, held, node in fi.global_writes:
        if name in mod.locks:
            continue
        module_locked = any(k[0] == "M" and k[1] == mod.name
                            for k in held)
        if not module_locked:
            findings.append(Finding(
                mod.filename, node.lineno, node.col_offset, "C005",
                RULES["C005"],
                f"module-global {name} written without a module lock:"
                " conveyor/pool workers sharing this module race the"
                " write — guard it with a module-level Lock"))


def _check_c007(cls: _Class, mod: _Module, findings: list) -> None:
    for mi in cls.methods.values():
        for key, held, node in mi.notifies:
            if key not in (held | mi.entry_held):
                findings.append(Finding(
                    mod.filename, node.lineno, node.col_offset, "C007",
                    RULES["C007"],
                    f"{_fmt_key(key)}.notify called without holding"
                    " the condition's lock: RuntimeError at best, a"
                    " lost wakeup at worst — notify inside ``with"
                    " cond:``"))


# ---------------- driver ----------------


def check_source(src: str, filename: str = "<string>",
                 modname: "str | None" = None) -> list:
    """Analyze one source text (tests); returns unsuppressed findings."""
    return check_sources([(src, filename, modname or "m")])


def check_sources(sources) -> list:
    """Analyze (src, filename, modname) triples as ONE program (cross-
    module lock-order edges resolve across them)."""
    with _REG_LOCK:
        return _check_sources_locked(sources)


def _check_sources_locked(sources) -> list:
    with _REG_LOCK:
        _CLASSES.clear()
    findings: list = []
    mods = []
    lines_by_file: dict = {}
    for src, filename, modname in sources:
        lines = src.splitlines()
        lines_by_file[filename] = lines
        if file_skipped(lines):
            continue
        mod = _scan_module(src, filename, modname, findings)
        if mod is not None:
            mods.append(mod)
    if mods:
        _check_classes(mods, findings)
    kept = []
    for filename, lines in lines_by_file.items():
        here = [f for f in findings if f.file == filename]
        kept.extend(filter_suppressed(here, lines, RULES))
    return sorted(kept, key=lambda f: (f.file, f.line, f.col, f.code))


def check_paths(paths) -> list:
    sources = []
    for f in paths:
        sources.append((f.read_text(encoding="utf-8"), str(f), f.stem))
    return check_sources(sources)


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    files = collect_files(paths, changed=changed)
    findings = check_paths(files)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
