"""Runtime leak sanitizer: every tracked resource handle must drain.

The static half (``analysis/lifecycle.py``) proves acquire/release
PAIRING; this module catches what static analysis cannot see — dynamic
call paths, chaos-injected faults, cancellation racing a release. With
``YDB_TPU_LEAKSAN=1`` the resource-bearing layers wrap their
acquire/release sites in :func:`track` handles:

  * conveyor.task       — a submitted task until its handle completes
  * broker.slot         — a ResourceBroker grant until release()
  * resident.flight     — a ResidentStore single-flight promotion
  * blockcache.flight   — a DeviceBlockCache single-flight fill
  * session.active      — a statement's in-flight registry row
  * rm.slot             — a ResourceManager compute-slot grant
  * serving.conn        — a protocol-front connection/session (pgwire
                          socket, RequestProxy server-side session)
  * serving.seat        — a front-door admission seat or a
                          RequestProxy operation-thread handoff

Each live handle retains its creation-site stack, so
:func:`assert_drained` — hooked at statement completion (per-owner) and
``Cluster.stop`` (global) — raises :class:`LeakError` naming exactly
which handles leaked and where they were acquired. The chaos harness
(tests/test_chaos.py) runs its seeded fault scenarios under this gate:
every injected fault + cancellation must still drain to zero.

Disabled (the default), every :func:`track` site costs one module-global
bool check returning ``None`` and every :func:`close` a ``None`` test —
safe to leave compiled into hot paths. ``kernelbench
--leaksan-overhead`` holds that budget. Like ``sanitizer``, this module
keeps a bare dependency set (os + threading + traceback) so the
low-level runtime modules can import it unconditionally.
"""

from __future__ import annotations

import os
import threading
import traceback

#: In-process override of the YDB_TPU_LEAKSAN env gate (the
#: chaos.CHAOS_FORCE idiom): None = follow the environment, True/False
#: = force. Set via :func:`set_force` (or :class:`activate`) so the
#: hot-path gate recomputes.
LEAKSAN_FORCE: "bool | None" = None

#: creation-stack frames retained per handle: enough to name the
#: acquire site and its caller without making armed tracking heavy
STACK_DEPTH = 8


def enabled() -> bool:
    if LEAKSAN_FORCE is not None:
        return LEAKSAN_FORCE
    return os.environ.get("YDB_TPU_LEAKSAN", "0") not in ("0", "", "off")


# the single check on the disabled hot path (chaos._ARMED idiom):
# recomputed whenever the force pin or (via refresh()) the env changes
_ON = enabled()

#: guards the handle registry AND the gate writes (chaos._state_lock
#: idiom); hot-path READS of _ON stay lock-free by design
_meta_lock = threading.Lock()


def refresh() -> None:
    """Recompute the hot-path gate after an environment change (tests
    that monkeypatch YDB_TPU_LEAKSAN call this; set_force calls it)."""
    global _ON
    with _meta_lock:
        _ON = enabled()


def set_force(value: "bool | None") -> None:
    """Pin the gate in-process (True/False) or return to the
    environment (None)."""
    global LEAKSAN_FORCE, _ON
    with _meta_lock:
        LEAKSAN_FORCE = value
        _ON = enabled()


class LeakError(AssertionError):
    """A tracked resource handle outlived its drain point."""


class Handle:
    """One live acquisition of a tracked resource kind."""

    __slots__ = ("kind", "site", "owner", "seq", "stack", "closed")

    def __init__(self, kind: str, site: str, owner, seq: int,
                 stack: list):
        self.kind = kind
        self.site = site
        self.owner = owner
        self.seq = seq
        self.stack = stack
        self.closed = False

    def close(self) -> None:
        """Idempotent: a handle released twice (retry paths) is fine —
        double-release bugs are the lifecycle analyzer's beat."""
        if self.closed:
            return
        self.closed = True
        with _meta_lock:
            _LIVE.pop(self.seq, None)

    def describe(self) -> str:
        where = "".join(traceback.format_list(self.stack[-3:])).rstrip()
        return (f"{self.kind}[{self.site}]"
                + (f" owner={self.owner}" if self.owner is not None
                   else "")
                + f" acquired at:\n{where}")


_LIVE: dict = {}  # seq -> Handle
_seq = 0


def track(kind: str, site: str = "", owner=None) -> "Handle | None":
    """Open a handle around a resource acquisition. Returns None when
    the sanitizer is off (one module-global bool per call site); the
    matching release calls :func:`close` on whatever this returned."""
    if not _ON:
        return None
    global _seq
    stack = traceback.extract_stack(limit=STACK_DEPTH)[:-1]
    with _meta_lock:
        _seq += 1
        h = Handle(kind, site, owner, _seq, stack)
        _LIVE[h.seq] = h
    return h


def close(handle: "Handle | None") -> None:
    """Release the handle a :func:`track` site returned (None-safe, so
    disabled-path call sites stay branch-free)."""
    if handle is not None:
        handle.close()


def live(kind: "str | None" = None, owner=None) -> list:
    """Currently open handles, optionally filtered by kind/owner."""
    with _meta_lock:
        hs = list(_LIVE.values())
    return [h for h in hs
            if (kind is None or h.kind == kind)
            and (owner is None or h.owner == owner)]


def counts() -> dict:
    """Live-handle gauge per kind (the drain-to-zero surface the soak
    and chaos acceptance tests assert on). Empty dict when drained."""
    out: dict = {}
    with _meta_lock:
        for h in _LIVE.values():
            out[h.kind] = out.get(h.kind, 0) + 1
    return out


def assert_drained(kinds=None, owner=None, where: str = "") -> None:
    """Raise :class:`LeakError` naming every live handle (optionally
    scoped to ``kinds`` and/or ``owner``). No-op when disabled — the
    hooks in Session.execute / Cluster.stop cost one bool when off."""
    if not _ON:
        return
    leaked = [h for h in live(owner=owner)
              if kinds is None or h.kind in kinds]
    if not leaked:
        return
    names = "\n\n".join(h.describe() for h in leaked[:8])
    more = f"\n... and {len(leaked) - 8} more" if len(leaked) > 8 else ""
    raise LeakError(
        f"{len(leaked)} leaked resource handle(s)"
        + (f" at {where}" if where else "") + f":\n{names}{more}")


def reset() -> None:
    """Forget all live handles (test isolation between runs)."""
    with _meta_lock:
        _LIVE.clear()


class activate:
    """Context manager forcing the sanitizer on (tests): fresh handle
    state on entry and exit so runs stay independent."""

    def __enter__(self) -> "activate":
        reset()
        set_force(True)
        return self

    def __exit__(self, *exc) -> None:
        set_force(None)
        reset()
