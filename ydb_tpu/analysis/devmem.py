"""Device-memory static analyzer: HBM provenance rules M001-M008.

ROADMAP item 1 turns warm statements into (cached executable, resident
input set) pairs, which makes every stray device-array copy an HBM
leak and every unrounded shape a retrace storm on real TPUs. This pass
walks the runtime packages — engine, ssa, kqp, parallel, blocks,
serving — and proves the discipline statically; the runtime half
(``analysis/memsan.py``) measures the bytes those seams actually
allocate per statement.

Rules:

  M001 unbudgeted-device-alloc   device-array creation (``jnp.zeros/
                                 ones/full/stack/asarray``,
                                 ``jax.device_put``,
                                 ``TableBlock.from_numpy``) outside a
                                 budget-charging seam (a ``memsan.seam/
                                 charge`` or ``timeline.add_bytes``
                                 site, transitively via callers)
  M002 use-after-donation        a name passed at a donated argnum of
                                 a ``donate_argnums`` jit referenced
                                 after the call — the buffer was
                                 consumed by the dispatch
  M003 donated-jit-rebuild       ``jax.jit`` over a bound method /
                                 reused function object (the PR 9 bug:
                                 jax's cache keys on function equality,
                                 so a re-jit after grow() silently
                                 reuses the old-capacity trace — or
                                 retraces per call for bound methods,
                                 which mint a fresh object per access)
  M004 unrounded-jit-shape       a block/array built with a
                                 data-dependent size (``len(...)``,
                                 ``.shape``) that never passes through
                                 ``shape_class``/``_round_up``/
                                 ``size_buckets`` — every distinct
                                 length becomes its own trace
  M005 device-closure-in-pool    a device array captured by a closure
                                 submitted to a conveyor/stream pool —
                                 the task handle pins the HBM buffer
                                 for the statement's lifetime
  M006 grow-only-device-container  a container attribute accumulating
                                 device arrays with no eviction/budget
                                 valve anywhere in the class (the
                                 device-sharpened lifecycle R007)
  M007 per-dispatch-aux-staging  host->device staging of constant aux
                                 outside the cached ``device_aux``
                                 idiom — re-ships the same tables
                                 every dispatch
  M008 device-across-yield       a device buffer bound before a
                                 ``yield`` and used after it — the
                                 slab stays pinned while the consumer
                                 parks the generator

Trace-context exemptions (M001/M004/M007): allocations under an XLA
trace are device temporaries, not HBM residents, so the scan skips
functions jit-decorated, nested defs handed to ``jit/vmap/pmap/
shard_map/grad`` in their builder, and nested defs *returned* by their
builder (the plan-lowering emit idiom — builders wire them into a
traced dispatch). A module whose first lines carry ``# ydb-devmem:
device-module`` declares itself trace-context wholesale (pure kernel
modules); provenance rules still apply there.

Escape hatch: decorate a function ``@analysis.budget_ok("reason")`` to
declare its device allocations budgeted or bounded — it is neither
reported nor counted against callees. Line-level ``# ydb-lint:
disable=M001`` pragmas (shared suppress machinery) silence individual
sites.

Interprocedural: the analyzer reuses hotpath's module index and call
resolution. A function whose every indexed caller is (transitively)
budget-charging inherits the charge — staging helpers called only from
charging seams need no annotation of their own.

Run: ``python -m ydb_tpu.analysis.devmem [path ...] [--json]
[--changed]``. Default scope: the runtime packages of ydb_tpu. Exit 1
on any unsuppressed finding. ``tests/test_devmem_clean.py`` enforces a
clean tree as a tier-1 test.
"""

from __future__ import annotations

import ast
import json
import sys

from ydb_tpu.analysis.hotpath import _Index, _Module, _modname_for
from ydb_tpu.analysis.lint import Finding, _dotted
from ydb_tpu.analysis.paths import collect_files, parse_cli
from ydb_tpu.analysis.suppress import file_skipped, filter_suppressed

RULES = {
    "M001": "unbudgeted-device-alloc",
    "M002": "use-after-donation",
    "M003": "donated-jit-rebuild",
    "M004": "unrounded-jit-shape",
    "M005": "device-closure-in-pool",
    "M006": "grow-only-device-container",
    "M007": "per-dispatch-aux-staging",
    "M008": "device-across-yield",
}

#: the runtime packages the device-memory discipline governs
RUNTIME_PACKAGES = ("engine", "ssa", "kqp", "parallel", "blocks",
                    "serving")

#: device-array creators (M001/M007 subjects; M004 size checks)
_CREATOR_ROOTS = {
    "jnp.zeros", "jnp.ones", "jnp.full", "jnp.stack", "jnp.asarray",
    "jnp.array", "jnp.arange", "jnp.concatenate", "jnp.empty",
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.asarray",
    "jax.numpy.stack", "jax.device_put",
}
_CREATOR_METHODS = {"from_numpy"}
#: size-rounding seams that legitimize a data-dependent capacity (M004)
_ROUNDERS = {"shape_class", "_round_up", "round_up", "size_buckets"}
#: wrappers that put a callee into trace context
_TRACE_WRAPPERS = ("jit", "vmap", "pmap", "shard_map", "grad")
#: pool-submission entry points (M005)
_SUBMIT_NAMES = {"submit", "spawn", "defer", "map_async",
                 "apply_async"}


def _device_module(lines) -> bool:
    """``# ydb-devmem: device-module`` within the first 10 lines: the
    module is trace-context wholesale (pure kernel code)."""
    for ln in lines[:10]:
        if "ydb-devmem:" in ln and "device-module" in ln:
            return True
    return False


def _budget_ok_reason(node) -> "str | None":
    """The reason of an ``@analysis.budget_ok("...")`` decorator (or
    bare ``@budget_ok``); None when the function carries none."""
    for dec in getattr(node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        last = _dotted(target).rsplit(".", 1)[-1].lstrip("_")
        if last == "budget_ok":
            if isinstance(dec, ast.Call) and dec.args and \
                    isinstance(dec.args[0], ast.Constant):
                return str(dec.args[0].value)
            return "unspecified"
    return None


def _is_jit_decorated(node) -> bool:
    """Any decorator mentioning jit/pmap/vmap (including
    ``functools.partial(jax.jit, ...)``) puts the body under trace."""
    for dec in getattr(node, "decorator_list", ()):
        for sub in ast.walk(dec):
            if isinstance(sub, (ast.Name, ast.Attribute)):
                last = _dotted(sub).rsplit(".", 1)[-1]
                if last in _TRACE_WRAPPERS:
                    return True
    return False


def _is_creator(call: ast.Call) -> "str | None":
    """The creator name when ``call`` builds a device array."""
    root = _dotted(call.func)
    if root in _CREATOR_ROOTS:
        return root
    attr = call.func.attr if isinstance(call.func, ast.Attribute) \
        else ""
    if attr in _CREATOR_METHODS:
        return f".{attr}"
    return None


def _charging_call(call: ast.Call, imports: dict) -> bool:
    """Does this call charge a byte budget? ``memsan.seam/charge``
    (by any alias) and ``timeline.add_bytes`` (the resident/stream/
    shuffle byte ledgers) qualify."""
    fn = call.func
    root = _dotted(fn)
    last = root.rsplit(".", 1)[-1]
    if last == "add_bytes":
        return True
    if last in ("seam", "charge"):
        if "memsan" in root:
            return True
        if isinstance(fn, ast.Name):
            origin = imports.get(fn.id, "")
            return "memsan" in origin
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            origin = imports.get(fn.value.id, "")
            return "memsan" in origin
    return False


def _contains(expr, pred) -> bool:
    for sub in ast.walk(expr):
        if pred(sub):
            return True
    return False


def _data_dependent_size(expr) -> bool:
    """Does a size expression embed a raw data length (len()/.shape)
    without passing through a rounding seam?"""
    dependent = _contains(expr, lambda s: (
        isinstance(s, ast.Call) and isinstance(s.func, ast.Name)
        and s.func.id == "len")
        or (isinstance(s, ast.Attribute) and s.attr == "shape"))
    if not dependent:
        return False
    rounded = _contains(expr, lambda s: isinstance(s, ast.Call) and
                        _dotted(s.func).rsplit(".", 1)[-1] in _ROUNDERS)
    return not rounded


def _donated_argnums(call: ast.Call) -> "tuple | None":
    """The donate_argnums of a jax.jit call (None when absent). An
    IfExp value takes its true branch — the donating configuration is
    the hazardous one."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.IfExp):
                v = v.body
            nums = []
            for sub in ast.walk(v):
                if isinstance(sub, ast.Constant) and \
                        isinstance(sub.value, int):
                    nums.append(sub.value)
            return tuple(nums)
    return None


def _local_device_names(fn_node) -> dict:
    """name -> assignment lineno for locals bound directly to a device
    array (creator call / from_numpy / device_aux result) in this
    function's own body (nested defs excluded — their locals are their
    own scope)."""
    out: dict = {}

    def walk(stmts):
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(st, ast.Assign) and \
                    isinstance(st.value, ast.Call):
                name = _is_creator(st.value)
                if name is None:
                    last = _dotted(st.value.func).rsplit(".", 1)[-1]
                    if last in ("device_aux",):
                        name = last
                if name is not None:
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = st.lineno
            # recurse into compound-statement bodies (with/for/if/try)
            # but never nested scopes
            for part in ("body", "orelse", "finalbody"):
                sub = getattr(st, part, None)
                if isinstance(sub, list):
                    walk(sub)
            for h in getattr(st, "handlers", None) or ():
                walk(h.body)
    walk(fn_node.body)
    return out


class _ClassLedger:
    """Per-class evidence for M006: container attrs, device stores,
    removal/budget valves."""

    def __init__(self):
        self.containers: set = set()        # attr names init'd {} / []
        self.stores: list = []              # (attr, node, creator)
        self.removals: set = set()          # attrs with pop/del/clear
        self.has_valve = False              # evict/budget-family method


class _FnScan:
    """All M-rule checks over ONE indexed function (nested defs scanned
    in scope context)."""

    def __init__(self, info, mod: _Module, budget_ok: "str | None",
                 device_mod: bool, ledger: "_ClassLedger | None",
                 index: "_Index | None" = None):
        self.info = info
        self.mod = mod
        self.budget_ok = budget_ok
        self.device_mod = device_mod
        self.ledger = ledger
        self._index = index
        self.findings: list = []            # direct findings
        self.deferred: list = []            # coverage-gated findings
        self.calls: list = []               # (Call, traced) for edges
        self.charging = False
        self.device_locals = _local_device_names(info.node)
        # names put under trace in THIS body: jit(f)/vmap(f) args,
        # returned nested defs (the emit idiom), nested defs escaping
        # as call arguments (CompiledProgram(run=run)), nested defs
        # invoked from a traced scope (fixpoint in run())
        self.traced_names: set = set()
        self.nested_defs: dict = {}
        self._prepass(info.node)
        # donated jit bindings: local name / self-attr -> argnums
        self.donated: dict = {}
        # findings muted during trace-propagation passes
        self._mute = False
        # Lambda nodes that are arguments of a trace wrapper
        self._traced_lambdas: set = set()

    # ---- pre-pass: trace context + charging evidence ----

    def _prepass(self, fn_node) -> None:
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Call):
                if _charging_call(node, self.mod.imports):
                    self.charging = True
                last = _dotted(node.func).rsplit(".", 1)[-1]
                if last in _TRACE_WRAPPERS:
                    for a in list(node.args) + \
                            [k.value for k in node.keywords]:
                        if isinstance(a, ast.Name):
                            self.traced_names.add(a.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)) and \
                    node is not fn_node:
                self.nested_defs[node.name] = node
            elif isinstance(node, ast.Return) and \
                    node.value is not None:
                # a returned nested def is deferred computation the
                # builder wires into a traced dispatch (the lowering
                # emit idiom) — bare or inside a returned tuple
                vals = node.value.elts if isinstance(
                    node.value, (ast.Tuple, ast.List)) else [node.value]
                for v in vals:
                    if isinstance(v, ast.Name):
                        self.traced_names.add(v.id)

    # ---- driver ----

    def run(self) -> None:
        traced0 = self.device_mod or _is_jit_decorated(self.info.node)
        # trace propagation to fixpoint (a nested def called from a
        # traced scope is itself traced), muted; then one emit pass
        self._mute = True
        for _ in range(6):
            before = len(self.traced_names)
            self.calls = []
            self.donated = {}
            self._walk(self.info.node.body, traced=traced0)
            if len(self.traced_names) == before:
                break
        self._mute = False
        self.calls = []
        self.donated = {}
        self._walk(self.info.node.body, traced=traced0)
        self._check_donation_uses(self.info.node)
        if any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in self._own_exprs(self.info.node)):
            self._check_yield_pins(self.info.node)

    def _emit(self, node, code: str, message: str) -> None:
        if self._mute:
            return
        self.findings.append(Finding(
            self.info.filename, node.lineno, node.col_offset, code,
            RULES[code], message))

    # ---- scoped walk: M001/M003/M004/M005/M007 ----

    def _walk(self, stmts, traced: bool) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # the nested def's OWN trace context; its children are
                # walked here only (never through _node)
                sub_traced = traced or \
                    st.name in self.traced_names or \
                    _is_jit_decorated(st)
                self._walk(st.body, traced=sub_traced)
                continue
            for node in ast.iter_child_nodes(st):
                self._node(node, st, traced)

    def _node(self, node, stmt, traced: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def inside a compound statement: walk its body in
            # its own trace context
            self._walk(node.body, traced=traced or
                       node.name in self.traced_names or
                       _is_jit_decorated(node))
            return
        if isinstance(node, ast.Call):
            self._call(node, stmt, traced)
        if isinstance(node, ast.Lambda):
            # lambdas handed to a trace wrapper run under XLA; any
            # other lambda body (tree_map stackers, sort keys) runs in
            # the enclosing context
            self._node(node.body, stmt,
                       traced or node in self._traced_lambdas)
            return
        for sub in ast.iter_child_nodes(node):
            self._node(sub, stmt, traced)

    def _call(self, call: ast.Call, stmt, traced: bool) -> None:
        self.calls.append((call, traced))
        root = _dotted(call.func)
        attr = call.func.attr if isinstance(call.func, ast.Attribute) \
            else ""
        creator = _is_creator(call)
        last = root.rsplit(".", 1)[-1]

        # ---- trace propagation (consumed by run()'s fixpoint) ----
        if isinstance(call.func, ast.Name) and traced and \
                call.func.id in self.nested_defs:
            # a nested def invoked from a traced scope is traced
            self.traced_names.add(call.func.id)
        if attr not in _SUBMIT_NAMES:
            # a nested def escaping as a call argument is deferred
            # computation wired into a traced dispatch
            # (CompiledProgram(run=run), _GroupByLowered(lower=lower));
            # pool submits stay host context (M005 territory)
            for a in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(a, ast.Name) and \
                        a.id in self.nested_defs:
                    self.traced_names.add(a.id)
                elif isinstance(a, ast.Lambda) and \
                        last in _TRACE_WRAPPERS:
                    self._traced_lambdas.add(a)

        # ---- M003 + donated-jit tracking ----
        if root.rsplit(".", 1)[-1] == "jit" and (
                root.startswith("jax") or root == "jit"):
            self._jit_site(call, stmt)

        if creator is not None and not traced and not self._mute:
            fnkey = (self.info.modname, self.info.qualname)
            # ---- M004: data-dependent size (trace-coverage-gated:
            # shapes inside a trace are static by construction) ----
            size_args = [kw.value for kw in call.keywords
                         if kw.arg == "capacity"]
            if not size_args and call.args and creator != ".from_numpy":
                size_args = [call.args[0]]
            for sa in size_args:
                if _data_dependent_size(sa):
                    self.deferred.append((Finding(
                        self.info.filename, call.lineno,
                        call.col_offset, "M004", RULES["M004"],
                        f"{creator}(...) sized by a raw data length:"
                        " every distinct length is a fresh trace;"
                        " round through shape_class()/_round_up() so"
                        " same-class re-runs reuse the executable"),
                        fnkey, None))
            # ---- M007: aux staged outside device_aux ----
            if self._touches_aux(call) and \
                    self.info.node.name != "device_aux":
                self.deferred.append((Finding(
                    self.info.filename, call.lineno, call.col_offset,
                    "M007", RULES["M007"],
                    f"{creator}(...) stages constant aux per dispatch;"
                    " route it through the cached device_aux idiom so"
                    " repeated dispatches reuse the staged tables"),
                    fnkey, None))
            # ---- M001: deferred until coverage is known. A creator
            # METHOD whose callee charges its own budget (the
            # instrumented from_numpy) budgets the call site too ----
            elif not (self.charging or self.budget_ok):
                callee = None
                if creator.startswith("."):
                    tgt = _resolve_call(self._index, self.mod,
                                        self.info, call) \
                        if self._index is not None else None
                    if tgt is not None:
                        callee = (tgt.modname, tgt.qualname)
                self.deferred.append((Finding(
                    self.info.filename, call.lineno, call.col_offset,
                    "M001", RULES["M001"],
                    f"{creator}(...) creates a device array outside"
                    " any budget-charging seam: charge it via"
                    " memsan.seam()/charge() (or a byte ledger) or"
                    " annotate the function @analysis.budget_ok"),
                    fnkey, callee))

        # ---- M005: device capture into a pool submit ----
        if attr in _SUBMIT_NAMES and call.args:
            self._submit_site(call)

        # ---- M006 evidence: stores handled at statement level ----
        if self.ledger is not None and not self._mute:
            self._ledger_call(call, attr)

    # ---- M003 / M002 ----

    def _jit_site(self, call: ast.Call, stmt) -> None:
        if not call.args:
            return
        target = call.args[0]
        donated = _donated_argnums(call)
        rebuild_path = any(
            k in self.info.node.name.lower()
            for k in ("grow", "rebuild", "rejit", "retrace", "resize"))
        hazard = None
        if isinstance(target, ast.Attribute) and (donated or
                                                  rebuild_path):
            # a one-time bound-method jit in __init__ is benign; the
            # PR 9 shape is donating or re-jitting on a grow path,
            # where jax's function-equality cache silently reuses the
            # old-capacity trace
            hazard = (f"jax.jit({_dotted(target)}) re-jits a bound"
                      " method/attribute on a donate/grow path: jax's"
                      " cache keys on function equality, so this"
                      " either silently reuses a stale trace after"
                      " grow()/rebuild or retraces per call (bound"
                      " methods mint a fresh object per access); wrap"
                      " a fresh local function per (re)build instead")
        elif isinstance(target, ast.Name) and donated:
            if target.id not in self.nested_defs and \
                    target.id not in self.mod.fns:
                hazard = (f"jax.jit({target.id},"
                          " donate_argnums=...) over a reused function"
                          " object: a later re-jit of the same object"
                          " returns the cached old-shape trace (the"
                          " grow/retrace hazard); build a fresh"
                          " wrapper function at each (re)jit")
        if hazard:
            self._emit(call, "M003", hazard)
        if donated:
            # record where the donating callable lands (M002)
            parent = stmt
            if isinstance(parent, ast.Assign) and \
                    len(parent.targets) == 1:
                t = parent.targets[0]
                if isinstance(t, ast.Name):
                    self.donated[t.id] = donated
                elif isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    self.donated[f"self.{t.attr}"] = donated

    def _check_donation_uses(self, fn_node) -> None:
        """M002: a name passed at a donated argnum loaded after the
        donating call (line-ordered within this function)."""
        if not self.donated:
            return
        calls = []  # (lineno, [donated arg names])
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            key = None
            f = node.func
            if isinstance(f, ast.Name) and f.id in self.donated:
                key = f.id
            elif isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "self" and \
                    f"self.{f.attr}" in self.donated:
                key = f"self.{f.attr}"
            if key is None:
                continue
            names = []
            for pos in self.donated[key]:
                if pos < len(node.args) and \
                        isinstance(node.args[pos], ast.Name):
                    names.append(node.args[pos].id)
            if names:
                calls.append((node.lineno, names))
        if not calls:
            return
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                for lineno, names in calls:
                    if node.id in names and node.lineno > lineno:
                        self._emit(
                            node, "M002",
                            f"{node.id!r} was donated to a jitted"
                            f" dispatch at line {lineno} and is"
                            " referenced afterwards: the buffer was"
                            " consumed by XLA — re-stage it or drop"
                            " donation for this input")

    # ---- M005 ----

    def _submit_site(self, call: ast.Call) -> None:
        task = call.args[0]
        captured: "list[str]" = []
        if isinstance(task, ast.Lambda):
            params = {a.arg for a in task.args.args}
            for sub in ast.walk(task.body):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in self.device_locals and \
                        sub.id not in params:
                    captured.append(sub.id)
        elif isinstance(task, ast.Name) and \
                task.id in self.nested_defs:
            nd = self.nested_defs[task.id]
            params = {a.arg for a in nd.args.args}
            locals_ = {t.id for n in ast.walk(nd)
                       for t in ([n] if isinstance(n, ast.Name) and
                                 isinstance(n.ctx, ast.Store) else [])}
            for sub in ast.walk(nd):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Load) and \
                        sub.id in self.device_locals and \
                        sub.id not in params and sub.id not in locals_:
                    captured.append(sub.id)
        if captured:
            names = ", ".join(sorted(set(captured)))
            self._emit(
                call, "M005",
                f"closure submitted to a pool captures device"
                f" array(s) {names}: the task handle pins the HBM"
                " buffer until the pool runs and drops it — pass host"
                " data / a loader and stage inside the task, or hand"
                " over an owning reference the task releases")

    # ---- M006 evidence ----

    def _ledger_call(self, call: ast.Call, attr: str) -> None:
        led = self.ledger
        f = call.func
        if not (isinstance(f, ast.Attribute) and
                isinstance(f.value, ast.Attribute) and
                isinstance(f.value.value, ast.Name) and
                f.value.value.id == "self"):
            if attr in ("pop", "clear") and \
                    isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name):
                pass
            return
        target_attr = f.value.attr
        if attr in ("pop", "clear", "popitem"):
            led.removals.add(target_attr)
        elif attr in ("append", "add", "setdefault") and call.args:
            arg = call.args[-1]
            creator = isinstance(arg, ast.Call) and \
                _is_creator(arg) is not None
            tracked = isinstance(arg, ast.Name) and \
                arg.id in self.device_locals
            if creator or tracked:
                led.stores.append((target_attr, call))

    def scan_statements_for_ledger(self) -> None:
        """Subscript stores + dels feeding the class M006 ledger."""
        led = self.ledger
        if led is None:
            return
        name = self.info.node.name
        for node in ast.walk(self.info.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            self._self_attr(t.value):
                        a = self._self_attr(t.value)
                        v = node.value
                        creator = isinstance(v, ast.Call) and \
                            _is_creator(v) is not None
                        tracked = isinstance(v, ast.Name) and \
                            v.id in self.device_locals
                        if creator or tracked:
                            led.stores.append((a, node))
                    elif isinstance(t, ast.Attribute) and \
                            name == "__init__" and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        if self._container_init(node.value):
                            led.containers.add(t.attr)
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript) and \
                            self._self_attr(t.value):
                        led.removals.add(self._self_attr(t.value))
        low = name.lower()
        if any(k in low for k in ("evict", "budget", "trim", "sweep",
                                  "invalidate", "drop", "clear",
                                  "release")):
            led.has_valve = True

    @staticmethod
    def _self_attr(node) -> "str | None":
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self":
            return node.attr
        return None

    @staticmethod
    def _container_init(value) -> bool:
        if isinstance(value, (ast.Dict, ast.List)):
            return True
        if isinstance(value, ast.Call):
            last = _dotted(value.func).rsplit(".", 1)[-1]
            if last == "share" and value.args and \
                    isinstance(value.args[0], (ast.Dict, ast.List)):
                return True
            if last in ("dict", "list", "OrderedDict", "defaultdict"):
                return True
        return False

    # ---- M007 helper ----

    @staticmethod
    def _touches_aux(call: ast.Call) -> bool:
        for a in call.args:
            for sub in ast.walk(a):
                n = ""
                if isinstance(sub, ast.Name):
                    n = sub.id
                elif isinstance(sub, ast.Attribute):
                    n = sub.attr
                if "aux" in n.lower():
                    return True
        return False

    # ---- M008 ----

    def _own_exprs(self, fn_node):
        """AST nodes of this function excluding nested defs/lambdas."""
        stack = list(fn_node.body)
        while stack:
            st = stack.pop()
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                continue
            yield st
            stack.extend(ast.iter_child_nodes(st))

    def _check_yield_pins(self, fn_node) -> None:
        yields = [n.lineno for n in self._own_exprs(fn_node)
                  if isinstance(n, (ast.Yield, ast.YieldFrom))]
        if not yields or not self.device_locals:
            return
        for node in self._own_exprs(fn_node):
            if not (isinstance(node, ast.Name) and
                    isinstance(node.ctx, ast.Load)):
                continue
            bound = self.device_locals.get(node.id)
            if bound is None:
                continue
            if any(bound < y < node.lineno for y in yields):
                self._emit(
                    node, "M008",
                    f"device buffer {node.id!r} (bound at line"
                    f" {bound}) is held across a yield: the slab"
                    " stays pinned in HBM while the consumer parks"
                    " the generator — stage per iteration or release"
                    " before yielding")


# ---------------- program-level driver ----------------


def _resolve_call(index: _Index, mod: _Module, info, call: ast.Call):
    """hotpath's call resolution, reused for coverage edges."""
    fn = call.func
    if isinstance(fn, ast.Name):
        name = fn.id
        if name in mod.classes:
            return None
        local = mod.fns.get(name)
        if local is not None and local.cls is None:
            return local
        origin = mod.imports.get(name)
        if origin is not None:
            return index.resolve_from(origin)
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    recv = fn.value
    if isinstance(recv, ast.Name) and recv.id == "self" and \
            info.cls is not None:
        local = mod.fns.get(f"{info.cls}.{fn.attr}")
        if local is not None:
            return local
    if isinstance(recv, ast.Name):
        origin = mod.imports.get(recv.id)
        if origin is not None:
            tgt = index.resolve_from(f"{origin}.{fn.attr}")
            if tgt is not None:
                return tgt
            # origin may be an imported CLASS (TableBlock.from_numpy):
            # fall through to the unique-method map
    return index.unique_method(fn.attr)


def check_sources(sources, report_files=None) -> list:
    """Analyze (src, filename, modname) triples as one program;
    ``report_files`` narrows REPORTING without shrinking the coverage
    index (the hotpath rule — a charging caller outside the changed
    set must still cover its staging helper)."""
    findings: list = []
    modules: list = []
    lines_by_file: dict = {}
    device_mods: set = set()
    for src, filename, modname in sources:
        lines = src.splitlines()
        lines_by_file[filename] = lines
        if file_skipped(lines):
            continue
        try:
            tree = ast.parse(src, filename=filename)
        except SyntaxError as e:
            findings.append(Finding(
                filename, e.lineno or 0, e.offset or 0, "M000",
                "syntax-error", str(e.msg)))
            continue
        m = _Module(
            modname if modname is not None else _modname_for(filename),
            filename, tree)
        modules.append(m)
        if _device_module(lines):
            device_mods.add(m.modname)
    index = _Index(modules)

    scans: dict = {}
    ledgers: dict = {}
    deferred: list = []
    charging: set = set()
    edges: dict = {}  # (mod, qual) -> set of (caller key, traced)
    for m in modules:
        for info in m.fns.values():
            reason = _budget_ok_reason(info.node)
            led = None
            if info.cls is not None:
                led = ledgers.setdefault((m.modname, info.cls),
                                         _ClassLedger())
            scan = _FnScan(info, m, reason, m.modname in device_mods,
                           led, index)
            scan.run()
            scan.scan_statements_for_ledger()
            key = (info.modname, info.qualname)
            scans[key] = scan
            findings.extend(scan.findings)
            deferred.extend(scan.deferred)
            if scan.charging or reason is not None:
                charging.add(key)
            for call, traced in scan.calls:
                tgt = _resolve_call(index, m, info, call)
                if tgt is not None:
                    edges.setdefault(
                        (tgt.modname, tgt.qualname),
                        set()).add((key, traced))

    # discharge fixpoint for M001: a function is discharged when it
    # charges a budget itself (or is budget_ok), or when every indexed
    # call site reaching it is either under trace (XLA temporaries) or
    # inside a discharged function (allocations land in the caller's
    # charged seam)
    covered = set(charging)
    changed = True
    while changed:
        changed = False
        for key, callers in edges.items():
            if key in covered or not callers:
                continue
            if all(t or c in covered for c, t in callers):
                covered.add(key)
                changed = True

    # trace fixpoint for M004/M007: reached ONLY from trace-context
    # call sites — shapes are static by construction there, and aux is
    # a traced operand, so the retrace/re-staging rules do not apply.
    # Charging is NOT enough here: a charged seam still retraces on
    # unrounded shapes.
    trace_covered: set = set()
    changed = True
    while changed:
        changed = False
        for key, callers in edges.items():
            if key in trace_covered:
                continue
            if key in charging:
                continue  # a charging seam is a host boundary
            if callers and all(t or c in trace_covered
                               for c, t in callers):
                trace_covered.add(key)
                changed = True

    for f, fnkey, callee in deferred:
        if fnkey in trace_covered:
            continue
        if f.code == "M001" and (fnkey in covered or
                                 callee in covered):
            continue
        findings.append(f)

    # M006: stores into grow-only containers with no valve
    for (modname, cls), led in ledgers.items():
        if led.has_valve:
            continue
        for attr, node in led.stores:
            if attr in led.containers and attr not in led.removals:
                findings.append(Finding(
                    next(m.filename for m in modules
                         if m.modname == modname),
                    node.lineno, node.col_offset, "M006",
                    RULES["M006"],
                    f"device arrays accumulate in self.{attr} and"
                    f" {cls} never evicts from it (no pop/del/clear,"
                    " no evict/budget valve): a grow-only device"
                    " container pins HBM for the process lifetime"))

    kept: list = []
    for filename, lines in lines_by_file.items():
        if report_files is not None and filename not in report_files:
            continue
        here = [f for f in findings if f.file == filename]
        kept.extend(filter_suppressed(here, lines, RULES))
    return sorted(kept, key=lambda f: (f.file, f.line, f.col, f.code))


def check_source(src: str, filename: str = "<string>",
                 modname: "str | None" = None) -> list:
    """Analyze one source text (tests)."""
    return check_sources([(src, filename, modname)])


def runtime_scope(files) -> list:
    """Restrict collected files to the runtime packages (paths outside
    a ydb_tpu tree — fixtures — pass through untouched)."""
    kept = []
    for f in files:
        parts = str(f).split("/")
        if "ydb_tpu" in parts:
            i = len(parts) - 1 - parts[::-1].index("ydb_tpu")
            if i + 1 >= len(parts) or \
                    parts[i + 1] not in RUNTIME_PACKAGES:
                continue
        kept.append(f)
    return kept


def check_paths(paths, report_files=None) -> list:
    sources = []
    for f in runtime_scope(paths):
        sources.append((f.read_text(encoding="utf-8"), str(f), None))
    return check_sources(sources, report_files=report_files)


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    files = collect_files(paths)
    report = None
    if changed:
        report = {str(f) for f in collect_files(paths, changed=True)}
    findings = check_paths(files, report_files=report)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
