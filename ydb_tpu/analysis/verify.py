"""SSA program verifier: a typed static checker run before lowering.

The reference validates every serialized scan program before executing
it (TProgramContainer::Init, ydb/core/tx/program/program.cpp:553;
column resolution + kernel registry checks in
formats/arrow/program.h). Our port lowers step lists straight into a
JAX trace, where a malformed program surfaces as an opaque XLA/trace
error deep inside ``ssa/compiler.py``. This verifier walks the step
list with a typed symbol table — exactly the scope the trace-time
``env`` dict will hold — and emits structured ``Diagnostic`` records
(step index, expression path, error code, fix hint) instead.

It is the mandatory precondition of ``ssa.compiler.compile_program``
and of every scan/transform entry in the executors: no program reaches
the kernel layer unverified ("a typed plan checker in front of the
tensor compiler keeps the kernel layer simple" — the Tensor Query
Processor argument, PAPERS.md).

Beyond types, the verifier infers *nullability* through the program
(the compiler uses the result to type its output schema), and rejects
ranking-window partition/order keys that may be NULL: the window
lowering sorts raw physical values, so a NULL key would rank by the
stale bits under the null — silently wrong results rather than an
error (ADVICE round 5, ssa/compiler.py:321).

Division/modulo results are typed nullable unless the divisor is a
provably nonzero literal (a zero divisor NULLs the row at runtime),
so V005 also catches window keys derived from divisions. The scan
executor types its RESULT schema from the original program's analysis
— keyed AVG over a non-null input stays non-null even though the
two-phase rewrite computes it via a division fixup.

Error codes (see ydb_tpu/analysis/README.md):
  V001 unknown-column          expression references a column not in scope
  V002 filter-not-boolean      FilterStep predicate is not BOOL
  V003 agg-input-mismatch      AggSpec input column/dtype unusable
  V004 dead-projection         ProjectStep names a column not in scope
  V005 window-key-nullable     window partition/order key may be NULL
  V006 group-capacity          GroupByStep.max_groups is not positive
  V007 expr-type               expression cannot be typed (bad operands)
  V008 sort-desc-arity         descending flags do not match sort keys
  V009 unknown-window-function window function is not rank-family
  V010 duplicate-output-column projection/group-by emits one output
                               name twice (later write would silently
                               shadow the earlier column)
"""

from __future__ import annotations

import dataclasses

from ydb_tpu import dtypes
from ydb_tpu.analysis.diagnostics import Diagnostic, VerificationError
from ydb_tpu.ssa.ops import Agg, Op
from ydb_tpu.ssa.program import (
    AggSpec,
    AssignStep,
    Call,
    Col,
    Const,
    DictMap,
    DictPredicate,
    FilterStep,
    GroupByStep,
    Program,
    ProjectStep,
    SortStep,
    UdfCall,
    WindowStep,
    agg_result_type,
    infer_type,
)

_EMPTY_SCHEMA = dtypes.Schema(())

#: Aggregates whose input must be orderable/summable numerics — a STRING
#: input (physically a dictionary id) would silently aggregate ids.
_NUMERIC_AGGS = (Agg.SUM, Agg.AVG, Agg.VAR_SAMP, Agg.STDDEV_SAMP)

_WINDOW_FUNCS = ("rank", "dense_rank", "row_number")

#: Ops whose runtime validity collapses to "all args valid" — plus the
#: documented zero-divisor approximation for DIV/MOD/DIV_INT.
_NEVER_NULL_OPS = (Op.IS_NULL, Op.IS_NOT_NULL)


@dataclasses.dataclass
class ProgramAnalysis:
    """Verification result: findings plus the derived output scope."""

    diagnostics: list
    out_names: tuple
    out_types: dict
    out_nullable: dict

    @property
    def errors(self) -> list:
        return [d for d in self.diagnostics if d.severity == "error"]

    def raise_if_errors(self) -> "ProgramAnalysis":
        if self.errors:
            raise VerificationError(self.errors)
        return self


def infer_nullable(expr, nullable: dict) -> bool:
    """May ``expr`` evaluate to NULL, given per-column nullability?

    Mirrors the validity arithmetic of ssa/compiler lowering (Kleene
    AND of argument validities for most ops), with the zero-divisor
    approximation documented in the module docstring. Unknown columns
    count as non-null — the scope walk reports those separately.
    """
    if isinstance(expr, Col):
        return nullable.get(expr.name, False)
    if isinstance(expr, Const):
        return expr.value is None
    if isinstance(expr, (DictPredicate, DictMap)):
        return nullable.get(expr.column, False)
    if isinstance(expr, UdfCall):
        return any(infer_nullable(a, nullable) for a in expr.args)
    if isinstance(expr, Call):
        op = expr.op
        if op in _NEVER_NULL_OPS:
            return False
        if op is Op.NULLIF:  # produces NULL on equality by design
            return True
        if op is Op.COALESCE:
            return all(infer_nullable(a, nullable) for a in expr.args)
        if op in (Op.DIV, Op.MOD, Op.DIV_INT):
            # a zero divisor NULLs the row at runtime regardless of
            # operand nullability; only a provably nonzero literal
            # divisor is safe
            div = expr.args[1] if len(expr.args) > 1 else None
            if not (isinstance(div, Const) and div.value is not None
                    and div.value != 0):
                return True
        return any(infer_nullable(a, nullable) for a in expr.args)
    return True  # unknown node kind: assume the worst


class _Verifier:
    def __init__(self, schema: dtypes.Schema):
        self.diags: list = []
        self.types: dict = {f.name: f.type for f in schema.fields}
        self.nullable: dict = {f.name: f.nullable for f in schema.fields}
        self.names: list = list(schema.names)

    def diag(self, code, name, message, step=None, path="", hint="",
             severity="error"):
        self.diags.append(Diagnostic(
            code=code, name=name, message=message, step=step, path=path,
            hint=hint, severity=severity))

    # ---- expressions ----

    def expr(self, e, step: int, path: str):
        """Return (LogicalType | None, nullable); None = poisoned (a
        diagnostic was already emitted for this subtree)."""
        if isinstance(e, Col):
            if e.name not in self.types:
                self.diag(
                    "V001", "unknown-column",
                    f"column {e.name!r} is not in scope"
                    f" (live columns: {sorted(self.types)})",
                    step, path,
                    hint="assign it earlier or fix the column name")
                return None, False
            return self.types[e.name], self.nullable[e.name]
        if isinstance(e, Const):
            return e.type, e.value is None
        if isinstance(e, (DictPredicate, DictMap)):
            if e.column not in self.types:
                self.diag(
                    "V001", "unknown-column",
                    f"column {e.column!r} is not in scope", step, path)
                return None, False
            if not self.types[e.column].is_string:
                self.diag(
                    "V007", "expr-type",
                    f"dictionary {type(e).__name__} on non-string column"
                    f" {e.column!r} ({self.types[e.column]})", step, path)
                return None, False
            null = self.nullable[e.column]
            if isinstance(e, DictPredicate):
                return dtypes.BOOL, null
            return (dtypes.INT32 if e.kind in ("xrank", "strlen")
                    else dtypes.STRING), null
        if isinstance(e, UdfCall):
            null = False
            for j, a in enumerate(e.args):
                _, n = self.expr(a, step, f"{path}.args[{j}]")
                null = null or n
            return e.out_type, null
        if isinstance(e, Call):
            return self._call(e, step, path)
        self.diag("V007", "expr-type",
                  f"unknown expression node {type(e).__name__}", step, path)
        return None, False

    def _call(self, e: Call, step: int, path: str):
        arg_ts = []
        for j, a in enumerate(e.args):
            t, _ = self.expr(a, step, f"{path}.args[{j}]")
            arg_ts.append(t)
        null = infer_nullable(e, self.nullable)
        if any(t is None for t in arg_ts):
            return None, null  # sub-diagnostic already emitted
        op = e.op
        if op in (Op.HOUR, Op.MINUTE, Op.SECOND) and (
                not arg_ts or arg_ts[0].kind != dtypes.Kind.TIMESTAMP):
            self.diag(
                "V007", "expr-type",
                f"{op.name} needs a timestamp operand, got"
                f" {arg_ts[0] if arg_ts else 'nothing'}", step, path,
                hint="CAST or use a timestamp column")
            return None, null
        if op is Op.IN_SET and not all(
                isinstance(a, Const) for a in e.args[1:]):
            self.diag("V007", "expr-type",
                      "IN_SET members must be constants", step, path)
            return None, null
        try:
            t = infer_type(e, _EMPTY_SCHEMA, self.types)
        except (TypeError, KeyError, IndexError, NotImplementedError) as ex:
            self.diag("V007", "expr-type",
                      f"cannot type {op.name} call: {ex}", step, path)
            return None, null
        return t, null

    # ---- steps ----

    def step(self, i: int, s) -> None:
        if isinstance(s, AssignStep):
            t, null = self.expr(s.expr, i, f"steps[{i}].expr")
            self.types[s.name] = t if t is not None else dtypes.INT64
            self.nullable[s.name] = null
            if s.name not in self.names:
                self.names.append(s.name)
        elif isinstance(s, FilterStep):
            t, _ = self.expr(s.expr, i, f"steps[{i}].expr")
            if t is not None and t.kind != dtypes.Kind.BOOL:
                self.diag(
                    "V002", "filter-not-boolean",
                    f"filter predicate must be BOOL, got {t}", i,
                    f"steps[{i}].expr",
                    hint="compare the expression instead of filtering"
                         " on its raw value")
        elif isinstance(s, GroupByStep):
            self._group_by(i, s)
        elif isinstance(s, ProjectStep):
            kept: list = []
            for j, n in enumerate(s.names):
                if n in kept:
                    self.diag(
                        "V010", "duplicate-output-column",
                        f"projection lists column {n!r} twice — the"
                        " output would carry one physical column under"
                        " a repeated name", i, f"steps[{i}].names[{j}]",
                        hint="drop the repeated name or alias it via"
                             " an assign first")
                    continue
                if n not in self.types:
                    self.diag(
                        "V004", "dead-projection",
                        f"projection names column {n!r} which is not in"
                        f" scope (live columns: {sorted(self.types)})", i,
                        f"steps[{i}].names[{j}]",
                        hint="assign the column before projecting it")
                    self.types[n] = dtypes.INT64
                    self.nullable[n] = False
                kept.append(n)
            self.names = kept
            self.types = {n: self.types[n] for n in kept}
            self.nullable = {n: self.nullable[n] for n in kept}
        elif isinstance(s, SortStep):
            for j, k in enumerate(s.keys):
                self.expr(Col(k), i, f"steps[{i}].keys[{j}]")
            if s.descending and len(s.descending) != len(s.keys):
                self.diag(
                    "V008", "sort-desc-arity",
                    f"{len(s.descending)} descending flags for"
                    f" {len(s.keys)} sort keys", i, f"steps[{i}]")
        elif isinstance(s, WindowStep):
            self._window(i, s)
        else:
            self.diag("V007", "expr-type",
                      f"unknown step kind {type(s).__name__}", i,
                      f"steps[{i}]")

    def _group_by(self, i: int, s: GroupByStep) -> None:
        if s.max_groups is not None and s.max_groups <= 0:
            self.diag(
                "V006", "group-capacity",
                f"max_groups must be positive, got {s.max_groups}", i,
                f"steps[{i}].max_groups",
                hint="omit max_groups to size groups to the block")
        out_types: dict = {}
        out_nullable: dict = {}
        seen: set = set()
        for j, k in enumerate(s.keys):
            if k in seen:
                self.diag(
                    "V010", "duplicate-output-column",
                    f"group-by key {k!r} appears twice", i,
                    f"steps[{i}].keys[{j}]",
                    hint="drop the repeated key")
            seen.add(k)
            t, null = self.expr(Col(k), i, f"steps[{i}].keys[{j}]")
            out_types[k] = t if t is not None else dtypes.INT64
            out_nullable[k] = null
        keyed = bool(s.keys)
        for j, spec in enumerate(s.aggs):
            path = f"steps[{i}].aggs[{j}]"
            if spec.out_name in seen:
                self.diag(
                    "V010", "duplicate-output-column",
                    f"aggregate output {spec.out_name!r} collides with"
                    " an earlier key or aggregate — the later column"
                    " would silently shadow the earlier one", i, path,
                    hint="rename the aggregate output")
            seen.add(spec.out_name)
            out_types[spec.out_name] = dtypes.INT64
            out_nullable[spec.out_name] = False
            if spec.func is Agg.COUNT_ALL:
                continue
            if spec.column is None:
                self.diag(
                    "V003", "agg-input-mismatch",
                    f"{spec.func.name} needs an input column"
                    " (only COUNT_ALL takes none)", i, path)
                continue
            t, null = self.expr(Col(spec.column), i, f"{path}.column")
            if t is None:
                continue
            if spec.func in _NUMERIC_AGGS and t.is_string:
                self.diag(
                    "V003", "agg-input-mismatch",
                    f"{spec.func.name} over string column"
                    f" {spec.column!r} would aggregate dictionary ids,"
                    " not values", i, path,
                    hint="use MIN/MAX/COUNT for strings")
                continue
            try:
                out_types[spec.out_name] = agg_result_type(
                    spec, _EMPTY_SCHEMA, self.types)
            except (TypeError, KeyError, NotImplementedError) as ex:
                self.diag("V003", "agg-input-mismatch",
                          f"cannot type {spec.func.name}: {ex}", i, path)
                continue
            if spec.func in (Agg.COUNT, Agg.COUNT_ALL):
                out_nullable[spec.out_name] = False
            elif spec.func in (Agg.VAR_SAMP, Agg.STDDEV_SAMP):
                # NULL for single-row groups (n-1 denominator)
                out_nullable[spec.out_name] = True
            else:
                # a keyed group exists because >= 1 live row carries the
                # key, so a non-null input forces a non-null state; a
                # keyless aggregate over zero rows is NULL (except COUNT)
                out_nullable[spec.out_name] = null or not keyed
        self.names = list(s.keys) + [a.out_name for a in s.aggs]
        self.types = out_types
        self.nullable = out_nullable

    def _window(self, i: int, s: WindowStep) -> None:
        if s.func not in _WINDOW_FUNCS:
            self.diag(
                "V009", "unknown-window-function",
                f"window function {s.func!r} is not supported"
                f" (supported: {', '.join(_WINDOW_FUNCS)})", i,
                f"steps[{i}].func")
        if s.descending and len(s.descending) != len(s.order_keys):
            self.diag(
                "V008", "sort-desc-arity",
                f"{len(s.descending)} descending flags for"
                f" {len(s.order_keys)} window order keys", i,
                f"steps[{i}]")
        for role, keys in (("partition", s.partition),
                           ("order", s.order_keys)):
            for j, k in enumerate(keys):
                path = f"steps[{i}].{role}[{j}]"
                t, null = self.expr(Col(k), i, path)
                if t is None:
                    continue
                if null:
                    self.diag(
                        "V005", "window-key-nullable",
                        f"window {role} key {k!r} may be NULL; the"
                        " ranking lowering sorts raw physical values,"
                        " so NULL keys would rank by stale bits"
                        " instead of grouping as NULL", i, path,
                        hint="COALESCE the key or filter NULLs ahead"
                             " of the window")
        self.types[s.out_name] = dtypes.INT64
        self.nullable[s.out_name] = False
        if s.out_name not in self.names:
            self.names.append(s.out_name)


def analyze_program(program: Program,
                    schema: dtypes.Schema) -> ProgramAnalysis:
    """Walk the program statically; never raises on malformed input —
    every defect becomes a ``Diagnostic``."""
    v = _Verifier(schema)
    for i, s in enumerate(program.steps):
        v.step(i, s)
    return ProgramAnalysis(
        diagnostics=v.diags,
        out_names=tuple(v.names),
        out_types=dict(v.types),
        out_nullable=dict(v.nullable),
    )


def verify_program(program: Program, schema: dtypes.Schema) -> list:
    """Diagnostics only (empty list = program is well-formed)."""
    return analyze_program(program, schema).diagnostics


def check_program(program: Program,
                  schema: dtypes.Schema) -> ProgramAnalysis:
    """Verify and raise ``VerificationError`` (a PlanError) on any
    error-severity finding; returns the analysis otherwise so callers
    can reuse the inferred output nullability."""
    return analyze_program(program, schema).raise_if_errors()
