"""Shared suppression machinery for the AST linters (lint + concurrency).

One syntax across every rule family:

  * ``# ydb-lint: disable=L001`` (or the rule name; comma-separate
    several; ``all`` kills every rule) on the offending line, or alone
    on the line above it
  * ``# ydb-lint: skip-file`` within the first ten lines skips the file

Both ``analysis/lint.py`` (L-rules) and ``analysis/concurrency.py``
(C-rules) filter their findings through :func:`filter_suppressed`
with their own rule tables, so a suppression names exactly the rule it
silences regardless of which checker emitted it.
"""

from __future__ import annotations

import re

_SUPPRESS_RE = re.compile(r"#\s*ydb-lint:\s*disable=([\w\-,]+)")
_SKIP_FILE_RE = re.compile(r"#\s*ydb-lint:\s*skip-file")


def suppressed_codes(line: str, rules: dict, name_to_code: dict) -> set:
    """Rule codes disabled by the trailing comment on ``line``."""
    m = _SUPPRESS_RE.search(line)
    if not m:
        return set()
    out: set = set()
    for tok in m.group(1).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.lower() == "all":
            out.update(rules)
        elif tok.upper() in rules:
            out.add(tok.upper())
        elif tok.lower() in name_to_code:
            out.add(name_to_code[tok.lower()])
    return out


def file_skipped(lines: list) -> bool:
    """True when a skip-file pragma sits in the first ten lines."""
    return any(_SKIP_FILE_RE.search(ln) for ln in lines[:10])


def filter_suppressed(findings: list, lines: list, rules: dict) -> list:
    """Drop findings whose line (or the comment line above) carries a
    matching disable pragma. Findings must expose .line and .code and
    sort stably by position."""
    name_to_code = {v: k for k, v in rules.items()}
    kept = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.code)):
        here = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        above = lines[f.line - 2] if 1 < f.line <= len(lines) + 1 else ""
        sup = suppressed_codes(here, rules, name_to_code)
        if above.strip().startswith("#"):
            sup |= suppressed_codes(above, rules, name_to_code)
        if f.code not in sup:
            kept.append(f)
    return kept
