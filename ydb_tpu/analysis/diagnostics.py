"""Structured plan-time diagnostics.

The reference rejects malformed scan programs at parse time with typed
statuses (TProgramContainer::Init, ydb/core/tx/program/program.cpp:553);
trace-time failure is too late for a production front end — the user
gets an opaque XLA shape error instead of "step 3 filters on a non-bool
expression". This module is the shared vocabulary: a ``Diagnostic`` is
one finding (error code, step index, expression path, message, fix
hint), and ``VerificationError`` carries a batch of them as a
``PlanError`` so every existing SQL-surface error handler keeps working.

``PlanError`` itself lives here (re-exported by ``ydb_tpu.sql.planner``
for compatibility) so the analysis layer does not depend on the SQL
layer. This module has no ydb_tpu imports at all — it sits below
everything.
"""

from __future__ import annotations

import dataclasses


class PlanError(Exception):
    """A statement that can never execute: planning/verification reject.

    Historically defined in ydb_tpu.sql.planner; hoisted here so the
    static analysis layer can raise it without importing the SQL
    planner. ``from ydb_tpu.sql.planner import PlanError`` still works.
    """


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One static-analysis finding against a program or source tree.

    ``step`` is the index into ``Program.steps`` (None for
    program-level findings); ``path`` locates the offending expression
    within the step (e.g. ``steps[2].expr.args[1]``).
    """

    code: str            # stable machine code, e.g. "V001"
    name: str            # kebab-case rule name, e.g. "unknown-column"
    message: str
    step: int | None = None
    path: str = ""
    hint: str = ""
    severity: str = "error"  # error | warning

    def render(self) -> str:
        loc = f"step {self.step}" if self.step is not None else "program"
        if self.path:
            loc += f" ({self.path})"
        out = f"{self.code} {self.name} @ {loc}: {self.message}"
        if self.hint:
            out += f" [hint: {self.hint}]"
        return out

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class VerificationError(PlanError):
    """A program failed static verification. Carries every error-level
    ``Diagnostic`` so callers (and tests) can assert on step index and
    code rather than parsing the message."""

    def __init__(self, diagnostics):
        self.diagnostics = tuple(diagnostics)
        super().__init__(
            "program verification failed:\n"
            + "\n".join("  " + d.render() for d in self.diagnostics)
        )
