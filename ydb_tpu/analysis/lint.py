"""Trace-safety lint: AST checks for jit-hazard patterns.

JAX traces Python once and replays the compiled computation; code that
is harmless in eager Python silently corrupts or de-optimizes a traced
function. The classic hazards (each is a rule below):

  L001 host-sync-in-trace      ``.item()`` / ``float(jnp...)`` /
                               ``np.asarray(jnp...)`` inside a function
                               that builds traced values: forces a
                               device sync per call, or fails under jit
  L002 python-branch-on-traced ``if``/``while`` on a ``jnp`` boolean:
                               trace-time constant folding or a
                               ConcretizationTypeError, never data-
                               dependent control flow
  L003 wall-clock-in-trace     ``time.time()`` etc. inside traced code
                               bakes the clock of the FIRST trace into
                               the compiled program
  L004 unseeded-randomness     legacy ``np.random.*`` global RNG /
                               argless ``default_rng()`` / stdlib
                               ``random.*``: irreproducible plans and
                               divergent retraces
  L005 mutable-default-arg     ``def f(x=[])``: one shared list across
                               every call — a classic cache poisoner
  L006 set-iteration-order     iterating a set literal / ``set(...)``
                               feeds hash order into trace order; two
                               processes compile different programs
  L007 block-in-trace          ``jax.block_until_ready(...)`` / the
                               ``.block_until_ready()`` method inside a
                               trace-suspect function: under jit it is
                               a no-op on tracers at best, and in the
                               fused plan-lowering paths it would split
                               the single-dispatch computation back
                               into synchronized fragments

"Trace-suspect" means the function's own body calls into ``jnp.*`` /
``jax.lax.*`` / ``jax.nn.*`` — the practical signature of code that
runs under trace in this repo (lowering closures, kernels). L004-L006
apply everywhere.

Suppression: append ``# ydb-lint: disable=L001`` (or the rule name;
comma-separate several; ``all`` kills every rule) to the offending
line, or place it alone on the line above. ``# ydb-lint: skip-file``
within the first ten lines skips the file. (Shared machinery:
``analysis/suppress.py`` — the concurrency checker's C-rules use the
same syntax.)

Run: ``python -m ydb_tpu.analysis.lint [path ...] [--json] [--changed]``
(default path: the ydb_tpu package). Exit code 1 on any unsuppressed
finding; ``--json`` emits a machine-readable report; ``--changed``
scopes the scan to git-touched files (pre-commit fast path, shared
with the concurrency CLI via ``analysis/paths.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import sys

from ydb_tpu.analysis.paths import collect_files, parse_cli
from ydb_tpu.analysis.suppress import file_skipped, filter_suppressed

RULES = {
    "L001": "host-sync-in-trace",
    "L002": "python-branch-on-traced",
    "L003": "wall-clock-in-trace",
    "L004": "unseeded-randomness",
    "L005": "mutable-default-arg",
    "L006": "set-iteration-order",
    "L007": "block-in-trace",
}
_TRACE_ROOTS = ("jnp.", "jax.lax.", "jax.nn.", "jax.scipy.")
_CLOCK_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "time.monotonic_ns",
}
_STDLIB_RANDOM = {
    "random.random", "random.randint", "random.randrange",
    "random.choice", "random.choices", "random.shuffle",
    "random.sample", "random.uniform", "random.gauss",
}
#: host materializers: a jnp call wrapped in one of these is an
#: EXPLICIT device->host transfer, not an accidental trace hazard
_MATERIALIZERS = {"int", "float", "bool", "len", "str", "repr"}
_MATERIALIZER_ROOTS = {"np.asarray", "np.array", "jax.device_get"}
#: static METADATA predicates: they return plain Python values at trace
#: time (dtype algebra, shape queries) — branching on them is fine
_STATIC_JNP = {
    "jnp.issubdtype", "jnp.iinfo", "jnp.finfo", "jnp.result_type",
    "jnp.dtype", "jnp.shape", "jnp.ndim",
}

@dataclasses.dataclass(frozen=True)
class Finding:
    file: str
    line: int
    col: int
    code: str
    name: str
    message: str

    def render(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: "
                f"{self.code} [{self.name}] {self.message}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _dotted(node) -> str:
    """Dotted name of an attribute/name chain ('' if not a plain one)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_trace_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    root = _dotted(node.func)
    if root in _STATIC_JNP:
        return False
    return any(root.startswith(p) for p in _TRACE_ROOTS)


def _has_trace_call(node, *, through_materializers: bool) -> bool:
    """Does the subtree contain a jnp/jax.lax call? With
    ``through_materializers`` False, subtrees under an explicit host
    materializer (int(...), np.asarray(...)) do not count."""
    if _is_trace_call(node):
        return True
    if not through_materializers and isinstance(node, ast.Call):
        fn = node.func
        if (isinstance(fn, ast.Name) and fn.id in _MATERIALIZERS) or \
                _dotted(fn) in _MATERIALIZER_ROOTS:
            return False
    return any(
        _has_trace_call(c, through_materializers=through_materializers)
        for c in ast.iter_child_nodes(node))


class _FunctionChecker(ast.NodeVisitor):
    """Per-function trace-hazard rules (L001-L003). Nested functions are
    handled by their own checker instance (a nested def is its own
    trace unit — lowering closures)."""

    def __init__(self, out: list, filename: str, fn: ast.AST):
        self.out = out
        self.filename = filename
        self.fn = fn

    def run(self):
        for stmt in self.fn.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):  # do not descend: own unit
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def _emit(self, node, code, message):
        self.out.append(Finding(
            self.filename, node.lineno, node.col_offset, code,
            RULES[code], message))

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                and not node.args:
            self._emit(node, "L001",
                       ".item() forces a device sync inside traced code"
                       " (and fails under jit); keep values on device or"
                       " materialize once outside the trace")
        root = _dotted(fn)
        if root == "jax.block_until_ready" or (
                isinstance(fn, ast.Attribute)
                and fn.attr == "block_until_ready"):
            self._emit(node, "L007",
                       "block_until_ready inside traced code is a no-op"
                       " on tracers and a fusion barrier in plan-lowering"
                       " paths; sync once outside the trace (after the"
                       " fused dispatch) instead")
        if (isinstance(fn, ast.Name) and fn.id in ("int", "float", "bool")
                or root in _MATERIALIZER_ROOTS):
            if any(_has_trace_call(a, through_materializers=True)
                   for a in node.args):
                what = root or fn.id
                self._emit(node, "L001",
                           f"{what}(...) over a jnp expression"
                           " materializes a traced value; hoist the"
                           " host conversion out of the traced function")
        if root in _CLOCK_CALLS:
            self._emit(node, "L003",
                       f"{root}() inside traced code bakes the clock of"
                       " the first trace into the compiled program; pass"
                       " timestamps in as arguments")
        self.generic_visit(node)

    def _check_branch(self, node, kind: str):
        if _has_trace_call(node.test, through_materializers=False):
            self._emit(node, "L002",
                       f"Python `{kind}` on a jnp expression: under jit"
                       " this folds at trace time or raises; use"
                       " jnp.where / lax.cond / lax.while_loop")

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)


class _ModuleChecker(ast.NodeVisitor):
    """Whole-file rules (L004-L006) + dispatch of trace-suspect
    functions to _FunctionChecker."""

    def __init__(self, filename: str):
        self.filename = filename
        self.out: list = []

    def _emit(self, node, code, message):
        self.out.append(Finding(
            self.filename, node.lineno, node.col_offset, code,
            RULES[code], message))

    # ---- L005: mutable default arguments ----

    def _check_defaults(self, node):
        args = node.args
        for d in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            bad = None
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                bad = type(d).__name__.lower()
            elif isinstance(d, ast.Call) and isinstance(d.func, ast.Name) \
                    and d.func.id in ("list", "dict", "set", "bytearray"):
                bad = f"{d.func.id}()"
            if bad is not None:
                self._emit(d, "L005",
                           f"mutable default argument {bad} is shared"
                           " across calls; default to None and build"
                           " inside the function")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        if _has_trace_call(node, through_materializers=True):
            _FunctionChecker(self.out, self.filename, node).run()
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    # ---- L004: nondeterministic randomness ----

    def visit_Call(self, node):
        root = _dotted(node.func)
        if root.startswith("np.random.") or \
                root.startswith("numpy.random."):
            tail = root.split("random.", 1)[1]
            if tail == "default_rng":
                if not node.args and not node.keywords:
                    self._emit(node, "L004",
                               "default_rng() without a seed is"
                               " irreproducible; pass an explicit seed")
            elif tail not in ("Generator", "SeedSequence"):
                self._emit(node, "L004",
                           f"legacy global RNG np.random.{tail}(...) is"
                           " process-global state; use a seeded"
                           " np.random.default_rng(seed)")
        elif root in _STDLIB_RANDOM:
            self._emit(node, "L004",
                       f"{root}() uses the process-global stdlib RNG;"
                       " use a seeded random.Random(seed) instance")
        self.generic_visit(node)

    # ---- L006: set iteration order ----

    def _check_iter(self, node, it):
        bad = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id in ("set", "frozenset"))
        if bad:
            self._emit(node, "L006",
                       "iterating a set: hash order is process-dependent"
                       " and would feed nondeterminism into trace/plan"
                       " order; iterate sorted(...) or a tuple")

    def visit_For(self, node):
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def visit_comprehension_generators(self, node):
        for gen in node.generators:
            self._check_iter(gen.iter, gen.iter)

    def visit_ListComp(self, node):
        self.visit_comprehension_generators(node)
        self.generic_visit(node)

    visit_SetComp = visit_ListComp
    visit_DictComp = visit_ListComp
    visit_GeneratorExp = visit_ListComp


def lint_source(src: str, filename: str = "<string>") -> list:
    """Lint one source text; returns unsuppressed findings sorted by
    position."""
    lines = src.splitlines()
    if file_skipped(lines):
        return []
    try:
        tree = ast.parse(src, filename=filename)
    except SyntaxError as e:
        return [Finding(filename, e.lineno or 0, e.offset or 0, "L000",
                        "syntax-error", str(e.msg))]
    checker = _ModuleChecker(filename)
    checker.visit(tree)
    return filter_suppressed(checker.out, lines, RULES)


def lint_paths(paths, changed: bool = False) -> list:
    findings: list = []
    for f in collect_files(paths, changed=changed):
        findings.extend(
            lint_source(f.read_text(encoding="utf-8"), str(f)))
    return findings


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    findings = lint_paths(paths, changed=changed)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
