"""Runtime thread-sanitizer: lockset (Eraser-style) race detection for
the runtime's designated shared structures.

The static half (``analysis/concurrency.py``) proves lock DISCIPLINE;
this module catches what static analysis cannot see — dynamic call
paths, monkeypatched layers, test harness threads. With
``YDB_TPU_TSAN=1`` the lock-bearing classes construct their locks
through :func:`make_lock` / :func:`make_condition` (which track the
per-thread held-lock set) and wrap their shared containers in
:func:`share` proxies that run the Eraser lockset algorithm per access:

  * while a single thread owns a structure, anything goes (init phase)
  * once a second thread touches it, the candidate lockset is the
    intersection of the locks held at every access
  * a WRITE with an empty candidate lockset raises :class:`RaceError`
    naming the structure, the operation, and the threads involved

Instrumented structures (wired in their owning modules): the conveyor
task heap, the scan-executor cache and device block cache, the probe
registry, counter groups, and the interconnect session map. When the
env flag is off every factory returns the plain primitive — zero
overhead on the hot path.

The stress suite (``tests/test_tsan.py``) hammers these structures from
seeded thread pools so tier-1 runs double as a race detector; its
self-test proves the proxy flags a deliberately racy class.
"""

from __future__ import annotations

import os
import threading


def enabled() -> bool:
    """YDB_TPU_TSAN truthy, or force-activated by a test."""
    if _FORCE is not None:
        return _FORCE
    return os.environ.get("YDB_TPU_TSAN", "0") not in ("0", "", "off")


_FORCE: "bool | None" = None


class RaceError(AssertionError):
    """Conflicting unsynchronized access to a shared structure."""


# ---- per-thread held-lock set ----

_HELD = threading.local()


def _held_counts() -> dict:
    counts = getattr(_HELD, "counts", None)
    if counts is None:
        counts = _HELD.counts = {}
    return counts


def held_locks() -> frozenset:
    """Names of tracked locks the calling thread currently holds."""
    return frozenset(k for k, v in _held_counts().items() if v > 0)


class TrackedLock:
    """threading.Lock wrapper feeding the held-lock set. Also works as
    the lock of a ``threading.Condition`` (wait/notify release and
    re-acquire through acquire/release, so tracking stays exact)."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str = "lock"):
        self.name = name
        self._inner = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            counts = _held_counts()
            counts[self.name] = counts.get(self.name, 0) + 1
        return ok

    def release(self) -> None:
        counts = _held_counts()
        n = counts.get(self.name, 0)
        if n <= 1:
            counts.pop(self.name, None)
        else:
            counts[self.name] = n - 1
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedRLock(TrackedLock):
    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no .locked before 3.12
        return bool(_held_counts().get(self.name, 0))

    # Condition's full-release protocol: an RLock acquired N deep must
    # release ALL levels across a wait() — delegate to the inner
    # RLock's implementation while zeroing/restoring the held count, so
    # tracking stays exact through nested with-blocks
    def _release_save(self):
        counts = _held_counts()
        depth = counts.pop(self.name, 0)
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, saved):
        state, depth = saved
        self._inner._acquire_restore(state)
        if depth:
            _held_counts()[self.name] = depth

    def _is_owned(self):
        return self._inner._is_owned()


def make_lock(name: str):
    """A lock for a designated shared structure: tracked under TSAN,
    a plain threading.Lock otherwise (decided at construction)."""
    return TrackedLock(name) if enabled() else threading.Lock()


def make_rlock(name: str):
    return TrackedRLock(name) if enabled() else threading.RLock()


def make_condition(name: str):
    """Condition over a TRACKED RLock: a bare threading.Condition() is
    RLock-backed, so the sanitized variant must match — a re-entered
    ``with self._cv:`` must not deadlock only under TSAN."""
    return threading.Condition(TrackedRLock(name)) if enabled() \
        else threading.Condition()


# ---- Eraser lockset state ----

class _SharedState:
    __slots__ = ("name", "owner", "lockset", "threads", "write_seen")

    def __init__(self, name: str):
        self.name = name
        self.owner = None       # exclusive-phase thread id
        self.lockset = None     # None until a second thread appears
        self.threads: set = set()
        self.write_seen = False


_STATES: dict = {}
_meta_lock = threading.Lock()


def _state_for(name: str) -> _SharedState:
    with _meta_lock:
        st = _STATES.get(name)
        if st is None:
            st = _STATES[name] = _SharedState(name)
        return st


def reset_states() -> None:
    """Forget all lockset state (test isolation between stress runs).

    States reset IN PLACE, never dropped: long-lived proxies (the
    probe registry, the process-wide conveyor's heap token) hold direct
    references to their _SharedState, and replacing dict entries would
    split identity — the stale object keeps accumulating while fresh
    lookups see an empty one. In-place reset restores the exclusive
    init phase for every structure, old or new."""
    with _meta_lock:
        for st in _STATES.values():
            st.owner = None
            st.lockset = None
            st.threads = set()
            st.write_seen = False


def _record(st: _SharedState, op: str, write: bool) -> None:
    if not enabled():
        return  # always-on proxies (module registries) idle cheaply
    tid = threading.get_ident()
    held = held_locks()
    with _meta_lock:
        st.threads.add(tid)
        if st.owner is None:
            st.owner = tid
        if st.owner == tid and st.lockset is None:
            # exclusive phase: single-threaded init is always fine
            return
        if st.lockset is None:
            # second thread: candidate lockset starts from ITS locks;
            # writes before this point were unobserved init
            st.lockset = held
            st.write_seen = write
        else:
            st.lockset = st.lockset & held
            st.write_seen = st.write_seen or write
        if st.write_seen and not st.lockset:
            threads = sorted(st.threads)
            raise RaceError(
                f"unsynchronized access to {st.name}: {op} "
                f"({'write' if write else 'read'}) on thread {tid} "
                f"with locks {sorted(held) or '{}'} — candidate "
                f"lockset is empty across threads {threads}; a write "
                "is involved, so two of these accesses can interleave "
                "mid-operation. Guard every access with one lock "
                "(see analysis/README.md, C001)")


#: container reads worth recording (method names)
_READS = {
    "get", "items", "keys", "values", "copy", "index", "count",
}
#: container mutations
_WRITES = {
    "setdefault", "pop", "popitem", "update", "clear", "move_to_end",
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "add", "sort", "reverse",
}


class ShareProxy:
    """Access-checking wrapper around a dict/list/set-like structure.

    Pure delegation: the wrapped object stays the single source of
    truth; the proxy only records (thread, locks-held) per access and
    runs the lockset check. Not a subclass — C-level bypasses (heapq)
    need explicit :func:`note` instrumentation instead.
    """

    __slots__ = ("_obj", "_st")

    def __init__(self, obj, state: _SharedState):
        object.__setattr__(self, "_obj", obj)
        object.__setattr__(self, "_st", state)

    # -- attribute-routed container methods --

    def __getattr__(self, name):
        attr = getattr(self._obj, name)
        if name in _WRITES or name in _READS:
            st = self._st
            write = name in _WRITES

            def op(*a, **k):
                _record(st, name, write)
                return attr(*a, **k)
            return op
        return attr

    # -- dunders (not routed through __getattr__) --

    def __getitem__(self, k):
        _record(self._st, "__getitem__", False)
        return self._obj[k]

    def __setitem__(self, k, v):
        _record(self._st, "__setitem__", True)
        self._obj[k] = v

    def __delitem__(self, k):
        _record(self._st, "__delitem__", True)
        del self._obj[k]

    def __contains__(self, k):
        _record(self._st, "__contains__", False)
        return k in self._obj

    def __len__(self):
        _record(self._st, "__len__", False)
        return len(self._obj)

    def __iter__(self):
        _record(self._st, "__iter__", False)
        return iter(self._obj)

    def __bool__(self):
        _record(self._st, "__bool__", False)
        return bool(self._obj)

    def __repr__(self):
        return f"ShareProxy({self._obj!r})"


def share(obj, name: str):
    """Wrap ``obj`` in an access-checking proxy under TSAN; return it
    untouched otherwise. Call at construction of the owning class."""
    if not enabled():
        return obj
    return ShareProxy(obj, _state_for(name))


def share_always(obj, name: str) -> ShareProxy:
    """Unconditional proxy for MODULE-level registries (constructed at
    import, before any test can set the env): recording self-gates on
    :func:`enabled` per access, so the idle cost is one flag check."""
    return ShareProxy(obj, _state_for(name))


def token(name: str) -> "_SharedState | None":
    """Explicit instrumentation handle for structures a proxy cannot
    intercept (heapq mutates lists at the C level). None when TSAN is
    off — callers skip :func:`note` on None."""
    return _state_for(name) if enabled() else None


def note(tok: "_SharedState | None", op: str,
         write: bool = True) -> None:
    """Record one access on an explicit instrumentation token."""
    if tok is not None:
        _record(tok, op, write)


class activate:
    """Context manager forcing TSAN on (tests): fresh lockset state on
    entry and exit so runs stay independent."""

    def __enter__(self) -> "activate":
        global _FORCE
        reset_states()
        with _meta_lock:
            _FORCE = True
        return self

    def __exit__(self, *exc) -> None:
        global _FORCE
        with _meta_lock:
            _FORCE = None
        reset_states()
