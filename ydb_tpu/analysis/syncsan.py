"""Sync sanitizer: per-statement host-boundary counters (YDB_TPU_SYNCSAN=1).

The runtime half of the dispatch-purity pillar. ``hotpath.py`` proves
statically that no host work is *written* on the warm path; this
sanitizer counts what actually *crosses* the host boundary per
statement — H2D transfers, D2H transfers, blocking syncs and XLA
compilations — and enforces a warm-statement budget: after warmup,
**zero compilations** and a bounded sync count, or the statement
raises ``SyncBudgetError``.

Seams patched while armed (restored on disarm):

  ``jax.block_until_ready``   blocking sync
  ``jax.device_get``          one D2H transfer + one blocking sync
                              (the repo batches whole blocks through a
                              single call — one RTT, one count)
  ``jnp.asarray``             H2D transfer when staging host data
  ``np.asarray``              D2H sync when materializing a jax.Array

Compilations are counted through ``jax.monitoring``: the
``/jax/core/compile/backend_compile_duration`` event fires exactly
once per XLA backend compile (never on a warm cache hit), so the
listener is the ground truth the compile caches are judged against.
``.item()`` lives on the C++ ArrayImpl and cannot be patched — the
static analyzer (H001) owns that seam.

Counters attribute to the active statement: the thread that called
``begin_statement`` resolves via a thread-local; conveyor workers
resolve via the obs span they inherited (``tracing.wrap_current``
propagates spans across the pool) and the trace-id registry; anything
else lands in the orphan totals. ``end_statement`` annotates the obs
span (``syncsan_*`` attributes, surfaced by EXPLAIN ANALYZE) and
enforces the budget.

Gates mirror ``leaksan.py``: ``YDB_TPU_SYNCSAN=1`` env,
``set_force()`` pin, ``activate()`` context manager for tests and
bench. All functions are None-safe no-ops while disabled.
"""

from __future__ import annotations

import os
import threading

from ydb_tpu.obs import tracing

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: tri-state pin: None -> follow the env var; True/False -> forced
_FORCE: "bool | None" = None

_meta_lock = threading.Lock()
_tls = threading.local()


def enabled() -> bool:
    if _FORCE is not None:
        return _FORCE
    return os.environ.get("YDB_TPU_SYNCSAN", "") not in ("", "0")


_ON = enabled()


class SyncBudgetError(AssertionError):
    """A warm statement exceeded its host-boundary budget."""


class Budget:
    __slots__ = ("compiles", "syncs", "warmup")

    def __init__(self, compiles: int = 0, syncs: "int | None" = None,
                 warmup: int = 1):
        self.compiles = compiles
        self.syncs = syncs
        self.warmup = warmup


_budget: "Budget | None" = None
_warm_seen: dict = {}  # label -> statements ended (warmup tracking)


class Statement:
    """Counters for one statement (one ``begin``/``end`` pair)."""

    __slots__ = ("label", "trace_id", "span", "h2d", "d2h", "syncs",
                 "compiles", "_lock")

    def __init__(self, label: str, trace_id: "str | None"):
        self.label = label
        self.trace_id = trace_id
        self.span = tracing.current_span()
        self.h2d = 0
        self.d2h = 0
        self.syncs = 0
        self.compiles = 0
        self._lock = threading.Lock()

    def note(self, *, h2d: int = 0, d2h: int = 0, syncs: int = 0,
             compiles: int = 0) -> None:
        with self._lock:
            self.h2d += h2d
            self.d2h += d2h
            self.syncs += syncs
            self.compiles += compiles

    def snapshot(self) -> dict:
        with self._lock:
            return {"h2d": self.h2d, "d2h": self.d2h,
                    "syncs": self.syncs, "compiles": self.compiles}


_by_trace: dict = {}       # trace_id -> Statement
_orphans = Statement("<orphan>", None)


def _resolve() -> "Statement | None":
    st = getattr(_tls, "stat", None)
    if st is not None:
        return st
    span = tracing.current_span()
    if span is not None:
        st = _by_trace.get(span.trace_id)
        if st is not None:
            return st
    return _orphans


def _note(**counts) -> None:
    if not _ON:
        return
    st = _resolve()
    if st is not None:
        st.note(**counts)


# ---------------- seam patches ----------------

_patched = False
_orig: dict = {}
_listener_registered = False


def _is_device_value(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:
        return False


def _install() -> None:
    global _patched, _listener_registered
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
    except Exception:
        return

    def block_until_ready(x):
        _note(syncs=1)
        return _orig["block_until_ready"](x)

    def device_get(x):
        _note(d2h=1, syncs=1)
        return _orig["device_get"](x)

    def jnp_asarray(a, *args, **kwargs):
        if isinstance(a, np.ndarray):
            _note(h2d=1)
        return _orig["jnp_asarray"](a, *args, **kwargs)

    def np_asarray(a, *args, **kwargs):
        if _is_device_value(a):
            _note(d2h=1, syncs=1)
        return _orig["np_asarray"](a, *args, **kwargs)

    # jax.monitoring offers no per-listener removal, so register once
    # for the process and gate the body on _ON instead.
    def _on_event(event, duration, **kw):
        if _ON and event == _COMPILE_EVENT:
            _note(compiles=1)

    with _meta_lock:
        if _patched:
            return
        _orig["block_until_ready"] = jax.block_until_ready
        _orig["device_get"] = jax.device_get
        _orig["jnp_asarray"] = jnp.asarray
        _orig["np_asarray"] = np.asarray
        jax.block_until_ready = block_until_ready
        jax.device_get = device_get
        jnp.asarray = jnp_asarray
        np.asarray = np_asarray
        _patched = True
        if not _listener_registered:
            jax.monitoring.register_event_duration_secs_listener(
                _on_event)
            _listener_registered = True


def _uninstall() -> None:
    global _patched
    import jax
    import jax.numpy as jnp
    import numpy as np

    with _meta_lock:
        if not _patched:
            return
        jax.block_until_ready = _orig["block_until_ready"]
        jax.device_get = _orig["device_get"]
        jnp.asarray = _orig["jnp_asarray"]
        np.asarray = _orig["np_asarray"]
        _patched = False


# ---------------- gates (leaksan idiom) ----------------


def refresh() -> None:
    """Re-read the gate; arm or disarm the seams to match."""
    global _ON
    with _meta_lock:
        _ON = enabled()
        on = _ON
    # the seam patchers take the lock themselves (their idempotence
    # checks run under it); racing refreshes converge on the last gate
    if on:
        _install()
    else:
        _uninstall()


def set_force(value: "bool | None") -> None:
    """Pin the sanitizer on/off regardless of the env (tests, bench);
    ``None`` returns control to ``YDB_TPU_SYNCSAN``."""
    global _FORCE
    with _meta_lock:
        _FORCE = value
    refresh()


# honor an env set before import
if _ON:
    refresh()


# ---------------- statement lifecycle ----------------


def begin_statement(label: str,
                    trace_id: "str | None" = None,
                    span=None) -> "Statement | None":
    """Open a counting window for one statement. Returns None (and
    counts nothing) while the sanitizer is off. ``span`` pins the obs
    span the counters annotate at close — callers opening the window
    BEFORE activating their root span (the session statement path)
    must pass it, else ``current_span()`` is still the caller's
    parent (or None) and the ``syncsan_*`` attrs land elsewhere."""
    if not _ON:
        return None
    st = Statement(label, trace_id)
    if span is not None:
        st.span = span
    _tls.stat = st
    if trace_id is not None:
        with _meta_lock:
            _by_trace[trace_id] = st
    return st


def _close(st: "Statement | None") -> None:
    if getattr(_tls, "stat", None) is st:
        _tls.stat = None
    if st is not None and st.trace_id is not None:
        with _meta_lock:
            _by_trace.pop(st.trace_id, None)


def discard(st: "Statement | None") -> None:
    """Drop a window without budget enforcement (error paths)."""
    _close(st)


def end_statement(st: "Statement | None", *,
                  enforce: bool = True) -> "dict | None":
    """Close the window: annotate the obs span with ``syncsan_*``
    attributes and enforce the warm budget. Returns the counter
    snapshot (None while disabled)."""
    if st is None:
        return None
    _close(st)
    snap = st.snapshot()
    if st.span is not None:
        st.span.set(syncsan_h2d=snap["h2d"], syncsan_d2h=snap["d2h"],
                    syncsan_syncs=snap["syncs"],
                    syncsan_compiles=snap["compiles"])
    if enforce and _budget is not None:
        with _meta_lock:
            seen = _warm_seen.get(st.label, 0)
            _warm_seen[st.label] = seen + 1
        if seen >= _budget.warmup:
            if snap["compiles"] > _budget.compiles:
                raise SyncBudgetError(
                    f"statement {st.label!r} compiled"
                    f" {snap['compiles']}x on the warm path"
                    f" (budget {_budget.compiles}); a compile cache"
                    " is missing or its key is unstable")
            if _budget.syncs is not None and \
                    snap["syncs"] > _budget.syncs:
                raise SyncBudgetError(
                    f"statement {st.label!r} blocked on the device"
                    f" {snap['syncs']}x (budget {_budget.syncs});"
                    " host work leaked into the dispatch loop")
    return snap


def set_budget(compiles: int = 0, syncs: "int | None" = None,
               warmup: int = 1) -> None:
    """Arm the warm-statement budget: statements past ``warmup`` (per
    label) must stay within ``compiles``/``syncs``."""
    global _budget
    with _meta_lock:
        _budget = Budget(compiles=compiles, syncs=syncs, warmup=warmup)
        _warm_seen.clear()


def clear_budget() -> None:
    global _budget
    with _meta_lock:
        _budget = None
        _warm_seen.clear()


def totals() -> dict:
    """Aggregate counters across live windows + orphans (bench)."""
    agg = _orphans.snapshot()
    with _meta_lock:
        stats = list(_by_trace.values())
    for st in stats:
        for k, v in st.snapshot().items():
            agg[k] += v
    return agg


def reset() -> None:
    """Drop all windows, budgets and orphan counts (tests)."""
    global _orphans
    with _meta_lock:
        _by_trace.clear()
        _warm_seen.clear()
        _orphans = Statement("<orphan>", None)
    _tls.stat = None


class activate:
    """``with syncsan.activate():`` — force the sanitizer on for a
    scope regardless of the env var, starting from clean counters."""

    def __init__(self, budget: "Budget | None" = None):
        self._budget = budget

    def __enter__(self):
        reset()
        set_force(True)
        if self._budget is not None:
            set_budget(compiles=self._budget.compiles,
                       syncs=self._budget.syncs,
                       warmup=self._budget.warmup)
        return self

    def __exit__(self, *exc):
        clear_budget()
        set_force(None)
        reset()
        return False
