"""Shared CLI path collection for the analysis checkers.

Both ``python -m ydb_tpu.analysis.lint`` and
``python -m ydb_tpu.analysis.concurrency`` accept the same shape:

    python -m ydb_tpu.analysis.<tool> [path ...] [--json] [--changed]

``--changed`` scopes the run to .py files touched in the working tree
(staged, unstaged, and untracked — what a pre-commit hook cares about),
intersected with the requested roots. When git is unavailable or the
tree is not a repository, the full requested roots are scanned instead:
a pre-commit fast path must degrade to the safe superset, never to a
silent no-op.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path


def default_root() -> Path:
    """The ydb_tpu package directory (the default scan target)."""
    return Path(__file__).resolve().parents[1]


def expand_roots(paths) -> list:
    """Files/dirs -> sorted .py file list (dirs recurse)."""
    files: list = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    return files


def changed_py_files(repo_dir: Path) -> "list | None":
    """.py paths touched in the working tree per git, or None when git
    cannot answer (not a repo, git missing, command failure)."""
    out = []
    for args in (("diff", "--name-only", "HEAD"),
                 ("ls-files", "--others", "--exclude-standard")):
        try:
            proc = subprocess.run(
                ("git", "-C", str(repo_dir)) + args,
                capture_output=True, text=True, timeout=30, check=True)
        except (OSError, subprocess.SubprocessError):
            return None
        out.extend(proc.stdout.splitlines())
    root = _git_toplevel(repo_dir)
    if root is None:
        return None
    return [root / ln for ln in dict.fromkeys(out)
            if ln.endswith(".py")]


def _git_toplevel(repo_dir: Path) -> "Path | None":
    try:
        proc = subprocess.run(
            ("git", "-C", str(repo_dir), "rev-parse", "--show-toplevel"),
            capture_output=True, text=True, timeout=30, check=True)
    except (OSError, subprocess.SubprocessError):
        return None
    top = proc.stdout.strip()
    return Path(top) if top else None


def collect_files(argv_paths, changed: bool = False) -> list:
    """Resolve CLI path args (+ optional --changed scoping) to the .py
    file list a checker should scan."""
    roots = [Path(p) for p in argv_paths] or [default_root()]
    files = expand_roots(roots)
    if not changed:
        return files
    touched = changed_py_files(roots[0] if roots[0].is_dir()
                               else roots[0].parent)
    if touched is None:
        return files  # git unavailable: degrade to the full scan
    touched_set = {p.resolve() for p in touched}
    return [f for f in files if f.resolve() in touched_set]


def parse_cli(argv) -> tuple:
    """Split argv into (paths, as_json, changed); shared by both CLIs."""
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    changed = "--changed" in argv
    paths = [a for a in argv if not a.startswith("--")]
    return paths, as_json, changed
