"""Dispatch-purity static analyzer: warm-path host-work rules H001-H006.

The device is >100x idle because everything above the kernels is
per-statement host orchestration (ROADMAP item 1). The warm statement
path — plan-cache hit, compile-cache hit, resident inputs — should be
a thin corridor from SQL text to one fused device dispatch; every
`.item()`, numpy allocation or stray compile on that corridor is a
host round trip multiplied by QPS. This pass walks the corridor
statically; the runtime half (``analysis/syncsan.py``) counts what
actually crossed the boundary per statement.

Unlike the whole-tree linters (lint/concurrency/lifecycle), this
analyzer is PATH-SCOPED: it builds an interprocedural call graph from
the declared hot-path roots and only judges code reachable from them.
Cold paths (boot, DDL, compaction, the compile itself) may do all the
host work they like.

Roots (the warm statement corridor, one per layer):

  kqp.session   Session._execute_admitted   warm execute
  kqp.batch     BatchDispatcher.execute     micro-batched dispatch
  ssa.compiler  CompiledProgram.__call__    cached-executable call
  engine.scan   ScanExecutor.run_stream     block-streamed fast path
  engine.resident  ResidentStore.lookup     HBM-resident lookup

Rules:

  H001 device-sync-in-dispatch  ``.item()`` / ``block_until_ready`` /
                                ``jax.device_get`` / ``np.asarray`` /
                                ``.to_numpy()`` on the warm path — a
                                blocking device->host round trip per
                                statement
  H002 unstable-cache-key       a cache subscript/get keyed by a
                                runtime-formatted string (f-string,
                                ``.format``, ``%``) or ``id(...)`` —
                                embeds shapes/identities as text and
                                retraces or misses per shape
  H003 per-dispatch-compile     ``jax.jit`` / ``compile_program`` /
                                ``.lower()``/``.compile()`` reachable
                                on the warm path — compilation must
                                hide behind a cache, never per dispatch
  H004 per-dispatch-plan        ``parse`` / ``plan_select*`` /
                                ``plan_signature`` on the warm path —
                                planning must hide behind the plan
                                cache
  H005 host-alloc-in-dispatch   ``np.zeros``/``np.empty``/
                                ``np.concatenate``/... — host array
                                allocation inside the dispatch loop
  H006 python-row-loop          a Python ``for`` over rows/blocks
                                (``range(len(...))``, ``.tolist()``,
                                any name containing row/block) — O(n)
                                interpreter work per statement

Escape hatch: decorate a function with ``@analysis.host_ok("reason")``
(or bare ``@host_ok``) to declare its host work deliberate — the lazy
result fetch, a cache-miss compile helper. The function is neither
reported nor descended into. Line-level ``# ydb-lint: disable=H001``
pragmas (shared suppress machinery) silence individual sites; for
those the walker still stops at compile/plan boundary calls (their
bodies are cold by definition).

Run: ``python -m ydb_tpu.analysis.hotpath [path ...] [--json]
[--changed]``. Default path: the ydb_tpu package. Exit 1 on any
unsuppressed finding. ``tests/test_hotpath_clean.py`` enforces a clean
tree as a tier-1 test.
"""

from __future__ import annotations

import ast
import json
import sys

from ydb_tpu.analysis.lint import Finding, _dotted, _has_trace_call
from ydb_tpu.analysis.paths import collect_files, parse_cli
from ydb_tpu.analysis.suppress import file_skipped, filter_suppressed

RULES = {
    "H001": "device-sync-in-dispatch",
    "H002": "unstable-cache-key",
    "H003": "per-dispatch-compile",
    "H004": "per-dispatch-plan",
    "H005": "host-alloc-in-dispatch",
    "H006": "python-row-loop",
}

#: (module-path suffix, ClassName.method) — the declared warm roots
HOT_ROOTS = (
    ("kqp.session", "Session._execute_admitted"),
    ("kqp.batch", "BatchDispatcher.execute"),
    ("ssa.compiler", "CompiledProgram.__call__"),
    ("engine.scan", "ScanExecutor.run_stream"),
    ("engine.resident", "ResidentStore.lookup"),
)

#: device->host sync call roots (H001)
_SYNC_ROOTS = {"jax.device_get", "np.asarray", "np.array",
               "numpy.asarray", "numpy.array", "jax.block_until_ready"}
#: method names that fetch a block to host (H001)
_FETCH_METHODS = {"to_numpy", "host_columns", "validity_numpy",
                  "block_until_ready"}
#: compile-family (H003): the call is the finding, the body is cold
_COMPILE_ROOTS = {"jax.jit", "jax.pmap", "jax.xla_computation"}
_COMPILE_NAMES = {"compile_program"}
#: planning-family (H004)
_PLAN_NAMES = {"parse", "plan_select", "plan_select_full",
               "plan_signature"}
#: host allocators + per-dispatch device staging (H005)
_ALLOC_ROOTS = {"np.zeros", "np.empty", "np.ones", "np.full",
                "np.arange", "np.concatenate", "np.stack", "np.copy",
                "numpy.zeros", "numpy.empty", "numpy.concatenate",
                "jnp.asarray", "jnp.array", "jax.numpy.asarray"}

#: method names too generic for the unique-method fallback — they
#: collide with dict/list/str/stdlib methods and would wire unrelated
#: classes into the call graph (``self.aux.items()`` is not
#: ``StreamScheduler.items``)
_GENERIC_METHODS = {
    "items", "keys", "values", "get", "set", "pop", "add", "append",
    "extend", "update", "clear", "copy", "close", "open", "read",
    "write", "run", "start", "stop", "put", "join", "split", "strip",
    "format", "encode", "decode", "sort", "index", "count", "remove",
    "insert", "send", "result", "done", "wait", "acquire", "release",
    "submit", "shutdown", "flush", "seek", "tell", "name",
}


def _host_ok_reason(node) -> "str | None":
    """The reason string of an ``@analysis.host_ok("...")`` decorator
    (or bare ``@host_ok``); None when the function carries none."""
    for dec in getattr(node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        # match host_ok, analysis.host_ok and underscore-aliased
        # imports (_host_ok) alike
        last = _dotted(target).rsplit(".", 1)[-1].lstrip("_")
        if last == "host_ok":
            if isinstance(dec, ast.Call) and dec.args and \
                    isinstance(dec.args[0], ast.Constant):
                return str(dec.args[0].value)
            return "unspecified"
    return None


class _FnInfo:
    """One indexed function/method: AST + location + host_ok status."""

    __slots__ = ("modname", "qualname", "cls", "node", "filename",
                 "host_ok")

    def __init__(self, modname, qualname, cls, node, filename):
        self.modname = modname
        self.qualname = qualname
        self.cls = cls              # enclosing class name or None
        self.node = node
        self.filename = filename
        self.host_ok = _host_ok_reason(node)


class _Module:
    """Per-module symbol table: functions, classes and import aliases."""

    def __init__(self, modname: str, filename: str, tree):
        self.modname = modname
        self.filename = filename
        self.fns: dict[str, _FnInfo] = {}     # qualname -> info
        self.classes: set[str] = set()
        self.imports: dict[str, str] = {}     # alias -> dotted origin
        for st in tree.body:
            self._top(st)
        # imports inside function bodies count too (the repo defers
        # heavy imports into the statement path deliberately)
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._imp(node)

    def _top(self, st) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.fns[st.name] = _FnInfo(
                self.modname, st.name, None, st, self.filename)
        elif isinstance(st, ast.ClassDef):
            self.classes.add(st.name)
            for sub in st.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    q = f"{st.name}.{sub.name}"
                    self.fns[q] = _FnInfo(
                        self.modname, q, st.name, sub, self.filename)

    def _imp(self, node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                self.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                self.imports[a.asname or a.name] = \
                    f"{node.module}.{a.name}"


class _Index:
    """Cross-module function index for call resolution."""

    def __init__(self, modules: list[_Module]):
        self.modules = {m.modname: m for m in modules}
        # method name -> [infos] for the unique-method fallback
        self.methods: dict[str, list] = {}
        for m in modules:
            for info in m.fns.values():
                if info.cls is not None:
                    self.methods.setdefault(
                        info.node.name, []).append(info)

    def by_suffix(self, suffix: str) -> "_Module | None":
        for name, m in self.modules.items():
            if name == suffix or name.endswith("." + suffix):
                return m
        return None

    def resolve_from(self, origin: str) -> "_FnInfo | None":
        """Resolve an import origin ``pkg.mod.func`` to an indexed
        module-level function."""
        mod, _, name = origin.rpartition(".")
        m = self.modules.get(mod)
        if m is None:
            # the index stores short module paths ("kqp.session") when
            # scanning a package subtree; try suffix-matching
            for k, cand in self.modules.items():
                if mod == k or mod.endswith("." + k) or \
                        k.endswith("." + mod):
                    m = cand
                    break
        if m is None:
            return None
        info = m.fns.get(name)
        if info is not None and info.cls is None:
            return info
        return None

    def unique_method(self, name: str) -> "_FnInfo | None":
        """The one scanned class method with this name (None when the
        name is ambiguous — each layer then needs its own root — or
        generic enough to collide with stdlib container methods)."""
        if name in _GENERIC_METHODS or name.startswith("__"):
            return None
        infos = self.methods.get(name, ())
        return infos[0] if len(infos) == 1 else None


class _WarmVisitor(ast.NodeVisitor):
    """Hazard rules over ONE warm function body. Nested defs are
    visited too: a closure defined on the dispatch path (staging
    thunks, distribution callbacks) runs on the dispatch path."""

    def __init__(self, out: list, info: _FnInfo, chain: str,
                 callees: list):
        self.out = out
        self.info = info
        self.chain = chain
        self.callees = callees  # raw call nodes for the walker

    def run(self) -> None:
        for stmt in self.info.node.body:
            self.visit(stmt)

    def _emit(self, node, code: str, message: str) -> None:
        self.out.append(Finding(
            self.info.filename, node.lineno, node.col_offset, code,
            RULES[code], f"{message} [warm path: {self.chain}]"))

    # ---- calls: H001 / H003 / H004 / H005 + callee collection ----

    def visit_Call(self, node: ast.Call):
        fn = node.func
        root = _dotted(fn)
        attr = fn.attr if isinstance(fn, ast.Attribute) else ""
        if attr == "item" and not node.args:
            self._emit(node, "H001",
                       ".item() blocks on the device per statement;"
                       " keep the value device-resident or fetch once"
                       " at the result boundary")
        elif root in _SYNC_ROOTS or attr in _FETCH_METHODS:
            what = root or f".{attr}()"
            self._emit(node, "H001",
                       f"{what} forces a device->host transfer on the"
                       " warm path; results should stay on device"
                       " until the deliberate fetch (mark that site"
                       " @analysis.host_ok)")
        elif isinstance(fn, ast.Name) and \
                fn.id in ("int", "float", "bool") and any(
                    _has_trace_call(a, through_materializers=True)
                    for a in node.args):
            self._emit(node, "H001",
                       f"{fn.id}(...) over a device expression"
                       " materializes per statement; hoist the"
                       " conversion out of the dispatch loop")
        # ``.lower`` is jax only with example args (str.lower() has
        # none); ``.compile`` is jax except the re.compile root
        compile_method = (attr == "compile" and root != "re.compile") \
            or (attr == "lower" and bool(node.args))
        if root in _COMPILE_ROOTS or root in _COMPILE_NAMES or \
                compile_method:
            self._emit(node, "H003",
                       f"compile call {root or attr}(...) reachable on"
                       " the warm path: compilation must hide behind"
                       " the compile cache (mark the guarded miss-path"
                       " helper @analysis.host_ok)")
            return  # the compile body is cold; do not descend
        if (isinstance(fn, ast.Name) and fn.id in _PLAN_NAMES) or \
                attr in _PLAN_NAMES:
            self._emit(node, "H004",
                       f"planning call {root or attr}(...) reachable"
                       " on the warm path: parse/plan must hide behind"
                       " the plan cache")
            return  # the planner body is cold; do not descend
        if root in _ALLOC_ROOTS:
            self._emit(node, "H005",
                       f"{root}(...) allocates a host array per"
                       " statement; stage once at plan/compile time or"
                       " keep the buffer device-resident")
        self.callees.append(node)
        self.generic_visit(node)

    # ---- H002: string-formatted cache keys ----

    @staticmethod
    def _formats_at_runtime(expr) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.JoinedStr):
                return True
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr == "format":
                    return True
                if isinstance(f, ast.Name) and f.id == "id":
                    return True
            if isinstance(sub, ast.BinOp) and \
                    isinstance(sub.op, ast.Mod) and \
                    isinstance(sub.left, ast.Constant) and \
                    isinstance(sub.left.value, str):
                return True
        return False

    def _check_cache_key(self, node, recv, key_expr) -> None:
        name = _dotted(recv)
        if "cache" not in name.lower():
            return
        if self._formats_at_runtime(key_expr):
            self._emit(node, "H002",
                       f"cache {name} keyed by a runtime-formatted"
                       " string / id(): text keys embed shapes and"
                       " identities unstably (retrace or permanent"
                       " miss per shape); key on a structured tuple of"
                       " hashable plan-time values")

    def visit_Subscript(self, node: ast.Subscript):
        self._check_cache_key(node, node.value, node.slice)
        self.generic_visit(node)

    # ---- H006: row/block loops ----

    @staticmethod
    def _rowish(it) -> "str | None":
        for sub in ast.walk(it):
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr == "tolist":
                    return ".tolist()"
                if isinstance(f, ast.Name) and f.id == "range" and \
                        sub.args and isinstance(sub.args[0], ast.Call) \
                        and isinstance(sub.args[0].func, ast.Name) \
                        and sub.args[0].func.id == "len":
                    return "range(len(...))"
            name = ""
            if isinstance(sub, ast.Name):
                name = sub.id
            elif isinstance(sub, ast.Attribute):
                name = sub.attr
            low = name.lower()
            if "row" in low or "block" in low:
                return name
        return None

    def visit_For(self, node: ast.For):
        why = self._rowish(node.iter)
        if why is not None:
            self._emit(node, "H006",
                       f"Python for-loop over {why} on the warm path:"
                       " per-row/per-block interpreter work multiplies"
                       " by statement rate; vectorize on device or"
                       " bound and justify it with a pragma")
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    # cache .get/.setdefault calls are Calls — hook them off the same
    # visit_Call traffic via generic inspection
    def generic_visit(self, node):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("get", "setdefault") and node.args:
            self._check_cache_key(node, node.func.value, node.args[0])
        super().generic_visit(node)


class _Walker:
    """Interprocedural BFS from the declared roots."""

    def __init__(self, index: _Index, roots):
        self.index = index
        self.roots = roots
        self.findings: list = []
        self.seen: set = set()

    def run(self) -> list:
        queue: list = []
        for suffix, qual in self.roots:
            m = self.index.by_suffix(suffix)
            if m is None:
                continue
            info = m.fns.get(qual)
            if info is not None:
                queue.append((info, qual))
        while queue:
            info, chain = queue.pop(0)
            key = (info.modname, info.qualname)
            if key in self.seen:
                continue
            self.seen.add(key)
            if info.host_ok is not None:
                continue  # declared deliberate: no report, no descent
            callees: list = []
            _WarmVisitor(self.findings, info, chain, callees).run()
            for call in callees:
                target = self._resolve(info, call)
                if target is None or target.host_ok is not None:
                    continue
                tkey = (target.modname, target.qualname)
                if tkey not in self.seen:
                    queue.append(
                        (target, f"{chain} -> {target.qualname}"))
        return self.findings

    def _resolve(self, info: _FnInfo, call: ast.Call) -> "_FnInfo | None":
        fn = call.func
        mod = self.index.modules[info.modname]
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in mod.classes:
                return None  # constructors are setup, not dispatch
            local = mod.fns.get(name)
            if local is not None and local.cls is None:
                return local
            origin = mod.imports.get(name)
            if origin is not None:
                return self.index.resolve_from(origin)
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        # self.m(...) -> same-class method first
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id == "self" and \
                info.cls is not None:
            local = mod.fns.get(f"{info.cls}.{fn.attr}")
            if local is not None:
                return local
        # module_alias.f(...)
        if isinstance(recv, ast.Name):
            origin = mod.imports.get(recv.id)
            if origin is not None:
                return self.index.resolve_from(f"{origin}.{fn.attr}")
        # anything else: follow only when the method name is unique
        # across every scanned class (each layer's entry is otherwise
        # its own declared root)
        return self.index.unique_method(fn.attr)


# ---------------- driver ----------------


def _modname_for(filename: str) -> str:
    """Dotted module path relative to the ydb_tpu package ("kqp.session"
    for .../ydb_tpu/kqp/session.py); the bare stem otherwise."""
    from pathlib import PurePath

    parts = list(PurePath(filename).parts)
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    for anchor in range(len(parts) - 1, -1, -1):
        if parts[anchor] == "ydb_tpu":
            return ".".join(parts[anchor + 1:])
    return parts[-1] if parts else filename


def check_sources(sources, roots=HOT_ROOTS,
                  report_files=None) -> list:
    """Analyze (src, filename, modname) triples as one program; modname
    None derives the dotted path from the filename. Returns
    unsuppressed findings sorted by position. ``report_files`` (a set
    of filenames) restricts REPORTING without shrinking the call-graph
    index — a path-scoped analyzer must always resolve against the
    whole program, or a file subset makes ambiguous methods look
    unique and the walker wanders into cold code."""
    findings: list = []
    modules: list = []
    lines_by_file: dict = {}
    for src, filename, modname in sources:
        lines = src.splitlines()
        lines_by_file[filename] = lines
        if file_skipped(lines):
            continue
        try:
            tree = ast.parse(src, filename=filename)
        except SyntaxError as e:
            findings.append(Finding(
                filename, e.lineno or 0, e.offset or 0, "H000",
                "syntax-error", str(e.msg)))
            continue
        modules.append(_Module(
            modname if modname is not None else _modname_for(filename),
            filename, tree))
    index = _Index(modules)
    findings.extend(_Walker(index, roots).run())
    kept: list = []
    for filename, lines in lines_by_file.items():
        if report_files is not None and filename not in report_files:
            continue
        here = [f for f in findings if f.file == filename]
        kept.extend(filter_suppressed(here, lines, RULES))
    return sorted(kept, key=lambda f: (f.file, f.line, f.col, f.code))


def check_source(src: str, filename: str = "<string>",
                 modname: "str | None" = None,
                 roots=HOT_ROOTS) -> list:
    """Analyze one source text (tests)."""
    return check_sources([(src, filename, modname)], roots=roots)


def check_paths(paths, roots=HOT_ROOTS, report_files=None) -> list:
    sources = []
    for f in paths:
        sources.append((f.read_text(encoding="utf-8"), str(f), None))
    return check_sources(sources, roots=roots,
                         report_files=report_files)


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    # index the FULL requested roots always; --changed only narrows
    # which files findings are reported for (see check_sources)
    files = collect_files(paths)
    report = None
    if changed:
        report = {str(f) for f in collect_files(paths, changed=True)}
    findings = check_paths(files, report_files=report)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
