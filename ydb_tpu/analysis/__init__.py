"""Static analysis: SSA verify, lint, concurrency, lifecycle, hotpath,
devmem.

Six pillars (README.md in this directory):
  * ``verify`` — the typed SSA program checker every SQL→SSA lowering
    passes through before any JAX trace (the TProgramContainer::Init
    analog, ydb/core/tx/program/program.cpp:553).
  * ``lint`` — an AST linter over the Python tree flagging jit-hazard
    patterns (host syncs, Python control flow on traced values,
    wall-clock/randomness inside traces, mutable defaults,
    nondeterministic set iteration). ``python -m ydb_tpu.analysis.lint``.
  * ``concurrency`` + ``sanitizer`` — lock/guard discipline over the
    threaded runtime (C001-C008: guard inconsistency, lock-order
    cycles, blocking under locks, orphan daemon threads, ...) plus an
    Eraser-style runtime race detector for the designated shared
    structures (``YDB_TPU_TSAN=1``).
    ``python -m ydb_tpu.analysis.concurrency``.
  * ``lifecycle`` + ``leaksan`` — acquire/release pairing discipline
    over every slot, flight, gauge and handle the runtime hands out
    (R001-R008: release not in finally, flights stranded across
    yields/submits, grow-only containers, unreachable stop paths, ...)
    plus a runtime leak sanitizer (``YDB_TPU_LEAKSAN=1``) whose
    tracked handles must drain to zero at statement completion and
    Cluster.stop. ``python -m ydb_tpu.analysis.lifecycle``.
  * ``hotpath`` + ``syncsan`` — dispatch purity. The static half
    walks an interprocedural call graph from the declared warm
    statement roots (session execute, batch dispatch, cached
    executable call, streamed scan, resident lookup) and flags
    per-statement host work (H001-H006: device syncs, unstable cache
    keys, per-dispatch compile/plan calls, host allocation, Python
    row loops); the runtime half (``YDB_TPU_SYNCSAN=1``) counts
    transfers/syncs/compiles per statement at the JAX seams,
    attributes them to obs spans and enforces a warm budget of zero
    compilations. ``python -m ydb_tpu.analysis.hotpath``.
  * ``devmem`` + ``memsan`` — device-memory discipline. The static
    half walks the runtime packages (engine, ssa, kqp, parallel,
    blocks, serving) and flags HBM provenance hazards (M001-M008:
    unbudgeted device allocation, use-after-donation, donated-jit
    rebuild hazards, unrounded jit shapes, device arrays pinned in
    pool closures, grow-only device containers, per-dispatch aux
    staging, buffers held across generator yields); the runtime half
    (``YDB_TPU_MEMSAN=1``) tracks live/peak device bytes per
    statement at the allocation seams and enforces a warm peak-bytes
    budget with zero unbudgeted allocations.
    ``python -m ydb_tpu.analysis.devmem``.

``python -m ydb_tpu.analysis`` runs all six and exits 1 on any
finding. ``sanitizer``, ``leaksan``, ``syncsan`` and ``memsan`` keep a
bare import-time dependency set (os + threading + obs.tracing) so the
low-level runtime modules (conveyor, probes, counters, blockcache)
can import them safely: ``from ydb_tpu.analysis import leaksan``.

``host_ok`` is the hotpath escape hatch: decorating a function
declares its host work deliberate (the lazy result fetch, a guarded
compile-cache miss path) — the analyzer neither reports nor descends
into it, and the reason string documents why at the site. ``budget_ok``
is the devmem analog: the decorated function's device allocations are
declared budgeted/bounded and the analyzer skips it.
"""

# host_ok is defined BEFORE the verify import: modules inside the
# verify->ssa import chain (ssa.compiler) resolve
# ``from ydb_tpu.analysis import host_ok`` against this partially
# initialized package, so the name must already be bound when the
# chain re-enters here.
def host_ok(reason: str):
    """Mark a function's host work as deliberate for the dispatch-
    purity analyzer (``hotpath.py``). The decorated function is
    excluded from the warm-path walk; ``reason`` says why the host
    boundary crossing is intentional (e.g. "lazy result fetch")."""

    def mark(fn):
        fn.__host_ok__ = reason
        return fn

    return mark


# budget_ok sits beside host_ok (before the verify import) for the
# same import-cycle reason: runtime modules inside the verify->ssa
# chain resolve it against the partially initialized package.
def budget_ok(reason: str):
    """Mark a function's device allocations as deliberately budgeted
    or bounded for the device-memory analyzer (``devmem.py``). The
    decorated function is excluded from the M-rule scan; ``reason``
    names the budget that covers it (e.g. "charged to the resident
    ledger")."""

    def mark(fn):
        fn.__budget_ok__ = reason
        return fn

    return mark


from ydb_tpu.analysis.diagnostics import (  # noqa: F401,E402
    Diagnostic,
    PlanError,
    VerificationError,
)
from ydb_tpu.analysis.verify import (  # noqa: F401,E402
    ProgramAnalysis,
    analyze_program,
    check_program,
    infer_nullable,
    verify_program,
)
