"""Static analysis: SSA program verification + trace-safety lint.

Two pillars (README.md in this directory):
  * ``verify`` — the typed SSA program checker every SQL→SSA lowering
    passes through before any JAX trace (the TProgramContainer::Init
    analog, ydb/core/tx/program/program.cpp:553).
  * ``lint`` — an AST linter over the Python tree flagging jit-hazard
    patterns (host syncs, Python control flow on traced values,
    wall-clock/randomness inside traces, mutable defaults,
    nondeterministic set iteration). ``python -m ydb_tpu.analysis.lint``.
"""

from ydb_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    PlanError,
    VerificationError,
)
from ydb_tpu.analysis.verify import (  # noqa: F401
    ProgramAnalysis,
    analyze_program,
    check_program,
    infer_nullable,
    verify_program,
)
