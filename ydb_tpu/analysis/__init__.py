"""Static analysis: SSA verification + trace-safety lint + concurrency.

Three pillars (README.md in this directory):
  * ``verify`` — the typed SSA program checker every SQL→SSA lowering
    passes through before any JAX trace (the TProgramContainer::Init
    analog, ydb/core/tx/program/program.cpp:553).
  * ``lint`` — an AST linter over the Python tree flagging jit-hazard
    patterns (host syncs, Python control flow on traced values,
    wall-clock/randomness inside traces, mutable defaults,
    nondeterministic set iteration). ``python -m ydb_tpu.analysis.lint``.
  * ``concurrency`` + ``sanitizer`` — lock/guard discipline over the
    threaded runtime (C001-C008: guard inconsistency, lock-order
    cycles, blocking under locks, orphan daemon threads, ...) plus an
    Eraser-style runtime race detector for the designated shared
    structures (``YDB_TPU_TSAN=1``).
    ``python -m ydb_tpu.analysis.concurrency``.

``sanitizer`` keeps a bare dependency set (os + threading) so the
low-level runtime modules (conveyor, probes, counters, blockcache)
can import it safely: ``from ydb_tpu.analysis import sanitizer``.
"""

from ydb_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    PlanError,
    VerificationError,
)
from ydb_tpu.analysis.verify import (  # noqa: F401
    ProgramAnalysis,
    analyze_program,
    check_program,
    infer_nullable,
    verify_program,
)
