"""Static analysis: SSA verification, lint, concurrency, lifecycle.

Four pillars (README.md in this directory):
  * ``verify`` — the typed SSA program checker every SQL→SSA lowering
    passes through before any JAX trace (the TProgramContainer::Init
    analog, ydb/core/tx/program/program.cpp:553).
  * ``lint`` — an AST linter over the Python tree flagging jit-hazard
    patterns (host syncs, Python control flow on traced values,
    wall-clock/randomness inside traces, mutable defaults,
    nondeterministic set iteration). ``python -m ydb_tpu.analysis.lint``.
  * ``concurrency`` + ``sanitizer`` — lock/guard discipline over the
    threaded runtime (C001-C008: guard inconsistency, lock-order
    cycles, blocking under locks, orphan daemon threads, ...) plus an
    Eraser-style runtime race detector for the designated shared
    structures (``YDB_TPU_TSAN=1``).
    ``python -m ydb_tpu.analysis.concurrency``.
  * ``lifecycle`` + ``leaksan`` — acquire/release pairing discipline
    over every slot, flight, gauge and handle the runtime hands out
    (R001-R008: release not in finally, flights stranded across
    yields/submits, grow-only containers, unreachable stop paths, ...)
    plus a runtime leak sanitizer (``YDB_TPU_LEAKSAN=1``) whose
    tracked handles must drain to zero at statement completion and
    Cluster.stop. ``python -m ydb_tpu.analysis.lifecycle``.

``python -m ydb_tpu.analysis`` runs all four and exits 1 on any
finding. ``sanitizer`` and ``leaksan`` keep a bare dependency set
(os + threading + traceback) so the low-level runtime modules
(conveyor, probes, counters, blockcache) can import them safely:
``from ydb_tpu.analysis import leaksan``.
"""

from ydb_tpu.analysis.diagnostics import (  # noqa: F401
    Diagnostic,
    PlanError,
    VerificationError,
)
from ydb_tpu.analysis.verify import (  # noqa: F401
    ProgramAnalysis,
    analyze_program,
    check_program,
    infer_nullable,
    verify_program,
)
