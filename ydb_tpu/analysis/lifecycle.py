"""Resource-lifecycle static analyzer: acquire/release rules R001-R008.

PR 12 threaded deadlines, cooperative cancellation and load shedding
through every layer — exactly the error paths where a leaked conveyor
slot, a stranded resident flight or an orphaned session-registry row
turns "degrade gracefully" into "wedge after an hour of traffic". This
pass proves acquire/release PAIRING statically; the runtime half
(``analysis/leaksan.py``) catches what static analysis cannot see.

The analyzer learns pairs from a resource map — broker/lock
``acquire``/``release``, leaksan ``track``/``close``, session registry
``_register_active``/``_unregister_active``, workload
``admit``/``finish``, generic ``register``/``unregister`` and
``begin``/``end`` — plus "flight" containers (any ``self`` attribute
whose name contains ``flight``: ``_flights``, ``_inflight``).

Rules:

  R001 release-not-on-all-paths  an owned acquire whose matching
                                 release exists in the same function
                                 but never inside a ``finally`` — an
                                 exception or early return strands the
                                 resource
  R002 generator-holds-resource  a generator registers a flight / owns
                                 an acquire before a ``yield`` without
                                 a ``finally`` releasing it — an
                                 abandoned (never-closed) stream runs
                                 no ``finally`` late, and none at all
                                 protects a stranded registration
  R003 gauge-decrement-skipped   a ``self.x += 1`` / ``-= 1`` gauge
                                 pair in one method whose decrement is
                                 not ``finally``-protected — the
                                 exception path leaks the count
  R004 cancellation-swallowed    an ``except`` clause naming
                                 StatementCancelled / ConveyorTimeout /
                                 _Cancelled that neither re-raises nor
                                 records the error — cancellation must
                                 propagate so slots release
  R005 stoppable-not-stopped     a class holds (constructs in
                                 ``__init__``) a thread-owning object
                                 with a stop method, but no stop path
                                 of the holder ever reaches it
  R006 deadline-ignored-wait     a broker ``acquire`` without a
                                 ``deadline=`` — PR 12's discipline:
                                 admission waits on the statement path
                                 must observe the active Deadline
  R007 unbounded-growth          inserts into a container attribute
                                 with no removal, rebuild or bound
                                 check anywhere in the class
  R008 cross-thread-unowned      a flight registered before a conveyor
                                 ``submit`` whose closure has no
                                 ``finally`` releasing it — the
                                 resource crossed threads with no owner
                                 responsible for release

Suppression shares the lint machinery (``# ydb-lint: disable=R001`` on
the line or alone above it; ``skip-file``). Run:

    python -m ydb_tpu.analysis.lifecycle [path ...] [--json] [--changed]

Default path: the ydb_tpu package. Exit 1 on unsuppressed findings.
``tests/test_lifecycle_clean.py`` enforces a clean tree as a tier-1
test.
"""

from __future__ import annotations

import ast
import json
import sys
import threading

from ydb_tpu.analysis.lint import Finding, _dotted
from ydb_tpu.analysis.paths import collect_files, parse_cli
from ydb_tpu.analysis.suppress import file_skipped, filter_suppressed

RULES = {
    "R001": "release-not-on-all-paths",
    "R002": "generator-holds-resource",
    "R003": "gauge-decrement-skipped",
    "R004": "cancellation-swallowed",
    "R005": "stoppable-not-stopped",
    "R006": "deadline-ignored-wait",
    "R007": "unbounded-growth",
    "R008": "cross-thread-unowned",
}

#: acquire method name -> matching release method names (same receiver)
_PAIRS = {
    "acquire": ("release",),
    "track": ("close",),
    "_register_active": ("_unregister_active",),
    "register": ("unregister",),
    "admit": ("finish",),
    "begin": ("end",),
}
_RELEASES = {r for rs in _PAIRS.values() for r in rs}
#: container mutation names that GROW the receiver
_INSERTS = {"add", "append", "appendleft", "setdefault"}
#: ...and the ones that SHRINK it
_REMOVALS = {"pop", "popitem", "popleft", "discard", "remove", "clear"}
#: cancellation types that must propagate (or be recorded as the
#: statement's error) so the layers above release their resources
_CANCEL_EXCS = {"StatementCancelled", "ConveyorTimeout", "_Cancelled",
                "CancelledError", "DeadlineExceeded"}
_INIT_NAMES = {"__init__", "__new__", "__post_init__",
               "__init_subclass__", "__set_name__"}
_STOP_NAMES = {"stop", "close", "shutdown", "join", "terminate",
               "cancel", "quit", "stop_all", "drain_and_stop",
               "__exit__", "__del__"}
_SUBMITTERS = {"submit", "submit_if_free"}
_EMPTY_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                "deque", "Counter"}


def _is_flight(attr: str) -> bool:
    return "flight" in attr


class _Fn:
    """Lifecycle summary of one function body (nested defs included —
    a closure's ``finally`` release counts as the function's, because
    the closure IS the ownership continuation across threads)."""

    def __init__(self, name: str, node):
        self.name = name
        self.node = node
        self.is_gen = False
        self.last_yield_line = 0
        # (recv_dotted, pair_name, node, owned, in_finally)
        self.acquires: list = []
        # (recv_dotted, release_name, node, in_finally)
        self.releases: list = []
        # (attr, node, in_finally) — self.attr += 1 / -= 1
        self.incs: list = []
        self.decs: list = []
        # (attr, node, in_finally, in_nested)
        self.inserts: list = []
        self.removals: list = []
        # self.attr = ... reassignments (attr, node)
        self.reassigns: list = []
        # attrs referenced as len(self.attr) / in a comparison bound
        self.len_refs: set = set()
        # (node, arg_names, has_lambda) — conveyor submit sites
        self.submits: list = []
        self.nested: dict = {}  # name -> FunctionDef node
        self.handlers: list = []  # ExceptHandler nodes
        # broker acquire calls missing a deadline (R006)
        self.broker_no_deadline: list = []


class _Class:
    def __init__(self, name: str, module: str, node):
        self.name = name
        self.module = module
        self.node = node
        self.methods: dict = {}      # name -> _Fn
        self.method_nodes: dict = {}  # name -> ast node
        self.attr_ctors: dict = {}   # attr -> ctor class name (init)
        self.containers: dict = {}   # attr -> init assign node
        self.spawns_thread = False
        self.self_name = "self"


class _Walk:
    """One pass over a function body, tracking the enclosing
    ``finally`` and nested-def depth."""

    def __init__(self, fn: _Fn, self_name: "str | None"):
        self.fn = fn
        self.self_name = self_name

    # -- receiver helpers --

    def _self_attr(self, expr) -> "str | None":
        """attr when ``expr`` is self.<attr> (or self.<attr>[...])."""
        base = expr
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == self.self_name:
            return base.attr
        return None

    # -- statements --

    def body(self, stmts, fin: bool, depth: int) -> None:
        for st in stmts:
            self.stmt(st, fin, depth)

    def stmt(self, st, fin: bool, depth: int) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if depth == 0:
                self.fn.nested[st.name] = st
            # a nested def has its own finally scoping
            self.body(st.body, False, depth + 1)
        elif isinstance(st, ast.Lambda):
            pass
        elif isinstance(st, ast.Try):
            self.body(st.body, fin, depth)
            for h in st.handlers:
                self.fn.handlers.append(h)
                self.body(h.body, fin, depth)
            self.body(st.orelse, fin, depth)
            self.body(st.finalbody, True, depth)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.expr(item.context_expr, fin, depth)
            self.body(st.body, fin, depth)
        elif isinstance(st, ast.Expr):
            if isinstance(st.value, ast.Call):
                self.call(st.value, fin, depth, owned=True)
            else:
                self.expr(st.value, fin, depth)
        elif isinstance(st, ast.Assign):
            for tgt in st.targets:
                self.target(tgt, st, fin, depth)
            if isinstance(st.value, ast.Call):
                owned = any(isinstance(t, ast.Name)
                            for t in st.targets)
                self.call(st.value, fin, depth, owned=owned)
            else:
                self.expr(st.value, fin, depth)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.target(st.target, st, fin, depth)
                self.expr(st.value, fin, depth)
        elif isinstance(st, ast.AugAssign):
            attr = self._self_attr(st.target)
            if attr is not None and \
                    isinstance(st.value, ast.Constant) and \
                    st.value.value == 1:
                if isinstance(st.op, ast.Add):
                    self.fn.incs.append((attr, st, fin))
                elif isinstance(st.op, ast.Sub):
                    self.fn.decs.append((attr, st, fin))
            self.expr(st.value, fin, depth)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = self._self_attr(tgt)
                    if attr is not None:
                        self.fn.removals.append(
                            (attr, st, fin, depth > 0))
        elif isinstance(st, (ast.If, ast.While)):
            self.expr(st.test, fin, depth)
            self.body(st.body, fin, depth)
            self.body(st.orelse, fin, depth)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.expr(st.iter, fin, depth)
            self.body(st.body, fin, depth)
            self.body(st.orelse, fin, depth)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.expr(st.value, fin, depth)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.stmt):
                    self.stmt(child, fin, depth)
                elif isinstance(child, ast.expr):
                    self.expr(child, fin, depth)

    def target(self, tgt, st, fin: bool, depth: int) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.target(el, st, fin, depth)
            return
        if isinstance(tgt, ast.Subscript):
            attr = self._self_attr(tgt)
            if attr is not None:
                self.fn.inserts.append((attr, st, fin, depth > 0))
            return
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and \
                tgt.value.id == self.self_name:
            self.fn.reassigns.append((tgt.attr, st))

    # -- expressions --

    def expr(self, e, fin: bool, depth: int) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self.call(e, fin, depth, owned=False)
            return
        if isinstance(e, (ast.Yield, ast.YieldFrom)):
            if depth == 0:
                self.fn.is_gen = True
                self.fn.last_yield_line = max(
                    self.fn.last_yield_line, e.lineno)
            if getattr(e, "value", None) is not None:
                self.expr(e.value, fin, depth)
            return
        if isinstance(e, ast.Lambda):
            return  # runs later; bodies checked at the submit site
        if isinstance(e, ast.Compare):
            # an ORDERING comparison involving the attr (len() or set
            # >=) is a bound/alignment check; membership (in/not in)
            # is not — a dedup test against a grow-only cache is the
            # leak, not its bound
            ordered = any(not isinstance(op, (ast.In, ast.NotIn))
                          for op in e.ops)
            for sub in [e.left] + list(e.comparators):
                if isinstance(sub, ast.Call) and \
                        _dotted(sub.func) == "len" and sub.args:
                    attr = self._self_attr(sub.args[0])
                    if attr is not None:
                        self.fn.len_refs.add(attr)
                elif ordered:
                    attr = self._self_attr(sub)
                    if attr is not None:
                        self.fn.len_refs.add(attr)
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.expr(child, fin, depth)

    def call(self, node: ast.Call, fin: bool, depth: int,
             owned: bool) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            recv = _dotted(f.value)
            name = f.attr
            if name in _PAIRS:
                self.fn.acquires.append((recv, name, node, owned, fin))
            if name in _RELEASES:
                self.fn.releases.append((recv, name, node, fin))
            attr = self._self_attr(f.value)
            if attr is not None:
                if name in _INSERTS:
                    self.fn.inserts.append((attr, node, fin, depth > 0))
                elif name in _REMOVALS:
                    self.fn.removals.append(
                        (attr, node, fin, depth > 0))
            if name == "acquire" and "broker" in recv.lower():
                has_deadline = len(node.args) >= 3 or any(
                    k.arg == "deadline" for k in node.keywords)
                if not has_deadline:
                    self.fn.broker_no_deadline.append(node)
            if name in _SUBMITTERS:
                arg_names = [a.id for a in node.args
                             if isinstance(a, ast.Name)]
                has_lambda = any(isinstance(a, ast.Lambda)
                                 for a in node.args)
                self.fn.submits.append((node, arg_names, has_lambda))
        elif isinstance(f, ast.Name):
            if f.id == "len" and node.args:
                attr = self._self_attr(node.args[0])
                if attr is not None:
                    self.fn.len_refs.add(attr)
        for a in node.args:
            self.expr(a, fin, depth)
        for k in node.keywords:
            self.expr(k.value, fin, depth)
        if isinstance(f, ast.Attribute):
            self.expr(f.value, fin, depth)
        elif not isinstance(f, ast.Name):
            self.expr(f, fin, depth)


def _scan_fn(node, self_name: "str | None") -> _Fn:
    fn = _Fn(node.name, node)
    _Walk(fn, self_name).body(node.body, False, 0)
    return fn


_CLASSES: dict = {}  # bare class name -> _Class (unique across run)
# serializes whole-analysis runs: the registry is process-global, so
# concurrent check_sources() calls must not interleave clear/register
_REG_LOCK = threading.RLock()


def _ctor_name(value) -> "str | None":
    if isinstance(value, ast.Call):
        name = _dotted(value.func).rsplit(".", 1)[-1]
        return name or None
    return None


def _scan_class(node: ast.ClassDef, modname: str) -> _Class:
    cls = _Class(node.name, modname, node)
    for st in node.body:
        if not isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        self_name = st.args.args[0].arg if st.args.args else None
        cls.method_nodes[st.name] = st
        cls.methods[st.name] = _scan_fn(st, self_name)
        if st.name in _INIT_NAMES and self_name is not None:
            _scan_init(st, self_name, cls)
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            ctor = _dotted(n.func).rsplit(".", 1)[-1]
            if ctor in ("Thread", "Timer"):
                cls.spawns_thread = True
    with _REG_LOCK:
        _CLASSES.setdefault(cls.name, cls)
    return cls


def _scan_init(node, self_name: str, cls: _Class) -> None:
    for st in ast.walk(node):
        if not isinstance(st, ast.Assign):
            continue
        for tgt in st.targets:
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == self_name):
                continue
            ctor = _ctor_name(st.value)
            if ctor is not None and ctor[:1].isupper() and \
                    ctor not in _EMPTY_CTORS:
                cls.attr_ctors.setdefault(tgt.attr, ctor)
            if isinstance(st.value, (ast.Dict, ast.List, ast.Set)) \
                    or ctor in _EMPTY_CTORS:
                cls.containers.setdefault(tgt.attr, st)


# ---------------- rules ----------------


def _release_in_finally(fn: _Fn, recv: str, names) -> bool:
    return any(r_fin for r_recv, r_name, _n, r_fin in fn.releases
               if r_recv == recv and r_name in names and r_fin)


def _has_release(fn: _Fn, recv: str, names) -> bool:
    return any(r_recv == recv and r_name in names
               for r_recv, r_name, _n, _f in fn.releases)


def _check_r001(fn: _Fn, filename: str, findings: list) -> None:
    for recv, name, node, owned, fin in fn.acquires:
        if not owned or fin:
            continue
        names = _PAIRS[name]
        if not _has_release(fn, recv, names):
            continue  # cross-function protocol — leaksan's beat
        if not _release_in_finally(fn, recv, names):
            findings.append(Finding(
                filename, node.lineno, node.col_offset, "R001",
                RULES["R001"],
                f"{recv}.{name}() has a matching"
                f" {'/'.join(names)}() in this function but never"
                " inside a finally: an exception (or early return)"
                " between them strands the resource — release in a"
                " finally or use a with-block"))


def _removal_in_finally(fn: _Fn, attr: str) -> bool:
    return any(r_fin for r_attr, _n, r_fin, _nested in fn.removals
               if r_attr == attr and r_fin)


def _check_r002(fn: _Fn, filename: str, findings: list) -> None:
    if not fn.is_gen:
        return
    for attr, node, fin, nested in fn.inserts:
        if nested or fin or not _is_flight(attr):
            continue
        if node.lineno >= fn.last_yield_line:
            continue  # registered after the last yield: no suspension
        if not _removal_in_finally(fn, attr):
            findings.append(Finding(
                filename, node.lineno, node.col_offset, "R002",
                RULES["R002"],
                f"generator registers self.{attr} before a yield with"
                " no finally removing it: a consumer abandoning the"
                " stream strands the flight and wedges every waiter —"
                " pop it in a finally around the yields"))
    for recv, name, node, owned, fin in fn.acquires:
        if not owned or fin or node.lineno >= fn.last_yield_line:
            continue
        names = _PAIRS[name]
        if not _release_in_finally(fn, recv, names):
            findings.append(Finding(
                filename, node.lineno, node.col_offset, "R002",
                RULES["R002"],
                f"generator owns {recv}.{name}() across a yield with"
                f" no finally {'/'.join(names)}(): an abandoned"
                " stream never releases it"))


def _check_r003(fn: _Fn, filename: str, findings: list) -> None:
    dec_attrs: dict = {}
    for attr, _node, fin in fn.decs:
        dec_attrs[attr] = dec_attrs.get(attr, False) or fin
    for attr, node, _fin in fn.incs:
        if attr not in dec_attrs:
            continue  # paired in another method: the pair-table's beat
        dec_lines = [d.lineno for a, d, _f in fn.decs if a == attr]
        if not any(ln > node.lineno for ln in dec_lines):
            continue  # decrement precedes: accounting, not a gauge
        if not dec_attrs[attr]:
            findings.append(Finding(
                filename, node.lineno, node.col_offset, "R003",
                RULES["R003"],
                f"self.{attr} += 1 has a later -= 1 in this method"
                " but not in a finally: an exception between them"
                " leaks the gauge — decrement in a finally"))


def _handler_names(h) -> set:
    t = h.type
    names = set()
    for e in ([t] if not isinstance(t, ast.Tuple) else t.elts) \
            if t is not None else []:
        d = _dotted(e)
        if d:
            names.add(d.rsplit(".", 1)[-1])
    return names


def _handler_propagates(h) -> bool:
    for n in ast.walk(h):
        if isinstance(n, ast.Raise):
            return True
    if h.name:
        for n in ast.walk(h):
            if isinstance(n, ast.Name) and n.id == h.name and \
                    isinstance(n.ctx, ast.Load):
                return True
    for n in ast.walk(h):
        if isinstance(n, ast.Assign):
            for tgt in n.targets:
                if isinstance(tgt, ast.Attribute) and \
                        tgt.attr in ("error", "errors"):
                    return True
        if isinstance(n, ast.Call):
            d = _dotted(n.func).lower()
            if any(w in d for w in ("error", "record", "reject",
                                    "fail", "note")):
                return True
    return False


def _check_r004(fn: _Fn, filename: str, findings: list) -> None:
    for h in fn.handlers:
        caught = _handler_names(h) & _CANCEL_EXCS
        if not caught or _handler_propagates(h):
            continue
        findings.append(Finding(
            filename, h.lineno, h.col_offset, "R004", RULES["R004"],
            f"except {'/'.join(sorted(caught))} neither re-raises nor"
            " records the error: swallowed cancellation never reaches"
            " the layers holding slots for this statement — re-raise,"
            " or store it as the task's error"))


def _stop_reachable_attrs(cls: _Class) -> set:
    """Attrs referenced from the class's stop-path methods (one level
    of self-calls deep)."""
    nodes = [n for name, n in cls.method_nodes.items()
             if name in _STOP_NAMES]
    extra = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    isinstance(sub.func.value, ast.Name) and \
                    sub.func.value.id == cls.self_name:
                extra.add(sub.func.attr)
    nodes += [cls.method_nodes[m] for m in extra
              if m in cls.method_nodes]
    refs = set()
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id == cls.self_name:
                refs.add(sub.attr)
    return refs


def _check_r005(cls: _Class, filename: str, findings: list) -> None:
    stoppable = {}
    for attr, ctor in cls.attr_ctors.items():
        target = _CLASSES.get(ctor)
        if target is None or target is cls:
            continue
        if target.spawns_thread and \
                set(target.method_nodes) & {"stop", "close",
                                            "shutdown"}:
            stoppable[attr] = ctor
    if not stoppable:
        return
    reachable = _stop_reachable_attrs(cls)
    init = cls.method_nodes.get("__init__")
    for attr, ctor in sorted(stoppable.items()):
        if attr in reachable:
            continue
        node = cls.containers.get(attr) or init or cls.node
        findings.append(Finding(
            filename, _attr_assign_line(init, attr, cls.self_name,
                                        node), 0, "R005",
            RULES["R005"],
            f"{cls.name}.{attr} holds a {ctor} (thread-owning, has a"
            " stop method) but no stop/close/shutdown path of"
            f" {cls.name} reaches it: its thread runs until process"
            " exit — add a stop path that stops the member"))


def _attr_assign_line(init, attr: str, self_name: str,
                      fallback) -> int:
    if init is not None:
        for st in ast.walk(init):
            if isinstance(st, ast.Assign):
                for tgt in st.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == self_name and \
                            tgt.attr == attr:
                        return st.lineno
    return fallback.lineno


def _check_r006(fn: _Fn, filename: str, findings: list) -> None:
    for node in fn.broker_no_deadline:
        findings.append(Finding(
            filename, node.lineno, node.col_offset, "R006",
            RULES["R006"],
            "broker acquire without deadline=: an admission wait on"
            " the statement path must observe the active Deadline"
            " (PR 12 discipline) or a cancelled statement keeps"
            " queueing for slots it will never use"))


def _check_r007(cls: _Class, filename: str, findings: list) -> None:
    inserted: dict = {}
    removed: set = set()
    bounded: set = set()
    for name, fn in cls.methods.items():
        for attr, node, _fin, _nested in fn.inserts:
            if name not in _INIT_NAMES:
                inserted.setdefault(attr, []).append(node)
        for attr, _node, _fin, _nested in fn.removals:
            removed.add(attr)
        bounded |= fn.len_refs
        if name not in _INIT_NAMES:
            for attr, _node in fn.reassigns:
                # a rebuild/reset outside __init__ bounds the growth
                removed.add(attr)
    for attr in sorted(inserted):
        if attr not in cls.containers:
            continue
        if attr in removed or attr in bounded:
            continue
        node = inserted[attr][0]
        findings.append(Finding(
            filename, node.lineno, node.col_offset, "R007",
            RULES["R007"],
            f"{cls.name}.{attr} only ever grows: inserts with no"
            " removal, rebuild or len() bound anywhere in the class —"
            " a hot path feeding it leaks without limit; cap it, evict"
            " from it, or remove entries when their owner finishes"))


def _check_r008(fn: _Fn, filename: str, findings: list) -> None:
    if not fn.submits:
        return
    for attr, node, fin, nested in fn.inserts:
        if nested or not _is_flight(attr):
            continue
        after = [s for s, _a, _l in fn.submits
                 if s.lineno >= node.lineno]
        if not after:
            continue
        if not _removal_in_finally(fn, attr):
            findings.append(Finding(
                filename, node.lineno, node.col_offset, "R008",
                RULES["R008"],
                f"self.{attr} registered before a conveyor submit with"
                " no finally releasing it (in the closure or here):"
                " the flight crossed threads with no owner responsible"
                " for its release — discard it in the task's finally"))


# ---------------- driver ----------------


def _check_module(tree, filename: str, modname: str,
                  findings: list) -> None:
    fns: list = []
    for st in tree.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fns.append((_scan_fn(st, None), None))
        elif isinstance(st, ast.ClassDef):
            cls = _scan_class(st, modname)
            for fn in cls.methods.values():
                fns.append((fn, cls))
    for fn, _cls in fns:
        _check_r001(fn, filename, findings)
        _check_r002(fn, filename, findings)
        _check_r003(fn, filename, findings)
        _check_r004(fn, filename, findings)
        _check_r006(fn, filename, findings)
        _check_r008(fn, filename, findings)
        # nested defs get the per-function rules too (their facts also
        # fold into the parent for R001/R008 ownership)
        for sub in fn.nested.values():
            sub_fn = _scan_fn(sub, None)
            _check_r002(sub_fn, filename, findings)
            _check_r004(sub_fn, filename, findings)


def check_source(src: str, filename: str = "<string>",
                 modname: "str | None" = None) -> list:
    """Analyze one source text (tests); returns unsuppressed findings."""
    return check_sources([(src, filename, modname or "m")])


def check_sources(sources) -> list:
    """Analyze (src, filename, modname) triples as ONE program (R005
    resolves member classes across modules)."""
    with _REG_LOCK:
        return _check_sources_locked(sources)


def _check_sources_locked(sources) -> list:
    with _REG_LOCK:
        _CLASSES.clear()
    findings: list = []
    trees = []
    lines_by_file: dict = {}
    for src, filename, modname in sources:
        lines = src.splitlines()
        lines_by_file[filename] = lines
        if file_skipped(lines):
            continue
        try:
            tree = ast.parse(src, filename=filename)
        except SyntaxError as e:
            findings.append(Finding(
                filename, e.lineno or 0, e.offset or 0, "R000",
                "syntax-error", str(e.msg)))
            continue
        trees.append((tree, filename, modname))
    # pass 1: register every class (R005 needs the full registry
    # before any holder is judged)
    for tree, _filename, modname in trees:
        for st in tree.body:
            if isinstance(st, ast.ClassDef):
                _scan_class(st, modname)
    # pass 2: per-module rules
    for tree, filename, modname in trees:
        _check_module(tree, filename, modname, findings)
        for st in tree.body:
            if isinstance(st, ast.ClassDef):
                cls = _CLASSES.get(st.name)
                if cls is not None and cls.node is st:
                    _check_r005(cls, filename, findings)
                    _check_r007(cls, filename, findings)
    kept = []
    for filename, lines in lines_by_file.items():
        here = [f for f in findings if f.file == filename]
        kept.extend(filter_suppressed(here, lines, RULES))
    return sorted(kept, key=lambda f: (f.file, f.line, f.col, f.code))


def check_paths(paths) -> list:
    sources = []
    for f in paths:
        sources.append((f.read_text(encoding="utf-8"), str(f), f.stem))
    return check_sources(sources)


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    files = collect_files(paths, changed=changed)
    findings = check_paths(files)
    if as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"{len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
