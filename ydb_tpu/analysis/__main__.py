"""Unified analyzer entrypoint: every pillar, one command.

    python -m ydb_tpu.analysis [path ...] [--json] [--changed]

Runs the four static pillars in order over a single shared CLI surface
(``paths.py`` collection + ``suppress.py`` pragmas):

  verify       SSA program checker self-test — the one pillar that
               checks programs, not files, so here it proves the
               checker itself is alive: a clean program must produce
               zero diagnostics and a known-bad one must be rejected
  lint         L-rules (jit hazards)            — lint.py
  concurrency  C-rules (lock/guard discipline)  — concurrency.py
  lifecycle    R-rules (acquire/release pairing) — lifecycle.py

Exit status 1 when ANY stage reports findings, so CI and builders
invoke exactly one command. Per-tool runs stay available
(``python -m ydb_tpu.analysis.lint`` etc.) for focused iteration.
"""

from __future__ import annotations

import json
import sys

from ydb_tpu.analysis import concurrency, lifecycle, lint
from ydb_tpu.analysis.paths import collect_files, parse_cli


def _verify_selftest() -> list:
    """Prove the SSA verifier accepts a clean program and rejects a
    defective one. Returns findings-shaped dicts (file/line/col/code/
    name/message) so the JSON surface matches the AST checkers."""
    from ydb_tpu import dtypes
    from ydb_tpu.analysis.verify import verify_program
    from ydb_tpu.ssa import AssignStep, Call, Col, Op, Program
    from ydb_tpu.ssa.program import lit

    sch = dtypes.schema(("a", dtypes.INT64, False))
    clean = Program((
        AssignStep("c", Call(Op.ADD, Col("a"), lit(1))),
    ))
    bad = Program((
        AssignStep("c", Call(Op.ADD, Col("nope"), lit(1))),
    ))
    out = []
    diags = verify_program(clean, sch)
    if diags:
        out.append({
            "file": "<verify-selftest>", "line": 0, "col": 0,
            "code": "V900", "name": "verify-selftest",
            "message": "clean program rejected: "
                       + "; ".join(d.code for d in diags),
        })
    if not verify_program(bad, sch):
        out.append({
            "file": "<verify-selftest>", "line": 0, "col": 0,
            "code": "V901", "name": "verify-selftest",
            "message": "defective program (unknown column) accepted",
        })
    return out


def run_all(paths=(), changed: bool = False) -> dict:
    """All four pillars over one collected file list. Returns
    ``{stage: [finding dict, ...]}`` in run order."""
    files = collect_files(list(paths), changed=changed)
    lint_findings: list = []
    for p in files:
        lint_findings.extend(
            lint.lint_source(p.read_text(encoding="utf-8"), str(p)))
    return {
        "verify": _verify_selftest(),
        "lint": [f.to_dict() for f in lint_findings],
        "concurrency": [f.to_dict()
                        for f in concurrency.check_paths(files)],
        "lifecycle": [f.to_dict()
                      for f in lifecycle.check_paths(files)],
    }


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    stages = run_all(paths, changed=changed)
    total = sum(len(v) for v in stages.values())
    if as_json:
        print(json.dumps(stages, indent=2))
        return 1 if total else 0
    for stage, findings in stages.items():
        for f in findings:
            print(f"{f['file']}:{f['line']}:{f['col']}: "
                  f"{f['code']} [{f['name']}] {f['message']}")
        print(f"{stage}: {len(findings)} finding(s)")
    print(f"total: {total} finding(s)")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
