"""Unified analyzer entrypoint: every pillar, one command.

    python -m ydb_tpu.analysis [path ...] [--json] [--changed]

Runs the six static pillars in order over a single shared CLI surface
(``paths.py`` collection + ``suppress.py`` pragmas):

  verify       SSA program checker self-test — the one pillar that
               checks programs, not files, so here it proves the
               checker itself is alive: a clean program must produce
               zero diagnostics and a known-bad one must be rejected
  lint         L-rules (jit hazards)            — lint.py
  concurrency  C-rules (lock/guard discipline)  — concurrency.py
  lifecycle    R-rules (acquire/release pairing) — lifecycle.py
  hotpath      H-rules (dispatch purity)        — hotpath.py
  devmem       M-rules (HBM provenance/budget)  — devmem.py

Exit status 1 when ANY stage reports findings, so CI and builders
invoke exactly one command. Per-tool runs stay available
(``python -m ydb_tpu.analysis.lint`` etc.) for focused iteration.
"""

from __future__ import annotations

import json
import sys

from ydb_tpu.analysis import (concurrency, devmem, hotpath, lifecycle,
                              lint)
from ydb_tpu.analysis.paths import collect_files, parse_cli


def _verify_selftest() -> list:
    """Prove the SSA verifier accepts a clean program and rejects a
    defective one. Returns findings-shaped dicts (file/line/col/code/
    name/message) so the JSON surface matches the AST checkers."""
    from ydb_tpu import dtypes
    from ydb_tpu.analysis.verify import verify_program
    from ydb_tpu.ssa import AssignStep, Call, Col, Op, Program
    from ydb_tpu.ssa.program import lit

    sch = dtypes.schema(("a", dtypes.INT64, False))
    clean = Program((
        AssignStep("c", Call(Op.ADD, Col("a"), lit(1))),
    ))
    bad = Program((
        AssignStep("c", Call(Op.ADD, Col("nope"), lit(1))),
    ))
    out = []
    diags = verify_program(clean, sch)
    if diags:
        out.append({
            "file": "<verify-selftest>", "line": 0, "col": 0,
            "code": "V900", "name": "verify-selftest",
            "message": "clean program rejected: "
                       + "; ".join(d.code for d in diags),
        })
    if not verify_program(bad, sch):
        out.append({
            "file": "<verify-selftest>", "line": 0, "col": 0,
            "code": "V901", "name": "verify-selftest",
            "message": "defective program (unknown column) accepted",
        })
    return out


def run_all(paths=(), changed: bool = False) -> dict:
    """All six pillars over one collected file list. Returns
    ``{stage: [finding dict, ...]}`` in run order."""
    files = collect_files(list(paths), changed=changed)
    lint_findings: list = []
    for p in files:
        lint_findings.extend(
            lint.lint_source(p.read_text(encoding="utf-8"), str(p)))
    # the hotpath walker is path-scoped: its call-graph index must
    # always cover the full roots — under --changed it only narrows
    # which files findings are REPORTED for, else a file subset makes
    # ambiguous methods look unique and the walk enters cold code
    hot_files = files
    hot_report = None
    if changed:
        hot_files = collect_files(list(paths))
        hot_report = {str(f) for f in files}
    return {
        "verify": _verify_selftest(),
        "lint": [f.to_dict() for f in lint_findings],
        "concurrency": [f.to_dict()
                        for f in concurrency.check_paths(files)],
        "lifecycle": [f.to_dict()
                      for f in lifecycle.check_paths(files)],
        "hotpath": [f.to_dict() for f in hotpath.check_paths(
            hot_files, report_files=hot_report)],
        # devmem is interprocedural like hotpath: same full-index /
        # narrowed-reporting split under --changed, else a charging
        # caller outside the changed set can't cover its helper
        "devmem": [f.to_dict() for f in devmem.check_paths(
            hot_files, report_files=hot_report)],
    }


def format_findings(stages: dict) -> str:
    """Readable multi-finding summary for clean-tree assertions: every
    finding on its own ``file:line:col: CODE [name] message`` line,
    grouped by stage, instead of one opaque repr of the whole dict."""
    out = []
    for stage, findings in stages.items():
        if not findings:
            continue
        out.append(f"{stage}: {len(findings)} finding(s)")
        for f in findings:
            out.append(f"  {f['file']}:{f['line']}:{f['col']}: "
                       f"{f['code']} [{f['name']}] {f['message']}")
    return "\n".join(out) if out else "no findings"


def main(argv=None) -> int:
    paths, as_json, changed = parse_cli(argv)
    stages = run_all(paths, changed=changed)
    total = sum(len(v) for v in stages.values())
    if as_json:
        print(json.dumps(stages, indent=2))
        return 1 if total else 0
    for stage, findings in stages.items():
        for f in findings:
            print(f"{f['file']}:{f['line']}:{f['col']}: "
                  f"{f['code']} [{f['name']}] {f['message']}")
        print(f"{stage}: {len(findings)} finding(s)")
    print(f"total: {total} finding(s)")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
