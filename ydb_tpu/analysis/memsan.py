"""Memory sanitizer: per-statement device-byte footprint (YDB_TPU_MEMSAN=1).

The runtime half of the device-memory pillar. ``devmem.py`` proves
statically that device arrays are only *created* inside budget-charging
seams; this sanitizer measures what those seams actually *allocate* per
statement — live and peak HBM bytes, attributed to the owning statement
(span trace-id) and to the allocation component — and enforces a
warm-statement budget: after warmup, peak device bytes within
``Budget.peak_bytes`` and **zero unbudgeted allocations**, or the
statement raises ``MemBudgetError``.

Instrumented seams (each charges its bytes explicitly):

  ``staging``   TableBlock.from_numpy / device_aux — host->device ingest
  ``resident``  ResidentStore.promote (released on eviction/clear)
  ``stack``     FusedPlan.run_stacked member stacking (released after
                the batched dispatch returns)
  ``shuffle``   repartition send/recv bucket capacity
  ``dispatch``  fused-plan output blocks

Seams wrap their device work in :func:`seam` and account the result via
:func:`charge` / :func:`release`. While armed, the raw jax allocators
(``jnp.zeros/ones/full/stack``, ``jax.device_put``) are patched to
catch CONCRETE device allocations outside any seam — those count as
*unbudgeted* (the runtime shadow of devmem rule M001). Allocations
under an active trace (tracers) are XLA temporaries, not HBM buffers,
and are ignored. ``jnp.asarray`` is syncsan's patch point (the two
sanitizers must not fight over one seam's restore order); asarray-based
staging is charged by the staging seams themselves.

Charges attribute to the active statement exactly like syncsan: the
beginning thread via a thread-local, conveyor workers via the inherited
obs span's trace id, anything else to the orphan window.
``end_statement`` annotates the obs span (``memsan_*`` attributes,
surfaced by EXPLAIN ANALYZE and ``QueryProfile.memsan``) and enforces
the budget. Component totals persist process-wide for the
``sys_device_memory`` sysview and the ``/counters/prometheus`` gauges.

Gates mirror ``leaksan.py``: ``YDB_TPU_MEMSAN=1`` env, ``set_force()``
pin, ``activate()`` context manager for tests and bench. Every entry
point is a single module-global bool check while disabled.
"""

from __future__ import annotations

import os
import threading

from ydb_tpu.obs import tracing

#: tri-state pin: None -> follow the env var; True/False -> forced
_FORCE: "bool | None" = None

_meta_lock = threading.Lock()
_tls = threading.local()


def enabled() -> bool:
    if _FORCE is not None:
        return _FORCE
    return os.environ.get("YDB_TPU_MEMSAN", "") not in ("", "0")


_ON = enabled()


def armed() -> bool:
    """Cheap inline gate for charge sites: guard ``nbytes_of`` walks
    with ``if memsan.armed():`` so the disarmed path costs one
    module-global read."""
    return _ON


class MemBudgetError(AssertionError):
    """A warm statement exceeded its device-memory budget."""


class Budget:
    __slots__ = ("peak_bytes", "warmup")

    def __init__(self, peak_bytes: "int | None" = None,
                 warmup: int = 1):
        self.peak_bytes = peak_bytes
        self.warmup = warmup


_budget: "Budget | None" = None
_warm_seen: dict = {}  # label -> statements ended (warmup tracking)


class Statement:
    """Byte ledger for one statement (one ``begin``/``end`` pair)."""

    __slots__ = ("label", "trace_id", "span", "live", "peak",
                 "charges", "unbudgeted", "unbudgeted_bytes",
                 "by_component", "_lock")

    def __init__(self, label: str, trace_id: "str | None"):
        self.label = label
        self.trace_id = trace_id
        self.span = tracing.current_span()
        self.live = 0
        self.peak = 0
        self.charges = 0
        self.unbudgeted = 0
        self.unbudgeted_bytes = 0
        self.by_component: dict = {}
        self._lock = threading.Lock()

    def note_charge(self, nbytes: int, component: str,
                    budgeted: bool = True) -> None:
        with self._lock:
            self.live += nbytes
            self.peak = max(self.peak, self.live)
            self.charges += 1
            self.by_component[component] = \
                self.by_component.get(component, 0) + nbytes
            if not budgeted:
                self.unbudgeted += 1
                self.unbudgeted_bytes += nbytes

    def note_release(self, nbytes: int) -> None:
        with self._lock:
            self.live -= nbytes

    def snapshot(self) -> dict:
        with self._lock:
            return {"live": self.live, "peak": self.peak,
                    "charges": self.charges,
                    "unbudgeted": self.unbudgeted,
                    "unbudgeted_bytes": self.unbudgeted_bytes,
                    "by_component": dict(self.by_component)}


_by_trace: dict = {}       # trace_id -> Statement
_orphans = Statement("<orphan>", None)

#: process-wide per-component ledger (sys_device_memory + prometheus):
#: component -> {live, peak, charges, releases, evictions}
_components: dict = {}
_global_live = 0
_global_peak = 0


def _resolve() -> "Statement | None":
    st = getattr(_tls, "stat", None)
    if st is not None:
        return st
    span = tracing.current_span()
    if span is not None:
        st = _by_trace.get(span.trace_id)
        if st is not None:
            return st
    return _orphans


def _component_note(component: str, *, nbytes: int = 0,
                    release: int = 0, evicted: bool = False) -> None:
    global _global_live, _global_peak
    with _meta_lock:
        c = _components.get(component)
        if c is None:
            c = _components[component] = {
                "live": 0, "peak": 0, "charges": 0, "releases": 0,
                "evictions": 0}
        if nbytes:
            c["live"] += nbytes
            c["peak"] = max(c["peak"], c["live"])
            c["charges"] += 1
            _global_live += nbytes
            _global_peak = max(_global_peak, _global_live)
        if release:
            c["live"] -= release
            c["releases"] += 1
            _global_live -= release
            if evicted:
                c["evictions"] += 1


# ---------------- charge / release ----------------


class Ticket:
    """One live charge; :func:`release` returns its bytes."""

    __slots__ = ("nbytes", "component", "owner", "stat", "closed")

    def __init__(self, nbytes: int, component: str, owner, stat):
        self.nbytes = nbytes
        self.component = component
        self.owner = owner
        self.stat = stat
        self.closed = False


def nbytes_of(tree) -> int:
    """Total device bytes across a pytree of arrays (0 for leaves
    without ``nbytes`` — lengths, treedef constants)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, "nbytes", 0) or 0)
    return total


def charge(nbytes: int, component: str,
           owner=None) -> "Ticket | None":
    """Account ``nbytes`` of device memory to the active statement and
    the process-wide component ledger. Returns None (and counts
    nothing) while the sanitizer is off; the matching free calls
    :func:`release` on whatever this returned (sites whose buffers are
    GC-owned simply never release — the bytes stay counted as the
    statement's allocation footprint, which is the budgeted quantity)."""
    if not _ON:
        return None
    nbytes = int(nbytes)
    st = _resolve()
    st.note_charge(nbytes, component, budgeted=True)
    _component_note(component, nbytes=nbytes)
    return Ticket(nbytes, component, owner, st)


def release(ticket: "Ticket | None", *, evicted: bool = False) -> None:
    """Return a charge's bytes (None-safe and idempotent, so
    disabled-path and retry call sites stay branch-free). ``evicted``
    marks budget-valve frees (the eviction column of
    ``sys_device_memory``)."""
    if ticket is None or ticket.closed:
        return
    ticket.closed = True
    ticket.stat.note_release(ticket.nbytes)
    _component_note(ticket.component, release=ticket.nbytes,
                    evicted=evicted)


# ---------------- seams + allocator patches ----------------


class _Seam:
    """Marks "inside a budget-charging seam" on this thread: patched
    allocators under it stay silent (the seam charges the authoritative
    total; wrapper-counting the constituent allocations would double
    count)."""

    __slots__ = ()

    def __enter__(self):
        _tls.seam_depth = getattr(_tls, "seam_depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.seam_depth -= 1
        return False


class _NoopSeam:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_SEAM = _Seam()
_NOOP = _NoopSeam()


def seam(component: str = "") -> "object":
    """``with memsan.seam("staging"):`` — the enclosed device-array
    construction belongs to a charging seam. One bool when off."""
    return _SEAM if _ON else _NOOP


def in_seam() -> bool:
    return getattr(_tls, "seam_depth", 0) > 0


def _note_raw(result) -> None:
    """A patched allocator produced ``result`` outside any seam: a
    concrete device allocation no budget charged — the runtime shadow
    of devmem M001."""
    if not _ON or in_seam():
        return
    try:
        import jax

        if isinstance(result, jax.core.Tracer):
            return  # abstract value under trace: not an HBM buffer
        nbytes = int(getattr(result, "nbytes", 0) or 0)
    except Exception:
        return
    if not nbytes:
        return
    st = _resolve()
    st.note_charge(nbytes, "unbudgeted", budgeted=False)
    _component_note("unbudgeted", nbytes=nbytes)


_patched = False
_orig: dict = {}

#: patched allocator set — deliberately DISJOINT from syncsan's patch
#: set (jnp.asarray / np.asarray / device_get / block_until_ready):
#: overlapping patches restore in undefined order when both sanitizers
#: disarm, leaving a stale wrapper installed
_PATCH = ("zeros", "ones", "full", "stack")


def _install() -> None:
    global _patched
    try:
        import jax
        import jax.numpy as jnp
    except Exception:
        return

    def _wrap(orig):
        def alloc(*args, **kwargs):
            r = orig(*args, **kwargs)
            _note_raw(r)
            return r
        return alloc

    def device_put(x, *args, **kwargs):
        r = _orig["device_put"](x, *args, **kwargs)
        _note_raw(r)
        return r

    with _meta_lock:
        if _patched:
            return
        for name in _PATCH:
            _orig[name] = getattr(jnp, name)
            setattr(jnp, name, _wrap(_orig[name]))
        _orig["device_put"] = jax.device_put
        jax.device_put = device_put
        _patched = True


def _uninstall() -> None:
    global _patched
    import jax
    import jax.numpy as jnp

    with _meta_lock:
        if not _patched:
            return
        for name in _PATCH:
            setattr(jnp, name, _orig[name])
        jax.device_put = _orig["device_put"]
        _patched = False


# ---------------- gates (leaksan idiom) ----------------


def refresh() -> None:
    """Re-read the gate; arm or disarm the allocator patches to match."""
    global _ON
    with _meta_lock:
        _ON = enabled()
        on = _ON
    if on:
        _install()
    else:
        _uninstall()


def set_force(value: "bool | None") -> None:
    """Pin the sanitizer on/off regardless of the env (tests, bench);
    ``None`` returns control to ``YDB_TPU_MEMSAN``."""
    global _FORCE
    with _meta_lock:
        _FORCE = value
    refresh()


# honor an env set before import
if _ON:
    refresh()


# ---------------- statement lifecycle ----------------


def begin_statement(label: str,
                    trace_id: "str | None" = None,
                    span=None) -> "Statement | None":
    """Open a byte ledger for one statement. Returns None (and counts
    nothing) while the sanitizer is off. ``span`` pins the obs span the
    ledger annotates at close — callers opening the window BEFORE
    activating their root span (the session statement path) must pass
    it (the syncsan rule)."""
    if not _ON:
        return None
    st = Statement(label, trace_id)
    if span is not None:
        st.span = span
    _tls.stat = st
    if trace_id is not None:
        with _meta_lock:
            _by_trace[trace_id] = st
    return st


def _close(st: "Statement | None") -> None:
    if getattr(_tls, "stat", None) is st:
        _tls.stat = None
    if st is not None and st.trace_id is not None:
        with _meta_lock:
            _by_trace.pop(st.trace_id, None)


def discard(st: "Statement | None") -> None:
    """Drop a window without budget enforcement (error paths)."""
    _close(st)


def end_statement(st: "Statement | None", *,
                  enforce: bool = True) -> "dict | None":
    """Close the ledger: annotate the obs span with ``memsan_*``
    attributes and enforce the warm budget. Returns the byte snapshot
    (None while disabled)."""
    if st is None:
        return None
    _close(st)
    snap = st.snapshot()
    if st.span is not None:
        st.span.set(memsan_peak=snap["peak"], memsan_live=snap["live"],
                    memsan_charges=snap["charges"],
                    memsan_unbudgeted=snap["unbudgeted"])
    if enforce and _budget is not None:
        with _meta_lock:
            seen = _warm_seen.get(st.label, 0)
            _warm_seen[st.label] = seen + 1
        if seen >= _budget.warmup:
            if snap["unbudgeted"]:
                raise MemBudgetError(
                    f"statement {st.label!r} made"
                    f" {snap['unbudgeted']} device allocation(s)"
                    f" ({snap['unbudgeted_bytes']} bytes) outside any"
                    " budget-charging seam on the warm path; route the"
                    " allocation through a memsan seam or annotate the"
                    " site @analysis.budget_ok (devmem M001)")
            if _budget.peak_bytes is not None and \
                    snap["peak"] > _budget.peak_bytes:
                raise MemBudgetError(
                    f"statement {st.label!r} peaked at"
                    f" {snap['peak']} device bytes"
                    f" (budget {_budget.peak_bytes}); per-component:"
                    f" {snap['by_component']}")
    return snap


def set_budget(peak_bytes: "int | None" = None,
               warmup: int = 1) -> None:
    """Arm the warm-statement budget: statements past ``warmup`` (per
    label) must stay within ``peak_bytes`` and make zero unbudgeted
    allocations."""
    global _budget
    with _meta_lock:
        _budget = (peak_bytes if isinstance(peak_bytes, Budget)
                   else Budget(peak_bytes=peak_bytes, warmup=warmup))
        _warm_seen.clear()


def clear_budget() -> None:
    global _budget
    with _meta_lock:
        _budget = None
        _warm_seen.clear()


# ---------------- surfaces ----------------


def totals() -> dict:
    """Aggregate ledger across live windows + orphans (bench)."""
    agg = _orphans.snapshot()
    agg.pop("by_component", None)
    with _meta_lock:
        stats = list(_by_trace.values())
    for st in stats:
        snap = st.snapshot()
        for k in ("live", "peak", "charges", "unbudgeted",
                  "unbudgeted_bytes"):
            agg[k] += snap[k]
    return agg


def component_totals() -> dict:
    """Process-wide per-component byte ledger (the sys_device_memory
    rows and the run_background devmem counters). Empty when nothing
    was ever charged."""
    with _meta_lock:
        return {k: dict(v) for k, v in _components.items()}


def global_peak() -> int:
    """Process-wide peak live device bytes across all components (the
    /counters/prometheus gauge)."""
    with _meta_lock:
        return _global_peak


def budget_bytes() -> "int | None":
    """The armed per-statement peak budget, if any (sysview column)."""
    b = _budget
    return b.peak_bytes if b is not None else None


def reset() -> None:
    """Drop all windows, budgets, component ledgers and orphan counts
    (tests)."""
    global _orphans, _global_live, _global_peak
    with _meta_lock:
        _by_trace.clear()
        _warm_seen.clear()
        _components.clear()
        _global_live = 0
        _global_peak = 0
        _orphans = Statement("<orphan>", None)
    _tls.stat = None


class activate:
    """``with memsan.activate():`` — force the sanitizer on for a scope
    regardless of the env var, starting from a clean ledger."""

    def __init__(self, budget: "Budget | None" = None):
        self._budget = budget

    def __enter__(self):
        reset()
        set_force(True)
        if self._budget is not None:
            set_budget(peak_bytes=self._budget.peak_bytes,
                       warmup=self._budget.warmup)
        return self

    def __exit__(self, *exc):
        clear_budget()
        set_force(None)
        reset()
        return False
