"""gRPC API layer tests: query/scheme/topic/discovery services over a
real gRPC server + client SDK, auth tickets, CLI workload runner
(SURVEY.md §2.12, layer 9)."""

import pyarrow as pa
import pytest

from ydb_tpu.api.client import ApiError, Driver
from ydb_tpu.api.server import make_server
from ydb_tpu.kqp.session import Cluster


@pytest.fixture
def served():
    cluster = Cluster()
    server, port = make_server(cluster, port=0)
    server.start()
    driver = Driver(f"127.0.0.1:{port}")
    yield cluster, driver
    driver.close()
    server.stop(0)


def test_query_service_end_to_end(served):
    _cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE t (id int64, name string, amount "
              "decimal(10,2), d date, PRIMARY KEY (id))")
    step, committed = q.execute(
        "INSERT INTO t VALUES (1, 'ann', 12.50, date '2026-01-05'), "
        "(2, 'bob', 0.75, date '2026-02-06'), (3, NULL, NULL, NULL)")
    assert committed
    out = q.execute("SELECT id, name, amount, d FROM t ORDER BY id")
    assert isinstance(out, pa.Table)
    assert out.column("id").to_pylist() == [1, 2, 3]
    assert out.column("name").to_pylist() == ["ann", "bob", None]
    import decimal

    assert out.column("amount").to_pylist() == [
        decimal.Decimal("12.50"), decimal.Decimal("0.75"), None]
    assert str(out.column("d").to_pylist()[0]) == "2026-01-05"
    with pytest.raises(ApiError):
        q.execute("SELECT nope FROM t")


def test_scheme_service(served):
    _cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE users (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 3)")
    sc = driver.scheme_client()
    entries = sc.list_directory("/")
    assert ("/users", "table") in entries
    d = sc.describe_table("/users")
    assert d.store == "row" and d.shards == 3
    assert list(d.primary_key) == ["id"]
    with pytest.raises(ApiError):
        sc.describe_table("/missing")


def test_topic_service(served):
    cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, changefeed = on)")
    q.execute("INSERT INTO t VALUES (7)")
    cluster.run_background()
    tc = driver.topic_client()
    msgs = tc.read("t_changefeed", "app")
    assert len(msgs) == 1
    p, off, data = msgs[0]
    assert b'"key": [7]' in data or b'"key":[7]' in data
    tc.commit("t_changefeed", "app", p, off)
    assert tc.read("t_changefeed", "app") == []
    # direct topic write
    p2, off2 = tc.write("t_changefeed", "hello", key="k")
    assert off2 >= 0
    with pytest.raises(ApiError):
        tc.write("missing", "x")


def test_discovery(served):
    _cluster, driver = served
    eps = driver.discovery()
    assert len(eps) == 1 and eps[0][0] == "127.0.0.1"


def test_auth_tickets():
    cluster = Cluster()
    server, port = make_server(cluster, port=0,
                               auth_tokens={"secret-token"})
    server.start()
    try:
        import grpc

        bad = Driver(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError):
            bad.query_client()
        bad.close()
        good = Driver(f"127.0.0.1:{port}", auth_token="secret-token")
        q = good.query_client()
        q.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
        good.close()
    finally:
        server.stop(0)


def test_workload_runner_smoke():
    from ydb_tpu.workload.runner import run_tpch

    results = run_tpch(sf=0.002, queries=["q1", "q6"], iterations=1)
    assert [r[0] for r in results] == ["q1", "q6"]
    assert all(r[1] > 0 for r in results)
    assert results[0][2] > 0  # q1 returns groups


def test_cli_parser_smoke():
    from ydb_tpu import cli

    ap_error = False
    try:
        cli.main(["scheme"])  # missing subcommand
    except SystemExit as e:
        ap_error = e.code != 0
    assert ap_error


def test_string_alias_decodes_correctly(served):
    _cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE t (id int64, name string, PRIMARY KEY (id))")
    q.execute("INSERT INTO t VALUES (1, 'ann')")
    out = q.execute("SELECT name AS n FROM t")
    assert out.column("n").to_pylist() == ["ann"]
    q.close()


def test_session_lifecycle_and_commit_validation(served):
    _cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, changefeed = on)")
    q.close()
    tc = driver.topic_client()
    with pytest.raises(ApiError):
        tc.commit("t_changefeed", "c", -1, 0)
    with pytest.raises(ApiError):
        tc.commit("t_changefeed", "c", 99, 0)


def test_topic_streaming_sessions(served):
    """Streaming write + read sessions (SURVEY §2.13 gRPC topic-session
    row; persqueue_v1 stream sessions)."""
    cluster, driver = served
    q = driver.query_client()
    q.execute("CREATE TABLE st (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, changefeed = on)")
    tc = driver.topic_client()

    # streaming writes: one ack per item, producer seqno dedup holds
    acks = tc.stream_write(
        "st_changefeed",
        [(f"m{i}".encode(), "", "prod-1", i + 1) for i in range(5)],
    )
    assert len(acks) == 5
    tc.stream_write("st_changefeed", [(b"m0", "", "prod-1", 1)])
    # the duplicate seqno was swallowed: still exactly five messages

    # streaming read with auto-commit: exactly the five messages, then
    # the idle timeout ends the stream
    got = list(tc.stream_read("st_changefeed", "sapp",
                              idle_timeout_ms=300))
    assert sorted(d for _, _, d in got) == [
        f"m{i}".encode() for i in range(5)]
    # offsets were committed: a new session sees nothing
    assert list(tc.stream_read("st_changefeed", "sapp",
                               idle_timeout_ms=200)) == []
    # without auto-commit nothing advances durably
    got2 = list(tc.stream_read("st_changefeed", "s2",
                               auto_commit=False, idle_timeout_ms=200))
    assert len(got2) == 5
    got3 = list(tc.stream_read("st_changefeed", "s2",
                               auto_commit=False, idle_timeout_ms=200))
    assert len(got3) == 5


def test_export_import_service_roundtrip():
    """Export/Import gRPC service (ydb_export/ydb_import analog,
    VERDICT r4 item 9): snapshot a table into the cluster store via the
    SDK, import it back as a NEW resharded cluster table with string
    ids remapped into the shared dictionary set."""
    from ydb_tpu.api.client import ApiError, Driver
    from ydb_tpu.api.server import make_server
    from ydb_tpu.kqp.session import Cluster

    c = Cluster()
    srv, port = make_server(c, 0)
    srv.start()
    try:
        d = Driver(f"127.0.0.1:{port}")
        q = d.query_client()
        q.execute("CREATE TABLE inv (id int64, name text, qty int64, "
                  "PRIMARY KEY (id)) WITH (shards = 2)")
        q.execute("INSERT INTO inv VALUES (1, 'bolt', 10), "
                  "(2, 'nut', 20), (3, 'washer', 30)")
        ex = d.export_client()
        man = ex.export_table("inv", "inv_snap")
        assert man["rows"] == 3 and man["parts"] >= 1
        # a write AFTER the snapshot must not appear in the restore
        q.execute("INSERT INTO inv VALUES (4, 'screw', 40)")
        assert ex.import_table("inv_snap", table="inv2", shards=3) == 3
        out = q.execute("SELECT i.name AS n, i.qty AS v FROM inv2 i "
                        "ORDER BY v")
        assert out.to_pydict() == {"n": ["bolt", "nut", "washer"],
                                   "v": [10, 20, 30]}
        assert ("inv_snap", 3, 1) in [
            (n, r, s) for n, r, s in ex.list_backups()]
        # joins across original + restored prove the shared-dict remap
        out2 = q.execute(
            "SELECT a.name AS n FROM inv a JOIN inv2 b "
            "ON a.name = b.name WHERE b.qty = 20")
        assert out2.to_pydict()["n"] == ["nut"]
        import pytest as _pytest

        with _pytest.raises(ApiError):
            ex.import_table("inv_snap", table="inv2")  # exists
        with _pytest.raises(ApiError):
            ex.export_table("nope")
    finally:
        srv.stop(0)


def test_rate_limiter_service():
    """RateLimiter gRPC service over runtime.quoter (kesus token
    buckets): create/acquire/deplete/refill/describe via the SDK."""
    import time

    from ydb_tpu.api.client import ApiError, Driver
    from ydb_tpu.api.server import make_server
    from ydb_tpu.kqp.session import Cluster

    c = Cluster()
    srv, port = make_server(c, 0)
    srv.start()
    try:
        d = Driver(f"127.0.0.1:{port}")
        rl = d.rate_limiter_client()
        rl.create_resource("api/read", rate=50.0, burst=2.0)
        assert rl.acquire("api/read")[0]
        assert rl.acquire("api/read")[0]
        ok, retry = rl.acquire("api/read")
        assert not ok and retry > 0
        time.sleep(0.1)  # rate 50/s refills ~5 tokens
        assert rl.acquire("api/read")[0]
        desc = rl.describe_resource("api/read")
        assert desc["rate"] == 50.0 and desc["burst"] == 2.0
        import pytest as _pytest

        with _pytest.raises(ApiError):
            rl.acquire("api/missing")
        with _pytest.raises(ApiError):
            rl.create_resource("bad", rate=0.0)
    finally:
        srv.stop(0)


def test_monitoring_coordination_cms_auth_services():
    """Four more reference gRPC services (10 of 17): Monitoring health,
    Coordination (kesus sessions + counting semaphores with
    contention), Cms dynamic config (versioned, stale-version refusal),
    and Auth WhoAmI on open and token-authenticated clusters."""
    from ydb_tpu.api.client import Driver
    from ydb_tpu.api.server import make_server, pb
    from ydb_tpu.kqp.session import Cluster

    srv, port = make_server(Cluster(), 0)
    srv.start()
    try:
        d = Driver(f"127.0.0.1:{port}")
        h = d._call("/ydb_tpu.Monitoring/HealthCheck",
                    pb.HealthCheckRequest(), pb.HealthCheckResponse)
        assert h.status == "GOOD"
        mk = pb.CoordSemaphoreRequest
        s1 = d._call("/ydb_tpu.Coordination/CreateSession",
                     pb.CoordSessionRequest(),
                     pb.CoordSessionResponse).session_id
        s2 = d._call("/ydb_tpu.Coordination/CreateSession",
                     pb.CoordSessionRequest(),
                     pb.CoordSessionResponse).session_id
        d._call("/ydb_tpu.Coordination/CreateSemaphore",
                mk(name="lock", limit=1), pb.CoordSemaphoreResponse)
        acq = "/ydb_tpu.Coordination/AcquireSemaphore"
        assert d._call(acq, mk(session_id=s1, name="lock", count=1),
                       pb.CoordSemaphoreResponse).acquired
        assert not d._call(acq, mk(session_id=s2, name="lock", count=1),
                           pb.CoordSemaphoreResponse).acquired
        desc = d._call("/ydb_tpu.Coordination/DescribeSemaphore",
                       mk(name="lock"), pb.CoordSemaphoreResponse)
        assert desc.count == 1 and desc.limit == 1
        d._call("/ydb_tpu.Coordination/ReleaseSemaphore",
                mk(session_id=s1, name="lock"),
                pb.CoordSemaphoreResponse)
        assert d._call(acq, mk(session_id=s2, name="lock", count=1),
                       pb.CoordSemaphoreResponse).acquired
        v = d._call("/ydb_tpu.Cms/SetConfig",
                    pb.SetConfigRequest(yaml="n_shards: 8",
                                        expect_version=-1),
                    pb.SetConfigResponse)
        assert not v.error and v.version == 1
        g = d._call("/ydb_tpu.Cms/GetConfig", pb.GetConfigRequest(),
                    pb.GetConfigResponse)
        assert g.yaml.strip() == "n_shards: 8" and g.version == 1
        stale = d._call("/ydb_tpu.Cms/SetConfig",
                        pb.SetConfigRequest(yaml="n_shards: 2",
                                            expect_version=0),
                        pb.SetConfigResponse)
        assert stale.error  # optimistic version check
        w = d._call("/ydb_tpu.Auth/WhoAmI", pb.WhoAmIRequest(),
                    pb.WhoAmIResponse)
        assert not w.authenticated
    finally:
        srv.stop(0)

    srv2, port2 = make_server(Cluster(), 0, auth_tokens={"tok1"})
    srv2.start()
    try:
        d2 = Driver(f"127.0.0.1:{port2}", auth_token="tok1")
        w2 = d2._call("/ydb_tpu.Auth/WhoAmI", pb.WhoAmIRequest(),
                      pb.WhoAmIResponse)
        assert w2.authenticated and w2.user == "tok1"
    finally:
        srv2.stop(0)


def test_operation_service_async_export():
    """Operation service (11th of 17; ydb_operation analog): async
    export returns an operation id immediately; polling reaches ready
    with the result; list shows it; cancel forgets finished ops and
    refuses unknown ids."""
    import time

    from ydb_tpu.api.client import Driver
    from ydb_tpu.api.server import make_server, pb
    from ydb_tpu.kqp.session import Cluster

    srv, port = make_server(Cluster(), 0)
    srv.start()
    try:
        d = Driver(f"127.0.0.1:{port}")
        q = d.query_client()
        q.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id))")
        q.execute("INSERT INTO t VALUES (1, 1), (2, 2)")
        resp = d._call("/ydb_tpu.Export/ExportBackup",
                       pb.ExportRequest(table="t", name="snap",
                                        async_op=True),
                       pb.ExportResponse)
        assert resp.operation_id and not resp.error
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = d._call("/ydb_tpu.Operation/GetOperation",
                         pb.GetOperationRequest(id=resp.operation_id),
                         pb.OperationStatus)
            if st.ready:
                break
            time.sleep(0.02)
        assert st.ready and not st.error and st.rows == 2
        lst = d._call("/ydb_tpu.Operation/ListOperations",
                      pb.ListOperationsRequest(),
                      pb.ListOperationsResponse)
        assert any(o.id == resp.operation_id for o in lst.operations)
        # async failure surfaces on poll, not as an RPC error
        bad = d._call("/ydb_tpu.Export/ExportBackup",
                      pb.ExportRequest(table="nope", async_op=True),
                      pb.ExportResponse)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st2 = d._call("/ydb_tpu.Operation/GetOperation",
                          pb.GetOperationRequest(id=bad.operation_id),
                          pb.OperationStatus)
            if st2.ready:
                break
            time.sleep(0.02)
        assert st2.ready and "unknown table" in st2.error
        # cancel: forgets finished, refuses unknown
        gone = d._call("/ydb_tpu.Operation/CancelOperation",
                       pb.CancelOperationRequest(id=resp.operation_id),
                       pb.OperationStatus)
        assert not gone.error
        miss = d._call("/ydb_tpu.Operation/GetOperation",
                       pb.GetOperationRequest(id=resp.operation_id),
                       pb.OperationStatus)
        assert miss.error == "unknown operation"
    finally:
        srv.stop(0)


def test_scripting_service():
    """Scripting service (12th of 17): a multi-statement script runs in
    one session, aborts at the first error with per-statement status,
    and returns the final SELECT as arrow IPC."""
    from ydb_tpu.api.arrow_io import ipc_to_table
    from ydb_tpu.api.client import Driver
    from ydb_tpu.api.server import make_server, pb
    from ydb_tpu.kqp.session import Cluster

    srv, port = make_server(Cluster(), 0)
    srv.start()
    try:
        d = Driver(f"127.0.0.1:{port}")
        r = d._call("/ydb_tpu.Scripting/ExecuteScript",
                    pb.ExecuteScriptRequest(script=(
                        "CREATE TABLE t (id int64, v int64, "
                        "PRIMARY KEY (id)); "
                        "INSERT INTO t VALUES (1, 10), (2, 20); "
                        "SELECT t.v AS v FROM t ORDER BY v")),
                    pb.ExecuteScriptResponse)
        assert not r.error and len(r.statements) == 3
        assert ipc_to_table(r.last_result_ipc).to_pydict() == {
            "v": [10, 20]}
        bad = d._call("/ydb_tpu.Scripting/ExecuteScript",
                      pb.ExecuteScriptRequest(script=(
                          "INSERT INTO t VALUES (3, 30); "
                          "SELECT nope FROM t; "
                          "INSERT INTO t VALUES (4, 40)")),
                      pb.ExecuteScriptResponse)
        assert bad.error and len(bad.statements) == 2  # aborted at 2nd
        out = d.query_client().execute("SELECT COUNT(*) AS n FROM t")
        assert out.to_pydict()["n"] == [3]  # 3rd stmt never ran
    finally:
        srv.stop(0)
