"""DQ data plane on the wire: two real OS processes, a TPC-H join
planned from SQL, scan stages in the parent, join + final stages in the
worker — channel data (and its credit-flow acks) crosses the TCP
interconnect, and killing the worker mid-query fails the query with a
clean error instead of a hang (VERDICT r4 item 3; reference
dq_compute_actor_channels.h:15, kqp_node_service.cpp:55)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ydb_tpu.dq.node_service import DistExecuter
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.kqp.dq_lower import partition_source, plan_to_stages
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.runtime.actors import ActorId, ActorSystem
from ydb_tpu.runtime.interconnect import Interconnect
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from ydb_tpu.dq.node_service import DqNodeService
from ydb_tpu.runtime.actors import ActorSystem
from ydb_tpu.runtime.interconnect import Interconnect

port_file = sys.argv[1]
system = ActorSystem(node=2)
ic = Interconnect(system, listen_port=0)
system.register(DqNodeService(ic))  # ActorId(2, 1)
with open(port_file + ".tmp", "w") as f:
    f.write(str(ic.port))
import os
os.replace(port_file + ".tmp", port_file)
ic.serve()
"""


def _spawn_worker(port_file):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.Popen([sys.executable, "-c", WORKER, str(port_file)],
                            env=env)
    deadline = time.monotonic() + 60
    while not os.path.exists(str(port_file)):
        if proc.poll() is not None:
            raise RuntimeError("worker died during startup")
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError("worker did not report a port")
        time.sleep(0.02)
    with open(port_file) as f:
        return proc, int(f.read())


def _remote_placement(stages):
    """Join stages and the final transform run on the worker; scans stay
    with the data in the parent — so every shuffle crosses the wire."""
    placement = {}
    for si, s in enumerate(stages):
        if s.join is not None or si == len(stages) - 1:
            placement[si] = 2
    return placement


@pytest.fixture
def parent_node():
    system = ActorSystem(node=1)
    ic = Interconnect(system, listen_port=0)
    yield system, ic
    ic.close()


def test_tpch_join_shuffles_across_processes(tmp_path, parent_node):
    system, ic = parent_node
    proc, port = _spawn_worker(tmp_path / "port")
    try:
        ic.add_peer(2, "127.0.0.1", port)
        data = tpch.TpchData(sf=0.004, seed=23)
        catalog = Catalog(
            schemas={t: data.schema(t) for t in data.tables},
            primary_keys=dict(tpch.PRIMARY_KEYS),
            dicts=data.dicts,
        )
        plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
        stages = plan_to_stages(plan, n_tasks=2)
        placement = _remote_placement(stages)
        assert placement, "q3 must have remote-placed join stages"
        sources = {
            t: partition_source(
                ColumnSource(cols, data.schema(t), data.dicts), 2)
            for t, cols in data.tables.items()
        }
        ex = DistExecuter(system, services={2: ActorId(2, 1)},
                          pump=lambda: ic.pump(0.05))
        res = ex.run(stages, sources, placement, dicts=data.dicts,
                     block_rows=1 << 12, timeout=180.0)

        db = Database(
            sources={
                t: ColumnSource(cols, data.schema(t), data.dicts)
                for t, cols in data.tables.items()
            },
            dicts=data.dicts,
        )
        ref = to_host(execute_plan(plan, db, use_dq=False))
        assert res.num_rows == ref.num_rows
        for c in ("l_orderkey", "revenue", "o_orderdate"):
            np.testing.assert_array_equal(
                np.asarray(res.cols[c][0]), np.asarray(ref.cols[c][0]),
                err_msg=c)
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGKILL)
        proc.wait()


def test_three_nodes_worker_to_worker_shuffle(tmp_path, parent_node):
    """Scan stages SHIPPED to worker 3 (host partitions travel in
    StartTasks), join stages on worker 2 — the shuffle flows worker-to-
    worker on routes the executer's address book taught (StartTasks.peers;
    the hello handshake alone only teaches the executer's reverse route)."""
    system, ic = parent_node
    p2, port2 = _spawn_worker(tmp_path / "p2")
    p3, port3 = _spawn_worker2(tmp_path / "p3", node=3)
    try:
        ic.add_peer(2, "127.0.0.1", port2)
        ic.add_peer(3, "127.0.0.1", port3)
        n = 30_000
        rng = np.random.default_rng(3)
        import ydb_tpu.dtypes as dtypes

        ta = {"k": rng.integers(0, 1000, n).astype(np.int64),
              "v": rng.integers(0, 50, n).astype(np.int64)}
        tb = {"k": np.arange(1000, dtype=np.int64),
              "w": (np.arange(1000) % 7).astype(np.int64)}
        sa = dtypes.Schema((dtypes.Field("k", dtypes.INT64),
                            dtypes.Field("v", dtypes.INT64)))
        sb = dtypes.Schema((dtypes.Field("k", dtypes.INT64),
                            dtypes.Field("w", dtypes.INT64)))
        catalog = Catalog(schemas={"ta": sa, "tb": sb}, primary_keys={})
        plan = plan_select_full(parse(
            "SELECT b.w AS w, SUM(a.v) AS s FROM ta a JOIN tb b "
            "ON a.k = b.k GROUP BY b.w ORDER BY w"), catalog).plan
        stages = plan_to_stages(plan, n_tasks=3)
        from ydb_tpu.dq.graph import SourceInput

        placement = {}
        for si, s in enumerate(stages):
            if any(isinstance(i, SourceInput) for i in s.inputs):
                placement[si] = 3
            elif s.join is not None:
                placement[si] = 2
        sources = {"ta": partition_source(ColumnSource(ta, sa), 3),
                   "tb": partition_source(ColumnSource(tb, sb), 3)}
        ex = DistExecuter(system,
                          services={2: ActorId(2, 1), 3: ActorId(3, 1)},
                          pump=lambda: ic.pump(0.05),
                          peers=dict(ic.peers))
        res = ex.run(stages, sources, placement, block_rows=1024,
                     timeout=180.0)
        ref = to_host(execute_plan(plan, Database(
            sources={"ta": ColumnSource(ta, sa),
                     "tb": ColumnSource(tb, sb)}), use_dq=False))
        np.testing.assert_array_equal(
            np.asarray(res.cols["w"][0]), np.asarray(ref.cols["w"][0]))
        np.testing.assert_array_equal(
            np.asarray(res.cols["s"][0]), np.asarray(ref.cols["s"][0]))
    finally:
        for p in (p2, p3):
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
            p.wait()


def _spawn_worker2(port_file, node):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         WORKER.replace("ActorSystem(node=2)", f"ActorSystem(node={node})"),
         str(port_file)],
        env=env)
    deadline = time.monotonic() + 60
    while not os.path.exists(str(port_file)):
        if proc.poll() is not None:
            raise RuntimeError("worker died during startup")
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError("worker did not report a port")
        time.sleep(0.02)
    with open(port_file) as f:
        return proc, int(f.read())


def test_worker_death_mid_query_aborts_cleanly(tmp_path, parent_node):
    system, ic = parent_node
    proc, port = _spawn_worker(tmp_path / "port")
    ic.add_peer(2, "127.0.0.1", port)
    n = 50_000
    rng = np.random.default_rng(7)
    ta = {"k": rng.integers(0, 5_000, n).astype(np.int64),
          "v": rng.integers(0, 100, n).astype(np.int64)}
    tb = {"k": np.arange(5_000, dtype=np.int64),
          "w": rng.integers(0, 10, 5_000).astype(np.int64)}
    import ydb_tpu.dtypes as dtypes

    sa = dtypes.Schema((dtypes.Field("k", dtypes.INT64),
                        dtypes.Field("v", dtypes.INT64)))
    sb = dtypes.Schema((dtypes.Field("k", dtypes.INT64),
                        dtypes.Field("w", dtypes.INT64)))
    catalog = Catalog(schemas={"ta": sa, "tb": sb}, primary_keys={})
    plan = plan_select_full(parse(
        "SELECT b.w AS w, SUM(a.v) AS s FROM ta a JOIN tb b "
        "ON a.k = b.k GROUP BY b.w ORDER BY w"), catalog).plan
    stages = plan_to_stages(plan, n_tasks=2)
    placement = _remote_placement(stages)
    sources = {"ta": partition_source(ColumnSource(ta, sa), 2),
               "tb": partition_source(ColumnSource(tb, sb), 2)}

    pumps = [0]

    def pump():
        pumps[0] += 1
        if pumps[0] == 8 and proc.poll() is None:
            proc.send_signal(signal.SIGKILL)  # mid-query worker death
            proc.wait()
        ic.pump(0.05)

    ex = DistExecuter(system, services={2: ActorId(2, 1)}, pump=pump)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="aborted|unreachable"):
        ex.run(stages, sources, placement, block_rows=256, timeout=120.0)
    # clean FAST failure (liveness ping / undelivered channel data), not
    # a run to the 120s deadline
    assert time.monotonic() - t0 < 60
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
