"""SQL sequences: CREATE SEQUENCE / nextval defaults in INSERT /
DROP SEQUENCE, durable across reboot (reference: tx/sequenceshard +
the kqp sequencer filling sequence defaults)."""

import pytest

from ydb_tpu.kqp.session import Cluster, PlanError


def test_create_and_nextval_in_insert():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id))")
    s.execute("CREATE SEQUENCE ids START 100 CACHE 5")
    s.execute("INSERT INTO t VALUES (nextval('ids'), 1), "
              "(nextval('ids'), 2)")
    s.execute("INSERT INTO t VALUES (nextval('ids'), 3)")
    out = s.execute("SELECT id, v FROM t ORDER BY id")
    assert [int(x) for x in out.column("id")] == [100, 101, 102]

    # duplicate create fails; unknown sequence fails
    with pytest.raises(Exception):
        s.execute("CREATE SEQUENCE ids")
    with pytest.raises(KeyError):
        s.execute("INSERT INTO t VALUES (nextval('nope'), 0)")
    with pytest.raises(PlanError, match="literal"):
        s.execute("INSERT INTO t VALUES (nextval(id), 0)")
    with pytest.raises(PlanError, match="literal"):
        s.execute("INSERT INTO t VALUES (nextval(), 0)")
    with pytest.raises(ValueError, match="cache"):
        s.execute("CREATE SEQUENCE bad CACHE 0")


def test_concurrent_nextval_never_duplicates():
    import threading

    c = Cluster()
    s = c.session()
    s.execute("CREATE SEQUENCE cs START 1 CACHE 3")
    got = []
    lock = threading.Lock()

    def worker():
        for _ in range(25):
            v = c.sequences.next_val("cs")
            with lock:
                got.append(v)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert len(got) == 100 and len(set(got)) == 100


def test_sequence_survives_reboot_without_repeats():
    store = None
    c = Cluster()
    store = c.store
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    s.execute("CREATE SEQUENCE sq START 1 CACHE 4")
    s.execute("INSERT INTO t VALUES (nextval('sq')), (nextval('sq'))")

    c2 = Cluster(store=store)  # reboot: cached range burned
    s2 = c2.session()
    s2.execute("INSERT INTO t VALUES (nextval('sq'))")
    out = s2.execute("SELECT id FROM t ORDER BY id")
    ids = [int(x) for x in out.column("id")]
    assert ids[0:2] == [1, 2]
    assert ids[2] >= 5  # next durable range; never a repeat
    assert len(set(ids)) == 3


def test_drop_sequence():
    c = Cluster()
    s = c.session()
    s.execute("CREATE SEQUENCE gone")
    s.execute("DROP SEQUENCE gone")
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    with pytest.raises(KeyError):
        s.execute("INSERT INTO t VALUES (nextval('gone'))")
    s.execute("CREATE SEQUENCE gone START 7")  # name reusable
    s.execute("INSERT INTO t VALUES (nextval('gone'))")
    out = s.execute("SELECT id FROM t")
    assert [int(x) for x in out.column("id")] == [7]
