"""Mesh-parallel execution tests on the virtual 8-device CPU mesh
(SURVEY.md §4 tier-2: deterministic multi-node behavior in one process)."""

import numpy as np
import pytest

from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.parallel import MeshScan, make_mesh
from ydb_tpu.workload import tpch


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.002, seed=11)


def _source(data, table):
    return ColumnSource(
        columns=data.tables[table],
        schema=data.schema(table),
        dicts=data.dicts,
    )


def _oracle(data, table):
    cols = {
        n: (v, np.ones(len(v), dtype=bool))
        for n, v in data.tables[table].items()
    }
    return OracleTable(cols, data.schema(table))


def _match(engine: OracleTable, oracle: OracleTable):
    assert engine.num_rows == oracle.num_rows
    for name in oracle.cols:
        ev, eo = engine.cols[name]
        ov, oo = oracle.cols[name]
        np.testing.assert_array_equal(eo, oo, err_msg=f"validity {name}")
        if np.issubdtype(ev.dtype, np.floating):
            np.testing.assert_allclose(ev[eo], ov[oo], rtol=1e-9,
                                       err_msg=name)
        else:
            np.testing.assert_array_equal(ev[eo], ov[oo], err_msg=name)


def test_q1_mesh_psum_path(data):
    """Q1: dense slot states merged with psum/pmax over 8 shards."""
    mesh = make_mesh(8)
    prog = tpch.q1_program()
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    assert scan.partial.group_layout[0] == "dense_slots"
    res = scan.execute(_source(data, "lineitem"))
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    _match(res, ora)


def test_q6_mesh_keyless_psum(data):
    mesh = make_mesh(8)
    prog = tpch.q6_program()
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    assert scan.partial.group_layout[0] == "keyless"
    res = scan.execute(_source(data, "lineitem"))
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    _match(res, ora)


def test_generic_groupby_gather_path(data):
    """High-cardinality keys: compacted partials merged via all_gather."""
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program, SortStep

    mesh = make_mesh(4)
    prog = Program((
        GroupByStep(
            keys=("l_orderkey",),
            aggs=(
                AggSpec(Agg.SUM, "l_extendedprice", "total"),
                AggSpec(Agg.COUNT_ALL, None, "n"),
            ),
        ),
        SortStep(keys=("l_orderkey",)),
    ))
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    assert scan.partial.group_layout[0] == "compact"
    res = scan.execute(_source(data, "lineitem"))
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    _match(res, ora)


def test_no_groupby_gather_concat(data):
    from ydb_tpu.ssa import Call, Col, FilterStep, Op, Program, ProjectStep
    from ydb_tpu.ssa.program import decimal_lit

    mesh = make_mesh(8)
    prog = Program((
        FilterStep(Call(Op.GT, Col("l_quantity"), decimal_lit("49", 2))),
        ProjectStep(("l_orderkey",)),
    ))
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    res = scan.execute(_source(data, "lineitem"))
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    assert res.num_rows == ora.num_rows
    np.testing.assert_array_equal(
        np.sort(res.cols["l_orderkey"][0]),
        np.sort(ora.cols["l_orderkey"][0]),
    )


def test_uneven_shard_sizes(data):
    """Row count not divisible by mesh size: padding must not leak."""
    mesh = make_mesh(8)
    prog = tpch.q6_program()
    src = _source(data, "lineitem")
    # trim to a prime-ish row count
    n = src.num_rows - 13
    src = ColumnSource(
        {k: v[:n] for k, v in src.columns.items()}, src.schema, src.dicts
    )
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    res = scan.execute(src)
    ora_cols = {
        k: (v[:n], np.ones(n, dtype=bool))
        for k, v in data.tables["lineitem"].items()
    }
    ora = run_oracle(prog, OracleTable(ora_cols, tpch.LINEITEM_SCHEMA),
                     data.dicts)
    _match(res, ora)


def test_string_min_max_across_mesh():
    """Dictionary insertion order != lexicographic order: the cross-device
    MIN/MAX merge must re-pack ids by rank (review regression)."""
    from ydb_tpu import dtypes
    from ydb_tpu.blocks import DictionarySet
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program

    dicts = DictionarySet()
    d = dicts.for_column("s")
    # zebra gets id 0, apple id 1: id order is the reverse of lexicographic
    ids = d.encode([b"zebra", b"apple", b"middle", b"banana"])
    sch = dtypes.schema(("s", dtypes.STRING), ("g", dtypes.INT64))
    cols = {"s": ids, "g": np.zeros(4, dtype=np.int64)}
    src = ColumnSource(cols, sch, dicts)
    prog = Program((
        GroupByStep(keys=("g",), aggs=(
            AggSpec(Agg.MIN, "s", "lo"),
            AggSpec(Agg.MAX, "s", "hi"),
        )),
    ))
    mesh = make_mesh(4)  # one row per device: every device has a different local min
    scan = MeshScan(prog, sch, dicts, key_spaces={"g": 1}, mesh=mesh)
    assert scan.partial.group_layout[0] == "dense_slots"
    res = scan.execute(src)
    assert d.values[int(res.cols["lo"][0][0])] == b"apple"
    assert d.values[int(res.cols["hi"][0][0])] == b"zebra"
