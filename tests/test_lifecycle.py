"""Resource-lifecycle analyzer (R001-R008) + leak sanitizer: firing
fixtures per rule, drain tests per tracked handle kind, regression
tests for the true findings the pass surfaced, and the
100-concurrent-session deadline soak where every gauge drains to 0."""

import ast
import textwrap
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from ydb_tpu.analysis import leaksan, lifecycle


def _codes(src, filename="fix.py"):
    return [f.code for f in
            lifecycle.check_source(textwrap.dedent(src), filename)]


@pytest.fixture(autouse=True)
def _leaksan_off_after():
    """Every test leaves the sanitizer unpinned and empty."""
    yield
    leaksan.set_force(None)
    leaksan.reset()


# ---------- static rules: one firing fixture per R-rule ----------

def test_r000_syntax_error():
    assert _codes("def f(:\n") == ["R000"]


def test_r001_release_never_in_finally():
    src = """
    class C:
        def f(self):
            self.lock.acquire()
            self.work()
            self.lock.release()
    """
    assert "R001" in _codes(src)


def test_r001_clean_with_finally():
    src = """
    class C:
        def f(self):
            self.lock.acquire()
            try:
                self.work()
            finally:
                self.lock.release()
    """
    assert _codes(src) == []


def test_r001_skips_cross_function_protocol():
    # acquire with NO release anywhere in the function is a protocol
    # handing ownership elsewhere (leaksan's beat), not a finding
    src = """
    class C:
        def f(self):
            self.lock.acquire()
            return self.handle()
    """
    assert _codes(src) == []


def test_r002_generator_flight_without_finally():
    src = """
    class C:
        def gen(self, key, ev):
            self._flights[key] = ev
            yield key
    """
    assert "R002" in _codes(src)


def test_r002_clean_flight_popped_in_finally():
    src = """
    class C:
        def gen(self, key, ev):
            self._flights[key] = ev
            try:
                yield key
            finally:
                self._flights.pop(key, None)
    """
    assert _codes(src) == []


def test_r002_generator_owned_acquire_across_yield():
    src = """
    class C:
        def gen(self):
            self.lock.acquire()
            yield 1
            self.lock.release()
    """
    assert "R002" in _codes(src)


def test_r003_gauge_decrement_not_in_finally():
    src = """
    class C:
        def f(self):
            self.inflight += 1
            self.work()
            self.inflight -= 1
    """
    assert "R003" in _codes(src)


def test_r003_clean_decrement_in_finally():
    src = """
    class C:
        def f(self):
            self.inflight += 1
            try:
                self.work()
            finally:
                self.inflight -= 1
    """
    assert _codes(src) == []


def test_r003_skips_non_unit_accounting():
    # += nbytes / -= nbytes is byte accounting (blockcache tee), not a
    # paired gauge — constant-1 pairs only
    src = """
    class C:
        def f(self, nbytes):
            self.total += nbytes
            self.work()
            self.total -= nbytes
    """
    assert _codes(src) == []


def test_r004_swallowed_cancellation():
    src = """
    class C:
        def f(self):
            try:
                self.run()
            except StatementCancelled:
                pass
    """
    assert "R004" in _codes(src)


def test_r004_clean_reraise_or_record():
    reraise = """
    class C:
        def f(self):
            try:
                self.run()
            except StatementCancelled:
                self.cleanup()
                raise
    """
    record = """
    class C:
        def f(self):
            try:
                self.run()
            except ConveyorTimeout as e:
                self.result.error = e
    """
    assert _codes(reraise) == []
    assert _codes(record) == []


def test_r005_stoppable_member_unreachable():
    src = """
    import threading

    class Worker:
        def __init__(self):
            self.t = threading.Thread(target=self.run)
        def run(self):
            pass
        def stop(self):
            self.t.join()

    class Holder:
        def __init__(self):
            self.w = Worker()
    """
    assert "R005" in _codes(src)


def test_r005_clean_stop_path_reaches_member():
    src = """
    import threading

    class Worker:
        def __init__(self):
            self.t = threading.Thread(target=self.run)
        def run(self):
            pass
        def stop(self):
            self.t.join()

    class Holder:
        def __init__(self):
            self.w = Worker()
        def stop(self):
            self.w.stop()
    """
    assert _codes(src) == []


def test_r006_broker_acquire_without_deadline():
    src = """
    class C:
        def f(self):
            self.broker.acquire("scan")
            try:
                self.work()
            finally:
                self.broker.release("scan")
    """
    assert "R006" in _codes(src)


def test_r006_clean_with_deadline():
    src = """
    class C:
        def f(self, dl):
            self.broker.acquire("scan", deadline=dl)
            try:
                self.work()
            finally:
                self.broker.release("scan")
    """
    assert _codes(src) == []


def test_r007_grow_only_container():
    src = """
    class C:
        def __init__(self):
            self._cache = {}
        def put(self, k, v):
            self._cache[k] = v
    """
    assert "R007" in _codes(src)


def test_r007_clean_with_removal_or_bound():
    removal = """
    class C:
        def __init__(self):
            self._cache = {}
        def put(self, k, v):
            self._cache[k] = v
        def drop(self, k):
            self._cache.pop(k, None)
    """
    bound = """
    class C:
        def __init__(self):
            self._cache = {}
            self.cap = 8
        def put(self, k, v):
            self._cache[k] = v
            if len(self._cache) > self.cap:
                self.evict()
        def evict(self):
            pass
    """
    assert _codes(removal) == []
    assert _codes(bound) == []


def test_r007_membership_test_is_not_a_bound():
    # dedup against a grow-only set IS the leak shape, not its bound
    src = """
    class C:
        def __init__(self):
            self._seen = set()
        def note(self, k):
            if k in self._seen:
                return
            self._seen.add(k)
    """
    assert "R007" in _codes(src)


def test_r008_flight_crosses_submit_unowned():
    src = """
    class C:
        def f(self, pid):
            self._inflight.add(pid)
            self.conveyor.submit("promote", self.task)
    """
    assert "R008" in _codes(src)


def test_r008_clean_closure_owns_release():
    # the closure IS the ownership continuation across threads: its
    # finally-discard counts as the parent's release
    src = """
    class C:
        def f(self, pid):
            self._inflight.add(pid)

            def task():
                try:
                    self.load(pid)
                finally:
                    self._inflight.discard(pid)

            self.conveyor.submit("promote", task)
    """
    assert _codes(src) == []


def test_pragma_suppression():
    src = """
    class C:
        def __init__(self):
            self._cache = {}
        def put(self, k, v):
            self._cache[k] = v  # ydb-lint: disable=R007
    """
    assert _codes(src) == []


# ---------- leak sanitizer: gate, handles, drain checks ----------

def test_leaksan_disabled_is_free(monkeypatch):
    monkeypatch.delenv("YDB_TPU_LEAKSAN", raising=False)
    leaksan.refresh()
    assert leaksan.track("conveyor.task", "q") is None
    leaksan.close(None)  # None-safe
    assert leaksan.counts() == {}
    leaksan.assert_drained()  # no-op when off


def test_leaksan_track_close_and_stacks():
    with leaksan.activate():
        h = leaksan.track("broker.slot", "scan", owner="q1")
        assert leaksan.counts() == {"broker.slot": 1}
        assert "broker.slot[scan]" in h.describe()
        assert "test_lifecycle" in h.describe()  # creation site kept
        h.close()
        h.close()  # idempotent
        assert leaksan.counts() == {}


def test_leaksan_assert_drained_names_leaks():
    with leaksan.activate():
        leaksan.track("conveyor.task", "compaction")
        with pytest.raises(leaksan.LeakError) as ei:
            leaksan.assert_drained(where="test")
        assert "conveyor.task[compaction]" in str(ei.value)
        leaksan.reset()


def test_leaksan_owner_scoped_drain():
    with leaksan.activate():
        a = leaksan.track("session.active", "SELECT 1", owner=7)
        leaksan.track("session.active", "SELECT 2", owner=8)
        leaksan.close(a)
        leaksan.assert_drained(owner=7)  # 7 drained; 8 still open
        with pytest.raises(leaksan.LeakError):
            leaksan.assert_drained(owner=8)
        leaksan.reset()


# ---------- one drain test per tracked kind ----------

def test_kind_conveyor_task():
    from ydb_tpu.runtime.conveyor import Conveyor

    with leaksan.activate():
        cv = Conveyor(workers=1)
        try:
            gate = threading.Event()
            h = cv.submit("bg", gate.wait, 5.0)
            deadline = time.monotonic() + 5.0
            while not leaksan.live("conveyor.task") and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            assert leaksan.counts() == {"conveyor.task": 1}
            gate.set()
            h.wait(5.0)
            cv.wait_idle(timeout=5.0)
            assert leaksan.counts() == {}
        finally:
            cv.shutdown()


def test_kind_broker_slot():
    from ydb_tpu.runtime.conveyor import ResourceBroker

    with leaksan.activate():
        br = ResourceBroker(quotas={"scan": 2})
        br.acquire("scan")
        br.acquire("scan")
        assert leaksan.counts() == {"broker.slot": 2}
        br.release("scan")
        assert leaksan.counts() == {"broker.slot": 1}
        br.release("scan")
        assert leaksan.counts() == {}


def test_kind_rm_slot():
    from ydb_tpu.kqp.rm import ResourceManager

    with leaksan.activate():
        rm = ResourceManager()
        rm.acquire("q1", slots=1)
        rm.acquire("q1", slots=2)  # regrant: still one handle
        assert leaksan.counts() == {"rm.slot": 1}
        rm.release("q1")
        assert leaksan.counts() == {}


def test_kind_resident_flight():
    from ydb_tpu.engine import resident as resident_mod
    from ydb_tpu.runtime.conveyor import shared_conveyor

    prev = resident_mod.RESIDENT_FORCE
    resident_mod.RESIDENT_FORCE = True
    try:
        with leaksan.activate():
            store = resident_mod.ResidentStore("t", budget=1 << 20)
            gate = threading.Event()

            def loader():
                gate.wait(5.0)
                raise RuntimeError("load failed on purpose")

            assert store.promote_async(1, rows=10, loader=loader)
            deadline = time.monotonic() + 5.0
            while not leaksan.live("resident.flight") and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            assert leaksan.counts().get("resident.flight") == 1
            gate.set()
            store.drain(timeout=10.0)
            shared_conveyor().wait_idle(timeout=10.0)
            # the failing loader still drains: discard + close live in
            # the task's finally
            assert store.snapshot()["inflight"] == 0
            assert leaksan.counts() == {}
    finally:
        resident_mod.RESIDENT_FORCE = prev


def test_kind_stream_morsel():
    """stream.morsel handles open at flight admission and close at
    retire: live while prefetched flights wait behind the consumer,
    zero once the scan drains."""
    from ydb_tpu import dtypes
    from ydb_tpu.engine import stream_sched
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.reader import PortionStreamSource
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.runtime.conveyor import stream_conveyor

    schema = dtypes.schema(("id", dtypes.INT64, False),
                           ("v", dtypes.INT64))
    prev = stream_sched.PIPELINE_FORCE
    stream_sched.PIPELINE_FORCE = True
    try:
        with leaksan.activate():
            shard = ColumnShard(
                "s1", schema, MemBlobStore(), pk_column="id",
                upsert=False,
                config=ShardConfig(compact_portion_threshold=10**6))
            for off in range(6):
                base = off * 200
                wid = shard.write({
                    "id": np.arange(base, base + 200, dtype=np.int64),
                    "v": np.arange(base, base + 200, dtype=np.int64)})
                shard.commit([wid])
            src = PortionStreamSource(shard,
                                      shard.visible_portions(None))
            it = src.blocks(64)
            next(it)  # later morsels are admitted ahead, uncollected
            assert leaksan.live("stream.morsel")
            for _ in it:
                pass
            deadline = time.monotonic() + 5.0
            while leaksan.live("stream.morsel") and \
                    time.monotonic() < deadline:
                time.sleep(0.005)
            assert leaksan.live("stream.morsel") == []
            stream_conveyor().wait_idle(timeout=10.0)
            while leaksan.counts() and time.monotonic() < deadline:
                time.sleep(0.005)
            assert leaksan.counts() == {}
    finally:
        stream_sched.PIPELINE_FORCE = prev


class _FakeCol:
    def __init__(self):
        self.data = np.zeros(4, dtype=np.int64)
        self.validity = np.ones(4, dtype=bool)


class _FakeBlock:
    def __init__(self):
        self.columns = {"c": _FakeCol()}


def test_kind_blockcache_flight():
    from ydb_tpu.engine.blockcache import DeviceBlockCache

    with leaksan.activate():
        cache = DeviceBlockCache(budget=1 << 20)
        blocks = [_FakeBlock(), _FakeBlock()]
        g = cache.stream("k1", lambda: iter(blocks))
        next(g)  # first next registers the fill flight
        assert leaksan.counts() == {"blockcache.flight": 1}
        g.close()  # abandoned stream: the finally closes the flight
        assert leaksan.counts() == {}


def test_kind_session_active():
    from ydb_tpu.kqp.session import Cluster

    with leaksan.activate():
        c = Cluster()
        tok = c._register_active("SELECT 1", time.monotonic())
        assert leaksan.counts() == {"session.active": 1}
        with pytest.raises(leaksan.LeakError):
            leaksan.assert_drained(owner=tok)
        c._unregister_active(tok)
        assert leaksan.counts() == {}
        c.stop()


def test_kind_dq_spill():
    from ydb_tpu.dq.spilling import Spiller

    with leaksan.activate():
        sp = Spiller(mem_quota_bytes=0, prefix="spill/t9")
        a = sp.put({"x": np.arange(8)})
        sp.put({"x": np.arange(8)})
        assert leaksan.counts() == {"dq.spill": 2}
        sp.get(a)  # consumed: blob deleted, handle closed
        assert leaksan.counts() == {"dq.spill": 1}
        sp.close()  # aborted query: leftover blobs dropped
        assert leaksan.counts() == {}
        assert sp.store.list("spill/t9") == []
        sp.close()  # idempotent


# ---------- regression tests for the true findings fixed ----------

SESSION_PY = Path(lifecycle.__file__).parents[1] / "kqp" / "session.py"
STATS_PY = Path(lifecycle.__file__).parents[1] / "stats" / \
    "aggregator.py"


def _strip_method(src: str, cls_name: str, meth: str) -> str:
    """Remove one method body from a class, textually by AST lines."""
    tree = ast.parse(src)
    for st in tree.body:
        if isinstance(st, ast.ClassDef) and st.name == cls_name:
            for sub in st.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) and \
                        sub.name == meth:
                    lines = src.splitlines(keepends=True)
                    start = sub.lineno - 1
                    if sub.decorator_list:
                        start = sub.decorator_list[0].lineno - 1
                    del lines[start:sub.end_lineno]
                    return "".join(lines)
    raise AssertionError(f"{cls_name}.{meth} not found")


def test_r005_regression_cluster_without_stop():
    """Pre-fix shape: Cluster held the thread-owning
    StatisticsAggregator with NO stop path at all — R005 must fire on
    the real sources once Cluster.stop is stripped back out, and stay
    quiet with it present."""
    session_src = SESSION_PY.read_text(encoding="utf-8")
    stats_src = STATS_PY.read_text(encoding="utf-8")

    def run(src):
        return [f.code for f in lifecycle.check_sources([
            (src, "session.py", "session"),
            (stats_src, "aggregator.py", "aggregator"),
        ])]

    assert "R005" not in run(session_src)  # fixed tree is clean
    stripped = _strip_method(session_src, "Cluster", "stop")
    assert "R005" in run(stripped)


def test_cluster_stop_drains_and_checks():
    from ydb_tpu.kqp.session import Cluster

    with leaksan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE kv (k Int64 NOT NULL, v Int64, "
                  "PRIMARY KEY (k))")
        c.tables["kv"].insert({"k": [1, 2], "v": [7, 14]})
        out = s.execute("SELECT SUM(v) AS sv FROM kv")
        assert int(np.asarray(out.cols["sv"][0])[0]) == 21
        c.stop()  # stats thread stopped + global drain check passes
        assert c.stats._thread is None  # stop() joined + cleared it
        assert leaksan.counts() == {}


def test_execute_admission_released_on_unexpected_error():
    """Regression: an exception between workload admission and the
    compute-slot grant used to strand qid in the pool's running set
    forever. Any failure there must release the pool entry."""
    from ydb_tpu.kqp.rm import ResourceManager, WorkloadService
    from ydb_tpu.kqp.session import Cluster

    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE kv (k Int64 NOT NULL, "
              "PRIMARY KEY (k))")
    c.workload = WorkloadService()
    c.rm = ResourceManager()

    class _Boom(Exception):
        pass

    def boom(*a, **k):
        raise _Boom("rm exploded")

    c.rm.acquire = boom
    with pytest.raises(_Boom):
        s.execute("SELECT k FROM kv")
    assert c.workload.stats()["running"] == 0
    assert c.workload.stats()["queued"] == 0
    c.workload = None
    c.rm = None
    c.stop()


def test_console_on_change_unsubscribe():
    """Regression (R007): ConfigsDispatcher callbacks were append-only
    — a component torn down before its node leaked its callback for
    the dispatcher's lifetime. on_change now returns an unsubscribe."""
    from ydb_tpu.runtime.console import ConfigsDispatcher

    d = ConfigsDispatcher()
    seen = []
    off = d.on_change(seen.append)
    assert len(d._callbacks) == 1
    off()
    assert d._callbacks == []
    off()  # idempotent


def test_interconnect_remove_peer():
    """Regression (R007): the peer map only ever grew — nodes coming
    and going could not be forgotten."""
    from ydb_tpu.runtime.actors import ActorSystem
    from ydb_tpu.runtime.interconnect import Interconnect

    ic = Interconnect(ActorSystem(node=1), listen_port=None)
    ic.add_peer(2, "127.0.0.1", 19999)
    assert 2 in ic.peers
    ic.remove_peer(2)
    assert ic.peers == {}
    ic.remove_peer(2)  # absent: no-op


def test_spiller_close_drops_aborted_blobs():
    """Regression: Spiller had no teardown — a query aborted with
    parked/accumulated sids left spill blobs in the store forever
    (only get() deleted them). GraphHandle.close / ReleaseQuery now
    close every task's spiller."""
    from ydb_tpu.dq.spilling import Spiller
    from ydb_tpu.engine.blobs import MemBlobStore

    store = MemBlobStore()
    sp = Spiller(store=store, mem_quota_bytes=0, prefix="spill/q7")
    sids = [sp.put({"x": np.arange(16)}) for _ in range(3)]
    assert len(store.list("spill/q7")) == 3
    sp.get(sids[0])
    assert len(store.list("spill/q7")) == 2
    sp.close()  # abort path: leftover blobs deleted
    assert store.list("spill/q7") == []


# ---------- the 100-concurrent-session deadline soak ----------

def test_soak_100_sessions_every_3rd_deadline():
    """100 concurrent sessions, every 3rd statement forced past its
    deadline, pool admission + compute-slot planes armed: afterwards
    EVERY tracked gauge drains to zero — registry rows, pool running
    set, rm grants, conveyor tasks, broker slots, leaksan counts."""
    from ydb_tpu.chaos.deadline import StatementCancelled
    from ydb_tpu.kqp.rm import (PoolOverloaded, ResourceManager,
                                WorkloadService)
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.runtime.conveyor import shared_conveyor

    with leaksan.activate():
        c = Cluster()
        setup = c.session()
        setup.execute("CREATE TABLE kv (k Int64 NOT NULL, v Int64, "
                      "PRIMARY KEY (k)) WITH (shards = 2)")
        ks = list(range(600))
        c.tables["kv"].insert({"k": ks, "v": [k * 3 for k in ks]})
        c._invalidate_plans()
        setup.execute("SELECT SUM(v) AS sv FROM kv")  # warm plans
        c.workload = WorkloadService()
        c.workload.configure("default", concurrent_limit=16,
                             queue_size=256)
        c.rm = ResourceManager(compute_slots=32)

        ok = [0]
        cancelled = [0]
        failures = []
        lock = threading.Lock()

        def worker(i):
            try:
                s = c.session()
                for j in range(3):
                    stmt = i * 3 + j
                    if stmt % 3 == 2:  # every 3rd past its deadline
                        try:
                            s.execute("SELECT SUM(v) AS sv FROM kv",
                                      timeout=0.0)
                        except (StatementCancelled, PoolOverloaded):
                            with lock:
                                cancelled[0] += 1
                    else:
                        s.execute("SELECT COUNT(*) AS n FROM kv "
                                  "WHERE k < 100")
                        with lock:
                            ok[0] += 1
            except Exception as e:  # noqa: BLE001 - soak must report
                with lock:
                    failures.append(f"session {i}: {e!r}")

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(100)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        assert not any(t.is_alive() for t in threads), "soak wedged"
        assert failures == [], failures[:5]
        assert ok[0] == 200 and cancelled[0] == 100

        # every gauge drains to zero
        shared_conveyor().wait_idle(timeout=30.0)
        assert c.active_queries == {}
        assert c.workload.stats()["running"] == 0
        assert c.workload.stats()["queued"] == 0
        assert c.rm.used() == (0, 0)
        qs = shared_conveyor().queue_stats()
        assert qs["depth"] == 0 and qs["active"] == 0
        c.workload = None
        c.rm = None
        c.stop()  # global leaksan drain check runs here
        assert leaksan.counts() == {}, leaksan.counts()
