"""Cross-query batched serving tier: the kqp/batch.py dispatcher
(window gating, dedup vs stacked dispatch, deadline isolation inside a
batch), the engine/scanshare single-flight staging share, and the
observability surface (profile batching line, sys view columns,
batching counters). Every batched result must be bit-identical to the
serial path, and window=0 must leave the serial path untouched."""

import contextlib
import threading
import time

import numpy as np
import pytest

from ydb_tpu.analysis import leaksan
from ydb_tpu.chaos.deadline import StatementCancelled
from ydb_tpu.engine.scanshare import ScanShare
from ydb_tpu.kqp.batch import BatchDispatcher
from ydb_tpu.kqp.session import Cluster

from test_sql import Q1_SQL, Q6_SQL


# ---------------- fixtures ----------------

def _lineitem_cluster(sf=0.002):
    """Cluster holding TPC-H lineitem, three portions (the test_chaos
    loader trimmed to the one table the batched queries need)."""
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=7)
    c = Cluster()
    s = c.session()
    schema = data.schema("lineitem")
    cols = ", ".join(f"{f.name} {type_to_str(f.type)}"
                     for f in schema.fields)
    s.execute(f"CREATE TABLE lineitem ({cols}, "
              f"PRIMARY KEY (l_orderkey)) WITH (shards = 1)")
    src = data.tables["lineitem"]
    t = c.tables["lineitem"]
    n = len(src["l_orderkey"])
    step = max(1, n // 3)
    for off in range(0, n, step):
        arrays = {}
        for f in schema.fields:
            v = src[f.name][off:off + step]
            if f.type.is_string:
                arrays[f.name] = [
                    bytes(x) for x in data.dicts[f.name].decode(
                        np.asarray(v, dtype=np.int32))]
            else:
                arrays[f.name] = v
        t.insert(arrays)
    c._invalidate_plans()
    return c


@pytest.fixture(scope="module")
def cluster():
    c = _lineitem_cluster()
    yield c
    c.stop()


@contextlib.contextmanager
def _armed(c, window_ms, max_batch=None):
    bt = c.batcher
    w0, m0 = bt.window_ms, bt.max_batch
    bt.window_ms = float(window_ms)
    if max_batch is not None:
        bt.max_batch = max_batch
    try:
        yield bt
    finally:
        bt.window_ms, bt.max_batch = w0, m0


def _same_result(a, b):
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for name in a.schema.names:
        av, aok = a.cols[name]
        bv, bok = b.cols[name]
        np.testing.assert_array_equal(np.asarray(av), np.asarray(bv),
                                      err_msg=name)
        np.testing.assert_array_equal(np.asarray(aok), np.asarray(bok),
                                      err_msg=f"{name} validity")


# ---------------- scan share (single-flight staging) ----------------

def test_scanshare_single_flight():
    share = ScanShare()
    staging = threading.Event()   # filler is inside stage_fn
    release = threading.Event()   # attacher is waiting on the flight
    calls = []

    def stage():
        calls.append(threading.get_ident())
        staging.set()
        assert release.wait(5.0)
        return {"block": 42}

    out = [None, None]
    t0 = threading.Thread(
        target=lambda: out.__setitem__(0, share.get_or_stage("k", stage)))
    t0.start()
    assert staging.wait(5.0)
    t1 = threading.Thread(
        target=lambda: out.__setitem__(1, share.get_or_stage("k", stage)))
    t1.start()
    while share.attached == 0:   # t1 registered as an attacher
        time.sleep(0.001)
    release.set()
    t0.join(5.0)
    t1.join(5.0)
    assert len(calls) == 1       # staged exactly once
    assert out[0] is out[1]      # the attacher shares the SAME block
    assert share.snapshot() == {"staged": 1, "attached": 1,
                                "inflight": 0}


def test_scanshare_error_propagates_then_clears():
    share = ScanShare()
    staging = threading.Event()
    release = threading.Event()

    def boom():
        staging.set()
        assert release.wait(5.0)
        raise ValueError("staging fault")

    errs = [None, None]

    def fill():
        try:
            share.get_or_stage("k", boom)
        except ValueError as e:
            errs[0] = e

    def attach():
        try:
            share.get_or_stage("k", boom)
        except ValueError as e:
            errs[1] = e

    t0 = threading.Thread(target=fill)
    t0.start()
    assert staging.wait(5.0)
    t1 = threading.Thread(target=attach)
    t1.start()
    while share.attached == 0:
        time.sleep(0.001)
    release.set()
    t0.join(5.0)
    t1.join(5.0)
    assert errs[0] is not None and errs[1] is errs[0]
    # the failed flight cleared immediately: a retry restages fresh
    assert share.get_or_stage("k", lambda: "ok") == "ok"
    assert share.staged == 1


def test_scanshare_key_none_stages_privately():
    share = ScanShare()
    calls = []
    for _ in range(2):
        share.get_or_stage(None, lambda: calls.append(1))
    assert len(calls) == 2
    assert share.snapshot() == {"staged": 0, "attached": 0,
                                "inflight": 0}


# ---------------- stacked / shared dispatch bit-identity ----------------

def test_run_stacked_slices_match_run_shared():
    """Two members with DIFFERENT staged inputs stack into one vmapped
    dispatch; each slice must be bit-identical to that member's own
    non-donating serial dispatch (and the two members' answers really
    differ, so slicing is observable)."""
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan.executor import Database, _stage_fused_site
    from ydb_tpu.plan.nodes import TableScan
    from ydb_tpu.ssa import plan_fuse
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=0.002, seed=11)
    schema = data.schema("lineitem")
    cols_a = data.tables["lineitem"]
    cols_b = dict(cols_a)
    cols_b["l_quantity"] = np.asarray(cols_a["l_quantity"]) * 2
    db_a = Database(
        sources={"lineitem": ColumnSource(cols_a, schema, data.dicts)},
        dicts=data.dicts)
    db_b = Database(
        sources={"lineitem": ColumnSource(cols_b, schema, data.dicts)},
        dicts=data.dicts)

    plan = TableScan("lineitem", program=tpch.q6_program())
    sig = plan_fuse.plan_signature(plan, db_a)
    assert sig is not None and sig.sites
    # distinct host sources -> distinct member identities (the
    # dispatcher's stacked-routing input), stable per member
    ida = BatchDispatcher._identity_vector(sig, db_a)
    assert ida == BatchDispatcher._identity_vector(sig, db_a)
    assert ida != BatchDispatcher._identity_vector(sig, db_b)

    fused = plan_fuse.build(sig, db_a)
    ia = {s.key: _stage_fused_site(s, db_a, None, donate=False)[0]
          for s in sig.sites}
    ib = {s.key: _stage_fused_site(s, db_b, None, donate=False)[0]
          for s in sig.sites}
    ra, ta = fused.run_shared(ia)
    assert not fused.overflowed(ta)
    rb, tb = fused.run_shared(ib)
    assert not fused.overflowed(tb)
    out, tt = fused.run_stacked([ia, ib])
    assert not fused.overflowed(tt)

    def same(x, y):
        xv, xok = x.to_numpy(), x.validity_numpy()
        yv, yok = y.to_numpy(), y.validity_numpy()
        for name in x.schema.names:
            np.testing.assert_array_equal(xok[name], yok[name])
            np.testing.assert_array_equal(
                np.where(xok[name], xv[name], 0),
                np.where(yok[name], yv[name], 0), err_msg=name)

    same(plan_fuse.slice_member(out, 0), ra)
    same(plan_fuse.slice_member(out, 1), rb)
    # doubled quantities flip Q6's l_quantity filter: the two members'
    # revenues differ, so the slices are genuinely per-member
    assert (ra.to_numpy()["revenue"][0]
            != rb.to_numpy()["revenue"][0])


# ---------------- window gating ----------------

def test_window_zero_is_serial(cluster):
    s = cluster.session()
    assert not cluster.batcher.armed()
    s.execute(Q1_SQL)
    snap = cluster.batcher.snapshot()
    assert snap["batches"] == 0 and snap["solo"] == 0
    assert snap["scan_staged"] == 0
    assert s.last_profile.batch_size == 0
    assert s.last_profile.batch_id == 0


def test_solo_group_returns_to_serial_path(cluster):
    """One statement inside the window is NOT a batch: the caller runs
    the unchanged serial path, with the window wait attributed on the
    dispatch.batch span (visible as batch_size=1 in the profile)."""
    s = cluster.session()
    want = s.execute(Q1_SQL)
    with _armed(cluster, window_ms=30):
        got = s.execute(Q1_SQL)
    _same_result(got, want)
    snap = cluster.batcher.snapshot()
    assert snap["solo"] >= 1 and snap["batched_statements"] == 0
    assert s.last_profile.batch_size == 1
    assert s.last_profile.batch_wait_seconds >= 0.0


# ---------------- batched end-to-end ----------------

def test_batched_results_bit_identical(cluster):
    n = 4
    s0 = cluster.session()
    want = s0.execute(Q1_SQL)
    bt0 = cluster.batcher.snapshot()
    results = [None] * n
    errors = [None] * n
    profiles = [None] * n
    barrier = threading.Barrier(n)

    def work(i):
        s = cluster.session()
        barrier.wait()
        try:
            results[i] = s.execute(Q1_SQL)
            profiles[i] = s.last_profile
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[i] = e

    with _armed(cluster, window_ms=500, max_batch=n):
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    assert errors == [None] * n
    for r in results:
        _same_result(r, want)
    snap = cluster.batcher.snapshot()
    assert snap["batches"] >= bt0["batches"] + 1
    assert snap["batched_statements"] >= bt0["batched_statements"] + 2
    # same snapshot, same plan -> ONE deduped dispatch, scans staged
    # once and shared by every member
    assert snap["dedup_dispatches"] >= bt0["dedup_dispatches"] + 1
    assert snap["scan_staged"] >= bt0["scan_staged"] + 1
    batched = [p for p in profiles if p is not None and p.batch_size >= 2]
    assert batched, "no member profile recorded a batch seat"
    for p in batched:
        assert p.batch_id > 0
        assert p.shared_scan >= 1
        assert p.batch_execute_seconds >= 0.0

    # counters surface through run_background into the batching group
    cluster.run_background()
    g = cluster.counters.group(component="batching")
    assert g.counter("batches").value == snap["batches"]
    assert g.counter("batched_statements").value \
        == snap["batched_statements"]


def test_distinct_plans_never_share_a_batch(cluster):
    """Q1 and Q6 arrivals in the same window form separate groups (the
    cache key is the plan fingerprint) — both bit-identical to serial."""
    s0 = cluster.session()
    want = {Q1_SQL: s0.execute(Q1_SQL), Q6_SQL: s0.execute(Q6_SQL)}
    sqls = [Q1_SQL, Q6_SQL, Q1_SQL, Q6_SQL]
    results = [None] * len(sqls)
    errors = [None] * len(sqls)
    barrier = threading.Barrier(len(sqls))

    def work(i):
        s = cluster.session()
        barrier.wait()
        try:
            results[i] = s.execute(sqls[i])
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[i] = e

    with _armed(cluster, window_ms=400):
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(len(sqls))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    assert errors == [None] * len(sqls)
    for i, sql in enumerate(sqls):
        _same_result(results[i], want[sql])


# ---------------- deadline isolation inside a batch ----------------

def test_deadline_cancel_leaves_batchmates_intact(cluster):
    """The chaos scenario: one member's statement deadline fires while
    it waits in the batch. That member alone raises StatementCancelled;
    its batchmates complete with bit-identical results (the leader
    serves the abandoned seat harmlessly)."""
    s0 = cluster.session()
    want = s0.execute(Q1_SQL)
    results = [None] * 3
    errors = [None] * 3
    started = threading.Event()

    def leader():
        s = cluster.session()
        started.set()
        try:
            results[0] = s.execute(Q1_SQL)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[0] = e

    def doomed():
        s = cluster.session()
        try:
            results[1] = s.execute(Q1_SQL, timeout=0.12)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[1] = e

    def survivor():
        s = cluster.session()
        try:
            results[2] = s.execute(Q1_SQL)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[2] = e

    with _armed(cluster, window_ms=500, max_batch=8):
        t0 = threading.Thread(target=leader)
        t0.start()
        assert started.wait(5.0)
        time.sleep(0.05)  # enqueue the doomed member INSIDE the window
        t1 = threading.Thread(target=doomed)
        t1.start()
        t2 = threading.Thread(target=survivor)
        t2.start()
        for t in (t0, t1, t2):
            t.join(30.0)
    assert errors[0] is None and errors[2] is None
    assert isinstance(errors[1], StatementCancelled)
    _same_result(results[0], want)
    _same_result(results[2], want)


# ---------------- leak sanitizer drain ----------------

def test_batched_path_drains_under_leaksan(cluster):
    """Batch seats and staging flights all close — including the seat
    abandoned by a deadline-cancelled member."""
    with leaksan.activate():
        n = 3
        errors = [None] * n
        cancelled = [None] * n
        barrier = threading.Barrier(n)

        def work(i):
            s = cluster.session()
            barrier.wait()
            try:
                s.execute(Q1_SQL,
                          timeout=(0.1 if i == n - 1 else None))
            except StatementCancelled as e:
                cancelled[i] = e  # expected for the doomed member
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors[i] = e

        with _armed(cluster, window_ms=400, max_batch=n):
            threads = [threading.Thread(target=work, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
        assert errors == [None] * n
        counts = leaksan.counts()
        assert counts.get("batch.member", 0) == 0
        assert counts.get("scanshare.flight", 0) == 0
        leaksan.assert_drained(
            kinds=("batch.member", "scanshare.flight"),
            where="after batched burst")


# ---------------- observability surface ----------------

def test_sys_views_expose_batch_columns(cluster):
    s = cluster.session()
    top = s.execute("SELECT batch_id, batch_size, shared_scan "
                    "FROM sys_top_queries")
    assert tuple(top.schema.names) == ("batch_id", "batch_size",
                                       "shared_scan")
    sizes = np.asarray(top.cols["batch_size"][0])
    # earlier tests in this module ran real batches; they show here
    assert top.num_rows > 0 and int(sizes.max()) >= 2
    act = s.execute("SELECT query_text, batch_id, batch_size, "
                    "shared_scan FROM sys_active_queries")
    # the introspection statement itself is live and unbatched
    assert act.num_rows >= 1
    ids = np.asarray(act.cols["batch_id"][0])
    assert int(ids.min()) >= 0


def test_explain_analyze_prints_batching_line(cluster):
    s = cluster.session()
    with _armed(cluster, window_ms=30):
        txt = s.execute("EXPLAIN ANALYZE " + Q1_SQL)
    assert "batching: batch_id=" in txt
    assert "batch_size=1" in txt          # solo group: wait attribution
    assert "wait_seconds=" in txt and "execute_seconds=" in txt
    with _armed(cluster, window_ms=0):
        txt0 = s.execute("EXPLAIN ANALYZE " + Q1_SQL)
    assert "batching:" not in txt0        # disarmed: line absent
