"""Trace-safety lint: rule unit tests + the tier-1 enforcement that the
whole ydb_tpu tree lints clean (any new jit-hazard pattern fails CI
until fixed or explicitly suppressed)."""

from pathlib import Path

from ydb_tpu.analysis.lint import RULES, lint_paths, lint_source, main

PKG = Path(__file__).resolve().parents[1] / "ydb_tpu"


def codes(src: str) -> list:
    return [f.code for f in lint_source(src, "t.py")]


# ---------------- enforcement ----------------


def test_repo_lints_clean():
    findings = lint_paths([PKG])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_code_clean_and_dirty(tmp_path, capsys):
    assert main([str(PKG)]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "L005" in out


def test_json_report(tmp_path, capsys):
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("def f(x=[]):\n    return x\n")
    assert main([str(bad), "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep[0]["code"] == "L005"
    assert rep[0]["line"] == 1


# ---------------- rules ----------------


def test_host_sync_item_in_traced_fn():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    y = jnp.sum(x)\n"
           "    return y.item()\n")
    assert "L001" in codes(src)


def test_host_sync_float_of_jnp():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    return float(jnp.mean(x))\n")
    assert "L001" in codes(src)


def test_item_outside_traced_fn_ok():
    # host-side result marshalling (viewer/fq service) is fine
    src = ("import numpy as np\n"
           "def f(v):\n"
           "    return [x.item() for x in np.asarray(v)]\n")
    assert codes(src) == []


def test_python_branch_on_traced():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    if jnp.any(x > 0):\n"
           "        return 1\n"
           "    return 0\n")
    assert "L002" in codes(src)


def test_branch_on_materialized_value_ok():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    if int(jnp.sum(x)) > 0:  # explicit host round-trip\n"
           "        return 1\n"
           "    return 0\n")
    assert "L002" not in codes(src)


def test_branch_on_static_dtype_predicate_ok():
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    y = jnp.sum(x)\n"
           "    if jnp.issubdtype(x.dtype, jnp.floating):\n"
           "        return y\n"
           "    return -y\n")
    assert codes(src) == []


def test_wall_clock_in_trace():
    src = ("import time\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    t = time.time()\n"
           "    return jnp.sum(x) + t\n")
    assert "L003" in codes(src)


def test_wall_clock_in_host_fn_ok():
    src = ("import time\n"
           "def f():\n"
           "    return time.time()\n")
    assert codes(src) == []


def test_unseeded_randomness():
    assert "L004" in codes(
        "import numpy as np\ndef f():\n    return np.random.rand(3)\n")
    assert "L004" in codes(
        "import numpy as np\n"
        "def f():\n    return np.random.default_rng()\n")
    assert codes(
        "import numpy as np\n"
        "def f():\n    return np.random.default_rng(42)\n") == []


def test_mutable_default_arg():
    assert "L005" in codes("def f(x={}):\n    return x\n")
    assert "L005" in codes("def f(x=set()):\n    return x\n")
    assert codes("def f(x=None):\n    return x\n") == []


def test_block_until_ready_in_traced_fn():
    src = ("import jax\n"
           "import jax.numpy as jnp\n"
           "def f(x):\n"
           "    y = jnp.sum(x)\n"
           "    jax.block_until_ready(y)\n"
           "    return y\n")
    assert "L007" in codes(src)
    src = ("import jax.numpy as jnp\n"
           "def f(x):\n"
           "    return jnp.sum(x).block_until_ready()\n")
    assert "L007" in codes(src)


def test_block_until_ready_in_host_fn_ok():
    # benchmark harnesses sync eagerly outside any traced function
    src = ("import jax\n"
           "def f(out):\n"
           "    jax.block_until_ready(out)\n"
           "    return out\n")
    assert codes(src) == []


def test_set_iteration_order():
    assert "L006" in codes(
        "def f(v):\n    return [x for x in set(v)]\n")
    assert "L006" in codes(
        "def f():\n    for x in {1, 2}:\n        pass\n")
    assert codes(
        "def f(v):\n    return [x for x in sorted(set(v))]\n") == []


# ---------------- suppression ----------------


def test_suppression_same_line_and_name_alias():
    src = ("def f(x=[]):  # ydb-lint: disable=L005\n"
           "    return x\n")
    assert codes(src) == []
    src = ("def f(x=[]):  # ydb-lint: disable=mutable-default-arg\n"
           "    return x\n")
    assert codes(src) == []


def test_suppression_line_above():
    src = ("# ydb-lint: disable=L005\n"
           "def f(x=[]):\n"
           "    return x\n")
    assert codes(src) == []


def test_suppression_is_per_rule():
    src = ("def f(x=[]):  # ydb-lint: disable=L001\n"
           "    return x\n")
    assert "L005" in codes(src)


def test_skip_file():
    src = ("# ydb-lint: skip-file\n"
           "def f(x=[]):\n"
           "    return x\n")
    assert codes(src) == []


def test_rule_table_is_stable():
    assert set(RULES) == {"L001", "L002", "L003", "L004", "L005", "L006",
                          "L007"}
