"""Shard split/merge (resharding): stream-copy into a new shard
generation with an atomic scheme cutover, crash-orphan sweep on boot
(VERDICT r4 missing #8; reference
schemeshard__operation_split_merge.cpp)."""

import numpy as np

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.engine.blobs import MemBlobStore


def _counts(s):
    r = s.execute("select count(*) as n, sum(v) as t from kv")
    return int(r.column("n")[0]), int(r.column("t")[0])


def _mk(store):
    c = Cluster(store=store)
    s = c.session()
    s.execute("create table kv (k bigint not null, v bigint, "
              "primary key (k)) with (shards = 3)")
    s.execute("insert into kv (k, v) values " + ", ".join(
        f"({i}, {i * 2})" for i in range(200)))
    return c, s


def test_split_and_merge_preserve_data():
    store = MemBlobStore()
    c, s = _mk(store)
    before = _counts(s)
    assert before == (200, 2 * sum(range(200)))

    # SPLIT 3 -> 6
    gen = c.reshard_table("kv", 6)
    assert gen == 1
    assert len(c.tables["kv"].shards) == 6
    assert _counts(s) == before
    # every new shard holds some data (hash routing spreads keys)
    assert all(
        sh.visible_portions() for sh in c.tables["kv"].shards)
    # old generation's storage is gone
    assert not [b for b in store.list("kv/0/")]

    # writes keep flowing after the cutover
    s.execute("insert into kv (k, v) values (1000, 1)")
    assert _counts(s) == (201, before[1] + 1)

    # MERGE 6 -> 2
    gen = c.reshard_table("kv", 2)
    assert gen == 2
    assert len(c.tables["kv"].shards) == 2
    assert _counts(s) == (201, before[1] + 1)


def test_reshard_survives_reboot():
    store = MemBlobStore()
    c, s = _mk(store)
    c.reshard_table("kv", 5)
    want = _counts(s)

    # reboot the whole cluster from storage: the scheme journal carries
    # (n_shards=5, gen=1)
    c2 = Cluster(store=store)  # Cluster always boots from its store
    s2 = c2.session()
    assert len(c2.tables["kv"].shards) == 5
    assert c2.tables["kv"].gen == 1
    assert _counts(s2) == want


def test_row_table_reshard_and_reboot():
    """Row-store split/merge: same cutover protocol as column tables."""
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("create table r (k bigint not null, v bigint, "
              "primary key (k)) with (store = row, shards = 2)")
    s.execute("insert into r (k, v) values " + ", ".join(
        f"({i}, {i})" for i in range(100)))

    def counts():
        res = s.execute("select count(*) as n, sum(v) as t from r")
        return int(res.column("n")[0]), int(res.column("t")[0])

    before = counts()
    gen = c.reshard_table("r", 5)
    assert gen == 1 and len(c.tables["r"].shards) == 5
    assert counts() == before
    s.execute("insert into r (k, v) values (500, 1)")
    assert counts() == (101, before[1] + 1)

    c2 = Cluster(store=store)
    s2 = c2.session()
    assert len(c2.tables["r"].shards) == 5
    res = s2.execute("select count(*) as n from r")
    assert int(res.column("n")[0]) == 101
    # point reads still route correctly after the reshard
    assert c2.tables["r"].read_row((500,))["v"] == 1


def test_crashed_reshard_orphans_are_swept():
    """A crash BEFORE the scheme cutover: the half-built generation's
    blobs are orphans; boot sweeps them and serves the old generation."""
    store = MemBlobStore()
    c, s = _mk(store)
    want = _counts(s)
    t = c.tables["kv"]
    # build the new generation but 'crash' before the scheme journal
    t.reshard(8)
    assert any(b.startswith("kv/g1/") for b in store.list("kv/"))

    c2 = Cluster(store=store)  # Cluster always boots from its store
    s2 = c2.session()
    assert len(c2.tables["kv"].shards) == 3  # old generation serves
    assert _counts(s2) == want
    assert not any(
        b.startswith("kv/g1/") for b in store.list("kv/"))  # swept


def test_load_driven_split_and_merge():
    """Stats-driven shard management (VERDICT r4 missing 9; reference
    schemeshard__table_stats.cpp): crossing the rows/shard threshold
    splits at the background pass, deletion far below it merges —
    queries see identical data throughout."""
    import numpy as np

    from ydb_tpu.config import AppConfig
    from ydb_tpu.kqp.session import Cluster

    c = Cluster(config=AppConfig(n_shards=1, split_rows_per_shard=100,
                                 max_auto_shards=8))
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (shards = 1)")
    for lo in range(0, 500, 100):
        s.execute("INSERT INTO t VALUES " + ", ".join(
            f"({i}, {i * 3})" for i in range(lo, lo + 100)))
    assert len(c.tables["t"].shards) == 1
    st = c.run_background()
    assert st["splits"] >= 1
    n_after = len(c.tables["t"].shards)
    assert n_after > 1
    # repeated passes converge (rows/shard under threshold or cap)
    for _ in range(4):
        c.run_background()
    n_stable = len(c.tables["t"].shards)
    assert 500 / n_stable <= 100 or n_stable == 8
    out = s.execute("SELECT COUNT(*) AS n, SUM(t.v) AS sv FROM t")
    assert int(np.asarray(out.cols["n"][0])[0]) == 500
    assert int(np.asarray(out.cols["sv"][0])[0]) == sum(
        i * 3 for i in range(500))
    # merge: knock rows far below threshold/8 via a fresh small table
    # state — simulate by resharding check on low-rows table
    s.execute("CREATE TABLE small (id int64, PRIMARY KEY (id)) "
              "WITH (shards = 4)")
    s.execute("INSERT INTO small VALUES (1), (2), (3)")
    st2 = c.run_background()
    assert st2["merges"] >= 1
    assert len(c.tables["small"].shards) < 4
    out2 = s.execute("SELECT COUNT(*) AS n FROM small")
    assert int(np.asarray(out2.cols["n"][0])[0]) == 3
