"""DataShard execution-unit pipeline: dependency-ordered wait/restart
(VERDICT r4 missing 10; reference execution_unit_kind.h:7 +
datashard_pipeline.cpp). Conflicting operations park at WAIT_DEPS and
restart there when their blocker completes; plan-step arrival is a real
hold point (WAIT_PLAN) so operations genuinely overlap in flight."""

import pytest

from ydb_tpu import dtypes
from ydb_tpu.datashard.pipeline import ExecutionPipeline, Status, Unit
from ydb_tpu.datashard.shard import DataShard, RowOp
from ydb_tpu.engine.blobs import MemBlobStore


def _shard():
    schema = dtypes.schema(("id", dtypes.INT64), ("v", dtypes.INT64))
    return DataShard("s0", schema, MemBlobStore(), pk_columns=("id",))


def test_conflicting_ops_wait_and_restart():
    shard = _shard()
    p = ExecutionPipeline(shard, auto_plan=False)
    a = p.submit([RowOp((1,), {"id": 1, "v": 10}),
                  RowOp((2,), {"id": 2, "v": 20})])
    assert a.status is Status.WAITING and a.unit is Unit.WAIT_PLAN
    # B conflicts on key (2,): parks at WAIT_DEPS behind A
    b = p.submit([RowOp((2,), {"id": 2, "v": 99})])
    assert b.status is Status.WAITING and b.unit is Unit.WAIT_DEPS
    assert b.deps == {a.op_id}
    # C touches disjoint keys: sails past WAIT_DEPS to WAIT_PLAN
    c = p.submit([RowOp((7,), {"id": 7, "v": 70})])
    assert c.unit is Unit.WAIT_PLAN and "wait_deps" in c.trace
    assert p.in_flight == 3
    # A's plan step arrives: A commits; B RESTARTS at WAIT_DEPS and
    # advances to WAIT_PLAN (observable in its trace)
    p.plan(a.op_id)
    assert a.status is Status.DONE and a.step is not None
    assert b.unit is Unit.WAIT_PLAN
    assert b.trace.count("wait_deps") == 2  # parked + restarted
    p.plan(b.op_id)
    p.plan(c.op_id)
    assert b.status is Status.DONE and c.status is Status.DONE
    assert b.step > a.step  # dependency order carried into commit order
    # last write wins on the contended key
    rows = {k: r for page in shard.read(shard.snap, keys=[(2,)])
            for k, r in page}
    assert rows[(2,)]["v"] == 99


def test_abort_releases_waiters():
    shard = _shard()
    p = ExecutionPipeline(shard, auto_plan=False)
    lock = shard.acquire_lock()
    # the lock must OBSERVE the key before a conflicting write can
    # break it (optimistic-lock semantics)
    for _page in shard.read(shard.snap, keys=[(1,)], lock_id=lock):
        pass
    a = p.submit([RowOp((1,), {"id": 1, "v": 1})], lock_id=lock)
    b = p.submit([RowOp((1,), {"id": 1, "v": 2})])
    assert b.status is Status.WAITING
    # break A's lock, then deliver its plan: PREPARE aborts it...
    # (lock check happens at CHECK for new ops and PREPARE for staged)
    shard._break_locks((1,))
    with pytest.raises(ValueError):
        p.plan(999)  # unknown op refuses
    p.plan(a.op_id)
    assert a.status is Status.ABORTED and "lock" in a.error
    # ...and B was released, restarted, and can complete
    assert b.unit is Unit.WAIT_PLAN
    p.plan(b.op_id)
    assert b.status is Status.DONE
    rows = {k: r for page in shard.read(shard.snap, keys=[(1,)])
            for k, r in page}
    assert rows[(1,)]["v"] == 2


def test_full_trace_and_autoplan():
    shard = _shard()
    p = ExecutionPipeline(shard)  # auto_plan: no external coordinator
    op = p.submit([RowOp((5,), {"id": 5, "v": 5})])
    assert op.status is Status.DONE
    assert op.trace == ["check", "build_deps", "wait_deps", "build_tx",
                        "prepare", "wait_plan", "execute", "complete"]
    bad = p.submit([RowOp((6,), {"id": 6, "nope": 1})])
    assert bad.status is Status.ABORTED and "unknown column" in bad.error
