"""Kesus coordination tablet + SequenceShard tests: semaphore
contention/waiter promotion, ephemeral locks, session expiry recovery,
reboot survival, durable sequence ranges (reference:
ydb/core/kesus/tablet, ydb/core/tx/sequenceshard)."""

import pytest

from conftest import Clock

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.tablet.kesus import KesusTablet, SequenceShard



def test_semaphore_acquire_release_and_waiters():
    k = KesusTablet("k1", MemBlobStore())
    s1 = k.attach_session()
    s2 = k.attach_session()
    s3 = k.attach_session()
    k.create_semaphore("res", limit=2)
    assert k.acquire(s1, "res")
    assert k.acquire(s2, "res")
    # full: immediate reject without timeout, queue with timeout
    assert not k.acquire(s3, "res", timeout_s=0)
    assert not k.acquire(s3, "res", timeout_s=60)
    d = k.describe("res")
    assert set(d["owners"]) == {s1, s2} and d["waiters"] == [s3]
    # release -> FIFO promotion
    assert k.release(s1, "res") == [s3]
    d = k.describe("res")
    assert set(d["owners"]) == {s2, s3} and d["waiters"] == []


def test_counting_semaphore_respects_counts():
    k = KesusTablet("k2", MemBlobStore())
    s1, s2 = k.attach_session(), k.attach_session()
    k.create_semaphore("slots", limit=10)
    assert k.acquire(s1, "slots", count=7)
    assert not k.acquire(s2, "slots", count=4)  # 7+4 > 10
    assert k.release(s1, "slots") == []
    assert k.acquire(s2, "slots", count=4)


def test_ephemeral_lock_lifecycle():
    k = KesusTablet("k3", MemBlobStore())
    s1, s2 = k.attach_session(), k.attach_session()
    # first acquire creates the lock; second contends
    assert k.acquire(s1, "mylock", ephemeral=True)
    assert not k.acquire(s2, "mylock", ephemeral=True)
    k.release(s1, "mylock")
    # fully released ephemeral semaphore vanishes
    with pytest.raises(KeyError):
        k.describe("mylock")
    assert k.acquire(s2, "mylock", ephemeral=True)


def test_session_expiry_releases_holds():
    clock = Clock(100.0)
    k = KesusTablet("k4", MemBlobStore(), now=clock)
    s1 = k.attach_session(timeout_s=10)
    s2 = k.attach_session(timeout_s=1000)
    k.create_semaphore("res", limit=1)
    assert k.acquire(s1, "res")
    assert not k.acquire(s2, "res", timeout_s=60)
    clock.t += 50  # s1 deadline passes
    dead = k.tick()
    assert dead == [s1]
    # s2 promoted when the dead session's hold was dropped
    assert k.describe("res")["owners"] == {s2: 1}


def test_ping_extends_session():
    clock = Clock(100.0)
    k = KesusTablet("k5", MemBlobStore(), now=clock)
    s1 = k.attach_session(timeout_s=10)
    clock.t += 8
    assert k.ping_session(s1)
    clock.t += 8  # past the original deadline, inside the new one
    assert k.tick() == []
    clock.t += 5
    assert k.tick() == [s1]


def test_kesus_reboots_with_state():
    store = MemBlobStore()
    k = KesusTablet("k6", store)
    s1 = k.attach_session(timeout_s=1000)
    k.create_semaphore("res", limit=3)
    assert k.acquire(s1, "res", count=2)

    k2 = KesusTablet("k6", store)  # reboot from the same storage
    d = k2.describe("res")
    assert d["owners"] == {s1: 2} and d["limit"] == 3
    # the rebooted tablet keeps serving: release + new acquire work
    k2.release(s1, "res")
    s2 = k2.attach_session()
    assert s2 > s1
    assert k2.acquire(s2, "res", count=3)


def test_tick_never_promotes_a_co_dying_session():
    """Two sessions dying in one tick: the waiter among them must NOT
    end up owning the semaphore (code-review regression)."""
    clock = Clock(100.0)
    k = KesusTablet("kr1", MemBlobStore(), now=clock)
    s1 = k.attach_session(timeout_s=10)
    s2 = k.attach_session(timeout_s=10)
    k.create_semaphore("sem", limit=1)
    assert k.acquire(s1, "sem")
    assert not k.acquire(s2, "sem", timeout_s=1000)
    clock.t += 50  # both sessions lapse together
    assert k.tick() == sorted([s1, s2])
    d = k.describe("sem")
    assert d["owners"] == {} and d["waiters"] == []


def test_lapsed_waiter_is_never_promoted():
    clock = Clock(100.0)
    k = KesusTablet("kr2", MemBlobStore(), now=clock)
    s1 = k.attach_session(timeout_s=10_000)
    s2 = k.attach_session(timeout_s=10_000)
    k.create_semaphore("sem", limit=1)
    assert k.acquire(s1, "sem")
    assert not k.acquire(s2, "sem", timeout_s=5)  # waiter deadline +5
    clock.t += 50  # waiter lapsed (sessions still alive)
    assert k.release(s1, "sem") == []  # no stale promotion
    assert k.describe("sem")["owners"] == {}
    # the semaphore is free again: a fresh acquire succeeds instantly
    assert k.acquire(s1, "sem", timeout_s=5)
    assert k.describe("sem")["owners"] == {s1: 1}
    # and tick sweeps any lapsed waiters out of the queue
    assert not k.acquire(s2, "sem", timeout_s=5)
    clock.t += 50
    k.tick()
    assert k.describe("sem")["waiters"] == []


def test_delete_semaphore_clears_stale_waiters():
    k = KesusTablet("kr3", MemBlobStore())
    s1, s2 = k.attach_session(), k.attach_session()
    k.create_semaphore("x", limit=0)
    assert not k.acquire(s1, "x", timeout_s=10_000)  # queued forever
    k.delete_semaphore("x")
    k.create_semaphore("x", limit=5)
    assert k.acquire(s2, "x")
    assert k.release(s2, "x") == []  # stale waiter must not reappear
    assert k.describe("x")["owners"] == {}


def test_retried_acquire_does_not_duplicate_waiter():
    k = KesusTablet("kr4", MemBlobStore())
    s1, s2, s3 = (k.attach_session() for _ in range(3))
    k.create_semaphore("sem", limit=1)
    assert k.acquire(s1, "sem")
    assert not k.acquire(s2, "sem", timeout_s=60)
    assert not k.acquire(s2, "sem", timeout_s=60)  # client retry
    assert not k.acquire(s3, "sem", timeout_s=60)
    assert k.describe("sem")["waiters"] == [s2, s3]
    assert k.release(s1, "sem") == [s2]
    # s2's promotion must not double-count: s3 fits after s2 releases
    assert k.release(s2, "sem") == [s3]


def test_ephemeral_erase_clears_unpromotable_waiters():
    k = KesusTablet("kr5", MemBlobStore())
    s1, s2, s3 = (k.attach_session() for _ in range(3))
    assert k.acquire(s1, "L", ephemeral=True)  # limit=1
    # a count-2 waiter can never fit a limit-1 ephemeral lock
    assert not k.acquire(s2, "L", count=2, timeout_s=1000)
    assert k.release(s1, "L") == []  # lock vanishes, waiter must too
    assert k.acquire(s3, "L", ephemeral=True)
    assert k.describe("L")["waiters"] == []
    assert k.release(s3, "L") == []  # stale s2 never resurrects


def test_sequence_descending():
    seq = SequenceShard("sd", MemBlobStore())
    seq.create_sequence("down", start=100, increment=-1, cache=10)
    got = [seq.next_val("down") for _ in range(12)]
    assert got == list(range(100, 88, -1))  # no skips inside ranges


def test_sequence_durable_ranges():
    store = MemBlobStore()
    seq = SequenceShard("s1", store)
    seq.create_sequence("ids", start=1, cache=5)
    got = [seq.next_val("ids") for _ in range(7)]
    assert got == [1, 2, 3, 4, 5, 6, 7]

    # reboot: cached-but-unused values are skipped, never repeated
    seq2 = SequenceShard("s1", store)
    nxt = seq2.next_val("ids")
    assert nxt == 11  # second range [6, 11) was burned by the crash
    assert seq2.next_val("ids") == 12


def test_sequence_increment_and_missing():
    seq = SequenceShard("s2", MemBlobStore())
    seq.create_sequence("even", start=0, increment=2, cache=3)
    assert [seq.next_val("even") for _ in range(4)] == [0, 2, 4, 6]
    with pytest.raises(KeyError):
        seq.next_val("nope")
