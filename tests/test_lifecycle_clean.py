"""Tier-1 enforcement: the resource-lifecycle analyzer runs clean over
the whole ydb_tpu package (the analog of test_concurrency_clean for
C-rules). A finding here means a code change introduced an
acquire/release pairing hazard — fix the code or, for a reviewed false
positive, add a ``# ydb-lint: disable=R00x`` pragma with a comment
saying why."""

from pathlib import Path

from ydb_tpu.analysis import lifecycle
from ydb_tpu.analysis.paths import collect_files

PKG = Path(lifecycle.__file__).resolve().parents[1]


def test_lifecycle_clean_tree_wide():
    findings = lifecycle.check_paths(collect_files([PKG]))
    msg = "\n".join(f.render() for f in findings)
    assert findings == [], f"lifecycle findings:\n{msg}"


def test_unified_entrypoint_clean_tree_wide():
    """The one-command surface (python -m ydb_tpu.analysis) CI invokes
    must agree: every stage clean over the package. On failure the
    message is the per-stage summary (file:line: code message), not a
    raw dict dump."""
    from ydb_tpu.analysis.__main__ import format_findings, run_all

    stages = run_all([PKG])
    assert set(stages) == {"verify", "lint", "concurrency",
                           "lifecycle", "hotpath", "devmem"}
    bad = {k: v for k, v in stages.items() if v}
    assert not bad, \
        f"unified analyzer findings:\n{format_findings(stages)}"
