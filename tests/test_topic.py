"""PersQueue topics + CDC change exchange tests (SURVEY.md §2.13, §2.6):
offsets, producer dedup, consumer commits, retention, reboot, and the
row-table changefeed with exactly-once delivery."""

import json

import pytest

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.topic.pq import Partition
from ydb_tpu.topic.topic import Topic


def test_partition_write_read_offsets():
    p = Partition("t/0", MemBlobStore())
    offs = p.write([{"data": "a"}, {"data": "b"}, {"data": "c"}])
    assert offs == [0, 1, 2]
    assert p.head_offset == 3
    msgs = p.read(1)
    assert [(m["offset"], m["data"]) for m in msgs] == [(1, "b"),
                                                        (2, "c")]


def test_producer_seqno_dedup():
    p = Partition("t/1", MemBlobStore())
    assert p.write([{"data": "a"}], producer="w1", first_seqno=1) == [0]
    # exact retry: dropped
    assert p.write([{"data": "a"}], producer="w1", first_seqno=1) == [-1]
    # next seqno: accepted
    assert p.write([{"data": "b"}], producer="w1", first_seqno=2) == [1]
    # other producer independent
    assert p.write([{"data": "z"}], producer="w2", first_seqno=1) == [2]
    assert [m["data"] for m in p.read(0)] == ["a", "b", "z"]


def test_consumer_commit_and_retention():
    p = Partition("t/2", MemBlobStore())
    p.write([{"data": str(i), "ts": float(i)} for i in range(10)])
    p.commit("c1", 4)
    p.commit("c2", 8)
    assert p.committed("c1") == 4
    # default vacuum: below slowest consumer
    removed = p.vacuum()
    assert removed == 4 and p.tail_offset == 4
    assert p.read(0)[0]["offset"] == 4
    # age-based retention ignores consumers
    removed = p.vacuum(older_than_ts=7.0)
    assert p.tail_offset == 7
    # count-based
    p.vacuum(keep_offsets=1)
    assert p.tail_offset == 9
    # commits below tail clamp naturally on read
    assert [m["offset"] for m in p.read(0)] == [9]


def test_partition_survives_reboot():
    store = MemBlobStore()
    p = Partition("t/3", store)
    p.write([{"data": "x"}], producer="w", first_seqno=5)
    p.commit("c", 1)
    p2 = Partition("t/3", store)
    assert p2.head_offset == 1
    assert p2.committed("c") == 1
    # producer state survives: a replayed seqno still dedups
    assert p2.write([{"data": "x"}], producer="w", first_seqno=5) == [-1]


def test_topic_key_routing_and_read_session():
    t = Topic("events", MemBlobStore(), n_partitions=3)
    for i in range(30):
        t.write(f"m{i}", key=f"k{i % 5}")
    # same key -> same partition (ordering per key)
    p_first = t.partition_for("k0")
    assert all(t.partition_for("k0") == p_first for _ in range(3))
    r = t.reader("c1")
    batch = r.read_batch()
    assert len(batch) == 30
    r.commit_batch(batch)
    assert r.read_batch() == []


def test_changefeed_end_to_end():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE acc (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 2, changefeed = on)")
    s.execute("INSERT INTO acc VALUES (1, 10), (2, 20)")
    s.execute("UPDATE acc SET v = 11 WHERE id = 1")
    s.execute("DELETE FROM acc WHERE id = 2")
    shipped = c.run_background()["cdc_shipped"]
    assert shipped == 4  # 2 inserts + 1 update + 1 delete
    reader = c.topics["acc_changefeed"].reader("app")
    events = [json.loads(m["data"]) for m in reader.read_batch()]
    by_key = {}
    for e in events:
        by_key.setdefault(tuple(e["key"]), []).append(e)
    ins1, upd1 = by_key[(1,)]
    assert ins1["old"] is None and ins1["new"]["v"] == 10
    assert upd1["old"]["v"] == 10 and upd1["new"]["v"] == 11
    ins2, del2 = by_key[(2,)]
    assert del2["new"] is None and del2["old"]["v"] == 20
    # ordering per key follows commit order
    assert ins1["step"] < upd1["step"]
    # idempotent redelivery: drain again ships nothing new
    assert c.run_background()["cdc_shipped"] == 0
    assert len(c.topics["acc_changefeed"].reader("b").read_batch()) == 4


def test_changefeed_crash_between_ship_and_ack_is_exactly_once():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1, changefeed = on)")
    s.execute("INSERT INTO t VALUES (1)")
    t = c.tables["t"]
    topic = c.topics["t_changefeed"]
    # ship but "crash" before ack: changes remain queued
    shard = t.shards[0]
    changes = shard.pending_changes()
    t.drain_changes_to(topic)
    # simulate redelivery of the same changes (ack lost): re-ship raw
    for ch in changes:
        p = topic.partition_for(json.dumps(ch["key"]))
        topic.partitions[p].write(
            [{"data": "dup"}], producer=f"cdc/{shard.shard_id}",
            first_seqno=ch["seq"])
    msgs = topic.reader("x").read_batch()
    assert len(msgs) == 1  # producer dedup swallowed the redelivery


def test_changefeed_survives_reboot():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, changefeed = on)")
    s.execute("INSERT INTO t VALUES (1)")
    # crash BEFORE drain: the change queue is durable
    c2 = Cluster(store=store)
    assert c2.run_background()["cdc_shipped"] == 1
    msgs = c2.topics["t_changefeed"].reader("r").read_batch()
    assert len(msgs) == 1
    assert json.loads(msgs[0]["data"])["key"] == [1]


def test_cdc_old_image_within_one_commit():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1, changefeed = on)")
    s.execute("INSERT INTO t VALUES (1, 10), (1, 20)")  # same key twice
    c.run_background()
    events = [json.loads(m["data"])
              for m in c.topics["t_changefeed"].reader("r").read_batch()]
    assert events[0]["old"] is None and events[0]["new"]["v"] == 10
    assert events[1]["old"]["v"] == 10 and events[1]["new"]["v"] == 20


def test_drop_column_strip_emits_no_cdc_events():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, secret int64, "
              "PRIMARY KEY (id)) WITH (store = row, changefeed = on)")
    s.execute("INSERT INTO t VALUES (1, 42)")
    c.run_background()
    s.execute("ALTER TABLE t DROP COLUMN secret")
    assert c.run_background()["cdc_shipped"] == 0  # no phantom updates
