"""Path ACL tests: grants with subtree inheritance, enforcement at
the session for reads/writes/DDL, bootstrap-friendly activation,
durable ACEs (reference: library/aclib, schemeshard ACLs,
ticket-parser principals)."""

import pytest

from ydb_tpu.kqp.session import Cluster, PlanError


@pytest.fixture
def cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1, 10)")
    return c


def test_acl_disabled_until_first_ace(cluster):
    s = cluster.session()
    s.principal = "alice"
    # no ACEs anywhere: authenticated sessions keep full access
    assert int(s.execute("SELECT v FROM t").column("v")[0]) == 10
    cluster.scheme.grant("/t", "bob", "read")
    # enforcement now active: alice has no grant
    with pytest.raises(PlanError, match="access denied"):
        s.execute("SELECT v FROM t")


def test_grants_enforce_per_permission(cluster):
    sch = cluster.scheme
    sch.grant("/t", "reader", "read")
    sch.grant("/t", "writer", ["read", "write"])
    sch.grant("/", "admin", "full")

    r = cluster.session()
    r.principal = "reader"
    assert int(r.execute("SELECT v FROM t").column("v")[0]) == 10
    with pytest.raises(PlanError, match="access denied"):
        r.execute("INSERT INTO t VALUES (2, 20)")
    with pytest.raises(PlanError, match="access denied"):
        r.execute("DROP TABLE t")

    w = cluster.session()
    w.principal = "writer"
    w.execute("INSERT INTO t VALUES (2, 20)")
    with pytest.raises(PlanError, match="access denied"):
        w.execute("CREATE TABLE t2 (id int64, PRIMARY KEY (id))")

    a = cluster.session()
    a.principal = "admin"  # root grant inherits down the tree
    a.execute("CREATE TABLE t2 (id int64, PRIMARY KEY (id))")
    a.execute("INSERT INTO t2 VALUES (1)")
    assert int(a.execute("SELECT count(*) AS c FROM t2")
               .column("c")[0]) == 1


def test_revoke_and_access_list(cluster):
    sch = cluster.scheme
    sch.grant("/t", "u", ["read", "write"])
    assert sch.access_list("/t") == {"u": ["read", "write"]}
    sch.revoke("/t", "u", "write")
    assert sch.access_list("/t") == {"u": ["read"]}
    s = cluster.session()
    s.principal = "u"
    s.execute("SELECT v FROM t")
    with pytest.raises(PlanError, match="access denied"):
        s.execute("INSERT INTO t VALUES (3, 30)")
    sch.revoke("/t", "u")
    assert sch.access_list("/t") == {}


def test_aces_survive_reboot(cluster):
    cluster.scheme.grant("/t", "u", "read")
    c2 = Cluster(store=cluster.store)
    assert c2.scheme.access_list("/t") == {"u": ["read"]}
    s = c2.session()
    s.principal = "u"
    assert s.execute("SELECT v FROM t").num_rows == 1
    with pytest.raises(PlanError, match="access denied"):
        s.execute("DROP TABLE t")


def test_joins_check_every_scanned_table(cluster):
    s0 = cluster.session()
    s0.execute("CREATE TABLE u (id int64, w int64, PRIMARY KEY (id))")
    s0.execute("INSERT INTO u VALUES (1, 7)")
    cluster.scheme.grant("/t", "p", "read")  # NOT /u
    s = cluster.session()
    s.principal = "p"
    with pytest.raises(PlanError, match="access denied.*'/u'|/u"):
        s.execute("SELECT v, w FROM t, u WHERE t.id = u.id")


def test_scalar_subquery_cannot_leak_forbidden_table(cluster):
    """Plan-time subquery execution must pass the same read gate as
    the outer query (code-review security regression)."""
    s0 = cluster.session()
    s0.execute("CREATE TABLE pub (id int64, PRIMARY KEY (id))")
    s0.execute("INSERT INTO pub VALUES (1)")
    cluster.scheme.grant("/pub", "eve", "read")
    eve = cluster.session()
    eve.principal = "eve"
    with pytest.raises(PlanError, match="access denied"):
        eve.execute("SELECT id FROM pub "
                    "WHERE id <= (SELECT max(v) FROM t)")


def test_explain_requires_read_access(cluster):
    cluster.scheme.grant("/t", "other", "read")  # activate ACLs
    eve = cluster.session()
    eve.principal = "eve"
    with pytest.raises(PlanError, match="access denied"):
        eve.execute("EXPLAIN SELECT v FROM t")


def test_sys_prefix_is_read_only_exemption(cluster):
    cluster.scheme.grant("/t", "other", "read")  # activate ACLs
    eve = cluster.session()
    eve.principal = "eve"
    # reads of sys views pass without grants ...
    assert eve.execute(
        "SELECT count(*) AS c FROM sys_scheme_paths").num_rows == 1
    # ... but sys_ names grant no ddl/write escape hatch
    with pytest.raises(PlanError):
        eve.execute("CREATE TABLE sys_evil (id int64, "
                    "PRIMARY KEY (id))")
    root = cluster.session()  # even unauthenticated: prefix reserved
    with pytest.raises(PlanError, match="reserved"):
        root.execute("CREATE TABLE sys_evil (id int64, "
                     "PRIMARY KEY (id))")


def test_typo_revoke_fails_loud(cluster):
    from ydb_tpu.scheme.shard import SchemeError

    cluster.scheme.grant("/t", "u", "write")
    with pytest.raises(SchemeError, match="unknown permission"):
        cluster.scheme.revoke("/t", "u", "writes")
    assert cluster.scheme.access_list("/t") == {"u": ["write"]}


def test_session_cannot_be_hijacked_across_principals(cluster):
    from ydb_tpu.api.client import ApiError, Driver
    from ydb_tpu.api.server import make_server

    cluster.scheme.grant("/t", "alice", "read")
    server, port = make_server(cluster, port=0,
                               auth_tokens={"alice", "bob"})
    server.start()
    try:
        alice = Driver(f"127.0.0.1:{port}", auth_token="alice")
        qa = alice.query_client()  # creates a server-side session
        sid = qa.session_id
        assert sid
        import grpc

        bob = Driver(f"127.0.0.1:{port}", auth_token="bob")
        qb = bob.query_client()
        qb.session_id = sid  # guessed/stolen session id
        with pytest.raises(grpc.RpcError) as ei:
            qb.execute("SELECT v FROM t")
        assert ei.value.code() == grpc.StatusCode.PERMISSION_DENIED
        alice.close()
        bob.close()
    finally:
        server.stop(0)


def test_grpc_front_carries_principal(cluster):
    from ydb_tpu.api.client import ApiError, Driver
    from ydb_tpu.api.server import make_server

    cluster.scheme.grant("/t", "sesame", "read")
    server, port = make_server(cluster, port=0,
                               auth_tokens={"sesame", "other"})
    server.start()
    try:
        drv = Driver(f"127.0.0.1:{port}", auth_token="sesame")
        q = drv.query_client()
        out = q.execute("SELECT v FROM t")
        assert out.column("v").to_pylist() == [10]
        with pytest.raises(ApiError, match="access denied"):
            q.execute("INSERT INTO t VALUES (9, 9)")
        drv.close()
        drv2 = Driver(f"127.0.0.1:{port}", auth_token="other")
        with pytest.raises(ApiError, match="access denied"):
            drv2.query_client().execute("SELECT v FROM t")
        drv2.close()
    finally:
        server.stop(0)
