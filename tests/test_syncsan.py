"""Sync sanitizer (analysis/syncsan, YDB_TPU_SYNCSAN=1): seam
counters, statement attribution (thread-local + trace-id), warm
budget enforcement, profile / EXPLAIN ANALYZE surfacing, and the
tier-1 acceptance run — warm TPC-H Q1/Q6 through the engine-tier
scan executor must show ZERO XLA compilations and a bounded sync
count per statement."""

import threading

import numpy as np
import pytest

from ydb_tpu.analysis import syncsan
from ydb_tpu.obs.tracing import Tracer
from ydb_tpu.obs.tracing import activate as span_activate

#: the documented warm-statement sync budget for engine-tier scans:
#: one batched device_get at the deliberate result fetch, plus one
#: admission sync allowance for the morsel window on deep streams
#: (measured warm Q1/Q6: exactly 1 sync per statement)
WARM_SYNC_BUDGET = 2


@pytest.fixture(autouse=True)
def _syncsan_off_after():
    """Every test leaves the sanitizer unpinned, unbudgeted, empty."""
    yield
    syncsan.clear_budget()
    syncsan.set_force(None)
    syncsan.reset()


# ---------------- gates / None-safety ----------------


def test_disabled_is_none_safe():
    assert not syncsan.enabled()
    assert syncsan.begin_statement("q") is None
    assert syncsan.end_statement(None) is None
    syncsan.discard(None)  # no-op, no raise


def test_env_gate(monkeypatch):
    monkeypatch.setenv("YDB_TPU_SYNCSAN", "1")
    assert syncsan.enabled()
    monkeypatch.setenv("YDB_TPU_SYNCSAN", "0")
    assert not syncsan.enabled()
    syncsan.set_force(True)
    assert syncsan.enabled()  # pin beats env


def test_seams_restored_on_disarm():
    import jax
    import jax.numpy as jnp

    before = (jax.block_until_ready, jax.device_get, jnp.asarray,
              np.asarray)
    with syncsan.activate():
        assert jax.device_get is not before[1]
        assert np.asarray is not before[3]
    after = (jax.block_until_ready, jax.device_get, jnp.asarray,
             np.asarray)
    assert after == before


# ---------------- counters + attribution ----------------


def test_seam_counters_attribute_to_statement():
    import jax
    import jax.numpy as jnp

    host = np.arange(8)
    with syncsan.activate():
        st = syncsan.begin_statement("q")
        dev = jnp.asarray(host)         # H2D
        jax.block_until_ready(dev)      # sync
        back = jax.device_get(dev)      # D2H + sync
        again = np.asarray(dev)         # D2H + sync
        snap = syncsan.end_statement(st)
    np.testing.assert_array_equal(back, host)
    np.testing.assert_array_equal(again, host)
    assert snap["h2d"] >= 1
    assert snap["d2h"] >= 2
    assert snap["syncs"] >= 3
    assert snap["compiles"] == 0


def test_np_asarray_on_host_data_not_counted():
    with syncsan.activate():
        st = syncsan.begin_statement("q")
        np.asarray([1, 2, 3])  # host->host: free
        snap = syncsan.end_statement(st)
    assert snap == {"h2d": 0, "d2h": 0, "syncs": 0, "compiles": 0}


def test_trace_id_attribution_across_threads():
    """Conveyor workers carry no thread-local window; they resolve
    through the obs span they inherited and the trace-id registry."""
    import jax
    import jax.numpy as jnp

    with syncsan.activate():
        tr = Tracer()
        root = tr.trace("query")
        st = syncsan.begin_statement("q", trace_id=root.trace_id)
        dev = jnp.asarray(np.arange(4))

        def worker():
            with span_activate(root):
                jax.block_until_ready(dev)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        snap = syncsan.end_statement(st)
        root.finish()
    assert snap["syncs"] >= 1


def test_unattributed_counts_land_in_orphans():
    import jax
    import jax.numpy as jnp

    with syncsan.activate():
        jax.block_until_ready(jnp.asarray(np.arange(4)))
        tot = syncsan.totals()
    assert tot["h2d"] >= 1 and tot["syncs"] >= 1


def test_compile_listener_counts_cold_compile_only():
    import jax
    import jax.numpy as jnp

    with syncsan.activate():
        @jax.jit
        def f(x):
            return x * 2 + 1

        st = syncsan.begin_statement("cold")
        f(jnp.asarray(np.arange(6)))
        cold = syncsan.end_statement(st)
        st = syncsan.begin_statement("warm")
        f(jnp.asarray(np.arange(6)))
        warm = syncsan.end_statement(st)
    assert cold["compiles"] >= 1
    assert warm["compiles"] == 0


# ---------------- budget enforcement ----------------


def test_warm_budget_enforced_past_warmup():
    import jax
    import jax.numpy as jnp

    budget = syncsan.Budget(compiles=0, syncs=0, warmup=1)
    with syncsan.activate(budget=budget):
        st = syncsan.begin_statement("q")
        jax.block_until_ready(jnp.asarray(np.arange(4)))
        syncsan.end_statement(st)  # warmup statement: free pass
        st = syncsan.begin_statement("q")
        jax.block_until_ready(jnp.asarray(np.arange(4)))
        with pytest.raises(syncsan.SyncBudgetError, match="blocked"):
            syncsan.end_statement(st)
        # a different label gets its own warmup window
        st = syncsan.begin_statement("other")
        jax.block_until_ready(jnp.asarray(np.arange(4)))
        syncsan.end_statement(st)


def test_compile_budget_message_names_the_cache():
    with syncsan.activate(
            budget=syncsan.Budget(compiles=0, warmup=0)):
        st = syncsan.begin_statement("q")
        st.note(compiles=1)
        with pytest.raises(syncsan.SyncBudgetError,
                           match="compile cache"):
            syncsan.end_statement(st)


def test_discard_skips_enforcement():
    with syncsan.activate(
            budget=syncsan.Budget(compiles=0, syncs=0, warmup=0)):
        st = syncsan.begin_statement("q")
        st.note(syncs=5, compiles=5)
        syncsan.discard(st)  # error path: no budget raise


# ---------------- obs surfacing ----------------


def test_end_statement_annotates_span_and_profile():
    from ydb_tpu.obs.profile import build_profile

    with syncsan.activate():
        tr = Tracer()
        root = tr.trace("query")
        with span_activate(root):
            st = syncsan.begin_statement("q",
                                         trace_id=root.trace_id)
            st.note(h2d=2, d2h=1, syncs=3)
            syncsan.end_statement(st)
        root.finish()
        spans = tr.spans_for(root.trace_id)
    attrs = spans[0].attrs
    assert attrs["syncsan_h2d"] == 2
    assert attrs["syncsan_syncs"] == 3
    p = build_profile(spans, sql="q")
    assert p.syncsan == {"h2d": 2, "d2h": 1, "syncs": 3,
                         "compiles": 0}
    assert "syncsan" in p.to_dict()


def test_session_execute_populates_profile_syncsan():
    """The plain execute path: begin_statement runs BEFORE the root
    span is activated, so the session must pin the span explicitly —
    last_profile.syncsan carrying this statement's counters is the
    serving-tier bench's data source."""
    from ydb_tpu.kqp.session import Cluster

    with syncsan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE ev (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO ev VALUES (1, 2), (2, 4)")
        s.execute("SELECT sum(v) AS sv FROM ev")
        p = s.last_profile
    assert p is not None and p.syncsan, \
        "statement counters missing from the profile"
    assert set(p.syncsan) == {"h2d", "d2h", "syncs", "compiles"}


def test_explain_analyze_shows_syncsan_line():
    from ydb_tpu.kqp.session import Cluster

    with syncsan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE ev (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO ev VALUES (1, 2), (2, 4)")
        txt = s.execute("EXPLAIN ANALYZE SELECT sum(v) AS sv FROM ev")
    assert "syncsan:" in txt
    assert "compiles=" in txt


# ---------------- tier-1 acceptance: warm Q1/Q6 engine tier ----------


@pytest.fixture(scope="module")
def lineitem():
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=0.002, seed=7)
    return data, ColumnSource(
        columns=data.tables["lineitem"],
        schema=data.schema("lineitem"),
        dicts=data.dicts,
    )


def test_warm_q1_q6_zero_compiles_bounded_syncs(lineitem):
    """The acceptance budget from the dispatch-purity work: a warm
    statement through the engine tier (ScanExecutor.run_stream, the
    declared hot root) performs ZERO XLA compilations and at most
    WARM_SYNC_BUDGET blocking syncs — enforced by the sanitizer's own
    budget machinery, so a regression raises SyncBudgetError here."""
    from ydb_tpu.engine.scan import ScanExecutor
    from ydb_tpu.workload import tpch

    data, src = lineitem
    budget = syncsan.Budget(compiles=0, syncs=WARM_SYNC_BUDGET,
                            warmup=1)
    with syncsan.activate(budget=budget):
        for name, prog in (("q1", tpch.q1_program()),
                           ("q6", tpch.q6_program())):
            ex = ScanExecutor(prog, src, block_rows=4096)
            snaps = []
            for _ in range(3):
                st = syncsan.begin_statement(name)
                out = ex.run_stream(
                    src.blocks(4096, ex.read_cols))
                out.host_columns()  # the ONE deliberate fetch
                # end_statement enforces the budget past warmup —
                # a warm compile or sync regression raises here
                snaps.append(syncsan.end_statement(st))
            cold, warm = snaps[0], snaps[1:]
            assert cold["compiles"] >= 1, \
                f"{name}: cold run saw no compile — listener dead?"
            for snap in warm:
                assert snap["compiles"] == 0, (name, snap)
                assert 1 <= snap["syncs"] <= WARM_SYNC_BUDGET, \
                    (name, snap)
                assert snap["d2h"] == 1, (name, snap)  # batched fetch
