"""Per-op cross-check of the grown scalar op set (VERDICT r4 item 7):
every new Op member runs through the JAX compiler AND the CPU oracle on
random null-bearing data and must agree exactly (reference op families:
ydb/library/arrow_kernels/operations.h:5 — casts, math breadth, bit
ops, datetime extraction, div-by-zero -> NULL)."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks import TableBlock
from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.ssa import (
    AssignStep,
    Call,
    Col,
    Op,
    Program,
    ProjectStep,
    compile_program,
)

RNG = np.random.default_rng(11)
N = 257


def _inputs():
    """Input columns spanning the op domains (with nulls)."""
    return {
        "pos": (RNG.uniform(0.1, 5.0, N), dtypes.DOUBLE),      # > 0
        "unit": (RNG.uniform(-0.99, 0.99, N), dtypes.DOUBLE),  # (-1, 1)
        "ge1": (RNG.uniform(1.0, 6.0, N), dtypes.DOUBLE),      # >= 1
        "any_f": (RNG.uniform(-50.0, 50.0, N), dtypes.DOUBLE),
        "i": (RNG.integers(-100, 100, N), dtypes.INT64),
        "j": (RNG.integers(-5, 6, N), dtypes.INT64),           # incl. 0
        "sh": (RNG.integers(0, 8, N), dtypes.INT64),           # shifts
        "days": (RNG.integers(0, 20000, N).astype(np.int32),
                 dtypes.DATE),
        "us": (RNG.integers(0, 2_000_000_000, N)
               * np.int64(1_000_000), dtypes.TIMESTAMP),
    }


def _run_both(expr):
    cols = _inputs()
    sch = dtypes.schema(*((n, t) for n, (_a, t) in cols.items()))
    arrays = {n: np.asarray(a) for n, (a, _t) in cols.items()}
    validity = {n: RNG.random(N) > 0.1 for n in cols}
    blk = TableBlock.from_numpy(arrays, sch, validity)
    prog = Program((AssignStep("out", expr), ProjectStep(("out",))))
    got = compile_program(prog, sch)(blk).to_numpy()["out"]
    gval = np.asarray(
        compile_program(prog, sch)(blk).validity_numpy()["out"])
    oracle = OracleTable(
        {n: (arrays[n], validity[n]) for n in arrays}, sch)
    want_t = run_oracle(prog, oracle)
    want, wval = want_t.cols["out"]
    np.testing.assert_array_equal(gval, wval)
    ok = np.asarray(gval, dtype=bool)
    g, w = np.asarray(got)[ok], np.asarray(want)[ok]
    if g.dtype.kind == "f":
        np.testing.assert_allclose(g, w, rtol=1e-12, equal_nan=True)
    else:
        np.testing.assert_array_equal(g, w)


UNARY = {
    Op.SIN: "any_f", Op.COS: "any_f", Op.TAN: "unit",
    Op.ASIN: "unit", Op.ACOS: "unit", Op.ATAN: "any_f",
    Op.SINH: "unit", Op.COSH: "unit", Op.TANH: "any_f",
    Op.ASINH: "any_f", Op.ACOSH: "ge1", Op.ATANH: "unit",
    Op.CBRT: "any_f", Op.ERF: "any_f", Op.LOG2: "pos",
    Op.EXP2: "unit", Op.TRUNC: "any_f", Op.RINT: "any_f",
    Op.RADIANS: "any_f", Op.DEGREES: "any_f",
    Op.CAST_INT8: "j", Op.CAST_INT16: "i", Op.CAST_UINT64: "sh",
    Op.CAST_BOOL: "j", Op.BIT_NOT: "i",
}


@pytest.mark.parametrize("op", sorted(UNARY, key=lambda o: o.value))
def test_unary_op_matches_oracle(op):
    _run_both(Call(op, Col(UNARY[op])))


BINARY = {
    Op.ATAN2: ("any_f", "pos"), Op.HYPOT: ("any_f", "i"),
    Op.BIT_AND: ("i", "j"), Op.BIT_OR: ("i", "j"),
    Op.BIT_XOR: ("i", "j"), Op.SHIFT_LEFT: ("i", "sh"),
    Op.SHIFT_RIGHT: ("i", "sh"), Op.NULLIF: ("i", "j"),
    Op.DIV_INT: ("i", "j"),  # j includes 0: /0 must be NULL
}


@pytest.mark.parametrize("op", sorted(BINARY, key=lambda o: o.value))
def test_binary_op_matches_oracle(op):
    a, b = BINARY[op]
    _run_both(Call(op, Col(a), Col(b)))


DATE_OPS = (Op.DAY_OF_WEEK, Op.DAY_OF_YEAR, Op.WEEK, Op.QUARTER)


@pytest.mark.parametrize("op", sorted(DATE_OPS, key=lambda o: o.value))
def test_date_part_matches_oracle(op):
    _run_both(Call(op, Col("days")))


def test_second_matches_oracle():
    _run_both(Call(Op.SECOND, Col("us")))


def test_div_int_by_zero_is_null():
    sch = dtypes.schema(("a", dtypes.INT64), ("b", dtypes.INT64))
    blk = TableBlock.from_numpy(
        {"a": np.array([7, 8, -9]), "b": np.array([2, 0, 2])}, sch)
    prog = Program((AssignStep("q", Call(Op.DIV_INT, Col("a"),
                                         Col("b"))),
                    ProjectStep(("q",))))
    out = compile_program(prog, sch)(blk)
    assert list(np.asarray(out.validity_numpy()["q"])) == [
        True, False, True]
    got = np.asarray(out.to_numpy()["q"])
    assert got[0] == 3 and got[2] == -4  # trunc toward zero


def test_day_of_week_convention():
    # 1970-01-04 was a Sunday -> 0; 1970-01-01 Thursday -> 4
    sch = dtypes.schema(("d", dtypes.DATE))
    blk = TableBlock.from_numpy(
        {"d": np.array([3, 0], dtype=np.int32)}, sch)
    prog = Program((AssignStep("w", Call(Op.DAY_OF_WEEK, Col("d"))),
                    ProjectStep(("w",))))
    out = compile_program(prog, sch)(blk)
    assert list(np.asarray(out.to_numpy()["w"])) == [0, 4]


def test_op_vocabulary_breadth():
    """VERDICT r4 item 7 done-criterion: >= 80 scalar ops."""
    assert len(Op) >= 80, len(Op)
