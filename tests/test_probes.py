"""lwtrace-analog probes + memory observability (SURVEY §2.1 lwtrace
row, §2.14 memory-profiling row)."""

import numpy as np

from ydb_tpu.obs.probes import TraceSession, list_probes, memory_stats, probe


def test_probe_sessions_collect_and_detach():
    p = probe("test.alpha")
    q = probe("test.beta")
    assert not p  # nothing attached: fire is near-free
    p.fire(x=1)  # no-op
    with TraceSession("test.*") as sess:
        assert p and q
        p.fire(x=1)
        p.fire(x=2)
        q.fire(y=9)
    assert not p  # detached
    p.fire(x=3)   # not recorded
    assert sess.counts["test.alpha"] == 2
    assert sess.counts["test.beta"] == 1
    assert [e for e in sess.events] == [
        ("test.alpha", {"x": 1}), ("test.alpha", {"x": 2}),
        ("test.beta", {"y": 9})]
    assert "test.alpha" in list_probes()


def test_probe_predicate_filters():
    p = probe("test.gamma")
    with TraceSession("test.gamma",
                      predicate=lambda n, kw: kw["x"] > 5) as sess:
        p.fire(x=1)
        p.fire(x=10)
    assert sess.counts["test.gamma"] == 1


def test_engine_probes_fire_during_scan_and_commit():
    from ydb_tpu import dtypes
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.ssa.ops import Agg
    from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program

    schema = dtypes.schema(("id", dtypes.INT64, False),
                           ("v", dtypes.INT64))
    shard = ColumnShard("probe_s", schema, MemBlobStore(),
                        pk_column="id", upsert=True,
                        config=ShardConfig(
                            compact_portion_threshold=10 ** 9))
    prog = Program((GroupByStep(keys=(), aggs=(
        AggSpec(Agg.COUNT_ALL, None, "n"),)),))
    with TraceSession("columnshard.*") as sess:
        wid = shard.write({"id": np.arange(10, dtype=np.int64),
                           "v": np.ones(10, dtype=np.int64)})
        shard.commit([wid])
        shard.scan(prog)
    assert sess.counts["columnshard.commit"] == 1
    assert sess.counts["columnshard.scan"] == 1
    name, params = [e for e in sess.events
                    if e[0] == "columnshard.scan"][0]
    assert params["portions"] == 1


def test_memory_stats_reports_rss():
    st = memory_stats()
    assert st["vmrss_mb"] > 0
    assert st["vmhwm_mb"] >= st["vmrss_mb"] * 0.5
