"""Streaming (federated) query tests: continuous SQL over topics,
incremental group state, crash/replay exactly-once via sink seqno
dedup (reference: ydb/core/fq/libs checkpoint coordinator + row
dispatcher)."""

import json

import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.fq import FederatedQueryService, StreamingQuery
from ydb_tpu.topic.topic import Topic

EVENTS = dtypes.schema(
    ("region", dtypes.STRING, False),
    ("amount", dtypes.INT64, False),
)

SQL = ("select region, count(*) as n, sum(amount) as total "
       "from stream group by region")


def send(topic, **row):
    topic.write(json.dumps(row))


@pytest.fixture
def env():
    store = MemBlobStore()
    source = Topic("events", store, n_partitions=2)
    sink = Topic("results", store, n_partitions=1)
    svc = FederatedQueryService(store)
    return store, source, sink, svc


def sink_records(sink):
    out = []
    for m in sink.partitions[0].read(0, limit=1000):
        out.append(json.loads(m["data"]))
    return out


def test_incremental_group_aggregation(env):
    _store, source, sink, svc = env
    q = svc.create_query("agg", SQL, EVENTS, source, sink)
    send(source, region="eu", amount=10)
    send(source, region="us", amount=5)
    assert q.poll() == 2
    assert q.results() == [
        {"region": "eu", "n": 1, "total": 10},
        {"region": "us", "n": 1, "total": 5},
    ]
    # second batch folds into the same groups
    send(source, region="eu", amount=7)
    assert q.poll() == 1
    assert q.results()[0] == {"region": "eu", "n": 2, "total": 17}
    # only the changed group was re-emitted in the second batch
    recs = sink_records(sink)
    assert recs[-1] == {"region": "eu", "n": 2, "total": 17}
    assert q.poll() == 0  # no new data


def test_filter_and_min_max(env):
    _store, source, _sink, svc = env
    q = svc.create_query(
        "mm",
        "select region, min(amount) as lo, max(amount) as hi "
        "from stream where amount > 0 group by region",
        EVENTS, source)
    send(source, region="eu", amount=3)
    send(source, region="eu", amount=-99)  # filtered out
    send(source, region="eu", amount=8)
    q.poll()
    send(source, region="eu", amount=1)
    q.poll()
    assert q.results() == [{"region": "eu", "lo": 1, "hi": 8}]


def test_crash_replay_is_exactly_once(env):
    """Simulate a crash BETWEEN sink emission and checkpoint: the
    replayed batch's emission must be deduplicated by seqno."""
    store, source, sink, svc = env
    q = svc.create_query("eo", SQL, EVENTS, source, sink)
    send(source, region="eu", amount=10)
    assert q.poll() == 1
    assert len(sink_records(sink)) == 1

    # crash after emit, before checkpoint: rebuild the query from
    # storage with the checkpoint rolled back one step by replaying
    # the same batch — emulate by constructing a fresh query whose
    # tablet state we reset to the pre-poll cursor
    send(source, region="eu", amount=5)
    # poison the checkpoint path: run the batch manually
    offsets, state, seq, _meta = q._state()
    rows = [{"region": "eu", "amount": 5}]
    out = q._run_batch(rows)
    changed = q._fold(state, out)
    q.sink.partitions[0].write(
        [{"data": json.dumps(dict(zip(("region",),
                                      json.loads(k))) | state[k])}
         for k in changed],
        producer="fq/eo", first_seqno=seq + 1)
    # CRASH here: checkpoint never happens. Recover:
    q2 = StreamingQuery("eo", SQL, EVENTS, source, sink, store)
    assert q2.poll() == 1  # replays the un-checkpointed message
    recs = sink_records(sink)
    # the replayed emission was dropped by producer-seqno dedup
    assert len(recs) == 2
    assert recs[-1] == {"region": "eu", "n": 2, "total": 15}
    assert q2.results() == [{"region": "eu", "n": 2, "total": 15}]


def test_state_survives_reboot(env):
    store, source, sink, svc = env
    q = svc.create_query("rb", SQL, EVENTS, source, sink)
    send(source, region="eu", amount=4)
    q.poll()
    q2 = StreamingQuery("rb", SQL, EVENTS, source, sink, store)
    assert q2.results() == [{"region": "eu", "n": 1, "total": 4}]
    send(source, region="eu", amount=6)
    assert q2.poll() == 1
    assert q2.results() == [{"region": "eu", "n": 2, "total": 10}]


def test_poison_messages_skipped(env):
    _store, source, _sink, svc = env
    q = svc.create_query("ps", SQL, EVENTS, source, sink=None)
    source.write("not json at all")
    send(source, region="eu", amount=2)
    assert q.poll() == 1
    assert q.results() == [{"region": "eu", "n": 1, "total": 2}]


def test_tumbling_windows_with_watermark(env):
    """Event-time tumbling windows: finalize on watermark pass, emit
    once with bounds, drop too-late arrivals."""
    store, source, sink, svc = env
    EV = dtypes.schema(("region", dtypes.STRING, False),
                       ("amount", dtypes.INT64, False),
                       ("ts", dtypes.INT64, False))
    q = svc.create_query(
        "win", "select region, count(*) as n, sum(amount) as total "
        "from stream group by region", EV, source, sink,
        window=("ts", 100, 20))  # 100us windows, 20us lateness

    send(source, region="eu", amount=1, ts=10)
    send(source, region="eu", amount=2, ts=50)   # same window [0,100)
    send(source, region="us", amount=5, ts=110)  # window [100,200)
    q.poll()
    # watermark = 110-20 = 90: nothing finalized yet
    assert sink_records(sink) == []
    open_w = q.results()
    assert {w["window_start"] for w in open_w} == {0, 100}

    send(source, region="eu", amount=4, ts=95)   # in-lateness arrival
    send(source, region="eu", amount=9, ts=230)  # advances watermark
    q.poll()
    # watermark = 230-20 = 210: windows [0,100) (incl. the late ts=95
    # row) AND [100,200) finalize in order
    recs = sink_records(sink)
    assert recs == [
        {"window_start": 0, "window_end": 100,
         "region": "eu", "n": 3, "total": 7},
        {"window_start": 100, "window_end": 200,
         "region": "us", "n": 1, "total": 5},
    ]
    # too-late arrival for a finalized window: dropped + counted
    send(source, region="eu", amount=100, ts=5)
    send(source, region="us", amount=1, ts=320)
    q.poll()
    assert q.watermark_info()["late_dropped"] == 1
    recs = sink_records(sink)
    # watermark 300 finalized [200,300) (the eu ts=230 row)
    assert recs[-1] == {"window_start": 200, "window_end": 300,
                        "region": "eu", "n": 1, "total": 9}
    # finalized state dropped; only open windows remain
    assert all(w["window_start"] >= 300 for w in q.results())


def test_below_watermark_rows_fold_into_open_windows(env):
    """A row below the watermark whose WINDOW is still open must fold
    in, not count as late (code-review regression)."""
    _store, source, sink, svc = env
    EV = dtypes.schema(("region", dtypes.STRING, False),
                       ("amount", dtypes.INT64, False),
                       ("ts", dtypes.INT64, False))
    q = svc.create_query(
        "open", "select region, count(*) as n, sum(amount) as total "
        "from stream group by region", EV, source, sink,
        window=("ts", 100, 20))
    send(source, region="eu", amount=1, ts=150)  # watermark -> 130
    q.poll()
    send(source, region="eu", amount=2, ts=120)  # < watermark, window
    q.poll()                                     # [100,200) still open
    assert q.watermark_info()["late_dropped"] == 0
    send(source, region="eu", amount=0, ts=500)  # finalize [100,200)
    q.poll()
    recs = sink_records(sink)
    assert recs[0] == {"window_start": 100, "window_end": 200,
                       "region": "eu", "n": 2, "total": 3}


def test_windowed_state_survives_reboot(env):
    store, source, sink, svc = env
    EV = dtypes.schema(("region", dtypes.STRING, False),
                       ("amount", dtypes.INT64, False),
                       ("ts", dtypes.INT64, False))
    q = svc.create_query(
        "winrb", "select region, count(*) as n, sum(amount) as total "
        "from stream group by region", EV, source, sink,
        window=("ts", 100, 0))
    send(source, region="eu", amount=3, ts=10)
    q.poll()
    q2 = StreamingQuery(
        "winrb", "select region, count(*) as n, sum(amount) as total "
        "from stream group by region", EV, source, sink, store,
        window=("ts", 100, 0))
    send(source, region="eu", amount=4, ts=60)
    send(source, region="eu", amount=1, ts=150)
    q2.poll()
    recs = sink_records(sink)
    assert recs[-1] == {"window_start": 0, "window_end": 100,
                        "region": "eu", "n": 2, "total": 7}


def test_rejects_non_foldable_aggregates(env):
    store, source, _sink, _svc = env
    with pytest.raises(ValueError):
        StreamingQuery(
            "bad", "select region, avg(amount) as a from stream "
            "group by region", EVENTS, source, None, store)
