"""BlobDepot tests: dedup refcounting, crash-safe GC, reboot,
decommission, and running a real tablet over the depot adapter
(reference: ydb/core/blob_depot)."""

import pytest

from ydb_tpu.blobstorage.blob_depot import BlobDepot, DepotBlobStore
from ydb_tpu.engine.blobs import MemBlobStore


def test_dedup_and_refcounted_delete():
    be = MemBlobStore()
    d = BlobDepot("d1", be)
    payload = b"x" * 1000
    d.put("a", payload)
    d.put("b", payload)       # same content: stored once
    d.put("c", b"different")
    st = d.stats()
    assert st["names"] == 3 and st["payloads"] == 2
    assert st["logical_bytes"] == 2009
    assert st["physical_bytes"] == 1009

    d.delete("a")             # refcount 2 -> 1: payload stays
    assert d.get("b") == payload
    d.delete("b")             # 1 -> 0: payload physically gone
    assert not any(k.startswith("depot/d1/data/")
                   and b"x" * 10 in be.get(k)
                   for k in be.list("depot/d1/data/"))
    with pytest.raises(KeyError):
        d.get("a")
    assert d.get("c") == b"different"


def test_overwrite_moves_reference_and_sweeps():
    be = MemBlobStore()
    d = BlobDepot("d2", be)
    d.put("k", b"v1")
    phys_before = set(be.list("depot/d2/data/"))
    d.put("k", b"v2")
    assert d.get("k") == b"v2"
    st = d.stats()
    assert st["names"] == 1 and st["payloads"] == 1
    # the displaced payload was physically collected, not just
    # unreferenced (overwrite-only workloads must not leak)
    phys_after = set(be.list("depot/d2/data/"))
    assert len(phys_after) == 1 and phys_after != phys_before


def test_gc_resurrection_safe():
    """A digest re-referenced between trash-mark and sweep must not be
    deleted."""
    be = MemBlobStore()
    d = BlobDepot("d3", be)
    d.put("a", b"payload")
    # mark trash without sweeping (delete() normally sweeps; emulate a
    # crash between the index commit and the sweep)
    def fn(txc):
        row = txc.get("names", ("a",))
        txc.erase("names", ("a",))
        d._dec_locked(txc, row["digest"])
    d.executor.run(fn)
    d.put("b", b"payload")  # resurrects the digest
    assert d.collect_garbage() == 0  # unmarked, not deleted
    assert d.get("b") == b"payload"


def test_depot_reboot():
    be = MemBlobStore()
    d = BlobDepot("d4", be)
    d.put("a", b"one")
    d.put("b", b"two")
    d2 = BlobDepot("d4", be)  # reboot over the same backend
    assert d2.get("a") == b"one" and d2.get("b") == b"two"
    assert d2.stats()["names"] == 2
    d2.delete("a")
    with pytest.raises(KeyError):
        d2.get("a")


def test_boot_sweeps_crash_trash():
    """Trash left by a crash between index commit and physical delete
    is reclaimed on the next boot."""
    be = MemBlobStore()
    d = BlobDepot("d7", be)
    d.put("a", b"doomed")

    # emulate the crash: index drops the name and trash-marks, but the
    # physical delete never runs
    def fn(txc):
        row = txc.get("names", ("a",))
        txc.erase("names", ("a",))
        d._dec_locked(txc, row["digest"])
    d.executor.run(fn)
    assert be.list("depot/d7/data/")  # garbage present

    d2 = BlobDepot("d7", be)  # boot sweeps
    assert be.list("depot/d7/data/") == []
    assert d2.stats()["payloads"] == 0


def test_decommit_never_touches_sibling_depots():
    be = MemBlobStore()
    d_a = BlobDepot("da", be)
    d_a.put("x", b"payload-a")
    d_b = BlobDepot("db", be)
    assert d_b.decommit("") == 0  # nothing outside depot/tablet space
    assert d_a.get("x") == b"payload-a"  # sibling untouched


def test_decommit_absorbs_direct_blobs():
    be = MemBlobStore()
    be.put("legacy/1", b"aaa")
    be.put("legacy/2", b"bbb")
    be.put("legacy/3", b"aaa")  # dup content
    d = BlobDepot("d5", be)
    assert d.decommit("legacy/") == 3
    assert be.list("legacy/") == []  # originals drained
    assert d.get("legacy/1") == b"aaa" and d.get("legacy/2") == b"bbb"
    assert d.stats()["payloads"] == 2  # deduped during absorption


def test_tablet_runs_over_depot_adapter():
    """A real tablet executor (PQ partition) works unchanged over the
    depot's virtual store."""
    from ydb_tpu.topic.pq import Partition

    be = MemBlobStore()
    depot = BlobDepot("vg", be)
    store = DepotBlobStore(depot)
    p = Partition("pq0", store)
    offs = p.write([{"data": f"m{i}"} for i in range(5)])
    assert offs == list(range(5))
    # reboot the partition over the same depot: WAL replays through
    # the indirection
    p2 = Partition("pq0", store)
    msgs = p2.read(0, limit=10)
    assert [m["data"] for m in msgs] == [f"m{i}" for i in range(5)]
