"""Streaming read-iterator protocol tests: quota credit flow,
continuation resume across shard reboot, snapshot stability
(reference: datashard__read_iterator.cpp, kqp_read_actor.cpp)."""

import pytest

from ydb_tpu import dtypes
from ydb_tpu.datashard.read_iterator import ReadIterator
from ydb_tpu.datashard.shard import DataShard, RowOp
from ydb_tpu.engine.blobs import MemBlobStore

SCHEMA = dtypes.schema(("id", dtypes.INT64, False),
                       ("v", dtypes.INT64, True))


def make_shard(n_rows=20, store=None):
    store = store if store is not None else MemBlobStore()
    s = DataShard("s0", SCHEMA, store, ("id",))
    wid = s.propose([RowOp((i,), {"id": i, "v": i * 10})
                     for i in range(n_rows)])
    s.prepare([wid])
    s.commit_at([wid], step=5)
    return store, s


def drain(it, page_rows=7):
    got = []
    while True:
        page = it.next_page(page_rows)
        if page is None:
            it.ack(1000)
            continue
        got.extend(page.rows)
        if page.finished:
            return got


def test_pages_quota_and_finish():
    _store, s = make_shard(20)
    it = ReadIterator(s, snapshot=5, quota_rows=5)
    p1 = it.next_page(page_rows=3)
    assert [k for k, _ in p1.rows] == [(0,), (1,), (2,)]
    assert p1.continuation == (2,) and not p1.finished
    p2 = it.next_page(page_rows=10)  # only 2 credit left
    assert len(p2.rows) == 2 and p2.continuation == (4,)
    # out of credit: stalled until ack
    assert it.next_page() is None
    it.ack(100)
    rest = drain(it)
    assert [k for k, _ in rest] == [(i,) for i in range(5, 20)]


def test_range_and_columns():
    _store, s = make_shard(20)
    it = ReadIterator(s, snapshot=5, lo=(5,), hi=(9,),
                      columns=("v",), quota_rows=100)
    rows = drain(it)
    assert [k for k, _ in rows] == [(5,), (6,), (7,), (8,)]
    assert rows[0][1] == {"v": 50}


def test_snapshot_stability_mid_stream():
    """Writes landing after the session opened never appear."""
    _store, s = make_shard(10)
    it = ReadIterator(s, snapshot=5, quota_rows=100)
    p1 = it.next_page(page_rows=4)
    assert len(p1.rows) == 4
    # a later commit inserts rows INSIDE the remaining range
    wid = s.propose([RowOp((4, ), {"id": 4, "v": 999}),
                     RowOp((100,), {"id": 100, "v": 1000})])
    s.prepare([wid])
    s.commit_at([wid], step=9)
    rest = drain(it)
    keys = [k for k, _ in p1.rows + rest]
    assert keys == [(i,) for i in range(10)]  # no (100,), old (4,)
    vals = dict(p1.rows + rest)
    assert vals[(4,)]["v"] == 40  # snapshot value, not 999


def test_resume_across_shard_reboot():
    store, s = make_shard(12)
    it = ReadIterator(s, snapshot=5, quota_rows=100)
    p1 = it.next_page(page_rows=5)
    token = it.resume_token()
    assert token["continuation"] == (4,)

    s2 = DataShard("s0", SCHEMA, store, ("id",))  # reboot
    it2 = ReadIterator.from_token(s2, token, quota_rows=100)
    rest = drain(it2)
    assert [k for k, _ in p1.rows] + [k for k, _ in rest] == \
        [(i,) for i in range(12)]


def test_iterator_fenced_by_undecided_volatile():
    from ydb_tpu.datashard.shard import VolatileUndecided

    _store, s = make_shard(5)
    wid = s.propose([RowOp((2,), {"id": 2, "v": 0})])
    assert s.apply_volatile([wid], txid=1, step=7, expected_peers=[9])
    it = ReadIterator(s, snapshot=8, quota_rows=100)
    with pytest.raises(VolatileUndecided):
        it.next_page()
    s.deliver_readset(1, 9, True)
    rows = drain(it)
    assert dict(rows)[(2,)]["v"] == 0
