"""Mediator time cast: per-node time caches follow the coordinator
barrier without coordinator round trips (SURVEY §2.5 mediator row)."""

import threading

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program
from ydb_tpu.tx.coordinator import Coordinator
from ydb_tpu.tx.mediator import Mediator, NodeTimeCache
from ydb_tpu.tx.sharded import ShardedTable

SCHEMA = dtypes.schema(("id", dtypes.INT64, False), ("v", dtypes.INT64))
COUNT = Program((GroupByStep(keys=(), aggs=(
    AggSpec(Agg.COUNT_ALL, None, "n"),)),))


def test_time_caches_follow_commits_and_reads_are_consistent():
    coord = Coordinator(MemBlobStore())
    med = Mediator(coord)
    cache_a, cache_b = med.register(), med.register()
    t = ShardedTable("t", SCHEMA, MemBlobStore(), coord, n_shards=2,
                     pk_column="id", upsert=True)
    assert cache_a.read_snapshot() == coord.read_snapshot()

    t.insert({"id": np.arange(10, dtype=np.int64),
              "v": np.ones(10, dtype=np.int64)})
    step1 = coord.read_snapshot()
    # both caches learned the barrier WITHOUT asking the coordinator
    assert cache_a.read_snapshot() == step1
    assert cache_b.read_snapshot() == step1
    # a scan at the cached snapshot sees the commit
    res = t.scan(COUNT, snap=cache_a.read_snapshot())
    assert int(res.cols["n"][0][0]) == 10

    # late joiner starts at the current barrier
    late = med.register()
    assert late.read_snapshot() == step1


def test_wait_for_blocks_until_barrier_passes():
    coord = Coordinator()
    med = Mediator(coord)
    cache = med.register()
    target = coord.read_snapshot() + 1
    got = []

    def waiter():
        got.append(cache.wait_for(target, timeout=10))

    th = threading.Thread(target=waiter)
    th.start()
    # a background (volatile) step advances the barrier
    step = coord.background_plan()
    th.join(timeout=10)
    assert not th.is_alive()
    assert got and got[0] >= target and step >= target

    empty = NodeTimeCache()
    try:
        empty.wait_for(5, timeout=0.1)
        raise AssertionError("expected TimeoutError")
    except TimeoutError:
        pass
