"""DataShard (row-store OLTP) tests: MVCC reads, 2PC, locks, read
iterator paging, SQL UPDATE/DELETE on row tables (SURVEY.md §2.6)."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.datashard.shard import DataShard, LockBroken, RowOp, TxRejected
from ydb_tpu.datashard.table import RowTable
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.sql.planner import PlanError
from ydb_tpu.tx.coordinator import Coordinator


SCHEMA = dtypes.schema(("id", dtypes.INT64), ("v", dtypes.INT64))


def _shard(store=None):
    return DataShard("t/0", SCHEMA, store or MemBlobStore(), ("id",))


def test_propose_commit_read_mvcc():
    ds = _shard()
    w1 = ds.propose([RowOp((1,), {"id": 1, "v": 10}),
                     RowOp((2,), {"id": 2, "v": 20})])
    ds.prepare([w1])
    ds.commit_at([w1], step=5)
    w2 = ds.propose([RowOp((1,), {"id": 1, "v": 11}),
                     RowOp((2,), None)])  # update + delete
    ds.commit_at([w2], step=9)

    def rows_at(snap):
        return [r for page in ds.read(snap) for r in page]

    assert rows_at(4) == []
    assert rows_at(5) == [((1,), {"id": 1, "v": 10}),
                          ((2,), {"id": 2, "v": 20})]
    assert rows_at(9) == [((1,), {"id": 1, "v": 11})]
    assert ds.last_step == 9


def test_read_iterator_paging_and_range():
    ds = _shard()
    w = ds.propose([RowOp((i,), {"id": i, "v": i}) for i in range(50)])
    ds.commit_at([w], step=1)
    pages = list(ds.read(1, page_rows=16))
    assert [len(p) for p in pages] == [16, 16, 16, 2]
    ranged = [r for page in ds.read(1, lo=(10,), hi=(20,)) for r in page]
    assert [k for k, _ in ranged] == [(i,) for i in range(10, 20)]
    pts = [r for page in ds.read(1, keys=[(3,), (99,), (7,)])
           for r in page]
    assert [k for k, _ in pts] == [(3,), (7,)]


def test_shard_survives_reboot():
    store = MemBlobStore()
    ds = _shard(store)
    w = ds.propose([RowOp((1,), {"id": 1, "v": 10})])
    ds.commit_at([w], step=3)
    ds2 = DataShard("t/0", SCHEMA, store, ("id",))
    rows = [r for page in ds2.read(3) for r in page]
    assert rows == [((1,), {"id": 1, "v": 10})]
    assert ds2.last_step == 3


def test_optimistic_lock_breaks_on_conflicting_write():
    ds = _shard()
    w = ds.propose([RowOp((1,), {"id": 1, "v": 10})])
    ds.commit_at([w], step=1)
    lock = ds.acquire_lock()
    _ = [r for page in ds.read(1, lo=(0,), hi=(100,), lock_id=lock)
         for r in page]
    # a conflicting write commits
    w2 = ds.propose([RowOp((1,), {"id": 1, "v": 99})])
    ds.commit_at([w2], step=2)
    assert ds.lock_broken(lock)
    # a tx that validated under the lock must now fail at prepare
    w3 = ds.propose([RowOp((1,), {"id": 1, "v": 50})], lock_id=lock)
    with pytest.raises(LockBroken):
        ds.prepare([w3])
    # non-conflicting lock stays valid
    lock2 = ds.acquire_lock()
    _ = [r for page in ds.read(2, keys=[(5,)], lock_id=lock2)
         for r in page]
    w4 = ds.propose([RowOp((7,), {"id": 7, "v": 1})])
    ds.commit_at([w4], step=3)
    assert not ds.lock_broken(lock2)


def test_precondition_insert_semantics():
    ds = _shard()
    w = ds.propose([RowOp((1,), {"id": 1, "v": 10})],
                   expect={(1,): None})  # INSERT: must not exist
    ds.prepare([w])
    ds.commit_at([w], step=1)
    w2 = ds.propose([RowOp((1,), {"id": 1, "v": 20})],
                    expect={(1,): None})
    with pytest.raises(TxRejected):
        ds.prepare([w2])


def test_row_table_two_phase_commit_and_abort():
    store = MemBlobStore()
    coord = Coordinator()
    t = RowTable("t", SCHEMA, store, coord, n_shards=3)
    res = t.insert({"id": np.arange(10, dtype=np.int64),
                    "v": np.arange(10, dtype=np.int64) * 10})
    assert res.committed
    src = t.source_at()
    assert sorted(src.columns["id"]) == list(range(10))
    # all-or-nothing: snapshot before commit sees nothing
    old_snap = res.step - 1
    assert t.source_at(old_snap).num_rows == 0
    t.delete_keys([(0,), (5,)])
    assert sorted(t.source_at().columns["id"]) == [1, 2, 3, 4, 6, 7, 8, 9]


def test_sql_row_table_update_delete():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE kv (id int64, city string, score double, "
              "PRIMARY KEY (id)) WITH (store = row, shards = 2)")
    s.execute("INSERT INTO kv VALUES (1, 'berlin', 1.0), "
              "(2, 'tokyo', 2.0), (3, 'berlin', 3.0)")
    out = s.execute("SELECT id, score FROM kv ORDER BY id")
    assert list(out.column("id")) == [1, 2, 3]

    s.execute("UPDATE kv SET score = score * 10 WHERE city = 'berlin'")
    out = s.execute("SELECT id, score FROM kv ORDER BY id")
    assert list(out.column("score")) == [10.0, 2.0, 30.0]

    s.execute("UPDATE kv SET city = 'kyoto' WHERE id = 2")
    out = s.execute("SELECT city FROM kv WHERE id = 2")
    assert out.strings("city") == [b"kyoto"]

    s.execute("DELETE FROM kv WHERE score >= 30")
    out = s.execute("SELECT id FROM kv ORDER BY id")
    assert list(out.column("id")) == [1, 2]

    # UPDATE on a column-store table is rejected with guidance
    s.execute("CREATE TABLE olap (id int64, PRIMARY KEY (id))")
    with pytest.raises(PlanError):
        s.execute("UPDATE olap SET id = 1")
    with pytest.raises(PlanError):
        s.execute("UPDATE kv SET id = 9")   # key column


def test_sql_row_table_survives_reboot():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE r (id int64, name string, PRIMARY KEY (id)) "
              "WITH (store = row)")
    s.execute("INSERT INTO r VALUES (1, 'a'), (2, 'b')")
    s.execute("UPDATE r SET name = 'z' WHERE id = 1")
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT id, name FROM r ORDER BY id")
    assert list(out.column("id")) == [1, 2]
    assert out.strings("name") == [b"z", b"b"]
    # joins across row + column tables work (same ColumnSource seam)
    s2 = c2.session()
    s2.execute("CREATE TABLE facts (id int64, amount int64, "
               "PRIMARY KEY (id))")
    s2.execute("INSERT INTO facts VALUES (1, 100), (2, 200), (1, 300)")
    out = s2.execute(
        "SELECT r.name AS name, sum(f.amount) AS total "
        "FROM facts f JOIN r ON f.id = r.id GROUP BY r.name "
        "ORDER BY r.name")
    assert out.strings("name") == [b"b", b"z"]
    assert list(out.column("total")) == [200, 400]


def test_row_table_alter_add_drop():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row)")
    s.execute("INSERT INTO t VALUES (1, 10)")
    s.execute("ALTER TABLE t ADD COLUMN w int64")
    out = s.execute("SELECT id, w FROM t")
    assert not out.validity("w").any()
    s.execute("INSERT INTO t VALUES (2, 20, 200)")
    s.execute("ALTER TABLE t DROP COLUMN v")
    s.execute("ALTER TABLE t ADD COLUMN v int64")
    out = s.execute("SELECT id, v FROM t ORDER BY id")
    assert not out.validity("v").any()   # no resurrection


def test_row_drop_then_recreate_does_not_resurrect():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1)")
    s.execute("INSERT INTO t VALUES (1), (2), (3)")
    s.execute("DROP TABLE t")
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1)")
    s.execute("INSERT INTO t VALUES (100)")
    out = s.execute("SELECT id FROM t ORDER BY id")
    assert list(out.column("id")) == [100]
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT id FROM t ORDER BY id")
    assert list(out.column("id")) == [100]


def test_update_string_column_from_other_column():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, a string, b string, "
              "PRIMARY KEY (id)) WITH (store = row)")
    s.execute("INSERT INTO t VALUES (1, 'aaa', 'bbb'), (2, 'xxx', 'yyy')")
    s.execute("UPDATE t SET a = b WHERE id = 2")
    out = s.execute("SELECT id, a FROM t ORDER BY id")
    assert out.strings("a") == [b"aaa", b"yyy"]
    with pytest.raises(PlanError):
        s.execute("UPDATE t SET a = id")  # unsupported string expr


def test_concurrent_update_no_lost_increment():
    """Two racing read-modify-write UPDATEs must serialize: the second
    sees a broken lock at prepare and retries against the new state."""
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1)")
    s.execute("INSERT INTO t VALUES (1, 0)")

    t = c.tables["t"]
    # interleave manually: tx A locks+reads, then tx B commits a write
    # before A's commit -> A's prepare must fail and the session retry
    locks = t.lock_all_shards()
    snap = c.coordinator.read_snapshot()
    row = dict(t.read_row((1,), snap))
    row["v"] = row["v"] + 1
    # B sneaks in a conflicting committed write
    t.upsert_rows([{"id": 1, "v": 100}])
    from ydb_tpu.datashard.shard import RowOp

    res = t._commit_ops([RowOp((1,), row)], lock_ids=locks)
    t.release_locks(locks)
    assert not res.committed and "prepare" in res.error

    # the SQL surface hides the retry: increments never lost
    s.execute("UPDATE t SET v = v + 1 WHERE id = 1")
    out = s.execute("SELECT v FROM t")
    assert list(out.column("v")) == [101]


def test_drop_table_crash_between_scheme_and_blob_delete():
    """Crash after the scheme drop committed but before blob deletion:
    the boot sweep must finish the job (trash record)."""
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1)")
    s.execute("INSERT INTO t VALUES (1), (2)")
    # simulate the crash point: scheme drop commits, deletion never runs
    t = c.tables["t"]
    c.scheme.drop_table("/t", trash_prefixes=t.storage_prefixes())
    assert c.scheme.trash()
    # new process boots: sweep deletes the orphaned shard state
    c2 = Cluster(store=store)
    assert not c2.scheme.trash()
    s2 = c2.session()
    s2.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 1)")
    s2.execute("INSERT INTO t VALUES (100)")
    c3 = Cluster(store=store)
    out = c3.session().execute("SELECT id FROM t")
    assert list(out.column("id")) == [100]


def test_eager_lock_registration():
    ds = _shard()
    w = ds.propose([RowOp((1,), {"id": 1, "v": 10})])
    ds.commit_at([w], step=1)
    lock = ds.acquire_lock()
    it = ds.read(1, lo=(0,), hi=(100,), lock_id=lock)  # NOT consumed yet
    w2 = ds.propose([RowOp((1,), {"id": 1, "v": 99})])
    ds.commit_at([w2], step=2)
    assert ds.lock_broken(lock)   # broke despite unconsumed iterator
    list(it)
