"""PDisk chunk device + LSM VDisk hull (SURVEY §2.3 PDisk/VDisk rows;
reference blobstorage_pdisk_impl.h, vdisk/hulldb): chunk allocation,
double-buffered superblock, WAL replay, flush/compaction, torn-tail
recovery, and a blob GROUP running its part stores on LSM disks."""

import numpy as np
import pytest

from ydb_tpu.blobstorage.pdisk import PDisk
from ydb_tpu.blobstorage.vdisk_lsm import LsmBlobStore


def test_pdisk_alloc_io_and_superblock(tmp_path):
    p = PDisk(str(tmp_path / "d0"), chunk_size=4096)
    a, b = p.alloc(), p.alloc()
    assert a != b
    p.write(a, 0, b"hello")
    p.write(b, 100, b"world")
    assert p.read(a, 0, 5) == b"hello"
    assert p.read(b, 100, 5) == b"world"
    with pytest.raises(ValueError):
        p.write(a, 4090, b"spans-boundary")
    p.release(b)
    p.commit_meta({"owner": "vdisk-1"})
    p.close()

    p2 = PDisk(str(tmp_path / "d0"), chunk_size=4096)
    assert p2.meta == {"owner": "vdisk-1"}
    assert p2.alloc() == b  # released chunk is reusable after reboot
    p2.close()


def test_pdisk_superblock_double_buffer_survives_torn_write(tmp_path):
    path = str(tmp_path / "d1")
    p = PDisk(path, chunk_size=4096)
    p.commit_meta({"gen": 1})
    p.commit_meta({"gen": 2})
    p.close()
    # corrupt the most recent superblock slot (seq=2 -> slot 0)
    with open(path, "r+b") as f:
        f.seek(0 * 4096 + 20)
        f.write(b"\xff" * 16)
    p2 = PDisk(path, chunk_size=4096)
    assert p2.meta == {"gen": 1}  # falls back to the older generation
    p2.close()


def test_lsm_put_get_delete_flush_compact(tmp_path):
    p = PDisk(str(tmp_path / "d2"), chunk_size=4096)
    lsm = LsmBlobStore(p, memtable_bytes=2048, max_runs=3)
    for i in range(40):
        lsm.put(f"k/{i:03d}", f"value-{i}".encode() * 20)
    assert lsm.get("k/005") == b"value-5" * 20
    assert len(lsm.runs) >= 1  # flushes happened
    lsm.delete("k/005")
    assert not lsm.exists("k/005")
    with pytest.raises(KeyError):
        lsm.get("k/005")
    # overwrite: newest wins across runs
    lsm.put("k/006", b"NEW")
    assert lsm.get("k/006") == b"NEW"
    listed = lsm.list("k/")
    assert "k/005" not in listed and "k/006" in listed
    assert len(listed) == 39
    # force compaction down to one run
    for i in range(100, 140):
        lsm.put(f"k/{i}", b"x" * 100)
    lsm.flush()
    assert len(lsm.runs) <= 3


def test_lsm_recovery_replays_wal_and_manifest(tmp_path):
    path = str(tmp_path / "d3")
    p = PDisk(path, chunk_size=4096)
    lsm = LsmBlobStore(p, memtable_bytes=1 << 14)
    lsm.put("a", b"1")
    lsm.put("b", b"2" * 500)
    lsm.flush()              # a,b in an SST run
    lsm.put("c", b"3")       # c only in the WAL
    lsm.delete("a")          # tombstone only in the WAL
    p.close()                # crash (no graceful flush)

    p2 = PDisk(path, chunk_size=4096)
    lsm2 = LsmBlobStore(p2)
    assert lsm2.get("b") == b"2" * 500
    assert lsm2.get("c") == b"3"
    assert not lsm2.exists("a")
    assert lsm2.list("") == ["b", "c"]
    p2.close()


def test_group_on_lsm_disks_heals(tmp_path):
    """A full erasure group whose VDisks store parts in LSM hulls on
    PDisk files — put/get/reconstruct/self-heal end to end."""
    from ydb_tpu.blobstorage.group import DSProxy, GroupInfo, VDisk

    disks = []
    for i in range(6):
        pd = PDisk(str(tmp_path / f"pd{i}"), chunk_size=8192)
        disks.append(VDisk(f"d{i}", backing=LsmBlobStore(pd)))
    group = GroupInfo(7, "block42", disks)
    proxy = DSProxy(group)
    rng = np.random.default_rng(3)
    blobs = {f"blob/{i}": rng.bytes(777 + i) for i in range(8)}
    for bid, data in blobs.items():
        proxy.put(bid, data)
    # one disk dies: reads reconstruct
    disks[2].down = True
    for bid, data in blobs.items():
        assert proxy.get(bid) == data
    # replace it with a fresh LSM disk and rebuild
    fresh = VDisk("d2r", backing=LsmBlobStore(
        PDisk(str(tmp_path / "pd2r"), chunk_size=8192)))
    proxy.self_heal(2, fresh)
    disks[2] = fresh
    for bid, data in blobs.items():
        assert proxy.get(bid) == data
