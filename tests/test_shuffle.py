"""Hash-shuffle (all_to_all repartition) tests on the CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu import dtypes
from ydb_tpu.blocks import TableBlock
from ydb_tpu.parallel.dist import _local, _relocal, stack_blocks
from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from ydb_tpu.parallel.shuffle import hash_rows, repartition


def _stacked_random(n_dev, rows_per_dev, seed=3):
    rng = np.random.default_rng(seed)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    blocks = []
    for d in range(n_dev):
        n = rows_per_dev - (d % 3)  # uneven live counts
        blocks.append(TableBlock.from_numpy(
            {
                "k": rng.integers(0, 1000, n),
                "v": rng.integers(0, 10, n) + d * 1000,
            },
            sch, capacity=rows_per_dev,
        ))
    return blocks, sch


def test_repartition_preserves_rows_and_colocates_keys():
    n_dev = 8
    mesh = make_mesh(n_dev)
    blocks, sch = _stacked_random(n_dev, 64)

    def step(stacked):
        blk = _local(stacked)
        return _relocal(repartition(blk, ["k"], n_dev))

    fn = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS),
        check_vma=False,
    ))
    stacked = jax.device_put(
        stack_blocks(blocks), NamedSharding(mesh, P(SHARD_AXIS))
    )
    out = fn(stacked)

    # reassemble per-device results from the stacked output
    data_k = np.asarray(out.columns["k"].data)
    data_v = np.asarray(out.columns["v"].data)
    lens = np.asarray(out.length)
    got = []
    per_dev_keys = []
    for d in range(n_dev):
        k = data_k[d][: lens[d]]
        v = data_v[d][: lens[d]]
        got.extend(zip(k.tolist(), v.tolist()))
        per_dev_keys.append(set(k.tolist()))

    want = []
    for b in blocks:
        c = b.to_numpy()
        want.extend(zip(c["k"].tolist(), c["v"].tolist()))
    assert sorted(got) == sorted(want)  # no row lost or duplicated

    # same key never appears on two shards
    for i in range(n_dev):
        for j in range(i + 1, n_dev):
            assert not (per_dev_keys[i] & per_dev_keys[j])


def test_hash_rows_distinguishes_null_from_zero():
    from ydb_tpu.blocks.block import Column

    d = jnp.array([0, 0], dtype=jnp.int64)
    v = jnp.array([True, False])
    h = hash_rows([Column(d, v)])
    assert int(h[0]) != int(h[1])
