"""Hash-shuffle (all_to_all repartition) tests on the CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ydb_tpu import dtypes
from ydb_tpu.blocks import TableBlock
from ydb_tpu.parallel.dist import _local, _relocal, stack_blocks
from ydb_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from ydb_tpu.parallel.shuffle import hash_rows, repartition


def _stacked_random(n_dev, rows_per_dev, seed=3):
    rng = np.random.default_rng(seed)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    blocks = []
    for d in range(n_dev):
        n = rows_per_dev - (d % 3)  # uneven live counts
        blocks.append(TableBlock.from_numpy(
            {
                "k": rng.integers(0, 1000, n),
                "v": rng.integers(0, 10, n) + d * 1000,
            },
            sch, capacity=rows_per_dev,
        ))
    return blocks, sch


def test_repartition_preserves_rows_and_colocates_keys():
    n_dev = 8
    mesh = make_mesh(n_dev)
    blocks, sch = _stacked_random(n_dev, 64)

    def step(stacked):
        blk = _local(stacked)
        return _relocal(repartition(blk, ["k"], n_dev))

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS),
        check_vma=False,
    ))
    stacked = jax.device_put(
        stack_blocks(blocks), NamedSharding(mesh, P(SHARD_AXIS))
    )
    out = fn(stacked)

    # reassemble per-device results from the stacked output
    data_k = np.asarray(out.columns["k"].data)
    data_v = np.asarray(out.columns["v"].data)
    lens = np.asarray(out.length)
    got = []
    per_dev_keys = []
    for d in range(n_dev):
        k = data_k[d][: lens[d]]
        v = data_v[d][: lens[d]]
        got.extend(zip(k.tolist(), v.tolist()))
        per_dev_keys.append(set(k.tolist()))

    want = []
    for b in blocks:
        c = b.to_numpy()
        want.extend(zip(c["k"].tolist(), c["v"].tolist()))
    assert sorted(got) == sorted(want)  # no row lost or duplicated

    # same key never appears on two shards
    for i in range(n_dev):
        for j in range(i + 1, n_dev):
            assert not (per_dev_keys[i] & per_dev_keys[j])


def test_hash_rows_distinguishes_null_from_zero():
    from ydb_tpu.blocks.block import Column

    d = jnp.array([0, 0], dtype=jnp.int64)
    v = jnp.array([True, False])
    h = hash_rows([Column(d, v)])
    assert int(h[0]) != int(h[1])


def test_hash_rows_deterministic_across_partitions():
    """The row hash is a pure function of (value, validity): dict-id
    string columns (int32 codes) and scaled decimals (int64) hash to the
    same destination no matter which device/partition holds the row —
    the property repartition's key colocation rests on."""
    from ydb_tpu.blocks.block import Column

    rng = np.random.default_rng(9)
    ids = rng.integers(0, 50, 256).astype(np.int32)  # dict codes
    dec = (rng.integers(-10 ** 6, 10 ** 6, 256) * 100).astype(np.int64)
    ok = rng.random(256) > 0.1
    full = hash_rows([Column(jnp.asarray(ids), jnp.asarray(ok)),
                      Column(jnp.asarray(dec), jnp.asarray(ok))])
    for s in range(4):  # round-robin partitions, as the mesh shards
        part = hash_rows([
            Column(jnp.asarray(ids[s::4]), jnp.asarray(ok[s::4])),
            Column(jnp.asarray(dec[s::4]), jnp.asarray(ok[s::4]))])
        np.testing.assert_array_equal(
            np.asarray(part), np.asarray(full)[s::4])


def test_null_keys_colocate_on_one_shard():
    """NULL join keys (canonical zeroed slots) form one hash class: the
    exchange lands every NULL-key row on the same device."""
    n_dev = 8
    mesh = make_mesh(n_dev)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    rng = np.random.default_rng(5)
    blocks = []
    for d in range(n_dev):
        k = rng.integers(1, 1000, 64)
        ok = np.ones(64, dtype=bool)
        ok[d::7] = False
        k[~ok] = 0  # canonical NULL slot, as the kernels emit
        blocks.append(TableBlock.from_numpy(
            {"k": k, "v": rng.integers(0, 10, 64)}, sch,
            validity={"k": ok, "v": np.ones(64, dtype=bool)},
            capacity=64))
    n_null = sum(int((~b.validity_numpy()["k"]).sum()) for b in blocks)
    assert n_null > 0

    def step(stacked):
        blk = _local(stacked)
        return _relocal(repartition(blk, ["k"], n_dev))

    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=P(SHARD_AXIS), out_specs=P(SHARD_AXIS),
        check_vma=False,
    ))
    out = fn(jax.device_put(
        stack_blocks(blocks), NamedSharding(mesh, P(SHARD_AXIS))))
    lens = np.asarray(out.length)
    ok = np.asarray(out.columns["k"].validity)
    per_dev_nulls = [int((~ok[d][: lens[d]]).sum()) for d in range(n_dev)]
    assert sum(per_dev_nulls) == n_null  # no NULL row lost
    assert sum(1 for c in per_dev_nulls if c) == 1, per_dev_nulls


def test_size_buckets_uniform_and_gates():
    from ydb_tpu.parallel import shuffle as sh
    from ydb_tpu.ssa.plan_fuse import shape_class

    old = sh.SHUFFLE_STATS_FORCE
    try:
        sh.SHUFFLE_STATS_FORCE = True
        # uniform keys over 8 destinations: mean x margin, far under
        # full capacity (the >=4x exchange reduction the bench asserts)
        assert sh.size_buckets(1 << 15, 8) <= (1 << 15) // 4
        # the estimate is shape-class rounded (zero-retrace re-runs)
        b = sh.size_buckets(1 << 15, 8, heavy=100)
        assert b == shape_class(b)
        # a heavy hitter widens the bucket, never past full capacity
        assert sh.size_buckets(1 << 15, 8, heavy=1 << 20) == 1 << 15
        # degenerate 1-shard mesh: no exchange, full capacity
        assert sh.size_buckets(1 << 15, 1) == 1 << 15
        sh.SHUFFLE_STATS_FORCE = False
        assert sh.size_buckets(1 << 15, 8) == 1 << 15  # stats off
    finally:
        sh.SHUFFLE_STATS_FORCE = old


def test_heavy_bound_joint_keys():
    from ydb_tpu.parallel.shuffle import heavy_bound
    from ydb_tpu.stats.cost import ColumnStats

    class TS:
        def __init__(self, cols):
            self.columns = cols

    stats = {"a": TS({"k": ColumnStats(heavy=500)}),
             "b": TS({"k": ColumnStats(heavy=200),
                      "j": ColumnStats(heavy=40)})}
    assert heavy_bound(stats, ["k"]) == 500  # max across tables
    # composite key: bounded by its rarest component
    assert heavy_bound(stats, ["k", "j"]) == 40
    assert heavy_bound(stats, ["missing"]) == 0
    assert heavy_bound(None, ["k"]) == 0


def test_repartition_overflow_reports_worst_and_grow_roundtrips():
    """100% skew with an undersized bucket: the traced worst count
    exceeds the capacity (rows were dropped), and re-exchanging at the
    observed size is lossless — the grace respill protocol."""
    n_dev = 8
    rows = 256
    mesh = make_mesh(n_dev)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    blocks = [TableBlock.from_numpy(
        {"k": np.full(rows, 3, dtype=np.int64),
         "v": np.arange(rows, dtype=np.int64) + d * rows},
        sch, capacity=rows) for d in range(n_dev)]
    stacked = stack_blocks(blocks)

    def run(B):
        def step(st):
            blk, worst = repartition(_local(st), ["k"], n_dev,
                                     bucket_rows=B, with_counts=True)
            return _relocal(blk), worst
        fn = jax.jit(shard_map(
            step, mesh=mesh, in_specs=P(SHARD_AXIS),
            out_specs=(P(SHARD_AXIS), P()), check_vma=False))
        return fn(jax.device_put(
            stacked, NamedSharding(mesh, P(SHARD_AXIS))))

    out, worst = run(64)  # undersized: every device sends all 256 rows
    assert int(np.asarray(worst)) == rows  # the observed grow target
    out, worst = run(int(np.asarray(worst)))
    assert int(np.asarray(worst)) <= rows
    lens = np.asarray(out.length)
    got = []
    for d in range(n_dev):
        got.extend(np.asarray(out.columns["v"].data)[d][: lens[d]].tolist())
    assert sorted(got) == list(range(n_dev * rows))  # lossless


def test_mesh_walk_round_up_is_shape_class():
    from ydb_tpu.parallel.mesh_exec import _round_up
    from ydb_tpu.ssa.plan_fuse import shape_class

    for n in (1, 1000, 1024, 5000, 1 << 17, (1 << 17) + 1):
        assert _round_up(n) == shape_class(n)
