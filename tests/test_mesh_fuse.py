"""Sharded whole-plan fusion (parallel.mesh_fuse): one shard_map'd
donated-buffer dispatch per plan on the virtual 8-device CPU mesh, with
results asserted BIT-identical to the single-chip executor — scans
(Q1), repartition joins (Q3/Q5), NULL join keys, the degenerate
1-device mesh, stats-sized shuffle buckets with the overflow->grow
protocol, and the SQL front door through Cluster.enable_mesh."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks.dictionary import DictionarySet
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.parallel import mesh_fuse, shuffle
from ydb_tpu.parallel.mesh import make_mesh
from ydb_tpu.parallel.mesh_exec import MeshDatabase, MeshPlanExecutor
from ydb_tpu.plan import (
    Database,
    LookupJoin,
    TableScan,
    Transform,
    execute_plan,
    to_host,
)
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program, SortStep
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

N_DEV = 8


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.005, seed=31)


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
    )


def _mesh_db(data, n_dev=N_DEV):
    return MeshDatabase(
        sources={
            t: [ColumnSource({k: v[s::n_dev] for k, v in cols.items()},
                             data.schema(t), data.dicts)
                for s in range(n_dev)]
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def single_db(data):
    return Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts,
    )


def _identical(got, ref):
    """Every column bit-identical — values AND validity, floats
    included (the fused lowering must reproduce the single-chip result
    exactly, not approximately)."""
    assert got.num_rows == ref.num_rows
    assert set(got.cols) == set(ref.cols)
    for c in got.cols:
        np.testing.assert_array_equal(
            np.asarray(got.cols[c][0]), np.asarray(ref.cols[c][0]),
            err_msg=c)
        np.testing.assert_array_equal(
            np.asarray(got.cols[c][1]), np.asarray(ref.cols[c][1]),
            err_msg=f"{c}:validity")


def _fused_plans(ex):
    return [v for v in ex._jit_cache.values()
            if isinstance(v, mesh_fuse.MeshFusedPlan)]


def test_q1_scan_aggregate_fused_bit_identical(data, single_db):
    plan = Transform(TableScan("lineitem"), tpch.q1_program())
    ex = MeshPlanExecutor(_mesh_db(data), make_mesh(N_DEV))
    res = ex.execute_fused(plan)
    assert res is not None, "q1 did not mesh-fuse"
    ref = to_host(execute_plan(plan, single_db, use_dq=False))
    _identical(res, ref)
    # second statement hits the compiled-plan cache, same bits out
    assert len(_fused_plans(ex)) == 1
    _identical(ex.execute_fused(plan), ref)
    assert len(_fused_plans(ex)) == 1


@pytest.mark.slow  # full q3 mesh build; join fusion is covered tier-1 by
# the NULL-key LookupJoin cases and the SQL-session test below
def test_q3_join_fused_bit_identical(data, catalog, single_db):
    plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
    ex = MeshPlanExecutor(_mesh_db(data), make_mesh(N_DEV))
    res = ex.execute_fused(plan)
    assert res is not None, "q3 did not mesh-fuse"
    _identical(res, to_host(execute_plan(plan, single_db, use_dq=False)))
    # the equi-joins repartitioned through stats-sized buckets
    (fused,) = _fused_plans(ex)
    assert fused.shuffle_capacity() > 0
    assert "shuffle" in fused.cap_kinds


@pytest.mark.slow  # deepest join chain = the longest 8-dev CPU trace
def test_q5_multi_join_fused_bit_identical(data, catalog, single_db):
    plan = plan_select_full(parse(TPCH["q5"]), catalog).plan
    ex = MeshPlanExecutor(_mesh_db(data), make_mesh(N_DEV))
    res = ex.execute_fused(plan)
    assert res is not None, "q5 did not mesh-fuse"
    _identical(res, to_host(execute_plan(plan, single_db, use_dq=False)))


def _null_key_case(rows=512, null_every=5):
    """Probe table with NULL join keys (canonical zeroed slots, as the
    kernels emit) against a unique-key build side."""
    rng = np.random.default_rng(17)
    lsch = dtypes.schema(("k", dtypes.INT64), ("g", dtypes.INT64),
                         ("v", dtypes.INT64))
    rsch = dtypes.schema(("rk", dtypes.INT64), ("w", dtypes.INT64))
    k = rng.integers(0, 32, rows)
    kv = np.ones(rows, dtype=bool)
    kv[::null_every] = False
    k[~kv] = 0  # canonical NULL slot
    lcols = {"k": k, "g": rng.integers(0, 3, rows),
             "v": rng.integers(0, 100, rows)}
    lval = {"k": kv, "g": np.ones(rows, dtype=bool),
            "v": np.ones(rows, dtype=bool)}
    rcols = {"rk": np.arange(0, 32, 2), "w": np.arange(0, 32, 2) * 10}
    return lsch, rsch, lcols, lval, rcols


def _null_key_dbs(n_dev=N_DEV):
    lsch, rsch, lcols, lval, rcols = _null_key_case()
    dicts = DictionarySet()
    single = Database(
        sources={"L": ColumnSource(lcols, lsch, dicts, validity=lval),
                 "R": ColumnSource(rcols, rsch, dicts)},
        dicts=dicts)
    mesh = MeshDatabase(
        sources={
            "L": [ColumnSource(
                {k: v[s::n_dev] for k, v in lcols.items()}, lsch, dicts,
                validity={k: v[s::n_dev] for k, v in lval.items()})
                for s in range(n_dev)],
            "R": [ColumnSource(
                {k: v[s::n_dev] for k, v in rcols.items()}, rsch, dicts)
                for s in range(n_dev)],
        },
        dicts=dicts)
    return single, mesh


@pytest.mark.parametrize("kind", [
    "inner", "left",
    pytest.param("semi", marks=pytest.mark.slow),
    pytest.param("anti", marks=pytest.mark.slow),
])
def test_null_join_keys_fused_bit_identical(kind):
    """NULL probe keys never match (inner/semi drop, left pads, anti
    keeps) — the sharded repartition join must agree with the
    single-chip kernels bit-for-bit."""
    single, mesh_db = _null_key_dbs()
    payload = ("w",) if kind in ("inner", "left") else ()
    aggs = (AggSpec(Agg.SUM, "v", "sv"),
            AggSpec(Agg.COUNT_ALL, None, "n"))
    if payload:
        aggs += (AggSpec(Agg.SUM, "w", "sw"),
                 AggSpec(Agg.COUNT, "w", "nw"))
    plan = Transform(
        LookupJoin(probe=TableScan("L"), build=TableScan("R"),
                   probe_keys=("k",), build_keys=("rk",),
                   payload=payload, kind=kind),
        Program((GroupByStep(keys=("g",), aggs=aggs),
                 SortStep(keys=("g",)))))
    ex = MeshPlanExecutor(mesh_db, make_mesh(N_DEV))
    res = ex.execute_fused(plan)
    assert res is not None, f"{kind} join did not mesh-fuse"
    _identical(res, to_host(execute_plan(plan, single, use_dq=False)))


def test_degenerate_single_device_mesh(data, single_db):
    """A 1-device mesh is the single-chip lowering verbatim: no
    collectives, same bits."""
    plan = Transform(TableScan("lineitem"), tpch.q1_program())
    db1 = MeshDatabase(
        sources={t: [ColumnSource(cols, data.schema(t), data.dicts)]
                 for t, cols in data.tables.items()},
        dicts=data.dicts)
    ex = MeshPlanExecutor(db1, make_mesh(1))
    res = ex.execute_fused(plan)
    assert res is not None
    _identical(res, to_host(execute_plan(plan, single_db, use_dq=False)))


def test_skew_overflow_grows_and_stays_identical():
    """100% key skew: every probe row routes to ONE destination, so the
    stats-sized bucket (no stats -> mean x margin) must overflow; the
    host grows it to the observed worst count, re-stages (donation
    consumed the inputs) and the final result is still bit-identical."""
    rows = 2048 * N_DEV
    lsch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    rsch = dtypes.schema(("rk", dtypes.INT64), ("w", dtypes.INT64))
    lcols = {"k": np.full(rows, 7, dtype=np.int64),
             "v": np.arange(rows, dtype=np.int64)}
    rcols = {"rk": np.array([7], dtype=np.int64),
             "w": np.array([100], dtype=np.int64)}
    dicts = DictionarySet()
    single = Database(
        sources={"L": ColumnSource(lcols, lsch, dicts),
                 "R": ColumnSource(rcols, rsch, dicts)},
        dicts=dicts)
    mesh_db = MeshDatabase(
        sources={
            "L": [ColumnSource(
                {k: v[s::N_DEV] for k, v in lcols.items()}, lsch, dicts)
                for s in range(N_DEV)],
            "R": [ColumnSource(
                {k: v[s::N_DEV] for k, v in rcols.items()}, rsch, dicts)
                for s in range(N_DEV)],
        },
        dicts=dicts)
    plan = Transform(
        LookupJoin(probe=TableScan("L"), build=TableScan("R"),
                   probe_keys=("k",), build_keys=("rk",),
                   payload=("w",), kind="inner"),
        Program((GroupByStep(keys=("k",), aggs=(
            AggSpec(Agg.SUM, "v", "sv"),
            AggSpec(Agg.SUM, "w", "sw"),
            AggSpec(Agg.COUNT_ALL, None, "n"))),)))
    ex = MeshPlanExecutor(mesh_db, make_mesh(N_DEV))
    res = ex.execute_fused(plan)
    assert res is not None
    (fused,) = _fused_plans(ex)
    assert fused.shuffle_grows >= 1, "skew never tripped the grow path"
    _identical(res, to_host(execute_plan(plan, single, use_dq=False)))
    # the grown capacity is cached: a re-run must not grow again
    grows = fused.shuffle_grows
    _identical(ex.execute_fused(plan),
               to_host(execute_plan(plan, single, use_dq=False)))
    assert fused.shuffle_grows == grows


@pytest.mark.slow  # two full q3 mesh builds; the sizing gate itself is
# covered tier-1 by tests/test_shuffle.py::test_size_buckets_uniform_and_gates
def test_shuffle_stats_gate_full_capacity_when_off(data, catalog,
                                                   single_db):
    """YDB_TPU_SHUFFLE_STATS=0 (via the in-process force) restores
    full-capacity buckets; results match either way."""
    plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
    ref = to_host(execute_plan(plan, single_db, use_dq=False))
    caps = {}
    old = shuffle.SHUFFLE_STATS_FORCE
    for force in (False, True):
        shuffle.SHUFFLE_STATS_FORCE = force
        try:
            ex = MeshPlanExecutor(_mesh_db(data), make_mesh(N_DEV))
            _identical(ex.execute_fused(plan), ref)
            (fused,) = _fused_plans(ex)
            caps[force] = fused.shuffle_capacity()
        finally:
            shuffle.SHUFFLE_STATS_FORCE = old
    # stats sizing must actually shrink the exchange on this shape
    assert caps[True] < caps[False], caps


def test_mesh_fuse_gate_falls_back_to_walk(data):
    """YDB_TPU_MESH_FUSE=0 (via the force) disables the fused path so
    the executor answers through the per-node walk (whose bit-identity
    test_mesh_exec already asserts)."""
    plan = Transform(TableScan("lineitem"), tpch.q1_program())
    ex = MeshPlanExecutor(_mesh_db(data), make_mesh(N_DEV))
    old = mesh_fuse.MESH_FUSE_FORCE
    mesh_fuse.MESH_FUSE_FORCE = False
    try:
        assert ex.execute_fused(plan) is None
    finally:
        mesh_fuse.MESH_FUSE_FORCE = old


def test_mesh_fused_from_sql_session(monkeypatch):
    """Cluster.enable_mesh routes SQL statements through the sharded
    fused dispatch (execute_fused returns a result, not a fallback) and
    the rows match the pre-mesh reference."""
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.parallel import mesh_exec as mex_mod

    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE fusers (id int64, grp int64, "
              "PRIMARY KEY (id)) WITH (shards = 3)")
    s.execute("CREATE TABLE forders (oid int64, uid int64, amount int64,"
              " PRIMARY KEY (oid)) WITH (shards = 5)")
    for i in range(0, 120, 30):
        s.execute("INSERT INTO fusers VALUES " + ", ".join(
            f"({j}, {j % 4})" for j in range(i, i + 30)))
    for i in range(0, 600, 100):
        s.execute("INSERT INTO forders VALUES " + ", ".join(
            f"({j}, {j % 120}, {j % 13})" for j in range(i, i + 100)))
    q = ("SELECT u.grp AS g, SUM(o.amount) AS total, COUNT(*) AS n "
         "FROM forders o JOIN fusers u ON o.uid = u.id "
         "GROUP BY u.grp ORDER BY g")
    ref = s.execute(q)
    c.enable_mesh()
    calls = []
    orig = mex_mod.MeshPlanExecutor.execute_fused

    def spy(self, plan):
        r = orig(self, plan)
        calls.append(r)
        return r

    monkeypatch.setattr(mex_mod.MeshPlanExecutor, "execute_fused", spy)
    res = s.execute(q)
    assert calls and calls[-1] is not None, (
        "session statement fell back off the fused mesh path")
    for col in ("g", "total", "n"):
        np.testing.assert_array_equal(
            np.asarray(res.cols[col][0]), np.asarray(ref.cols[col][0]),
            err_msg=col)
