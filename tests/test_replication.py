"""Async replication: changefeed topic -> replica table, resumable and
idempotent (SURVEY §2.14 async-replication row; reference
ydb/core/tx/replication)."""

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.replication import Replicator, replicate_once


def _source_cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE acc (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 2, changefeed = on)")
    return c, s


def _replica_cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE acc (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 2)")
    return c, s


def _rows(s):
    r = s.execute("select id, v from acc order by id")
    return list(zip((int(x) for x in r.column("id")),
                    (int(x) for x in r.column("v"))))


def test_replica_follows_source():
    src, ss = _source_cluster()
    dst, ds = _replica_cluster()
    ss.execute("INSERT INTO acc VALUES (1, 10), (2, 20), (3, 30)")
    ss.execute("UPDATE acc SET v = 11 WHERE id = 1")
    ss.execute("DELETE FROM acc WHERE id = 2")

    n = replicate_once(src.tables["acc"], src.topics["acc_changefeed"],
                       dst.tables["acc"])
    assert n == 5  # 3 inserts + 1 update + 1 delete
    assert _rows(ds) == [(1, 11), (3, 30)]
    assert _rows(ds) == _rows(ss)

    # incremental: later changes flow on the next cycle, offsets resume
    ss.execute("INSERT INTO acc VALUES (4, 40)")
    ss.execute("UPDATE acc SET v = 31 WHERE id = 3")
    n = replicate_once(src.tables["acc"], src.topics["acc_changefeed"],
                       dst.tables["acc"])
    assert n == 2
    assert _rows(ds) == _rows(ss) == [(1, 11), (3, 31), (4, 40)]


def test_replication_is_idempotent_on_redelivery():
    """A crash between apply and offset commit redelivers the batch;
    upsert/delete-by-key apply makes the replay harmless."""
    src, ss = _source_cluster()
    dst, ds = _replica_cluster()
    ss.execute("INSERT INTO acc VALUES (1, 10), (2, 20)")
    ss.execute("DELETE FROM acc WHERE id = 2")
    topic = src.topics["acc_changefeed"]
    src.tables["acc"].drain_changes_to(topic)

    rep = Replicator(topic, dst.tables["acc"], consumer="r")
    rep.poll()
    before = _rows(ds)
    # simulate lost offsets: reset the consumer and re-apply everything
    for part in topic.partitions:
        part.commit("r", 0)
    rep.poll()
    assert _rows(ds) == before == [(1, 10)]
