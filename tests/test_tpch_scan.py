"""End-to-end TPC-H Q1/Q6 through the block-streamed scan executor,
cross-checked against the independent numpy oracle engine (the
default-CPU-engine-as-correctness-oracle pattern, SURVEY.md §7.1.4)."""

import numpy as np
import pytest

from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.engine.scan import ColumnSource, execute_scan, required_columns
from ydb_tpu.workload import tpch


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.002, seed=7)


def _source(data, table):
    return ColumnSource(
        columns=data.tables[table],
        schema=data.schema(table),
        dicts=data.dicts,
    )


def _oracle(data, table):
    cols = {
        n: (v, np.ones(len(v), dtype=bool))
        for n, v in data.tables[table].items()
    }
    return OracleTable(cols, data.schema(table))


def assert_tables_match(engine: OracleTable, oracle: OracleTable, sort_by=None):
    assert set(engine.cols) == set(oracle.cols)
    assert engine.num_rows == oracle.num_rows
    for name in engine.cols:
        ev, eo = engine.cols[name]
        ov, oo = oracle.cols[name]
        np.testing.assert_array_equal(eo, oo, err_msg=f"validity {name}")
        if np.issubdtype(ev.dtype, np.floating):
            np.testing.assert_allclose(
                ev[eo], ov[oo], rtol=1e-9, err_msg=name
            )
        else:
            np.testing.assert_array_equal(ev[eo], ov[oo], err_msg=name)


def test_q1_engine_matches_oracle(data):
    prog = tpch.q1_program()
    res = execute_scan(prog, _source(data, "lineitem"), block_rows=4096)
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    assert res.num_rows == 4  # R/A/N x O/F with date cutoff -> 4 combos
    assert_tables_match(res, ora)


def test_q1_block_size_invariance(data):
    prog = tpch.q1_program()
    r1 = execute_scan(prog, _source(data, "lineitem"), block_rows=1024)
    r2 = execute_scan(prog, _source(data, "lineitem"), block_rows=1 << 16)
    for name in r1.cols:
        np.testing.assert_allclose(
            r1.cols[name][0], r2.cols[name][0], rtol=1e-12, err_msg=name
        )


def test_q6_engine_matches_oracle(data):
    prog = tpch.q6_program()
    res = execute_scan(prog, _source(data, "lineitem"), block_rows=4096)
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    assert res.num_rows == 1
    assert_tables_match(res, ora)
    # and the revenue is a plausible positive decimal(4)
    assert res.schema.field("revenue").type.scale == 4
    assert res.cols["revenue"][0][0] > 0


def test_projection_pushdown(data):
    prog = tpch.q6_program()
    cols = required_columns(prog, tpch.LINEITEM_SCHEMA)
    assert set(cols) == {
        "l_shipdate", "l_discount", "l_quantity", "l_extendedprice"
    }


def test_filter_only_program_concatenates(data):
    from ydb_tpu import dtypes
    from ydb_tpu.ssa import Call, Col, FilterStep, Op, Program, ProjectStep
    from ydb_tpu.ssa.program import decimal_lit

    prog = Program((
        FilterStep(Call(Op.GT, Col("l_quantity"), decimal_lit("49", 2))),
        ProjectStep(("l_orderkey", "l_quantity")),
    ))
    res = execute_scan(prog, _source(data, "lineitem"), block_rows=2048)
    ora = run_oracle(prog, _oracle(data, "lineitem"), data.dicts)
    assert res.num_rows == ora.num_rows > 0
    np.testing.assert_array_equal(
        np.sort(res.cols["l_orderkey"][0]), np.sort(ora.cols["l_orderkey"][0])
    )
