"""SQS-compatible HTTP queue proxy tests: queue lifecycle,
at-least-once visibility-timeout semantics, durable backing
(reference: ydb/core/ymq, core/http_proxy)."""

import json
import urllib.request

import pytest

from conftest import Clock

from ydb_tpu.api.sqs import SqsHttpServer, SqsService, SqsError
from ydb_tpu.engine.blobs import MemBlobStore



def call(port, action, params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(params).encode(),
        headers={"X-Amz-Target": f"AmazonSQS.{action}",
                 "Content-Type": "application/x-amz-json-1.0"},
        method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture
def server():
    srv = SqsHttpServer(MemBlobStore()).start()
    yield srv
    srv.stop()


def test_http_queue_lifecycle(server):
    p = server.port
    url = call(p, "CreateQueue", {"QueueName": "jobs"})["QueueUrl"]
    assert url.endswith("/queue/jobs")
    assert call(p, "ListQueues", {})["QueueUrls"] == [url]
    assert call(p, "GetQueueUrl", {"QueueName": "jobs"})["QueueUrl"] \
        == url

    mid = call(p, "SendMessage", {
        "QueueUrl": url, "MessageBody": "work #1"})["MessageId"]
    assert mid.startswith("jobs-")
    msgs = call(p, "ReceiveMessage", {"QueueUrl": url})["Messages"]
    assert len(msgs) == 1 and msgs[0]["Body"] == "work #1"
    call(p, "DeleteMessage", {"QueueUrl": url,
                              "ReceiptHandle": msgs[0]["ReceiptHandle"]})
    assert call(p, "ReceiveMessage", {"QueueUrl": url})["Messages"] == []
    attrs = call(p, "GetQueueAttributes",
                 {"QueueUrl": url})["Attributes"]
    assert attrs["ApproximateNumberOfMessages"] == "0"


def test_http_error_shapes(server):
    p = server.port
    req = urllib.request.Request(
        f"http://127.0.0.1:{p}/",
        data=json.dumps({"QueueUrl": "x/nope"}).encode(),
        headers={"X-Amz-Target": "AmazonSQS.SendMessage"},
        method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    body = json.loads(ei.value.read())
    assert body["__type"] == "QueueDoesNotExist"


def test_visibility_timeout_redelivery():
    clock = Clock(1000.0)
    svc = SqsService(MemBlobStore(), now=clock)
    svc.dispatch("CreateQueue", {"QueueName": "q",
                                 "Attributes": {"VisibilityTimeout": 10}})
    svc.dispatch("SendMessage", {"QueueName": "q", "MessageBody": "m1"})

    got = svc.dispatch("ReceiveMessage", {"QueueName": "q"})["Messages"]
    assert len(got) == 1
    # invisible while leased
    assert svc.dispatch("ReceiveMessage",
                        {"QueueName": "q"})["Messages"] == []
    clock.t += 15  # lease lapses -> redelivered (at-least-once)
    again = svc.dispatch("ReceiveMessage",
                         {"QueueName": "q"})["Messages"]
    assert len(again) == 1 and again[0]["Body"] == "m1"
    assert again[0]["ReceiptHandle"] != got[0]["ReceiptHandle"]
    # stale handle no longer deletes
    with pytest.raises(SqsError):
        svc.dispatch("DeleteMessage", {
            "QueueName": "q",
            "ReceiptHandle": got[0]["ReceiptHandle"]})
    svc.dispatch("DeleteMessage", {
        "QueueName": "q", "ReceiptHandle": again[0]["ReceiptHandle"]})
    assert svc.dispatch("GetQueueAttributes", {"QueueName": "q"})[
        "Attributes"]["ApproximateNumberOfMessages"] == "0"


def test_out_of_order_delete_advances_commit_over_prefix():
    svc = SqsService(MemBlobStore())
    svc.dispatch("CreateQueue", {"QueueName": "q"})
    for i in range(3):
        svc.dispatch("SendMessage", {"QueueName": "q",
                                     "MessageBody": f"m{i}"})
    msgs = svc.dispatch("ReceiveMessage", {
        "QueueName": "q", "MaxNumberOfMessages": 3})["Messages"]
    assert [m["Body"] for m in msgs] == ["m0", "m1", "m2"]
    # delete the middle first: commit cannot pass m0 yet
    svc.dispatch("DeleteMessage", {
        "QueueName": "q", "ReceiptHandle": msgs[1]["ReceiptHandle"]})
    q = svc.queues["q"]
    assert q.part.committed("sqs") == 0
    svc.dispatch("DeleteMessage", {
        "QueueName": "q", "ReceiptHandle": msgs[0]["ReceiptHandle"]})
    assert q.part.committed("sqs") == 2  # prefix m0..m1 committed
    svc.dispatch("DeleteMessage", {
        "QueueName": "q", "ReceiptHandle": msgs[2]["ReceiptHandle"]})
    assert q.part.committed("sqs") == 3


def test_queue_backlog_survives_reboot():
    store = MemBlobStore()
    svc = SqsService(store)
    svc.dispatch("CreateQueue", {"QueueName": "q"})
    svc.dispatch("SendMessage", {"QueueName": "q", "MessageBody": "x"})

    # new service over the same storage: recreate queue, backlog intact
    svc2 = SqsService(store)
    svc2.dispatch("CreateQueue", {"QueueName": "q"})
    msgs = svc2.dispatch("ReceiveMessage", {"QueueName": "q"})["Messages"]
    assert len(msgs) == 1 and msgs[0]["Body"] == "x"


def test_purge_and_max_messages():
    svc = SqsService(MemBlobStore())
    svc.dispatch("CreateQueue", {"QueueName": "q"})
    for i in range(5):
        svc.dispatch("SendMessage", {"QueueName": "q",
                                     "MessageBody": str(i)})
    two = svc.dispatch("ReceiveMessage", {
        "QueueName": "q", "MaxNumberOfMessages": 2})["Messages"]
    assert len(two) == 2
    svc.dispatch("PurgeQueue", {"QueueName": "q"})
    assert svc.dispatch("ReceiveMessage",
                        {"QueueName": "q"})["Messages"] == []
