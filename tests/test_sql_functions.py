"""Scalar-function breadth through the SQL surface: string transforms
(plan-time dictionary maps), math, date parts, greatest/least — each
verified against directly computed expectations (reference op
vocabulary: ydb/library/arrow_kernels/operations.h)."""

import numpy as np
import pytest

from ydb_tpu.kqp.session import Cluster


@pytest.fixture(scope="module")
def session():
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, name string, x int64, "
              "f double, d date, PRIMARY KEY (id))")
    s.execute(
        "INSERT INTO t VALUES "
        "(1, '  Widget A ', 5, 2.0, date '2024-03-07'), "
        "(2, 'gadget-B', -7, 100.0, date '2024-11-30'), "
        "(3, 'THING c', 0, 0.5, date '2025-01-01')")
    return s


def col(out, name):
    return list(out.column(name))


def strs(out, name):
    return [v.decode() if isinstance(v, bytes) else v
            for v in out.strings(name)]


def test_string_transforms(session):
    out = session.execute(
        "SELECT id, upper(name) AS u, lower(name) AS l, "
        "trim(name) AS t, replace(name, '-', '_') AS r, "
        "length(name) AS n FROM t ORDER BY id")
    assert strs(out, "u") == ["  WIDGET A ", "GADGET-B", "THING C"]
    assert strs(out, "l") == ["  widget a ", "gadget-b", "thing c"]
    assert strs(out, "t") == ["Widget A", "gadget-B", "THING c"]
    assert strs(out, "r") == ["  Widget A ", "gadget_B", "THING c"]
    assert col(out, "n") == [11, 8, 7]


def test_concat_and_affix_predicates(session):
    out = session.execute(
        "SELECT id, concat(trim(name), '!') AS bang, "
        "concat('<', name) AS tagged FROM t ORDER BY id")
    assert strs(out, "bang") == ["Widget A!", "gadget-B!", "THING c!"]
    assert strs(out, "tagged")[0] == "<  Widget A "

    out = session.execute(
        "SELECT id FROM t WHERE starts_with(name, 'gadget')")
    assert col(out, "id") == [2]
    out = session.execute(
        "SELECT id FROM t WHERE ends_with(trim(name), 'c')")
    assert col(out, "id") == [3]


def test_math_functions(session):
    out = session.execute(
        "SELECT id, sign(x) AS sg, abs(x) AS ax, log10(f) AS lg, "
        "power(f, 2) AS p2, greatest(x, 1) AS g, "
        "least(x, 1) AS le FROM t ORDER BY id")
    assert col(out, "sg") == [1, -1, 0]
    assert col(out, "ax") == [5, 7, 0]
    np.testing.assert_allclose(
        col(out, "lg"), [np.log10(2.0), 2.0, np.log10(0.5)],
        rtol=1e-12)
    np.testing.assert_allclose(col(out, "p2"), [4.0, 10000.0, 0.25])
    assert col(out, "g") == [5, 1, 1]
    assert col(out, "le") == [1, -7, 0]


def test_hour_minute_on_timestamps():
    from ydb_tpu.engine.oracle import OracleTable  # noqa: F401
    from ydb_tpu.kqp.session import Cluster

    s = Cluster().session()
    s.execute("CREATE TABLE ev (id int64, ts timestamp, "
              "PRIMARY KEY (id))")
    # 2024-03-07 13:45:07 UTC in microseconds
    us = (19789 * 86_400 + 13 * 3600 + 45 * 60 + 7) * 1_000_000
    s.execute(f"INSERT INTO ev VALUES (1, {us})")
    out = s.execute("SELECT extract(hour from ts) AS h, "
                    "extract(minute from ts) AS m FROM ev")
    assert int(out.column("h")[0]) == 13
    assert int(out.column("m")[0]) == 45
    # DATE operands are rejected identically on both engines
    s.execute("CREATE TABLE dd (id int64, d date, PRIMARY KEY (id))")
    s.execute("INSERT INTO dd VALUES (1, date '2024-01-01')")
    with pytest.raises(Exception, match="timestamp"):
        s.execute("SELECT extract(hour from d) AS h FROM dd")


def test_date_parts(session):
    out = session.execute(
        "SELECT id, extract(year from d) AS y, "
        "extract(month from d) AS m, extract(day from d) AS dd "
        "FROM t ORDER BY id")
    assert col(out, "y") == [2024, 2024, 2025]
    assert col(out, "m") == [3, 11, 1]
    assert col(out, "dd") == [7, 30, 1]


def test_functions_in_filters_and_groups(session):
    out = session.execute(
        "SELECT length(name) AS n, count(*) AS c FROM t "
        "WHERE sign(x) >= 0 GROUP BY length(name) ORDER BY n")
    assert list(zip(col(out, "n"), col(out, "c"))) == [(7, 1), (11, 1)]


def test_nested_string_transforms(session):
    out = session.execute(
        "SELECT id FROM t WHERE upper(trim(name)) = 'WIDGET A'")
    assert col(out, "id") == [1]


def test_sign_and_greatest_on_decimals():
    """sign() of a decimal must type as plain int (+/-1, not 10^-scale)
    and greatest(decimal, float_literal) must descale like the compiler
    path (code-review regressions)."""
    from ydb_tpu.kqp.session import Cluster

    s = Cluster().session()
    s.execute("CREATE TABLE d (id int64, price decimal(10,2), "
              "f double, PRIMARY KEY (id))")
    s.execute("INSERT INTO d VALUES (1, 5.00, 1.5), "
              "(2, -3.25, 1.5), (3, 0.00, 1.5)")
    out = s.execute("SELECT id, sign(price) AS sg, "
                    "greatest(price, 1.5) AS g, "
                    "greatest(price, f) AS gf FROM d ORDER BY id")
    assert [int(v) for v in out.column("sg")] == [1, -1, 0]
    # decimal x decimal-literal: scale-2 decimal (raw cents)
    g = [float(v) / 100 for v in out.column("g")]
    assert g == [5.0, 1.5, 1.5], g
    # decimal x double column: descaled to double (the mixed path)
    gf = [float(v) for v in out.column("gf")]
    assert gf == [5.0, 1.5, 1.5], gf


def test_greatest_on_strings_rejected(session):
    from ydb_tpu.sql.planner import PlanError

    with pytest.raises((PlanError, Exception)) as ei:
        session.execute("SELECT greatest(name, name) AS g FROM t")
    assert "string" in str(ei.value)


def test_long_replace_patterns_do_not_collide(session):
    a = "a" * 30 + "X"
    b = "a" * 30 + "Y"
    out = session.execute(
        f"SELECT id, replace(name, '{a}', 'z') AS r1, "
        f"replace(name, '{b}', 'z') AS r2 FROM t WHERE id = 1")
    # neither pattern matches; both columns must be INDEPENDENT
    # transforms (same source), not one aliased to the other
    assert strs(out, "r1") == strs(out, "r2") == ["  Widget A "]
    out2 = session.execute(
        "SELECT replace(concat(name, '"
        + a + "'), '" + a + "', '!') AS r1, "
        "replace(concat(name, '" + a + "'), '" + b + "', '!') AS r2 "
        "FROM t WHERE id = 1")
    assert strs(out2, "r1") == ["  Widget A !"]
    assert strs(out2, "r2") == ["  Widget A " + a]
