"""Scalar UDFs (SURVEY §2.9 UDF-ABI row): registered host functions
usable in SQL expressions, lowered through jax.pure_callback on the
device path and called directly by the oracle."""

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.kqp.session import Cluster


def _cluster():
    c = Cluster()
    s = c.session()
    s.execute("create table kv (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into kv (k, v) values (1, 10), (2, 20), (3, 33)")
    c.register_udf(
        "mix", lambda a, b: (a * 1000003 + b) % 97, dtypes.INT64)
    c.register_udf(
        "halve", lambda a: a.astype(np.float64) / 2.0, dtypes.DOUBLE)
    return c, s


def test_udf_in_select_and_where():
    c, s = _cluster()
    r = s.execute("select k, mix(k, v) as m from kv order by k")
    want = [(k * 1000003 + v) % 97 for k, v in ((1, 10), (2, 20), (3, 33))]
    assert [int(x) for x in r.column("m")] == want

    r = s.execute("select k from kv where halve(v) > 9.0 order by k")
    assert [int(x) for x in r.column("k")] == [2, 3]


def test_udf_inside_aggregate():
    c, s = _cluster()
    r = s.execute("select sum(mix(k, v)) as t from kv")
    want = sum((k * 1000003 + v) % 97 for k, v in
               ((1, 10), (2, 20), (3, 33)))
    assert int(r.column("t")[0]) == want


def test_unknown_udf_still_errors():
    c, s = _cluster()
    import pytest

    from ydb_tpu.sql.planner import PlanError

    with pytest.raises(PlanError, match="unknown function"):
        s.execute("select nosuch(k) from kv")
