"""SQL frontend tests: parser, planner, end-to-end SQL execution.

TPC-H Q1/Q6/Q3/Q5 in actual SQL against the engine, cross-checked with
the hand-built programs/oracle — the KQP compile+execute suite shape
(ydb/core/kqp/ut/query) for the supported dialect."""

import numpy as np
import pytest

from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.kqp import Cluster
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql import parse
from ydb_tpu.sql.planner import Catalog, PlanError, plan_select
from ydb_tpu.workload import tpch

Q1_SQL = """
select
  l_returnflag, l_linestatus,
  sum(l_quantity) as sum_qty,
  sum(l_extendedprice) as sum_base_price,
  sum(l_extendedprice * (1.00 - l_discount)) as sum_disc_price,
  sum(l_extendedprice * (1.00 - l_discount) * (1.00 + l_tax)) as sum_charge,
  avg(l_quantity) as avg_qty,
  avg(l_extendedprice) as avg_price,
  avg(l_discount) as avg_disc,
  count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

Q6_SQL = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

Q3_SQL = """
select l_orderkey,
       sum(l_extendedprice * (1.00 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate, l_orderkey
limit 10
"""

Q5_SQL = """
select n_name,
       sum(l_extendedprice * (1.00 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey
  and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1995-01-01'
group by n_name
order by revenue desc
"""


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.005, seed=31)


@pytest.fixture(scope="module")
def db(data):
    return Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys={
            "orders": ("o_orderkey",), "customer": ("c_custkey",),
            "supplier": ("s_suppkey",), "nation": ("n_nationkey",),
            "region": ("r_regionkey",),
            "lineitem": ("l_orderkey", "l_linenumber"),
        },
        dicts=data.dicts,
    )


def _oracle(data, table):
    cols = {
        n: (v, np.ones(len(v), dtype=bool))
        for n, v in data.tables[table].items()
    }
    return OracleTable(cols, data.schema(table))


def _sql(sql, catalog, db):
    return to_host(execute_plan(plan_select(parse(sql), catalog), db))


def test_parser_roundtrip_shapes():
    s = parse(Q1_SQL)
    assert len(s.items) == 10
    assert s.group_by and s.order_by
    s3 = parse(Q3_SQL)
    assert s3.limit == 10
    assert len(_flatten(s3.from_)) == 3


def _flatten(f):
    from ydb_tpu.sql.planner import _flatten_from

    return _flatten_from(f)[0]


def test_q1_sql_matches_program(data, db, catalog):
    res = _sql(Q1_SQL, catalog, db)
    ora = run_oracle(tpch.q1_program(), _oracle(data, "lineitem"),
                     data.dicts)
    assert res.num_rows == ora.num_rows
    for name in ("sum_qty", "sum_disc_price", "avg_price", "count_order"):
        np.testing.assert_allclose(
            np.asarray(res.cols[name][0], dtype=np.float64),
            np.asarray(ora.cols[name][0], dtype=np.float64),
            rtol=1e-9, err_msg=name,
        )


def test_q6_sql_matches_program(data, db, catalog):
    res = _sql(Q6_SQL, catalog, db)
    ora = run_oracle(tpch.q6_program(), _oracle(data, "lineitem"),
                     data.dicts)
    assert int(res.cols["revenue"][0][0]) == int(ora.cols["revenue"][0][0])


def test_q3_sql_matches_plan(data, db, catalog):
    res = _sql(Q3_SQL, catalog, db)
    ref = to_host(execute_plan(tpch.q3_plan(), db))
    np.testing.assert_array_equal(
        res.cols["revenue"][0], ref.cols["revenue"][0]
    )
    np.testing.assert_array_equal(
        res.cols["l_orderkey"][0], ref.cols["l_orderkey"][0]
    )


def test_q5_sql_matches_plan(data, db, catalog):
    res = _sql(Q5_SQL, catalog, db)
    ref = to_host(execute_plan(tpch.q5_plan(), db))
    np.testing.assert_array_equal(
        res.cols["revenue"][0], ref.cols["revenue"][0]
    )
    np.testing.assert_array_equal(res.cols["n_name"][0],
                                  ref.cols["n_name"][0])


def test_sql_misc_features(data, db, catalog):
    # IN over strings, LIKE, HAVING, expression select, year()
    res = _sql(
        """
        select l_shipmode, count(*) as n,
               sum(l_extendedprice) / 100 as total
        from lineitem
        where l_shipmode in ('AIR', 'MAIL') and l_quantity >= 10
        group by l_shipmode
        having count(*) > 1
        order by l_shipmode
        """,
        catalog, db,
    )
    assert res.num_rows == 2
    d = data.dicts["l_shipmode"]
    names = [d.values[int(i)] for i in res.cols["l_shipmode"][0]]
    assert names == sorted(names)  # ordered lexicographically via ranks
    assert set(names) == {b"AIR", b"MAIL"}

    res2 = _sql(
        """
        select year(o_orderdate) as y, count(*) as n
        from orders where o_orderpriority like '1%'
        group by year(o_orderdate) order by y
        """,
        catalog, db,
    )
    ys = res2.cols["y"][0]
    assert list(ys) == sorted(ys) and len(ys) >= 5


def test_error_cases(catalog):
    with pytest.raises(PlanError):
        plan_select(parse("select nope from lineitem"), catalog)
    with pytest.raises(PlanError):
        plan_select(
            parse("select l_orderkey from lineitem group by l_shipmode"),
            catalog,
        )
    with pytest.raises(SyntaxError):
        parse("select from")
    with pytest.raises(PlanError):
        # cross join without equi condition
        plan_select(parse(
            "select l_orderkey from lineitem, orders"), catalog)


def test_cluster_end_to_end_sql():
    c = Cluster(n_shards=3)
    s = c.session()
    s.execute("""
        create table events (
            id bigint not null,
            ts date not null,
            kind string,
            amount decimal(10, 2),
            primary key (id)
        )
    """)
    r = s.execute("""
        insert into events (id, ts, kind, amount) values
        (1, date '2024-01-01', 'buy', 10.50),
        (2, date '2024-01-02', 'sell', 3.25),
        (3, date '2024-01-02', 'buy', 1.00),
        (4, date '2024-02-01', 'buy', null)
    """)
    assert r.committed
    res = s.execute("""
        select kind, count(*) as n, sum(amount) as total
        from events group by kind order by kind
    """)
    assert res.num_rows == 2
    kinds = [c.dicts["kind"].values[int(i)] for i in res.cols["kind"][0]]
    assert kinds == [b"buy", b"sell"]
    np.testing.assert_array_equal(res.cols["n"][0], [3, 1])
    np.testing.assert_array_equal(res.cols["total"][0], [1150, 325])

    # second insert + repeated query (plan cache path)
    s.execute("insert into events values (5, date '2024-03-01', 'sell', 2.00)")
    res2 = s.execute("""
        select kind, count(*) as n, sum(amount) as total
        from events group by kind order by kind
    """)
    np.testing.assert_array_equal(res2.cols["n"][0], [3, 2])


def test_select_distinct(data, db, catalog):
    res = _sql("select distinct l_shipmode from lineitem order by l_shipmode",
               catalog, db)
    assert res.num_rows == 7  # all ship modes, deduplicated
    d = data.dicts["l_shipmode"]
    names = [d.values[int(i)] for i in res.cols["l_shipmode"][0]]
    assert names == sorted(names)


def test_on_condition_orientation(data, db, catalog):
    # reversed operand order in ON must plan identically
    a = _sql("""select count(*) n from lineitem l
                join orders o on o_orderkey = l_orderkey
                where o_orderdate < date '1995-01-01'""", catalog, db)
    b = _sql("""select count(*) n from lineitem l
                join orders o on l_orderkey = o_orderkey
                where o_orderdate < date '1995-01-01'""", catalog, db)
    assert int(a.cols["n"][0][0]) == int(b.cols["n"][0][0]) > 0


def test_no_payload_join_preserves_multiplicity(data, db, catalog):
    # lineitem joined to itself-shaped non-unique side must not collapse
    # multiplicity: count(*) over orders x lineitem on orderkey equals
    # lineitem rows with matching order (orders unique -> semi fine),
    # but joining the non-unique direction must expand
    res = _sql("""select count(*) n from orders, lineitem
                  where o_orderkey = l_orderkey""", catalog, db)
    n_li = len(data.tables["lineitem"]["l_orderkey"])
    assert int(res.cols["n"][0][0]) == n_li  # every lineitem has its order


def test_left_join_where_equi_cond_stays_post_join():
    """WHERE a.ya = b.yb on a LEFT JOIN must filter AFTER the join (drop
    NULL-extended rows), not fold into the ON condition."""
    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("""create table a (k bigint not null, ya bigint,
                 primary key (k))""")
    s.execute("""create table b (k bigint not null, yb bigint,
                 primary key (k))""")
    s.execute("insert into a values (1, 10), (2, 20), (3, 30)")
    s.execute("insert into b values (1, 10), (2, 99)")
    # matches: k=1 (ya=yb=10 kept), k=2 (20!=99 dropped),
    # k=3 (no match -> NULL yb -> dropped by WHERE)
    res = s.execute("""select a.k as k, yb from a
                       left join b on a.k = b.k
                       where ya = yb order by k""")
    assert res.num_rows == 1
    assert int(res.cols["k"][0][0]) == 1
    assert int(res.cols["yb"][0][0]) == 10
    # sanity: without the WHERE all three left rows survive
    res2 = s.execute("""select a.k as k from a
                        left join b on a.k = b.k order by k""")
    assert res2.num_rows == 3


def test_left_join_residual_on_colliding_name_raises():
    """A residual predicate referencing a build-side column shadowed by a
    probe-side column of the same name must raise, not silently resolve
    to the probe side."""
    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table a (k bigint not null, ya bigint, primary key (k))")
    s.execute("create table b (k bigint not null, yb bigint, primary key (k))")
    s.execute("insert into a values (1, 1), (2, 20)")
    s.execute("insert into b values (2, 99)")
    with pytest.raises(PlanError, match="not carried through the join"):
        s.execute("""select a.k from a left join b on a.k = b.k
                     where a.ya = b.k""")


def test_explain_renders_the_physical_plan(data, db, catalog):
    from ydb_tpu.kqp.session import Cluster

    c = Cluster()
    s = c.session()
    s.execute("create table kv (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into kv (k, v) values (1, 2), (3, 4)")
    text = s.execute("explain select k, sum(v) as t from kv "
                     "where k > 0 group by k order by t desc limit 5")
    assert "Transform" in text and "TableScan kv" in text
    assert "group_by[keys=['k']" in text
    assert "limit=5" in text
    # joins show probe/build structure
    text2 = s.execute(
        "explain select a.k from kv a, kv b where a.k = b.k")
    assert "Join" in text2


# ---------------- UNION [ALL] ----------------


def test_union_all_with_rename_order_limit(data, db, catalog):
    """Branch outputs align by position (second branch's alias differs),
    trailing ORDER BY/LIMIT bind to the whole union."""
    li = data.tables["lineitem"]
    sql = """
    select l_orderkey, l_quantity from lineitem where l_quantity < 3
    union all
    select l_orderkey, l_quantity * 2 as q2 from lineitem
    where l_quantity > 48
    order by l_quantity desc limit 5"""
    from ydb_tpu.sql.planner import plan_select_full

    pq = plan_select_full(parse(sql), catalog)
    assert pq.out_names == ("l_orderkey", "l_quantity")
    out = to_host(execute_plan(pq.plan, db))
    got = np.asarray(out.cols["l_quantity"][0])
    # l_quantity is decimal(2)-scaled: SQL "< 3" means 300 cents
    lo = li["l_quantity"][li["l_quantity"] < 300]
    hi = li["l_quantity"][li["l_quantity"] > 4800] * 2
    assert len(lo) and len(hi), "both branches must select rows"
    want = np.sort(np.concatenate([lo, hi]))[::-1][:5]
    assert np.array_equal(got, want)


def test_union_all_in_from_groups_across_branches(data, db, catalog):
    """The TPC-DS channel-union shape: union in a derived table, one
    aggregation over all branches, string key decodes via the shared
    dictionary."""
    li = data.tables["lineitem"]
    sql = """
    select l_returnflag, sum(amt) as total from (
      select l_returnflag, l_extendedprice as amt from lineitem
      where l_quantity < 25
      union all
      select l_returnflag, l_extendedprice as amt from lineitem
      where l_quantity >= 25
    ) u group by l_returnflag order by l_returnflag"""
    from ydb_tpu.sql.planner import plan_select_full

    pq = plan_select_full(parse(sql), catalog)
    out = to_host(execute_plan(pq.plan, db))
    rf = li["l_returnflag"]
    want = {int(k): int(li["l_extendedprice"][rf == k].sum())
            for k in np.unique(rf)}
    got_k = np.asarray(out.cols["l_returnflag"][0])
    got_v = np.asarray(out.cols["total"][0])
    assert {int(k): int(v) for k, v in zip(got_k, got_v)} == want


def test_union_distinct_dedups(data, db, catalog):
    sql = ("select l_returnflag from lineitem "
           "union select l_returnflag from lineitem")
    from ydb_tpu.sql.planner import plan_select_full

    pq = plan_select_full(parse(sql), catalog)
    out = to_host(execute_plan(pq.plan, db))
    got = np.sort(np.asarray(out.cols["l_returnflag"][0]))
    want = np.unique(data.tables["lineitem"]["l_returnflag"])
    assert np.array_equal(got, want)


def test_union_arity_mismatch_raises(data, db, catalog):
    from ydb_tpu.sql.planner import plan_select_full

    with pytest.raises(PlanError, match="columns"):
        plan_select_full(parse(
            "select l_orderkey, l_quantity from lineitem "
            "union all select l_orderkey from lineitem"), catalog)


def test_union_mixed_chain_rejected():
    with pytest.raises(SyntaxError, match="mixed UNION"):
        parse("select 1 as a from t union all select 2 as a from t "
              "union select 3 as a from t")


def test_union_all_permuted_columns(data, db, catalog):
    """A later branch whose output names PERMUTE the first branch's must
    remap by position without corrupting either column (code-review
    regression: sequential renames through one shared env)."""
    li = data.tables["lineitem"]
    sql = """
    select l_orderkey, l_partkey from lineitem where l_quantity < 2
    union all
    select l_partkey, l_orderkey from lineitem where l_quantity > 49"""
    from ydb_tpu.sql.planner import plan_select_full

    pq = plan_select_full(parse(sql), catalog)
    assert pq.out_names == ("l_orderkey", "l_partkey")
    out = to_host(execute_plan(pq.plan, db))
    lo = li["l_quantity"] < 200
    hi = li["l_quantity"] > 4900
    want_ok = np.concatenate([li["l_orderkey"][lo], li["l_partkey"][hi]])
    want_pk = np.concatenate([li["l_partkey"][lo], li["l_orderkey"][hi]])
    assert np.array_equal(np.asarray(out.cols["l_orderkey"][0]), want_ok)
    assert np.array_equal(np.asarray(out.cols["l_partkey"][0]), want_pk)


def test_union_cte_scoping(data, db, catalog):
    """A statement-level WITH scopes over every branch; a later branch's
    own WITH shadows locally without rewriting sibling branches
    (code-review regression: shared cte dict registered all branches'
    CTEs before planning any)."""
    from ydb_tpu.sql.planner import plan_select_full

    li = data.tables["lineitem"]
    sql = """
    with base as (select l_orderkey as v from lineitem
                  where l_quantity < 2)
    select v from base
    union all
    with base as (select l_partkey as v from lineitem
                  where l_quantity < 2)
    select v from base"""
    pq = plan_select_full(parse(sql), catalog)
    out = to_host(execute_plan(pq.plan, db))
    m = li["l_quantity"] < 200
    want = np.concatenate([li["l_orderkey"][m], li["l_partkey"][m]])
    assert np.array_equal(np.asarray(out.cols["v"][0]), want)


def test_union_interior_order_by_rejected():
    with pytest.raises(SyntaxError, match="non-final UNION branch"):
        parse("select a from t order by a limit 3 "
              "union all select a from t")


def test_sql_path_device_block_cache(monkeypatch):
    """The cluster-owned block cache serves warm SQL scans and every
    mutation (INSERT/UPDATE/DELETE) is immediately visible — the cache
    keys on per-shard visible-portion ids, so a commit changes the key
    (shared_sausagecache analog on the SQL path)."""
    monkeypatch.setenv("YDB_TPU_SCAN_CACHE_BYTES", str(64 << 20))
    c = Cluster(n_shards=2)
    s = c.session()
    s.execute("create table kv (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into kv values (1, 10), (2, 20), (3, 30)")

    def total():
        r = s.execute("select sum(v) as s from kv")
        return int(np.asarray(r.cols["s"][0])[0])

    assert total() == 60
    assert total() == 60
    assert c.scan_block_cache.hits > 0
    s.execute("insert into kv values (4, 40)")
    assert total() == 100
    assert total() == 100
    # a row-store table (UPDATE/DELETE surface) keeps exact semantics
    # alongside the cache (its sources are not portion-backed)
    s.execute("create table rt (k bigint not null, v bigint, "
              "primary key (k)) with (store = row)")
    s.execute("insert into rt values (1, 1), (2, 2)")
    s.execute("update rt set v = 9 where k = 1")
    s.execute("delete from rt where k = 2")
    r = s.execute("select sum(v) as s from rt")
    assert int(np.asarray(r.cols["s"][0])[0]) == 9


def test_block_cache_cleared_on_drop_table(monkeypatch):
    """A re-created same-name table reuses shard ids and restarts
    portion ids, so DROP TABLE must clear the cluster block cache or a
    warm SELECT would serve the dropped table's rows (code-review
    finding)."""
    monkeypatch.setenv("YDB_TPU_SCAN_CACHE_BYTES", str(64 << 20))
    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table t (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into t values (1, 111)")
    r = s.execute("select sum(v) as s from t")
    assert int(np.asarray(r.cols["s"][0])[0]) == 111
    s.execute("drop table t")
    s.execute("create table t (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into t values (1, 222)")
    r = s.execute("select sum(v) as s from t")
    assert int(np.asarray(r.cols["s"][0])[0]) == 222


def test_block_cache_pruned_for_gcd_portions(monkeypatch):
    """Compaction/TTL churn must not leave cluster-cache entries keyed
    by GC'd portion ids pinning HBM budget until LRU pressure: the
    per-statement Database snapshot prunes against the live portion
    sets, mirroring ColumnShard.scan's per-shard prune (ADVICE r5)."""
    monkeypatch.setenv("YDB_TPU_SCAN_CACHE_BYTES", str(64 << 20))
    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table t (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into t values (1, 10)")
    s.execute("insert into t values (2, 20)")  # second portion
    r = s.execute("select sum(v) as s from t")  # warm: keys current set
    assert int(np.asarray(r.cols["s"][0])[0]) == 30
    assert len(c.scan_block_cache) >= 1
    shard = c.tables["t"].shards[0]
    shard.compact()
    shard.gc_blobs(keep_snap=shard.snap)  # pre-compaction portions die
    live = set(shard.portions)
    # the warm entry references dead portion ids until the next
    # statement snapshot prunes it
    assert any(
        not live.issuperset(pids)
        for key in c.scan_block_cache for _, pids in key[0])
    r = s.execute("select sum(v) as s from t")
    assert int(np.asarray(r.cols["s"][0])[0]) == 30
    for key in c.scan_block_cache:
        for _sid, pids in key[0]:
            assert live.issuperset(pids), key
    # the emergency valve (budget -> 0 mid-process) frees everything:
    # entries cached under the old budget can never be served again
    assert len(c.scan_block_cache) >= 1
    monkeypatch.setenv("YDB_TPU_SCAN_CACHE_BYTES", "0")
    s.execute("select sum(v) as s from t")
    assert len(c.scan_block_cache) == 0


# ---------------- window functions ----------------


def test_window_rank_through_sql_and_dq(data, db, catalog):
    """rank() over a JOIN-bearing plan: the DQ stage graph must treat
    the WindowStep as a merge barrier (per-task evaluation would rank
    within partitions of the data, not the data)."""
    from ydb_tpu.sql.planner import plan_select_full

    li = data.tables["lineitem"]
    ords = data.tables["orders"]
    sql = """
    select l_orderkey, revenue, rank() over (order by revenue desc)
           as rnk
    from (select l_orderkey,
                 sum(l_extendedprice * (1.00 - l_discount)) as revenue
          from lineitem, orders
          where l_orderkey = o_orderkey
            and o_orderdate < date '1995-03-15'
          group by l_orderkey) r
    order by rnk, l_orderkey
    limit 10"""
    pq = plan_select_full(parse(sql), catalog)
    out = to_host(execute_plan(pq.plan, db))
    # independent numpy reference
    cutoff = (np.datetime64("1995-03-15", "D")
              - np.datetime64("1970-01-01", "D")).astype(int)
    omap = {k: d for k, d in zip(ords["o_orderkey"].tolist(),
                                 ords["o_orderdate"].tolist())}
    import collections
    rev = collections.defaultdict(int)
    for k, p, dsc in zip(li["l_orderkey"].tolist(),
                         li["l_extendedprice"].tolist(),
                         li["l_discount"].tolist()):
        if omap[k] < cutoff:
            rev[k] += p * (100 - dsc)
    ranked = sorted(rev.items(), key=lambda kv: (-kv[1], kv[0]))
    want = []
    rnk = 0
    prev = None
    for i, (k, v) in enumerate(ranked[:10]):
        if v != prev:
            rnk = i + 1
        want.append((k, rnk))
        prev = v
    got = list(zip(np.asarray(out.cols["l_orderkey"][0]).tolist(),
                   np.asarray(out.cols["rnk"][0]).tolist()))
    assert got == want


def test_window_mixed_with_aggregate_rejected(data, db, catalog):
    with pytest.raises(PlanError, match="window functions cannot mix"):
        from ydb_tpu.sql.planner import plan_select_full

        plan_select_full(parse(
            "select sum(l_quantity) as s, "
            "rank() over (order by l_orderkey) as r from lineitem"),
            catalog)


def test_ranking_window_with_args_is_a_syntax_error():
    """rank(x) OVER (...) used to silently DROP the argument list; it
    must fail at parse time instead of rewriting the query's meaning."""
    with pytest.raises(SyntaxError, match="no arguments"):
        parse("select rank(l_quantity) over (order by l_orderkey) as r"
              " from lineitem")
    with pytest.raises(SyntaxError, match="no arguments"):
        parse("select dense_rank(distinct l_tax) over"
              " (order by l_orderkey) as r from lineitem")
    # argument-free ranking calls still parse
    parse("select row_number() over (order by l_orderkey) as r"
          " from lineitem")


def test_nested_window_rejected_with_targeted_error(catalog):
    """Windows hidden inside expressions or WHERE/HAVING used to fall
    through to a generic late PlanError; they must fail with the
    targeted top-level-select-items message."""
    with pytest.raises(PlanError, match="top-level select items"):
        plan_select(parse(
            "select rank() over (order by l_orderkey) + 1 as r"
            " from lineitem"), catalog)
    with pytest.raises(PlanError, match="not allowed in WHERE"):
        plan_select(parse(
            "select l_orderkey from lineitem"
            " where rank() over (order by l_orderkey) < 5"), catalog)
    with pytest.raises(PlanError, match="not allowed in HAVING"):
        plan_select(parse(
            "select l_orderkey, sum(l_quantity) as s from lineitem"
            " group by l_orderkey"
            " having rank() over (order by l_orderkey) < 5"), catalog)
    # nested windows inside a DERIVED TABLE get the same treatment
    with pytest.raises(PlanError, match="top-level select items"):
        plan_select(parse(
            "select r from (select rank() over (order by l_orderkey)"
            " * 2 as r from lineitem) t"), catalog)


def test_or_of_exists_decorrelates():
    """EXISTS(A) OR EXISTS(B) (and mixed with plain predicates) lowers
    through the counting scalar-join rewrite (TPC-DS q10/q35 shape)."""
    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table cu (id bigint not null, nm string, "
              "primary key (id))")
    s.execute("create table w (k bigint not null, cid bigint, "
              "primary key (k))")
    s.execute("create table ct (k bigint not null, cid bigint, "
              "primary key (k))")
    s.execute("insert into cu values (1,'a'),(2,'b'),(3,'c'),(4,'d')")
    s.execute("insert into w values (10, 1), (11, 3)")
    s.execute("insert into ct values (20, 2), (21, 3)")
    r = s.execute(
        "select id from cu c where "
        "exists (select * from w where c.id = cid) "
        "or exists (select * from ct where c.id = cid) order by id")
    assert np.asarray(r.cols["id"][0]).tolist() == [1, 2, 3]
    r2 = s.execute(
        "select id from cu c where nm = 'd' "
        "or not exists (select * from w where c.id = cid) "
        "order by id")
    assert np.asarray(r2.cols["id"][0]).tolist() == [2, 4]
