"""KeyValue tablet (SURVEY §2.3 keyvalue row; reference
ydb/core/keyvalue): durable KV commands over the tablet executor with
spilled-blob lifecycle and crash recovery."""

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.tablet.keyvalue import INLINE_LIMIT, KeyValueTablet


def test_write_read_range_rename_delete():
    store = MemBlobStore()
    kv = KeyValueTablet("kv1", store)
    kv.write("a", b"1")
    kv.write("b", b"2")
    kv.write("c", b"3")
    assert kv.read("b") == b"2"
    assert kv.read("nope") is None
    assert kv.read_range("a", "c") == [("a", b"1"), ("b", b"2")]
    assert kv.rename("b", "bb")
    assert kv.read("b") is None and kv.read("bb") == b"2"
    assert not kv.rename("ghost", "x")
    assert kv.delete_range("a", "c") == 2  # a, bb
    assert kv.read("a") is None
    assert kv.read("c") == b"3"


def test_large_values_spill_and_gc():
    store = MemBlobStore()
    kv = KeyValueTablet("kv1", store)
    big = bytes(range(256)) * ((INLINE_LIMIT // 256) + 4)
    kv.write("big", big)
    assert len(store.list("kv1/kvblob/")) == 1
    assert kv.read("big") == big
    # overwrite drops the old blob AFTER commit
    kv.write("big", b"small now")
    assert store.list("kv1/kvblob/") == []
    assert kv.read("big") == b"small now"
    # copy duplicates spilled blobs (single-owner refs)
    kv.write("big", big)
    kv.copy_range("big", "bih", prefix_to="copy/")
    assert len(store.list("kv1/kvblob/")) == 2
    assert kv.read("copy/big") == big
    kv.delete_range("big", "bih")
    assert len(store.list("kv1/kvblob/")) == 1  # copy's blob survives
    assert kv.read("copy/big") == big


def test_self_rename_and_copy_overwrite_blob_lifecycle():
    store = MemBlobStore()
    kv = KeyValueTablet("kv1", store)
    big = b"z" * (INLINE_LIMIT + 1)
    kv.write("a", big)
    assert kv.rename("a", "a")  # no-op must NOT free the blob
    assert kv.read("a") == big
    assert len(store.list("kv1/kvblob/")) == 1
    # copy over an existing spilled destination releases its old blob
    kv.write("c/a", b"q" * (INLINE_LIMIT + 1))
    kv.copy_range("a", "b", prefix_to="c/")
    assert kv.read("c/a") == big
    assert len(store.list("kv1/kvblob/")) == 2  # a's + c/a's fresh copy


def test_reboot_recovers_state_and_blob_seq():
    store = MemBlobStore()
    kv = KeyValueTablet("kv1", store)
    big = b"x" * (INLINE_LIMIT + 1)
    kv.write("k", b"inline")
    kv.write("big", big)
    kv.rename("k", "k2")

    kv2 = KeyValueTablet.boot("kv1", store)
    assert kv2.read("k2") == b"inline"
    assert kv2.read("k") is None
    assert kv2.read("big") == big
    # new generation's spilled blobs cannot collide with old ones
    kv2.write("big2", big)
    assert len(store.list("kv1/kvblob/")) == 2
    assert kv2.read("big2") == big
