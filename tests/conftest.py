"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §4 tier-2 analog: a
deterministic in-process multi-"node" runtime). Real-TPU behavior is covered
by bench.py / __graft_entry__.py on hardware.

Note: the environment's TPU plugin forces its own platform selection via a
sitecustomize hook, so setting JAX_PLATFORMS in the environment is not
enough — we must override the jax config *after* import, before any backend
initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running out-of-core / subprocess tests")


class Clock:
    """Injectable manual clock shared by coordination-plane tests."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t
