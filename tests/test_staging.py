"""Low-copy block staging pipeline tests (PR 3 tentpole, part 2).

Covers: rechunk's aligned pass-through / single-buffer fast paths,
TableBlock.from_numpy tail-only padding (padding validity never leaks),
the shared-pool depth-k prefetch in stream_blocks (incl. abandoned
generators not leaking producer tasks), per-scan stage timers, the
scan-executor LRU cap, and the kernelbench smoke wiring.
"""

import gc
import time

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks.block import TableBlock
from ydb_tpu.engine.blobs import DirBlobStore
from ydb_tpu.engine.reader import rechunk, stream_blocks
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.obs import probes
from ydb_tpu.runtime.conveyor import shared_conveyor
from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program
from ydb_tpu.ssa.program import Call, Col, FilterStep, Op, lit

SCHEMA = dtypes.schema(("a", dtypes.INT64), ("b", dtypes.DOUBLE))


def _payload(n, seed=0):
    rng = np.random.default_rng(seed)
    return ({"a": rng.integers(0, 100, n).astype(np.int64),
             "b": rng.random(n)},
            {"a": np.ones(n, dtype=bool),
             "b": rng.random(n) > 0.2})


def test_rechunk_aligned_payload_passes_arrays_through():
    p = _payload(64)
    out = list(rechunk(iter([p]), ("a", "b"), 64))
    assert len(out) == 1
    cols, valid = out[0]
    # identity, not a copy: the aligned fast path
    assert cols["a"] is p[0]["a"]
    assert valid["b"] is p[1]["b"]


def test_rechunk_single_buffered_piece_skips_concat():
    p = _payload(40)
    out = list(rechunk(iter([p]), ("a", "b"), 64))
    assert len(out) == 1
    # whole-payload piece: original arrays flush through unconcatenated
    assert out[0][0]["a"] is p[0]["a"]


def test_rechunk_recut_matches_naive_concat():
    pieces = [_payload(n, seed=i) for i, n in enumerate([10, 64, 3, 57,
                                                         128, 1])]
    cap = 48
    got = list(rechunk(iter(pieces), ("a", "b"), cap))
    cat_a = np.concatenate([p[0]["a"] for p in pieces])
    cat_vb = np.concatenate([p[1]["b"] for p in pieces])
    assert sum(len(c["a"]) for c, _ in got) == len(cat_a)
    assert all(len(c["a"]) == cap for c, _ in got[:-1])
    np.testing.assert_array_equal(
        np.concatenate([c["a"] for c, _ in got]), cat_a)
    np.testing.assert_array_equal(
        np.concatenate([v["b"] for _, v in got]), cat_vb)


def test_from_numpy_tail_padding_never_leaks_validity():
    cols, valid = _payload(70)
    blk = TableBlock.from_numpy(cols, SCHEMA, valid, capacity=128)
    assert int(blk.length) == 70
    for name in ("a", "b"):
        v = np.asarray(blk.columns[name].validity)
        assert not v[70:].any(), f"padding validity leaked in {name}"
    np.testing.assert_array_equal(blk.to_numpy()["a"], cols["a"])
    # default validity (None) must also stay False in the tail
    blk2 = TableBlock.from_numpy(cols, SCHEMA, None, capacity=96)
    for name in ("a", "b"):
        v = np.asarray(blk2.columns[name].validity)
        assert v[:70].all() and not v[70:].any()


def test_from_numpy_aligned_no_padding():
    cols, valid = _payload(128)
    blk = TableBlock.from_numpy(cols, SCHEMA, valid, capacity=128)
    assert blk.capacity == 128 and int(blk.length) == 128
    np.testing.assert_array_equal(
        np.asarray(blk.columns["b"].validity), valid["b"])


@pytest.mark.parametrize("depth", [0, 1, 3])
def test_stream_blocks_prefetch_depths_agree(depth):
    pieces = [_payload(n, seed=i) for i, n in enumerate([100, 30, 250])]
    base = list(stream_blocks(iter(pieces), ("a", "b"), SCHEMA, 64,
                              prefetch=False))
    got = list(stream_blocks(iter(pieces), ("a", "b"), SCHEMA, 64,
                             depth=depth))
    assert len(got) == len(base)
    for g, b in zip(got, base):
        assert int(g.length) == int(b.length)
        np.testing.assert_array_equal(np.asarray(g.columns["a"].data),
                                      np.asarray(b.columns["a"].data))


def test_stream_blocks_empty_stream_emits_one_block():
    out = list(stream_blocks(iter([]), ("a", "b"), SCHEMA, 16))
    assert len(out) == 1 and int(out[0].length) == 0


def test_abandoned_stream_releases_shared_pool_producer():
    def slow_payloads():
        for i in range(50):
            time.sleep(0.01)
            yield _payload(64, seed=i)

    gen = stream_blocks(slow_payloads(), ("a", "b"), SCHEMA, 64, depth=2)
    next(gen)  # producer is now parked on the bounded queue
    gen.close()  # GeneratorExit -> stop flag + drain
    del gen
    gc.collect()
    # the producer task must exit promptly instead of leaking a worker
    shared_conveyor().wait_idle(timeout=10.0)


def _mk_shard(tmp_path, rows=500):
    shard = ColumnShard(
        "t", SCHEMA, DirBlobStore(str(tmp_path)),
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=128,
                           scan_cache_entries=2))
    rng = np.random.default_rng(1)
    shard.commit([shard.write({
        "a": rng.integers(0, 10, rows).astype(np.int64),
        "b": rng.random(rows)})])
    return shard


def _prog(threshold):
    return Program((
        FilterStep(Call(Op.GE, Col("a"), lit(threshold))),
        GroupByStep(("a",), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))


def test_scan_reports_stage_timers_and_fires_probe(tmp_path):
    shard = _mk_shard(tmp_path)
    with probes.TraceSession("columnshard.scan.stages") as sess:
        out = shard.scan(_prog(0))
    assert out.num_rows > 0
    stages = shard.last_scan_stages
    for key in ("read", "merge", "stage", "compute"):
        assert key in stages, stages
    assert stages["read"] > 0.0
    assert stages["compute"] > 0.0
    assert sess.counts["columnshard.scan.stages"] == 1
    (_, params), = sess.events
    assert params["shard"] == "t" and "stage" in params


def test_scan_cache_lru_bounded(tmp_path):
    shard = _mk_shard(tmp_path)
    for t in range(4):
        shard.scan(_prog(t))
    assert len(shard._scan_cache) <= 2
    # most-recent program stays cached (LRU keeps the tail)
    key3 = (_prog(3), ())
    assert any(k[0] == _prog(3) for k in shard._scan_cache)
    # re-scanning a cached program must not grow the cache
    shard.scan(_prog(3))
    assert len(shard._scan_cache) <= 2
    assert key3  # silence lint: structural key shape documented above


def test_scan_results_unchanged_by_staging_pipeline(tmp_path):
    # end-to-end: the low-copy + prefetch path produces the same result
    # as the synchronous path
    shard = _mk_shard(tmp_path, rows=700)
    out = shard.scan(_prog(2))
    a = shard.source_at().columns["a"]
    expect = {int(v): int((a[a >= 2] == v).sum())
              for v in np.unique(a[a >= 2])}
    got = {int(k): int(n) for k, n in zip(out.column("a"),
                                          out.column("n"))}
    assert got == expect


def test_kernelbench_smoke():
    from ydb_tpu.obs import kernelbench

    assert kernelbench.main(["--smoke", "--json"]) == 0
