"""Volatile distributed transactions: optimistic apply, readset
exchange, reader fencing, abort-on-restart semantics, barrier
monotonicity (reference: ydb/core/tx/datashard/volatile_tx.h:91,
datashard_outreadset.h; VERDICT r3 missing #9 / weak #7)."""

import pytest

from ydb_tpu import dtypes
from ydb_tpu.datashard.shard import (
    DataShard,
    RowOp,
    VolatileUndecided,
)
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.tx.coordinator import Coordinator

SCHEMA = dtypes.schema(("id", dtypes.INT64, False),
                       ("v", dtypes.INT64, True))


def make_shards(n=2):
    store = MemBlobStore()
    return store, [DataShard(f"s{i}", SCHEMA, store, ("id",))
                   for i in range(n)]


def propose(shard, key, v):
    return shard.propose([RowOp((key,), {"id": key, "v": v})])


def test_volatile_commit_across_shards():
    _store, (a, b) = make_shards()
    coord = Coordinator()
    wa, wb = propose(a, 1, 10), propose(b, 2, 20)
    res = coord.commit_volatile([a, b], [[wa], [wb]])
    assert res.committed
    snap = coord.read_snapshot()
    assert snap >= res.step
    rows_a = [r for page in a.read(snap) for r in page]
    rows_b = [r for page in b.read(snap) for r in page]
    assert rows_a[0][1]["v"] == 10 and rows_b[0][1]["v"] == 20


def test_volatile_abort_rolls_back_all_participants():
    _store, (a, b) = make_shards()
    coord = Coordinator()
    wa = propose(a, 1, 10)
    # b's write id is bogus -> b's local validation fails
    res = coord.commit_volatile([a, b], [[wa], [9999]])
    assert not res.committed and "volatile abort" in res.error
    snap = coord.read_snapshot()
    assert [r for page in a.read(snap) for r in page] == []
    # staged entry on a was aborted, not left dangling
    assert a.executor.db.table("pending").get((wa,)) is None


def test_undecided_volatile_fences_readers():
    _store, (a, b) = make_shards()
    wa = propose(a, 1, 10)
    assert a.apply_volatile([wa], txid=7, step=5, expected_peers=[1])
    # the decision never arrived: snapshot readers at step >= 5 block
    with pytest.raises(VolatileUndecided):
        a.read(5, keys=[(1,)])
    with pytest.raises(VolatileUndecided):
        list(a.read(6))
    # readers BELOW the volatile step pass (it is ordered after them)
    assert [r for page in a.read(4) for r in page] == []
    # non-intersecting point reads pass too
    assert [r for page in a.read(6, keys=[(42,)]) for r in page] == []
    # decision arrives -> effects durable and readable
    assert b is not a
    assert a.deliver_readset(7, 1, True) is True
    rows = [r for page in a.read(5) for r in page]
    assert rows[0][1]["v"] == 10


def test_negative_readset_rolls_back():
    _store, (a, _b) = make_shards()
    wa = propose(a, 1, 10)
    assert a.apply_volatile([wa], txid=9, step=3, expected_peers=[1])
    assert a.deliver_readset(9, 1, False) is False
    assert [r for page in a.read(3) for r in page] == []
    assert a.executor.db.table("pending").get((wa,)) is None


def test_restart_forgets_undecided_volatile():
    """Volatile effects are not durable before the decision: a reboot
    auto-aborts them (the reference's volatile contract)."""
    store, (a, _b) = make_shards()
    wa = propose(a, 1, 10)
    assert a.apply_volatile([wa], txid=11, step=4, expected_peers=[1])
    a2 = DataShard("s0", SCHEMA, store, ("id",))  # reboot
    # no fence, no data: the undecided tx evaporated ...
    assert [r for page in a2.read(10) for r in page] == []
    # ... but the durably staged pending entry survives for repair
    assert a2.executor.db.table("pending").get((wa,)) is not None


def test_barrier_never_passes_undecided_step():
    """A later classic commit must not advance the read barrier past an
    undecided volatile step (snapshot monotonicity)."""
    _store, (a, b) = make_shards()
    coord = Coordinator()

    class SlowShard:
        """Participant that accepts but never hears back (peer lost)."""

        def __init__(self, inner):
            self.inner = inner
            self.calls = []

        def apply_volatile(self, args, txid, step, peers):
            self.calls.append(("apply", step))
            return self.inner.apply_volatile(args, txid, step, peers)

        def deliver_readset(self, txid, frm, ok):
            self.calls.append(("rs", txid))
            return None  # swallow: decision never settles

    wa, wb = propose(a, 1, 10), propose(b, 2, 20)
    slow_a = SlowShard(a)
    import threading

    started = threading.Event()
    release = threading.Event()

    real_apply = slow_a.apply_volatile

    def blocking_apply(args, txid, step, peers):
        ok = real_apply(args, txid, step, peers)
        started.set()
        release.wait(timeout=10)
        return ok

    slow_a.apply_volatile = blocking_apply
    t = threading.Thread(
        target=lambda: coord.commit_volatile(
            [slow_a, b], [[wa], [wb]]),
        daemon=True)
    t.start()
    assert started.wait(timeout=10)
    vol_step = a._volatile and next(
        iter(a._volatile.values())).step
    # while the volatile tx is outstanding, a background plan at a
    # LATER step cannot drag the barrier past the undecided step
    later = coord.background_plan()
    assert later > vol_step
    assert coord.read_snapshot() < vol_step
    release.set()
    t.join(timeout=10)
    assert coord.read_snapshot() >= later


def test_prepare_rejects_key_with_undecided_volatile():
    """expect-preconditions (and blind writes) must not validate
    against committed data while an undecided volatile write owns the
    key (code-review regression)."""
    _store, (a, _b) = make_shards()
    wa = propose(a, 1, 10)
    assert a.apply_volatile([wa], txid=21, step=5, expected_peers=[1])
    # fail-if-exists INSERT for the same key: committed data says the
    # key is free, but the volatile write at step 5 owns it
    w2 = a.propose([RowOp((1,), {"id": 1, "v": 99})],
                   expect={(1,): None})
    import pytest as _pytest

    from ydb_tpu.datashard.shard import TxRejected

    with _pytest.raises(TxRejected, match="undecided volatile"):
        a.prepare([w2])
    # decision lands -> the key is committed -> precondition now
    # fails for the RIGHT reason (key exists)
    a.deliver_readset(21, 1, True)
    with _pytest.raises(TxRejected, match="precondition"):
        a.prepare([w2])


def test_volatile_never_overtakes_classic_commit_mid_apply():
    """A volatile commit finishing while a classic commit is still
    applying must not advance the barrier past the classic step
    (code-review regression: torn cross-shard read)."""
    import threading

    _store, shards = make_shards(4)
    a, b, c, d = shards
    coord = Coordinator()

    applied_first = threading.Event()
    release = threading.Event()
    real_commit_at = b.commit_at

    def slow_commit_at(write_ids, step):
        applied_first.set()
        release.wait(timeout=10)
        return real_commit_at(write_ids, step)

    b.commit_at = slow_commit_at
    wa, wb = propose(a, 1, 10), propose(b, 2, 20)
    classic = {}
    t = threading.Thread(
        target=lambda: classic.update(
            res=coord.commit([a, b], [[wa], [wb]])), daemon=True)
    t.start()
    assert applied_first.wait(timeout=10)
    classic_step = coord.last_step
    # volatile commit on OTHER shards completes while classic mid-apply
    wc, wd = propose(c, 3, 30), propose(d, 4, 40)
    vres = coord.commit_volatile([c, d], [[wc], [wd]])
    assert vres.committed and vres.step > classic_step
    # barrier must still be short of the classic step: shard a has the
    # write, shard b does not yet
    assert coord.read_snapshot() < classic_step
    release.set()
    t.join(timeout=10)
    assert classic["res"].committed
    assert coord.read_snapshot() >= vres.step


def test_sql_multi_shard_upsert_goes_volatile():
    """The row-table SQL path commits multi-shard writes through the
    volatile protocol end to end."""
    from ydb_tpu.kqp.session import Cluster

    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 4)")
    s.execute("INSERT INTO t VALUES " + ", ".join(
        f"({i}, {i * 10})" for i in range(16)))
    out = s.execute("SELECT count(*) AS c, sum(v) AS s FROM t")
    assert int(out.column("c")[0]) == 16
    assert int(out.column("s")[0]) == sum(i * 10 for i in range(16))
