"""HBM-resident column tier tests (engine/resident.py): promotion and
eviction lifecycle, invalidation across compaction/TTL rewrites,
mid-stream resident/host fallback equality, the YDB_TPU_RESIDENT=0 A/B
switch, and the single-flight DeviceBlockCache fill."""

import threading

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.analysis import sanitizer
from ydb_tpu.engine import resident as resident_mod
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.resident import ResidentStore
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa.program import Program, lit

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("ts", dtypes.DATE, False),
    ("tag", dtypes.STRING),
    ("val", dtypes.INT64),
)


@pytest.fixture(autouse=True)
def _restore_force():
    yield
    resident_mod.RESIDENT_FORCE = None


def _shard(upsert=False, **cfg):
    return ColumnShard(
        "rshard", SCHEMA, MemBlobStore(),
        pk_column="id", ttl_column="ts", upsert=upsert,
        config=ShardConfig(**cfg) if cfg else None,
    )


def _write(shard, ids, ts=None, vals=None):
    n = len(ids)
    cols = shard.encode_strings({
        "id": np.asarray(ids, dtype=np.int64),
        "ts": np.asarray(ts if ts is not None else [100] * n,
                         dtype=np.int32),
        "tag": [b"x"] * n,
        "val": np.asarray(vals if vals is not None else ids,
                          dtype=np.int64),
    })
    return shard.write(cols)


def _agg_prog():
    return Program((
        GroupByStep(keys=(), aggs=(
            AggSpec(Agg.SUM, "val", "s"),
            AggSpec(Agg.COUNT_ALL, None, "n"),
        )),
    ))


def _sum_n(shard, snap=None):
    out = shard.scan(_agg_prog(), snap)
    return int(out.cols["s"][0][0]), int(out.cols["n"][0][0])


def test_eager_promotion_at_commit():
    resident_mod.RESIDENT_FORCE = True
    shard = _shard()
    shard.commit([_write(shard, list(range(100)))])
    shard.resident.drain()
    snap = shard.resident.snapshot()
    assert snap["portions"] == 1 and snap["promotions"] == 1
    assert snap["bytes"] > 0
    # the FIRST scan is already served from the resident tier
    assert _sum_n(shard) == (sum(range(100)), 100)
    assert shard.resident.hits >= 1 and shard.resident.misses == 0


def test_heat_driven_promotion():
    # commit while the tier is off: nothing promoted eagerly
    resident_mod.RESIDENT_FORCE = False
    shard = _shard()
    shard.commit([_write(shard, list(range(50)))])
    resident_mod.RESIDENT_FORCE = True
    assert shard.resident.snapshot()["portions"] == 0
    # first host-path scan: heat 1, below threshold
    assert _sum_n(shard) == (sum(range(50)), 50)
    shard.resident.drain()
    assert shard.resident.snapshot()["portions"] == 0
    # second scan crosses PROMOTE_HEAT: async promotion via blob loader
    _sum_n(shard)
    shard.resident.drain()
    snap = shard.resident.snapshot()
    assert snap["portions"] == 1 and snap["promotions"] == 1
    hits0 = shard.resident.hits
    assert _sum_n(shard) == (sum(range(50)), 50)
    assert shard.resident.hits > hits0


def test_eviction_order_zskips_then_cold(monkeypatch):
    """Victims: zone-pruned-away portions first, then coldest by
    (heat, LRU tick) — and the budget bounds resident bytes."""
    store = ResidentStore("evict-test", budget=10 ** 9)
    a = np.arange(1000, dtype=np.int64)
    v = np.ones(1000, dtype=bool)
    for pid in (1, 2, 3):
        assert store.promote(pid, 1000, {"c": a}, {"c": v})
    per = store.snapshot()["bytes"] // 3
    # portion 2: zone maps keep pruning it away -> zero resident value
    store.note_pruned(2)
    # portion 1: hottest by access
    store.lookup(1, ("c",))
    store.lookup(1, ("c",))
    store.lookup(3, ("c",))
    # shrink the budget to fit two portions: 2 must go first
    store._budget = per * 2 + 1
    assert store.promote(9, 1000, {"c": a}, {"c": v}) or True
    with store._lock:
        assert 2 not in store._info
    # shrink to one portion: of (1, 3, 9), the coldest goes; 1 stays
    store._budget = per + 1
    store.lookup(1, ("c",))  # force an over-budget evict pass
    with store._lock:
        store._evict_to_budget_locked(store._budget)
        assert 1 in store._info
        assert store._nbytes <= per + 1
    assert store.snapshot()["evictions"] >= 2
    # a portion larger than the whole valve spills, never pins
    store._budget = 10
    assert not store.promote(7, 1000, {"c": a}, {"c": v})
    assert store.snapshot()["spills"] == 1


def test_budget_env_valve(monkeypatch):
    resident_mod.RESIDENT_FORCE = True
    shard = _shard()
    monkeypatch.setenv("YDB_TPU_RESIDENT_BYTES", "0")
    assert not shard.resident.enabled()
    monkeypatch.setenv("YDB_TPU_RESIDENT_BYTES", "1048576")
    assert shard.resident.enabled()
    assert shard.resident.budget() == 1048576
    monkeypatch.setenv("YDB_TPU_RESIDENT_BYTES", "junk")
    assert not shard.resident.enabled()


def test_invalidation_across_compaction_and_gc():
    resident_mod.RESIDENT_FORCE = True
    shard = _shard(compact_portion_threshold=10 ** 9)
    shard.commit([_write(shard, [1, 2, 3], vals=[10, 20, 30])])
    shard.commit([_write(shard, [4], vals=[40])])
    shard.resident.drain()
    assert shard.resident.snapshot()["portions"] == 2
    old_pids = {m.portion_id for m in shard.visible_portions()}
    shard.compact()
    shard.resident.drain()  # compaction output promotes eagerly
    # old portions still resident: old-snapshot readers keep hitting
    # them until GC proves no snapshot can name them
    assert shard.resident.snapshot()["portions"] == 3
    shard.gc_blobs(keep_snap=shard.snap)
    with shard.resident._lock:
        assert not (old_pids & set(shard.resident._info))
    assert shard.resident.snapshot()["invalidations"] >= 2
    # post-GC scans serve the new portion, correct rows
    assert _sum_n(shard) == (100, 4)


def test_no_stale_reads_after_ttl():
    resident_mod.RESIDENT_FORCE = True
    shard = _shard(compact_portion_threshold=10 ** 9)
    shard.commit([_write(shard, [1, 2], ts=[10, 10], vals=[5, 5])])
    shard.commit([_write(shard, [3, 4], ts=[999, 999], vals=[7, 7])])
    shard.resident.drain()
    assert _sum_n(shard) == (24, 4)
    shard.evict_ttl(cutoff=100)
    # resident arrays of the expired portion must not leak into reads
    assert _sum_n(shard) == (14, 2)
    shard.gc_blobs(keep_snap=shard.snap)
    assert _sum_n(shard) == (14, 2)


def test_mid_stream_resident_host_fallback_equality():
    """Some portions resident, some not: the mixed stream must produce
    exactly the all-host results (row order included)."""
    resident_mod.RESIDENT_FORCE = True
    shard = _shard()
    shard.commit([_write(shard, list(range(0, 300)))])      # promoted
    shard.resident.drain()
    resident_mod.RESIDENT_FORCE = False
    shard.commit([_write(shard, list(range(300, 500)))])    # host-only
    shard.commit([_write(shard, list(range(500, 900)))])    # host-only
    resident_mod.RESIDENT_FORCE = True
    shard.commit([_write(shard, list(range(900, 1000)))])   # promoted
    shard.resident.drain()
    assert shard.resident.snapshot()["portions"] == 2
    prog = Program((
        FilterStep(Call(Op.GE, Col("val"), lit(100))),
        GroupByStep(keys=(), aggs=(
            AggSpec(Agg.SUM, "val", "s"),
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.MIN, "id", "lo"),
            AggSpec(Agg.MAX, "id", "hi"),
        )),
    ))
    hits0 = shard.resident.hits
    on = shard.scan(prog)
    assert shard.resident.hits > hits0
    resident_mod.RESIDENT_FORCE = False
    off = shard.scan(prog)
    for name in on.cols:
        a, aok = (np.asarray(x) for x in on.cols[name])
        b, bok = (np.asarray(x) for x in off.cols[name])
        assert np.array_equal(aok, bok)
        assert np.array_equal(np.where(aok, a, 0), np.where(bok, b, 0))


def test_resident_off_bit_identity(monkeypatch):
    """YDB_TPU_RESIDENT=0 restores the pre-tier scan path exactly."""
    outs = {}
    for label, env in (("on", "1"), ("off", "0")):
        monkeypatch.setenv("YDB_TPU_RESIDENT", env)
        shard = _shard()
        shard.commit([_write(shard, list(range(500)))])
        shard.commit([_write(shard, list(range(500, 800)))])
        shard.resident.drain()
        assert shard.resident.enabled() == (env == "1")
        outs[label] = shard.scan(_agg_prog())
    for name in outs["on"].cols:
        a, aok = (np.asarray(x) for x in outs["on"].cols[name])
        b, bok = (np.asarray(x) for x in outs["off"].cols[name])
        assert np.array_equal(aok, bok)
        assert np.array_equal(np.where(aok, a, 0), np.where(bok, b, 0))


def test_upsert_merged_clusters_stay_on_host_path():
    """K-way dedup merges rewrite rows: those clusters must bypass the
    resident tier, and results must match the tier-off scan."""
    resident_mod.RESIDENT_FORCE = True
    shard = _shard(upsert=True)
    shard.commit([_write(shard, [1, 2, 3], vals=[10, 20, 30])])
    shard.commit([_write(shard, [2, 3, 4], vals=[21, 31, 41])])
    shard.resident.drain()
    on = _sum_n(shard)
    resident_mod.RESIDENT_FORCE = False
    assert _sum_n(shard) == on == (10 + 21 + 31 + 41, 4)


def test_resident_span_attribution():
    from ydb_tpu.obs import tracing
    from ydb_tpu.obs.tracing import Tracer

    resident_mod.RESIDENT_FORCE = True
    shard = _shard()
    shard.commit([_write(shard, list(range(100)))])
    shard.resident.drain()
    tr = Tracer()
    root = tr.trace("q")
    with tracing.activate(root):
        shard.scan(_agg_prog())
    root.finish()
    spans = [s for s in tr.spans_for(root.trace_id)
             if s.name == "shard.scan"]
    assert spans and spans[0].attrs["resident_portions"] == 1
    assert spans[0].attrs["resident_rows"] == 100


def test_sysview_and_viewer_surface():
    resident_mod.RESIDENT_FORCE = True
    from ydb_tpu.kqp.session import Cluster

    c = Cluster(n_shards=2)
    s = c.session()
    s.execute("create table t (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into t values (1, 10)")
    s.execute("insert into t values (2, 20)")
    for sh in c.tables["t"].shards:
        sh.resident.drain()
    r = s.execute("select shard, portions, bytes, promotions "
                  "from sys_resident_store order by shard")
    total = int(np.asarray(r.cols["portions"][0]).sum())
    assert total >= 1
    # aggregate counters ride the maintenance cadence
    c.run_background()
    enc = c.counters.encode_prometheus()
    assert "resident" in enc
    # viewer endpoint renders per-shard rows + totals
    import json as _json

    from ydb_tpu.obs.viewer import Viewer

    v = Viewer(c).start()
    try:
        body, ctype = v.render("/viewer/json/resident", {})
        payload = _json.loads(body)
        assert payload["total"]["portions"] >= 1
        assert ctype.startswith("application/json")
    finally:
        v.stop()


def test_concurrent_scans_during_promotion_tsan():
    """Scans racing heat-driven promotions and commits under the
    sanitizer: no lockset violations, every result exact."""
    with sanitizer.activate():
        resident_mod.RESIDENT_FORCE = True
        shard = _shard()
        shard.commit([_write(shard, list(range(200)))])
        want = (sum(range(200)), 200)
        errs: list = []
        stop = threading.Event()

        def scanner():
            try:
                while not stop.is_set():
                    if _sum_n(shard) != want:
                        errs.append("mismatch")
                        return
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=scanner) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            # churn: repeated invalidate + re-promotion under scans
            for _ in range(5):
                shard.resident.clear()
                _sum_n(shard)
                _sum_n(shard)
                shard.resident.drain()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errs
        assert shard.resident.snapshot()["portions"] >= 0


def test_blockcache_single_flight():
    """Two concurrent misses on one key: exactly one fill runs; the
    other serves the cached entry after waiting."""
    from ydb_tpu.engine.blockcache import DeviceBlockCache

    class _Col:
        data = np.zeros(64, dtype=np.int64)
        validity = np.ones(64, dtype=bool)

    class _Blk:
        columns = {"c": _Col()}

    cache = DeviceBlockCache(budget=1 << 20)
    fills = []
    gate = threading.Event()
    done: list = []

    def make_blocks():
        fills.append(1)
        gate.wait(10)
        return iter([_Blk()])

    def run():
        done.append(len(list(cache.stream(("k",), make_blocks))))

    threads = [threading.Thread(target=run) for _ in range(4)]
    for t in threads:
        t.start()
    # let every thread reach the flight gate, then release the filler
    import time as _time

    _time.sleep(0.1)
    gate.set()
    for t in threads:
        t.join(timeout=30)
    assert done == [1, 1, 1, 1]
    assert len(fills) == 1  # single flight: one decode for 4 scans
    assert cache.flight_waits >= 1
    assert cache.hits >= 3


def test_blockcache_flight_released_on_abandoned_stream():
    """A filler whose consumer abandons the stream mid-way must still
    release the flight so later scans are not wedged."""
    from ydb_tpu.engine.blockcache import DeviceBlockCache

    class _Col:
        data = np.zeros(8, dtype=np.int64)
        validity = np.ones(8, dtype=bool)

    class _Blk:
        columns = {"c": _Col()}

    cache = DeviceBlockCache(budget=1 << 20)
    g = cache.stream(("k",), lambda: iter([_Blk(), _Blk()]))
    next(g)
    g.close()  # abandon mid-stream
    with cache._lock:
        assert ("k",) not in cache._flights
    # the next scan fills normally (no 30s wait)
    assert len(list(cache.stream(("k",), lambda: iter([_Blk()])))) == 1


def test_bounded_under_sustained_ingest_and_scan(monkeypatch):
    """Sustained ingest+scan stress: resident bytes never exceed the
    valve; spills/evictions absorb the pressure."""
    resident_mod.RESIDENT_FORCE = True
    monkeypatch.setenv("YDB_TPU_RESIDENT_BYTES", str(64 << 10))
    shard = _shard()
    total = 0
    for i in range(12):
        ids = list(range(i * 500, (i + 1) * 500))
        shard.commit([_write(shard, ids)])
        total += len(ids)
        _sum_n(shard)
        shard.resident.drain()
        assert shard.resident.nbytes <= 64 << 10
    snap = shard.resident.snapshot()
    assert snap["evictions"] + snap["spills"] > 0
    assert _sum_n(shard)[1] == total
