"""Join kernels + multi-table plan execution (Q3/Q5), cross-checked
against independent python-dict reference joins."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks import TableBlock
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.ssa import join as jk
from ydb_tpu.ssa import kernels
from ydb_tpu.workload import tpch


def _block(**cols):
    sch = []
    arrays = {}
    validity = {}
    for name, spec in cols.items():
        arr, t = spec[0], spec[1]
        sch.append((name, t))
        arrays[name] = np.asarray(arr)
        if len(spec) > 2:
            validity[name] = np.asarray(spec[2])
    return TableBlock.from_numpy(arrays, dtypes.schema(*sch), validity or None)


def test_lookup_join_inner_left_semi_anti():
    probe = _block(
        k=([1, 2, 3, 2, 9], dtypes.INT64),
        pv=([10, 20, 30, 21, 90], dtypes.INT64),
    )
    build = _block(
        bk=([2, 3, 4], dtypes.INT64),
        bv=([200, 300, 400], dtypes.INT64),
    )
    joined, found = jk.lookup_join(probe, build, ["k"], ["bk"], ["bv"])
    inner = kernels.compact(joined, found)
    res = TableBlock.to_numpy(inner)
    np.testing.assert_array_equal(res["k"], [2, 3, 2])
    np.testing.assert_array_equal(res["bv"], [200, 300, 200])

    # left: unmatched rows keep NULL payload
    lres = joined.validity_numpy()
    assert lres["bv"].tolist() == [False, True, True, True, False]

    semi = kernels.compact(probe, found)
    assert TableBlock.to_numpy(semi)["k"].tolist() == [2, 3, 2]
    anti = kernels.compact(probe, ~found & probe.row_mask())
    assert TableBlock.to_numpy(anti)["k"].tolist() == [1, 9]


def test_lookup_join_null_keys_never_match():
    probe = _block(k=([1, 1], dtypes.INT64, [True, False]))
    build = _block(bk=([1], dtypes.INT64), bv=([5], dtypes.INT64))
    _, found = jk.lookup_join(probe, build, ["k"], ["bk"], ["bv"])
    assert np.asarray(found)[:2].tolist() == [True, False]


def test_two_column_key_packing():
    probe = _block(
        a=([1, 1, 2], dtypes.INT64),
        b=([7, 8, 7], dtypes.INT64),
    )
    build = _block(
        x=([1, 2], dtypes.INT64),
        y=([7, 7], dtypes.INT64),
        v=([100, 200], dtypes.INT64),
    )
    _, found = jk.lookup_join(probe, build, ["a", "b"], ["x", "y"], ["v"])
    assert np.asarray(found)[:3].tolist() == [True, False, True]


def test_expand_join_n_to_m():
    probe = _block(k=([1, 2, 3], dtypes.INT64), p=([10, 20, 30], dtypes.INT64))
    build = _block(k2=([2, 2, 1, 5], dtypes.INT64),
                   q=([201, 202, 101, 501], dtypes.INT64))
    out, total = jk.expand_join(
        probe, build, ["k"], ["k2"], ["k", "p"], ["q"], out_capacity=16
    )
    assert int(total) == 3
    res = TableBlock.to_numpy(out)
    got = sorted(zip(res["k"].tolist(), res["q"].tolist()))
    assert got == [(1, 101), (2, 201), (2, 202)]


def test_expand_join_overflow_reports_total():
    probe = _block(k=([7] * 4, dtypes.INT64))
    build = _block(k2=([7] * 4, dtypes.INT64), q=(list(range(4)), dtypes.INT64))
    out, total = jk.expand_join(
        probe, build, ["k"], ["k2"], ["k"], ["q"], out_capacity=8
    )
    assert int(total) == 16  # 4x4 cross on same key; caller must retry
    assert int(out.length) == 8


# ---------------- reference joins for Q3/Q5 ----------------


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.01, seed=23)


@pytest.fixture(scope="module")
def db(data):
    return Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


def _ref_q3(data):
    t = data.tables
    d = tpch._days("1995-03-15")
    seg = data.dicts["c_mktsegment"].eq_id(b"BUILDING")
    cust = set(t["customer"]["c_custkey"][
        t["customer"]["c_mktsegment"] == seg].tolist())
    omask = (t["orders"]["o_orderdate"] < d) & np.isin(
        t["orders"]["o_custkey"], list(cust))
    orders = {
        k: (dt, sp)
        for k, dt, sp in zip(
            t["orders"]["o_orderkey"][omask],
            t["orders"]["o_orderdate"][omask],
            t["orders"]["o_shippriority"][omask],
        )
    }
    li = t["lineitem"]
    lmask = li["l_shipdate"] > d
    agg = {}
    for ok, price, disc in zip(
        li["l_orderkey"][lmask], li["l_extendedprice"][lmask],
        li["l_discount"][lmask],
    ):
        if int(ok) in orders:
            dt, sp = orders[int(ok)]
            key = (int(ok), int(dt), int(sp))
            agg[key] = agg.get(key, 0) + int(price) * (100 - int(disc))
    rows = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0][1], kv[0][0]))[:10]
    return rows


def test_q3_matches_reference(db, data):
    out = to_host(execute_plan(tpch.q3_plan(), db))
    ref = _ref_q3(data)
    assert out.num_rows == len(ref)
    for i, ((ok, dt, sp), rev) in enumerate(ref):
        assert int(out.cols["l_orderkey"][0][i]) == ok
        assert int(out.cols["o_orderdate"][0][i]) == dt
        assert int(out.cols["revenue"][0][i]) == rev


def _ref_q5(data):
    t = data.tables
    d0, d1 = tpch._days("1994-01-01"), tpch._days("1995-01-01")
    asia = data.dicts["r_name"].eq_id(b"ASIA")
    rk = set(t["region"]["r_regionkey"][
        t["region"]["r_name"] == asia].tolist())
    nations = {
        int(nk): int(nm)
        for nk, nrk, nm in zip(
            t["nation"]["n_nationkey"], t["nation"]["n_regionkey"],
            t["nation"]["n_name"])
        if int(nrk) in rk
    }
    omask = (t["orders"]["o_orderdate"] >= d0) & (
        t["orders"]["o_orderdate"] < d1)
    orders = dict(zip(
        t["orders"]["o_orderkey"][omask].tolist(),
        t["orders"]["o_custkey"][omask].tolist(),
    ))
    supp = dict(zip(t["supplier"]["s_suppkey"].tolist(),
                    t["supplier"]["s_nationkey"].tolist()))
    cust = dict(zip(t["customer"]["c_custkey"].tolist(),
                    t["customer"]["c_nationkey"].tolist()))
    li = t["lineitem"]
    agg = {}
    for ok, sk, price, disc in zip(
        li["l_orderkey"].tolist(), li["l_suppkey"].tolist(),
        li["l_extendedprice"].tolist(), li["l_discount"].tolist(),
    ):
        ck = orders.get(ok)
        if ck is None:
            continue
        sn = supp[sk]
        if sn not in nations or cust[ck] != sn:
            continue
        agg[sn] = agg.get(sn, 0) + price * (100 - disc)
    return sorted(
        ((nations[sn], rev) for sn, rev in agg.items()),
        key=lambda kv: -kv[1],
    )


def test_q5_matches_reference(db, data):
    out = to_host(execute_plan(tpch.q5_plan(), db))
    ref = _ref_q5(data)
    assert out.num_rows == len(ref)
    np.testing.assert_array_equal(
        out.cols["revenue"][0], [rev for _, rev in ref]
    )
    np.testing.assert_array_equal(
        out.cols["n_name"][0], [nm for nm, _ in ref]
    )


def test_lookup_join_int64_max_key_matches():
    """No value sentinel: INT64_MAX is a legitimate joinable key."""
    big = np.iinfo(np.int64).max
    probe = _block(k=([big, 5], dtypes.INT64))
    build = _block(bk=([big], dtypes.INT64), bv=([1], dtypes.INT64))
    _, found = jk.lookup_join(probe, build, ["k"], ["bk"], ["bv"])
    assert np.asarray(found)[:2].tolist() == [True, False]
    out, total = jk.expand_join(
        probe, build, ["k"], ["bk"], ["k"], ["bv"], out_capacity=8
    )
    assert int(total) == 1
    assert TableBlock.to_numpy(out)["k"].tolist() == [big]
