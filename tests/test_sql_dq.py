"""SQL through the DQ stage graph: planned SELECTs lower to scan ->
hash-partition channels -> grace-bucket join stages -> final aggregate,
executed by the credit-flow compute actors on the simulated multi-node
runtime — and match the single-chip executor (VERDICT r4 item 6)."""

import numpy as np
import pytest

from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.kqp.dq_lower import (
    execute_plan_dq,
    partition_source,
    plan_to_stages,
)
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.runtime.test_runtime import SimRuntime
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

N_TASKS = 3


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.004, seed=17)


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def single_db(data):
    return Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def dq_sources(data):
    return {
        t: partition_source(
            ColumnSource(cols, data.schema(t), data.dicts), N_TASKS)
        for t, cols in data.tables.items()
    }


def _run_both(name, catalog, single_db, dq_sources, data):
    plan = plan_select_full(parse(TPCH[name]), catalog).plan
    ref = to_host(execute_plan(plan, single_db))
    rt = SimRuntime(n_nodes=2)
    res = execute_plan_dq(plan, dq_sources, rt, dicts=data.dicts,
                          n_tasks=N_TASKS, block_rows=1 << 12)
    return res, ref


def _match(res, ref, cols):
    assert res.num_rows == ref.num_rows
    for c in cols:
        np.testing.assert_array_equal(
            np.asarray(res.cols[c][0]), np.asarray(ref.cols[c][0]),
            err_msg=c)


def test_q1_through_dq(data, catalog, single_db, dq_sources):
    res, ref = _run_both("q1", catalog, single_db, dq_sources, data)
    _match(res, ref, ("l_returnflag", "l_linestatus", "sum_qty",
                      "sum_charge", "count_order"))


def test_q3_join_through_dq(data, catalog, single_db, dq_sources):
    res, ref = _run_both("q3", catalog, single_db, dq_sources, data)
    _match(res, ref, ("l_orderkey", "revenue", "o_orderdate",
                      "o_shippriority"))


def test_q5_join_chain_through_dq(data, catalog, single_db, dq_sources):
    res, ref = _run_both("q5", catalog, single_db, dq_sources, data)
    _match(res, ref, ("n_name", "revenue"))


def test_q12_case_agg_through_dq(data, catalog, single_db, dq_sources):
    res, ref = _run_both("q12", catalog, single_db, dq_sources, data)
    _match(res, ref, ("l_shipmode", "high_line_count", "low_line_count"))


def test_orderby_no_groupby_through_dq(data, catalog, single_db,
                                       dq_sources):
    """A group-less ORDER BY (and its LIMIT top-k) must apply ONCE over
    the merged inputs, not per block — the per-block sort + arrival-order
    concat regression (SortStep split in kqp/dq_lower._split_at_sort)."""
    sql = ("SELECT l.l_orderkey AS k, l.l_extendedprice AS p "
           "FROM lineitem l JOIN orders o ON l.l_orderkey = o.o_orderkey "
           "ORDER BY p DESC, k LIMIT 50")
    plan = plan_select_full(parse(sql), catalog).plan
    ref = to_host(execute_plan(plan, single_db, use_dq=False))
    rt = SimRuntime(n_nodes=2)
    res = execute_plan_dq(plan, dq_sources, rt, dicts=data.dicts,
                          n_tasks=N_TASKS, block_rows=1 << 10)
    _match(res, ref, ("k", "p"))


def test_default_executor_routes_joins_to_dq(catalog, single_db):
    """execute_plan (the production entry) runs join plans on the DQ
    stage graph by default; YDB_TPU_DQ=0 (use_dq=False) is the only way
    back to the recursive walk."""
    from ydb_tpu.plan import executor as ex

    plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
    called = []
    orig = ex._execute_plan_dq
    ex._execute_plan_dq = lambda p, d: (called.append(1), orig(p, d))[1]
    try:
        out = to_host(execute_plan(plan, single_db))
    finally:
        ex._execute_plan_dq = orig
    assert called, "join plan bypassed the DQ executor"
    ref = to_host(execute_plan(plan, single_db, use_dq=False))
    _match(out, ref, ("l_orderkey", "revenue"))


def test_stage_graph_shape(catalog):
    """q3 lowers to scan stages -> hash-partitioned join stages -> one
    result transform; joins never get a whole-table UnionAll input."""
    from ydb_tpu.dq.graph import HashPartition, ResultOutput

    plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
    stages = plan_to_stages(plan, n_tasks=4)
    joins = [s for s in stages if s.join is not None]
    assert len(joins) >= 2
    for s in joins:
        assert s.tasks == 4
        for inp in s.inputs:
            up = stages[inp.from_stage]
            assert isinstance(up.output, HashPartition)
    assert isinstance(stages[-1].output, ResultOutput)
    assert stages[-1].tasks == 1
