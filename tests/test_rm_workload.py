"""Resource manager + workload-service admission tests (reference:
ydb/core/kqp/rm_service/kqp_rm_service.h:82,
kqp_workload_service.cpp:37)."""

import threading

import pytest

from ydb_tpu.kqp.rm import (
    PoolOverloaded,
    ResourceExhausted,
    ResourceManager,
    WorkloadService,
)


def test_rm_budgets_and_release():
    rm = ResourceManager(memory_bytes=1000, compute_slots=2)
    rm.acquire("q1", memory=600, slots=1)
    rm.acquire("q2", memory=300, slots=1)
    with pytest.raises(ResourceExhausted, match="memory"):
        rm.acquire("q3", memory=200, slots=0)
    with pytest.raises(ResourceExhausted, match="slots"):
        rm.acquire("q4", memory=0, slots=1)
    snap = rm.snapshot()
    assert snap["memory_used"] == 900 and snap["slots_used"] == 2
    rm.release("q1")
    rm.acquire("q3", memory=200, slots=1)
    # re-acquire for the same query replaces, not adds
    rm.acquire("q3", memory=700, slots=1)
    assert rm.snapshot()["memory_used"] == 1000


def test_workload_admission_queue_fifo():
    ws = WorkloadService()
    ws.configure("etl", concurrent_limit=1, queue_size=2)
    assert ws.admit("a", "etl")
    assert not ws.admit("b", "etl")
    assert not ws.admit("c", "etl")
    with pytest.raises(PoolOverloaded):
        ws.admit("d", "etl")
    assert not ws.poll("c", "etl")  # b is ahead
    ws.finish("a", "etl")
    assert not ws.poll("c", "etl")  # still b's turn
    assert ws.poll("b", "etl")
    ws.finish("b", "etl")
    assert ws.poll("c", "etl")
    st = ws.stats("etl")
    assert st["admitted"] == 3 and st["rejected"] == 1


def test_workload_cancel_while_queued():
    ws = WorkloadService()
    ws.configure("p", concurrent_limit=1, queue_size=4)
    ws.admit("a", "p")
    ws.admit("b", "p")
    ws.admit("c", "p")
    ws.finish("b", "p")  # cancel in queue
    ws.finish("a", "p")
    assert ws.poll("c", "p")  # c skips the cancelled b


def test_rm_exhaustion_waits_instead_of_failing():
    """Pool-admitted queries wait for a compute slot rather than
    surfacing ResourceExhausted (code-review regression)."""
    import time

    from ydb_tpu.kqp.session import Cluster

    cluster = Cluster()
    cluster.rm = ResourceManager(compute_slots=1)
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    cluster.rm.acquire("hog", slots=1)  # external holder

    def free_later():
        time.sleep(0.2)
        cluster.rm.release("hog")

    t = threading.Thread(target=free_later)
    t.start()
    out = s.execute("SELECT count(*) AS c FROM t")  # waits ~200ms
    t.join()
    assert int(out.column("c")[0]) == 0
    assert cluster.rm.snapshot()["slots_used"] == 0


def test_session_admission_end_to_end():
    from ydb_tpu.kqp.session import Cluster

    cluster = Cluster()
    cluster.workload = WorkloadService()
    cluster.workload.configure("default", concurrent_limit=1,
                               queue_size=8)
    cluster.rm = ResourceManager(compute_slots=4)
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1), (2)")
    out = s.execute("SELECT count(*) AS c FROM t")
    assert int(out.column("c")[0]) == 2
    # all grants returned after each statement
    assert cluster.rm.snapshot()["slots_used"] == 0
    assert cluster.workload.stats()["running"] == 0
    assert cluster.workload.stats()["admitted"] >= 3

    # two threads through a 1-wide pool: both finish (queue turn-taking)
    results = []

    def run(i):
        sess = cluster.session()
        out = sess.execute("SELECT count(*) AS c FROM t")
        results.append(int(out.column("c")[0]))

    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert results == [2, 2]
