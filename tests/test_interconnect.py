"""Cross-PROCESS actor transport: two real OS processes, TCP sessions,
handshake, undelivered notifications, and tablet-style failover — the
actor system's node boundary stops being a simulation (VERDICT r4 item
7; reference interconnect_tcp_proxy.h:20)."""

import os
import signal
import subprocess
import sys
import time

import pytest

from ydb_tpu.runtime.actors import Actor, ActorId, ActorSystem
from ydb_tpu.runtime.interconnect import Interconnect, Undelivered

CHILD = r"""
import sys
from ydb_tpu.engine.blobs import DirBlobStore
from ydb_tpu.runtime.actors import Actor, ActorSystem
from ydb_tpu.runtime.interconnect import Interconnect

store_dir, port_file = sys.argv[1], sys.argv[2]


class CounterTablet(Actor):
    '''Minimal persistent tablet: WAL-append each increment, replay on
    boot — killing the process loses nothing.'''

    def __init__(self, store):
        super().__init__()
        self.store = store
        self.n = 0
        self.seq = 0
        for bid in store.list("wal/"):
            self.n += 1
            self.seq += 1

    def receive(self, message, sender):
        if message == ("inc",):
            self.seq += 1
            self.store.put(f"wal/{self.seq:08d}", b"+1")
            self.n += 1
            self.send(sender, ("ack", self.n))
        elif message == ("get",):
            self.send(sender, ("val", self.n))


system = ActorSystem(node=2)
tablet = CounterTablet(DirBlobStore(store_dir))
system.register(tablet)  # ActorId(2, 1)
ic = Interconnect(system, listen_port=0)
with open(port_file + ".tmp", "w") as f:
    f.write(str(ic.port))
import os
os.replace(port_file + ".tmp", port_file)
ic.serve()
"""


def _spawn_child(store_dir, port_file):
    env = dict(os.environ, PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-c", CHILD, str(store_dir), str(port_file)],
        env=env,
    )
    deadline = time.monotonic() + 30
    while not os.path.exists(port_file):
        if proc.poll() is not None:
            raise RuntimeError("child died during startup")
        if time.monotonic() > deadline:
            proc.kill()
            raise TimeoutError("child did not report a port")
        time.sleep(0.02)
    with open(port_file) as f:
        return proc, int(f.read())


class Client(Actor):
    def __init__(self):
        super().__init__()
        self.got = []

    def receive(self, message, sender):
        self.got.append(message)


def _pump_until(ic, cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ic.pump(0.05)
        if cond():
            return
    raise TimeoutError("condition not reached")


def test_two_process_transport_and_failover(tmp_path):
    store_dir = tmp_path / "tablet_store"
    system = ActorSystem(node=1)
    client = Client()
    client_id = system.register(client)
    ic = Interconnect(system, listen_port=0)
    tablet_id = ActorId(2, 1)

    proc1, port1 = _spawn_child(store_dir, str(tmp_path / "p1.port"))
    try:
        ic.add_peer(2, "127.0.0.1", port1)

        # three increments over the wire, acked over the wire back
        for _ in range(3):
            system.send(tablet_id, ("inc",), sender=client_id)
        _pump_until(ic, lambda: len(client.got) >= 3)
        assert client.got[-1] == ("ack", 3)

        # hard-kill the node: in-flight peer session dies
        proc1.kill()
        proc1.wait(timeout=10)

        # sends now produce Undelivered notifications (pipes would
        # retry). The FIRST send after a kill can still succeed locally
        # (TCP buffers it; the RST arrives later), so keep sending until
        # the session observes the dead peer.
        client.got.clear()
        deadline = time.monotonic() + 15
        while not any(isinstance(m, Undelivered) for m in client.got):
            if time.monotonic() > deadline:
                raise TimeoutError("no Undelivered after peer death")
            system.send(tablet_id, ("get",), sender=client_id)
            ic.pump(0.05)

        # failover: a NEW process boots the tablet from the same store
        # (WAL replay) on a new port; the proxy re-establishes a session
        proc2, port2 = _spawn_child(store_dir, str(tmp_path / "p2.port"))
        try:
            ic.add_peer(2, "127.0.0.1", port2)
            client.got.clear()
            system.send(tablet_id, ("get",), sender=client_id)
            _pump_until(
                ic, lambda: ("val", 3) in client.got)
        finally:
            proc2.kill()
            proc2.wait(timeout=10)
    finally:
        if proc1.poll() is None:
            proc1.kill()
        ic.close()


def test_unknown_peer_is_undelivered():
    system = ActorSystem(node=1)
    client = Client()
    cid = system.register(client)
    ic = Interconnect(system, listen_port=None)
    try:
        system.send(ActorId(9, 1), "hello", sender=cid)
        system.run()
        assert any(isinstance(m, Undelivered) for m in client.got)
    finally:
        ic.close()


def test_handshake_version_gate():
    """An incompatible peer is refused AT HANDSHAKE with an explicit
    reason (the interconnect_handshake.cpp version gate, VERDICT r4
    weak 7): the listener rejects a mismatched hello, and a client
    whose handshake is rejected surfaces Undelivered to the sender
    instead of failing cryptically mid-stream."""
    import socket
    import threading

    from ydb_tpu.runtime.interconnect import (
        Undelivered,
        _recv_frame,
        _send_frame,
    )

    # server side: a version-99 hello gets an explicit reject frame
    sys_a = ActorSystem(node=1)
    ic_a = Interconnect(sys_a, listen_port=0)
    try:
        s = socket.create_connection(("127.0.0.1", ic_a.port),
                                     timeout=5)
        _send_frame(s, ("hello", 2, 1, None, 99))
        resp = _recv_frame(s)
        s.close()
        assert resp[0] == "reject" and "protocol version" in resp[1]
    finally:
        ic_a.close()

    # client side: a rejecting peer turns the envelope into Undelivered
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def fake_peer():
        conn, _ = srv.accept()
        _recv_frame(conn)  # the hello
        _send_frame(conn, ("reject", "protocol version 1 != 2"))
        conn.close()

    t = threading.Thread(target=fake_peer, daemon=True)
    t.start()
    sys_b = ActorSystem(node=2)
    ic_b = Interconnect(sys_b, listen_port=0, max_retries=0)
    try:
        ic_b.add_peer(1, "127.0.0.1", srv.getsockname()[1])

        class Probe(Actor):
            def __init__(self):
                super().__init__()
                self.got = []

            def receive(self, message, sender):
                self.got.append(message)

        probe = Probe()
        pid = sys_b.register(probe)
        sys_b.send(ActorId(1, 7), ("ping",), sender=pid)
        deadline = time.monotonic() + 10
        while not probe.got and time.monotonic() < deadline:
            ic_b.pump(0.05)
        assert probe.got and isinstance(probe.got[0], Undelivered)
        assert "protocol version" in probe.got[0].reason
    finally:
        ic_b.close()
        srv.close()
