"""Serializability checking under interleaved transactions + chaos
(SURVEY §4 tier 4: the reference validates isolation with a
serializability checker over concurrent histories,
tests/library/serializability, and drives chaos with nemesis restarts;
tier 2's deterministic interleaving is the scheduling discipline).

The lost-update probe interleaves optimistic read-modify-write
transactions at the PROTOCOL level (lock -> snapshot read -> 2PC
commit), with a seeded scheduler choosing which transaction advances
each step — real interleavings, deterministic replay. A regression
that stops breaking optimistic locks on conflict shows up as a lost
update (final counters < committed increments)."""

import random

from ydb_tpu import dtypes
from ydb_tpu.datashard.shard import DataShard, RowOp
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster


def test_interleaved_rmw_serializes_no_lost_updates():
    """Workers interleave mid-transaction (between snapshot read and
    commit): conflicting commits MUST break the reader's optimistic
    lock, forcing a retry — every committed increment lands."""
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE counters (id int64, v int64, "
              "PRIMARY KEY (id)) WITH (store = row, shards = 2)")
    s.execute("INSERT INTO counters VALUES (0, 0), (1, 0)")
    table = cluster.tables["counters"]
    rng = random.Random(17)

    class Worker:
        def __init__(self, wid):
            self.rng = random.Random(wid)
            self.committed = [0, 0]
            self.remaining = 12
            self.state = "idle"

        def step(self):
            if self.remaining == 0:
                return False
            if self.state == "idle":
                self.key = self.rng.randrange(2)
                self.locks = table.lock_all_shards()
                snap = cluster.coordinator.read_snapshot()
                row = table.read_row((self.key,), snap)
                self.new_v = row["v"] + 1
                self.state = "read"  # <- interleave point
            else:
                res = table._commit_ops(
                    [RowOp((self.key,),
                           {"id": self.key, "v": self.new_v})],
                    lock_ids=self.locks)
                table.release_locks(self.locks)
                if res.committed:
                    self.committed[self.key] += 1
                    self.remaining -= 1
                # conflict -> retry the whole transaction
                self.state = "idle"
            return True

    workers = [Worker(i) for i in range(4)]
    live = list(workers)
    conflicts_possible = 0
    while live:
        w = rng.choice(live)
        in_read = sum(1 for x in workers if x.state == "read")
        if in_read > 1:
            conflicts_possible += 1
        if not w.step():
            live.remove(w)
    # the schedule really interleaved transactions
    assert conflicts_possible > 0

    out = s.execute("SELECT id, v FROM counters ORDER BY id")
    got = [int(x) for x in out.column("v")]
    want = [sum(w.committed[k] for w in workers) for k in (0, 1)]
    assert got == want, (got, want)
    assert sum(want) == 4 * 12


def test_snapshot_reads_are_stable_under_writes():
    """A reader pinned to a snapshot must see the same rows no matter
    how many commits land after it (repeatable read, the history
    property the checker validates per-read)."""
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = row, shards = 2)")
    s.execute("INSERT INTO t VALUES (1, 10), (2, 20)")
    table = cluster.tables["t"]
    snap = cluster.coordinator.read_snapshot()
    before = {k: dict(r) for k, r in sorted(
        table.read_rows([(1,), (2,)], snap).items())}
    assert before == {(1,): {"id": 1, "v": 10},
                      (2,): {"id": 2, "v": 20}}  # non-vacuous base
    for i in range(5):
        s.execute("UPDATE t SET v = v + 100 WHERE id = 1")
        s.execute(f"INSERT INTO t VALUES ({10 + i}, {i})")
    after = {k: dict(r) for k, r in sorted(
        table.read_rows([(1,), (2,)], snap).items())}
    assert before == after
    now = s.execute("SELECT v FROM t WHERE id = 1")
    assert int(now.column("v")[0]) == 510


def test_chaos_reboot_mid_workload_loses_nothing():
    """Nemesis-style restart: shards reboot from storage between
    batches of committed writes; every committed row must survive,
    uncommitted volatile state must not resurrect."""
    store = MemBlobStore()
    schema = dtypes.schema(("id", dtypes.INT64, False),
                           ("v", dtypes.INT64, True))
    rng = random.Random(5)
    committed = {}
    shard = DataShard("c0", schema, store, ("id",))
    step = 0
    for round_no in range(6):
        for _ in range(20):
            k = rng.randrange(50)
            v = rng.randrange(1_000_000)
            wid = shard.propose([RowOp((k,), {"id": k, "v": v})])
            shard.prepare([wid])
            step += 1
            shard.commit_at([wid], step)
            committed[k] = v
        # stage-but-crash: an undecided volatile tx must evaporate
        wid = shard.propose([RowOp((999,), {"id": 999, "v": 1})])
        assert shard.apply_volatile([wid], txid=1000 + round_no,
                                    step=step + 1, expected_peers=[1])
        shard = DataShard("c0", schema, store, ("id",))  # nemesis
    rows = {k[0]: r["v"] for page in shard.read(step + 10)
            for k, r in page}
    assert 999 not in rows  # the undecided volatile write evaporated
    assert rows == committed
