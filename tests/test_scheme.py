"""SchemeShard + scheme board + DDL tests (SURVEY.md §2.5).

Covers: path-tree DDL operations persisted through the tablet executor
(reboot-safe), scheme board pub/sub propagation to per-node caches, and
the SQL DDL surface (CREATE/ALTER/DROP TABLE) end to end including full
cluster reboot from the blob store."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.runtime.test_runtime import SimRuntime
from ydb_tpu.scheme.board import SchemeBoardReplica, SchemeCache
from ydb_tpu.scheme.model import TableDescription
from ydb_tpu.scheme.shard import SchemeError, SchemeShardCore
from ydb_tpu.sql.planner import PlanError
from ydb_tpu.tablet.executor import TabletExecutor


def _core(store=None):
    store = store or MemBlobStore()
    return SchemeShardCore(TabletExecutor.boot("schemeshard", store)), store


def _desc(path, n_shards=2):
    return TableDescription(
        path=path,
        schema=dtypes.schema(("id", dtypes.INT64), ("v", dtypes.STRING)),
        primary_key=("id",),
        n_shards=n_shards,
    )


def test_scheme_path_tree_and_table_lifecycle():
    core, store = _core()
    core.mkdir("/app")
    core.create_table(_desc("/app/events"))
    assert core.kind("/app") == "dir"
    assert core.kind("/app/events") == "table"
    assert core.children("/") == ["/app"]
    assert core.children("/app") == ["/app/events"]
    d = core.describe("/app/events")
    assert d.primary_key == ("id",) and d.schema_version == 1

    with pytest.raises(SchemeError):
        core.create_table(_desc("/app/events"))      # exists
    with pytest.raises(SchemeError):
        core.create_table(_desc("/nodir/t"))          # no parent
    with pytest.raises(SchemeError):
        core.mkdir("/app/events/sub")                 # parent not a dir

    core.drop_table("/app/events")
    assert core.describe("/app/events") is None
    assert core.children("/app") == []
    ops = [o["kind"] for o in core.operations_log()]
    assert ops == ["mkdir", "create_table", "drop_table"]


def test_scheme_alter_versioning_and_rules():
    core, _ = _core()
    core.create_table(_desc("/t"))
    d = core.alter_table(
        "/t", add_columns=[dtypes.Field("extra", dtypes.DOUBLE, True)])
    assert d.schema_version == 2 and "extra" in d.schema
    with pytest.raises(SchemeError):
        core.alter_table(
            "/t", add_columns=[dtypes.Field("x", dtypes.INT32, False)])
    with pytest.raises(SchemeError):
        core.alter_table("/t", drop_columns=["id"])   # key column
    d = core.alter_table("/t", drop_columns=["extra"])
    assert d.schema_version == 3 and "extra" not in d.schema


def test_scheme_survives_tablet_reboot():
    core, store = _core()
    core.mkdir("/a")
    core.create_table(_desc("/a/t1"))
    core.alter_table(
        "/a/t1", add_columns=[dtypes.Field("z", dtypes.INT32, True)])
    # tablet dies; new executor boots from the same store
    core2 = SchemeShardCore(TabletExecutor.boot("schemeshard", store))
    d = core2.describe("/a/t1")
    assert d is not None and "z" in d.schema and d.schema_version == 2
    assert core2.children("/") == ["/a"]


def test_scheme_board_propagation():
    rt = SimRuntime(n_nodes=3)
    replica = rt.system(1).register(SchemeBoardReplica())
    cache2 = SchemeCache(replica)
    cache3 = SchemeCache(replica)
    rt.system(2).register(cache2)
    rt.system(3).register(cache3)
    rt.dispatch()

    core, _ = _core()
    # populator edge: schemeshard listeners push into the board
    from ydb_tpu.scheme.board import BoardPublish

    core.listeners.append(
        lambda p, d, v: rt.system(1).send(replica, BoardPublish(p, d, v)))
    core.create_table(_desc("/t"))
    rt.dispatch()
    assert cache2.resolve("/t")["primary_key"] == ["id"]
    assert cache3.resolve("/t")["primary_key"] == ["id"]

    core.alter_table(
        "/t", add_columns=[dtypes.Field("w", dtypes.INT32, True)])
    rt.dispatch()
    assert any(c[0] == "w" for c in cache2.resolve("/t")["schema"])

    # late subscriber gets a snapshot
    cache_late = SchemeCache(replica)
    rt.system(2).register(cache_late)
    rt.dispatch()
    assert cache_late.resolve("/t") is not None

    core.drop_table("/t")
    rt.dispatch()
    assert cache2.resolve("/t") is None
    assert cache_late.resolve("/t") is None


def test_sql_ddl_end_to_end():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, name string, v double, "
              "PRIMARY KEY (id)) WITH (shards = 2)")
    assert c.tables["t"].schema.names == ("id", "name", "v")
    assert len(c.tables["t"].shards) == 2
    s.execute("INSERT INTO t VALUES (1, 'a', 1.5), (2, 'b', 2.5)")

    s.execute("ALTER TABLE t ADD COLUMN w int32")
    out = s.execute("SELECT id, w FROM t ORDER BY id")
    assert list(out.column("id")) == [1, 2]
    assert not out.validity("w").any()     # pre-ALTER rows read as NULL

    s.execute("INSERT INTO t VALUES (3, 'c', 3.5, 30)")
    out = s.execute("SELECT id, w FROM t WHERE w IS NOT NULL")
    assert list(out.column("id")) == [3]
    assert list(out.column("w")) == [30]

    s.execute("ALTER TABLE t DROP COLUMN v")
    with pytest.raises(PlanError):
        s.execute("SELECT v FROM t")

    s.execute("DROP TABLE t")
    with pytest.raises(PlanError):
        s.execute("SELECT id FROM t")
    with pytest.raises(PlanError):
        s.execute("DROP TABLE t")


def test_cluster_reboots_from_store():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE users (id int64, city string, "
              "PRIMARY KEY (id)) WITH (shards = 3)")
    s.execute("INSERT INTO users VALUES (1, 'berlin'), (2, 'tokyo'), "
              "(3, 'berlin'), (4, 'lima')")

    # process dies; a new cluster boots from the same blob store
    c2 = Cluster(store=store)
    s2 = c2.session()
    out = s2.execute("SELECT city, count(*) AS n FROM users "
                     "GROUP BY city ORDER BY city")
    assert [v.decode() for v in out.strings("city")] == \
        ["berlin", "lima", "tokyo"]
    assert list(out.column("n")) == [2, 1, 1]

    # writes keep working after reboot (coordinator clock resumed)
    s2.execute("INSERT INTO users VALUES (5, 'tokyo')")
    out = s2.execute("SELECT count(*) AS n FROM users")
    assert list(out.column("n")) == [5]


# ---------- review regressions ----------

def test_drop_then_recreate_does_not_resurrect_rows():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (shards = 1)")
    s.execute("INSERT INTO t VALUES (1), (2), (3)")
    s.execute("DROP TABLE t")
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (shards = 1)")
    s.execute("INSERT INTO t VALUES (100)")
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT id FROM t ORDER BY id")
    assert list(out.column("id")) == [100]


def test_drop_add_same_column_reads_null():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v double, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1, 42.0)")
    s.execute("ALTER TABLE t DROP COLUMN v")
    s.execute("ALTER TABLE t ADD COLUMN v double")
    out = s.execute("SELECT id, v FROM t")
    assert not out.validity("v").any()
    # and survives a reboot (column_added restored from scheme)
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT id, v FROM t")
    assert not out.validity("v").any()


def test_dict_journal_is_durable_before_shard_wal():
    store = MemBlobStore()
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, v string, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1, 'hello')")
    # the dict blob must exist the moment any shard WAL references ids
    assert store.list("cluster/dicts/")
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT v FROM t")
    assert out.strings("v") == [b"hello"]


def test_with_option_validation():
    c = Cluster()
    s = c.session()
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
                  "WITH (shards = x)")
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
                  "WITH (sharsd = 2)")
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
                  "WITH (store = rows)")


def test_board_stale_update_cannot_resurrect_drop():
    from ydb_tpu.scheme.board import BoardPublish, SchemeBoardReplica

    rep = SchemeBoardReplica()
    rep.system = None
    sent = []
    rep.send = lambda t, m: sent.append(m)
    core, _ = _core()
    versions = []
    core.listeners.append(lambda p, d, v: versions.append((p, d, v)))
    core.create_table(_desc("/t"))
    core.alter_table(
        "/t", add_columns=[dtypes.Field("w", dtypes.INT32, True)])
    core.drop_table("/t")
    (p1, d1, v1), (p2, d2, v2), (p3, d3, v3) = versions
    assert v1 < v2 < v3 and d3 is None
    # deliver out of order: create, drop, then the STALE alter replay
    rep._apply(BoardPublish(p1, d1, v1))
    rep._apply(BoardPublish(p3, d3, v3))
    assert rep._apply(BoardPublish(p2, d2, v2)) is False
    assert rep.entries["/t"][0] is None   # still deleted
