"""Block model + Arrow bridge tests (mirror of formats/arrow ut coverage)."""

import numpy as np
import pyarrow as pa
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks import Dictionary, DictionarySet, TableBlock
from ydb_tpu.blocks.arrow_bridge import (
    block_to_record_batch,
    record_batch_to_block,
    schema_from_arrow,
)


def test_block_roundtrip_numpy():
    sch = dtypes.schema(("a", dtypes.INT32), ("b", dtypes.DOUBLE))
    blk = TableBlock.from_numpy(
        {"a": np.arange(10, dtype=np.int32), "b": np.linspace(0, 1, 10)}, sch
    )
    assert blk.capacity == 1024
    assert int(blk.length) == 10
    out = blk.to_numpy()
    np.testing.assert_array_equal(out["a"], np.arange(10))
    assert np.asarray(blk.row_mask()).sum() == 10


def test_block_is_pytree():
    import jax

    sch = dtypes.schema(("a", dtypes.INT64))
    blk = TableBlock.from_numpy({"a": np.arange(5, dtype=np.int64)}, sch)
    leaves = jax.tree_util.tree_leaves(blk)
    assert len(leaves) == 3  # data, validity, length

    def f(b):
        return b.columns["a"].data.sum()

    assert int(jax.jit(f)(blk)) == 10


def test_dictionary_predicates():
    d = Dictionary()
    ids = d.encode([b"AIR", b"MAIL", b"AIR", b"SHIP"])
    np.testing.assert_array_equal(ids, [0, 1, 0, 2])
    assert d.eq_id(b"MAIL") == 1
    assert d.eq_id(b"TRUCK") == -1
    np.testing.assert_array_equal(d.like_mask("%AI%"), [True, True, False])
    np.testing.assert_array_equal(d.prefix_mask(b"A"), [True, False, False])
    rank = d.sort_rank()
    # AIR < MAIL < SHIP
    assert rank[0] < rank[1] < rank[2]


def test_arrow_roundtrip_with_nulls_strings_decimals():
    import decimal as pydec

    batch = pa.record_batch(
        {
            "k": pa.array([1, 2, None, 4], type=pa.int64()),
            "s": pa.array(["x", None, "y", "x"], type=pa.string()),
            "d": pa.array(
                [pydec.Decimal("1.25"), pydec.Decimal("-2.50"), None,
                 pydec.Decimal("0.01")],
                type=pa.decimal128(12, 2),
            ),
        }
    )
    sch = schema_from_arrow(batch.schema)
    assert sch.field("s").type.is_string
    assert sch.field("d").type.scale == 2

    dicts = DictionarySet()
    blk = record_batch_to_block(batch, dicts)
    data = blk.to_numpy()
    valid = blk.validity_numpy()
    np.testing.assert_array_equal(valid["k"], [True, True, False, True])
    np.testing.assert_array_equal(data["d"], [125, -250, 0, 1])
    # same string -> same id
    assert data["s"][0] == data["s"][3]

    back = block_to_record_batch(blk, dicts)
    assert back.column("k").to_pylist() == [1, 2, None, 4]
    assert back.column("s").to_pylist() == [b"x", None, b"y", b"x"]
    assert [str(x) if x is not None else None for x in back.column("d").to_pylist()] == [
        "1.25", "-2.50", None, "0.01"
    ]


def test_arrow_dictionary_array_remap():
    dicts = DictionarySet()
    b1 = pa.record_batch(
        {"s": pa.array(["b", "a"]).dictionary_encode()}
    )
    b2 = pa.record_batch(
        {"s": pa.array(["a", "c"]).dictionary_encode()}
    )
    sch = schema_from_arrow(b1.schema)
    blk1 = record_batch_to_block(b1, dicts, sch)
    blk2 = record_batch_to_block(b2, dicts, sch)
    d = dicts["s"]
    assert d.decode(blk1.to_numpy()["s"]) == [b"b", b"a"]
    assert d.decode(blk2.to_numpy()["s"]) == [b"a", b"c"]


def test_capacity_quantization_and_overflow():
    sch = dtypes.schema(("a", dtypes.INT32))
    blk = TableBlock.from_numpy({"a": np.arange(1500, dtype=np.int32)}, sch)
    assert blk.capacity == 2048
    with pytest.raises(ValueError):
        TableBlock.from_numpy(
            {"a": np.arange(10, dtype=np.int32)}, sch, capacity=5
        )
