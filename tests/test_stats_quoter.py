"""Statistics service, audit log, quoter, CBO-lite join ordering
(SURVEY §2.14 rows: statistics, audit, quoter; VERDICT r4 missing #5)."""

import numpy as np
import pytest

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.runtime.quoter import Quoter, ThrottledError
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.plan.nodes import LookupJoin, TableScan
from ydb_tpu.workload import tpch


def _mk_cluster():
    c = Cluster()
    s = c.session()
    s.execute("create table kv (k bigint not null, v bigint, "
              "primary key (k))")
    s.execute("insert into kv (k, v) values (1, 10), (2, 20), (3, 30)")
    return c, s


def test_table_stats_and_sys_views():
    c, s = _mk_cluster()
    r = s.execute("select table_name, rows from sys_table_stats")
    assert r.strings("table_name") == [b"kv"]
    assert int(r.column("rows")[0]) == 3
    # audit: the CREATE and INSERT are recorded, SELECTs are not
    r = s.execute("select kind, status from sys_audit order by kind")
    kinds = r.strings("kind")
    assert b"createtable" in kinds and b"insert" in kinds
    assert all(v == b"ok" for v in r.strings("status"))
    n_before = len(kinds)
    s.execute("select count(*) as n from kv")
    r = s.execute("select kind from sys_audit")
    assert r.num_rows == n_before  # reads not audited


def test_quoter_throttles_requests():
    clock = [0.0]
    q = Quoter(clock=lambda: clock[0])
    q.configure("kqp", rate=1000.0, burst=1000.0)
    q.configure("kqp/requests", rate=1.0, burst=2.0)
    c, s = _mk_cluster()
    c.quoter = q
    s.execute("select count(*) as n from kv")
    s.execute("select count(*) as n from kv")
    with pytest.raises(ThrottledError):
        s.execute("select count(*) as n from kv")
    clock[0] += 1.0  # one token refills
    assert s.execute("select count(*) as n from kv") is not None
    # hierarchical: parent exhaustion throttles the child
    q.configure("kqp", rate=0.0, burst=0.0)
    clock[0] += 10.0
    assert not q.try_acquire("kqp/requests")


def test_cbo_orders_smallest_connectable_first():
    """q5's FROM lists customer, orders, lineitem, supplier, nation,
    region — with stats, the probe side starts from customer and joins
    dimensions before fact expansions where connectivity allows."""
    data = tpch.TpchData(sf=0.005, seed=9)
    counts = {t: len(next(iter(cols.values())))
              for t, cols in data.tables.items()}
    catalog = Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
        row_counts=counts,
    )
    from ydb_tpu.workload.queries import TPCH

    pq = plan_select_full(parse(TPCH["q5"]), catalog)

    # walk the left-deep probe spine: collect build-side scan tables
    order = []

    def walk(node):
        if isinstance(node, TableScan):
            order.append(node.table)
            return
        if hasattr(node, "probe"):
            walk(node.probe)
            b = node.build
            while not isinstance(b, TableScan):
                if hasattr(b, "probe"):
                    b = b.probe
                elif hasattr(b, "input"):
                    b = b.input
                else:
                    return
            order.append(b.table)
        elif hasattr(node, "input"):
            walk(node.input)

    walk(pq.plan)
    # supplier (small) joins before lineitem (the big fact expansion)
    assert order.index("supplier") < order.index("lineitem")

    # and the result still matches the no-stats plan
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan import Database, execute_plan, to_host

    db = Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts)
    res = to_host(execute_plan(pq.plan, db))
    catalog2 = Catalog(
        schemas=catalog.schemas, primary_keys=catalog.primary_keys,
        dicts=catalog.dicts)
    ref = to_host(execute_plan(
        plan_select_full(parse(TPCH["q5"]), catalog2).plan, db))
    np.testing.assert_array_equal(
        np.asarray(res.cols["revenue"][0]),
        np.asarray(ref.cols["revenue"][0]))
    np.testing.assert_array_equal(
        np.asarray(res.cols["n_name"][0]),
        np.asarray(ref.cols["n_name"][0]))
