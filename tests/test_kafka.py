"""Kafka wire-protocol frontend tests: a from-the-spec minimal client
(independent framing code) drives ApiVersions/Metadata/Produce/Fetch/
ListOffsets/offset APIs against the topic plane (reference:
ydb/core/kafka_proxy)."""

import socket
import struct
import zlib

import pytest

from ydb_tpu.api.kafka import KafkaServer
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.topic.topic import Topic


def enc_str(s):
    if s is None:
        return struct.pack("!h", -1)
    b = s.encode()
    return struct.pack("!h", len(b)) + b


def enc_bytes(b):
    if b is None:
        return struct.pack("!i", -1)
    return struct.pack("!i", len(b)) + b


def enc_msgset(entries, corrupt=False):
    out = b""
    for key, value, ts in entries:
        body = (struct.pack("!bbq", 1, 0, ts)
                + enc_bytes(key) + enc_bytes(value))
        crc = zlib.crc32(body) & 0xFFFFFFFF
        if corrupt:
            crc ^= 0xDEAD
        msg = struct.pack("!I", crc) + body
        out += struct.pack("!qi", -1, len(msg)) + msg
    return out


class MiniKafkaClient:
    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=10)
        self.corr = 0

    def call(self, api_key, api_version, body, expect_response=True):
        self.corr += 1
        req = (struct.pack("!hhi", api_key, api_version, self.corr)
               + enc_str("mini") + body)
        self.sock.sendall(struct.pack("!i", len(req)) + req)
        if not expect_response:
            return None
        (size,) = struct.unpack("!i", self._recv(4))
        payload = self._recv(size)
        (corr,) = struct.unpack("!i", payload[:4])
        assert corr == self.corr
        return payload[4:]

    def _recv(self, n):
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            assert c, "server closed"
            buf += c
        return buf

    def close(self):
        self.sock.close()


def parse_msgset(buf):
    out = []
    off = 0
    while off + 12 <= len(buf):
        o, size = struct.unpack("!qi", buf[off:off + 12])
        off += 12
        body = buf[off:off + size]
        off += size
        (crc,) = struct.unpack("!I", body[:4])
        assert zlib.crc32(body[4:]) & 0xFFFFFFFF == crc, "bad crc"
        magic, _attrs, ts = struct.unpack("!bbq", body[4:14])
        p = 14
        (klen,) = struct.unpack("!i", body[p:p + 4])
        p += 4 + max(klen, 0)
        key = None if klen == -1 else body[p - klen:p]
        (vlen,) = struct.unpack("!i", body[p:p + 4])
        p += 4
        value = None if vlen == -1 else body[p:p + vlen]
        out.append((o, ts, key, value))
    return out


@pytest.fixture
def served():
    cluster = Cluster()
    cluster.topics["events"] = Topic("events", MemBlobStore(),
                                     n_partitions=2)
    srv = KafkaServer(cluster).start()
    client = MiniKafkaClient(srv.port)
    yield cluster, srv, client
    client.close()
    srv.stop()


def test_api_versions_and_metadata(served):
    _cluster, srv, c = served
    resp = c.call(18, 0, b"")
    err, n = struct.unpack("!hi", resp[:6])
    assert err == 0 and n >= 8
    keys = {struct.unpack("!hhh", resp[6 + i * 6:12 + i * 6])[0]
            for i in range(n)}
    assert {0, 1, 2, 3, 8, 9, 10, 18} <= keys

    resp = c.call(3, 1, struct.pack("!i", -1))  # all topics
    r = memoryview(resp)
    (n_brokers,) = struct.unpack("!i", r[:4])
    assert n_brokers == 1
    off = 4
    node, = struct.unpack("!i", r[off:off + 4])
    off += 4
    hlen, = struct.unpack("!h", r[off:off + 2])
    host = bytes(r[off + 2:off + 2 + hlen]).decode()
    off += 2 + hlen
    port, = struct.unpack("!i", r[off:off + 4])
    off += 4 + 2  # port + null rack
    assert (host, port) == (srv.host, srv.port)
    controller, n_topics = struct.unpack("!ii", r[off:off + 8])
    assert controller == node and n_topics == 1
    off += 8
    terr, = struct.unpack("!h", r[off:off + 2])
    off += 2
    tlen, = struct.unpack("!h", r[off:off + 2])
    tname = bytes(r[off + 2:off + 2 + tlen]).decode()
    off += 2 + tlen + 1  # + is_internal
    nparts, = struct.unpack("!i", r[off:off + 4])
    assert (terr, tname, nparts) == (0, "events", 2)


def _produce(c, topic, partition, entries, acks=1, corrupt=False):
    body = (struct.pack("!hi", acks, 1000) + struct.pack("!i", 1)
            + enc_str(topic) + struct.pack("!i", 1)
            + struct.pack("!i", partition)
            + enc_bytes(enc_msgset(entries, corrupt=corrupt)))
    return c.call(0, 2, body, expect_response=acks != 0)


def _fetch(c, topic, partition, offset, max_bytes=1 << 20):
    body = (struct.pack("!iii", -1, 100, 1) + struct.pack("!i", 1)
            + enc_str(topic) + struct.pack("!i", 1)
            + struct.pack("!iqi", partition, offset, max_bytes))
    resp = c.call(1, 2, body)
    r = _SkipReader(resp)
    r.i32()  # throttle
    assert r.i32() == 1
    assert r.string() == topic
    assert r.i32() == 1
    pid, err, hw = r.i32(), r.i16(), r.i64()
    mset = r.bytes_()
    return err, hw, parse_msgset(mset)


class _SkipReader:
    def __init__(self, buf):
        self.buf, self.off = buf, 0

    def _take(self, n):
        b = self.buf[self.off:self.off + n]
        self.off += n
        return b

    def i16(self):
        return struct.unpack("!h", self._take(2))[0]

    def i32(self):
        return struct.unpack("!i", self._take(4))[0]

    def i64(self):
        return struct.unpack("!q", self._take(8))[0]

    def string(self):
        n = self.i16()
        return None if n == -1 else self._take(n).decode()

    def bytes_(self):
        n = self.i32()
        return b"" if n == -1 else self._take(n)


def test_produce_fetch_roundtrip(served):
    _cluster, _srv, c = served
    resp = _produce(c, "events", 0,
                    [(None, b"hello", 1000), (b"k", b"world", 2000)])
    r = _SkipReader(resp)
    assert r.i32() == 1 and r.string() == "events" and r.i32() == 1
    pid, err, base = r.i32(), r.i16(), r.i64()
    assert (pid, err, base) == (0, 0, 0)

    err, hw, msgs = _fetch(c, "events", 0, 0)
    assert err == 0 and hw == 2
    assert [(m[0], m[3]) for m in msgs] == [(0, b"hello"), (1, b"world")]
    assert msgs[0][1] == 1000  # producer timestamp preserved (ms)

    # fetch from the middle
    err, hw, msgs = _fetch(c, "events", 0, 1)
    assert [(m[0], m[3]) for m in msgs] == [(1, b"world")]


def test_produce_acks0_and_corrupt_crc(served):
    _cluster, _srv, c = served
    _produce(c, "events", 1, [(None, b"fire", 1)], acks=0)
    err, hw, msgs = _fetch(c, "events", 1, 0)
    assert hw == 1 and msgs[0][3] == b"fire"

    resp = _produce(c, "events", 1, [(None, b"bad", 1)], corrupt=True)
    r = _SkipReader(resp)
    r.i32()
    r.string()
    r.i32()
    _pid, err, _base = r.i32(), r.i16(), r.i64()
    assert err == 2  # CORRUPT_MESSAGE
    err, hw, _ = _fetch(c, "events", 1, 0)
    assert hw == 1  # nothing appended


def test_list_offsets_and_group_offsets(served):
    _cluster, _srv, c = served
    _produce(c, "events", 0, [(None, b"a", 1), (None, b"b", 1)])

    body = (struct.pack("!i", -1) + struct.pack("!i", 1)
            + enc_str("events") + struct.pack("!i", 2)
            + struct.pack("!iq", 0, -1)     # latest
            + struct.pack("!iq", 0, -2))    # earliest
    resp = c.call(2, 1, body)
    r = _SkipReader(resp)
    assert r.i32() == 1 and r.string() == "events" and r.i32() == 2
    rows = [(r.i32(), r.i16(), r.i64(), r.i64()) for _ in range(2)]
    assert rows[0][3] == 2 and rows[1][3] == 0

    # FindCoordinator
    resp = c.call(10, 0, enc_str("grp"))
    r = _SkipReader(resp)
    assert r.i16() == 0 and r.i32() == 1

    # OffsetCommit v2
    body = (enc_str("grp") + struct.pack("!i", -1) + enc_str("m1")
            + struct.pack("!q", -1) + struct.pack("!i", 1)
            + enc_str("events") + struct.pack("!i", 1)
            + struct.pack("!iq", 0, 2) + enc_str(None))
    resp = c.call(8, 2, body)
    r = _SkipReader(resp)
    assert r.i32() == 1 and r.string() == "events" and r.i32() == 1
    assert (r.i32(), r.i16()) == (0, 0)

    # OffsetFetch v1
    body = (enc_str("grp") + struct.pack("!i", 1) + enc_str("events")
            + struct.pack("!i", 1) + struct.pack("!i", 0))
    resp = c.call(9, 1, body)
    r = _SkipReader(resp)
    assert r.i32() == 1 and r.string() == "events" and r.i32() == 1
    pid, off = r.i32(), r.i64()
    r.string()
    assert (pid, off, r.i16()) == (0, 2, 0)


def test_key_roundtrip_and_offset_rewind(served):
    _cluster, _srv, c = served
    _produce(c, "events", 0, [(b"user-1", b"v1", 500)])
    err, _hw, msgs = _fetch(c, "events", 0, 0)
    assert err == 0 and msgs[0][2] == b"user-1"  # key preserved

    def commit(offset):
        body = (enc_str("g") + struct.pack("!i", -1) + enc_str("m")
                + struct.pack("!q", -1) + struct.pack("!i", 1)
                + enc_str("events") + struct.pack("!i", 1)
                + struct.pack("!iq", 0, offset) + enc_str(None))
        c.call(8, 2, body)

    def fetch_committed():
        body = (enc_str("g") + struct.pack("!i", 1) + enc_str("events")
                + struct.pack("!i", 1) + struct.pack("!i", 0))
        r = _SkipReader(c.call(9, 1, body))
        r.i32()
        r.string()
        r.i32()
        r.i32()
        off = r.i64()
        return off

    commit(1)
    assert fetch_committed() == 1
    commit(0)  # explicit seek-back must rewind (reprocessing flow)
    assert fetch_committed() == 0


def test_sasl_plain_auth():
    cluster = Cluster()
    cluster.topics["ev"] = Topic("ev", MemBlobStore(), n_partitions=1)
    srv = KafkaServer(cluster, auth_tokens={"sesame"}).start()
    c = MiniKafkaClient(srv.port)
    try:
        # unauthenticated data API -> SASL_AUTHENTICATION_FAILED (58)
        resp = c.call(3, 1, struct.pack("!i", -1))
        assert _SkipReader(resp).i16() == 58

        # handshake advertises PLAIN
        r = _SkipReader(c.call(17, 1, enc_str("PLAIN")))
        assert r.i16() == 0 and r.i32() == 1 and r.string() == "PLAIN"

        # wrong password rejected
        bad = b"\x00user\x00nope"
        r = _SkipReader(c.call(36, 0, enc_bytes(bad)))
        assert r.i16() == 58

        # right password accepted, then data APIs work
        good = b"\x00user\x00sesame"
        r = _SkipReader(c.call(36, 0, enc_bytes(good)))
        assert r.i16() == 0
        resp = c.call(3, 1, struct.pack("!i", -1))
        assert _SkipReader(resp).i32() == 1  # brokers array, not error
    finally:
        c.close()
        srv.stop()


def test_unknown_topic_and_unsupported_version(served):
    _cluster, _srv, c = served
    resp = _produce(c, "missing", 0, [(None, b"x", 1)])
    r = _SkipReader(resp)
    r.i32()
    r.string()
    r.i32()
    _pid, err, _ = r.i32(), r.i16(), r.i64()
    assert err == 3  # UNKNOWN_TOPIC_OR_PARTITION

    resp = c.call(3, 9, struct.pack("!i", -1))  # Metadata v9: too new
    r = _SkipReader(resp)
    assert r.i16() == 35  # UNSUPPORTED_VERSION
