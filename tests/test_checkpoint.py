"""DQ checkpoint/resume tests (SURVEY.md §5.4): aligned barriers, task
state save/load, crash + restore mid-stream with exact results."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.dq.checkpoint import (
    CheckpointStorage, TriggerCheckpoint,
)
from ydb_tpu.dq.compute import build_stage_graph, run_stage_graph
from ydb_tpu.dq.graph import (
    HashPartition, ResultOutput, SourceInput, StageSpec, UnionAllInput,
)
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.runtime.test_runtime import SimRuntime
from ydb_tpu.ssa import Agg, AggSpec, twophase
from ydb_tpu.ssa.program import GroupByStep, Program, SortStep


SCHEMA = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
AGG = Program((
    GroupByStep(keys=("k",), aggs=(
        AggSpec(Agg.SUM, "v", "total"),
        AggSpec(Agg.COUNT_ALL, None, "n"),
    )),
))


def _sources(n_parts=3, rows=900, seed=2):
    rng = np.random.default_rng(seed)
    parts, merged = [], {"k": [], "v": []}
    for _ in range(n_parts):
        k = rng.integers(0, 7, rows).astype(np.int64)
        v = rng.integers(0, 100, rows).astype(np.int64)
        parts.append(ColumnSource({"k": k, "v": v}, SCHEMA, None))
        merged["k"].append(k)
        merged["v"].append(v)
    merged = {c: np.concatenate(a) for c, a in merged.items()}
    return parts, merged


def _stages(n_parts):
    partial, final = twophase.split(AGG)
    return [
        StageSpec(program=partial, inputs=(SourceInput("t"),),
                  output=HashPartition(("k",)), tasks=n_parts),
        StageSpec(program=None, inputs=(UnionAllInput(0),),
                  output=HashPartition(("k",)), tasks=2,
                  final_program=final),
        StageSpec(program=None, inputs=(UnionAllInput(1),),
                  output=ResultOutput(), tasks=1,
                  final_program=Program((SortStep(keys=("k",)),))),
    ]


def _expected(merged):
    ora = run_oracle(
        Program((AGG.steps[0], SortStep(keys=("k",)))),
        OracleTable({c: (a, np.ones(len(a), dtype=bool))
                     for c, a in merged.items()}, SCHEMA))
    return ora


def test_checkpoint_completes_and_result_unaffected():
    parts, merged = _sources()
    store = MemBlobStore()
    storage = CheckpointStorage(store, "g1")
    rt = SimRuntime(n_nodes=2)
    handle = build_stage_graph(
        _stages(len(parts)), {"t": parts}, rt,
        checkpoint_storage=storage)
    handle.start()
    # let some blocks flow, then checkpoint mid-stream
    for _ in range(5):
        for s in rt.nodes.values():
            s.step()
    rt.system(1).send(handle.coordinator_id, TriggerCheckpoint())
    rt.dispatch()
    assert handle.collector.done
    assert storage.latest_complete() == 1
    out = handle.collector.table()
    exp = _expected(merged)
    np.testing.assert_array_equal(out.cols["total"][0],
                                  exp.cols["total"][0])
    np.testing.assert_array_equal(out.cols["n"][0], exp.cols["n"][0])


def test_crash_and_resume_from_checkpoint_exact_result():
    parts, merged = _sources(n_parts=2, rows=20000, seed=9)
    store = MemBlobStore()
    storage = CheckpointStorage(store, "g2")

    # ---- first run: checkpoint mid-stream, then "crash" ----
    rt = SimRuntime(n_nodes=2)
    handle = build_stage_graph(_stages(len(parts)), {"t": parts}, rt,
                               checkpoint_storage=storage)
    # small blocks so the stream has many pump steps
    for a in handle.actors:
        a.block_rows = 128
    handle.start()
    for _ in range(40):  # progress partway
        for s in rt.nodes.values():
            s.step()
    rt.system(1).send(handle.coordinator_id, TriggerCheckpoint())
    # drive until the checkpoint completes, then abandon the runtime
    for _ in range(20000):
        progressed = any(s.step() for s in rt.nodes.values())
        if storage.latest_complete() == 1:
            break
        if not progressed:
            break
    assert storage.latest_complete() == 1
    assert not handle.collector.done  # crashed mid-flight

    # ---- recovery: fresh runtime restores from the checkpoint ----
    storage.drop_incomplete()
    rt2 = SimRuntime(n_nodes=2)
    out = run_stage_graph(_stages(len(parts)), {"t": parts}, rt2,
                          checkpoint_storage=storage,
                          restore_checkpoint=storage.latest_complete())
    exp = _expected(merged)
    np.testing.assert_array_equal(out.cols["k"][0], exp.cols["k"][0])
    np.testing.assert_array_equal(out.cols["total"][0],
                                  exp.cols["total"][0])
    np.testing.assert_array_equal(out.cols["n"][0], exp.cols["n"][0])


def test_two_inflight_checkpoints_resume_exact():
    """Barriers for two checkpoints ride the channels simultaneously;
    each task must cut every channel at ITS barrier for each checkpoint
    (per-channel hold queues), so restoring from the second checkpoint
    still reproduces the exact result."""
    parts, merged = _sources(n_parts=2, rows=20000, seed=11)
    store = MemBlobStore()
    storage = CheckpointStorage(store, "g4")
    rt = SimRuntime(n_nodes=2)
    handle = build_stage_graph(_stages(len(parts)), {"t": parts}, rt,
                               checkpoint_storage=storage)
    for a in handle.actors:
        a.block_rows = 128
    handle.start()
    for _ in range(40):
        for s in rt.nodes.values():
            s.step()
    # two checkpoints injected back-to-back
    rt.system(1).send(handle.coordinator_id, TriggerCheckpoint())
    rt.system(1).send(handle.coordinator_id, TriggerCheckpoint())
    for _ in range(40000):
        progressed = any(s.step() for s in rt.nodes.values())
        if storage.latest_complete() == 2:
            break
        if not progressed:
            break
    assert storage.latest_complete() == 2
    # recovery from the SECOND checkpoint must be exact
    storage.drop_incomplete()
    rt2 = SimRuntime(n_nodes=2)
    out = run_stage_graph(_stages(len(parts)), {"t": parts}, rt2,
                          checkpoint_storage=storage,
                          restore_checkpoint=2)
    exp = _expected(merged)
    np.testing.assert_array_equal(out.cols["k"][0], exp.cols["k"][0])
    np.testing.assert_array_equal(out.cols["total"][0],
                                  exp.cols["total"][0])
    np.testing.assert_array_equal(out.cols["n"][0], exp.cols["n"][0])


def test_storage_roundtrip_and_gc():
    storage = CheckpointStorage(MemBlobStore(), "g3")
    storage.save_task(1, 0, {"acc": [], "source_pos": 3,
                             "in_finished": []})
    assert storage.load_task(1, 0)["source_pos"] == 3
    assert storage.load_task(1, 99) is None
    assert storage.latest_complete() is None
    storage.mark_complete(1)
    storage.save_task(2, 0, {"acc": [], "source_pos": 9,
                             "in_finished": []})  # incomplete
    assert storage.latest_complete() == 1
    storage.drop_incomplete()
    assert storage.load_task(2, 0) is None
    assert storage.load_task(1, 0) is not None
