"""Runtime thread-sanitizer: self-tests + seeded multi-thread stress.

The stress suites hammer the DESIGNATED shared structures (scan cache,
device block cache, conveyor heap, probe/counter registries) with the
sanitizer active, so tier-1 runs double as a race detector: a dropped
lock in any of those paths turns these tests red with a RaceError
naming the structure. The self-tests prove the detector actually fires
— including on the exact PR 3 scan-cache shape with its lock removed.
"""

import contextlib
import threading

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.analysis import sanitizer
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa.program import Program, lit

SCHEMA = dtypes.schema(("a", dtypes.INT64, False), ("b", dtypes.INT64))


def _run_threads(fns, timeout=30.0):
    """Run thunks on threads; re-raise the first exception."""
    errors: list = []

    def wrap(fn):
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=wrap, args=(fn,)) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if errors:
        raise errors[0]


# ---------------- detector self-tests ----------------


def test_racy_toy_class_is_flagged():
    """The injected unguarded-mutation race: two threads write a shared
    dict with no lock — the sanitizer must raise, deterministically."""
    with sanitizer.activate():
        shared = sanitizer.share({}, "toy.racy")

        def writer():
            shared["w"] = 1

        t = threading.Thread(target=writer)
        t.start()
        t.join()
        with pytest.raises(sanitizer.RaceError) as exc:
            shared["main"] = 2
        assert "toy.racy" in str(exc.value)


def test_guarded_class_is_clean():
    with sanitizer.activate():
        lock = sanitizer.make_lock("toy.lock")
        shared = sanitizer.share({}, "toy.guarded")

        def writer():
            for i in range(50):
                with lock:
                    shared[i] = i

        _run_threads([writer] * 4)
        with lock:
            assert len(shared) == 50


def test_single_thread_init_phase_never_flags():
    # exclusive-phase accesses (construction) are unchecked by design
    with sanitizer.activate():
        shared = sanitizer.share({}, "toy.init")
        for i in range(100):
            shared[i] = i
        assert len(shared) == 100


def test_read_sharing_without_writes_is_clean():
    with sanitizer.activate():
        shared = sanitizer.share({"k": 1}, "toy.readshare")

        def reader():
            for _ in range(100):
                assert shared.get("k") == 1

        _run_threads([reader] * 4)


def test_tracked_lock_held_set_and_condition_roundtrip():
    with sanitizer.activate():
        cv = sanitizer.make_condition("toy.cv")
        assert sanitizer.held_locks() == frozenset()
        with cv:
            assert "toy.cv" in sanitizer.held_locks()
        assert sanitizer.held_locks() == frozenset()

        fired = []

        def waiter():
            with cv:
                cv.wait(timeout=10.0)
                fired.append(sanitizer.held_locks())

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.05)
        with cv:
            cv.notify_all()
        t.join(timeout=10)
        # after wait() returns the condition's lock is re-held
        assert fired and "toy.cv" in fired[0]


def test_tracked_condition_is_reentrant_like_plain_condition():
    # threading.Condition() is RLock-backed; the sanitized variant must
    # not deadlock on a re-entered ``with cv:`` only under TSAN
    with sanitizer.activate():
        cv = sanitizer.make_condition("toy.recv")
        with cv:
            with cv:
                assert "toy.recv" in sanitizer.held_locks()
        assert sanitizer.held_locks() == frozenset()


def test_activate_epochs_reset_long_lived_proxy_state():
    # a proxy created in epoch 1 and raced across threads must come
    # back clean in epoch 2 (states reset in place, not orphaned)
    with sanitizer.activate():
        shared = sanitizer.share({}, "toy.epoch")

        def writer():
            with pytest.raises(sanitizer.RaceError):
                for _ in range(2):
                    shared["w"] = 1

        shared["main"] = 0
        t = threading.Thread(target=writer)
        t.start()
        t.join()
    with sanitizer.activate():
        # fresh epoch: single-threaded writes on the SAME proxy are the
        # exclusive init phase again — no stale lockset survives
        for i in range(5):
            shared[i] = i


def test_tsan_off_is_zero_overhead_passthrough(monkeypatch):
    monkeypatch.delenv("YDB_TPU_TSAN", raising=False)
    raw = {}
    assert sanitizer.share(raw, "toy.off") is raw
    assert isinstance(sanitizer.make_lock("x"), type(threading.Lock()))
    assert sanitizer.token("toy.off") is None
    sanitizer.note(None, "nothing")  # no-op on a None token


# ---------------- PR 3 scan-cache LRU race regression ----------------


def _mk_shard(entries=2):
    shard = ColumnShard(
        "tsan", SCHEMA, MemBlobStore(),
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           scan_block_rows=64,
                           scan_cache_entries=entries))
    rng = np.random.default_rng(7)
    shard.commit([shard.write({
        "a": rng.integers(0, 8, 300).astype(np.int64),
        "b": rng.integers(0, 100, 300).astype(np.int64)})])
    return shard


def _prog(threshold):
    return Program((
        FilterStep(Call(Op.GE, Col("a"), lit(threshold))),
        GroupByStep(("a",), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))


def test_scan_cache_stress_under_sanitizer():
    """Concurrent scans hammer ColumnShard._scan_cache with
    scan_cache_entries=2 (constant touch/evict churn — the PR 3 race
    surface) under the sanitizer proxies: the guarded implementation
    must survive with zero findings and correct results."""
    with sanitizer.activate():
        shard = _mk_shard(entries=2)
        expect = {t: int(shard.scan(_prog(t)).cols["n"][0].sum())
                  for t in range(4)}

        def scanner(seed):
            rng = np.random.default_rng(seed)
            for _ in range(12):
                t = int(rng.integers(0, 4))
                out = shard.scan(_prog(t))
                assert int(out.cols["n"][0].sum()) == expect[t]

        _run_threads([lambda s=s: scanner(s) for s in range(4)],
                     timeout=120.0)
        # even the assertion must respect the guard: an unlocked len()
        # here is itself a cross-thread access the proxy flags
        with shard._scan_cache_lock:
            assert len(shard._scan_cache) <= 2


def test_scan_cache_without_lock_is_caught():
    """Remove the scan-cache lock (reintroducing the pre-PR 3 bug) and
    the sanitizer must flag the unsynchronized LRU mutation."""
    with sanitizer.activate():
        shard = _mk_shard(entries=2)
        # simulate the unguarded implementation: the with-statement
        # still runs, but no lock is actually taken
        shard._scan_cache_lock = contextlib.nullcontext()
        shard.scan(_prog(0))  # populate from this thread

        def other():
            shard.scan(_prog(1))

        with pytest.raises(sanitizer.RaceError) as exc:
            _run_threads([other])
        assert "_scan_cache" in str(exc.value)


def test_concurrent_commits_mint_unique_snapshots():
    """commit() allocates its snapshot inside _commit's critical
    section: concurrent committers must never share a snapshot id
    (the TOCTOU `self.snap + 1` read this PR closed)."""
    with sanitizer.activate():
        shard = _mk_shard()
        snaps: list = []

        def committer(base):
            for i in range(5):
                wid = shard.write({
                    "a": np.asarray([base + i], dtype=np.int64),
                    "b": np.asarray([i], dtype=np.int64)})
                snaps.append(shard.commit([wid]))

        _run_threads([lambda b=b: committer(b * 100) for b in range(4)])
        assert len(snaps) == 20
        assert len(set(snaps)) == 20, sorted(snaps)


# ---------------- designated-structure stress ----------------


def test_conveyor_stress_under_sanitizer():
    from ydb_tpu.runtime.conveyor import Conveyor, ResourceBroker

    with sanitizer.activate():
        conveyor = Conveyor(
            workers=3, broker=ResourceBroker(quotas={"q": 2}))
        try:
            handles = []

            def submitter(base):
                for i in range(20):
                    handles.append(conveyor.submit(
                        "q", lambda v=base * 100 + i: v * 2))

            _run_threads([lambda b=b: submitter(b) for b in range(3)])
            got = sorted(h.wait(30.0) for h in list(handles))
            assert len(got) == 60
        finally:
            conveyor.shutdown()


def test_blockcache_stress_under_sanitizer():
    from ydb_tpu.engine.blockcache import DeviceBlockCache

    class _Col:
        data = np.zeros(16, dtype=np.int64)
        validity = np.ones(16, dtype=bool)

    class _Blk:
        columns = {"c": _Col()}

    with sanitizer.activate():
        cache = DeviceBlockCache(budget=1 << 20)

        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(40):
                key = ("k", int(rng.integers(0, 6)))
                got = cache.get(key)
                if got is None:
                    list(cache.stream(key, lambda: iter([_Blk()])))

        _run_threads([lambda s=s: worker(s) for s in range(4)])
        assert cache.hits + cache.misses > 0


def test_probe_and_counter_registries_under_sanitizer():
    from ydb_tpu.obs import probes
    from ydb_tpu.obs.counters import CounterGroup

    with sanitizer.activate():
        root = CounterGroup()

        def worker(seed):
            for i in range(30):
                probes.probe(f"tsan.stress.{seed}.{i % 5}")
                g = root.group(worker=str(seed % 2))
                g.counter(f"c{i % 3}").inc()
                g.histogram("h").observe(0.001 * i)

        _run_threads([lambda s=s: worker(s) for s in range(4)])
        snap = root.snapshot()
        assert sum(v for k, v in snap.items()
                   if k.startswith("c")) == 4 * 30
