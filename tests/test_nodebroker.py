"""NodeBroker dynamic registration + TenantPool tests, including
dynamic interconnect peer discovery between two live actor systems
(reference: ydb/core/mind/node_broker.cpp, tenant_pool.cpp)."""

import time

from conftest import Clock

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.runtime.actors import Actor, ActorId, ActorSystem
from ydb_tpu.runtime.interconnect import Interconnect
from ydb_tpu.runtime.nodebroker import NodeBroker, TenantPool



def test_register_renew_expire():
    clock = Clock(1000.0)
    nb = NodeBroker(MemBlobStore(), lease_s=30, now=clock)
    a = nb.register("10.0.0.1", 19001)
    b = nb.register("10.0.0.2", 19001)
    assert a.node_id == 1024 and b.node_id == 1025
    assert nb.resolve(1025) == ("10.0.0.2", 19001)

    # same endpoint re-registers -> same id (restart inside lease)
    a2 = nb.register("10.0.0.1", 19001)
    assert a2.node_id == a.node_id

    clock.t += 20
    nb.extend(a.node_id)
    clock.t += 15  # b's lease (30s) lapsed; a extended at +20
    assert nb.tick() == [b.node_id]
    assert [n.node_id for n in nb.nodes()] == [a.node_id]
    # epoch bumped on expiry (stale resolution fencing)
    assert nb.nodes()[0].epoch == 2
    # freed id is reused
    c = nb.register("10.0.0.3", 19001)
    assert c.node_id == 1025


def test_broker_reboot_keeps_registrations():
    store = MemBlobStore()
    clock = Clock(1000.0)
    nb = NodeBroker(store, lease_s=300, now=clock)
    a = nb.register("h1", 1)
    nb2 = NodeBroker(store, lease_s=300, now=clock)
    assert nb2.resolve(a.node_id) == ("h1", 1)
    assert nb2.register("h2", 2).node_id == a.node_id + 1


class Echo(Actor):
    def receive(self, message, sender):
        if message[0] == "ping":
            self.send(sender, ("pong", message[1]))


class Collector(Actor):
    def __init__(self):
        super().__init__()
        self.got = []

    def receive(self, message, sender):
        self.got.append(message)


def test_dynamic_peer_discovery_end_to_end():
    """Two actor systems find each other through the broker alone."""
    nb = NodeBroker(MemBlobStore(), lease_s=300)

    sys_a = ActorSystem(node=0)
    sys_b = ActorSystem(node=0)
    ic_a = Interconnect(sys_a, listen_port=0)
    ic_b = Interconnect(sys_b, listen_port=0)
    try:
        a = nb.register("127.0.0.1", ic_a.port)
        b = nb.register("127.0.0.1", ic_b.port)
        sys_a.node = a.node_id
        sys_b.node = b.node_id

        echo = Echo()
        sys_b.register(echo)  # ActorId(b, 1)
        coll = Collector()
        sys_a.register(coll)  # ActorId(a, 1)

        nb.connect_peers(ic_a)
        nb.connect_peers(ic_b)

        sys_a.send(ActorId(b.node_id, 1), ("ping", 7),
                   sender=ActorId(a.node_id, 1))
        deadline = time.time() + 10
        while not coll.got and time.time() < deadline:
            ic_b.pump(0.05)
            ic_a.pump(0.05)
        assert coll.got == [("pong", 7)]
    finally:
        ic_a.close()
        ic_b.close()


def test_tenant_pool_slots():
    tp = TenantPool(slots=4)
    assert tp.claim("/Root/a", 3)
    assert not tp.claim("/Root/b", 2)
    assert tp.claim("/Root/b", 1)
    assert tp.free_slots() == 0
    tp.release("/Root/a", 2)
    assert tp.free_slots() == 2 and tp.tenants() == {
        "/Root/a": 1, "/Root/b": 1}
    tp.release("/Root/a")
    assert "/Root/a" not in tp.tenants()
