"""ColumnShard state-plane tests: MVCC, compaction, TTL, WAL recovery.

Coverage mirrors the reference's columnshard ut_rw / engine change tests
(tx/columnshard/ut_rw, engines/changes/*) at the capability level."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import DirBlobStore, MemBlobStore
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa.program import Program, lit

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("ts", dtypes.DATE, False),
    ("tag", dtypes.STRING),
    ("val", dtypes.INT64),
)


def _shard(store=None, **cfg):
    return ColumnShard(
        "shard1", SCHEMA, store or MemBlobStore(),
        pk_column="id", ttl_column="ts",
        config=ShardConfig(**cfg) if cfg else None,
    )


def _write(shard, ids, ts=None, tags=None, vals=None):
    n = len(ids)
    cols = shard.encode_strings({
        "id": np.asarray(ids, dtype=np.int64),
        "ts": np.asarray(ts if ts is not None else [100] * n, dtype=np.int32),
        "tag": tags if tags is not None else [b"x"] * n,
        "val": np.asarray(vals if vals is not None else ids, dtype=np.int64),
    })
    return shard.write(cols)


def _count(shard, snap=None):
    prog = Program((
        GroupByStep(keys=(), aggs=(AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    return int(shard.scan(prog, snap).cols["n"][0][0])


def test_write_commit_scan_mvcc():
    shard = _shard()
    w1 = _write(shard, [1, 2, 3])
    assert _count(shard) == 0  # uncommitted writes invisible
    s1 = shard.commit([w1])
    assert _count(shard) == 3
    w2 = _write(shard, [4, 5])
    s2 = shard.commit([w2])
    assert _count(shard) == 5
    # reads at the older snapshot still see the old state
    assert _count(shard, s1) == 3
    assert _count(shard, s2) == 5
    assert _count(shard, 0) == 0


def test_scan_with_program_and_strings():
    shard = _shard()
    shard.commit([_write(shard, [1, 2, 3, 4],
                         tags=[b"a", b"b", b"a", b"c"],
                         vals=[10, 20, 30, 40])])
    from ydb_tpu.ssa.program import DictPredicate

    prog = Program((
        FilterStep(DictPredicate("tag", "eq", b"a")),
        GroupByStep(keys=(), aggs=(AggSpec(Agg.SUM, "val", "s"),)),
    ))
    assert int(shard.scan(prog).cols["s"][0][0]) == 40


def test_compaction_preserves_snapshots_and_sorts_pk():
    shard = _shard()
    s_old = None
    for batch in ([5, 3], [9, 1], [7, 2]):
        s_old = shard.commit([_write(shard, batch)])
    assert len(shard.visible_portions()) == 3
    shard.compact()
    vis = shard.visible_portions()
    assert len(vis) == 1
    # merged portion is PK-sorted with correct stats
    assert (vis[0].pk_min, vis[0].pk_max) == (1, 9)
    assert _count(shard) == 6
    # reader at the pre-compaction snapshot sees the old portions
    assert _count(shard, s_old) == 6
    metas_old = shard.visible_portions(s_old)
    assert len(metas_old) == 3


def test_pk_range_pruning():
    shard = _shard()
    shard.commit([_write(shard, [1, 2, 3])])
    shard.commit([_write(shard, [100, 200])])
    pruned = shard.visible_portions(pk_range=(150, None))
    assert len(pruned) == 1
    assert pruned[0].pk_min == 100


def test_ttl_eviction():
    shard = _shard()
    shard.commit([_write(shard, [1, 2, 3], ts=[10, 20, 30])])
    shard.commit([_write(shard, [4], ts=[50])])
    evicted = shard.evict_ttl(cutoff=25)
    assert evicted == 2
    assert _count(shard) == 2
    prog = Program((FilterStep(Call(Op.GE, Col("id"), lit(0))),))
    res = shard.scan(prog)
    assert sorted(res.cols["id"][0].tolist()) == [3, 4]


def test_gc_blobs():
    shard = _shard()
    shard.commit([_write(shard, [1])])
    shard.commit([_write(shard, [2])])
    shard.compact()
    n_before = len(shard.store.list("shard1/portion/"))
    assert shard.gc_blobs(keep_snap=shard.snap) == 2
    assert len(shard.store.list("shard1/portion/")) == n_before - 2
    assert _count(shard) == 2  # live data untouched


def test_boot_replays_wal(tmp_path):
    store = DirBlobStore(str(tmp_path))
    shard = ColumnShard("s", SCHEMA, store, pk_column="id",
                        ttl_column="ts")
    shard.commit([_write(shard, [1, 2], tags=[b"x", b"y"])])
    shard.commit([_write(shard, [3], tags=[b"z"])])
    snap = shard.snap

    # new process: recover purely from storage
    shard2 = ColumnShard.boot("s", SCHEMA, store, pk_column="id",
                              ttl_column="ts")
    assert shard2.snap == snap
    assert _count(shard2) == 3
    # dictionaries recovered (ids in portions must decode)
    assert shard2.dicts["tag"].values == [b"x", b"y", b"z"]
    # and the recovered shard continues writing correctly
    shard2.commit([_write(shard2, [4], tags=[b"w"])])
    assert _count(shard2) == 4


def test_boot_from_checkpoint_plus_tail(tmp_path):
    store = DirBlobStore(str(tmp_path))
    cfg = ShardConfig(checkpoint_interval=2)
    shard = ColumnShard("s", SCHEMA, store, pk_column="id", config=cfg)
    for i in range(5):
        shard.commit([_write(shard, [i * 10 + 1, i * 10 + 2])])
    shard2 = ColumnShard.boot("s", SCHEMA, store, pk_column="id", config=cfg)
    assert _count(shard2) == 10
    assert shard2.snap == shard.snap
    assert shard2.next_portion_id == shard.next_portion_id


def test_auto_compaction_trigger():
    shard = _shard(compact_portion_threshold=3)
    shard.commit([_write(shard, [1])])
    shard.commit([_write(shard, [2])])
    assert not shard.maybe_compact()
    shard.commit([_write(shard, [3])])
    assert shard.maybe_compact()
    assert len(shard.visible_portions()) == 1


def test_crash_mid_compaction_replays_to_precompaction_state():
    """Compaction outputs are WAL-staged and only activate at the
    compact_commit record: a crash anywhere mid-compaction (here: on the
    commit record itself, after every staged add) must boot back to the
    exact pre-compaction state — no lost rows, no duplicates."""

    class CrashingStore(MemBlobStore):
        armed = False

        def put(self, blob_id, data):
            if self.armed and b'"compact_commit"' in data:
                raise RuntimeError("injected crash before commit record")
            super().put(blob_id, data)

    store = CrashingStore()
    shard = ColumnShard(
        "s", SCHEMA, store, pk_column="id", upsert=True,
        config=ShardConfig(compact_portion_threshold=10**9,
                           max_portion_rows=64, checkpoint_interval=4),
    )
    # overlapping upserts: compaction will merge + dedup
    for i in range(5):
        wid = shard.write({
            "id": np.arange(0, 200, 2, dtype=np.int64),
            "ts": np.full(100, 100, dtype=np.int32),
            "tag": np.zeros(100, dtype=np.int64),
            "val": np.full(100, i, dtype=np.int64),
        })
        shard.commit([wid])
    pre = _count(shard)
    store.armed = True
    with pytest.raises(RuntimeError):
        shard.compact()
    store.armed = False
    booted = ColumnShard.boot(
        "s", SCHEMA, store, pk_column="id",
        config=ShardConfig(compact_portion_threshold=10**9,
                           max_portion_rows=64, checkpoint_interval=4),
    )
    booted.upsert = True
    assert _count(booted) == pre
    # staged blobs were orphan-collected; a fresh compaction completes
    booted.compact()
    assert _count(booted) == pre


# ---------------- device block cache (HBM page-cache analog) ----------------


def _sum_val(shard, snap=None):
    prog = Program((
        GroupByStep(keys=(), aggs=(AggSpec(Agg.SUM, "val", "s"),)),
    ))
    return int(shard.scan(prog, snap).cols["s"][0][0])


def test_block_cache_hits_and_invalidates_on_commit():
    """Warm scans reuse device-resident blocks; a commit changes the
    visible portion set, so the next scan must see the new rows (the
    shared_sausagecache analog keyed by immutable portion ids)."""
    shard = _shard(scan_cache_bytes=64 << 20)
    shard.commit([_write(shard, [1, 2, 3], vals=[10, 20, 30])])
    snap1 = shard.snap
    assert _sum_val(shard) == 60
    assert len(shard.block_cache) == 1
    # warm scan: same result, served from the cached blocks
    assert _sum_val(shard) == 60
    # new commit -> new key -> fresh read sees the extra rows
    shard.commit([_write(shard, [4], vals=[40])])
    assert _sum_val(shard) == 100
    # a warm scan AT THE OLD SNAPSHOT must keep resolving through its
    # own entry (same portion set as the first scan), never the newer
    # commit's blocks — and vice versa
    assert _sum_val(shard, snap1) == 60
    assert _sum_val(shard, snap1) == 60
    assert _sum_val(shard) == 100
    assert len(shard.block_cache) == 2
    # GC of superseded portions frees their now-unreachable entries
    shard.compact()
    shard.gc_blobs(keep_snap=shard.snap)
    assert _sum_val(shard) == 100
    live = set(shard.portions)
    assert all(set(k[0]) <= live for k in shard.block_cache)


def test_block_cache_correct_after_compaction_and_ttl():
    shard = _shard(scan_cache_bytes=64 << 20,
                   compact_portion_threshold=10 ** 9)
    for i in range(4):
        shard.commit([_write(shard, [i * 10 + 1, i * 10 + 2],
                             ts=[50 + i, 50 + i])])
    before = _count(shard)
    assert _sum_val(shard) > 0
    shard.compact()
    assert _count(shard) == before  # post-compaction portions re-read
    evicted = shard.evict_ttl(52)
    assert evicted > 0
    assert _count(shard) < before


def test_block_cache_respects_budget():
    """Entries beyond the byte budget evict LRU; an over-budget scan
    is never pinned at all."""
    shard = _shard(scan_cache_bytes=1)  # nothing fits
    shard.commit([_write(shard, list(range(100)))])
    assert _count(shard) == 100
    assert len(shard.block_cache) == 0
    assert shard.block_cache.nbytes == 0


def test_block_cache_off_by_default_on_cpu():
    shard = _shard()
    shard.commit([_write(shard, [1, 2])])
    assert _count(shard) == 2
    assert len(shard.block_cache) == 0
