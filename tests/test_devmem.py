"""Device-memory analyzer (analysis/devmem): one firing and one clean
fixture per M-rule, plus the suppression surfaces (``@budget_ok``
decorator, ``# ydb-lint: disable=M00x`` pragma, ``# ydb-devmem:
device-module`` trace-context declaration) and the interprocedural
charge-coverage fixpoint."""

import textwrap

from ydb_tpu.analysis import devmem


def _check(src: str, filename: str = "seed.py"):
    return devmem.check_source(textwrap.dedent(src), filename)


def _codes(src: str):
    return [f.code for f in _check(src)]


# ---------------- M001: unbudgeted device alloc ----------------


def test_m001_fires_on_bare_creator():
    codes = _codes("""
        import jax.numpy as jnp

        def stage(n):
            return jnp.zeros(n)
    """)
    assert "M001" in codes


def test_m001_clean_when_function_charges():
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def stage(n):
            with memsan.seam("staging"):
                out = jnp.zeros(n)
            memsan.charge(memsan.nbytes_of(out), "staging")
            return out
    """) == []


def test_m001_clean_under_budget_ok():
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import budget_ok

        @budget_ok("bounded scratch: one int32[8] vector")
        def stage(n):
            return jnp.zeros(n)
    """) == []


def test_m001_clean_under_jit():
    assert _codes("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return jnp.zeros(x.shape) + x
    """) == []


def test_m001_clean_for_nested_def_handed_to_jit():
    assert _codes("""
        import jax
        import jax.numpy as jnp

        def build(cap):
            def dispatch(x):
                return jnp.zeros(cap) + x
            return jax.jit(dispatch)
    """) == []


def test_m001_from_numpy_call_counts_as_creator():
    codes = _codes("""
        def ingest(arrays, schema):
            return TableBlock.from_numpy(arrays, schema)
    """)
    assert "M001" in codes


def test_m001_charging_caller_covers_helper():
    """The interprocedural fixpoint: a helper whose every caller
    charges inherits the charge."""
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def _helper(n):
            return jnp.zeros(n)

        def stage(n):
            with memsan.seam("staging"):
                out = _helper(n)
            memsan.charge(memsan.nbytes_of(out), "staging")
            return out
    """) == []


def test_m001_pragma_suppresses_site():
    assert _codes("""
        import jax.numpy as jnp

        def stage(n):
            return jnp.zeros(n)  # ydb-lint: disable=M001
    """) == []


def test_device_module_pragma_declares_trace_context():
    assert _codes("""
        # ydb-devmem: device-module
        import jax.numpy as jnp

        def kernel(x):
            return jnp.zeros(x.shape)
    """) == []


# ---------------- M002: use after donation ----------------


def test_m002_fires_on_use_after_donating_call():
    codes = _codes("""
        import jax

        def run(self, block):
            fn = jax.jit(_fresh(), donate_argnums=(0,))
            out = fn(block)
            return block.length
    """)
    assert "M002" in codes


def test_m002_clean_when_donated_input_dropped():
    assert _codes("""
        import jax

        def run(self, block):
            fn = jax.jit(_fresh(), donate_argnums=(0,))
            out = fn(block)
            return out
    """) == []


# ---------------- M003: donated-jit rebuild hazard ----------------


def test_m003_fires_on_bound_method_jit_on_grow_path():
    codes = _codes("""
        import jax

        class Plan:
            def grow(self, cap):
                self._fn = jax.jit(self._dispatch)
    """)
    assert "M003" in codes


def test_m003_fires_on_donating_reused_function_object():
    codes = _codes("""
        import jax

        class Plan:
            def build(self, fn):
                self._fn = jax.jit(fn, donate_argnums=(0,))
    """)
    assert "M003" in codes


def test_m003_clean_for_one_time_init_jit():
    assert _codes("""
        import jax

        class Plan:
            def __init__(self):
                self._fn = jax.jit(self._dispatch)
    """) == []


def test_m003_clean_for_fresh_local_wrapper():
    assert _codes("""
        import jax

        class Plan:
            def grow(self, cap):
                def _dispatch(x):
                    return self._step(x, cap)
                self._fn = jax.jit(_dispatch, donate_argnums=(0,))
    """) == []


# ---------------- M004: unrounded data-dependent shape ----------------


def test_m004_fires_on_len_sized_alloc():
    codes = _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def stage(xs):
            with memsan.seam("staging"):
                out = jnp.zeros(len(xs))
            memsan.charge(memsan.nbytes_of(out), "staging")
            return out
    """)
    assert "M004" in codes


def test_m004_clean_through_shape_class():
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def stage(xs):
            with memsan.seam("staging"):
                out = jnp.zeros(shape_class(len(xs)))
            memsan.charge(memsan.nbytes_of(out), "staging")
            return out
    """) == []


# ---------------- M005: device closure into a pool ----------------


def test_m005_fires_on_lambda_capturing_device_array():
    codes = _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def submit_work(pool, host):
            with memsan.seam("staging"):
                dev = jnp.asarray(host)
            memsan.charge(memsan.nbytes_of(dev), "staging")
            pool.submit(lambda: dev + 1)
    """)
    assert "M005" in codes


def test_m005_clean_when_task_stages_inside():
    assert _codes("""
        def submit_work(pool, host):
            pool.submit(lambda: stage_and_run(host))
    """) == []


# ---------------- M006: grow-only device container ----------------


def test_m006_fires_on_valveless_device_cache():
    codes = _codes("""
        import jax.numpy as jnp

        class Cache:
            def __init__(self):
                self._store = {}

            def put(self, key, host):
                self._store[key] = jnp.asarray(host)  # ydb-lint: disable=M001
    """)
    assert "M006" in codes


def test_m006_clean_with_eviction_valve():
    assert _codes("""
        import jax.numpy as jnp

        class Cache:
            def __init__(self):
                self._store = {}

            def put(self, key, host):
                self._store[key] = jnp.asarray(host)  # ydb-lint: disable=M001

            def evict(self, key):
                del self._store[key]
    """) == []


# ---------------- M007: per-dispatch aux staging ----------------


def test_m007_fires_on_inline_aux_staging():
    codes = _codes("""
        import jax.numpy as jnp

        def dispatch(self, cp):
            staged = {}
            for k in cp.aux:
                staged[k] = jnp.asarray(cp.aux[k])
            return self._fn(staged)
    """)
    assert "M007" in codes


def test_m007_clean_inside_device_aux_itself():
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def device_aux(aux):
            out = {}
            with memsan.seam("staging"):
                for k in aux:
                    out[k] = jnp.asarray(aux[k])
            memsan.charge(memsan.nbytes_of(out), "staging")
            return out
    """) == []


# ---------------- M008: device buffer across yield ----------------


def test_m008_fires_on_buffer_held_across_yield():
    codes = _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def stream(host_blocks):
            with memsan.seam("staging"):
                dev = jnp.asarray(host_blocks[0])
            memsan.charge(memsan.nbytes_of(dev), "staging")
            yield "header"
            yield dev
    """)
    assert "M008" in codes


def test_m008_clean_when_staged_per_iteration():
    assert _codes("""
        import jax.numpy as jnp
        from ydb_tpu.analysis import memsan

        def stream(host_blocks):
            for b in host_blocks:
                with memsan.seam("staging"):
                    dev = jnp.asarray(b)
                memsan.charge(memsan.nbytes_of(dev), "staging")
                yield dev
    """) == []


# ---------------- shared surfaces ----------------


def test_syntax_error_reported_as_m000():
    findings = _check("def broken(:\n")
    assert [f.code for f in findings] == ["M000"]


def test_runtime_scope_keeps_runtime_packages_only(tmp_path):
    inside = tmp_path / "ydb_tpu" / "engine" / "scan.py"
    outside = tmp_path / "ydb_tpu" / "workload" / "gen.py"
    fixture = tmp_path / "fixtures" / "seed.py"
    for p in (inside, outside, fixture):
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("x = 1\n")
    kept = {str(p) for p in devmem.runtime_scope(
        [inside, outside, fixture])}
    assert str(inside) in kept
    assert str(fixture) in kept       # non-tree paths pass through
    assert str(outside) not in kept   # non-runtime package dropped


def test_findings_carry_the_unified_schema():
    (finding,) = [f for f in _check("""
        import jax.numpy as jnp

        def stage(n):
            return jnp.zeros(n)
    """) if f.code == "M001"]
    d = finding.to_dict()
    assert set(d) == {"file", "line", "col", "code", "name", "message"}
    assert d["name"] == devmem.RULES["M001"]
