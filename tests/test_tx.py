"""Distributed commit + sharded table tests (coordinator/mediator plane).

Capability mirror of the reference's coordinator/mediator + datashard
ordering tests (coordinator_volatile_ut.cpp, datashard_ut_order.cpp):
atomic cross-shard visibility at plan steps, abort-on-failure, consistent
snapshots during background churn."""

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.shard import ShardConfig
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa.program import Program, lit
from ydb_tpu.tx import Coordinator, ShardedTable

SCHEMA = dtypes.schema(
    ("k", dtypes.INT64, False),
    ("ts", dtypes.DATE, False),
    ("v", dtypes.INT64),
)

COUNT = Program((
    GroupByStep(keys=(), aggs=(
        AggSpec(Agg.COUNT_ALL, None, "n"),
        AggSpec(Agg.SUM, "v", "s"),
    )),
))


def _table(n_shards=4, **cfg):
    coord = Coordinator()
    t = ShardedTable(
        "t", SCHEMA, MemBlobStore(), coord, n_shards=n_shards,
        pk_column="k", ttl_column="ts",
        config=ShardConfig(**cfg) if cfg else None,
    )
    return t, coord


def _ins(t, ks, ts=None, vs=None):
    n = len(ks)
    return t.insert({
        "k": np.asarray(ks, dtype=np.int64),
        "ts": np.asarray(ts if ts is not None else [100] * n, dtype=np.int32),
        "v": np.asarray(vs if vs is not None else ks, dtype=np.int64),
    })


def test_atomic_cross_shard_commit():
    t, coord = _table()
    r1 = _ins(t, list(range(100)))
    assert r1.committed
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 100
    # rows spread over multiple shards
    occupied = [s for s in t.shards if s.visible_portions()]
    assert len(occupied) >= 2
    # snapshot before the tx sees nothing on ANY shard
    res0 = t.scan(COUNT, snap=r1.step - 1)
    assert int(res0.cols["n"][0][0]) == 0


def test_snapshot_isolation_across_txs():
    t, coord = _table()
    r1 = _ins(t, [1, 2, 3])
    r2 = _ins(t, [10, 20, 30], vs=[100, 100, 100])
    assert r2.step > r1.step
    assert int(t.scan(COUNT, snap=r1.step).cols["n"][0][0]) == 3
    assert int(t.scan(COUNT, snap=r2.step).cols["n"][0][0]) == 6
    assert int(t.scan(COUNT).cols["s"][0][0]) == 6 + 300


def test_abort_releases_all_participants():
    t, coord = _table(n_shards=2)
    # sabotage one shard's prepare by droppings its buffer mid-flight
    wid0 = t.shards[0].write({
        "k": np.array([2], dtype=np.int64),
        "ts": np.array([1], dtype=np.int32),
        "v": np.array([2], dtype=np.int64),
    })

    class Broken:
        def prepare(self, args):
            raise RuntimeError("disk full")

        def abort(self, token):
            pass

        def commit_at(self, token, step):  # pragma: no cover
            raise AssertionError("must not commit")

    res = coord.commit([t.shards[0], Broken()], [[wid0], [99]])
    assert not res.committed and "disk full" in res.error
    # shard 0's write was aborted: nothing visible, buffer drained
    assert int(t.scan(COUNT).cols["n"][0][0]) == 0
    assert t.shards[0]._insert_buffer == {}


def test_background_churn_keeps_snapshots_consistent():
    t, coord = _table(n_shards=2, compact_portion_threshold=2)
    steps = []
    for i in range(6):
        steps.append(_ins(t, [i * 10 + 1, i * 10 + 2]).step)
    t.run_background()  # compactions take steps from the coordinator
    # old snapshots still read exactly their prefix
    for i, s in enumerate(steps):
        n = int(t.scan(COUNT, snap=s).cols["n"][0][0])
        assert n == (i + 1) * 2
    # TTL eviction also rides coordinator steps (all rows have ts=100)
    pre = coord.read_snapshot()
    evicted = t.run_background(ttl_cutoff=101)["evicted"]
    assert evicted == 12
    assert int(t.scan(COUNT).cols["n"][0][0]) == 0
    assert int(t.scan(COUNT, snap=pre).cols["n"][0][0]) == 12


def test_ttl_eviction_correctness_coordinated():
    t, coord = _table(n_shards=2)
    _ins(t, [1, 2], ts=[10, 50])
    _ins(t, [3, 4], ts=[60, 5])
    pre = coord.read_snapshot()
    total = sum(s.evict_ttl(30) for s in t.shards)
    assert total == 2
    assert int(t.scan(COUNT).cols["n"][0][0]) == 2
    assert int(t.scan(COUNT, snap=pre).cols["n"][0][0]) == 4


def test_string_columns_shared_dictionary():
    coord = Coordinator()
    sch = dtypes.schema(("k", dtypes.INT64, False), ("s", dtypes.STRING))
    t = ShardedTable("t2", sch, MemBlobStore(), coord, n_shards=3,
                     pk_column="k")
    t.insert({"k": np.arange(10, dtype=np.int64),
              "s": [b"a", b"b"] * 5})
    from ydb_tpu.ssa.program import DictPredicate

    prog = Program((
        FilterStep(DictPredicate("s", "eq", b"a")),
        GroupByStep(keys=(), aggs=(AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    assert int(t.scan(prog).cols["n"][0][0]) == 5


def test_coordinator_restart_preserves_step_monotonicity():
    """VERDICT r2 weak #6: a rebooted Coordinator(store) must resume
    strictly after every step it might ever have assigned, so shard
    snapshots never run backwards across a coordinator crash."""
    store = MemBlobStore()
    coord = Coordinator(store, reserve=8)
    t = ShardedTable("t", SCHEMA, store, coord, n_shards=2, pk_column="k")
    r = _ins(t, list(range(20)))
    assert r.committed
    last_step = coord.last_step
    assert coord.read_snapshot() >= r.step

    # crash: drop the coordinator object, reboot from the same store
    coord2 = Coordinator(store, reserve=8)
    assert coord2.last_step >= last_step          # never reassigns a step
    assert coord2.read_snapshot() >= r.step       # barrier stays readable
    _, step = coord2.plan()
    assert step > last_step

    # rebind the table (and every shard's background snapshot source) to
    # the rebooted coordinator, then prove post-crash commits and
    # background compaction still see a monotonic clock
    t.coordinator = coord2
    for s in t.shards:
        s.snap_source = coord2.background_plan
    r2 = _ins(t, list(range(20, 40)))
    assert r2.committed and r2.step > r.step
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 40
    # background compaction takes steps from the NEW clock
    for s in t.shards:
        s.compact()
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 40
    assert all(s.snap <= coord2.last_step for s in t.shards)


def test_coordinator_reserve_batches_persistence():
    """Hi-lo reservation: one persisted put per `reserve` steps, and the
    persisted ceiling always covers every handed-out step."""
    store = MemBlobStore()
    coord = Coordinator(store, reserve=16)
    for _ in range(40):
        _, step = coord.plan()
        ceiling = int(store.get(Coordinator.STEP_KEY).decode())
        assert ceiling >= step
