"""Whole-plan single-trace fusion (ssa.plan_fuse): fused vs per-node
walk bit-identity across TPC-H shapes and NULL patterns, shape-class
compile-cache reuse, expand-join overflow growth, unfusible fallback,
EXPLAIN ANALYZE surface, and the YDB_TPU_FUSE_PLAN escape hatch."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan.executor import Database, execute_plan
from ydb_tpu.plan.nodes import ExpandJoin, LookupJoin, TableScan, \
    Transform
from ydb_tpu.ssa import (
    Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op, Program,
    plan_fuse,
)
from ydb_tpu.ssa.program import AssignStep, ProjectStep, SortStep, \
    UdfCall, lit
from ydb_tpu.obs import profile as profile_mod
from ydb_tpu.workload import tpch


def make_db(data: "tpch.TpchData") -> Database:
    return Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts)


@pytest.fixture(scope="module")
def tpch_db():
    data = tpch.TpchData(sf=0.002, seed=5)
    return make_db(data), data


def run_ab(plan, db):
    """Execute fused then per-node; returns (fused, walk) blocks."""
    old = plan_fuse.FUSE_FORCE
    try:
        plan_fuse.FUSE_FORCE = True
        fused = execute_plan(plan, db, use_dq=False)
        plan_fuse.FUSE_FORCE = False
        walk = execute_plan(plan, db, use_dq=False)
    finally:
        plan_fuse.FUSE_FORCE = old
    return fused, walk


def assert_identical(a, b):
    """Bit-identity: same schema, same live rows, same values AND the
    same validity — positionally (every tested plan orders its output
    deterministically)."""
    assert a.schema.names == b.schema.names
    assert int(a.length) == int(b.length)
    av, aok = a.to_numpy(), a.validity_numpy()
    bv, bok = b.to_numpy(), b.validity_numpy()
    for name in a.schema.names:
        np.testing.assert_array_equal(aok[name], bok[name],
                                      err_msg=f"validity({name})")
        np.testing.assert_array_equal(
            np.where(aok[name], av[name], 0),
            np.where(bok[name], bv[name], 0), err_msg=name)


# ---------------- bit-identity across TPC-H shapes ----------------


def test_q3_joins_topk_bit_identity(tpch_db):
    """Semi + inner join feeding a grouped top-10: the acceptance
    shape."""
    db, _ = tpch_db
    plan = tpch.q3_plan()
    assert plan_fuse.plan_signature(plan, db) is not None
    fused, walk = run_ab(plan, db)
    assert int(fused.length) == 10
    assert_identical(fused, walk)


def test_q1_agg_avg_bit_identity(tpch_db):
    """Q1's SUM/AVG/COUNT battery (the AVG final fixup) + sort."""
    db, _ = tpch_db
    plan = Transform(TableScan("lineitem"), tpch.q1_program())
    fused, walk = run_ab(plan, db)
    assert int(fused.length) > 0
    assert_identical(fused, walk)


def test_q6_global_agg_bit_identity(tpch_db):
    db, _ = tpch_db
    plan = Transform(TableScan("lineitem"), tpch.q6_program())
    fused, walk = run_ab(plan, db)
    assert int(fused.length) == 1
    assert_identical(fused, walk)


def null_db(n=3000, seed=11):
    """Synthetic pair of tables with NULLs in group keys, agg inputs
    and join keys (a NULL key matches nothing)."""
    rng = np.random.default_rng(seed)
    t_schema = dtypes.schema(("k", dtypes.INT64), ("j", dtypes.INT64),
                             ("v", dtypes.INT64))
    d_schema = dtypes.schema(("dk", dtypes.INT64), ("w", dtypes.INT64))
    t_cols = {
        "k": rng.integers(0, 7, n).astype(np.int64),
        "j": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.integers(-100, 100, n).astype(np.int64),
    }
    t_valid = {
        "k": rng.random(n) > 0.1,
        "j": rng.random(n) > 0.15,
        "v": rng.random(n) > 0.2,
    }
    d_cols = {
        "dk": np.arange(50, dtype=np.int64),
        "w": rng.integers(0, 10, 50).astype(np.int64),
    }
    d_valid = {"dk": np.ones(50, bool), "w": rng.random(50) > 0.3}
    return Database(sources={
        "t": ColumnSource(t_cols, t_schema, validity=t_valid),
        "d": ColumnSource(d_cols, d_schema, validity=d_valid),
    })


def test_null_patterns_join_agg_bit_identity():
    db = null_db()
    plan = Transform(
        LookupJoin(
            probe=TableScan("t"), build=TableScan("d"),
            probe_keys=("j",), build_keys=("dk",),
            payload=("w",), kind="left",
        ),
        Program((
            AssignStep("vw", Call(Op.ADD, Col("v"), Col("w"))),
            GroupByStep(
                keys=("k",),
                aggs=(AggSpec(Agg.SUM, "vw", "s"),
                      AggSpec(Agg.AVG, "v", "a"),
                      AggSpec(Agg.COUNT, "w", "c"),
                      AggSpec(Agg.COUNT_ALL, None, "n")),
            ),
            SortStep(keys=("k",)),
        )))
    fused, walk = run_ab(plan, db)
    # NULL group key forms its own group; NULL-fed aggs stay NULL-aware
    assert int(fused.length) == 8
    assert_identical(fused, walk)


def test_expand_join_overflow_grows_and_matches():
    """An expand join whose true fanout exceeds fanout_hint: the fused
    dispatch overflows its static capacity, grows it, re-stages and
    re-dispatches — results still bit-identical to the walk."""
    rng = np.random.default_rng(3)
    n_probe, n_build = 500, 4000
    p_schema = dtypes.schema(("pk", dtypes.INT64), ("pv", dtypes.INT64))
    b_schema = dtypes.schema(("bk", dtypes.INT64), ("bv", dtypes.INT64))
    db = Database(sources={
        "p": ColumnSource({
            "pk": rng.integers(0, 40, n_probe).astype(np.int64),
            "pv": rng.integers(0, 100, n_probe).astype(np.int64),
        }, p_schema),
        "b": ColumnSource({
            "bk": rng.integers(0, 40, n_build).astype(np.int64),
            "bv": rng.integers(0, 100, n_build).astype(np.int64),
        }, b_schema),
    })
    plan = Transform(
        ExpandJoin(
            probe=TableScan("p"), build=TableScan("b"),
            probe_keys=("pk",), build_keys=("bk",),
            probe_payload=("pk", "pv"), build_payload=("bv",),
            fanout_hint=1.0,  # true fanout ~100: forces overflow growth
        ),
        Program((
            GroupByStep(keys=("pk",),
                        aggs=(AggSpec(Agg.SUM, "bv", "s"),
                              AggSpec(Agg.COUNT_ALL, None, "n"))),
            SortStep(keys=("pk",)),
        )))
    sig = plan_fuse.plan_signature(plan, db)
    assert sig is not None
    fused, walk = run_ab(plan, db)
    assert_identical(fused, walk)
    # the grown capacity is kept on the cached plan for later statements
    key = sig.cache_key(db)
    cached = db._compile_cache[key]
    assert cached.expand_caps[0] > plan_fuse.DEFAULT_CAPACITY_QUANTUM


# ---------------- shape-class compile cache ----------------


def test_shape_class_sizes():
    q = plan_fuse.DEFAULT_CAPACITY_QUANTUM
    for n in (1, 1000, 1024, 8192, 8193, 60000, 600858):
        c = plan_fuse.shape_class(n)
        assert c >= n and c % q == 0
        if n > 8 * q:
            assert c <= n * 1.25 + q  # bounded dead padding
    assert plan_fuse.shape_class(1) == q
    # growing within a class must not change the class
    assert plan_fuse.shape_class(8193) == plan_fuse.shape_class(10000)


def test_shape_class_cache_hit_on_same_class_data():
    """Different data with the same shape-class vector reuses the
    compiled FusedPlan: no rebuild, compile_cache=hit, zero compile
    seconds."""
    data = tpch.TpchData(sf=0.002, seed=5)
    db = make_db(data)
    plan = tpch.q3_plan()

    def fuse_keys():
        return [k for k in db._compile_cache if k[0] == "plan_fuse"]

    old = plan_fuse.FUSE_FORCE
    try:
        plan_fuse.FUSE_FORCE = True
        first = execute_plan(plan, db, use_dq=False)
        assert len(fuse_keys()) == 1

        # same shape class, different rows AND different values: slice
        # a few hundred rows off lineitem and shuffle the remainder
        li = data.tables["lineitem"]
        n = len(li["l_orderkey"])
        keep = plan_fuse.shape_class(n) - plan_fuse.shape_class(n - 300)
        assert keep == 0  # sliced table stays in the class
        perm = np.random.default_rng(9).permutation(n - 300)
        db.sources["lineitem"] = ColumnSource(
            {k: v[:n - 300][perm] for k, v in li.items()},
            data.schema("lineitem"), data.dicts)

        with profile_mod.profiled("q3") as h:
            second = execute_plan(plan, db, use_dq=False)
        assert len(fuse_keys()) == 1  # reused, not rebuilt
        p = h.profile
        assert p.compile_cache == "hit"
        assert p.compile_seconds == 0.0
        assert p.fused_stages == 6 and p.fragments_elided == 5
        assert not any(s["name"] == "ssa.compile" for s in p.spans)

        plan_fuse.FUSE_FORCE = False
        walk = execute_plan(plan, db, use_dq=False)
    finally:
        plan_fuse.FUSE_FORCE = old
    assert_identical(second, walk)
    assert int(first.length) == 10


def test_different_class_recompiles():
    """A table in a different shape class gets its own FusedPlan."""
    data = tpch.TpchData(sf=0.002, seed=5)
    db = make_db(data)
    plan = tpch.q3_plan()
    old = plan_fuse.FUSE_FORCE
    try:
        plan_fuse.FUSE_FORCE = True
        execute_plan(plan, db, use_dq=False)
        li = data.tables["lineitem"]
        n = len(li["l_orderkey"])
        half = n // 2
        assert plan_fuse.shape_class(half) != plan_fuse.shape_class(n)
        db.sources["lineitem"] = ColumnSource(
            {k: v[:half] for k, v in li.items()},
            data.schema("lineitem"), data.dicts)
        execute_plan(plan, db, use_dq=False)
    finally:
        plan_fuse.FUSE_FORCE = old
    keys = [k for k in db._compile_cache if k[0] == "plan_fuse"]
    assert len(keys) == 2


# ---------------- fallback rules ----------------


def test_udf_subtree_not_fusible_falls_back(tpch_db):
    db, _ = tpch_db
    plan = Transform(
        TableScan("lineitem", Program((
            ProjectStep(("l_orderkey", "l_quantity")),
        ))),
        Program((
            AssignStep("q2", UdfCall(
                "double", (Col("l_quantity"),), dtypes.INT64,
                lambda a: a * 2)),
            GroupByStep(keys=("l_orderkey",),
                        aggs=(AggSpec(Agg.SUM, "q2", "s"),)),
            SortStep(keys=("l_orderkey",), limit=20),
        )))
    assert plan_fuse.plan_signature(plan, db) is None
    # forcing fusion on still executes (per-node walk fallback), and
    # matches the forced-off side
    fused, walk = run_ab(plan, db)
    assert_identical(fused, walk)


def test_oversized_table_not_fusible(tpch_db, monkeypatch):
    db, _ = tpch_db
    monkeypatch.setattr(plan_fuse, "FUSE_MAX_ROWS", 100)
    assert plan_fuse.plan_signature(tpch.q3_plan(), db) is None


def test_missing_table_not_fusible(tpch_db):
    db, _ = tpch_db
    plan = Transform(TableScan("no_such_table"),
                     Program((ProjectStep(("x",)),)))
    assert plan_fuse.plan_signature(plan, db) is None


# ---------------- EXPLAIN ANALYZE / session surface ----------------


def _ev_cluster():
    from ydb_tpu.kqp.session import Cluster

    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE ev (id int64, ts int64, v int64, "
              "PRIMARY KEY (id)) WITH (shards = 2)")
    for base in (0, 100, 200):
        vals = ", ".join(f"({base + i}, {base + i}, {(base + i) * 3})"
                         for i in range(8))
        s.execute(f"INSERT INTO ev VALUES {vals}")
    return c


def test_explain_analyze_reports_fusion():
    c = _ev_cluster()
    s = c.session()
    sql = ("EXPLAIN ANALYZE SELECT ts, sum(v) AS sv FROM ev "
           "WHERE ts >= 100 GROUP BY ts")
    txt = s.execute(sql)
    assert "fusion: fused_stages=2 fragments_elided=1" in txt
    p = s.last_profile
    assert p.fused_stages == 2 and p.fragments_elided == 1
    # the whole build is ONE compile span; the dispatch is ONE fused
    # computation under ONE plan.fuse span
    assert sum(1 for sp in p.spans if sp["name"] == "ssa.compile") == 1
    fuse = [sp for sp in p.spans if sp["name"] == "plan.fuse"]
    assert len(fuse) == 1
    assert fuse[0]["attrs"]["fused_stages"] == 2
    assert fuse[0]["attrs"]["compile_cache"] == "miss"
    # warm rerun: cached FusedPlan, no compile
    txt2 = s.execute(sql)
    assert "compile_cache=hit" in txt2
    assert "compile_seconds=0.000000" in txt2
    assert "fusion: fused_stages=2" in txt2


# ---------------- env gates ----------------


def test_fuse_plan_env_gate(monkeypatch):
    monkeypatch.setattr(plan_fuse, "FUSE_FORCE", None)
    monkeypatch.setenv("YDB_TPU_FUSE_PLAN", "0")
    assert not plan_fuse.fusion_enabled()
    data = tpch.TpchData(sf=0.002, seed=5)
    db = make_db(data)
    plan = tpch.q3_plan()
    with profile_mod.profiled("q3") as h:
        gated = execute_plan(plan, db, use_dq=False)
    assert not any(s["name"] == "plan.fuse" for s in h.profile.spans)
    assert h.profile.fused_stages == 0

    monkeypatch.setenv("YDB_TPU_FUSE_PLAN", "1")
    assert plan_fuse.fusion_enabled()
    with profile_mod.profiled("q3") as h:
        fused = execute_plan(plan, db, use_dq=False)
    assert any(s["name"] == "plan.fuse" for s in h.profile.spans)
    assert h.profile.fused_stages == 6
    assert_identical(fused, gated)
