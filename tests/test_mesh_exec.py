"""Distributed plan execution on the 8-device CPU mesh: grace-style
hash-repartition joins (VERDICT r4 item 3) and the portion store feeding
the mesh (item 4). Results must match the single-chip executor / oracle
bit-for-bit on integers."""

import numpy as np
import pytest

from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.parallel.dist import MeshScan
from ydb_tpu.parallel.mesh import make_mesh
from ydb_tpu.parallel.mesh_exec import MeshDatabase, MeshPlanExecutor
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

N_DEV = 8


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=0.005, seed=23)


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
    )


def _shard_source(data, table, s, n):
    """Round-robin row partition s of n for a table."""
    cols = data.tables[table]
    return ColumnSource(
        {k: v[s::n] for k, v in cols.items()},
        data.schema(table), data.dicts,
    )


@pytest.fixture(scope="module")
def mesh_db(data):
    return MeshDatabase(
        sources={
            t: [_shard_source(data, t, s, N_DEV) for s in range(N_DEV)]
            for t in data.tables
        },
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def single_db(data):
    return Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


def _match(mesh_res, ref_res, int_cols, float_cols=()):
    assert mesh_res.num_rows == ref_res.num_rows
    for c in int_cols:
        np.testing.assert_array_equal(
            np.asarray(mesh_res.cols[c][0]), np.asarray(ref_res.cols[c][0]),
            err_msg=c)
    for c in float_cols:
        np.testing.assert_allclose(
            np.asarray(mesh_res.cols[c][0], dtype=np.float64),
            np.asarray(ref_res.cols[c][0], dtype=np.float64),
            rtol=1e-9, err_msg=c)


@pytest.mark.slow  # per-stage 8-dev traces dominate single-core CI
def test_q3_mesh_join_matches_single_chip(data, catalog, mesh_db,
                                          single_db):
    plan = plan_select_full(parse(TPCH["q3"]), catalog).plan
    mesh = make_mesh(N_DEV)
    ex = MeshPlanExecutor(mesh_db, mesh)
    res = ex.execute(plan)
    ref = to_host(execute_plan(plan, single_db))
    _match(res, ref, ("l_orderkey", "revenue", "o_orderdate",
                      "o_shippriority"))


@pytest.mark.slow  # per-stage 8-dev traces dominate single-core CI
def test_q5_mesh_join_matches_single_chip(data, catalog, mesh_db,
                                          single_db):
    plan = plan_select_full(parse(TPCH["q5"]), catalog).plan
    mesh = make_mesh(N_DEV)
    ex = MeshPlanExecutor(mesh_db, mesh)
    res = ex.execute(plan)
    ref = to_host(execute_plan(plan, single_db))
    _match(res, ref, ("n_name", "revenue"))


def test_mesh_scan_from_portion_store(tmp_path, data):
    """Sharded ON-DISK table scanned via per-shard portion streams on the
    mesh: out-of-core and multi-chip compose (VERDICT r4 item 4)."""
    from ydb_tpu.engine.blobs import DirBlobStore
    from ydb_tpu.engine.reader import PortionStreamSource
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.engine.oracle import OracleTable, run_oracle

    li = data.tables["lineitem"]
    n = len(li["l_orderkey"])
    shards = []
    for s in range(N_DEV):
        store = DirBlobStore(str(tmp_path / f"s{s}"))
        shard = ColumnShard(
            f"s{s}", tpch.LINEITEM_SCHEMA, store, dicts=data.dicts,
            config=ShardConfig(compact_portion_threshold=10 ** 9,
                               portion_chunk_rows=1 << 10),
        )
        # several portions per shard so the stream really streams
        idx = np.arange(s, n, N_DEV)
        for piece in np.array_split(idx, 3):
            wid = shard.write({k: v[piece] for k, v in li.items()})
            shard.commit([wid])
        shards.append(shard)

    mesh = make_mesh(N_DEV)
    prog = tpch.q1_program()
    scan = MeshScan(prog, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    assert scan.partial.group_layout[0] == "dense_slots"
    sources = [
        PortionStreamSource(sh, sh.visible_portions(),
                            columns=scan.read_cols)
        for sh in shards
    ]
    res = scan.execute_sources(sources, block_rows=1 << 12)

    table = OracleTable(
        {k: (v, np.ones(len(v), dtype=bool)) for k, v in li.items()},
        tpch.LINEITEM_SCHEMA)
    ora = run_oracle(prog, table, data.dicts)
    assert res.num_rows == ora.num_rows
    for name in ("sum_qty", "sum_charge", "count_order"):
        np.testing.assert_allclose(
            np.asarray(res.cols[name][0], dtype=np.float64),
            np.asarray(ora.cols[name][0], dtype=np.float64), rtol=1e-9,
            err_msg=name)

    # compact layout (unbounded keys) takes the gather path
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program, SortStep

    prog2 = Program((
        GroupByStep(keys=("l_orderkey",), aggs=(
            AggSpec(Agg.SUM, "l_extendedprice", "total"),
            AggSpec(Agg.COUNT_ALL, None, "cnt"),
        )),
        SortStep(keys=("l_orderkey",)),
    ))
    scan2 = MeshScan(prog2, tpch.LINEITEM_SCHEMA, data.dicts, mesh=mesh)
    assert scan2.partial.group_layout[0] == "compact"
    sources2 = [
        PortionStreamSource(sh, sh.visible_portions(),
                            columns=scan2.read_cols)
        for sh in shards
    ]
    res2 = scan2.execute_sources(sources2, block_rows=1 << 12)
    ora2 = run_oracle(prog2, table, data.dicts)
    assert res2.num_rows == ora2.num_rows
    np.testing.assert_array_equal(
        np.asarray(res2.cols["l_orderkey"][0]),
        np.asarray(ora2.cols["l_orderkey"][0]))
    np.testing.assert_array_equal(
        np.asarray(res2.cols["total"][0]),
        np.asarray(ora2.cols["total"][0]))


def test_mesh_from_sql_session():
    """Cluster.enable_mesh routes session SELECTs (join AND scan+agg)
    SPMD over the mesh, with shard counts != device count grouped via
    device_partitions — results identical to the non-mesh path
    (VERDICT r4 item 4: the mesh reachable from SQL text)."""
    import numpy as np

    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.plan import executor as ex

    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE musers (id int64, grp int64, "
              "PRIMARY KEY (id)) WITH (shards = 3)")
    s.execute("CREATE TABLE morders (oid int64, uid int64, amount int64,"
              " PRIMARY KEY (oid)) WITH (shards = 5)")
    for i in range(0, 120, 30):
        s.execute("INSERT INTO musers VALUES " + ", ".join(
            f"({j}, {j % 4})" for j in range(i, i + 30)))
    for i in range(0, 600, 100):
        s.execute("INSERT INTO morders VALUES " + ", ".join(
            f"({j}, {j % 120}, {j % 13})" for j in range(i, i + 100)))
    q = ("SELECT u.grp AS g, SUM(o.amount) AS total, COUNT(*) AS n "
         "FROM morders o JOIN musers u ON o.uid = u.id "
         "GROUP BY u.grp ORDER BY g")
    q2 = ("SELECT o.uid AS u2, SUM(o.amount) AS t FROM morders o "
          "GROUP BY o.uid ORDER BY t DESC, u2 LIMIT 5")
    ref, ref2 = s.execute(q), s.execute(q2)
    c.enable_mesh()
    calls = []
    orig = ex._execute_plan_mesh

    def spy(p, d):
        r = orig(p, d)
        calls.append(r)
        return r

    ex._execute_plan_mesh = spy
    try:
        res, res2 = s.execute(q), s.execute(q2)
    finally:
        ex._execute_plan_mesh = orig
    # invoked AND succeeded (a None would mean a silent fallback to
    # DQ/recursive produced the matching rows, not the mesh)
    assert len(calls) == 2 and all(r is not None for r in calls), calls
    for col in ("g", "total", "n"):
        np.testing.assert_array_equal(
            np.asarray(res.cols[col][0]), np.asarray(ref.cols[col][0]),
            err_msg=col)
    for col in ("u2", "t"):
        np.testing.assert_array_equal(
            np.asarray(res2.cols[col][0]), np.asarray(ref2.cols[col][0]),
            err_msg=col)
