"""DQ stage-graph + actor runtime tests on the simulated multi-node
runtime (tier-2: deterministic dispatch, virtual time, interceptors)."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.dq import (
    HashPartition,
    ResultOutput,
    SourceInput,
    StageSpec,
    UnionAllInput,
    run_stage_graph,
)
from ydb_tpu.dq.spilling import Spiller
from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.runtime.actors import Actor, ActorSystem
from ydb_tpu.runtime.test_runtime import SimRuntime
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa import twophase
from ydb_tpu.ssa.program import Program, ProjectStep, SortStep, lit


class Echo(Actor):
    def __init__(self, reply=False):
        super().__init__()
        self.got = []
        self.reply = reply

    def receive(self, message, sender):
        self.got.append(message)
        if self.reply and isinstance(message, int) and sender is not None:
            self.send(sender, message + 1)


def test_actor_system_basics():
    sys = ActorSystem()
    a, b = Echo(), Echo(reply=True)
    ida, idb = sys.register(a), sys.register(b)
    sys.send(idb, 41, sender=ida)
    sys.run()
    assert b.got == [41]
    assert a.got == [42]


def test_sim_runtime_virtual_time_and_interception():
    rt = SimRuntime(n_nodes=2)
    a, b = Echo(), Echo(reply=True)
    ida = rt.system(1).register(a)
    idb = rt.system(2).register(b)

    # cross-node send
    rt.system(1).send(idb, 1, sender=ida)
    rt.dispatch()
    assert b.got == [1] and a.got == [2]

    # scheduled message fires only after virtual time advances
    rt.system(2).schedule(5.0, idb, "tick")
    rt.dispatch()
    assert "tick" not in b.got
    rt.advance_time(5.0)
    rt.dispatch()
    assert "tick" in b.got

    # interceptor can drop messages (race/failure interleaving hook)
    rt.observer = lambda env: "drop" if env.message == "lost" else "pass"
    rt.system(1).send(idb, "lost")
    rt.system(1).send(idb, "kept")
    rt.dispatch()
    assert "lost" not in b.got and "kept" in b.got


def _make_sources(n_parts=4, rows=3000, seed=5):
    rng = np.random.default_rng(seed)
    sch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    parts = []
    all_cols = {"k": [], "v": []}
    for p in range(n_parts):
        cols = {
            "k": rng.integers(0, 50, rows // n_parts),
            "v": rng.integers(0, 1000, rows // n_parts),
        }
        parts.append(ColumnSource(
            {k: np.asarray(v) for k, v in cols.items()}, sch))
        for k in all_cols:
            all_cols[k].append(cols[k])
    merged = {k: np.concatenate(v) for k, v in all_cols.items()}
    return sch, parts, merged


AGG = Program((
    FilterStep(Call(Op.GE, Col("v"), lit(100))),
    GroupByStep(keys=("k",), aggs=(
        AggSpec(Agg.SUM, "v", "total"),
        AggSpec(Agg.COUNT_ALL, None, "n"),
    )),
    SortStep(keys=("k",)),
))


def _run_two_stage(runtime, sch, parts, window=4, quota=64 << 20):
    """scan(partial agg) -> HashPartition(k) -> final agg -> result."""
    partial, final = twophase.split(AGG)
    # stage 0: partial agg per partition, shuffle by key
    s0 = StageSpec(
        program=partial, inputs=(SourceInput("t"),),
        output=HashPartition(("k",)), tasks=len(parts),
    )
    # stage 1: merge partials per key bucket
    s1 = StageSpec(
        program=None, inputs=(UnionAllInput(0),),
        output=HashPartition(("k",)), tasks=2,
        final_program=final,
    )
    # stage 2: gather buckets into the ordered result
    s2 = StageSpec(
        program=None, inputs=(UnionAllInput(1),),
        output=ResultOutput(), tasks=1,
        final_program=Program((SortStep(keys=("k",)),)),
    )
    return run_stage_graph(
        [s0, s1, s2], {"t": parts}, runtime,
        window=window, spill_quota_bytes=quota,
    )


def test_stage_graph_distributed_agg_matches_oracle():
    sch, parts, merged = _make_sources()
    rt = SimRuntime(n_nodes=3)
    res = _run_two_stage(rt, sch, parts)
    ora = run_oracle(AGG, OracleTable(
        {k: (v, np.ones(len(v), dtype=bool)) for k, v in merged.items()},
        sch,
    ))
    np.testing.assert_array_equal(res.cols["k"][0], ora.cols["k"][0])
    np.testing.assert_array_equal(res.cols["total"][0],
                                  ora.cols["total"][0])
    np.testing.assert_array_equal(res.cols["n"][0], ora.cols["n"][0])


def test_stage_graph_with_tiny_window_and_spilling():
    """Credit window of 1 + zero memory quota: every parked block spills,
    results stay exact."""
    sch, parts, merged = _make_sources(n_parts=3, rows=1500)
    rt = SimRuntime(n_nodes=2)
    res = _run_two_stage(rt, sch, parts, window=1, quota=0)
    ora = run_oracle(AGG, OracleTable(
        {k: (v, np.ones(len(v), dtype=bool)) for k, v in merged.items()},
        sch,
    ))
    np.testing.assert_array_equal(res.cols["total"][0],
                                  ora.cols["total"][0])


def test_spiller_quota_and_roundtrip():
    sp = Spiller(mem_quota_bytes=100, prefix="s")
    small = {"a": np.arange(4, dtype=np.int64)}       # 32 bytes
    big = {"a": np.arange(100, dtype=np.int64)}       # 800 bytes -> spill
    s1 = sp.put(small)
    s2 = sp.put(big)
    assert sp.spill_count == 1
    np.testing.assert_array_equal(sp.get(s2)["a"], big["a"])
    np.testing.assert_array_equal(sp.get(s1)["a"], small["a"])
    with pytest.raises(KeyError):
        sp.get(s2)


def test_spiller_peek_does_not_consume():
    sp = Spiller(mem_quota_bytes=0, prefix="s")  # everything spills
    sid = sp.put({"a": np.arange(8, dtype=np.int64)})
    np.testing.assert_array_equal(sp.peek(sid)["a"], np.arange(8))
    np.testing.assert_array_equal(sp.peek(sid)["a"], np.arange(8))
    np.testing.assert_array_equal(sp.get(sid)["a"], np.arange(8))
    with pytest.raises(KeyError):
        sp.peek(sid)


def test_aggregate_accumulation_spills_beyond_quota():
    """Operator spilling (SURVEY §2.9 spilling-interface row): an agg
    stage's accumulated partial states live in the spiller, so a zero
    quota forces them to blobs while results stay exact."""
    sch, parts, merged = _make_sources(n_parts=3, rows=900)
    rt = SimRuntime(n_nodes=1)
    handle_res = _run_two_stage(rt, sch, parts, window=4, quota=0)
    ora = run_oracle(AGG, OracleTable(
        {k: (v, np.ones(len(v), dtype=bool)) for k, v in merged.items()},
        sch,
    ))
    np.testing.assert_array_equal(handle_res.cols["total"][0],
                                  ora.cols["total"][0])


def test_filter_map_stage_without_agg():
    sch, parts, merged = _make_sources(n_parts=2, rows=400)
    prog = Program((
        FilterStep(Call(Op.GE, Col("v"), lit(900))),
        ProjectStep(("k", "v")),
    ))
    rt = SimRuntime(n_nodes=2)
    s0 = StageSpec(program=prog, inputs=(SourceInput("t"),),
                   output=ResultOutput(), tasks=1)
    # single-task result stage reading the source directly
    res = run_stage_graph([s0], {"t": [parts[0]]}, rt)
    ora = run_oracle(prog, OracleTable(
        {k: (v[: 200], np.ones(200, dtype=bool))
         for k, v in merged.items()}, sch))
    assert res.num_rows == ora.num_rows


def test_source_partitions_differ_from_task_count():
    """Strided partition assignment: every partition is read exactly once
    whether tasks < partitions or tasks > partitions."""
    sch, parts, merged = _make_sources(n_parts=4, rows=2000)
    total = int(merged["v"].sum())
    prog = Program((GroupByStep(keys=(), aggs=(
        AggSpec(Agg.SUM, "v", "total"),)),))
    partial, final = twophase.split(prog)
    for tasks in (2, 3, 4, 6):
        rt = SimRuntime(n_nodes=2)
        s0 = StageSpec(program=partial, inputs=(SourceInput("t"),),
                       output=HashPartition(()), tasks=tasks)
        s1 = StageSpec(program=None, inputs=(UnionAllInput(0),),
                       output=ResultOutput(), tasks=1,
                       final_program=final)
        res = run_stage_graph([s0, s1], {"t": parts}, rt)
        assert int(res.cols["total"][0][0]) == total, tasks


def test_multi_consumer_stage_gets_full_stream():
    """A producer feeding two consumer stages must route the FULL stream
    to each (per-consumer channel groups), not split it across them."""
    sch, parts, merged = _make_sources(n_parts=2, rows=1000)
    total = int(merged["v"].sum())
    keyless = Program((GroupByStep(keys=(), aggs=(
        AggSpec(Agg.SUM, "v", "total"),)),))
    _, final = twophase.split(keyless)
    rt = SimRuntime(n_nodes=2)
    s0 = StageSpec(program=None, inputs=(SourceInput("t"),),
                   output=HashPartition(("k",)), tasks=2)
    # two independent consumers of stage 0, same output schema
    s1 = StageSpec(program=None, inputs=(UnionAllInput(0),),
                   output=HashPartition(()), tasks=2,
                   final_program=keyless)
    s2 = StageSpec(program=None, inputs=(UnionAllInput(0),),
                   output=HashPartition(()), tasks=1,
                   final_program=keyless)
    # result merges both totals: 2x the table sum iff each consumer saw
    # every row
    s3 = StageSpec(program=None, inputs=(UnionAllInput(1), UnionAllInput(2)),
                   output=ResultOutput(), tasks=1,
                   final_program=final)
    res = run_stage_graph([s0, s1, s2, s3], {"t": parts}, rt)
    assert int(res.cols["total"][0][0]) == 2 * total


def test_multi_input_schema_mismatch_raises():
    sch, parts, merged = _make_sources(n_parts=2, rows=200)
    rt = SimRuntime(n_nodes=1)
    s0 = StageSpec(program=Program((ProjectStep(("k",)),)),
                   inputs=(SourceInput("t"),),
                   output=HashPartition(("k",)), tasks=1)
    s1 = StageSpec(program=Program((ProjectStep(("v",)),)),
                   inputs=(SourceInput("t"),),
                   output=HashPartition(("v",)), tasks=1)
    s2 = StageSpec(program=None, inputs=(UnionAllInput(0), UnionAllInput(1)),
                   output=ResultOutput(), tasks=1)
    with pytest.raises(ValueError, match="share one schema"):
        run_stage_graph([s0, s1, s2], {"t": parts}, rt)
