"""Pallas group-by kernel: interpreter-mode equivalence with the
scatter path (real-TPU execution is covered by bench on hardware)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ydb_tpu.ssa import pallas_kernels
from ydb_tpu.ssa.kernels import scatter_sum


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32])
def test_grouped_sum_matches_scatter(dtype):
    rng = np.random.default_rng(4)
    n, k = 3000, 37
    vals = jnp.asarray(rng.integers(0, 100, n), dtype=dtype)
    gid = jnp.asarray(rng.integers(0, k, n), dtype=jnp.int32)
    valid = jnp.asarray(rng.random(n) < 0.8)
    ref = scatter_sum(vals, valid, gid, k, dtype=dtype)
    got = pallas_kernels.scatter_sum_pallas(vals, valid, gid, k,
                                            dtype=dtype, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6)


def test_grouped_sum_edge_shapes():
    # non-multiple-of-tile row count, single group, empty-ish input
    vals = jnp.asarray(np.ones(5, dtype=np.float32))
    gid = jnp.asarray(np.zeros(5, dtype=np.int32))
    out = pallas_kernels.grouped_sum(vals, gid, 1, interpret=True)
    assert float(out[0]) == 5.0
    # all rows dropped (gid beyond num_groups)
    gid2 = jnp.asarray(np.full(5, 99, dtype=np.int32))
    out = pallas_kernels.grouped_sum(vals, gid2, 3, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), [0, 0, 0])


def test_gating():
    assert not pallas_kernels.supported(jnp.int64, 10)   # exactness
    assert not pallas_kernels.supported(jnp.float32, 10**6)  # VMEM
    assert pallas_kernels.supported(jnp.float32, 2048)
