"""Table / KeyValue / FederationDiscovery gRPC services (the last of
the reference's 16 public API services: ydb_table_v1.proto —
rpc_create_table/rpc_execute_data_query/rpc_load_rows/rpc_read_table;
ydb_keyvalue_v1.proto; ydb_federation_discovery_v1.proto)."""

import numpy as np
import pyarrow as pa
import pytest

from ydb_tpu.api.client import ApiError, Driver
from ydb_tpu.api.server import make_server
from ydb_tpu.kqp.session import Cluster


@pytest.fixture
def served():
    cluster = Cluster()
    server, port = make_server(cluster, port=0)
    server.start()
    driver = Driver(f"127.0.0.1:{port}")
    yield cluster, driver
    driver.close()
    server.stop(0)


def test_table_ddl_lifecycle(served):
    _cluster, driver = served
    t = driver.table_client()
    t.create_table(
        "orders",
        [("id", "int64", True), ("who", "string", False),
         ("amt", "float64", False)],
        primary_key=["id"], store="column", shards=2)
    d = driver.scheme_client().describe_table("/orders")
    assert d.shards == 2 and d.store == "column"
    ver = t.alter_table("orders", [("note", "string")])
    assert ver > 1
    d2 = driver.scheme_client().describe_table("/orders")
    assert "note" in [c.name for c in d2.columns]
    # duplicate create surfaces as an error, not a crash
    with pytest.raises(ApiError):
        t.create_table("orders", [("id", "int64", True)],
                       primary_key=["id"])
    t.drop_table("orders")
    with pytest.raises(ApiError):
        driver.scheme_client().describe_table("/orders")


def test_execute_data_query_tx_control(served):
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("acct", [("id", "int64", True),
                            ("bal", "int64", False)],
                   primary_key=["id"], store="row")
    (_, committed), tx = t.execute(
        "INSERT INTO acct VALUES (1, 100), (2, 50)")
    assert committed and tx == ""
    # interactive tx: begin -> statements under tx_id -> commit
    _, tx = t.execute("UPDATE acct SET bal = bal - 30 WHERE id = 1",
                      begin=True)
    assert tx
    # another session sees nothing while the tx is open
    other = driver.table_client()
    out, _ = other.execute("SELECT bal FROM acct ORDER BY id")
    assert out.column("bal").to_pylist() == [100, 50]
    (_, committed), tx3 = t.execute(
        "UPDATE acct SET bal = bal + 30 WHERE id = 2",
        tx_id=tx, commit=True)
    assert committed and tx3 == ""
    out, _ = other.execute("SELECT bal FROM acct ORDER BY id")
    assert out.column("bal").to_pylist() == [70, 80]
    # unknown tx id is rejected
    with pytest.raises(ApiError):
        t.execute("SELECT 1 AS one", tx_id="tx-999")


def test_bulk_upsert_and_stream_read(served):
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("ev", [("id", "int64", True),
                          ("tag", "string", False),
                          ("v", "float64", False)],
                   primary_key=["id"], store="column", shards=2)
    n = 10_000
    at = pa.table({
        "id": pa.array(np.arange(n, dtype=np.int64)),
        "tag": pa.array([f"t{i % 7}" for i in range(n)]),
        "v": pa.array(np.linspace(0.0, 1.0, n)),
    })
    assert t.bulk_upsert("ev", at) == n
    out, _ = t.execute("SELECT count(*) AS c, sum(v) AS s FROM ev")
    assert out.column("c").to_pylist() == [n]
    assert abs(out.column("s").to_pylist()[0] - n / 2) < 1.0
    # streaming ReadTable: batches reassemble to the full table
    got = pa.concat_tables(
        t.read_table("ev", columns=["id", "tag"], batch_rows=2048))
    assert got.num_rows == n
    assert sorted(got.column("id").to_pylist()) == list(range(n))
    assert got.column("tag").to_pylist()[:3] is not None
    # error path: unknown table
    with pytest.raises(ApiError):
        list(t.read_table("nope"))
    with pytest.raises(ApiError):
        t.bulk_upsert("nope", at)
    # missing column rejected
    with pytest.raises(ApiError):
        t.bulk_upsert("ev", at.drop_columns(["v"]))


def test_copy_table_and_explain(served):
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("src", [("id", "int64", True),
                           ("name", "string", False)],
                   primary_key=["id"], store="column")
    t.execute("INSERT INTO src VALUES (1, 'a'), (2, 'b'), (3, 'c')")
    assert t.copy_table("src", "dst") == 3
    out, _ = t.execute("SELECT id, name FROM dst ORDER BY id")
    assert out.column("name").to_pylist() == ["a", "b", "c"]
    # source unchanged, independent afterwards
    t.execute("INSERT INTO dst VALUES (4, 'd')")
    out, _ = t.execute("SELECT count(*) AS c FROM src")
    assert out.column("c").to_pylist() == [3]
    plan = t.explain("SELECT id FROM src WHERE id = 2")
    assert "src" in plan
    t.close()


def test_keyvalue_service(served):
    cluster, driver = served
    kv = driver.keyvalue_client()
    kv.create_volume("vol1")
    with pytest.raises(ApiError):
        kv.create_volume("vol1")  # duplicate
    kv.write("vol1", "a", b"1")
    kv.write("vol1", "b", b"2")
    kv.write("vol1", "c", b"3")
    assert kv.read("vol1", "b") == b"2"
    assert kv.read("vol1", "nope") is None
    assert kv.list_range("vol1", "a", "c") == [("a", b"1"),
                                               ("b", b"2")]
    assert kv.rename("vol1", "b", "bb")
    assert kv.read("vol1", "bb") == b"2"
    assert kv.delete_range("vol1", "a", "b") == 1
    assert kv.read("vol1", "a") is None
    with pytest.raises(ApiError):
        kv.write("ghost", "k", b"v")

    # durability: a NEW proxy over the same store still sees the data
    server2, port2 = make_server(cluster, port=0)
    server2.start()
    d2 = Driver(f"127.0.0.1:{port2}")
    try:
        kv2 = d2.keyvalue_client()
        assert kv2.read("vol1", "bb") == b"2"
        assert kv2.read("vol1", "c") == b"3"
        kv2.drop_volume("vol1")
        with pytest.raises(ApiError):
            kv2.read("vol1", "bb")
    finally:
        d2.close()
        server2.stop(0)


def test_federation_discovery(served):
    _cluster, driver = served
    dbs = driver.federation_databases()
    assert len(dbs) == 1
    assert dbs[0]["status"] == "AVAILABLE"
    assert dbs[0]["endpoint"].startswith("127.0.0.1:")


def test_copy_table_decimal_roundtrip(served):
    """DescribeTable->CreateTable type round-trip including decimal
    (type_to_str's 'decimal(s)' is schema-JSON, not DDL — the copy
    path must emit a DDL-parseable spelling)."""
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("px", [("id", "int64", True),
                          ("amt", "decimal(10,2)", False),
                          ("w", "float64", False)],
                   primary_key=["id"], store="column")
    t.execute("INSERT INTO px VALUES (1, 12.50, 0.5), (2, 0.75, 1.5)")
    assert t.copy_table("px", "px2") == 2
    out, _ = t.execute("SELECT amt, w FROM px2 ORDER BY id")
    import decimal

    assert out.column("amt").to_pylist() == [
        decimal.Decimal("12.50"), decimal.Decimal("0.75")]
    assert out.column("w").to_pylist() == [0.5, 1.5]


def test_table_service_enforces_acls():
    """The structured Table API honours path ACLs exactly as the SQL
    front door (principal-less internal sessions are ACL-exempt, so
    every handler must bind the ticket's principal)."""
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE sec (id int64, v int64, PRIMARY KEY (id)) "
              "WITH (store = column)")
    s.execute("INSERT INTO sec VALUES (1, 10)")
    cluster.scheme.grant("/sec", "alice", ["read", "write"])
    cluster.scheme.grant("/", "admin", "full")
    server, port = make_server(cluster, port=0,
                               auth_tokens={"alice", "admin", "eve"})
    server.start()
    try:
        eve = Driver(f"127.0.0.1:{port}", auth_token="eve")
        te = eve.table_client()
        # eve has no grants anywhere: reads, writes, DDL all denied
        with pytest.raises(ApiError, match="access denied"):
            list(te.read_table("sec"))
        with pytest.raises(ApiError, match="access denied"):
            te.bulk_upsert("sec", pa.table(
                {"id": pa.array([9], pa.int64()),
                 "v": pa.array([9], pa.int64())}))
        with pytest.raises(ApiError, match="access denied"):
            te.create_table("evil", [("id", "int64", True)],
                            primary_key=["id"])
        with pytest.raises(ApiError, match="access denied"):
            te.drop_table("sec")
        with pytest.raises(ApiError, match="access denied"):
            te.copy_table("sec", "sec_copy")
        eve.close()
        # alice reads and writes; admin does DDL
        alice = Driver(f"127.0.0.1:{port}", auth_token="alice")
        ta = alice.table_client()
        got = pa.concat_tables(ta.read_table("sec"))
        assert got.num_rows == 1
        assert ta.bulk_upsert("sec", pa.table(
            {"id": pa.array([2], pa.int64()),
             "v": pa.array([20], pa.int64())})) == 1
        alice.close()
        admin = Driver(f"127.0.0.1:{port}", auth_token="admin")
        tadm = admin.table_client()
        assert tadm.copy_table("sec", "sec_copy") == 2
        tadm.drop_table("sec_copy")
        admin.close()
    finally:
        server.stop(0)


def test_delete_session_rolls_back_open_tx(served):
    """Dropping a session with an open interactive tx must release its
    shard locks (not leak them), so later writers proceed."""
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("lk", [("id", "int64", True), ("v", "int64", False)],
                   primary_key=["id"], store="row")
    t.execute("INSERT INTO lk VALUES (1, 1)")
    _, tx = t.execute("UPDATE lk SET v = 2 WHERE id = 1", begin=True)
    assert tx
    t.close()  # DeleteSession with the tx still open
    # the buffered write vanished and the lock is free
    t2 = driver.table_client()
    out, _ = t2.execute("SELECT v FROM lk")
    assert out.column("v").to_pylist() == [1]
    (_, ok), _ = t2.execute("UPDATE lk SET v = 7 WHERE id = 1",
                            begin=True, commit=True)
    out, _ = t2.execute("SELECT v FROM lk")
    assert out.column("v").to_pylist() == [7]


def test_kv_volume_prefix_names_do_not_collide(served):
    """Registry probes are exact-key: volume 'a' must not shadow 'ab'."""
    _cluster, driver = served
    kv = driver.keyvalue_client()
    kv.create_volume("ab")
    kv.create_volume("a")  # exact-key check: no phantom 'exists'
    kv.write("ab", "k", b"ab-val")
    kv.write("a", "k", b"a-val")
    assert kv.read("ab", "k") == b"ab-val"
    assert kv.read("a", "k") == b"a-val"
    with pytest.raises(ApiError):
        kv.read("abc", "k")  # never created


def test_bulk_upsert_bool_and_nulls(served):
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("flags", [("id", "int64", True),
                             ("ok", "bool", False)],
                   primary_key=["id"], store="column")
    at = pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                   "ok": pa.array([True, None, False])})
    assert t.bulk_upsert("flags", at) == 3
    out, _ = t.execute("SELECT id, ok FROM flags ORDER BY id")
    assert out.column("ok").to_pylist() == [True, None, False]


def test_out_of_band_rollback_resets_api_tx(served):
    """SQL ROLLBACK through the Query service on the same session must
    invalidate the Table service's open tx id (no silent autocommit
    under a stale id)."""
    _cluster, driver = served
    t = driver.table_client()
    t.create_table("ob", [("id", "int64", True), ("v", "int64", False)],
                   primary_key=["id"], store="row")
    t.execute("INSERT INTO ob VALUES (1, 1)")
    _, tx = t.execute("UPDATE ob SET v = 2 WHERE id = 1", begin=True)
    assert tx
    # the Query service shares the session map keyed by session id
    from ydb_tpu.api.build import ensure_protos
    pb = ensure_protos()
    driver._call("/ydb_tpu.Query/ExecuteQuery",
                 pb.ExecuteQueryRequest(session_id=t.session_id,
                                        sql="ROLLBACK"),
                 pb.ExecuteQueryResponse)
    # stale tx id now rejected instead of silently autocommitting
    with pytest.raises(ApiError, match="unknown tx"):
        t.execute("UPDATE ob SET v = 3 WHERE id = 1", tx_id=tx)
    out, _ = t.execute("SELECT v FROM ob")
    assert out.column("v").to_pylist() == [1]


def test_kv_volume_name_validation(served):
    _cluster, driver = served
    kv = driver.keyvalue_client()
    with pytest.raises(ApiError, match="'/'-free"):
        kv.create_volume("a/log")
    with pytest.raises(ApiError, match="'/'-free"):
        kv.create_volume("")
