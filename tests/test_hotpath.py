"""Dispatch-purity analyzer (H001-H006): seeded warm-path fixtures
firing every rule, the @host_ok escape hatch, pragma suppression, and
the interprocedural walk (descent, chain breadcrumbs, cold-body
boundaries at compile/plan calls, constructors not followed)."""

import ast
import textwrap

from ydb_tpu.analysis import hotpath
from ydb_tpu.analysis.hotpath import HOT_ROOTS, RULES


ROOT = (("kqp.session", "Session._execute_admitted"),)


def _findings(src, modname="kqp.session", extra=()):
    sources = [(textwrap.dedent(src), f"<{modname}>", modname)]
    for s, m in extra:
        sources.append((textwrap.dedent(s), f"<{m}>", m))
    return hotpath.check_sources(sources, roots=ROOT)


def _codes(src, **kw):
    return [f.code for f in _findings(src, **kw)]


# ---------------- per-rule firing fixtures ----------------


def test_h000_syntax_error():
    assert _codes("def f(:\n") == ["H000"]


def test_h001_item_sync():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            return total.item()
    """
    fs = _findings(src)
    assert [f.code for f in fs] == ["H001"]
    assert "warm path: Session._execute_admitted" in fs[0].message


def test_h001_sync_roots_and_fetch_methods():
    src = """
    import numpy as np

    class Session:
        def _execute_admitted(self, sql):
            a = np.asarray(out)
            b = jax.device_get(out)
            c = block.to_numpy()
            jax.block_until_ready(out)
            return a, b, c
    """
    assert _codes(src) == ["H001"] * 4


def test_h002_formatted_cache_key():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            self._plan_cache[f"{sql}:{shape}"] = plan
            hit = self._plan_cache.get("%s" % sql)
            self._exec_cache[id(plan)] = fn
            return hit
    """
    assert _codes(src) == ["H002"] * 3


def test_h002_structured_key_is_clean():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            self._plan_cache[(sql, tuple(shape))] = plan
            return self._plan_cache.get((sql, dialect))
    """
    assert _codes(src) == []


def test_h003_compile_calls_flagged_and_body_cold():
    src = """
    import jax

    class Session:
        def _execute_admitted(self, sql):
            fn = jax.jit(kern)
            return compile_program(prog, sch)

    def compile_program(prog, sch):
        return arr.item()  # cold compile body: never reported
    """
    assert _codes(src) == ["H003", "H003"]


def test_h003_str_lower_not_confused_with_jax_lower():
    src = """
    import re

    class Session:
        def _execute_admitted(self, sql):
            pat = re.compile("x")
            return sql.lower()
    """
    assert _codes(src) == []


def test_h004_plan_calls_flagged_and_body_cold():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            plan = parse(sql)
            return plan_signature(plan, db)

    def parse(sql):
        return np.asarray(sql)  # cold planner body: never reported
    """
    assert _codes(src) == ["H004", "H004"]


def test_h005_host_alloc():
    src = """
    import numpy as np
    import jax.numpy as jnp

    class Session:
        def _execute_admitted(self, sql):
            pad = np.zeros(128)
            staged = jnp.asarray(aux)
            return pad, staged
    """
    assert _codes(src) == ["H005", "H005"]


def test_h006_row_loops():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            for r in rows:
                use(r)
            for i in range(len(xs)):
                use(i)
            for v in vals.tolist():
                use(v)
    """
    assert _codes(src) == ["H006"] * 3


def test_h006_bounded_non_row_loop_clean():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            for shard in self.shards:
                use(shard)
    """
    assert _codes(src) == []


# ---------------- path scoping ----------------


def test_cold_code_is_not_judged():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            return run(sql)

        def boot(self):
            return huge.item()  # unreachable from the root: fine
    """
    assert _codes(src) == []


def test_interprocedural_descent_with_chain():
    src = """
    import numpy as np

    class Session:
        def _execute_admitted(self, sql):
            return helper(sql)

    def helper(sql):
        return stage(sql)

    def stage(sql):
        return np.asarray(sql)
    """
    fs = _findings(src)
    assert [f.code for f in fs] == ["H001"]
    assert ("Session._execute_admitted -> helper -> stage"
            in fs[0].message)


def test_self_method_descent():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            return self._finish(sql)

        def _finish(self, sql):
            return out.item()
    """
    assert _codes(src) == ["H001"]


def test_cross_module_import_descent():
    session = """
    from ydb_tpu.plan.executor import run_plan

    class Session:
        def _execute_admitted(self, sql):
            return run_plan(sql)
    """
    executor = """
    import numpy as np

    def run_plan(sql):
        return np.asarray(sql)
    """
    fs = _findings(session, extra=((executor, "plan.executor"),))
    assert [f.code for f in fs] == ["H001"]
    assert "run_plan" in fs[0].message


def test_constructor_calls_not_followed():
    src = """
    class Cursor:
        def __init__(self, x):
            self.v = x.item()  # setup, not dispatch

    class Session:
        def _execute_admitted(self, sql):
            return Cursor(sql)
    """
    assert _codes(src) == []


def test_generic_method_names_not_wired_across_classes():
    src = """
    class StreamScheduler:
        def items(self):
            return buf.item()

    class Session:
        def _execute_admitted(self, sql):
            return self.aux.items()
    """
    assert _codes(src) == []


# ---------------- escapes ----------------


def test_host_ok_callee_not_reported_or_descended():
    src = """
    from ydb_tpu.analysis import host_ok

    class Session:
        def _execute_admitted(self, sql):
            return self._fetch()

        @host_ok("deliberate result fetch")
        def _fetch(self):
            return self.block.to_numpy()
    """
    assert _codes(src) == []


def test_host_ok_underscore_alias_matches():
    src = """
    from ydb_tpu.analysis import host_ok as _host_ok

    class Session:
        def _execute_admitted(self, sql):
            return self._fetch()

        @_host_ok("row DML readback")
        def _fetch(self):
            return self.block.to_numpy()
    """
    assert _codes(src) == []


def test_pragma_suppresses_on_line_and_line_above():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            a = out.item()  # ydb-lint: disable=H001
            # ydb-lint: disable=H001 deliberate: result boundary
            b = out2.item()
            return a, b
    """
    assert _codes(src) == []


def test_pragma_is_code_specific():
    src = """
    class Session:
        def _execute_admitted(self, sql):
            return out.item()  # ydb-lint: disable=H006
    """
    assert _codes(src) == ["H001"]


# ---------------- driver surface ----------------


def test_rule_table_complete():
    assert sorted(RULES) == \
        ["H001", "H002", "H003", "H004", "H005", "H006"]
    assert len(HOT_ROOTS) == 5


def test_cli_exit_code_clean_and_dirty(tmp_path, capsys):
    clean = tmp_path / "clean.py"
    clean.write_text("def f():\n    return 1\n")
    assert hotpath.main([str(clean)]) == 0
    bad = tmp_path / "ydb_tpu" / "kqp"
    bad.mkdir(parents=True)
    (bad / "session.py").write_text(
        "class Session:\n"
        "    def _execute_admitted(self, sql):\n"
        "        return out.item()\n")
    assert hotpath.main([str(bad / "session.py")]) == 1
    out = capsys.readouterr().out
    assert "H001" in out


def test_report_files_narrow_reporting_not_the_index():
    """--changed must not shrink the call-graph index (a file subset
    makes ambiguous methods look unique and the walk enters cold
    code) — it only filters which files findings are reported for."""
    session = textwrap.dedent("""
    from ydb_tpu.plan.executor import run_plan

    class Session:
        def _execute_admitted(self, sql):
            return run_plan(sql)
    """)
    executor = textwrap.dedent("""
    import numpy as np

    def run_plan(sql):
        return np.asarray(sql)
    """)
    sources = [(session, "<kqp.session>", "kqp.session"),
               (executor, "<plan.executor>", "plan.executor")]
    full = hotpath.check_sources(sources, roots=ROOT)
    assert [f.code for f in full] == ["H001"]
    only_session = hotpath.check_sources(
        sources, roots=ROOT, report_files={"<kqp.session>"})
    assert only_session == []  # the hazard file is out of scope
    only_exec = hotpath.check_sources(
        sources, roots=ROOT, report_files={"<plan.executor>"})
    assert [f.code for f in only_exec] == ["H001"]


def test_modname_derived_from_package_path():
    assert hotpath._modname_for(
        "/x/y/ydb_tpu/kqp/session.py") == "kqp.session"
    assert hotpath._modname_for("plain.py") == "plain"


def test_findings_sorted_and_json_shaped():
    src = """
    import numpy as np

    class Session:
        def _execute_admitted(self, sql):
            b = np.zeros(4)
            a = out.item()
            return a, b
    """
    fs = _findings(src)
    assert [(f.line, f.code) for f in fs] == \
        sorted((f.line, f.code) for f in fs)
    for f in fs:
        assert set(f.to_dict()) == \
            {"file", "line", "col", "code", "name", "message"}
