"""Monitoring HTTP endpoint tests: viewer JSON APIs, whiteboard,
counters pages (reference: core/viewer/viewer.cpp, core/mon/mon.cpp,
tablet/node_whiteboard.cpp)."""

import json
import urllib.request

import pytest

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.obs.viewer import Viewer
from ydb_tpu.topic.topic import Topic


@pytest.fixture
def served():
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, name string, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1, 'a'), (2, 'b')")
    s.execute("SELECT id FROM t ORDER BY id")
    cluster.topics["ev"] = Topic("ev", MemBlobStore(), n_partitions=1)
    cluster.topics["ev"].write("m1")
    v = Viewer(cluster).start()
    yield cluster, v
    v.stop()


def get(v, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{v.port}{path}", timeout=10) as r:
        ctype = r.headers["Content-Type"]
        return r.status, ctype, r.read()


def test_cluster_scheme_tables_topics(served):
    _cluster, v = served
    st, ctype, body = get(v, "/viewer/json/cluster")
    assert st == 200 and ctype.startswith("application/json")
    info = json.loads(body)
    assert info["tables"] == ["t"] and info["topics"] == ["ev"]
    assert info["uptime_seconds"] >= 0

    scheme = json.loads(get(v, "/viewer/json/scheme")[2])
    assert {"path": "/t", "type": "table"} in scheme

    tables = json.loads(get(v, "/viewer/json/tables")[2])
    assert sum(r["rows"] for r in tables
               if r["table_name"] == "t") == 2

    topics = json.loads(get(v, "/viewer/json/topics")[2])
    assert topics == [{"topic": "ev", "partition": 0,
                       "start_offset": 0, "end_offset": 1}]


def test_health_whiteboard_counters(served):
    _cluster, v = served
    health = json.loads(get(v, "/viewer/json/healthcheck")[2])
    assert health["status"] in ("GOOD", "DEGRADED", "EMERGENCY")

    wb = json.loads(get(v, "/viewer/json/whiteboard")[2])
    assert wb["tables"] == 1 and wb["topics"] == 1
    assert any(q["kind"] == "select" or "SELECT" in q["sql"].upper()
               for q in wb["recent_queries"])
    assert wb["memory"], "memory stats empty"

    counters = json.loads(get(v, "/counters")[2])
    assert counters, "counters snapshot empty"
    st, ctype, prom = get(v, "/counters/prometheus")
    assert st == 200 and b"# TYPE" in prom or prom != b""


def test_tablet_counters_aggregation(served):
    cluster, v = served
    data = json.loads(get(v, "/viewer/json/tablets")[2])
    rows = data["tablets"]
    assert rows, "no tablets collected"
    assert all(r["tx_committed"] <= r["tx_executed"] for r in rows)
    # the scheme tablet and the topic partition both show up
    types = {r["type"] for r in rows}
    assert "pq" in types
    agg = data["aggregates"]
    for t, a in agg.items():
        mine = [r for r in rows if r["type"] == t]
        assert a["tablets"] == len(mine)
        assert a["redo_bytes"] == sum(r["redo_bytes"] for r in mine)
    # durable writes happened, so redo bytes are nonzero somewhere
    assert sum(a["redo_bytes"] for a in agg.values()) > 0


def test_sysview_listing_and_rows(served):
    _cluster, v = served
    names = json.loads(get(v, "/viewer/json/sysview")[2])
    assert "sys_query_stats" in names
    rows = json.loads(
        get(v, "/viewer/json/sysview?name=sys_query_stats")[2])
    assert any("SELECT" in r["query_text"].upper() for r in rows)


def test_bearer_auth():
    cluster = Cluster()
    v = Viewer(cluster, auth_tokens={"tok"}).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(v, "/viewer/json/cluster")
        assert ei.value.code == 401
        req = urllib.request.Request(
            f"http://127.0.0.1:{v.port}/viewer/json/cluster",
            headers={"Authorization": "Bearer tok"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
    finally:
        v.stop()


def test_unknown_endpoint_404(served):
    _cluster, v = served
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(v, "/viewer/json/nope")
    assert ei.value.code == 404


def test_embedded_html_ui(served):
    """/viewer (and the reference's /monitoring alias) serves the
    self-contained SPA that polls the JSON endpoints."""
    _cluster, v = served
    for path in ("/viewer", "/monitoring"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{v.port}{path}")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/html")
            body = r.read().decode()
        assert "ydb_tpu viewer" in body
        assert "/viewer/json/tablets" in body  # polls the JSON APIs
