"""Fused vs per-aggregate group-by equivalence (PR 3 tentpole).

Every case compiles the SAME program twice — kernels.FUSED_FORCE
True/False — and cross-checks both lowerings against each other and
against the independent CPU oracle, across dtypes, NULL patterns,
decimals, and all three group-id tiers (dense one-hot, sorted, and the
>ONEHOT_GROUP_LIMIT scatter/Pallas tier).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks import DictionarySet, TableBlock
from ydb_tpu.engine.oracle import OracleTable, run_oracle
from ydb_tpu.ssa import (
    Agg,
    AggSpec,
    GroupByStep,
    Program,
    compile_program,
)
from ydb_tpu.ssa import kernels, pallas_kernels


def _block(cols, validity=None):
    sch = []
    arrays = {}
    for name, (arr, t) in cols.items():
        sch.append((name, t))
        arrays[name] = np.asarray(arr)
    return TableBlock.from_numpy(
        arrays, dtypes.schema(*sch), validity or None)


def _run(prog, blk, dicts=None, key_spaces=None, fused=True):
    kernels.FUSED_FORCE = fused
    try:
        cp = compile_program(prog, blk.schema, dicts, key_spaces)
        out = jax.jit(cp.run)(
            blk, {k: jnp.asarray(v) for k, v in cp.aux.items()})
        data, valid = out.host_columns()
        return data, valid
    finally:
        kernels.FUSED_FORCE = None


def _run_oracle(prog, blk, dicts=None):
    data, valid = blk.host_columns()
    table = OracleTable(
        {n: (data[n], valid[n]) for n in data}, blk.schema)
    out = run_oracle(prog, table, dicts)
    return ({n: v[0] for n, v in out.cols.items()},
            {n: v[1] for n, v in out.cols.items()})


def _sorted_by(data, valid, keys):
    # NULL key groups carry arbitrary data under validity=False: align
    # rows by (validity, value) per key so all three runs sort alike
    subkeys = []
    for k in reversed(keys):
        subkeys.append(np.asarray(data[k]))
        subkeys.append(np.asarray(valid[k]))
    return np.lexsort(tuple(subkeys))


def _assert_equivalent(prog, blk, dicts=None, key_spaces=None,
                       keys=("k",)):
    fd, fv = _run(prog, blk, dicts, key_spaces, fused=True)
    pd_, pv = _run(prog, blk, dicts, key_spaces, fused=False)
    od, ov = _run_oracle(prog, blk, dicts)
    fo, po, oo = (_sorted_by(fd, fv, keys), _sorted_by(pd_, pv, keys),
                  _sorted_by(od, ov, keys)) if keys else (None,) * 3
    for name in fd:
        f = np.asarray(fd[name])
        p = np.asarray(pd_[name])
        o = np.asarray(od[name])
        if keys:
            f, p, o = f[fo], p[po], o[oo]
            fvv, pvv, ovv = (np.asarray(fv[name])[fo],
                             np.asarray(pv[name])[po],
                             np.asarray(ov[name])[oo])
        else:
            fvv, pvv, ovv = (np.asarray(fv[name]), np.asarray(pv[name]),
                             np.asarray(ov[name]))
        np.testing.assert_array_equal(fvv, pvv,
                                      err_msg=f"validity {name}")
        np.testing.assert_array_equal(fvv, ovv,
                                      err_msg=f"oracle validity {name}")
        live = fvv
        # key columns under validity=False hold arbitrary padding;
        # SOME is "any valid value" — its value is only comparable
        # between the two device lowerings, not against the oracle
        check_oracle = not name.startswith("some_")
        if np.issubdtype(f.dtype, np.integer) or f.dtype == bool:
            np.testing.assert_array_equal(
                f[live], p[live], err_msg=f"fused vs peragg {name}")
            if check_oracle:
                np.testing.assert_array_equal(
                    f[live], o[live], err_msg=f"fused vs oracle {name}")
        else:
            np.testing.assert_allclose(
                f[live], p[live], rtol=1e-9,
                err_msg=f"fused vs peragg {name}")
            if check_oracle:
                np.testing.assert_allclose(
                    f[live], o[live], rtol=1e-9,
                    err_msg=f"fused vs oracle {name}")


_ALL_AGGS = (
    AggSpec(Agg.COUNT_ALL, None, "n"),
    AggSpec(Agg.SUM, "d", "sum_d"),
    AggSpec(Agg.SUM, "f", "sum_f"),
    AggSpec(Agg.SUM, "i", "sum_i"),
    AggSpec(Agg.AVG, "d", "avg_d"),
    AggSpec(Agg.AVG, "f", "avg_f"),
    AggSpec(Agg.COUNT, "i", "cnt_i"),
    AggSpec(Agg.MIN, "i", "min_i"),
    AggSpec(Agg.MAX, "f", "max_f"),
    AggSpec(Agg.VAR_SAMP, "f", "var_f"),
    AggSpec(Agg.STDDEV_SAMP, "d", "std_d"),
    AggSpec(Agg.SOME, "i", "some_i"),
)


def _mixed_block(n=4000, nulls=True, seed=11, key_vals=5):
    rng = np.random.default_rng(seed)
    cols = {
        "k": (rng.integers(0, key_vals, n).astype(np.int64),
              dtypes.INT64),
        "d": (rng.integers(-(10 ** 6), 10 ** 6, n).astype(np.int64),
              dtypes.decimal(2)),
        "f": (rng.normal(50.0, 9.0, n), dtypes.DOUBLE),
        "i": (rng.integers(-1000, 1000, n).astype(np.int64),
              dtypes.INT64),
    }
    validity = None
    if nulls:
        validity = {
            "d": rng.random(n) > 0.15,
            "f": rng.random(n) > 0.05,
            "i": rng.random(n) > 0.5,
        }
    return _block(cols, validity)


@pytest.mark.parametrize("nulls", [False, True])
def test_dense_tier_all_aggs(nulls):
    blk = _mixed_block(nulls=nulls)
    prog = Program((GroupByStep(("k",), _ALL_AGGS),))
    _assert_equivalent(prog, blk, key_spaces={"k": 5})


@pytest.mark.parametrize("nulls", [False, True])
def test_sorted_tier_all_aggs(nulls):
    # no key_spaces bound -> lexicographic-sort group ids
    blk = _mixed_block(nulls=nulls, key_vals=37)
    prog = Program((GroupByStep(("k",), _ALL_AGGS),))
    _assert_equivalent(prog, blk)


def test_null_group_key():
    rng = np.random.default_rng(5)
    n = 2000
    blk = _block(
        {"k": (rng.integers(0, 4, n).astype(np.int64), dtypes.INT64),
         "i": (rng.integers(0, 100, n).astype(np.int64), dtypes.INT64)},
        {"k": rng.random(n) > 0.3, "i": np.ones(n, dtype=bool)},
    )
    prog = Program((GroupByStep(
        ("k",),
        (AggSpec(Agg.COUNT_ALL, None, "n"),
         AggSpec(Agg.SUM, "i", "s"),
         AggSpec(Agg.MIN, "i", "lo"))),))
    # NULL keys form their own group in both tiers
    _assert_equivalent(prog, blk, key_spaces={"k": 4})
    _assert_equivalent(prog, blk)


def test_string_keys_and_string_minmax():
    dicts = DictionarySet()
    d = dicts.for_column("s")
    rng = np.random.default_rng(9)
    n = 3000
    ids = d.encode([b"pear", b"apple", b"fig", b"plum"])
    blk = _block(
        {"s": (rng.choice(ids, n), dtypes.STRING),
         "v": (rng.integers(0, 50, n).astype(np.int64), dtypes.INT64)},
    )
    prog = Program((GroupByStep(
        ("s",),
        (AggSpec(Agg.COUNT_ALL, None, "n"),
         AggSpec(Agg.MIN, "s", "first_s"),
         AggSpec(Agg.MAX, "s", "last_s"),
         AggSpec(Agg.SUM, "v", "sv"))),))
    _assert_equivalent(prog, blk, dicts=dicts, keys=("s",))


def test_keyless_global_aggregate():
    blk = _mixed_block(n=1500)
    prog = Program((GroupByStep((), _ALL_AGGS),))
    _assert_equivalent(prog, blk, keys=())


def test_large_group_scatter_tier():
    # > ONEHOT_GROUP_LIMIT dense groups: the fused path takes the 2D
    # scatter (or Pallas) tier instead of the hit-matrix GEMM
    rng = np.random.default_rng(3)
    n, k = 20_000, 700
    assert k > kernels.ONEHOT_GROUP_LIMIT
    blk = _block(
        {"k": (rng.integers(0, k, n).astype(np.int64), dtypes.INT64),
         "d": (rng.integers(0, 10 ** 6, n).astype(np.int64),
               dtypes.decimal(2)),
         "f": (rng.normal(0, 5, n), dtypes.DOUBLE)},
        {"d": rng.random(n) > 0.1, "f": np.ones(n, dtype=bool)},
    )
    prog = Program((GroupByStep(
        ("k",),
        (AggSpec(Agg.COUNT_ALL, None, "n"),
         AggSpec(Agg.SUM, "d", "sd"),
         AggSpec(Agg.AVG, "f", "af"),
         AggSpec(Agg.MAX, "d", "hi"))),))
    _assert_equivalent(prog, blk, key_spaces={"k": k})


def test_pallas_fused_multi_matches_scatter_tier():
    # the fused multi-column tile kernel (interpret mode on CPU) against
    # the 2D scatter fallback of fused_group_reduce
    rng = np.random.default_rng(8)
    n, k, s = 5000, 900, 6
    vals = jnp.asarray(rng.integers(0, 1000, (n, s)), dtype=jnp.float32)
    gid = jnp.asarray(rng.integers(0, k + 25, n), dtype=jnp.int32)
    ref = kernels.fused_group_reduce(vals, gid, k, dtype=jnp.float32)
    got = pallas_kernels.grouped_sum_multi(vals, gid, k, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6)


def test_decimal_sum_exactness_via_limb_split():
    # values whose naive f64 accumulation would round: the limb-encoded
    # GEMM must still produce bit-exact int64 sums
    n = 1024
    big = (1 << 50) + 1
    blk = _block(
        {"k": (np.zeros(n, dtype=np.int64), dtypes.INT64),
         "d": (np.full(n, big, dtype=np.int64), dtypes.decimal(2))},
    )
    prog = Program((GroupByStep(
        ("k",), (AggSpec(Agg.SUM, "d", "s"),)),))
    fd, _ = _run(prog, blk, key_spaces={"k": 1}, fused=True)
    pd_, _ = _run(prog, blk, key_spaces={"k": 1}, fused=False)
    assert int(fd["s"][0]) == n * big
    assert int(pd_["s"][0]) == n * big
    # negative values exercise the signed top limb
    blk2 = _block(
        {"k": (np.zeros(n, dtype=np.int64), dtypes.INT64),
         "d": (np.full(n, -big, dtype=np.int64), dtypes.decimal(2))},
    )
    fd2, _ = _run(prog, blk2, key_spaces={"k": 1}, fused=True)
    assert int(fd2["s"][0]) == -n * big


def test_nullable_flag_does_not_change_results():
    # identical data, schema declared nullable vs non-nullable: the
    # fused path's static count/mask collapse must be invisible
    rng = np.random.default_rng(2)
    n = 3000
    k = rng.integers(0, 6, n).astype(np.int64)
    v = rng.integers(0, 10 ** 5, n).astype(np.int64)
    specs = (AggSpec(Agg.COUNT_ALL, None, "n"),
             AggSpec(Agg.SUM, "v", "s"),
             AggSpec(Agg.AVG, "v", "a"),
             AggSpec(Agg.COUNT, "v", "c"))
    prog = Program((GroupByStep(("k",), specs),))
    outs = {}
    for nullable in (False, True):
        sch = dtypes.Schema((
            dtypes.Field("k", dtypes.INT64, nullable),
            dtypes.Field("v", dtypes.INT64, nullable),
        ))
        blk = TableBlock.from_numpy({"k": k, "v": v}, sch)
        outs[nullable], _ = _run(prog, blk, key_spaces={"k": 6},
                                 fused=True)
    order0 = np.argsort(outs[False]["k"])
    order1 = np.argsort(outs[True]["k"])
    for name in outs[False]:
        np.testing.assert_array_equal(
            np.asarray(outs[False][name])[order0],
            np.asarray(outs[True][name])[order1], err_msg=name)


def test_fused_flag_env_gating(monkeypatch):
    monkeypatch.setattr(kernels, "FUSED_FORCE", None)
    monkeypatch.setenv("YDB_TPU_FUSED_GROUPBY", "0")
    assert not kernels.fused_group_by_enabled()
    monkeypatch.setenv("YDB_TPU_FUSED_GROUPBY", "1")
    assert kernels.fused_group_by_enabled()
    monkeypatch.delenv("YDB_TPU_FUSED_GROUPBY")
    assert kernels.fused_group_by_enabled()  # default on
    monkeypatch.setattr(kernels, "FUSED_FORCE", False)
    assert not kernels.fused_group_by_enabled()
