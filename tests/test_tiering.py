"""Hot/cold blob tiering (SURVEY §2.7 tiering row; reference
ydb/core/tx/tiering + S3 external storage)."""

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore, TieredBlobStore
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program

COUNT = Program((GroupByStep(keys=(), aggs=(
    AggSpec(Agg.COUNT_ALL, None, "n"),
    AggSpec(Agg.SUM, "v", "s"),
)),))


def test_tier_basics():
    hot, cold = MemBlobStore(), MemBlobStore()
    t = TieredBlobStore(hot, cold)
    t.put("a", b"1")
    assert t.tier_of("a") == "hot"
    assert t.evict(lambda bid: True) == 1
    assert t.tier_of("a") == "cold"
    assert t.get("a") == b"1"          # transparent read-through
    assert t.exists("a") and "a" in t.list("")
    assert t.promote("a")
    assert t.tier_of("a") == "hot"
    # rewrite supersedes a cold copy
    t.evict(lambda bid: True)
    t.put("a", b"2")
    assert t.tier_of("a") == "hot" and t.get("a") == b"2"
    assert not cold.exists("a")
    t.delete("a")
    assert t.tier_of("a") is None


def test_shard_cold_eviction_keeps_scans_correct():
    hot, cold = MemBlobStore(), MemBlobStore()
    store = TieredBlobStore(hot, cold)
    schema = dtypes.schema(("id", dtypes.INT64, False),
                           ("v", dtypes.INT64))
    shard = ColumnShard("t", schema, store, pk_column="id", upsert=True,
                        config=ShardConfig(
                            compact_portion_threshold=10 ** 9))
    for i in range(3):
        wid = shard.write({
            "id": np.arange(i * 100, i * 100 + 100, dtype=np.int64),
            "v": np.full(100, i, dtype=np.int64)})
        shard.commit([wid])
    old_snap = shard.snap
    wid = shard.write({"id": np.arange(300, 400, dtype=np.int64),
                       "v": np.full(100, 9, dtype=np.int64)})
    shard.commit([wid])

    moved = shard.evict_to_cold(old_snap)
    assert moved == 3  # the three old portions' blobs
    tiers = {m.blob_id: store.tier_of(m.blob_id)
             for m in shard.visible_portions()}
    assert sorted(tiers.values()) == ["cold", "cold", "cold", "hot"]

    res = shard.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 400
    assert int(res.cols["s"][0][0]) == 100 * (0 + 1 + 2 + 9)
