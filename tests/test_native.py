"""Native host kernels: C++ and numpy twins must agree bit-for-bit
(routing and merges must not depend on whether the toolchain exists)."""

import numpy as np
import pytest

from ydb_tpu import native
from ydb_tpu.native import BloomFilter, hash_rows, kway_merge


@pytest.fixture
def both_paths(monkeypatch):
    """Run a fn under (native, fallback) and return both results."""
    def run(fn):
        a = fn()
        monkeypatch.setattr(native, "_lib", False)
        b = fn()
        monkeypatch.setattr(native, "_lib", None)
        return a, b
    return run


def test_native_library_builds():
    import os

    if os.environ.get("YDB_TPU_NO_NATIVE"):
        pytest.skip("native explicitly disabled")
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain; fallback twins cover behavior")
    assert native.available()


def test_hash_rows_native_matches_numpy(both_paths):
    rng = np.random.default_rng(7)
    keys = [rng.integers(-2**40, 2**40, 1000),
            rng.integers(0, 100, 1000)]
    valids = [rng.random(1000) < 0.9, np.ones(1000, dtype=bool)]
    a, b = both_paths(lambda: hash_rows(keys, valids))
    np.testing.assert_array_equal(a, b)
    # validity flips change the hash
    v2 = [~valids[0], valids[1]]
    assert (hash_rows(keys, valids) != hash_rows(keys, v2)).any()


def test_kway_merge_native_matches_numpy(both_paths):
    rng = np.random.default_rng(3)
    runs = [np.sort(rng.integers(0, 500, n))
            for n in (100, 0, 57, 333)]
    for dedup in (False, True):
        (ar, ai), (br, bi) = both_paths(
            lambda: kway_merge(runs, dedup=dedup))
        np.testing.assert_array_equal(ar, br)
        np.testing.assert_array_equal(ai, bi)


def test_kway_merge_order_and_dedup():
    runs = [np.array([1, 3, 5]), np.array([1, 2, 5, 9])]
    run_i, row_i = kway_merge(runs)
    merged = [int(runs[r][i]) for r, i in zip(run_i, row_i)]
    assert merged == [1, 1, 2, 3, 5, 5, 9]
    run_i, row_i = kway_merge(runs, dedup=True)
    merged = [(int(runs[r][i]), int(r)) for r, i in zip(run_i, row_i)]
    # newest-wins: duplicates resolve to the higher run index
    assert merged == [(1, 1), (2, 1), (3, 0), (5, 1), (9, 1)]


def test_kway_merge_empty():
    run_i, row_i = kway_merge([])
    assert len(run_i) == 0 and len(row_i) == 0
    run_i, row_i = kway_merge([np.empty(0, dtype=np.int64)], dedup=True)
    assert len(run_i) == 0


def test_bloom_filter_native_matches_numpy(both_paths):
    rng = np.random.default_rng(11)
    present = rng.integers(0, 2**63, 500).astype(np.uint64)
    probes = rng.integers(0, 2**63, 2000).astype(np.uint64)

    def run():
        bf = BloomFilter.for_items(500)
        bf.add(present)
        return bf.query(np.concatenate([present, probes]))

    a, b = run_twice = both_paths(run)
    np.testing.assert_array_equal(a, b)
    # no false negatives; false-positive rate sane at 10 bits/item
    assert a[:500].all()
    fp = a[500:].mean()
    assert fp < 0.05


def test_hash_rows_used_by_shuffle_routing():
    from ydb_tpu.dq.compute import _hash_rows

    payload = {
        "k": np.arange(100, dtype=np.int64),
        "__v_k": np.ones(100, dtype=bool),
    }

    class S:
        names = ("k",)

    h = _hash_rows(payload, S, ("k",))
    assert h.dtype == np.uint64 and len(h) == 100
    np.testing.assert_array_equal(
        h, hash_rows([payload["k"]], [payload["__v_k"]]))
