"""SSA program verifier tests: one per diagnostic code, asserting the
structured payload (code, step index, path) — the plan-time analog of
the reference's TProgramContainer::Init rejection tests."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.analysis import (
    VerificationError,
    analyze_program,
    check_program,
    verify_program,
)
from ydb_tpu.analysis.diagnostics import PlanError
from ydb_tpu.blocks import TableBlock
from ydb_tpu.ssa import (
    Agg,
    AggSpec,
    AssignStep,
    Call,
    Col,
    FilterStep,
    GroupByStep,
    Op,
    Program,
    ProjectStep,
    SortStep,
    compile_program,
)
from ydb_tpu.ssa.program import WindowStep, lit


SCH = dtypes.schema(
    ("a", dtypes.INT64, False),
    ("b", dtypes.INT64, True),
    ("s", dtypes.STRING, False),
)


def _only(diags, code):
    hits = [d for d in diags if d.code == code]
    assert hits, f"no {code} in {[d.code for d in diags]}"
    return hits[0]


def test_clean_program_has_no_diagnostics():
    prog = Program((
        AssignStep("c", Call(Op.ADD, Col("a"), lit(1))),
        FilterStep(Call(Op.GT, Col("c"), lit(3))),
        ProjectStep(("a", "c")),
    ))
    assert verify_program(prog, SCH) == []
    check_program(prog, SCH)  # does not raise


def test_unknown_column():
    prog = Program((
        AssignStep("c", Call(Op.ADD, Col("nope"), lit(1))),
    ))
    d = _only(verify_program(prog, SCH), "V001")
    assert d.name == "unknown-column"
    assert d.step == 0
    assert "nope" in d.message
    assert d.path == "steps[0].expr.args[0]"
    with pytest.raises(VerificationError) as ei:
        check_program(prog, SCH)
    assert ei.value.diagnostics[0].code == "V001"


def test_filter_not_boolean():
    prog = Program((
        AssignStep("c", Call(Op.ADD, Col("a"), lit(1))),
        FilterStep(Col("c")),
    ))
    d = _only(verify_program(prog, SCH), "V002")
    assert d.step == 1
    assert "BOOL" in d.message


def test_agg_dtype_mismatch():
    prog = Program((
        GroupByStep(("a",), (AggSpec(Agg.SUM, "s", "x"),)),
    ))
    d = _only(verify_program(prog, SCH), "V003")
    assert d.step == 0
    assert "string" in d.message
    assert "dictionary ids" in d.message


def test_dead_projection():
    prog = Program((
        FilterStep(Call(Op.GT, Col("a"), lit(0))),
        ProjectStep(("a", "ghost")),
    ))
    d = _only(verify_program(prog, SCH), "V004")
    assert d.step == 1
    assert "ghost" in d.message
    assert d.path == "steps[1].names[1]"


def test_nullable_window_key_rejected_as_plan_error():
    prog = Program((
        WindowStep("rank", ("b",), ("a",), (False,), "rnk"),
    ))
    d = _only(verify_program(prog, SCH), "V005")
    assert d.step == 0
    assert "NULL" in d.message
    # the targeted rejection is a PlanError: the SQL surface reports it
    # like any other plan-time failure
    with pytest.raises(PlanError, match="window.*NULL|NULL.*window"):
        check_program(prog, SCH)


def test_non_nullable_window_key_accepted():
    prog = Program((
        WindowStep("rank", ("a",), ("a",), (False,), "rnk"),
    ))
    assert verify_program(prog, SCH) == []


def test_group_capacity_must_be_positive():
    prog = Program((
        GroupByStep(("a",), (AggSpec(Agg.COUNT_ALL, None, "n"),),
                    max_groups=0),
    ))
    d = _only(verify_program(prog, SCH), "V006")
    assert d.step == 0


def test_expr_type_error_timestamp():
    prog = Program((AssignStep("h", Call(Op.HOUR, Col("a"))),))
    d = _only(verify_program(prog, SCH), "V007")
    assert "timestamp" in d.message


def test_sort_desc_arity():
    prog = Program((SortStep(("a", "b"), (True,)),))
    d = _only(verify_program(prog, SCH), "V008")
    assert d.step == 0


def test_unknown_window_function():
    prog = Program((WindowStep("ntile", (), ("a",), (False,), "x"),))
    d = _only(verify_program(prog, SCH), "V009")
    assert "ntile" in d.message


def test_duplicate_projection_column():
    prog = Program((ProjectStep(("a", "b", "a")),))
    d = _only(verify_program(prog, SCH), "V010")
    assert d.name == "duplicate-output-column"
    assert d.step == 0
    assert d.path == "steps[0].names[2]"
    assert "'a'" in d.message
    with pytest.raises(VerificationError):
        check_program(prog, SCH)


def test_duplicate_group_by_key():
    prog = Program((
        GroupByStep(("a", "a"), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    d = _only(verify_program(prog, SCH), "V010")
    assert d.path == "steps[0].keys[1]"


def test_aggregate_output_shadows_key():
    prog = Program((
        GroupByStep(("a",), (
            AggSpec(Agg.COUNT_ALL, None, "a"),   # collides with key
            AggSpec(Agg.SUM, "b", "t"),
            AggSpec(Agg.COUNT_ALL, None, "t"),   # collides with agg
        )),
    ))
    hits = [d for d in verify_program(prog, SCH) if d.code == "V010"]
    assert [d.path for d in hits] == \
        ["steps[0].aggs[0]", "steps[0].aggs[2]"]
    assert all(d.hint for d in hits)


def test_distinct_outputs_stay_clean():
    prog = Program((
        GroupByStep(("a",), (AggSpec(Agg.SUM, "b", "t"),)),
        ProjectStep(("a", "t")),
    ))
    assert not [d for d in verify_program(prog, SCH)
                if d.code == "V010"]


def test_multiple_diagnostics_accumulate():
    prog = Program((
        FilterStep(Col("a")),            # V002
        ProjectStep(("a", "ghost")),     # V004
    ))
    codes = {d.code for d in verify_program(prog, SCH)}
    assert {"V002", "V004"} <= codes


def test_compiler_is_a_choke_point():
    """compile_program rejects malformed programs with the structured
    error instead of a trace-time KeyError."""
    prog = Program((ProjectStep(("ghost",)),))
    with pytest.raises(VerificationError):
        compile_program(prog, SCH)


def test_scan_executor_verifies_original_program():
    from ydb_tpu.engine.scan import ColumnSource, ScanExecutor

    src = ColumnSource(
        {"a": np.arange(5, dtype=np.int64)},
        dtypes.schema(("a", dtypes.INT64, False)), None)
    prog = Program((FilterStep(Col("a")),))  # non-bool filter
    with pytest.raises(VerificationError) as ei:
        ScanExecutor(prog, src)
    assert ei.value.diagnostics[0].code == "V002"


def test_nullability_threads_into_out_schema():
    """The verifier's nullability inference types the compiled output
    schema: keyed aggregates over non-null inputs stay non-null, so a
    downstream window over the aggregate passes the V005 check."""
    prog = Program((
        GroupByStep(("a",), (
            AggSpec(Agg.SUM, "a", "total"),
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "b", "maybe"),
            AggSpec(Agg.STDDEV_SAMP, "a", "sd"),
        )),
    ))
    cp = compile_program(prog, SCH)
    by_name = {f.name: f for f in cp.out_schema.fields}
    assert not by_name["a"].nullable       # key from non-null column
    assert not by_name["total"].nullable   # keyed SUM over non-null
    assert not by_name["n"].nullable       # COUNT is never NULL
    assert by_name["maybe"].nullable       # input column is nullable
    assert by_name["sd"].nullable          # NULL for singleton groups

    downstream = Program((
        WindowStep("rank", (), ("total",), (True,), "rnk"),
    ))
    assert verify_program(downstream, cp.out_schema) == []
    bad = Program((
        WindowStep("rank", (), ("maybe",), (True,), "rnk"),
    ))
    assert _only(verify_program(bad, cp.out_schema), "V005")


def test_keyless_aggregate_is_nullable():
    prog = Program((GroupByStep((), (AggSpec(Agg.SUM, "a", "t"),)),))
    ana = analyze_program(prog, SCH)
    assert ana.out_nullable["t"]  # zero-row input -> NULL sum


def test_division_is_nullable_unless_nonzero_literal_divisor():
    """a / b NULLs rows where b == 0, whatever the operands declare —
    so windowing over a division is a V005 rejection, closing the
    zero-divisor bypass of the nullable-window-key guard."""
    by_col = Program((AssignStep("r", Call(Op.DIV, Col("a"), Col("a"))),))
    assert analyze_program(by_col, SCH).out_nullable["r"]
    by_lit = Program((AssignStep("r", Call(Op.DIV, Col("a"), lit(2))),))
    assert not analyze_program(by_lit, SCH).out_nullable["r"]
    by_zero = Program((AssignStep("r", Call(Op.DIV, Col("a"), lit(0))),))
    assert analyze_program(by_zero, SCH).out_nullable["r"]
    windowed = Program((
        AssignStep("r", Call(Op.DIV, Col("a"), Col("a"))),
        WindowStep("rank", (), ("r",), (False,), "rnk"),
    ))
    assert _only(verify_program(windowed, SCH), "V005")


def test_scan_result_schema_keeps_original_agg_nullability():
    """AVG lowers through a two-phase division fixup; the scan's RESULT
    schema must carry the original program's knowledge (keyed AVG over
    a non-null input is never NULL), not the fixup's widening — that is
    what keeps a downstream window over the average plannable."""
    from ydb_tpu.engine.scan import ColumnSource, ScanExecutor

    sch = dtypes.schema(("g", dtypes.INT64, False),
                        ("a", dtypes.INT64, False))
    src = ColumnSource(
        {"g": np.array([1, 1, 2], dtype=np.int64),
         "a": np.array([10, 20, 30], dtype=np.int64)}, sch, None)
    prog = Program((
        GroupByStep(("g",), (AggSpec(Agg.AVG, "a", "m"),)),
    ))
    ex = ScanExecutor(prog, src, block_rows=2)  # forces a real merge
    blk = ex.run_stream(src.blocks(2, ex.read_cols))
    assert not blk.schema.field("m").nullable
    assert not blk.schema.field("g").nullable
    # the executor's static out_schema agrees with delivered blocks
    assert ex.out_schema == blk.schema
    vals = dict(zip(blk.to_numpy()["g"].tolist(),
                    blk.to_numpy()["m"].tolist()))
    assert vals == {1: 15.0, 2: 30.0}
    downstream = Program((
        WindowStep("rank", (), ("m",), (True,), "rnk"),
    ))
    assert verify_program(downstream, blk.schema) == []


def test_verified_program_still_executes():
    import jax

    prog = Program((
        AssignStep("c", Call(Op.MUL, Col("a"), lit(2))),
        FilterStep(Call(Op.GT, Col("c"), lit(2))),
        ProjectStep(("c",)),
    ))
    blk = TableBlock.from_numpy(
        {"a": np.array([1, 2, 3], dtype=np.int64)},
        dtypes.schema(("a", dtypes.INT64, False)))
    cp = compile_program(prog, blk.schema)
    out = jax.jit(cp.run)(
        blk, {k: np.asarray(v) for k, v in cp.aux.items()})
    np.testing.assert_array_equal(out.to_numpy()["c"], [4, 6])


def test_diagnostic_renders_step_and_path():
    prog = Program((AssignStep("c", Col("nope")),))
    d = verify_program(prog, SCH)[0]
    text = d.render()
    assert "V001" in text and "step 0" in text and "steps[0].expr" in text
    as_dict = d.to_dict()
    assert as_dict["code"] == "V001" and as_dict["step"] == 0
