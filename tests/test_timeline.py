"""Data-movement timeline: ring bounding under concurrent writers,
Chrome-trace JSON schema round-trip, occupancy math on hand-built
fixtures, warm-query busy sums vs EXPLAIN ANALYZE stage seconds,
movement byte counters, conveyor queue telemetry, sys_active_queries
live introspection, the slow-query watchdog and error=1 profiles."""

import json
import threading

import pytest

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.obs import timeline
from ydb_tpu.obs.probes import TraceSession
from ydb_tpu.obs.timeline import (
    Event,
    TimelineRing,
    export_chrome_trace,
    intersect_seconds,
    merge_intervals,
    occupancy_from_events,
    union_seconds,
)


@pytest.fixture
def forced_timeline():
    """Timeline ON for the test, restored after (ring cleared both
    sides so other tests see a quiet ring)."""
    prev = timeline.TIMELINE_FORCE
    timeline.TIMELINE_FORCE = True
    timeline.RING.clear()
    yield timeline.RING
    timeline.TIMELINE_FORCE = prev
    timeline.RING.clear()


@pytest.fixture
def cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE ev (id int64, v int64, "
              "PRIMARY KEY (id)) WITH (shards = 2)")
    for base in (0, 100, 200):
        vals = ", ".join(f"({base + i}, {(base + i) * 3})"
                         for i in range(8))
        s.execute(f"INSERT INTO ev VALUES {vals}")
    return c


# ---------- ring bounding ----------

def test_ring_bounds_and_order():
    r = TimelineRing(capacity=8, name="t_bounds")
    for i in range(20):
        r.record(f"e{i}", "read", float(i), float(i) + 0.5)
    assert len(r) == 8
    assert r.recorded == 20
    assert r.dropped == 12
    evs = r.events()
    # oldest-first: the retained window is the last 8 records
    assert [e.name for e in evs] == [f"e{i}" for i in range(12, 20)]


def test_ring_concurrent_writers_stay_bounded():
    """Many threads hammering one small ring: the bound holds, every
    retained slot is a complete Event, and the total count equals the
    sum of writes (the ring lock is sanitizer-tracked, so the
    concurrency analyzer sees this interleaving too)."""
    r = TimelineRing(capacity=64, name="t_conc")
    per_thread = 500
    n_threads = 8
    start = threading.Barrier(n_threads)

    def writer(k):
        start.wait()
        for i in range(per_thread):
            r.record(f"w{k}.{i}", "read", float(i), float(i) + 1.0,
                     trace_id=k, args={"i": i})

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert r.recorded == per_thread * n_threads
    assert r.dropped == per_thread * n_threads - 64
    evs = r.events()
    assert len(evs) == 64
    for e in evs:
        assert isinstance(e, Event)
        assert e.end > e.start
        assert e.args["i"] >= 0


def test_ring_clear():
    r = TimelineRing(capacity=4, name="t_clear")
    r.record("a", "read", 0.0, 1.0)
    r.clear()
    assert len(r) == 0 and r.recorded == 0 and r.events() == []


# ---------- gating ----------

def test_disabled_ring_records_nothing(monkeypatch):
    monkeypatch.delenv("YDB_TPU_TIMELINE", raising=False)
    prev = timeline.TIMELINE_FORCE
    timeline.TIMELINE_FORCE = None
    try:
        assert not timeline.timeline_enabled()
        before = timeline.RING.recorded
        timeline.record("x", "read", 0.0, 1.0)
        with timeline.event("y", "decode"):
            pass
        assert timeline.RING.recorded == before
        timeline.TIMELINE_FORCE = False
        monkeypatch.setenv("YDB_TPU_TIMELINE", "1")
        assert not timeline.timeline_enabled()  # FORCE wins over env
    finally:
        timeline.TIMELINE_FORCE = prev


def test_env_enables(monkeypatch):
    prev = timeline.TIMELINE_FORCE
    timeline.TIMELINE_FORCE = None
    try:
        monkeypatch.setenv("YDB_TPU_TIMELINE", "1")
        assert timeline.timeline_enabled()
        monkeypatch.setenv("YDB_TPU_TIMELINE", "off")
        assert not timeline.timeline_enabled()
    finally:
        timeline.TIMELINE_FORCE = prev


# ---------- interval math ----------

def test_interval_math():
    assert merge_intervals([(0, 1), (2, 3), (0.5, 2.5)]) == [(0, 3)]
    assert union_seconds([(0, 1), (2, 3)]) == 2
    assert intersect_seconds([(0, 2)], [(1, 3)]) == 1
    assert intersect_seconds([(0, 1)], [(2, 3)]) == 0


def test_occupancy_serial_two_stage():
    """read [0,1) then compute [1,2): fractions 0.5 each, zero
    overlap (a fully serialized pipeline)."""
    evs = [Event("r", "read", 0.0, 1.0, 1, 1, {}),
           Event("c", "compute", 1.0, 2.0, 1, 1, {})]
    occ = occupancy_from_events(evs)
    assert occ["wall_seconds"] == pytest.approx(2.0)
    assert occ["busy"]["read"] == pytest.approx(1.0)
    assert occ["busy"]["compute"] == pytest.approx(1.0)
    assert occ["fraction"]["read"] == pytest.approx(0.5)
    assert occ["overlap"]["compute|read"] == 0.0
    assert occ["overlap"]["movement|compute"] == 0.0


def test_occupancy_overlapping_two_stage():
    """read [0,2), compute [1,3): 1s of overlap over min(2,2) = 0.5;
    two overlapping read intervals union (no double count)."""
    evs = [Event("r1", "read", 0.0, 1.5, 1, 1, {}),
           Event("r2", "read", 1.0, 2.0, 2, 1, {}),
           Event("c", "compute", 1.0, 3.0, 3, 1, {})]
    occ = occupancy_from_events(evs)
    assert occ["busy"]["read"] == pytest.approx(2.0)
    assert occ["overlap"]["compute|read"] == pytest.approx(0.5)
    assert occ["overlap"]["movement|compute"] == pytest.approx(0.5)
    # explicit wall overrides the observed extent
    occ = occupancy_from_events(evs, wall=4.0)
    assert occ["fraction"]["read"] == pytest.approx(0.5)


def test_occupancy_ignores_span_category():
    evs = [Event("query", "span", 0.0, 10.0, 1, 1, {}),
           Event("r", "read", 0.0, 1.0, 1, 1, {})]
    occ = occupancy_from_events(evs)
    assert "span" not in occ["busy"]
    assert occ["wall_seconds"] == pytest.approx(1.0)


# ---------- Chrome trace export ----------

def test_chrome_trace_schema_round_trip():
    r = TimelineRing(capacity=16, name="t_chrome")
    r.record("stage.read", "read", 1.0, 2.0, trace_id=7,
             args={"bytes": 10})
    r.record("plan.dispatch", "dispatch", 2.0, 2.5)
    trace = json.loads(json.dumps(export_chrome_trace(ring=r)))
    assert trace["displayTimeUnit"] == "ms"
    evs = trace["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 2
    assert meta and all(e["name"] == "thread_name" and
                        "name" in e["args"] for e in meta)
    for e in xs:
        # the trace_event contract Perfetto/chrome://tracing require
        assert set(e) >= {"name", "cat", "ph", "ts", "dur", "pid",
                          "tid"}
        assert isinstance(e["ts"], (int, float))
        assert e["dur"] >= 0
    read = next(e for e in xs if e["name"] == "stage.read")
    assert read["args"]["trace_id"] == 7
    assert read["args"]["bytes"] == 10
    assert read["dur"] == pytest.approx(1e6)  # 1s in µs


# ---------- end-to-end: warm query ----------

def test_warm_query_busy_matches_stage_seconds(forced_timeline,
                                               cluster):
    s = cluster.session()
    q = "SELECT id, sum(v) AS sv FROM ev GROUP BY id ORDER BY id"
    s.execute(q)  # warm: compile + cache fill
    forced_timeline.clear()
    s.execute(q)
    p = s.last_profile
    assert p is not None and p.stage_occupancy
    # every stage charge funnels through StageTimer.add, which records
    # the identical interval — so the per-stage event SUMS equal the
    # EXPLAIN ANALYZE stage seconds (within 10%, per acceptance)
    evs = [e for e in forced_timeline.events()
           if e.trace_id == p.trace_id]
    assert evs, "no ring events attributed to the query"
    for stage, total in p.stages.items():
        if total <= 0:
            continue
        ev_sum = sum(e.end - e.start for e in evs if e.cat == stage)
        assert ev_sum == pytest.approx(total, rel=0.1), stage
    occ = p.stage_occupancy
    assert 0 < occ["wall_seconds"] <= (p.seconds or 1.0) * 1.1
    # the staged scan path must report the movement-vs-compute
    # overlap coefficient (the serialized-pipeline detector)
    assert "movement|compute" in occ["overlap"]
    for v in occ["overlap"].values():
        assert 0.0 <= v <= 1.0
    # blob read + decode byte movement was accounted
    mv = timeline.movement_snapshot()
    assert mv.get("blob_read_bytes", 0) > 0
    assert mv.get("decoded_bytes", 0) > 0


def test_explain_analyze_prints_occupancy(forced_timeline, cluster):
    s = cluster.session()
    text = s.execute("EXPLAIN ANALYZE SELECT sum(v) AS sv FROM ev")
    assert "occupancy:" in str(text)


def test_viewer_timeline_endpoint(forced_timeline, cluster):
    from ydb_tpu.obs.viewer import Viewer

    s = cluster.session()
    s.execute("SELECT sum(v) AS sv FROM ev")
    v = Viewer(cluster).start()
    try:
        body, ctype = v.render("/viewer/json/timeline", {})
        out = json.loads(body)
        assert out["enabled"] is True
        assert out["events"] > 0
        assert "categories" in out and "movement_bytes" in out
        assert "active_queries" in out
        body, _ = v.render("/viewer/json/timeline", {"trace": ["1"]})
        trace = json.loads(body)
        assert trace["traceEvents"]
        assert any(e["ph"] == "X" for e in trace["traceEvents"])
    finally:
        v.stop()


# ---------- conveyor queue telemetry ----------

def test_conveyor_queue_stats():
    from ydb_tpu.runtime.conveyor import Conveyor

    cv = Conveyor(workers=2)
    try:
        hs = [cv.submit("scan", lambda: 1) for _ in range(6)]
        for h in hs:
            assert h.wait(5) == 1
        st = cv.queue_stats()
        assert st["submitted"] == 6
        assert st["completed"] == 6
        assert st["rejected"] == 0
        assert st["depth"] == 0
        assert st["workers"] == 2
        waits = st["waits"].get("scan", [])
        assert waits and all(w >= 0 for w in waits)
        # wait samples + high-water mark drain with the snapshot
        st2 = cv.queue_stats()
        assert st2["waits"] == {}
        assert st2["max_depth"] == 0
    finally:
        cv.shutdown()


def test_run_background_exports_conveyor_and_movement(cluster):
    c = cluster
    s = c.session()
    s.execute("SELECT sum(v) AS sv FROM ev")
    c.run_background()
    snap = c.counters.snapshot()
    conveyor_keys = [k for k in snap if "component=conveyor" in k]
    assert any(k.startswith("submitted") for k in conveyor_keys)
    assert any(k.startswith("completed") for k in conveyor_keys)
    movement_keys = [k for k in snap if "component=movement" in k]
    assert any(k.startswith("blob_read_bytes") for k in movement_keys)
    prom = c.counters.encode_prometheus()
    assert 'component="movement"' in prom
    assert 'component="conveyor"' in prom


# ---------- live query introspection ----------

def test_sys_active_queries_shows_then_clears(cluster):
    s = cluster.session()
    # a statement reading sys_active_queries observes ITSELF in
    # flight (registered before planning, still running while the
    # view materializes)
    out = s.execute("SELECT query_text, stage, elapsed_seconds "
                    "FROM sys_active_queries")
    assert out.num_rows == 1
    # ...and the registry clears once execution finishes
    assert cluster.active_query_snapshot() == []
    out = s.execute("SELECT query_text FROM sys_active_queries")
    assert out.num_rows == 1  # only itself again, not a leak


def test_active_registry_clears_on_failure(cluster):
    s = cluster.session()
    with pytest.raises(Exception):
        s.execute("SELECT * FROM no_such_table")
    assert cluster.active_query_snapshot() == []


def test_slow_query_watchdog_fires(cluster, monkeypatch):
    import time

    monkeypatch.setenv("YDB_TPU_SLOW_QUERY_SECONDS", "0.5")
    ts = TraceSession(pattern="query.slow").attach()
    try:
        tok = cluster._register_active("SELECT slow",
                                       time.monotonic() - 2.0)
        try:
            assert cluster.check_slow_queries() == 1
            # latched: the same statement does not re-fire
            assert cluster.check_slow_queries() == 0
        finally:
            cluster._unregister_active(tok)
        assert ts.counts["query.slow"] == 1
        name, params = ts.events[0]
        assert params["elapsed"] >= 0.5
        assert params["sql"] == "SELECT slow"
    finally:
        ts.detach()


def test_fast_query_does_not_fire_watchdog(cluster, monkeypatch):
    monkeypatch.setenv("YDB_TPU_SLOW_QUERY_SECONDS", "30")
    s = cluster.session()
    ts = TraceSession(pattern="query.slow").attach()
    try:
        s.execute("SELECT sum(v) AS sv FROM ev")
        assert cluster.check_slow_queries() == 0
        assert ts.counts["query.slow"] == 0
    finally:
        ts.detach()


# ---------- failed statements land in the profile ring ----------

def test_failed_query_recorded_with_error_flag(cluster):
    s = cluster.session()
    n_before = len(cluster.profiles.recent())
    with pytest.raises(Exception):
        s.execute("SELECT * FROM no_such_table")
    recent = cluster.profiles.recent()
    assert len(recent) == n_before + 1
    p = recent[-1]
    assert p.error == 1
    assert "no_such_table" in p.sql
    # ...and the sys view exposes the flag
    out = s.execute("SELECT query_text, error FROM sys_top_queries "
                    "WHERE error = 1")
    assert out.num_rows >= 1


def test_ok_query_has_error_zero(cluster):
    s = cluster.session()
    s.execute("SELECT sum(v) AS sv FROM ev")
    assert s.last_profile.error == 0
