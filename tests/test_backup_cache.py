"""Backup/export-import (SURVEY §2.14 backup row) and the shared page
cache (§2.4 shared page cache row)."""

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.engine.backup import export_table, import_table, read_manifest
from ydb_tpu.engine.blobs import CachedBlobStore, DirBlobStore, MemBlobStore
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program
from ydb_tpu.tx.coordinator import Coordinator
from ydb_tpu.tx.sharded import ShardedTable

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("v", dtypes.INT64),
    ("tag", dtypes.STRING),
)

COUNT = Program((GroupByStep(keys=(), aggs=(
    AggSpec(Agg.COUNT_ALL, None, "n"),
    AggSpec(Agg.SUM, "v", "s"),
)),))


def _table(store, n_shards=3, upsert=True):
    return ShardedTable("t", SCHEMA, store, Coordinator(MemBlobStore()),
                        n_shards=n_shards, pk_column="id", upsert=upsert)


def test_backup_roundtrip_with_reshard(tmp_path):
    t = _table(MemBlobStore())
    t.insert({"id": np.arange(200, dtype=np.int64),
              "v": np.arange(200, dtype=np.int64),
              "tag": [b"a" if i % 2 else b"b" for i in range(200)]})
    # upsert half the keys: backup must carry the LOGICAL rows
    t.insert({"id": np.arange(0, 200, 2, dtype=np.int64),
              "v": np.full(100, 1000, dtype=np.int64),
              "tag": [b"c"] * 100})

    dest = DirBlobStore(str(tmp_path / "bk"))
    man = export_table(t, dest, "t_backup")
    assert man["rows"] == 200  # deduped logical rows, not versions
    assert read_manifest(dest, "t_backup")["pk_column"] == "id"

    # import into a DIFFERENT shard count
    t2 = import_table(dest, "t_backup", MemBlobStore(),
                      Coordinator(MemBlobStore()), n_shards=5)
    res = t2.scan(COUNT)
    want_s = sum(1000 if i % 2 == 0 else i for i in range(200))
    assert int(res.cols["n"][0][0]) == 200
    assert int(res.cols["s"][0][0]) == want_s
    # string dictionary survived: tag decode works
    assert t2.dicts["tag"].get(b"c") is not None

    # snapshot isolation: a write AFTER the export is absent
    t.insert({"id": np.array([999], dtype=np.int64),
              "v": np.array([1], dtype=np.int64), "tag": [b"z"]})
    man2 = export_table(t, dest, "t_backup2",
                        snap=man["snapshot"])
    assert man2["rows"] == 200


def test_page_cache_hits_and_invalidation(tmp_path):
    base = DirBlobStore(str(tmp_path / "blobs"))
    cache = CachedBlobStore(base, capacity_bytes=1 << 20)
    cache.put("a", b"x" * 100)
    assert cache.get("a") == b"x" * 100   # miss -> fill
    assert cache.get("a") == b"x" * 100   # hit
    s = cache.stats()
    assert s["hits"] == 1 and s["misses"] == 1
    cache.put("a", b"y" * 50)             # write-through invalidates
    assert cache.get("a") == b"y" * 50
    assert cache.get_range("a", 10, 5) == b"y" * 5
    cache.delete("a")
    assert not cache.exists("a")
    assert cache.stats()["entries"] == 0

    # eviction under the byte budget
    small = CachedBlobStore(base, capacity_bytes=250)
    for i in range(5):
        small.put(f"b{i}", bytes([i]) * 100)
        small.get(f"b{i}")
    assert small.stats()["bytes"] <= 250


def test_page_cache_under_shard_scan(tmp_path):
    """A ColumnShard on a cached store: repeated scans hit the cache."""
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig

    base = DirBlobStore(str(tmp_path / "shard"))
    cache = CachedBlobStore(base)
    shard = ColumnShard(
        "s", dtypes.schema(("id", dtypes.INT64, False),
                           ("v", dtypes.INT64)),
        cache, pk_column="id", upsert=True,
        config=ShardConfig(compact_portion_threshold=10 ** 9,
                           portion_chunk_rows=256))
    for i in range(4):
        wid = shard.write({
            "id": np.arange(i * 500, i * 500 + 500, dtype=np.int64),
            "v": np.ones(500, dtype=np.int64)})
        shard.commit([wid])
    r1 = shard.scan(COUNT)
    miss_after_first = cache.stats()["misses"]
    r2 = shard.scan(COUNT)
    assert int(r2.cols["n"][0][0]) == int(r1.cols["n"][0][0]) == 2000
    s = cache.stats()
    assert s["misses"] == miss_after_first  # second scan: all cached
    assert s["hits"] > 0


def test_page_cache_memory_pressure():
    """shared_sausagecache memory-pressure contract (VERDICT r4
    missing 8): above the high watermark the cache budget halves and
    evicts to fit; when pressure clears it grows back toward the
    configured capacity; reads stay correct throughout."""
    from ydb_tpu.engine.blobs import CachedBlobStore, MemBlobStore

    base = MemBlobStore()
    cache = CachedBlobStore(base, capacity_bytes=10_000)
    for i in range(20):
        base.put(f"b{i}", bytes([i]) * 400)
    for i in range(20):
        assert cache.get(f"b{i}") == bytes([i]) * 400
    assert cache._bytes > 5_000
    assert cache.react_to_pressure(0.9) == "shrink"
    assert cache.capacity_bytes == 5_000 and cache._bytes <= 5_000
    assert cache.react_to_pressure(0.9) == "shrink"  # keeps halving
    assert cache.capacity_bytes == 4_096  # floor
    # reads still correct under the shrunken budget
    for i in range(20):
        assert cache.get(f"b{i}") == bytes([i]) * 400
    assert cache.react_to_pressure(0.5) == "grow"
    assert cache.capacity_bytes == 8_192
    assert cache.react_to_pressure(0.5) == "grow"
    assert cache.capacity_bytes == 10_000  # capped at configured
    assert cache.react_to_pressure(0.5) == "steady"
    assert cache.react_to_pressure(0.7) == "steady"  # hysteresis band


def test_cluster_background_reacts_to_memory_pressure():
    import jax  # noqa: F401  (conftest pinned cpu)

    from ydb_tpu.config import AppConfig
    from ydb_tpu.engine.blobs import CachedBlobStore, MemBlobStore
    from ydb_tpu.kqp.session import Cluster

    cache = CachedBlobStore(MemBlobStore(), capacity_bytes=1 << 20)
    c = Cluster(store=cache,
                config=AppConfig(memory_soft_limit_bytes=1))  # ~inf RSS
    st = c.run_background()
    assert st["cache_pressure"] == "shrink"
    assert cache.capacity_bytes < (1 << 20)
