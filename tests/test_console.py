"""Console dynamic config + CMS maintenance tests (reference:
ydb/core/cms/console selector configs + ConfigsDispatcher,
ydb/core/cms availability-budget permissions)."""

import pytest

from conftest import Clock

from ydb_tpu.config import ConfigError
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.runtime.console import (
    Cms,
    Console,
    ConfigsDispatcher,
    VersionMismatch,
)



def test_versioned_config_cas_and_validation():
    c = Console(MemBlobStore())
    assert c.set_config("n_shards: 8") == 1
    text, v = c.get_config()
    assert "n_shards: 8" in text and v == 1
    # CAS: stale expected version rejected
    with pytest.raises(VersionMismatch):
        c.set_config("n_shards: 2", expected_version=0)
    assert c.set_config("n_shards: 2", expected_version=1) == 2
    # invalid config rejected BEFORE commit; version unchanged
    with pytest.raises(ConfigError):
        c.set_config("nope_key: 1")
    assert c.version == 2


def test_selector_overrides_merge_in_order():
    c = Console(MemBlobStore())
    c.set_config("n_shards: 4\nplan_cache_size: 64")
    c.add_override({"tenant": "/Root/a"}, "n_shards: 16")
    c.add_override({"node_kind": "storage"}, "plan_cache_size: 8")

    base = c.resolve({})
    assert base.n_shards == 4 and base.plan_cache_size == 64
    a = c.resolve({"tenant": "/Root/a"})
    assert a.n_shards == 16 and a.plan_cache_size == 64
    both = c.resolve({"tenant": "/Root/a", "node_kind": "storage"})
    assert both.n_shards == 16 and both.plan_cache_size == 8


def test_dispatcher_receives_pushes():
    c = Console(MemBlobStore())
    c.set_config("n_shards: 4")
    d = ConfigsDispatcher({"tenant": "/Root/x"})
    seen = []
    c.subscribe(d)
    d.on_change(lambda cfg: seen.append(cfg.n_shards))
    assert seen == [4]  # immediate delivery on subscribe
    c.add_override({"tenant": "/Root/x"}, "n_shards: 32")
    assert seen[-1] == 32
    c.set_config("n_shards: 6")  # override still applies on top
    assert seen[-1] == 32 and d.version == c.version


def test_console_reboot_keeps_versions_and_overrides():
    store = MemBlobStore()
    c = Console(store)
    c.set_config("n_shards: 8")
    c.add_override({"tenant": "/t"}, "n_shards: 2")
    c2 = Console(store)
    assert c2.version == 2
    assert c2.resolve({"tenant": "/t"}).n_shards == 2


def test_cms_availability_budget():
    clock = Clock()
    cms = Cms(MemBlobStore(), max_unavailable=1, now=clock)
    assert cms.request(1, duration_s=100)
    assert cms.permitted(1)
    assert not cms.request(2)          # budget spent -> queued
    assert cms.request(1)              # idempotent re-request
    granted = cms.done(1)              # returning grants the queue head
    assert granted == [2] and cms.permitted(2) and not cms.permitted(1)


def test_cms_expired_permission_frees_budget():
    clock = Clock()
    cms = Cms(MemBlobStore(), max_unavailable=1, now=clock)
    assert cms.request(1, duration_s=50)
    clock.t += 60  # lapsed
    assert not cms.permitted(1)
    assert cms.request(2)  # expired permission no longer counts


def test_cms_expiry_grants_queue_fifo_no_jumping():
    """A fresh request must not jump nodes already queued when an
    expired permission frees budget (code-review regression)."""
    clock = Clock()
    cms = Cms(MemBlobStore(), max_unavailable=1, now=clock)
    assert cms.request(1, duration_s=50)
    assert not cms.request(2)          # queued behind 1
    clock.t += 60                      # 1's permission expires silently
    assert not cms.request(3)          # 2 is first in line, 3 queues
    assert cms.permitted(2) and not cms.permitted(3)
    assert cms.done(2) == [3]          # then 3 gets its turn


def test_cms_tick_grants_after_expiry():
    clock = Clock()
    cms = Cms(MemBlobStore(), max_unavailable=1, now=clock)
    cms.request(1, duration_s=50)
    assert not cms.request(2)
    clock.t += 60
    assert cms.tick() == [2]
    assert cms.permitted(2)


def test_invalid_override_rejected_before_commit():
    c = Console(MemBlobStore())
    c.set_config("n_shards: 4")
    with pytest.raises(ConfigError):
        c.add_override({"tenant": "/t"}, "nope_key: 1")
    assert c.version == 1  # nothing committed
    assert c.resolve({"tenant": "/t"}).n_shards == 4  # not poisoned


def test_cms_repeat_request_keeps_queue_position():
    clock = Clock()
    cms = Cms(MemBlobStore(), max_unavailable=1, now=clock)
    assert cms.request(1, duration_s=500)
    assert not cms.request(2)
    assert not cms.request(2)  # retry: same position, no duplicate
    assert not cms.request(3)
    assert cms.done(1) == [2]
    # node 2's duplicate must not consume the next free slot
    assert cms.done(2) == [3]


def test_cms_survives_reboot():
    store = MemBlobStore()
    clock = Clock()
    cms = Cms(store, max_unavailable=1, now=clock)
    cms.request(7, duration_s=500)
    cms2 = Cms(store, max_unavailable=1, now=clock)
    assert cms2.permitted(7)
    assert not cms2.request(8)
