"""Streaming scan pipeline tests: PK merge + newest-wins dedup, cluster
planning, fixed-capacity block stream, bounded-memory out-of-core scan
(SURVEY.md §2.7 scan reader; plain_reader/iterator/merge.cpp dedup)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.reader import PortionStreamSource, plan_clusters
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("v", dtypes.INT64),
)

COUNT = Program((GroupByStep(keys=(), aggs=(
    AggSpec(Agg.COUNT_ALL, None, "n"),
    AggSpec(Agg.SUM, "v", "s"),
)),))


def _shard(upsert=True, **cfg):
    store = MemBlobStore()
    return ColumnShard(
        "s1", SCHEMA, store, pk_column="id", upsert=upsert,
        config=ShardConfig(compact_portion_threshold=1000, **cfg),
    )


def _put(shard, ids, vals):
    wid = shard.write({"id": np.asarray(ids, dtype=np.int64),
                       "v": np.asarray(vals, dtype=np.int64)})
    return shard.commit([wid])


def _rows(shard, snap=None):
    src = PortionStreamSource(shard, shard.visible_portions(snap))
    out_i, out_v = [], []
    for blk in src.blocks(1 << 10):
        data = blk.to_numpy()
        n = int(blk.length)
        out_i += data["id"][:n].tolist()
        out_v += data["v"][:n].tolist()
    return dict(zip(out_i, out_v)), out_i


def test_upsert_same_pk_twice_sees_one_row():
    shard = _shard()
    _put(shard, [1, 2, 3], [10, 20, 30])
    snap1 = shard.snap
    _put(shard, [2], [99])
    rows, ids = _rows(shard)
    assert rows == {1: 10, 2: 99, 3: 30}
    assert len(ids) == 3  # the old row 2 is shadowed, not duplicated
    # older snapshot still sees the original value
    rows_old, _ = _rows(shard, snap=snap1)
    assert rows_old == {1: 10, 2: 20, 3: 30}


def test_upsert_within_batch_last_wins():
    shard = _shard()
    _put(shard, [7, 7, 7], [1, 2, 3])
    rows, ids = _rows(shard)
    assert rows == {7: 3}
    assert ids == [7]


def test_dedup_across_three_overlapping_portions():
    shard = _shard()
    _put(shard, [1, 2, 3, 4], [1, 1, 1, 1])
    _put(shard, [2, 3], [2, 2])
    _put(shard, [3, 5], [3, 3])
    rows, ids = _rows(shard)
    assert rows == {1: 1, 2: 2, 3: 3, 4: 1, 5: 3}
    assert sorted(ids) == [1, 2, 3, 4, 5]


def test_scan_program_respects_dedup():
    shard = _shard()
    _put(shard, list(range(100)), [1] * 100)
    _put(shard, list(range(50)), [2] * 50)   # overwrite half
    res = shard.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 100
    assert int(res.cols["s"][0][0]) == 50 * 1 + 50 * 2


def test_append_mode_keeps_duplicates():
    shard = _shard(upsert=False)
    _put(shard, [1, 2], [1, 1])
    _put(shard, [2, 3], [2, 2])
    rows, ids = _rows(shard)
    assert sorted(ids) == [1, 2, 2, 3]
    res = shard.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 4


def test_cluster_planning_overlap():
    from ydb_tpu.engine.portion import PortionMeta

    def m(pid, lo, hi, snap=1):
        return PortionMeta(pid, f"b{pid}", 10, snap, pk_min=lo, pk_max=hi)

    # [0,5] [3,8] overlap; [20,30] apart; statless joins everything
    c = plan_clusters([m(1, 0, 5), m(2, 3, 8), m(3, 20, 30)], dedup=True)
    assert [[p.portion_id for p in cl] for cl in c] == [[1, 2], [3]]
    c = plan_clusters([m(1, 0, 5), m(2, 3, 8), m(3, 20, 30)], dedup=False)
    assert len(c) == 3
    statless = PortionMeta(9, "b9", 10, 1)
    c = plan_clusters([m(1, 0, 5), m(3, 20, 30), statless], dedup=True)
    assert len(c) == 1 and len(c[0]) == 3


def test_block_capacities_stay_fixed():
    shard = _shard(upsert=False)
    for i in range(5):
        _put(shard, list(range(i * 100, i * 100 + 100)), [i] * 100)
    src = PortionStreamSource(shard, shard.visible_portions())
    caps = [b.capacity for b in src.blocks(128)]
    assert len(set(caps)) == 1  # one compiled program serves all blocks
    total = sum(int(b.length) for b in src.blocks(128))
    assert total == 500


def test_compaction_bounds_portion_size_and_dedups():
    shard = _shard(max_portion_rows=64)
    for i in range(6):
        _put(shard, list(range(0, 200, 2)), [i] * 100)  # same 100 keys
    shard.compact()
    live = shard.visible_portions()
    assert all(m.num_rows <= 64 for m in live)
    rows, ids = _rows(shard)
    assert len(ids) == 100
    assert set(rows.values()) == {5}  # newest write wins everywhere
    # clusters after compaction are all singletons (disjoint PK ranges)
    assert all(
        len(c) == 1 for c in plan_clusters(live, dedup=True)
    )


def test_sharded_table_upsert_across_shards():
    from ydb_tpu.tx.coordinator import Coordinator
    from ydb_tpu.tx.sharded import ShardedTable

    store = MemBlobStore()
    coord = Coordinator(MemBlobStore())
    t = ShardedTable("t", SCHEMA, store, coord, n_shards=3,
                     pk_column="id", upsert=True)
    t.insert({"id": np.arange(100, dtype=np.int64),
              "v": np.ones(100, dtype=np.int64)})
    t.insert({"id": np.arange(0, 100, 2, dtype=np.int64),
              "v": np.full(50, 7, dtype=np.int64)})
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 100
    assert int(res.cols["s"][0][0]) == 50 * 1 + 50 * 7
    # compaction keeps the dedup'd state
    for s in t.shards:
        s.compact()
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 100
    assert int(res.cols["s"][0][0]) == 50 * 1 + 50 * 7


PEAK_MB_HELPER = '''
def peak_mb() -> float:
    """True peak RSS of THIS process image, from /proc VmHWM.

    NOT resource.getrusage: on Linux ru_maxrss lives in the signal
    struct and SURVIVES execve, so a child forked from a fat parent
    (pytest with 200 tests of JAX buffers resident) inherits the
    parent's peak and reports ~1.4 GB before allocating a byte. VmHWM
    belongs to the mm, which execve replaces.
    """
    for line in open("/proc/self/status"):
        if line.startswith("VmHWM"):
            return float(line.split()[1]) / 1024.0
    raise RuntimeError("no VmHWM")
'''


def _run_rss_script(script: str, tmp_path) -> None:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c",
         PEAK_MB_HELPER + textwrap.dedent(script), str(tmp_path)],
        capture_output=True, text=True, env=env, timeout=900,
    )
    assert proc.returncode == 0, \
        (proc.stderr[-2000:] or "") + (proc.stdout[-500:] or "")


@pytest.mark.slow
def test_out_of_core_scan_bounded_rss(tmp_path):
    """Scan ~2.4 GB of disjoint-range portions under a 480 MB RSS cap
    (5x margin): the streaming reader must never materialize the table
    (VERDICT r1 item 2, r2 weak #2)."""
    _run_rss_script("""
        import sys
        import numpy as np
        import jax; jax.config.update("jax_platforms", "cpu")
        from ydb_tpu import dtypes
        from ydb_tpu.engine.blobs import DirBlobStore
        from ydb_tpu.engine.shard import ColumnShard, ShardConfig

        root = sys.argv[1]
        schema = dtypes.schema(("id", dtypes.INT64, False),
                               ("a", dtypes.INT64), ("b", dtypes.INT64))
        store = DirBlobStore(root)
        shard = ColumnShard(
            "big", schema, store, pk_column="id", upsert=True,
            config=ShardConfig(compact_portion_threshold=10**9,
                               scan_block_rows=1 << 18))
        rows_per_portion = 1 << 18      # 3 cols x 8B x 262k = ~6 MB
        n_portions = 400                # ~2.4 GB total, disjoint PK ranges
        for p in range(n_portions):
            base = p * rows_per_portion
            ids = np.arange(base, base + rows_per_portion, dtype=np.int64)
            wid = shard.write({"id": ids, "a": ids * 2, "b": ids % 7})
            shard.commit([wid])
        from ydb_tpu.ssa.ops import Agg
        from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program
        prog = Program((GroupByStep(keys=(), aggs=(
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "b", "s"),
        )),))
        res = shard.scan(prog)
        n = int(res.cols["n"][0][0])
        assert n == n_portions * rows_per_portion, n
        mb = peak_mb()
        print("peak_mb", mb)
        assert mb < 480, f"streaming scan exceeded RSS cap: {mb}"
    """, tmp_path)


@pytest.mark.slow
def test_overlapping_upsert_scan_bounded_rss(tmp_path):
    """The adversarial workload from VERDICT r2 weak #3: uniform-random
    upserts across the whole PK space make EVERY portion overlap every
    other — one giant cluster. The incremental K-way merge must still
    scan ~2 GB under a 400 MB cap (5x margin), with correct newest-wins
    dedup (no compaction to rescue it)."""
    _run_rss_script("""
        import sys
        import numpy as np
        import jax; jax.config.update("jax_platforms", "cpu")
        from ydb_tpu import dtypes
        from ydb_tpu.engine.blobs import DirBlobStore
        from ydb_tpu.engine.shard import ColumnShard, ShardConfig

        root = sys.argv[1]
        schema = dtypes.schema(("id", dtypes.INT64, False),
                               ("a", dtypes.INT64), ("b", dtypes.INT64))
        store = DirBlobStore(root)
        shard = ColumnShard(
            "hot", schema, store, pk_column="id", upsert=True,
            config=ShardConfig(compact_portion_threshold=10**9,
                               scan_block_rows=1 << 18,
                               portion_chunk_rows=1 << 12))
        rng = np.random.default_rng(7)
        key_space = 1 << 23             # 8.4M keys
        rows_per_portion = 1 << 18
        n_portions = 320                # ~2 GB raw, all-overlapping
        latest = np.full(key_space, -1, dtype=np.int32)  # oracle (32 MB)
        for p in range(n_portions):
            ids = rng.integers(0, key_space, rows_per_portion,
                               dtype=np.int64)
            wid = shard.write({"id": ids, "a": np.full(
                rows_per_portion, p, dtype=np.int64), "b": ids % 7})
            shard.commit([wid])
            latest[ids] = p
        seen = latest >= 0
        want_n = int(seen.sum())
        want_s = int(latest[seen].astype(np.int64).sum())
        del seen
        from ydb_tpu.ssa.ops import Agg
        from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program
        prog = Program((GroupByStep(keys=(), aggs=(
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.SUM, "a", "s"),
        )),))
        res = shard.scan(prog)
        n = int(res.cols["n"][0][0])
        s = int(res.cols["s"][0][0])
        assert n == want_n, (n, want_n)
        assert s == want_s, (s, want_s)
        mb = peak_mb()
        print("peak_mb", mb)
        assert mb < 400, f"overlap merge exceeded RSS cap: {mb}"
    """, tmp_path)
