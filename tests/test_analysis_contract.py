"""Unified-analyzer contract: ``python -m ydb_tpu.analysis --json``
emits one stable schema across all six pillars — a dict of stage ->
finding list, every finding carrying exactly
``{file, line, col, code, name, message}``. CI tooling and the
analysis gate parse this shape; a pillar drifting to its own schema is
a silent gate break."""

import json
import textwrap

from ydb_tpu.analysis import concurrency, devmem, hotpath, lifecycle, \
    lint
from ydb_tpu.analysis.__main__ import (
    _verify_selftest,
    format_findings,
    main,
    run_all,
)

STAGES = ("verify", "lint", "concurrency", "lifecycle", "hotpath",
          "devmem")
FIELDS = {"file", "line", "col", "code", "name", "message"}

#: one seeded violation per AST pillar, chosen from each pillar's
#: documented rule set (L005 / C005 / R001 / H001 / M001)
_SEEDS = {
    "lint": """
        def f(x=[]):
            return x
    """,
    "concurrency": """
        _cache = {}

        def put(k, v):
            _cache[k] = v
    """,
    "lifecycle": """
        class C:
            def f(self):
                self.lock.acquire()
                self.work()
                self.lock.release()
    """,
    "hotpath": """
        class Session:
            def _execute_admitted(self, sql):
                return out.item()
    """,
    "devmem": """
        import jax.numpy as jnp

        def stage(n):
            return jnp.zeros(n)
    """,
}


def _seeded(stage):
    src = textwrap.dedent(_SEEDS[stage])
    if stage == "lint":
        return lint.lint_source(src, "seed.py")
    if stage == "concurrency":
        return concurrency.check_source(src, "seed.py")
    if stage == "lifecycle":
        return lifecycle.check_source(src, "seed.py")
    if stage == "devmem":
        return devmem.check_source(src, "seed.py")
    return hotpath.check_source(src, "seed.py", modname="kqp.session")


def test_every_pillar_emits_the_unified_schema():
    for stage in ("lint", "concurrency", "lifecycle", "hotpath",
                  "devmem"):
        findings = _seeded(stage)
        assert findings, f"{stage} seed fired nothing"
        for f in findings:
            d = f.to_dict()
            assert set(d) == FIELDS, \
                f"{stage} finding schema drifted: {sorted(d)}"
            assert isinstance(d["line"], int)
            assert isinstance(d["col"], int)
            assert d["code"][0] in "LCRHM"
            # the JSON surface round-trips
            assert json.loads(json.dumps(d)) == d


def test_verify_selftest_dicts_match_the_schema():
    """The verify stage reports ready-made dicts (it checks programs,
    not files); on a healthy tree it reports none — force its failure
    shape by inspecting the synthesized payloads directly."""
    from ydb_tpu.analysis.__main__ import _verify_selftest

    assert _verify_selftest() == []  # healthy checker
    # schema of the synthesized failure payloads is pinned in source:
    # any drift would break this stage's JSON vs the other four
    import inspect

    src = inspect.getsource(_verify_selftest)
    for field in sorted(FIELDS):
        assert f'"{field}"' in src


def test_run_all_stage_order_and_shape(tmp_path):
    f = tmp_path / "ydb_tpu" / "kqp"
    f.mkdir(parents=True)
    (f / "session.py").write_text(textwrap.dedent(_SEEDS["hotpath"]))
    stages = run_all([tmp_path])
    assert tuple(stages) == STAGES
    assert [d["code"] for d in stages["hotpath"]] == ["H001"]
    for findings in stages.values():
        for d in findings:
            assert set(d) == FIELDS


def test_json_cli_round_trip(tmp_path, capsys):
    f = tmp_path / "ydb_tpu" / "kqp"
    f.mkdir(parents=True)
    (f / "session.py").write_text(textwrap.dedent(_SEEDS["hotpath"]))
    rc = main([str(tmp_path), "--json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert tuple(payload) == STAGES
    assert payload["hotpath"][0]["code"] == "H001"
    assert set(payload["hotpath"][0]) == FIELDS


def test_format_findings_is_readable():
    stages = {s: [] for s in STAGES}
    assert format_findings(stages) == "no findings"
    stages["hotpath"] = [d.to_dict() for d in _seeded("hotpath")]
    text = format_findings(stages)
    assert "hotpath: 1 finding(s)" in text
    assert "seed.py:4:" in text and "H001" in text
    assert "{" not in text  # never a raw dict dump
