"""Chaos fault-injection end-to-end (ydb_tpu/chaos): gates and seeded
replay, blob faults healed by RetryPolicy, conveyor delay/drop/worker
death with pool respawn, typed ConveyorTimeout surfaces, bit-identical
fallback chains (fused -> walk, resident -> staged host, mesh ->
single chip), statement deadlines -> StatementCancelled with resource
release, load shedding -> OverloadedError, and the ISSUE acceptance
scenario over TPC-H Q1/Q3/Q6."""

import threading
import time

import numpy as np
import pytest

from ydb_tpu import chaos
from ydb_tpu.chaos.deadline import Deadline, StatementCancelled
from ydb_tpu.chaos.retry import RetryPolicy
from ydb_tpu.kqp.rm import OverloadedError
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.runtime.conveyor import (Conveyor, ConveyorTimeout,
                                      ResourceBroker, shared_conveyor)


@pytest.fixture(autouse=True)
def _chaos_off_after():
    """Every test leaves the subsystem disarmed and gate-closed."""
    yield
    chaos.clear()
    chaos.CHAOS_FORCE = None


def _armed(scenario):
    chaos.CHAOS_FORCE = True
    chaos.install(scenario)


def _same_result(a, b):
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for name in a.schema.names:
        av, aok = a.cols[name]
        bv, bok = b.cols[name]
        np.testing.assert_array_equal(np.asarray(av), np.asarray(bv),
                                      err_msg=name)
        np.testing.assert_array_equal(np.asarray(aok), np.asarray(bok),
                                      err_msg=f"{name} validity")


def _kv_cluster(n=300):
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE kv (k Int64 NOT NULL, v Int64, "
              "PRIMARY KEY (k)) WITH (shards = 2)")
    t = c.tables["kv"]
    for off in range(0, n, n // 3):  # several portions per shard
        ks = list(range(off, min(n, off + n // 3)))
        t.insert({"k": ks, "v": [k * 7 for k in ks]})
    c._invalidate_plans()
    return c, s


AGG_SQL = ("SELECT k % 5 AS g, SUM(v) AS sv, COUNT(*) AS n FROM kv "
           "GROUP BY k % 5 ORDER BY g")


# ---------- gates, determinism, scenario DSL ----------

def test_gate_closed_by_default(monkeypatch):
    monkeypatch.delenv("YDB_TPU_CHAOS", raising=False)
    assert chaos.CHAOS_FORCE is None
    assert not chaos.chaos_enabled()
    with pytest.raises(RuntimeError):
        chaos.install(chaos.Scenario(seed=1, sites={
            "blob.get": {"kind": "io_error"}}))
    assert not chaos.armed()
    assert chaos.hit("blob.get") is None
    assert chaos.counters_snapshot() == {}


def test_force_overrides_env(monkeypatch):
    monkeypatch.setenv("YDB_TPU_CHAOS", "1")
    assert chaos.chaos_enabled()
    chaos.CHAOS_FORCE = False  # in-process pin beats the env
    assert not chaos.chaos_enabled()
    chaos.CHAOS_FORCE = True
    assert chaos.chaos_enabled()


def test_seeded_replay_is_deterministic():
    def fire_seq(seed):
        p = chaos.FaultPoint("blob.get", "io_error", p=0.5, seed=seed)
        return [p.roll() is not None for _ in range(20)]

    assert fire_seq(42) == fire_seq(42)
    assert fire_seq(42) != fire_seq(43)  # the seed IS the schedule


def test_sites_draw_independent_streams():
    # two sites under one scenario seed: removing one never shifts the
    # other's fire/skip sequence (per-site rng = seed ^ crc32(name))
    sc_both = chaos.Scenario(seed=9, sites={
        "blob.get": {"kind": "io_error", "p": 0.5},
        "conveyor.task": {"kind": "drop", "p": 0.5}})
    sc_one = chaos.Scenario(seed=9, sites={
        "blob.get": {"kind": "io_error", "p": 0.5}})

    def seq(sc):
        pt = sc.build_points()["blob.get"]
        return [pt.roll() is not None for _ in range(20)]

    assert seq(sc_both) == seq(sc_one)


def test_scenario_json_roundtrip(tmp_path):
    sc = chaos.Scenario(seed=7, sites={
        "blob.get_range": {"kind": "io_error", "p": 0.05},
        "mesh.dispatch": {"kind": "device_lost", "budget": 1},
        "conveyor.task": {"kind": "delay", "p": 0.1,
                          "latency": 0.001}})
    sc2 = chaos.Scenario.from_json(sc.to_json())
    assert sc2.seed == sc.seed and sc2.spec == sc.spec
    f = tmp_path / "scenario.json"
    f.write_text(sc.to_json())
    sc3 = chaos.Scenario.from_file(str(f))
    assert sc3.spec == sc.spec


def test_budget_caps_fires():
    p = chaos.FaultPoint("blob.get", "io_error", p=1.0, budget=3)
    fired = sum(p.roll() is not None for _ in range(10))
    assert fired == 3 and p.stats()["fired"] == 3
    assert p.stats()["hits"] == 10


# ---------- blob faults healed by RetryPolicy ----------

def test_blob_io_error_healed_by_retry():
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    _armed(chaos.Scenario(seed=21, sites={
        "blob.get_range": {"kind": "io_error", "p": 0.6, "budget": 6},
    }))
    got = s.execute(AGG_SQL)
    snap = chaos.counters_snapshot()
    assert snap["sites"]["blob.get_range"]["fired"] > 0  # faults DID fire
    assert sum(snap["retries"].values()) > 0  # ...and retries healed them
    _same_result(got, want)


def test_blob_torn_read_healed_by_refetch():
    # a torn read truncates the chunk: the decode fails, and ONLY a
    # re-fetch (fetch+decode retried as one unit) can heal it
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    _armed(chaos.Scenario(seed=5, sites={
        "blob.get_range": {"kind": "torn", "p": 1.0, "budget": 2},
    }))
    got = s.execute(AGG_SQL)
    assert chaos.counters_snapshot()["sites"]["blob.get_range"][
        "fired"] == 2
    _same_result(got, want)


def test_retry_policy_backoff_and_deadline():
    pol = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)
    assert pol.delay(0) == pytest.approx(0.001)
    assert pol.delay(1) == pytest.approx(0.002)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert pol.call(flaky, site="t.flaky") == "ok"
    assert len(calls) == 3
    # a spent deadline stops the retry loop with the LAST error
    calls.clear()
    with pytest.raises(OSError):
        pol.call(flaky, site="t.flaky", deadline=Deadline(0.0))
    assert len(calls) == 1


# ---------- conveyor faults + typed timeout surfaces ----------

def test_conveyor_task_drop_surfaces_error():
    conv = Conveyor(workers=1)
    try:
        _armed(chaos.Scenario(seed=3, sites={
            "conveyor.task": {"kind": "drop", "p": 1.0, "budget": 1}}))
        h = conv.submit("bg", lambda: 42)
        with pytest.raises(chaos.ChaosError):
            h.wait(timeout=5.0)
        chaos.clear()
        assert conv.submit("bg", lambda: 42).wait(timeout=5.0) == 42
    finally:
        conv.shutdown()


def test_conveyor_worker_death_respawns_pool():
    conv = Conveyor(workers=2)
    try:
        _armed(chaos.Scenario(seed=3, sites={
            "conveyor.task": {"kind": "worker_death", "p": 1.0,
                              "budget": 1}}))
        h = conv.submit("bg", lambda: 1)
        with pytest.raises(chaos.ChaosError):
            h.wait(timeout=5.0)
        chaos.clear()
        # the pool self-healed: full worker count, later tasks run
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if sum(t.is_alive() for t in conv._threads) == 2:
                break
            time.sleep(0.01)
        assert sum(t.is_alive() for t in conv._threads) == 2
        hs = [conv.submit("bg", lambda i=i: i * i) for i in range(4)]
        assert [h.wait(timeout=5.0) for h in hs] == [0, 1, 4, 9]
    finally:
        conv.shutdown()


def test_conveyor_delay_fault_just_slows():
    conv = Conveyor(workers=1)
    try:
        _armed(chaos.Scenario(seed=3, sites={
            "conveyor.task": {"kind": "delay", "p": 1.0, "budget": 1,
                              "latency": 0.02}}))
        t0 = time.perf_counter()
        assert conv.submit("bg", lambda: 7).wait(timeout=5.0) == 7
        assert time.perf_counter() - t0 >= 0.02
    finally:
        conv.shutdown()


def test_task_handle_wait_timeout_typed():
    conv = Conveyor(workers=1)
    ev = threading.Event()
    try:
        h = conv.submit("slowq", ev.wait, 5.0)
        with pytest.raises(ConveyorTimeout, match="slowq"):
            h.wait(timeout=0.01)
    finally:
        ev.set()
        conv.shutdown()


def test_wait_idle_names_busy_queues():
    conv = Conveyor(workers=1)
    ev = threading.Event()
    try:
        conv.submit("resident_promote", ev.wait, 5.0)
        with pytest.raises(ConveyorTimeout, match="resident_promote"):
            conv.wait_idle(timeout=0.05)
    finally:
        ev.set()
        conv.shutdown()


def test_broker_acquire_deadline_rejection():
    conv = Conveyor(workers=1, broker=ResourceBroker(quotas={"q": 1}))
    b = conv.broker
    b.acquire("q")  # holds the only slot
    try:
        with pytest.raises(ConveyorTimeout):
            b.acquire("q", deadline=Deadline(0.0))
        assert conv.queue_stats()["rejected_deadline"] == 1
    finally:
        b.release("q")
        conv.shutdown()


# ---------- bit-identical fallback chains ----------

def test_fused_to_walk_fallback_identical():
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    _armed(chaos.Scenario(seed=11, sites={
        "fuse.trace": {"kind": "io_error", "p": 1.0}}))
    got = s.execute(AGG_SQL)
    snap = chaos.counters_snapshot()
    assert snap["fallbacks"].get("fuse.trace", 0) >= 1
    _same_result(got, want)


def test_resident_to_host_fallback_identical():
    from ydb_tpu import dtypes
    from ydb_tpu.engine import resident as resident_mod
    from ydb_tpu.engine.blobs import MemBlobStore
    from ydb_tpu.engine.shard import ColumnShard
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep
    from ydb_tpu.ssa.program import Program

    schema = dtypes.schema(("id", dtypes.INT64, False),
                           ("val", dtypes.INT64))
    prev = resident_mod.RESIDENT_FORCE
    resident_mod.RESIDENT_FORCE = True
    try:
        shard = ColumnShard("chres", schema, MemBlobStore(),
                            pk_column="id")
        shard.commit([shard.write({
            "id": np.arange(200, dtype=np.int64),
            "val": np.arange(200, dtype=np.int64) * 3})])
        shard.resident.drain()
        assert shard.resident.snapshot()["portions"] == 1
        prog = Program((GroupByStep(keys=(), aggs=(
            AggSpec(Agg.SUM, "val", "s"),
            AggSpec(Agg.COUNT_ALL, None, "n"))),))
        want = shard.scan(prog)
        hits0 = shard.resident.hits
        shard.scan(prog)
        assert shard.resident.hits > hits0  # baseline IS resident-served
        # injected decode error mid-stream: the scan degrades to the
        # staged-host path for that portion, bit-identical
        _armed(chaos.Scenario(seed=2, sites={
            "resident.lookup": {"kind": "io_error", "p": 1.0}}))
        misses0 = shard.resident.misses
        got = shard.scan(prog)
        assert shard.resident.misses > misses0
        assert chaos.counters_snapshot()["fallbacks"][
            "resident.lookup"] >= 1
        _same_result(got, want)
    finally:
        resident_mod.RESIDENT_FORCE = prev


def test_mesh_device_loss_falls_back_identical():
    from ydb_tpu.plan import executor as ex

    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    c.enable_mesh()
    mesh_returns = []
    orig = ex._execute_plan_mesh

    def spy(p, d):
        r = orig(p, d)
        mesh_returns.append(r)
        return r

    _armed(chaos.Scenario(seed=4, sites={
        "mesh.dispatch": {"kind": "device_lost", "budget": 1}}))
    ex._execute_plan_mesh = spy
    try:
        got = s.execute(AGG_SQL)
    finally:
        ex._execute_plan_mesh = orig
    # the mesh WAS tried, lost a device, and the single-chip fallback
    # produced the same rows
    assert mesh_returns and mesh_returns[0] is None
    snap = chaos.counters_snapshot()
    assert snap["sites"]["mesh.dispatch"]["fired"] == 1
    assert snap["fallbacks"].get("mesh.dispatch", 0) >= 1
    _same_result(got, want)
    chaos.clear()
    got2 = s.execute(AGG_SQL)  # budget spent: mesh serves again
    _same_result(got2, want)


# ---------- statement deadlines + load shedding ----------

def test_statement_timeout_cancels_with_typed_reason():
    c, s = _kv_cluster()
    with pytest.raises(StatementCancelled):
        s.execute(AGG_SQL, timeout=0.0)
    p = s.last_profile
    assert p.error == 1 and p.error_reason == "cancelled"
    out = s.execute("SELECT query_text, error, error_reason "
                    "FROM sys_top_queries WHERE error = 1")
    assert out.num_rows >= 1
    reasons = [v.decode() for v in out.strings("error_reason")]
    assert "cancelled" in reasons
    # cancellation released its conveyor work: the pool drains idle
    shared_conveyor().wait_idle(timeout=10.0)
    qs = shared_conveyor().queue_stats()
    assert qs["depth"] == 0 and qs["active"] == 0
    # and the engine still serves (no wedged slot/quota)
    assert s.execute(AGG_SQL, timeout=30.0).num_rows == 5


def test_overload_shedding_typed_error():
    c, s = _kv_cluster()
    c.max_inflight_statements = 1
    tok = c._register_active("sleeper", time.monotonic())
    try:
        with pytest.raises(OverloadedError):
            s.execute(AGG_SQL)
    finally:
        c._unregister_active(tok)
        c.max_inflight_statements = 0
    assert s.last_profile.error == 1
    assert s.last_profile.error_reason == "overloaded"
    out = s.execute("SELECT error_reason FROM sys_top_queries "
                    "WHERE error = 1")
    assert "overloaded" in [v.decode()
                            for v in out.strings("error_reason")]


def test_chaos_admission_overload_site():
    c, s = _kv_cluster()
    _armed(chaos.Scenario(seed=8, sites={
        "session.admit": {"kind": "overload", "p": 1.0, "budget": 1}}))
    with pytest.raises(OverloadedError):
        s.execute(AGG_SQL)
    # budget spent: the next statement is admitted
    assert s.execute(AGG_SQL).num_rows == 5


def test_chaos_counters_exported_by_run_background():
    c, s = _kv_cluster()
    _armed(chaos.Scenario(seed=13, sites={
        "blob.get_range": {"kind": "io_error", "p": 0.5, "budget": 2}}))
    s.execute(AGG_SQL)
    c.run_background()
    snap = c.counters.snapshot()
    fired = [v for k, v in snap.items()
             if k.startswith("fired|") and "component=chaos" in k]
    assert fired and max(fired) > 0


# ---------- the ISSUE acceptance scenario ----------

def _tpch_cluster(sf=0.002):
    """Cluster holding TPC-H lineitem/orders/customer, several
    portions per table (the test_query_profile loader generalized)."""
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=7)
    c = Cluster()
    s = c.session()
    pks = {"lineitem": "l_orderkey", "orders": "o_orderkey",
           "customer": "c_custkey"}
    for tname, pk in pks.items():
        schema = data.schema(tname)
        cols = ", ".join(f"{f.name} {type_to_str(f.type)}"
                         for f in schema.fields)
        s.execute(f"CREATE TABLE {tname} ({cols}, "
                  f"PRIMARY KEY ({pk})) WITH (shards = 1)")
        src = data.tables[tname]
        t = c.tables[tname]
        n = len(src[pk])
        step = max(1, n // 3)
        for off in range(0, n, step):  # 3 commits -> 3 portions
            arrays = {}
            for f in schema.fields:
                v = src[f.name][off:off + step]
                if f.type.is_string:
                    arrays[f.name] = [
                        bytes(x) for x in data.dicts[f.name].decode(
                            np.asarray(v, dtype=np.int32))]
                else:
                    arrays[f.name] = v
            t.insert(arrays)
    c._invalidate_plans()
    return c, s


def test_acceptance_scenario_q1_q3_q6():
    """The ISSUE's seeded scenario: blob-read faults at p=0.05, one
    injected mesh device loss, and a fifth of statements pushed past
    their deadline — TPC-H Q1/Q3/Q6 complete, surviving queries
    bit-identical to fault-free, every cancelled statement surfacing a
    typed error in sys_top_queries, and no leaked conveyor tasks or
    resident-promotion flights afterwards. The whole scenario runs
    under the leak sanitizer: every seeded fault + cancellation must
    ALSO drain every tracked handle kind to zero (PR 13's invariant)."""
    from test_sql import Q1_SQL, Q3_SQL, Q6_SQL

    from ydb_tpu.analysis import leaksan
    from ydb_tpu.engine import resident as resident_mod

    with leaksan.activate():
        _acceptance_scenario(Q1_SQL, Q3_SQL, Q6_SQL, resident_mod,
                             leaksan)


def _acceptance_scenario(Q1_SQL, Q3_SQL, Q6_SQL, resident_mod,
                         leaksan):
    c, s = _tpch_cluster()
    queries = {"q1": Q1_SQL, "q3": Q3_SQL, "q6": Q6_SQL}
    want = {name: s.execute(sql) for name, sql in queries.items()}
    c.enable_mesh()

    _armed(chaos.Scenario(seed=42, sites={
        "blob.get_range": {"kind": "io_error", "p": 0.05},
        "mesh.dispatch": {"kind": "device_lost", "budget": 1},
    }))
    cancelled = 0
    stmt = 0
    for _round in range(2):
        for name, sql in queries.items():
            stmt += 1
            # cold block cache: chunk reads actually cross the faulted
            # blob surface instead of being served warm
            c.scan_block_cache.clear()
            if stmt % 5 == 0:  # 20% of statements past deadline
                with pytest.raises(StatementCancelled):
                    s.execute(sql, timeout=0.0)
                cancelled += 1
                assert s.last_profile.error_reason == "cancelled"
            else:
                got = s.execute(sql, timeout=60.0)
                _same_result(got, want[name])
    assert cancelled >= 1
    snap = chaos.counters_snapshot()
    assert snap["sites"]["blob.get_range"]["hits"] > 0
    # every cancelled statement surfaces typed in sys_top_queries
    out = s.execute("SELECT error_reason FROM sys_top_queries "
                    "WHERE error = 1")
    reasons = [v.decode() for v in out.strings("error_reason")]
    assert reasons.count("cancelled") >= cancelled
    chaos.clear()
    # nothing leaked: the conveyor drains to zero...
    shared_conveyor().wait_idle(timeout=30.0)
    qs = shared_conveyor().queue_stats()
    assert qs["depth"] == 0 and qs["active"] == 0
    # ...and resident-promotion flights opened after the scenario
    # (heat-driven async promotions on the conveyor) all land or
    # discard — no stranded _inflight entries
    prev_res = resident_mod.RESIDENT_FORCE
    resident_mod.RESIDENT_FORCE = True
    try:
        for _ in range(2):  # cross PROMOTE_HEAT on every portion
            for sql in queries.values():
                s.execute(sql)
        promoted = 0
        for t in c.tables.values():
            for sh in t.shards:
                store = getattr(sh, "resident", None)
                if store is None:
                    continue
                store.drain()
                psnap = store.snapshot()
                promoted += psnap["promotions"]
                assert psnap["inflight"] == 0
        assert promoted > 0
    finally:
        resident_mod.RESIDENT_FORCE = prev_res
    # the closing invariant: after faults, cancellations, device loss
    # and async promotions, EVERY tracked resource kind has drained —
    # conveyor tasks, broker slots, resident/blockcache flights,
    # session registry rows, rm grants, spilled blobs
    shared_conveyor().wait_idle(timeout=30.0)
    assert leaksan.counts() == {}, leaksan.counts()
    leaksan.assert_drained(where="chaos acceptance scenario")
