"""Tier-1 enforcement: the dispatch-purity analyzer runs clean over
the whole ydb_tpu package (the H-rule analog of test_lint_clean /
test_concurrency_clean / test_lifecycle_clean). A finding here means a
code change put host work on the warm statement corridor — fix the
code, mark a deliberate boundary ``@analysis.host_ok("reason")``, or
justify a reviewed site with a ``# ydb-lint: disable=H00x`` pragma."""

import ast
from pathlib import Path

from ydb_tpu.analysis import hotpath
from ydb_tpu.analysis.paths import collect_files

PKG = Path(hotpath.__file__).resolve().parents[1]


def test_hotpath_clean_tree_wide():
    findings = hotpath.check_paths(collect_files([PKG]))
    msg = "\n".join(f.render() for f in findings)
    assert findings == [], \
        f"{len(findings)} hot-path finding(s):\n{msg}"


def test_every_declared_root_resolves():
    """Each HOT_ROOT must name a real function — a rename would
    otherwise silently shrink the corridor and the clean test above
    would pass vacuously."""
    modules = []
    for f in collect_files([PKG]):
        try:
            tree = ast.parse(f.read_text(encoding="utf-8"),
                             filename=str(f))
        except SyntaxError:
            continue
        modules.append(hotpath._Module(
            hotpath._modname_for(str(f)), str(f), tree))
    index = hotpath._Index(modules)
    for suffix, qual in hotpath.HOT_ROOTS:
        m = index.by_suffix(suffix)
        assert m is not None, f"root module {suffix!r} not found"
        assert qual in m.fns, \
            f"root {qual!r} missing from {suffix!r} — renamed?"
