"""Multi-tenant front door end-to-end (ydb_tpu/serving): tenant
resolution and weighted shares, per-pool admission seats with typed
shedding and deadline-ordered queues, the tenant column / pool view /
per-tenant SLO gauges on the observability surface, cross-CONNECTION
pgwire batching (two sockets, one device dispatch group), two-tenant
noisy-neighbor isolation under the seeded chaos scenario, and the
1k-connection churn soak draining every serving.* leak handle."""

import pathlib
import threading
import time

import pytest

from test_batching import _armed, _lineitem_cluster, _same_result
from test_pgwire import MiniPgClient
from test_sql import Q1_SQL, Q6_SQL

from ydb_tpu import chaos, serving
from ydb_tpu.analysis import leaksan
from ydb_tpu.api.pgwire import PgWireServer
from ydb_tpu.chaos.deadline import StatementCancelled
from ydb_tpu.kqp.rm import OverloadedError
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.runtime.conveyor import shared_conveyor


@pytest.fixture(autouse=True)
def _chaos_off_after():
    yield
    chaos.clear()
    chaos.CHAOS_FORCE = None


@pytest.fixture(scope="module")
def front():
    """One lineitem cluster behind a front door with every tenant the
    module's tests use, plus a live pgwire listener."""
    c = _lineitem_cluster()
    # the per-tenant caps are the shed boundary under test; park the
    # legacy global valve far out of the way
    c.max_inflight_statements = max(c.max_inflight_statements, 1024)
    reg = serving.TenantRegistry()
    reg.register("gold", weight=3.0, max_inflight=32)
    reg.register("bronze", weight=1.0, max_inflight=16)
    reg.register("noisy", weight=1.0, max_inflight=2, queue_size=2)
    reg.register("victim", weight=2.0, max_inflight=8)
    reg.register("small", weight=0.5, max_inflight=1, queue_size=0)
    reg.bind_principal("gold-token", "gold")
    serving.install(c, reg)
    s = c.session()
    for sql in (Q1_SQL, Q6_SQL):  # warm plan + compile caches
        s.execute(sql)
    srv = PgWireServer(c).start()
    yield c, srv
    srv.stop()
    c.stop()


# ---------------- registry + statement classification ----------------

def test_registry_resolution_order():
    reg = serving.TenantRegistry()
    reg.register("gold", weight=3.0)
    reg.bind_principal("alice", "gold")
    # explicit registered tenant wins
    assert reg.resolve(tenant="gold", principal="bob") == "gold"
    # then the principal binding
    assert reg.resolve(principal="alice") == "gold"
    # unknown names and untagged clients land in the default pool
    assert reg.resolve(tenant="typo") == serving.DEFAULT_TENANT
    assert reg.resolve() == serving.DEFAULT_TENANT
    # an unknown tenant keeps the default pool's entitlements
    assert reg.get("typo").name == serving.DEFAULT_TENANT


def test_weighted_shares_floor():
    reg = serving.TenantRegistry()
    reg.register("big", weight=30.0)
    reg.register("tiny", weight=0.01)
    shares = reg.shares(16)
    assert shares["big"] > shares["tiny"]
    # a tiny weight degrades to trickle, never to zero
    assert shares["tiny"] == 1
    assert shares[serving.DEFAULT_TENANT] >= 1


def test_is_read_statement():
    assert serving.is_read_statement("SELECT 1 FROM t")
    assert serving.is_read_statement("  explain select k from t")
    assert serving.is_read_statement("-- note\nSELECT k FROM t")
    assert serving.is_read_statement("/* hint */ SELECT k FROM t")
    assert not serving.is_read_statement("INSERT INTO t VALUES (1)")
    assert not serving.is_read_statement("CREATE TABLE t (k int64)")
    assert not serving.is_read_statement("BEGIN")
    assert not serving.is_read_statement("-- dangling comment")


# ---------------- the admission plane itself ----------------

def test_front_door_shed_names_pool():
    c = Cluster()
    try:
        reg = serving.TenantRegistry()
        reg.register("small", max_inflight=1, queue_size=0)
        fd = serving.install(c, reg)
        seat = fd.admit("small")
        with pytest.raises(OverloadedError, match="small"):
            fd.admit("small")
        snap = fd.snapshot()["small"]
        assert snap["inflight"] == 1 and snap["shed"] == 1
        # ...while another tenant admits freely: per-pool isolation
        fd.admit("other").release()
        seat.release()
        fd.admit("small").release()
        snap = fd.snapshot()["small"]
        assert snap["inflight"] == 0 and snap["admitted"] == 2
        # the shed/admitted telemetry rides the cluster counters
        keys = [k for k in c.counters.snapshot()
                if "component=serving" in k and "tenant=small" in k]
        assert any(k.startswith("admitted") for k in keys)
        assert any(k.startswith("shed") for k in keys)
    finally:
        c.stop()


def test_edf_orders_queued_admissions():
    c = Cluster()
    try:
        reg = serving.TenantRegistry()
        reg.register("edf", max_inflight=1, queue_size=8)
        fd = serving.install(c, reg)
        seat = fd.admit("edf")
        order = []
        rec = threading.Lock()
        now = time.monotonic()

        def waiter(tag, dl):
            s = fd.admit("edf", deadline_at=dl, timeout=10.0)
            with rec:
                order.append(tag)
            s.release()

        # FIFO arrival far-then-near; EDF grant must invert it
        far = threading.Thread(target=waiter, args=("far", now + 60))
        far.start()
        while fd.snapshot()["edf"]["queued"] < 1:
            time.sleep(0.001)
        near = threading.Thread(target=waiter, args=("near", now + 30))
        near.start()
        while fd.snapshot()["edf"]["queued"] < 2:
            time.sleep(0.001)
        seat.release()
        far.join(10.0)
        near.join(10.0)
        assert order == ["near", "far"]
        # a queued admission whose deadline already passed is shed
        # instead of consuming a grant
        seat = fd.admit("edf")
        with pytest.raises(OverloadedError):
            fd.admit("edf", deadline_at=time.monotonic() - 1.0)
        seat.release()
    finally:
        c.stop()


def test_session_overload_is_typed_and_named(front):
    c, _ = front
    fd = c.front_door
    blocker = fd.admit("small")  # cap 1, queue 0: next admit sheds
    try:
        s = c.session()
        s.tenant = "small"
        with pytest.raises(OverloadedError, match="small"):
            s.execute(Q6_SQL)
        assert getattr(s.last_profile, "error_reason", None) \
            == "overloaded"
    finally:
        blocker.release()
    # seat released on the error path: the pool recovers
    s2 = c.session()
    s2.tenant = "small"
    assert s2.execute(Q6_SQL).num_rows > 0
    assert fd.snapshot()["small"]["inflight"] == 0


# ---------------- observability surface ----------------

def test_tenant_rides_profile_views_and_gauges(front):
    c, _ = front
    s = c.session()
    s.tenant = "gold"
    out = s.execute(Q1_SQL)
    assert out.num_rows > 0
    assert s.last_profile.tenant == "gold"
    view = s.execute("SELECT tenant FROM sys_top_queries")
    assert "gold" in {v.decode() for v in view.strings("tenant")}
    # a statement reading sys_active_queries observes ITSELF labeled
    live = s.execute("SELECT tenant FROM sys_active_queries")
    assert "gold" in {v.decode() for v in live.strings("tenant")}
    pools = s.execute(
        "SELECT tenant, weight, max_inflight, admitted, shed, "
        "pool_limit, conveyor_workers FROM sys_tenant_pools")
    names = {v.decode() for v in pools.strings("tenant")}
    assert {"default", "gold", "bronze", "noisy", "victim",
            "small"} <= names
    # per-tenant SLO gauges on the prometheus surface
    c.run_background()
    prom = c.counters.encode_prometheus()
    assert 'tenant="gold"' in prom
    assert "query_latency_p99" in prom


# ---------------- protocol fronts ----------------

def test_pgwire_tenant_startup_param(front):
    c, srv = front
    fd = c.front_door
    base = fd.snapshot()["bronze"]["admitted"]
    cl = MiniPgClient(srv.port, startup={"tenant": "bronze"})
    rows, _, tags, errors = cl.query(Q6_SQL)
    cl.close()
    assert not errors and rows
    assert fd.snapshot()["bronze"]["admitted"] > base


def test_pgwire_unknown_tenant_lands_in_default(front):
    c, srv = front
    base = c.front_door.snapshot()["default"]["admitted"]
    cl = MiniPgClient(srv.port, startup={"tenant": "no-such-pool"})
    _, _, _, errors = cl.query(Q6_SQL)
    cl.close()
    assert not errors
    assert c.front_door.snapshot()["default"]["admitted"] > base


def test_cross_connection_pgwire_batching(front):
    """The acceptance bar: the same warm SELECT from two DIFFERENT
    network connections joins ONE batch group (group size >= 2) — the
    window sees the cross-client queue because pgwire reads run
    outside the server's connection-serial lock."""
    c, srv = front
    bt0 = c.batcher.snapshot()
    clients = [MiniPgClient(srv.port) for _ in range(2)]
    results = [None, None]
    errors = [None, None]
    barrier = threading.Barrier(2)

    def work(i):
        try:
            barrier.wait()
            results[i] = clients[i].query(Q1_SQL)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[i] = e

    with _armed(c, window_ms=500, max_batch=2):
        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30.0)
    for cl in clients:
        cl.close()
    assert errors == [None, None]
    rows0, _, tags0, errs0 = results[0]
    rows1, _, tags1, errs1 = results[1]
    assert not errs0 and not errs1
    assert rows0 and rows0 == rows1  # same statement, same answer
    snap = c.batcher.snapshot()
    assert snap["batches"] >= bt0["batches"] + 1
    assert snap["batched_statements"] >= bt0["batched_statements"] + 2
    assert snap["max_batch_size"] >= 2


# ---------------- SLO isolation under the chaos scenario ----------------

def test_two_tenant_isolation_noisy_neighbor(front):
    """Tenant 'noisy' deadline-storms and cancel-floods its pool (cap
    2, queue 2) with the seeded noisy_neighbor chaos scenario armed on
    top; tenant 'victim' runs warm Q1 the whole time. The victim's
    answers stay bit-identical, its pool never sheds, its worst-case
    latency stays bounded, the noisy pool DID shed, the faults DID
    fire, and every leak-sanitizer handle drains to zero."""
    c, _ = front
    fd = c.front_door
    scen = chaos.Scenario.from_file(
        str(pathlib.Path(chaos.__file__).parent
            / "noisy_neighbor.json"))

    with leaksan.activate():
        vs = c.session()
        vs.tenant = "victim"
        want = vs.execute(Q1_SQL)

        chaos.CHAOS_FORCE = True
        chaos.install(scen)
        stop = threading.Event()
        rec = threading.Lock()
        stats = {"cancelled": 0, "shed": 0, "other": []}

        def noisy_worker():
            s = c.session()
            s.tenant = "noisy"
            while not stop.is_set():
                try:
                    # the storm: every statement already past deadline
                    s.execute(Q6_SQL, timeout=0.0)
                except StatementCancelled:
                    with rec:
                        stats["cancelled"] += 1
                except OverloadedError:
                    with rec:
                        stats["shed"] += 1
                except Exception as e:  # noqa: BLE001 - surfaced below
                    with rec:
                        stats["other"].append(repr(e)[-200:])
                    return

        storms = [threading.Thread(target=noisy_worker)
                  for _ in range(4)]
        for t in storms:
            t.start()
        lat = []
        try:
            for _ in range(20):
                t0 = time.perf_counter()
                got = vs.execute(Q1_SQL, timeout=30.0)
                lat.append(time.perf_counter() - t0)
                _same_result(got, want)
        finally:
            stop.set()
            for t in storms:
                t.join(20.0)
        snap = chaos.counters_snapshot()
        assert snap["sites"]["serving.admit"]["fired"] > 0
        chaos.clear()
        assert stats["other"] == []
        assert stats["cancelled"] > 0  # the storm really ran
        assert stats["shed"] > 0       # ...and overflowed its own pool
        door = fd.snapshot()
        assert door["noisy"]["shed"] > 0
        assert door["victim"]["shed"] == 0  # isolation by construction
        # worst-case victim latency stays inside a generous SLO while
        # 4 threads hammer the neighbor pool (warm Q1 is ~10ms here;
        # the bound only has to exclude starvation, not jitter)
        assert max(lat) < 5.0
        # the whole storm drains: seats, conns, tasks, flights
        shared_conveyor().wait_idle(timeout=30.0)
        assert not leaksan.counts()


# ---------------- connection-churn leak soak ----------------

def test_connection_churn_soak_drains(front):
    """1k pgwire connects/disconnects (the acceptance soak): every
    serving.conn handle must drain once the sockets close."""
    c, srv = front
    with leaksan.activate():
        held = MiniPgClient(srv.port, startup={"tenant": "gold"})
        # a query roundtrip proves the session loop (and its conn
        # handle) is live — the handshake alone races the handler
        held.query(Q6_SQL)
        assert leaksan.counts().get("serving.conn", 0) >= 1
        churned = [0]
        rec = threading.Lock()

        def churn(n):
            for _ in range(n):
                MiniPgClient(srv.port).close()
                with rec:
                    churned[0] += 1

        threads = [threading.Thread(target=churn, args=(125,))
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        assert churned[0] == 1000
        held.close()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            counts = leaksan.counts()
            if not counts.get("serving.conn") \
                    and not counts.get("serving.seat"):
                break
            time.sleep(0.05)
        counts = leaksan.counts()
        assert not counts.get("serving.conn"), counts
        assert not counts.get("serving.seat"), counts


# ---------------- gRPC-style front (skipped without protoc) ----------------

def test_request_proxy_close_drains_sessions():
    """RequestProxy sessions are serving.conn handles; close() must
    drop every server-side session (and join operation threads) so
    Cluster.stop's drain assertion passes."""
    try:
        from ydb_tpu.api import server as api_server
    except Exception as e:  # noqa: BLE001 - protoc-less containers
        pytest.skip(f"api.server unavailable: {e!r}")

    class Ctx:
        def invocation_metadata(self):
            return []

        def abort(self, code, msg):
            raise RuntimeError(msg)

    with leaksan.activate():
        c = Cluster()
        serving.install(c)
        proxy = api_server.RequestProxy(c)
        for _ in range(5):
            proxy.create_session(
                api_server.pb.CreateSessionRequest(), Ctx())
        assert leaksan.counts().get("serving.conn") == 5
        proxy.close()
        assert not leaksan.counts().get("serving.conn")
        c.stop()
