"""Concurrency-discipline analyzer: rule unit tests + the tier-1
enforcement that the whole ydb_tpu tree runs clean under C001-C008
(mirrors test_lint_clean.py — a new lock-discipline violation fails CI
until fixed or explicitly suppressed with a justification)."""

import subprocess
from pathlib import Path

from ydb_tpu.analysis.concurrency import (
    RULES,
    check_paths,
    check_source,
    main,
)
from ydb_tpu.analysis.paths import collect_files

PKG = Path(__file__).resolve().parents[1] / "ydb_tpu"


def codes(src: str) -> list:
    return [f.code for f in check_source(src, "t.py")]


# ---------------- enforcement ----------------


def test_repo_runs_clean():
    findings = check_paths(collect_files([PKG]))
    assert findings == [], "\n".join(f.render() for f in findings)


def test_cli_exit_code_clean_and_dirty(tmp_path, capsys):
    assert main([str(PKG)]) == 0
    bad = tmp_path / "bad.py"
    bad.write_text("_cache = {}\n"
                   "def put(k, v):\n"
                   "    _cache[k] = v\n")
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "C005" in out


def test_json_report(tmp_path, capsys):
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("_cache = {}\n"
                   "def put(k, v):\n"
                   "    _cache[k] = v\n")
    assert main([str(bad), "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep[0]["code"] == "C005"
    assert rep[0]["line"] == 3


# ---------------- C001 guard-inconsistency ----------------


def test_c001_attr_written_under_and_outside_lock():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._cache = {}\n"
           "    def put(self, k, v):\n"
           "        with self._lock:\n"
           "            self._cache[k] = v\n"
           "    def evict(self, k):\n"
           "        self._cache.pop(k, None)\n")
    assert "C001" in codes(src)


def test_c001_init_writes_exempt():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._cache = {}\n"
           "        self._cache['seed'] = 1\n"
           "    def put(self, k, v):\n"
           "        with self._lock:\n"
           "            self._cache[k] = v\n")
    assert codes(src) == []


def test_c001_interprocedural_guard_through_private_helper():
    # a private helper called only under the lock inherits the guard
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._m = {}\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            self._put()\n"
           "    def _put(self):\n"
           "        self._m['a'] = 1\n"
           "    def g(self):\n"
           "        with self._lock:\n"
           "            self._m.pop('a', None)\n")
    assert codes(src) == []


def test_c001_helper_also_called_unlocked_is_flagged():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._m = {}\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            self._put()\n"
           "    def g(self):\n"
           "        self._put()\n"
           "    def _put(self):\n"
           "        self._m['a'] = 1\n")
    assert "C001" in codes(src)


def test_c001_condition_aliases_its_wrapped_lock():
    # Condition(self._lock): with either guards the same lock
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._freed = threading.Condition(self._lock)\n"
           "        self._n = {}\n"
           "    def a(self):\n"
           "        with self._lock:\n"
           "            self._n['x'] = 1\n"
           "    def b(self):\n"
           "        with self._freed:\n"
           "            self._n.pop('x', None)\n")
    assert codes(src) == []


# ---------------- C002 lock ordering ----------------


def test_c002_two_lock_cycle():
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.l1 = threading.Lock()\n"
           "        self.l2 = threading.Lock()\n"
           "    def f(self):\n"
           "        with self.l1:\n"
           "            with self.l2:\n"
           "                pass\n"
           "    def g(self):\n"
           "        with self.l2:\n"
           "            with self.l1:\n"
           "                pass\n")
    assert "C002" in codes(src)


def test_c002_consistent_order_clean():
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.l1 = threading.Lock()\n"
           "        self.l2 = threading.Lock()\n"
           "    def f(self):\n"
           "        with self.l1:\n"
           "            with self.l2:\n"
           "                pass\n"
           "    def g(self):\n"
           "        with self.l1:\n"
           "            with self.l2:\n"
           "                pass\n")
    assert codes(src) == []


def test_c002_nonreentrant_self_deadlock():
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.l1 = threading.Lock()\n"
           "    def f(self):\n"
           "        with self.l1:\n"
           "            with self.l1:\n"
           "                pass\n")
    assert "C002" in codes(src)


def test_c002_rlock_reentry_ok():
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.l1 = threading.RLock()\n"
           "    def f(self):\n"
           "        with self.l1:\n"
           "            with self.l1:\n"
           "                pass\n")
    assert codes(src) == []


def test_c002_cross_class_cycle_via_typed_attrs():
    src = ("import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.lock = threading.Lock()\n"
           "        self.b = B()\n"
           "    def f(self):\n"
           "        with self.lock:\n"
           "            self.b.g()\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self.lock = threading.Lock()\n"
           "        self.a = A()\n"
           "    def g(self):\n"
           "        with self.lock:\n"
           "            pass\n"
           "    def h(self):\n"
           "        with self.lock:\n"
           "            self.a.f()\n")
    assert "C002" in codes(src)


# ---------------- C003 blocking under lock ----------------


def test_c003_sleep_under_lock():
    src = ("import threading, time\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            time.sleep(1.0)\n")
    assert "C003" in codes(src)


def test_c003_queue_get_untimed_under_lock():
    src = ("import threading, queue\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._q = queue.Queue()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            return self._q.get()\n")
    assert "C003" in codes(src)


def test_c003_timed_get_ok():
    src = ("import threading, queue\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._q = queue.Queue()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            return self._q.get(timeout=0.1)\n")
    assert codes(src) == []


def test_c003_own_condition_wait_ok_foreign_lock_flagged():
    # waiting on your own condition releases it — fine; holding a
    # SECOND lock across the wait is the deadlock shape
    ok = ("import threading\n"
          "class C:\n"
          "    def __init__(self):\n"
          "        self._cv = threading.Condition()\n"
          "    def f(self):\n"
          "        with self._cv:\n"
          "            self._cv.wait()\n")
    assert codes(ok) == []
    bad = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self._cv = threading.Condition()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            with self._cv:\n"
           "                self._cv.wait()\n")
    assert "C003" in codes(bad)


def test_c003_own_condition_wait_via_helper_ok():
    # the helper waits on the condition the CALLER holds: wait()
    # releases it, so the propagated finding would be a false positive
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "    def f(self):\n"
           "        with self._cv:\n"
           "            self._park()\n"
           "    def _park(self):\n"
           "        self._cv.wait()\n")
    assert codes(src) == []


def test_c002_module_rlock_reentry_ok():
    src = ("import threading\n"
           "_L = threading.RLock()\n"
           "class A:\n"
           "    def f(self):\n"
           "        with _L:\n"
           "            with _L:\n"
           "                pass\n")
    assert codes(src) == []


def test_c002_module_plain_lock_reentry_flagged():
    src = ("import threading\n"
           "_L = threading.Lock()\n"
           "class A:\n"
           "    def f(self):\n"
           "        with _L:\n"
           "            with _L:\n"
           "                pass\n")
    assert "C002" in codes(src)


def test_c003_interprocedural_through_helper():
    src = ("import threading, time\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "    def f(self):\n"
           "        with self._lock:\n"
           "            self._slow()\n"
           "    def _slow(self):\n"
           "        time.sleep(0.5)\n")
    assert "C003" in codes(src)


# ---------------- C004 orphan daemon threads ----------------


def test_c004_daemon_without_stop_path():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._t = threading.Thread(target=self._run,\n"
           "                                   daemon=True)\n"
           "        self._t.start()\n"
           "    def _run(self):\n"
           "        pass\n")
    assert "C004" in codes(src)


def test_c004_stop_method_clears():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._stop = threading.Event()\n"
           "        self._t = threading.Thread(target=self._run,\n"
           "                                   daemon=True)\n"
           "        self._t.start()\n"
           "    def _run(self):\n"
           "        pass\n"
           "    def stop(self):\n"
           "        self._stop.set()\n"
           "        self._t.join(timeout=5)\n")
    assert codes(src) == []


def test_c004_fire_and_forget_spawn():
    src = ("import threading\n"
           "def go(fn):\n"
           "    threading.Thread(target=fn, daemon=True).start()\n")
    assert "C004" in codes(src)


# ---------------- C005 module globals ----------------


def test_c005_unlocked_module_container_write():
    assert "C005" in codes("_cache = {}\n"
                           "def put(k, v):\n"
                           "    _cache[k] = v\n")


def test_c005_locked_write_ok():
    src = ("import threading\n"
           "_cache = {}\n"
           "_lock = threading.Lock()\n"
           "def put(k, v):\n"
           "    with _lock:\n"
           "        _cache[k] = v\n")
    assert codes(src) == []


def test_c005_global_singleton_reassign():
    src = ("_inst = None\n"
           "def get():\n"
           "    global _inst\n"
           "    if _inst is None:\n"
           "        _inst = object()\n"
           "    return _inst\n")
    assert "C005" in codes(src)


# ---------------- C006 per-call locks ----------------


def test_c006_lock_per_call():
    src = ("import threading\n"
           "def f():\n"
           "    lock = threading.Lock()\n"
           "    with lock:\n"
           "        return 1\n")
    assert "C006" in codes(src)


def test_c006_factory_returning_lock_ok():
    src = ("import threading\n"
           "def make():\n"
           "    lock = threading.Lock()\n"
           "    return lock\n")
    assert codes(src) == []


def test_c006_lazy_self_lock_outside_init():
    src = ("import threading\n"
           "class C:\n"
           "    def ensure(self):\n"
           "        self._lock = threading.Lock()\n")
    assert "C006" in codes(src)


# ---------------- C007 notify without lock ----------------


def test_c007_notify_outside_with():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "    def kick(self):\n"
           "        self._cv.notify_all()\n")
    assert "C007" in codes(src)


def test_c007_notify_inside_with_ok():
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "    def kick(self):\n"
           "        with self._cv:\n"
           "            self._cv.notify_all()\n")
    assert codes(src) == []


# ---------------- C008 late-binding closures ----------------


def test_c008_lambda_captures_loop_var():
    src = ("def go(pool, items):\n"
           "    for x in items:\n"
           "        pool.submit(lambda: work(x))\n")
    assert "C008" in codes(src)


def test_c008_default_binding_ok():
    src = ("def go(pool, items):\n"
           "    for x in items:\n"
           "        pool.submit(lambda x=x: work(x))\n")
    assert codes(src) == []


def test_c008_bound_method_eager_ok():
    # conveyor.submit('compaction', s.maybe_compact): binds eagerly
    src = ("def go(pool, shards):\n"
           "    for s in shards:\n"
           "        pool.submit('compaction', s.maybe_compact)\n")
    assert codes(src) == []


# ---------------- suppression ----------------


def test_suppression_same_line_and_name_alias():
    src = ("_cache = {}\n"
           "def put(k, v):\n"
           "    _cache[k] = v  # ydb-lint: disable=C005\n")
    assert codes(src) == []
    src = ("_cache = {}\n"
           "def put(k, v):\n"
           "    # ydb-lint: disable=unlocked-module-global\n"
           "    _cache[k] = v\n")
    assert codes(src) == []


def test_suppression_is_per_rule():
    src = ("_cache = {}\n"
           "def put(k, v):\n"
           "    _cache[k] = v  # ydb-lint: disable=C001\n")
    assert "C005" in codes(src)


def test_skip_file():
    src = ("# ydb-lint: skip-file\n"
           "_cache = {}\n"
           "def put(k, v):\n"
           "    _cache[k] = v\n")
    assert codes(src) == []


# ---------------- shared --changed path collection ----------------


def _git(tmp, *args):
    subprocess.run(
        ("git", "-c", "user.email=t@t", "-c", "user.name=t") + args,
        cwd=tmp, check=True, capture_output=True)


def test_changed_scopes_to_touched_files(tmp_path):
    _git(tmp_path, "init", "-q")
    (tmp_path / "a.py").write_text("def f(x=[]):\n    return x\n")
    _git(tmp_path, "add", ".")
    _git(tmp_path, "commit", "-qm", "seed")
    # clean tree: nothing in scope
    assert collect_files([tmp_path], changed=True) == []
    # an untracked file and a modified file both land in scope
    (tmp_path / "b.py").write_text("_c = {}\ndef g(v):\n    _c[1] = v\n")
    files = collect_files([tmp_path], changed=True)
    assert [f.name for f in files] == ["b.py"]
    # both CLIs honor the scope (lint shares the path collection)
    from ydb_tpu.analysis.lint import main as lint_main

    assert main([str(tmp_path), "--changed"]) == 1  # C005 in b.py
    assert lint_main([str(tmp_path), "--changed"]) == 0  # b.py L-clean


def test_changed_degrades_to_full_scan_outside_git(tmp_path):
    sub = tmp_path / "not_a_repo"
    sub.mkdir()
    (sub / "a.py").write_text("x = 1\n")
    files = collect_files([sub], changed=True)
    assert [f.name for f in files] == ["a.py"]


# ---------------- stability ----------------


def test_rule_table_is_stable():
    assert set(RULES) == {"C001", "C002", "C003", "C004", "C005",
                          "C006", "C007", "C008"}
