"""PostgreSQL wire-protocol frontend tests: a from-the-spec minimal
client (independent of the server code) drives the full handshake,
simple-query results, errors, auth, and extended-protocol resync
(reference: ydb/core/local_pgwire)."""

import socket
import struct

import pytest

from ydb_tpu.api.pgwire import PgWireServer
from ydb_tpu.kqp.session import Cluster


class MiniPgClient:
    """Just enough of the frontend side of PostgreSQL protocol 3.0."""

    def __init__(self, port, user="tester", password=None,
                 try_ssl=False, startup=None):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=10)
        if try_ssl:
            self.sock.sendall(struct.pack("!II", 8, 80877103))
            assert self._recv_exact(1) == b"N"
        params = (b"user\x00" + user.encode() + b"\x00"
                  + b"database\x00postgres\x00")
        for k, v in (startup or {}).items():  # e.g. tenant=gold
            params += k.encode() + b"\x00" + v.encode() + b"\x00"
        params += b"\x00"
        self.sock.sendall(
            struct.pack("!II", len(params) + 8, 196608) + params)
        self.params = {}
        self.backend_key = None
        self._password = password
        self.ready = False
        self._pump_until_ready()

    def _recv_exact(self, n):
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            assert c, "server closed"
            buf += c
        return buf

    def read_message(self):
        t = self._recv_exact(1)
        (ln,) = struct.unpack("!I", self._recv_exact(4))
        return t, self._recv_exact(ln - 4)

    def _pump_until_ready(self):
        msgs = []
        while True:
            t, body = self.read_message()
            msgs.append((t, body))
            if t == b"R":
                (code,) = struct.unpack("!I", body[:4])
                if code == 3:  # cleartext password requested
                    assert self._password is not None, "auth required"
                    pw = self._password.encode() + b"\x00"
                    self.sock.sendall(
                        b"p" + struct.pack("!I", len(pw) + 4) + pw)
                else:
                    assert code == 0
            elif t == b"S":
                k, v = body.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            elif t == b"K":
                self.backend_key = struct.unpack("!II", body)
            elif t == b"Z":
                self.ready = True
                return msgs
            elif t == b"E":
                raise RuntimeError(self._error_text(body))

    @staticmethod
    def _error_text(body):
        fields = {}
        for part in body.split(b"\x00"):
            if part:
                fields[chr(part[0])] = part[1:].decode()
        return fields.get("M", "unknown error")

    def query(self, sql):
        """Returns (rows, columns, tags, errors): rows as lists of
        str|None, columns as [(name, oid)]."""
        q = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(q) + 4) + q)
        rows, cols, tags, errors = [], [], [], []
        while True:
            t, body = self.read_message()
            if t == b"T":
                (n,) = struct.unpack("!H", body[:2])
                off = 2
                cols = []
                for _ in range(n):
                    end = body.index(b"\x00", off)
                    name = body[off:end].decode()
                    (oid,) = struct.unpack(
                        "!I", body[end + 7:end + 11])
                    cols.append((name, oid))
                    off = end + 19
            elif t == b"D":
                (n,) = struct.unpack("!H", body[:2])
                off = 2
                row = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", body[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif t == b"C":
                tags.append(body.rstrip(b"\x00").decode())
            elif t == b"E":
                errors.append(self._error_text(body))
            elif t == b"I":
                tags.append("")
            elif t == b"Z":
                return rows, cols, tags, errors

    def send_raw(self, type_byte, payload=b""):
        self.sock.sendall(
            type_byte + struct.pack("!I", len(payload) + 4) + payload)

    def close(self):
        self.send_raw(b"X")
        self.sock.close()


@pytest.fixture
def server():
    cluster = Cluster()
    srv = PgWireServer(cluster).start()
    yield srv
    srv.stop()


def test_handshake_and_query_roundtrip(server):
    c = MiniPgClient(server.port, try_ssl=True)
    assert c.params["server_encoding"] == "UTF8"
    assert c.backend_key is not None

    _, _, tags, errors = c.query(
        "CREATE TABLE t (id int64, name string, amount decimal(10,2), "
        "d date, PRIMARY KEY (id))")
    assert not errors and tags == ["CREATE"]
    _, _, tags, errors = c.query(
        "INSERT INTO t VALUES (1, 'ann', 12.50, date '2026-01-05'), "
        "(2, 'bob', 0.75, date '2026-02-06'), (3, NULL, NULL, NULL)")
    assert not errors and tags == ["INSERT 0 0"]

    rows, cols, tags, errors = c.query(
        "SELECT id, name, amount, d FROM t ORDER BY id")
    assert not errors and tags == ["SELECT 3"]
    assert [(n, o) for n, o in cols] == [
        ("id", 20), ("name", 25), ("amount", 1700), ("d", 1082)]
    assert rows[0] == ["1", "ann", "12.50", "2026-01-05"]
    assert rows[1] == ["2", "bob", "0.75", "2026-02-06"]
    assert rows[2] == ["3", None, None, None]
    c.close()


def test_multi_statement_and_error_recovery(server):
    c = MiniPgClient(server.port)
    _, _, tags, errors = c.query(
        "CREATE TABLE kv (k int64, v int64, PRIMARY KEY (k)); "
        "INSERT INTO kv VALUES (1, 10); INSERT INTO kv VALUES (2, 20)")
    assert not errors and len(tags) == 3

    # error aborts the rest of the string but not the connection
    _, _, tags, errors = c.query("SELECT nope FROM kv; SELECT k FROM kv")
    assert errors and not tags
    rows, _, tags, errors = c.query("SELECT k, v FROM kv ORDER BY k")
    assert not errors and rows == [["1", "10"], ["2", "20"]]
    c.close()


def test_auth_required(server):
    server.auth_tokens = {"sesame"}
    with pytest.raises((RuntimeError, AssertionError)):
        MiniPgClient(server.port, password="wrong")
    c = MiniPgClient(server.port, password="sesame")
    _, _, tags, errors = c.query(
        "CREATE TABLE a (k int64, PRIMARY KEY (k))")
    assert not errors
    c.close()
    server.auth_tokens = None


def test_failed_dml_aborts_rest_of_query_string():
    """A DML that returns TxResult(committed=False) must send an error
    AND abort the remaining statements (pg simple-query semantics)."""
    from ydb_tpu.tx.coordinator import TxResult

    executed = []

    class StubSession:
        def execute(self, sql):
            executed.append(sql)
            if "fail" in sql:
                return TxResult(1, 1, False, "lock conflict")
            return None

    class StubCluster:
        def session(self):
            return StubSession()

    srv = PgWireServer(StubCluster()).start()
    try:
        c = MiniPgClient(srv.port)
        _, _, tags, errors = c.query(
            "UPSERT fail; CREATE TABLE never_runs (k int64)")
        assert errors == ["lock conflict"] and not tags
        assert executed == ["UPSERT fail"]
        c.close()
    finally:
        srv.stop()


def _parse(c, name, query):
    c.send_raw(b"P", name.encode() + b"\x00" + query.encode()
               + b"\x00" + struct.pack("!H", 0))


def _bind(c, portal, stmt, params):
    body = portal.encode() + b"\x00" + stmt.encode() + b"\x00"
    body += struct.pack("!H", 1) + struct.pack("!H", 0)  # all text
    body += struct.pack("!H", len(params))
    for p in params:
        if p is None:
            body += struct.pack("!i", -1)
        else:
            b = str(p).encode()
            body += struct.pack("!i", len(b)) + b
    body += struct.pack("!H", 0)  # result formats: default
    c.send_raw(b"B", body)


def _collect_until_ready(c):
    msgs = []
    while True:
        t, body = c.read_message()
        msgs.append((t, body))
        if t == b"Z":
            return msgs


def test_extended_protocol_parameterized_flow(server):
    c = MiniPgClient(server.port)
    c.query("CREATE TABLE e (k int64, name string, PRIMARY KEY (k))")

    # Parse once, Bind/Execute twice with different parameters
    _parse(c, "ins", "INSERT INTO e VALUES ($1, $2)")
    for k, name in ((1, "ann"), (2, "bob's")):  # quote in the value
        _bind(c, "", "ins", [k, name])
        c.send_raw(b"E", b"\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")
    types = [t for t, _ in _collect_until_ready(c)]
    assert types.count(b"1") == 1 and types.count(b"2") == 2
    assert types.count(b"C") == 2 and b"E" not in types

    # select it back through Describe + Execute
    _parse(c, "", "SELECT k, name FROM e WHERE k >= $1 ORDER BY k")
    _bind(c, "", "", [1])
    c.send_raw(b"D", b"P\x00")  # Describe portal -> RowDescription
    c.send_raw(b"E", b"\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")
    msgs = _collect_until_ready(c)
    types = [t for t, _ in msgs]
    assert types.count(b"T") == 1  # exactly one RowDescription
    rows = [b for t, b in msgs if t == b"D"]
    assert len(rows) == 2
    # second row carries the escaped-quote string intact
    assert b"bob's" in rows[1]
    c.close()


def test_extended_protocol_errors_resync(server):
    c = MiniPgClient(server.port)
    # Execute of an unknown portal -> error, then resync on Sync
    c.send_raw(b"E", b"nope\x00" + struct.pack("!i", 0))
    t, body = c.read_message()
    assert t == b"E" and b"portal" in body
    c.send_raw(b"S")
    t, _ = c.read_message()
    assert t == b"Z"
    # binary parameters are rejected cleanly
    c.query("CREATE TABLE be (k int64, PRIMARY KEY (k))")
    _parse(c, "", "INSERT INTO be VALUES ($1)")
    t, _ = c.read_message()
    assert t == b"1"  # ParseComplete
    body = (b"\x00\x00" + struct.pack("!H", 1)
            + struct.pack("!H", 1)       # format 1 = binary
            + struct.pack("!H", 1)
            + struct.pack("!i", 4) + b"\x00\x00\x00\x07"
            + struct.pack("!H", 0))
    c.send_raw(b"B", body)
    t, body = c.read_message()
    assert t == b"E" and b"binary" in body
    c.send_raw(b"S")
    t, _ = c.read_message()
    assert t == b"Z"
    # simple protocol still healthy afterwards
    _, _, tags, errors = c.query("EXPLAIN SELECT k FROM be")
    assert not errors and tags == ["EXPLAIN"]
    c.close()


def test_param_substitution_is_injection_safe():
    """Placeholder-looking and quote-carrying parameter VALUES are
    inert data (code-review security regression)."""
    from ydb_tpu.api.pgwire import _substitute_params

    sql = _substitute_params("INSERT INTO t VALUES ($1, $2)",
                             [b"x", b"$1"], [])
    assert sql == "INSERT INTO t VALUES ('x', '$1')"
    evil = b"a'; DROP TABLE t; --"
    sql = _substitute_params("INSERT INTO t VALUES ($1, $2)",
                             [evil, b"$1"], [])
    assert sql == ("INSERT INTO t VALUES "
                   "('a''; DROP TABLE t; --', '$1')")
    # $n inside a query string literal is untouched
    sql = _substitute_params("SELECT '$1 off' FROM t WHERE k = $1",
                             [b"7"], [])
    assert sql == "SELECT '$1 off' FROM t WHERE k = 7"
    # explicit text OID forces quoting of numeric-looking strings
    sql = _substitute_params("INSERT INTO t VALUES ($1)",
                             [b"42"], [25])
    assert sql == "INSERT INTO t VALUES ('42')"


def test_execute_row_limit_and_portal_suspension(server):
    c = MiniPgClient(server.port)
    c.query("CREATE TABLE big (k int64, PRIMARY KEY (k))")
    c.query("INSERT INTO big VALUES " + ", ".join(
        f"({i})" for i in range(10)))
    _parse(c, "", "SELECT k FROM big ORDER BY k")
    _bind(c, "p1", "", [])
    # fetch in pages of 4: 4 + 4 + 2
    for expect_suspend in (True, True, False):
        c.send_raw(b"E", b"p1\x00" + struct.pack("!i", 4))
        c.send_raw(b"H")
    c.send_raw(b"S")
    msgs = _collect_until_ready(c)
    types = [t for t, _ in msgs]
    assert types.count(b"s") == 2          # two suspensions
    assert types.count(b"D") == 10         # every row exactly once
    assert any(t == b"C" and b"SELECT 2" in b for t, b in msgs)
    # re-Execute after completion: zero rows, no duplicates
    _bind(c, "p2", "", [])
    c.send_raw(b"E", b"p2\x00" + struct.pack("!i", 0))
    c.send_raw(b"E", b"p2\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")
    msgs = _collect_until_ready(c)
    assert [t for t, _ in msgs].count(b"D") == 10
    assert any(t == b"C" and b"SELECT 0" in b for t, b in msgs)
    c.close()


def test_param_substitution_order_and_null(server):
    c = MiniPgClient(server.port)
    c.query("CREATE TABLE p (k int64, a int64, b string, "
            "PRIMARY KEY (k))")
    # 10+ params: $10 must not be clobbered by $1's value
    cols = ", ".join(f"c{i} int64" for i in range(9))
    c.query(f"CREATE TABLE wide (k int64, {cols}, PRIMARY KEY (k))")
    placeholders = ", ".join(f"${i}" for i in range(1, 11))
    _parse(c, "", f"INSERT INTO wide VALUES ({placeholders})")
    _bind(c, "", "", [1, 10, 20, 30, 40, 50, 60, 70, 80, 90])
    c.send_raw(b"E", b"\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")
    types = [t for t, _ in _collect_until_ready(c)]
    assert b"E" not in types
    rows, _, _, errors = c.query("SELECT c8 FROM wide WHERE k = 1")
    assert not errors and rows[0] == ["90"]  # $10's value, not $1's+0
    # NULL parameter
    _parse(c, "", "INSERT INTO p VALUES ($1, $2, $3)")
    _bind(c, "", "", [5, None, "x"])
    c.send_raw(b"E", b"\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")
    assert b"E" not in [t for t, _ in _collect_until_ready(c)]
    rows, _, _, errors = c.query("SELECT a FROM p WHERE k = 5")
    assert not errors and rows[0] == [None]
    c.close()


def test_jdbc_shaped_describe_and_binary_results(server):
    """The JDBC driver handshake (VERDICT r4 item 10): Parse a named
    statement with a $1 parameter, Describe(statement) BEFORE Bind —
    expecting ParameterDescription with the declared oid AND the
    planned RowDescription — then Bind requesting BINARY results,
    Execute, and decode fixed-width network-order values."""
    c = MiniPgClient(server.port)
    c.query("CREATE TABLE j (id int64, name string, score double, "
            "flag bool, PRIMARY KEY (id))")
    c.query("INSERT INTO j VALUES (1, 'ann', 2.5, true), "
            "(2, 'bob', -0.25, false), (3, NULL, NULL, NULL)")

    # Parse named statement with one declared int8 ($1) parameter
    q = b"SELECT id, name, score, flag FROM j WHERE id >= $1 ORDER BY id"
    c.send_raw(b"P", b"stmt1\x00" + q + b"\x00"
               + struct.pack("!HI", 1, 20))
    # Describe(statement) before any Bind
    c.send_raw(b"D", b"Sstmt1\x00")
    c.send_raw(b"H")  # Flush
    t, body = c.read_message()
    assert t == b"1"  # ParseComplete
    t, body = c.read_message()
    assert t == b"t"  # ParameterDescription: one param, oid 20
    assert struct.unpack("!HI", body) == (1, 20)
    t, body = c.read_message()
    assert t == b"T", t  # RowDescription WITHOUT executing
    (ncols,) = struct.unpack("!H", body[:2])
    assert ncols == 4
    names, oids, off = [], [], 2
    for _ in range(ncols):
        end = body.index(b"\x00", off)
        names.append(body[off:end].decode())
        _tab, _att, oid, _tl, _tm, _fmt = struct.unpack(
            "!IhIhih", body[end + 1:end + 19])
        oids.append(oid)
        off = end + 19
    assert names == ["id", "name", "score", "flag"]
    assert oids == [20, 25, 701, 16]

    # Bind with param $1 = '1' (text) and ALL-BINARY results
    bind = (b"p1\x00stmt1\x00" + struct.pack("!H", 0)
            + struct.pack("!H", 1) + struct.pack("!I", 1) + b"1"
            + struct.pack("!HH", 1, 1))  # one code: binary for all
    c.send_raw(b"B", bind)
    c.send_raw(b"D", b"Pp1\x00")   # Describe(portal)
    c.send_raw(b"E", b"p1\x00" + struct.pack("!i", 0))
    c.send_raw(b"S")               # Sync
    rows = []
    fmts = None
    while True:
        t, body = c.read_message()
        if t == b"T":
            (nc,) = struct.unpack("!H", body[:2])
            fmts, off = [], 2
            for _ in range(nc):
                end = body.index(b"\x00", off)
                fmts.append(struct.unpack(
                    "!IhIhih", body[end + 1:end + 19])[5])
                off = end + 19
        elif t == b"D":
            (n,) = struct.unpack("!H", body[:2])
            off, row = 2, []
            for _ in range(n):
                (ln,) = struct.unpack("!i", body[off:off + 4])
                off += 4
                if ln == -1:
                    row.append(None)
                else:
                    row.append(body[off:off + ln])
                    off += ln
            rows.append(row)
        elif t == b"Z":
            break
    assert fmts == [1, 1, 1, 1]  # rowdesc advertises binary
    assert len(rows) == 3
    # binary decode: int8 BE, text bytes, float8 BE, bool byte
    assert struct.unpack("!q", rows[0][0])[0] == 1
    assert rows[0][1] == b"ann"
    assert struct.unpack("!d", rows[0][2])[0] == 2.5
    assert rows[0][3] == b"\x01"
    assert struct.unpack("!q", rows[1][0])[0] == 2
    assert struct.unpack("!d", rows[1][2])[0] == -0.25
    assert rows[1][3] == b"\x00"
    assert rows[2] == [struct.pack("!q", 3), None, None, None]
    c.close()
