"""KQP session pool (SURVEY §2.8 KQP-proxy row) and the volatile
single-shard commit fast path (VERDICT missing #9 scope)."""

import numpy as np
import pytest

from ydb_tpu.kqp.proxy import ProxyBusyError, SessionPool
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.tx.coordinator import Coordinator


def test_session_pool_reuses_and_caps():
    c = Cluster()
    pool = SessionPool(c, max_sessions=2)
    pool.execute("create table kv (k bigint not null, v bigint, "
                 "primary key (k))")
    pool.execute("insert into kv (k, v) values (1, 10)")
    r = pool.execute("select count(*) as n from kv")
    assert int(r.column("n")[0]) == 1
    assert pool.live == 1 and pool.idle == 1  # reuse, not churn
    assert pool.stats["reused"] >= 2

    # ceiling: two sessions held -> third acquire rejects
    s1, s2 = pool.acquire(), pool.acquire()
    with pytest.raises(ProxyBusyError):
        pool.acquire()
    pool.release(s1)
    pool.release(s2)
    assert pool.execute("select count(*) as n from kv") is not None


class _Shard:
    def __init__(self, fail_prepare=False):
        self.fail_prepare = fail_prepare
        self.committed_at = None
        self.aborted = False

    def prepare(self, args):
        if self.fail_prepare:
            raise RuntimeError("nope")
        return args

    def commit_at(self, token, step):
        self.committed_at = step

    def abort(self, args):
        self.aborted = True


def test_volatile_single_shard_commit():
    coord = Coordinator()
    s = _Shard()
    res = coord.commit([s], [["w1"]])
    assert res.committed and s.committed_at == res.step
    assert coord.read_snapshot() == res.step  # barrier advanced

    bad = _Shard(fail_prepare=True)
    res = coord.commit([bad], [["w2"]])
    assert not res.committed and bad.aborted
    # a failed volatile commit must not advance the read barrier past
    # anything unapplied
    assert coord.read_snapshot() < res.step
