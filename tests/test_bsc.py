"""NodeWarden + BSController automated self-heal (SURVEY §2.3
NodeWarden/BSC row; reference mind/bscontroller/self_heal.cpp)."""

import numpy as np

from ydb_tpu.blobstorage.controller import BSController, NodeWarden
from ydb_tpu.blobstorage.group import DSProxy, GroupInfo, VDisk


def _group(gid):
    group = GroupInfo(gid, "block42")
    proxy = DSProxy(group)
    rng = np.random.default_rng(gid)
    blobs = {f"b{gid}/{i}": rng.bytes(300 + i) for i in range(5)}
    for bid, data in blobs.items():
        proxy.put(bid, data)
    return proxy, blobs


def test_controller_heals_degraded_groups_from_spares():
    ctl = BSController()
    p1, blobs1 = _group(1)
    p2, blobs2 = _group(2)
    ctl.register_group(p1)
    ctl.register_group(p2)
    w = NodeWarden(1)
    for i in range(3):
        w.register_spare(VDisk(f"spare-{i}"))
    ctl.register_warden(w)

    assert ctl.check_and_heal() == []  # healthy: no-op

    p1.group.disks[0].down = True
    p2.group.disks[3].down = True
    p2.group.disks[5].down = True
    # worst-degraded group (2 down) heals first
    healed = ctl.check_and_heal()
    assert [h.group_id for h in healed] == [2, 2, 1]
    assert w.spare_count == 0
    assert ctl.degraded_groups() == []
    for proxy, blobs in ((p1, blobs1), (p2, blobs2)):
        for bid, data in blobs.items():
            assert proxy.get(bid) == data


def test_controller_stops_when_out_of_spares():
    ctl = BSController()
    p1, blobs1 = _group(7)
    ctl.register_group(p1)
    w = NodeWarden(1)
    w.register_spare(VDisk("only-spare"))
    ctl.register_warden(w)

    p1.group.disks[0].down = True
    p1.group.disks[1].down = True
    healed = ctl.check_and_heal()
    assert len(healed) == 1
    assert len(ctl.degraded_groups()) == 1  # one slot still down
    # block-4-2 tolerates the single remaining dead disk
    for bid, data in blobs1.items():
        assert p1.get(bid) == data
