"""Failpoint registry tests: trigger policies, storage fault
injection around live tablets, crash-consistency under injected WAL
faults (reference: datashard_failpoints.h, failure_injection.cpp,
PDiskFIT)."""

import pytest

from ydb_tpu import dtypes
from ydb_tpu.datashard.shard import DataShard, RowOp
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.runtime.failpoints import (
    FailpointBlobStore,
    Failpoints,
    InjectedFault,
)

SCHEMA = dtypes.schema(("id", dtypes.INT64, False),
                       ("v", dtypes.INT64, True))


def test_trigger_policies():
    fp = Failpoints()
    fp.arm("a", "nth", 3)
    fp.hit("a")
    fp.hit("a")
    with pytest.raises(InjectedFault):
        fp.hit("a")
    fp.hit("a")  # only the 3rd fires
    assert fp.stats("a") == {"hits": 4, "fired": 1}

    fp.arm("b", "times", 2)
    for _ in range(2):
        with pytest.raises(InjectedFault):
            fp.hit("b")
    fp.hit("b")  # recovered

    fp.arm("c", "prob", 0.5, seed=7)
    fired = sum(1 for _ in range(100)
                if _raises(lambda: fp.hit("c")))
    assert 20 < fired < 80  # seeded, deterministic per seed
    fp2 = Failpoints()
    fp2.arm("c", "prob", 0.5, seed=7)
    fired2 = sum(1 for _ in range(100)
                 if _raises(lambda: fp2.hit("c")))
    assert fired == fired2  # deterministic replay

    hits = []
    fp.arm("d", "always", action=lambda **ctx: hits.append(ctx))
    fp.hit("d", blob_id="x")
    assert hits == [{"blob_id": "x"}]


def _raises(fn) -> bool:
    try:
        fn()
        return False
    except InjectedFault:
        return True


def test_wal_write_fault_keeps_tablet_consistent():
    """A WAL put failing mid-commit must leave the tablet recoverable
    with only fully-committed state (the PDiskFIT property)."""
    fp = Failpoints()
    backend = MemBlobStore()
    store = FailpointBlobStore(backend, fp)
    shard = DataShard("f0", SCHEMA, store, ("id",))

    wid = shard.propose([RowOp((1,), {"id": 1, "v": 10})])
    shard.prepare([wid])
    shard.commit_at([wid], 5)

    # every further WAL write fails: even the durable staging of a
    # propose must surface the fault, committing nothing
    fp.arm("blob.put", "always")
    with pytest.raises(InjectedFault):
        shard.propose([RowOp((2,), {"id": 2, "v": 20})])
    fp.disarm("blob.put")

    # reboot from storage: committed row present, torn write absent
    shard2 = DataShard("f0", SCHEMA, backend, ("id",))
    rows = {k[0]: r["v"] for page in shard2.read(10)
            for k, r in page}
    assert rows == {1: 10}


def test_read_faults_fail_soft_then_recover():
    fp = Failpoints()
    backend = MemBlobStore()
    store = FailpointBlobStore(backend, fp)
    store.put("k", b"v")
    fp.arm("blob.get", "times", 2)
    for _ in range(2):
        with pytest.raises(InjectedFault):
            store.get("k")
    assert store.get("k") == b"v"  # transient fault passed
    assert fp.stats("blob.get")["fired"] == 2
