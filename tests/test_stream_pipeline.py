"""Morsel-driven streaming pipeline (engine.stream_sched): bit-identity
against the serialized path for plain and upsert-merge scans, chaos
blob faults healing without a consumer stall, mid-scan deadline and
abandoned-stream drain to zero under leaksan, and consumer work
stealing when the dedicated stream pool is saturated."""

import threading
import time

import numpy as np
import pytest

from ydb_tpu import chaos, dtypes
from ydb_tpu.analysis import leaksan
from ydb_tpu.chaos.deadline import Deadline, StatementCancelled, activate
from ydb_tpu.engine import stream_sched
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.reader import PortionStreamSource
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.runtime.conveyor import shared_conveyor, stream_conveyor

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("v", dtypes.INT64),
)

AGG_SQL = ("SELECT k % 5 AS g, SUM(v) AS sv, COUNT(*) AS n "
           "FROM kv GROUP BY k % 5 ORDER BY g")


@pytest.fixture(autouse=True)
def _clean():
    """Every test leaves the pipeline gate on the environment and the
    chaos subsystem disarmed."""
    yield
    stream_sched.PIPELINE_FORCE = None
    chaos.clear()
    chaos.CHAOS_FORCE = None


def _shard(upsert=True):
    return ColumnShard(
        "s1", SCHEMA, MemBlobStore(), pk_column="id", upsert=upsert,
        config=ShardConfig(compact_portion_threshold=1_000_000),
    )


def _put(shard, ids, vals):
    wid = shard.write({"id": np.asarray(list(ids), dtype=np.int64),
                       "v": np.asarray(list(vals), dtype=np.int64)})
    return shard.commit([wid])


def _scan(shard, cap=64):
    """Full scan; returns (source, per-block (ids, vals) lists) so
    identity checks cover block boundaries, not just totals."""
    src = PortionStreamSource(shard, shard.visible_portions(None))
    blocks = []
    for blk in src.blocks(cap):
        data = blk.to_numpy()
        n = int(blk.length)
        blocks.append((data["id"][:n].tolist(), data["v"][:n].tolist()))
    return src, blocks


def _kv_cluster(n=300):
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE kv (k Int64 NOT NULL, v Int64, "
              "PRIMARY KEY (k)) WITH (shards = 2)")
    t = c.tables["kv"]
    for off in range(0, n, n // 3):  # several portions per shard
        ks = list(range(off, min(n, off + n // 3)))
        t.insert({"k": ks, "v": [k * 7 for k in ks]})
    c._invalidate_plans()
    return c, s


def _same_result(a, b):
    assert a.schema.names == b.schema.names
    assert a.num_rows == b.num_rows
    for name in a.schema.names:
        av, aok = a.cols[name]
        bv, bok = b.cols[name]
        np.testing.assert_array_equal(np.asarray(av), np.asarray(bv),
                                      err_msg=name)
        np.testing.assert_array_equal(np.asarray(aok), np.asarray(bok),
                                      err_msg=f"{name} validity")


# ---------------- bit-identity: pipeline on == pipeline off ----------


def test_bit_identity_plain_scan():
    shard = _shard(upsert=False)
    for off in range(6):
        base = off * 100
        _put(shard, range(base, base + 100),
             (i * 3 for i in range(base, base + 100)))

    stream_sched.PIPELINE_FORCE = False
    _, serialized = _scan(shard)
    stream_sched.PIPELINE_FORCE = True
    src, pipelined = _scan(shard)

    assert pipelined == serialized  # same blocks, same order, same rows
    stats = src.last_pipeline
    assert stats is not None and stats["morsels_io"] > 0  # it DID fly


def test_bit_identity_upsert_merge():
    # overlapping PK ranges force merge clusters (inline K-way merge
    # morsels) interleaved with cold single-portion IO morsels
    shard = _shard(upsert=True)
    _put(shard, range(0, 200), (i * 2 for i in range(0, 200)))
    _put(shard, range(100, 300), (i * 5 for i in range(100, 300)))
    _put(shard, range(50, 150), (i * 9 for i in range(50, 150)))
    _put(shard, range(1000, 1200), (i for i in range(1000, 1200)))

    stream_sched.PIPELINE_FORCE = False
    _, serialized = _scan(shard)
    stream_sched.PIPELINE_FORCE = True
    src, pipelined = _scan(shard)

    assert pipelined == serialized
    stats = src.last_pipeline
    assert stats is not None
    assert stats["morsels_merge"] > 0 and stats["morsels_io"] > 0


# ---------------- chaos: blob faults heal, consumer never stalls -----


def test_chaos_blob_io_error_heals_under_pipeline():
    stream_sched.PIPELINE_FORCE = True
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    chaos.CHAOS_FORCE = True
    chaos.install(chaos.Scenario(seed=33, sites={
        "blob.get_range": {"kind": "io_error", "p": 0.6, "budget": 6},
    }))
    t0 = time.monotonic()
    got = s.execute(AGG_SQL)
    assert time.monotonic() - t0 < 30.0  # healed, not stalled
    snap = chaos.counters_snapshot()
    assert snap["sites"]["blob.get_range"]["fired"] > 0
    _same_result(got, want)


def test_chaos_blob_latency_does_not_stall_consumer():
    # pure-delay faults on every blob read: flights just take longer,
    # the consumer keeps draining in order and the result is identical
    stream_sched.PIPELINE_FORCE = True
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    chaos.CHAOS_FORCE = True
    chaos.install(chaos.Scenario(seed=7, sites={
        "blob.get_range": {"kind": "delay", "p": 1.0,
                           "latency": 0.005},
    }))
    t0 = time.monotonic()
    got = s.execute(AGG_SQL)
    assert time.monotonic() - t0 < 30.0
    assert chaos.counters_snapshot()["sites"]["blob.get_range"][
        "fired"] > 0
    _same_result(got, want)


def test_chaos_torn_read_heals_under_pipeline():
    # a torn read truncates the payload mid-chunk: the zero-copy
    # decode raises a transient kind and the flight re-fetches
    stream_sched.PIPELINE_FORCE = True
    c, s = _kv_cluster()
    want = s.execute(AGG_SQL)
    chaos.CHAOS_FORCE = True
    chaos.install(chaos.Scenario(seed=5, sites={
        "blob.get_range": {"kind": "torn", "p": 1.0, "budget": 2},
    }))
    got = s.execute(AGG_SQL)
    assert chaos.counters_snapshot()["sites"]["blob.get_range"][
        "fired"] == 2
    _same_result(got, want)


# ---------------- cancellation / abandonment: drain to zero ----------


def test_mid_scan_deadline_drains_morsel_flights():
    stream_sched.PIPELINE_FORCE = True
    shard = _shard(upsert=False)
    for off in range(8):
        base = off * 200
        _put(shard, range(base, base + 200),
             (i * 3 for i in range(base, base + 200)))

    with leaksan.activate():
        src = PortionStreamSource(shard, shard.visible_portions(None))
        with activate(Deadline(seconds=0.0)):
            with pytest.raises(StatementCancelled):
                for _ in src.blocks(64):
                    pass
        deadline = time.monotonic() + 5.0
        while leaksan.live("stream.morsel") and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        assert leaksan.live("stream.morsel") == []
        stream_conveyor().wait_idle(timeout=10.0)
        shared_conveyor().wait_idle(timeout=10.0)
        while leaksan.counts() and time.monotonic() < deadline:
            time.sleep(0.005)  # a worker may close its handle post-idle
        assert leaksan.counts() == {}
    # flights WERE admitted before the cancellation landed
    stats = src.last_pipeline
    assert stats is not None and stats["morsels_io"] > 0


def test_abandoned_stream_drains_morsel_flights():
    stream_sched.PIPELINE_FORCE = True
    shard = _shard(upsert=False)
    for off in range(8):
        base = off * 200
        _put(shard, range(base, base + 200),
             (i * 3 for i in range(base, base + 200)))

    with leaksan.activate():
        src = PortionStreamSource(shard, shard.visible_portions(None))
        it = src.blocks(64)
        next(it)
        it.close()  # consumer walks away mid-stream
        deadline = time.monotonic() + 5.0
        while leaksan.live("stream.morsel") and \
                time.monotonic() < deadline:
            time.sleep(0.005)
        assert leaksan.live("stream.morsel") == []
        stream_conveyor().wait_idle(timeout=10.0)
        shared_conveyor().wait_idle(timeout=10.0)
        while leaksan.counts() and time.monotonic() < deadline:
            time.sleep(0.005)  # a worker may close its handle post-idle
        assert leaksan.counts() == {}
    assert src.last_pipeline is not None


# ---------------- work stealing: saturated pool never blocks ---------


def test_consumer_steals_when_stream_pool_saturated():
    stream_sched.PIPELINE_FORCE = True
    shard = _shard(upsert=False)
    for off in range(6):
        base = off * 100
        _put(shard, range(base, base + 100),
             (i * 3 for i in range(base, base + 100)))
    stream_sched.PIPELINE_FORCE = False
    _, serialized = _scan(shard)
    stream_sched.PIPELINE_FORCE = True

    gate = threading.Event()
    cv = stream_conveyor()
    try:
        for _ in range(16):  # park every stream worker behind the gate
            cv.submit("test_gate", gate.wait)
        src, pipelined = _scan(shard)
    finally:
        gate.set()
    cv.wait_idle(timeout=10.0)

    assert pipelined == serialized  # stolen flights, identical stream
    stats = src.last_pipeline
    assert stats is not None and stats["stolen"] > 0
