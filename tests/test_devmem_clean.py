"""Tier-1 enforcement: the device-memory analyzer runs clean over the
runtime packages (the M-rule analog of test_hotpath_clean). A finding
here means a code change created a device array outside a
budget-charging seam, re-jitted on a grow path, or grew a device
container without a valve — fix the code, charge the bytes via
``memsan.seam()/charge()``, mark a bounded site
``@analysis.budget_ok("reason")``, or justify a reviewed site with a
``# ydb-lint: disable=M00x`` pragma."""

from pathlib import Path

from ydb_tpu.analysis import devmem
from ydb_tpu.analysis.paths import collect_files

PKG = Path(devmem.__file__).resolve().parents[1]


def test_devmem_clean_tree_wide():
    findings = devmem.check_paths(collect_files([PKG]))
    msg = "\n".join(f.render() for f in findings)
    assert findings == [], \
        f"{len(findings)} device-memory finding(s):\n{msg}"


def test_runtime_scope_covers_every_declared_package():
    """Each RUNTIME_PACKAGES entry must exist on disk — a package
    rename would otherwise silently shrink the scanned set and the
    clean test above would pass vacuously."""
    for pkg in devmem.RUNTIME_PACKAGES:
        assert (PKG / pkg).is_dir(), \
            f"runtime package {pkg!r} missing from {PKG} — renamed?"


def test_scope_actually_collects_runtime_files():
    files = devmem.runtime_scope(collect_files([PKG]))
    # every runtime package contributes at least one scanned module
    for pkg in devmem.RUNTIME_PACKAGES:
        assert any(pkg in Path(f).parts for f in files), \
            f"no files collected from runtime package {pkg!r}"
