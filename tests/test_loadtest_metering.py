"""Load-test service + metering tests (reference: ydb/core/load_test,
ydb/core/metering)."""

import io
import json

import pytest

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.obs.loadtest import LoadService
from ydb_tpu.obs.metering import Metering, request_units


def test_kv_upsert_and_select_load():
    cluster = Cluster()
    svc = LoadService(cluster)
    r = svc.run("kv_upsert", requests=20, key_space=10)
    assert r["kind"] == "kv_upsert" and r["requests"] == 20 and r["errors"] == 0
    assert r["rps"] > 0 and r["p99_ms"] >= r["p50_ms"] > 0

    r2 = svc.run("select", requests=10, key_space=10)
    assert r2["errors"] == 0 and r2["requests"] == 10
    assert len(svc.history) == 2

    # the load actually landed: table has some of the 10 keys
    out = cluster.session().execute("SELECT count(*) AS c FROM load_kv")
    n = int(out.column("c")[0])
    assert 1 <= n <= 10


def test_storage_put_load_and_unknown_kind():
    cluster = Cluster()
    svc = LoadService(cluster)
    r = svc.run("storage_put", requests=5, blob_bytes=128)
    assert r["errors"] == 0 and r["requests"] == 5
    with pytest.raises(KeyError):
        svc.run("nope")


def test_request_units_schedule():
    assert request_units("select", 0) == 1
    assert request_units("select", 128) == 1
    assert request_units("select", 129) == 2
    assert request_units("upsert", 10_000) == 1


def test_metering_records_and_aggregates():
    sink = io.StringIO()
    clock = [1000.0]
    m = Metering(tenant="/Root/a", sink=sink, now=lambda: clock[0])
    m.record("kqp.select", 2)
    clock[0] += 10
    m.record("kqp.upsert", 1)
    clock[0] += 3600
    m.record("kqp.select", 3)
    agg = m.aggregate(interval_s=3600)
    assert agg == [
        {"tenant": "/Root/a", "resource": "kqp.select",
         "interval_start": 0.0, "units": 2},
        {"tenant": "/Root/a", "resource": "kqp.upsert",
         "interval_start": 0.0, "units": 1},
        {"tenant": "/Root/a", "resource": "kqp.select",
         "interval_start": 3600.0, "units": 3},
    ]
    lines = [json.loads(x) for x in sink.getvalue().splitlines()]
    assert len(lines) == 3 and lines[0]["units"] == 2
    assert m.total_units() == 6 and m.total_units("kqp.select") == 5


def test_session_books_request_units():
    cluster = Cluster()
    s = cluster.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1), (2)")
    s.execute("SELECT id FROM t")
    res = {r["resource"] for r in cluster.metering.records}
    assert {"kqp.createtable", "kqp.insert", "kqp.select"} <= res
    assert cluster.metering.total_units() >= 3
