"""Global secondary indexes on the row store: online backfill + atomic
maintenance in the same 2PC as data writes (SURVEY §2.6 index-build
row; reference datashard build_index.cpp + indeximpl tables)."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.datashard.table import RowTable
from ydb_tpu.tx.coordinator import Coordinator

SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("city", dtypes.STRING),
    ("v", dtypes.INT64),
)


def _table():
    t = RowTable("users", SCHEMA, MemBlobStore(),
                 Coordinator(MemBlobStore()), n_shards=3,
                 pk_columns=("id",))
    t.insert({"id": np.arange(6, dtype=np.int64),
              "city": [b"ams", b"ber", b"ams", b"cdg", b"ber", b"ams"],
              "v": np.arange(6, dtype=np.int64) * 10})
    return t


def test_backfill_and_lookup():
    t = _table()
    t.add_index("by_city", "city")
    assert sorted(t.lookup_index("by_city", b"ams")) == [(0,), (2,), (5,)]
    assert sorted(t.lookup_index("by_city", b"ber")) == [(1,), (4,)]
    assert t.lookup_index("by_city", b"nope") == []


def test_index_maintained_by_writes():
    t = _table()
    t.add_index("by_city", "city")
    # new row
    t.insert({"id": np.array([9], dtype=np.int64), "city": [b"cdg"],
              "v": np.array([90], dtype=np.int64)})
    assert sorted(t.lookup_index("by_city", b"cdg")) == [(3,), (9,)]
    # value change moves the entry
    t.insert({"id": np.array([0], dtype=np.int64), "city": [b"cdg"],
              "v": np.array([0], dtype=np.int64)})
    assert sorted(t.lookup_index("by_city", b"ams")) == [(2,), (5,)]
    assert sorted(t.lookup_index("by_city", b"cdg")) == [(0,), (3,), (9,)]
    # delete removes the entry
    t.delete_keys([(9,)])
    assert sorted(t.lookup_index("by_city", b"cdg")) == [(0,), (3,)]


def test_same_key_twice_in_one_batch_keeps_index_consistent():
    t = _table()
    t.add_index("by_city", "city")
    # one batch writes id=0 twice: last value wins, no stale entry
    t.upsert_rows([
        {"id": 0, "city": t.dicts.for_column("city").add(b"ber"),
         "v": 1},
        {"id": 0, "city": t.dicts.for_column("city").add(b"cdg"),
         "v": 2},
    ])
    assert (0,) not in t.lookup_index("by_city", b"ams")
    assert (0,) not in t.lookup_index("by_city", b"ber")
    assert (0,) in t.lookup_index("by_city", b"cdg")


def test_index_guards():
    t = _table()
    with pytest.raises(ValueError):
        t.add_index("bad", "id")  # already the PK
    t.add_index("by_city", "city")
    with pytest.raises(ValueError):
        t.add_index("by_city", "v")  # duplicate name
