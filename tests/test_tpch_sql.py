"""TPC-H q1-q22 through SQL parse -> plan -> device execution,
verified against independent numpy reference implementations computed
straight off the generated tables (the canondata pattern,
ydb/tests/functional/tpc + SURVEY.md §7.1.4 oracle strategy)."""

import collections

import numpy as np
import pytest

from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpch
from ydb_tpu.workload.queries import TPCH

SF = 0.01


@pytest.fixture(scope="module")
def data():
    return tpch.TpchData(sf=SF, seed=11)


@pytest.fixture(scope="module")
def db(data):
    return Database(
        sources={
            t: ColumnSource(cols, data.schema(t), data.dicts)
            for t, cols in data.tables.items()
        },
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(
        schemas={t: data.schema(t) for t in data.tables},
        primary_keys=dict(tpch.PRIMARY_KEYS),
        dicts=data.dicts,
    )


_RESULTS: dict = {}  # memo shared with the golden-pinning test


def run_q(name, catalog, db):
    hit = _RESULTS.get(name)
    if hit is not None:
        return hit

    def scalar_exec(plan, t):
        out = to_host(execute_plan(plan, db))
        col = out.schema.names[0]
        v, ok = out.cols[col]
        assert len(v) == 1, f"scalar subquery returned {len(v)} rows"
        return v[0].item(), bool(ok[0])

    pq = plan_select_full(parse(TPCH[name]), catalog, scalar_exec)
    res = to_host(execute_plan(pq.plan, db))
    res.dicts = db.dicts
    res.dict_aliases = pq.dict_aliases
    _RESULTS[name] = res
    return res


def dec(data, table, col):
    """Decode a dictionary-encoded string column to a bytes object array."""
    d = data.dicts[col]
    vals = np.array(d.values + [b""], dtype=object)
    return vals[data.tables[table][col]]


def col_out(res, name):
    """Engine output column as float (decimals descaled) or raw array."""
    v, ok = res.cols[name]
    t = res.schema.field(name).type
    if t.is_decimal:
        return np.asarray(v, dtype=np.float64) / 10.0 ** t.scale
    return np.asarray(v)


def strings_out(res, name):
    src = getattr(res, "dict_aliases", {}).get(name, name)
    d = res.dicts[src]
    return np.array(d.decode(np.asarray(res.cols[name][0])), dtype=object)


def _days(s):
    return tpch._days(s)


def pk_map(keys, values):
    return dict(zip(keys.tolist(), values.tolist()))


def gather(mapping, keys, default=None):
    return np.array([mapping.get(k, default) for k in keys.tolist()])


# ---------------- the tests ----------------


def test_q1(data, catalog, db):
    res = run_q("q1", catalog, db)
    li = data.tables["lineitem"]
    m = li["l_shipdate"] <= _days("1998-12-01") - 90
    rf = dec(data, "lineitem", "l_returnflag")[m]
    ls = dec(data, "lineitem", "l_linestatus")[m]
    groups = sorted(set(zip(rf.tolist(), ls.tolist())))
    assert res.num_rows == len(groups)
    got_rf = strings_out(res, "l_returnflag")
    got_ls = strings_out(res, "l_linestatus")
    assert list(zip(got_rf, got_ls)) == groups
    qty = li["l_quantity"][m]
    for i, (a, b) in enumerate(groups):
        g = (rf == a) & (ls == b)
        np.testing.assert_allclose(
            col_out(res, "sum_qty")[i], qty[g].sum() / 100, rtol=1e-12)
        np.testing.assert_allclose(
            col_out(res, "avg_disc")[i],
            (li["l_discount"][m][g] / 100).mean(), rtol=1e-12)
        assert col_out(res, "count_order")[i] == int(g.sum())


def test_q2(data, catalog, db):
    res = run_q("q2", catalog, db)
    p, s, ps, n, r = (data.tables[t] for t in
                      ("part", "supplier", "partsupp", "nation", "region"))
    ptype = dec(data, "part", "p_type")
    pm = (p["p_size"] == 15) & np.array(
        [t.endswith(b"BRASS") for t in ptype])
    eur_regions = {r["r_regionkey"][i] for i in range(len(r["r_regionkey"]))
                   if dec(data, "region", "r_name")[i] == b"EUROPE"}
    nat_eur = {n["n_nationkey"][i] for i in range(25)
               if n["n_regionkey"][i] in eur_regions}
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    # min supplycost per part over european suppliers
    best: dict = {}
    for pk, sk, cost in zip(ps["ps_partkey"].tolist(),
                            ps["ps_suppkey"].tolist(),
                            ps["ps_supplycost"].tolist()):
        if supp_nat[sk] in nat_eur:
            best[pk] = min(best.get(pk, 1 << 60), cost)
    want = []
    sname = dec(data, "supplier", "s_name")
    nname = dec(data, "nation", "n_name")
    for pk, sk, cost in zip(ps["ps_partkey"].tolist(),
                            ps["ps_suppkey"].tolist(),
                            ps["ps_supplycost"].tolist()):
        i = pk - 1
        if not pm[i] or supp_nat[sk] not in nat_eur:
            continue
        if cost != best.get(pk):
            continue
        si = sk - 1
        want.append((-s["s_acctbal"][si], nname[supp_nat[sk]],
                     sname[si], pk))
    want.sort()
    want = want[:100]
    assert res.num_rows == len(want)
    got = list(zip(-col_out(res, "s_acctbal") * 100,
                   strings_out(res, "n_name"),
                   strings_out(res, "s_name"),
                   col_out(res, "p_partkey")))
    for g, w in zip(got, want):
        assert (int(g[0]), g[1], g[2], int(g[3])) == (
            int(w[0]), w[1], w[2], int(w[3]))


def test_q4(data, catalog, db):
    res = run_q("q4", catalog, db)
    o = data.tables["orders"]
    li = data.tables["lineitem"]
    late = set(li["l_orderkey"][
        li["l_commitdate"] < li["l_receiptdate"]].tolist())
    d0 = _days("1993-07-01")
    d1 = _days("1993-10-01")
    m = (o["o_orderdate"] >= d0) & (o["o_orderdate"] < d1) & np.isin(
        o["o_orderkey"], list(late))
    pri = dec(data, "orders", "o_orderpriority")[m]
    cnt = collections.Counter(pri.tolist())
    got = dict(zip(strings_out(res, "o_orderpriority"),
                   col_out(res, "order_count")))
    assert {k: int(v) for k, v in got.items()} == dict(cnt)
    assert list(strings_out(res, "o_orderpriority")) == sorted(cnt)


def test_q5(data, catalog, db):
    res = run_q("q5", catalog, db)
    c, o, li, s, n, r = (data.tables[t] for t in (
        "customer", "orders", "lineitem", "supplier", "nation", "region"))
    asia = {r["r_regionkey"][i] for i in range(5)
            if dec(data, "region", "r_name")[i] == b"ASIA"}
    nat_asia = {n["n_nationkey"][i] for i in range(25)
                if n["n_regionkey"][i] in asia}
    cust_nat = pk_map(c["c_custkey"], c["c_nationkey"])
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    d0, d1 = _days("1994-01-01"), _days("1995-01-01")
    omask = (o["o_orderdate"] >= d0) & (o["o_orderdate"] < d1)
    order_cust = pk_map(o["o_orderkey"][omask], o["o_custkey"][omask])
    nname = dec(data, "nation", "n_name")
    rev = collections.defaultdict(int)
    for ok_, sk, price, disc in zip(li["l_orderkey"].tolist(),
                                    li["l_suppkey"].tolist(),
                                    li["l_extendedprice"].tolist(),
                                    li["l_discount"].tolist()):
        ck = order_cust.get(ok_)
        if ck is None:
            continue
        nat = supp_nat[sk]
        if nat not in nat_asia or cust_nat[ck] != nat:
            continue
        rev[nname[nat]] += price * (100 - disc)
    want = sorted(rev.items(), key=lambda kv: -kv[1])
    got = list(zip(strings_out(res, "n_name"),
                   (col_out(res, "revenue") * 1e4).round().astype(np.int64)))
    assert [w[0] for w in want] == [g[0] for g in got]
    for (wn, wv), (gn, gv) in zip(want, got):
        assert wv == int(gv), (wn, wv, int(gv))


def test_q7(data, catalog, db):
    res = run_q("q7", catalog, db)
    c, o, li, s, n = (data.tables[t] for t in (
        "customer", "orders", "lineitem", "supplier", "nation"))
    nname = dec(data, "nation", "n_name")
    cust_nat = pk_map(c["c_custkey"], c["c_nationkey"])
    order_cust = pk_map(o["o_orderkey"], o["o_custkey"])
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    d0, d1 = _days("1995-01-01"), _days("1996-12-31")
    rev = collections.defaultdict(int)
    for ok_, sk, sd, price, disc in zip(
            li["l_orderkey"].tolist(), li["l_suppkey"].tolist(),
            li["l_shipdate"].tolist(), li["l_extendedprice"].tolist(),
            li["l_discount"].tolist()):
        if not (d0 <= sd <= d1):
            continue
        sn = nname[supp_nat[sk]]
        cn = nname[cust_nat[order_cust[ok_]]]
        if (sn, cn) not in ((b"FRANCE", b"GERMANY"),
                            (b"GERMANY", b"FRANCE")):
            continue
        year = (np.datetime64("1970-01-01") + sd).astype(
            "datetime64[Y]").astype(int) + 1970
        rev[(sn, cn, int(year))] += price * (100 - disc)
    want = sorted(rev.items())
    got = list(zip(strings_out(res, "supp_nation"),
                   strings_out(res, "cust_nation"),
                   col_out(res, "l_year"),
                   (col_out(res, "revenue") * 1e4).round().astype(np.int64)))
    assert len(got) == len(want)
    for (wk, wv), g in zip(want, got):
        assert wk == (g[0], g[1], int(g[2]))
        assert wv == int(g[3])


def test_q8(data, catalog, db):
    res = run_q("q8", catalog, db)
    p, c, o, li, s, n, r = (data.tables[t] for t in (
        "part", "customer", "orders", "lineitem", "supplier", "nation",
        "region"))
    nname = dec(data, "nation", "n_name")
    america = {r["r_regionkey"][i] for i in range(5)
               if dec(data, "region", "r_name")[i] == b"AMERICA"}
    nat_am = {n["n_nationkey"][i] for i in range(25)
              if n["n_regionkey"][i] in america}
    steel = {p["p_partkey"][i] for i in range(len(p["p_partkey"]))
             if dec(data, "part", "p_type")[i] == b"ECONOMY ANODIZED STEEL"}
    cust_nat = pk_map(c["c_custkey"], c["c_nationkey"])
    d0, d1 = _days("1995-01-01"), _days("1996-12-31")
    om = (o["o_orderdate"] >= d0) & (o["o_orderdate"] <= d1)
    order_cust = pk_map(o["o_orderkey"][om], o["o_custkey"][om])
    order_date = pk_map(o["o_orderkey"][om], o["o_orderdate"][om])
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    tot = collections.defaultdict(int)
    bra = collections.defaultdict(int)
    for ok_, pk, sk, price, disc in zip(
            li["l_orderkey"].tolist(), li["l_partkey"].tolist(),
            li["l_suppkey"].tolist(), li["l_extendedprice"].tolist(),
            li["l_discount"].tolist()):
        if pk not in steel or ok_ not in order_cust:
            continue
        if cust_nat[order_cust[ok_]] not in nat_am:
            continue
        year = (np.datetime64("1970-01-01") + order_date[ok_]).astype(
            "datetime64[Y]").astype(int) + 1970
        v = price * (100 - disc)
        tot[int(year)] += v
        if nname[supp_nat[sk]] == b"BRAZIL":
            bra[int(year)] += v
    want = {y: bra[y] / t for y, t in tot.items() if t}
    got = dict(zip(col_out(res, "o_year").tolist(),
                   col_out(res, "mkt_share").tolist()))
    assert set(got) == set(want)
    for y in want:
        np.testing.assert_allclose(got[y], want[y], rtol=1e-9)


def test_q9(data, catalog, db):
    res = run_q("q9", catalog, db)
    p, li, s, ps, o, n = (data.tables[t] for t in (
        "part", "lineitem", "supplier", "partsupp", "orders", "nation"))
    nname = dec(data, "nation", "n_name")
    green = {p["p_partkey"][i] for i in range(len(p["p_partkey"]))
             if b"green" in dec(data, "part", "p_name")[i]}
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    order_date = pk_map(o["o_orderkey"], o["o_orderdate"])
    ps_cost = {
        (a, b): c for a, b, c in zip(ps["ps_partkey"].tolist(),
                                     ps["ps_suppkey"].tolist(),
                                     ps["ps_supplycost"].tolist())
    }
    profit = collections.defaultdict(int)
    for ok_, pk, sk, qty, price, disc in zip(
            li["l_orderkey"].tolist(), li["l_partkey"].tolist(),
            li["l_suppkey"].tolist(), li["l_quantity"].tolist(),
            li["l_extendedprice"].tolist(), li["l_discount"].tolist()):
        if pk not in green or (pk, sk) not in ps_cost:
            continue
        year = (np.datetime64("1970-01-01") + order_date[ok_]).astype(
            "datetime64[Y]").astype(int) + 1970
        amount = price * (100 - disc) - ps_cost[(pk, sk)] * qty
        profit[(nname[supp_nat[sk]], int(year))] += amount
    want = sorted(profit.items(), key=lambda kv: (kv[0][0], -kv[0][1]))
    got = list(zip(strings_out(res, "nation"),
                   col_out(res, "o_year"),
                   (col_out(res, "sum_profit") * 1e4).round().astype(
                       np.int64)))
    assert len(got) == len(want)
    for (wk, wv), g in zip(want, got):
        assert wk == (g[0], int(g[1]))
        assert wv == int(g[2])


def test_q10(data, catalog, db):
    res = run_q("q10", catalog, db)
    c, o, li, n = (data.tables[t] for t in (
        "customer", "orders", "lineitem", "nation"))
    d0, d1 = _days("1993-10-01"), _days("1994-01-01")
    om = (o["o_orderdate"] >= d0) & (o["o_orderdate"] < d1)
    order_cust = pk_map(o["o_orderkey"][om], o["o_custkey"][om])
    rflag = dec(data, "lineitem", "l_returnflag")
    rev = collections.defaultdict(int)
    for i, ok_ in enumerate(li["l_orderkey"].tolist()):
        if rflag[i] != b"R" or ok_ not in order_cust:
            continue
        rev[order_cust[ok_]] += (
            li["l_extendedprice"][i] * (100 - li["l_discount"][i]))
    want = sorted(rev.items(), key=lambda kv: (-kv[1], kv[0]))[:20]
    got = list(zip(col_out(res, "c_custkey").astype(np.int64),
                   (col_out(res, "revenue") * 1e4).round().astype(np.int64)))
    assert [(int(a), int(b)) for a, b in got] == want
    # spot-check the carried customer attributes on the top row
    if want:
        ck = want[0][0]
        i = ck - 1
        assert strings_out(res, "c_name")[0] == dec(
            data, "customer", "c_name")[i]
        assert strings_out(res, "c_phone")[0] == dec(
            data, "customer", "c_phone")[i]


def test_q11(data, catalog, db):
    res = run_q("q11", catalog, db)
    ps, s, n = (data.tables[t] for t in ("partsupp", "supplier", "nation"))
    nname = dec(data, "nation", "n_name")
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    val = collections.defaultdict(int)
    total = 0
    for pk, sk, cost, qty in zip(ps["ps_partkey"].tolist(),
                                 ps["ps_suppkey"].tolist(),
                                 ps["ps_supplycost"].tolist(),
                                 ps["ps_availqty"].tolist()):
        if nname[supp_nat[sk]] != b"GERMANY":
            continue
        val[pk] += cost * qty
        total += cost * qty
    cut = total * 0.0001  # exact in integers: v > total/10000
    want = sorted(((k, v) for k, v in val.items() if v * 10000 > total),
                  key=lambda kv: -kv[1])
    got = list(zip(col_out(res, "ps_partkey").astype(np.int64),
                   (col_out(res, "value") * 100).round().astype(np.int64)))
    assert len(got) == len(want), (len(got), len(want), cut)
    assert sorted((int(a), int(b)) for a, b in got) == sorted(want)
    vv = [b for _, b in got]
    assert all(vv[i] >= vv[i + 1] for i in range(len(vv) - 1))


def test_q12(data, catalog, db):
    res = run_q("q12", catalog, db)
    o, li = data.tables["orders"], data.tables["lineitem"]
    pri = dec(data, "orders", "o_orderpriority")
    order_pri = pk_map(o["o_orderkey"], np.arange(len(pri)))
    mode = dec(data, "lineitem", "l_shipmode")
    d0, d1 = _days("1994-01-01"), _days("1995-01-01")
    hi = collections.defaultdict(int)
    lo = collections.defaultdict(int)
    for i, ok_ in enumerate(li["l_orderkey"].tolist()):
        if mode[i] not in (b"MAIL", b"SHIP"):
            continue
        if not (li["l_commitdate"][i] < li["l_receiptdate"][i]
                and li["l_shipdate"][i] < li["l_commitdate"][i]
                and d0 <= li["l_receiptdate"][i] < d1):
            continue
        p = pri[order_pri[ok_]]
        if p in (b"1-URGENT", b"2-HIGH"):
            hi[mode[i]] += 1
        else:
            lo[mode[i]] += 1
    modes = sorted(set(hi) | set(lo))
    assert list(strings_out(res, "l_shipmode")) == modes
    for i, m in enumerate(modes):
        assert int(col_out(res, "high_line_count")[i]) == hi[m]
        assert int(col_out(res, "low_line_count")[i]) == lo[m]


def test_q13(data, catalog, db):
    res = run_q("q13", catalog, db)
    c, o = data.tables["customer"], data.tables["orders"]
    comments = dec(data, "orders", "o_comment")
    import re

    rx = re.compile(rb"special.*requests", re.S)
    cnt = collections.defaultdict(int)
    for ck, cm in zip(o["o_custkey"].tolist(), comments):
        if rx.search(cm) is None:
            cnt[ck] += 1
    dist = collections.Counter(
        cnt.get(ck, 0) for ck in c["c_custkey"].tolist())
    want = sorted(dist.items(), key=lambda kv: (-kv[1], -kv[0]))
    got = list(zip(col_out(res, "c_count").astype(np.int64),
                   col_out(res, "custdist").astype(np.int64)))
    assert [(int(a), int(b)) for a, b in got] == want


def test_q14(data, catalog, db):
    res = run_q("q14", catalog, db)
    li, p = data.tables["lineitem"], data.tables["part"]
    ptype = dec(data, "part", "p_type")
    promo = {p["p_partkey"][i] for i in range(len(ptype))
             if ptype[i].startswith(b"PROMO")}
    d0, d1 = _days("1995-09-01"), _days("1995-10-01")
    m = (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
    tot = promo_rev = 0
    for i in np.flatnonzero(m):
        v = li["l_extendedprice"][i] * (100 - li["l_discount"][i])
        tot += v
        if li["l_partkey"][i] in promo:
            promo_rev += v
    want = 100.0 * (promo_rev / tot)
    np.testing.assert_allclose(
        col_out(res, "promo_revenue")[0], want, rtol=1e-9)


def test_q15(data, catalog, db):
    res = run_q("q15", catalog, db)
    li, s = data.tables["lineitem"], data.tables["supplier"]
    d0, d1 = _days("1996-01-01"), _days("1996-04-01")
    rev = collections.defaultdict(int)
    for i in np.flatnonzero(
            (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)):
        rev[li["l_suppkey"][i].item()] += (
            li["l_extendedprice"][i] * (100 - li["l_discount"][i]))
    best = max(rev.values())
    want = sorted(k for k, v in rev.items() if v == best)
    got = col_out(res, "s_suppkey").astype(np.int64).tolist()
    assert got == want
    np.testing.assert_allclose(
        col_out(res, "total_revenue"), best / 1e4, rtol=1e-12)
    assert strings_out(res, "s_name")[0] == dec(
        data, "supplier", "s_name")[want[0] - 1]


def test_q16(data, catalog, db):
    res = run_q("q16", catalog, db)
    ps, p, s = (data.tables[t] for t in ("partsupp", "part", "supplier"))
    brand = dec(data, "part", "p_brand")
    ptype = dec(data, "part", "p_type")
    sizes = {49, 14, 23, 45, 19, 3, 36, 9}
    import re

    bad_supp = {s["s_suppkey"][i] for i in range(len(s["s_suppkey"]))
                if re.search(rb"Customer.*Complaints",
                             dec(data, "supplier", "s_comment")[i])}
    groups = collections.defaultdict(set)
    for pk, sk in zip(ps["ps_partkey"].tolist(), ps["ps_suppkey"].tolist()):
        i = pk - 1
        if brand[i] == b"Brand#45" or ptype[i].startswith(
                b"MEDIUM POLISHED") or p["p_size"][i] not in sizes:
            continue
        if sk in bad_supp:
            continue
        groups[(brand[i], ptype[i], int(p["p_size"][i]))].add(sk)
    want = sorted(((k, len(v)) for k, v in groups.items()),
                  key=lambda kv: (-kv[1], kv[0]))
    got = list(zip(strings_out(res, "p_brand"),
                   strings_out(res, "p_type"),
                   col_out(res, "p_size").astype(np.int64),
                   col_out(res, "supplier_cnt").astype(np.int64)))
    assert len(got) == len(want)
    for (wk, wc), g in zip(want, got):
        assert wk == (g[0], g[1], int(g[2]))
        assert wc == int(g[3])


def test_q17(data, catalog, db):
    res = run_q("q17", catalog, db)
    li, p = data.tables["lineitem"], data.tables["part"]
    brand = dec(data, "part", "p_brand")
    cont = dec(data, "part", "p_container")
    sel = {p["p_partkey"][i] for i in range(len(brand))
           if brand[i] == b"Brand#23" and cont[i] == b"MED BOX"}
    by_part = collections.defaultdict(list)
    for pk, qty in zip(li["l_partkey"].tolist(), li["l_quantity"].tolist()):
        by_part[pk].append(qty)
    total = 0
    for i in range(len(li["l_partkey"])):
        pk = li["l_partkey"][i].item()
        if pk not in sel:
            continue
        qs = by_part[pk]
        avg = (sum(qs) / 100.0) / len(qs)
        if li["l_quantity"][i] / 100.0 < 0.2 * avg:
            total += li["l_extendedprice"][i]
    want = (total / 100.0) / 7.0
    got = col_out(res, "avg_yearly")[0]
    if want == 0:
        assert res.cols["avg_yearly"][1][0] == False or got == 0  # noqa: E712
    else:
        np.testing.assert_allclose(got, want, rtol=1e-9)


def test_q18(data, catalog, db):
    res = run_q("q18", catalog, db)
    c, o, li = (data.tables[t] for t in ("customer", "orders", "lineitem"))
    qty_by_order = collections.defaultdict(int)
    for ok_, qty in zip(li["l_orderkey"].tolist(),
                        li["l_quantity"].tolist()):
        qty_by_order[ok_] += qty
    big = {k for k, v in qty_by_order.items() if v > 300 * 100}
    order_cust = pk_map(o["o_orderkey"], o["o_custkey"])
    order_price = pk_map(o["o_orderkey"], o["o_totalprice"])
    order_date = pk_map(o["o_orderkey"], o["o_orderdate"])
    want = sorted(
        ((-order_price[k], order_date[k], k, order_cust[k],
          qty_by_order[k]) for k in big),
    )[:100]
    got_rows = list(zip(col_out(res, "o_orderkey").astype(np.int64),
                        col_out(res, "c_custkey").astype(np.int64),
                        (col_out(res, "total_qty") * 100).round().astype(
                            np.int64)))
    assert len(got_rows) == len(want)
    for w, g in zip(want, got_rows):
        assert (w[2], w[3], w[4]) == (int(g[0]), int(g[1]), int(g[2]))


def test_q19(data, catalog, db):
    res = run_q("q19", catalog, db)
    li, p = data.tables["lineitem"], data.tables["part"]
    brand = dec(data, "part", "p_brand")
    cont = dec(data, "part", "p_container")
    mode = dec(data, "lineitem", "l_shipmode")
    instr = dec(data, "lineitem", "l_shipinstruct")
    spec = [
        (b"Brand#12", {b"SM CASE", b"SM BOX", b"SM PACK", b"SM PKG"},
         100, 1100, 5),
        (b"Brand#23", {b"MED BAG", b"MED BOX", b"MED PKG", b"MED PACK"},
         1000, 2000, 10),
        (b"Brand#34", {b"LG CASE", b"LG BOX", b"LG PACK", b"LG PKG"},
         2000, 3000, 15),
    ]
    total = 0
    for i in range(len(li["l_partkey"])):
        if mode[i] not in (b"AIR", b"REG AIR") or \
                instr[i] != b"DELIVER IN PERSON":
            continue
        pk = li["l_partkey"][i].item()
        j = pk - 1
        q = li["l_quantity"][i]
        for b, cs, qlo, qhi, smax in spec:
            if (brand[j] == b and cont[j] in cs and qlo <= q <= qhi
                    and 1 <= p["p_size"][j] <= smax):
                total += li["l_extendedprice"][i] * (
                    100 - li["l_discount"][i])
                break
    got = (col_out(res, "revenue")[0] * 1e4).round()
    assert int(got) == total


def test_q20(data, catalog, db):
    res = run_q("q20", catalog, db)
    s, n, ps, p, li = (data.tables[t] for t in (
        "supplier", "nation", "partsupp", "part", "lineitem"))
    nname = dec(data, "nation", "n_name")
    pname = dec(data, "part", "p_name")
    forest = {p["p_partkey"][i] for i in range(len(pname))
              if pname[i].startswith(b"forest")}
    d0, d1 = _days("1994-01-01"), _days("1995-01-01")
    shipped = collections.defaultdict(int)
    for i in np.flatnonzero(
            (li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)):
        shipped[(li["l_partkey"][i].item(),
                 li["l_suppkey"][i].item())] += li["l_quantity"][i]
    good_supp = set()
    for pk, sk, av in zip(ps["ps_partkey"].tolist(),
                          ps["ps_suppkey"].tolist(),
                          ps["ps_availqty"].tolist()):
        if pk not in forest or (pk, sk) not in shipped:
            continue
        # availqty > 0.5 * sum(qty): exact integer compare at scale 3
        if av * 1000 > 5 * shipped[(pk, sk)]:
            good_supp.add(sk)
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    sname = dec(data, "supplier", "s_name")
    want = sorted(sname[sk - 1] for sk in good_supp
                  if nname[supp_nat[sk]] == b"CANADA")
    got = list(strings_out(res, "s_name"))
    assert got == want


def test_q21(data, catalog, db):
    res = run_q("q21", catalog, db)
    s, li, o, n = (data.tables[t] for t in (
        "supplier", "lineitem", "orders", "nation"))
    nname = dec(data, "nation", "n_name")
    sname = dec(data, "supplier", "s_name")
    supp_nat = pk_map(s["s_suppkey"], s["s_nationkey"])
    status = dec(data, "orders", "o_orderstatus")
    f_orders = {o["o_orderkey"][i].item() for i in range(len(status))
                if status[i] == b"F"}
    by_order = collections.defaultdict(set)
    late_by_order = collections.defaultdict(set)
    for ok_, sk, rd, cd in zip(li["l_orderkey"].tolist(),
                               li["l_suppkey"].tolist(),
                               li["l_receiptdate"].tolist(),
                               li["l_commitdate"].tolist()):
        by_order[ok_].add(sk)
        if rd > cd:
            late_by_order[ok_].add(sk)
    cnt = collections.Counter()
    for ok_, sk, rd, cd in zip(li["l_orderkey"].tolist(),
                               li["l_suppkey"].tolist(),
                               li["l_receiptdate"].tolist(),
                               li["l_commitdate"].tolist()):
        if rd <= cd or ok_ not in f_orders:
            continue
        if nname[supp_nat[sk]] != b"SAUDI ARABIA":
            continue
        if not (by_order[ok_] - {sk}):
            continue  # no other supplier in the order
        if late_by_order[ok_] - {sk}:
            continue  # another supplier was late too
        cnt[sname[sk - 1]] += 1
    want = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:100]
    got = list(zip(strings_out(res, "s_name"),
                   col_out(res, "numwait").astype(np.int64)))
    assert [(a, int(b)) for a, b in got] == want


def test_q22(data, catalog, db):
    res = run_q("q22", catalog, db)
    c, o = data.tables["customer"], data.tables["orders"]
    phones = dec(data, "customer", "c_phone")
    codes = {b"13", b"31", b"23", b"29", b"30", b"18", b"17"}
    in_codes = np.array([ph[:2] in codes for ph in phones])
    pos = in_codes & (c["c_acctbal"] > 0)
    avg = c["c_acctbal"][pos].astype(np.float64).sum() / int(pos.sum())
    has_order = set(o["o_custkey"].tolist())
    out = collections.defaultdict(lambda: [0, 0])
    for i in np.flatnonzero(in_codes):
        if c["c_acctbal"][i] / 100.0 <= avg / 100.0:
            continue
        if c["c_custkey"][i].item() in has_order:
            continue
        cc = phones[i][:2]
        out[cc][0] += 1
        out[cc][1] += c["c_acctbal"][i]
    want = sorted(out.items())
    got = list(zip(strings_out(res, "cntrycode"),
                   col_out(res, "numcust").astype(np.int64),
                   (col_out(res, "totacctbal") * 100).round().astype(
                       np.int64)))
    assert len(got) == len(want)
    for (wk, (wn, wv)), g in zip(want, got):
        assert (wk, wn, wv) == (g[0], int(g[1]), int(g[2]))


def test_golden_pinning(data, db, catalog):
    """Canondata-style pinning (VERDICT r4 weak 5): every TPC-H result
    at the fixed (sf, seed) must match the frozen golden checksums in
    tests/golden_tpch.json — catching CORRELATED generator+engine
    drift that the per-query numpy references (which share the
    generated data) cannot see. Regenerate the file deliberately when
    data or query semantics change on purpose."""
    import hashlib
    import json
    import os

    golden = json.load(open(os.path.join(
        os.path.dirname(__file__), "golden_tpch.json")))
    assert golden["sf"] == SF and golden["seed"] == 11

    def digest(out):
        h = hashlib.sha256()
        for f in out.schema.fields:
            v, ok = out.cols[f.name]
            h.update(f.name.encode())
            if f.type.is_string:
                src = out.dict_aliases.get(f.name, f.name)
                vals = [(x.decode("latin1") if okk else None)
                        for x, okk in zip(
                            data.dicts[src].decode(
                                np.asarray(v, dtype=np.int32)),
                            np.asarray(ok, dtype=bool))]
            elif f.type.is_floating:
                vals = [(round(float(x), 6) if okk else None)
                        for x, okk in zip(np.asarray(v),
                                          np.asarray(ok, dtype=bool))]
            else:
                vals = [(int(x) if okk else None)
                        for x, okk in zip(np.asarray(v),
                                          np.asarray(ok, dtype=bool))]
            h.update(json.dumps(vals).encode())
        return h.hexdigest()

    for name, want in golden["queries"].items():
        out = run_q(name, catalog, db)  # memoized from earlier tests
        assert out.num_rows == want["rows"], name
        assert digest(out) == want["sha"], (
            f"{name}: result drifted from the pinned golden")
