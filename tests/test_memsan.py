"""Memory sanitizer (analysis/memsan, YDB_TPU_MEMSAN=1): charge /
release ledger, seam gating of the patched raw allocators, statement
attribution (thread-local + trace-id), warm peak-byte budget
enforcement, profile / EXPLAIN ANALYZE / sysview / counters surfacing,
the instrumented-seam regressions (run_stacked stacking, shuffle grow
buckets), and the tier-1 acceptance run — warm TPC-H Q1/Q6 through the
full session path must make ZERO unbudgeted device allocations and
stay within the declared peak budget."""

import threading

import numpy as np
import pytest

from ydb_tpu.analysis import memsan
from ydb_tpu.obs.tracing import Tracer
from ydb_tpu.obs.tracing import activate as span_activate

from test_sql import Q1_SQL, Q6_SQL

#: the declared warm-statement peak budget for the sf=0.002 lineitem
#: acceptance run: generous vs the measured warm peak (warm statements
#: serve staging from the plan/resident caches, so their charged peak
#: is a small fraction of the cold footprint) but tight enough that an
#: accidental per-statement re-stage of the whole table across a few
#: growth PRs trips it
WARM_PEAK_BUDGET = 64 * 1024 * 1024


@pytest.fixture(autouse=True)
def _memsan_off_after():
    """Every test leaves the sanitizer unpinned, unbudgeted, empty."""
    yield
    memsan.clear_budget()
    memsan.set_force(None)
    memsan.reset()


# ---------------- gates / None-safety ----------------


def test_disabled_is_none_safe():
    assert not memsan.enabled()
    assert memsan.begin_statement("q") is None
    assert memsan.end_statement(None) is None
    memsan.discard(None)            # no-op, no raise
    assert memsan.charge(1024, "staging") is None
    memsan.release(None)            # no-op, no raise
    with memsan.seam("staging"):    # noop seam object
        pass
    assert not memsan.in_seam()


def test_env_gate(monkeypatch):
    monkeypatch.setenv("YDB_TPU_MEMSAN", "1")
    assert memsan.enabled()
    monkeypatch.setenv("YDB_TPU_MEMSAN", "0")
    assert not memsan.enabled()
    memsan.set_force(True)
    assert memsan.enabled()  # pin beats env


def test_allocator_patches_restored_on_disarm():
    import jax
    import jax.numpy as jnp

    before = (jnp.zeros, jnp.stack, jax.device_put)
    with memsan.activate():
        assert jnp.zeros is not before[0]
        assert jax.device_put is not before[2]
    after = (jnp.zeros, jnp.stack, jax.device_put)
    assert after == before


# ---------------- ledger + attribution ----------------


def test_charge_release_peak_and_components():
    with memsan.activate():
        st = memsan.begin_statement("q")
        t1 = memsan.charge(1000, "staging")
        t2 = memsan.charge(500, "stack", owner="run_stacked")
        memsan.release(t2)
        memsan.release(t2)  # idempotent
        snap = memsan.end_statement(st)
    assert snap["peak"] == 1500      # high-water before the release
    assert snap["live"] == 1000      # t1 is GC-owned: never released
    assert snap["charges"] == 2
    assert snap["unbudgeted"] == 0
    assert snap["by_component"] == {"staging": 1000, "stack": 500}
    assert t1 is not None and not t1.closed


def test_raw_alloc_outside_seam_counts_unbudgeted():
    import jax.numpy as jnp

    with memsan.activate():
        st = memsan.begin_statement("q")
        loose = jnp.zeros(128)           # M001's runtime shadow
        with memsan.seam("staging"):
            jnp.zeros(128)               # seam-covered: silent
        snap = memsan.end_statement(st)
    assert snap["unbudgeted"] == 1
    assert snap["unbudgeted_bytes"] == int(loose.nbytes)
    assert snap["by_component"] == {"unbudgeted": int(loose.nbytes)}


def test_tracer_allocs_under_jit_are_ignored():
    """jnp.zeros inside a traced function yields Tracers, not HBM
    buffers — the patched allocator must not count them."""
    import jax
    import jax.numpy as jnp

    with memsan.activate():
        @jax.jit
        def f(x):
            return jnp.zeros(x.shape) + x

        st = memsan.begin_statement("q")
        with memsan.seam("staging"):
            x = jnp.asarray(np.arange(6, dtype=np.float32))
        f(x)  # cold: traces, compiles, runs
        snap = memsan.end_statement(st)
    assert snap["unbudgeted"] == 0


def test_trace_id_attribution_across_threads():
    """Conveyor workers carry no thread-local window; charges resolve
    through the inherited obs span's trace id."""
    with memsan.activate():
        tr = Tracer()
        root = tr.trace("query")
        st = memsan.begin_statement("q", trace_id=root.trace_id)

        def worker():
            with span_activate(root):
                memsan.charge(4096, "staging", owner="worker")

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        snap = memsan.end_statement(st)
        root.finish()
    assert snap["by_component"] == {"staging": 4096}


def test_unattributed_charges_land_in_orphans():
    import jax.numpy as jnp

    with memsan.activate():
        jnp.zeros(64)  # no open statement anywhere
        tot = memsan.totals()
    assert tot["unbudgeted"] >= 1
    assert tot["charges"] >= 1


# ---------------- budget enforcement ----------------


def test_unbudgeted_alloc_raises_past_warmup():
    import jax.numpy as jnp

    with memsan.activate(budget=memsan.Budget(warmup=1)):
        st = memsan.begin_statement("q")
        jnp.zeros(32)
        memsan.end_statement(st)  # warmup statement: free pass
        st = memsan.begin_statement("q")
        jnp.zeros(32)
        with pytest.raises(memsan.MemBudgetError, match="outside any"):
            memsan.end_statement(st)


def test_peak_budget_raises_and_names_components():
    budget = memsan.Budget(peak_bytes=100, warmup=0)
    with memsan.activate(budget=budget):
        st = memsan.begin_statement("q")
        memsan.charge(200, "staging")
        with pytest.raises(memsan.MemBudgetError, match="peaked at"):
            memsan.end_statement(st)
        # a different label gets its own warmup window with warmup>=1
        memsan.set_budget(memsan.Budget(peak_bytes=100, warmup=1))
        st = memsan.begin_statement("other")
        memsan.charge(200, "staging")
        memsan.end_statement(st)


def test_discard_skips_enforcement():
    with memsan.activate(
            budget=memsan.Budget(peak_bytes=0, warmup=0)):
        st = memsan.begin_statement("q")
        memsan.charge(999, "staging")
        memsan.discard(st)  # error path: no budget raise


def test_set_budget_accepts_budget_instance():
    with memsan.activate():
        memsan.set_budget(memsan.Budget(peak_bytes=77, warmup=3))
        assert memsan.budget_bytes() == 77
        memsan.clear_budget()
        assert memsan.budget_bytes() is None


# ---------------- process-wide component ledger ----------------


def test_component_totals_global_peak_and_reset():
    with memsan.activate():
        memsan.charge(1000, "staging")
        t = memsan.charge(500, "resident")
        memsan.release(t, evicted=True)
        ct = memsan.component_totals()
        assert ct["staging"] == {"live": 1000, "peak": 1000,
                                 "charges": 1, "releases": 0,
                                 "evictions": 0}
        assert ct["resident"]["live"] == 0
        assert ct["resident"]["releases"] == 1
        assert ct["resident"]["evictions"] == 1
        assert memsan.global_peak() == 1500
        memsan.reset()
        assert memsan.component_totals() == {}
        assert memsan.global_peak() == 0


# ---------------- obs surfacing ----------------


def test_end_statement_annotates_span_and_profile():
    from ydb_tpu.obs.profile import build_profile

    with memsan.activate():
        tr = Tracer()
        root = tr.trace("query")
        with span_activate(root):
            st = memsan.begin_statement("q", trace_id=root.trace_id)
            memsan.charge(2048, "staging")
            memsan.end_statement(st)
        root.finish()
        spans = tr.spans_for(root.trace_id)
    attrs = spans[0].attrs
    assert attrs["memsan_peak"] == 2048
    assert attrs["memsan_charges"] == 1
    assert attrs["memsan_unbudgeted"] == 0
    p = build_profile(spans, sql="q")
    assert p.memsan == {"peak": 2048, "live": 2048, "charges": 1,
                        "unbudgeted": 0}
    assert "memsan" in p.to_dict()


def test_session_execute_populates_profile_memsan():
    """The plain execute path: the session opens the memsan window on
    the same bounds as syncsan's and pins the root span explicitly —
    last_profile.memsan carrying this statement's byte ledger is the
    serving-tier bench's data source."""
    from ydb_tpu.kqp.session import Cluster

    with memsan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE dm (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO dm VALUES (1, 2), (2, 4)")
        s.execute("SELECT sum(v) AS sv FROM dm")
        p = s.last_profile
    assert p is not None and p.memsan, \
        "statement byte ledger missing from the profile"
    assert set(p.memsan) == {"peak", "live", "charges", "unbudgeted"}
    assert p.memsan["unbudgeted"] == 0


def test_explain_analyze_shows_memsan_line():
    from ydb_tpu.kqp.session import Cluster

    with memsan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE dm (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO dm VALUES (1, 2), (2, 4)")
        txt = s.execute("EXPLAIN ANALYZE SELECT sum(v) AS sv FROM dm")
    assert "memsan:" in txt
    assert "peak=" in txt and "unbudgeted=" in txt


def test_sys_device_memory_view_and_counters():
    """The sysview rows come from the process-wide component ledger
    (with a <global> summary row) and run_background exports the same
    ledger as component=devmem counters plus the global peak gauge."""
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.obs.sysview import _device_memory_rows

    with memsan.activate():
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE dm (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO dm VALUES (1, 2), (2, 4)")
        s.execute("SELECT sum(v) AS sv FROM dm")

        comps, live, peak, charges, releases, evictions = \
            _device_memory_rows(c)
        assert "<global>" in comps and "staging" in comps
        g = comps.index("<global>")
        assert peak[g] == memsan.global_peak() > 0

        r = s.execute("SELECT live_bytes, peak_bytes, charges "
                      "FROM sys_device_memory")
        assert r.num_rows >= 2  # at least staging + <global>
        assert int(np.asarray(r.cols["peak_bytes"][0]).max()) > 0

        c.run_background()
        snap = c.counters.snapshot()
        devmem = {k: v for k, v in snap.items()
                  if "component=devmem" in k}
        assert any(k.startswith("peak_bytes|") for k in devmem), devmem
        assert any(k.startswith("global_peak_bytes|") for k in devmem)
        assert max(devmem.values()) > 0
    # sanitizer off: the view exists but reports no rows
    cols = _device_memory_rows(c)
    assert all(col == [] for col in cols)


# ---------------- instrumented-seam regressions ----------------


def test_run_stacked_charges_stack_ticket_and_dispatch():
    """The batched serving tier's stacking copy (the ISSUE's first
    expected true finding): run_stacked must charge the stacked member
    footprint to the ``stack`` component and RELEASE it after the
    dispatch returns (try/finally ticket), with the output blocks
    charged to ``dispatch``."""
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.plan.executor import Database, _stage_fused_site
    from ydb_tpu.plan.nodes import TableScan
    from ydb_tpu.ssa import plan_fuse
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=0.002, seed=11)
    schema = data.schema("lineitem")
    db = Database(
        sources={"lineitem": ColumnSource(
            data.tables["lineitem"], schema, data.dicts)},
        dicts=data.dicts)
    plan = TableScan("lineitem", program=tpch.q6_program())
    sig = plan_fuse.plan_signature(plan, db)
    assert sig is not None and sig.sites

    with memsan.activate():
        fused = plan_fuse.build(sig, db)
        inputs = {s.key: _stage_fused_site(s, db, None, donate=False)[0]
                  for s in sig.sites}
        memsan.reset()  # isolate the dispatch from staging charges
        st = memsan.begin_statement("stacked")
        out, tt = fused.run_stacked([inputs, inputs])
        assert not fused.overflowed(tt)
        snap = memsan.end_statement(st)
        ct = memsan.component_totals()
    assert snap["unbudgeted"] == 0, snap
    assert ct["stack"]["charges"] >= 1
    assert ct["stack"]["releases"] >= 1
    assert ct["stack"]["live"] == 0, "stack ticket leaked"
    assert ct["dispatch"]["charges"] >= 1
    assert ct["dispatch"]["peak"] > 0


def test_shuffle_grow_buckets_charge_grown_bytes():
    """The mesh shuffle's grow-on-overflow path (the ISSUE's second
    expected true finding): every dispatch attempt charges its bucket
    capacity to the ``shuffle`` component, so the post-grow re-dispatch
    shows up as a LARGER charge — the footprint an operator sees in
    sys_device_memory, not just a timeline counter."""
    from ydb_tpu import dtypes
    from ydb_tpu.blocks.dictionary import DictionarySet
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.parallel.mesh import make_mesh
    from ydb_tpu.parallel.mesh_exec import MeshDatabase, \
        MeshPlanExecutor
    from ydb_tpu.plan import LookupJoin, TableScan, Transform
    from ydb_tpu.ssa import Agg, AggSpec, GroupByStep, Program

    n_dev = 8
    rows = 2048 * n_dev  # 100% key skew: one destination overflows
    lsch = dtypes.schema(("k", dtypes.INT64), ("v", dtypes.INT64))
    rsch = dtypes.schema(("rk", dtypes.INT64), ("w", dtypes.INT64))
    lcols = {"k": np.full(rows, 7, dtype=np.int64),
             "v": np.arange(rows, dtype=np.int64)}
    rcols = {"rk": np.array([7], dtype=np.int64),
             "w": np.array([100], dtype=np.int64)}
    dicts = DictionarySet()
    mesh_db = MeshDatabase(
        sources={
            "L": [ColumnSource(
                {k: v[s::n_dev] for k, v in lcols.items()}, lsch,
                dicts) for s in range(n_dev)],
            "R": [ColumnSource(
                {k: v[s::n_dev] for k, v in rcols.items()}, rsch,
                dicts) for s in range(n_dev)],
        },
        dicts=dicts)
    plan = Transform(
        LookupJoin(probe=TableScan("L"), build=TableScan("R"),
                   probe_keys=("k",), build_keys=("rk",),
                   payload=("w",), kind="inner"),
        Program((GroupByStep(keys=("k",), aggs=(
            AggSpec(Agg.SUM, "v", "sv"),
            AggSpec(Agg.COUNT_ALL, None, "n"))),)))

    with memsan.activate():
        ex = MeshPlanExecutor(mesh_db, make_mesh(n_dev))
        res = ex.execute_fused(plan)
        assert res is not None
        from ydb_tpu.parallel.mesh_fuse import MeshFusedPlan
        (fused,) = [v for v in ex._jit_cache.values()
                    if isinstance(v, MeshFusedPlan)]
        assert fused.shuffle_grows >= 1, "skew never tripped grow"
        ct = memsan.component_totals()
    # the overflowed attempt AND the grown re-dispatch both charged
    assert ct["shuffle"]["charges"] >= 2, ct
    assert ct["shuffle"]["peak"] > 0


# ---------------- tier-1 acceptance: warm Q1/Q6 full session ---------


def test_warm_q1_q6_zero_unbudgeted_within_peak_budget():
    """The ISSUE's acceptance gate: warm TPC-H Q1/Q6 through the FULL
    session path under the armed sanitizer make zero unbudgeted device
    allocations and peak within the declared budget — enforced by the
    sanitizer's own budget machinery inside the session's
    end_statement, so a regression raises MemBudgetError out of
    s.execute() here."""
    from test_batching import _lineitem_cluster

    budget = memsan.Budget(peak_bytes=WARM_PEAK_BUDGET, warmup=1)
    with memsan.activate(budget=budget):
        c = _lineitem_cluster()
        try:
            for name, sql in (("q1", Q1_SQL), ("q6", Q6_SQL)):
                snaps = []
                for _ in range(3):
                    # warm runs are budget-enforced inside the session
                    s = c.session()
                    s.execute(sql)
                    snaps.append(dict(s.last_profile.memsan))
                cold, warm = snaps[0], snaps[1:]
                assert cold["charges"] >= 1, \
                    f"{name}: cold run charged nothing — seams dead?"
                assert cold["unbudgeted"] == 0, (name, cold)
                for snap in warm:
                    assert snap["unbudgeted"] == 0, (name, snap)
                    assert snap["peak"] <= WARM_PEAK_BUDGET, \
                        (name, snap)
        finally:
            c.stop()
